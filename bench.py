"""Benchmark: batched rule-check throughput on one chip.

North-star config from BASELINE.json: ~1M flow rules loaded, 100k+
buffered entries checked + accounted in one flush. The reference
publishes no numbers (BASELINE.md), so ``vs_baseline`` is reported
against the north-star target of 1 ms per 131072-entry flush
(vs_baseline > 1.0 means faster than target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from sentinel_tpu.metrics.nodes import make_stats
    from sentinel_tpu.rules.degrade_table import DegradeIndex
    from sentinel_tpu.rules.flow_table import FlowRuleDynState, FlowTableDevice
    from sentinel_tpu.rules.param_table import make_param_state
    from sentinel_tpu.runtime.flush import SystemDevice, flush_step_jit
    from __graft_entry__ import _example_batch

    n_rules = 1 << 20  # ~1M rules / resources
    n_rows = 1 << 20
    n_entries = 1 << 17  # 131072 buffered entries per flush
    k = 1

    stats = make_stats(n_rows)
    dindex = DegradeIndex([])
    ddev, ddyn = dindex.device, dindex.make_dyn_state()
    inf = float("inf")
    sysdev = SystemDevice(
        qps=jnp.float32(inf),
        max_thread=jnp.float32(inf),
        max_rt=jnp.float32(inf),
        load_threshold=jnp.float32(-1.0),
        cpu_threshold=jnp.float32(-1.0),
        cur_load=jnp.float32(-1.0),
        cur_cpu=jnp.float32(-1.0),
    )
    # Build the device rule table directly (bypasses the Python bean
    # layer, which is not the hot path being measured).
    dev = FlowTableDevice(
        grade=jnp.ones(n_rules, dtype=jnp.int32),
        count=jnp.full(n_rules, 20.0, dtype=jnp.float32),
        behavior=jnp.zeros(n_rules, dtype=jnp.int32),
        max_queueing_time_ms=jnp.zeros(n_rules, dtype=jnp.int32),
        cost1_ms=jnp.full(n_rules, 50, dtype=jnp.int32),
        warmup_warning_token=jnp.zeros(n_rules, dtype=jnp.int32),
        warmup_max_token=jnp.zeros(n_rules, dtype=jnp.int32),
        warmup_slope=jnp.zeros(n_rules, dtype=jnp.float32),
        warmup_refill_threshold=jnp.zeros(n_rules, dtype=jnp.int32),
    )
    dyn = FlowRuleDynState(
        latest_passed_time=jnp.full(n_rules, -(10**9), dtype=jnp.int32),
        stored_tokens=jnp.zeros(n_rules, dtype=jnp.float32),
        last_filled_time=jnp.full(n_rules, -(10**9), dtype=jnp.int32),
    )
    batch = _example_batch(n_entries, n_rows, n_rules, k)

    pdyn = make_param_state(8)

    # Warm-up / compile.
    stats, dyn, ddyn, pdyn, result = flush_step_jit(
        stats, dev, dyn, ddev, ddyn, pdyn, sysdev, batch
    )
    jax.block_until_ready(result.admitted)

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        stats, dyn, ddyn, pdyn, result = flush_step_jit(
            stats, dev, dyn, ddev, ddyn, pdyn, sysdev, batch
        )
    jax.block_until_ready(result.admitted)
    dt = (time.perf_counter() - t0) / iters

    checks_per_sec = n_entries / dt
    target_ms = 1.0
    out = {
        "metric": "batched_entry_checks_per_sec_per_chip_1M_rules",
        "value": round(checks_per_sec, 1),
        "unit": "entries/sec",
        "vs_baseline": round((target_ms / 1000.0) / dt, 4),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
