"""Benchmark: batched rule-check throughput on one chip.

North-star config from BASELINE.json: ~1M flow rules loaded, 100k+
buffered entries checked + accounted in one flush. The reference
publishes no numbers (BASELINE.md), so ``vs_baseline`` is reported
against the north-star target of 1 ms per 131072-entry flush,
normalized per entry so partial ladder stages stay comparable
(vs_baseline > 1.0 means faster than target).

Hardened (round-2): every backend touch happens in a SUBPROCESS with a
timeout — round 1 died rc=1/rc=124 with zero data because a wedged
TPU tunnel blocks inside native code where no Python-level signal
handler can run. The parent process never imports jax: it probes the
backend, walks a size ladder child-by-child, reports the LAST (largest)
completed stage, and always emits exactly ONE JSON line on stdout:
{"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# (n_rules == n_rows, n_entries, timed_iters); last stage is the
# north-star config. The TPU ladder starts at 131k rules: each child
# pays ~30-60 s of tunnel init, and the 16k stage only measures
# per-dispatch overhead (round-4 session: 160M/s at 16k vs 745M/s at
# 1M — dispatch floor, not kernel).
LADDER = [
    (1 << 17, 1 << 15, 20),
    (1 << 20, 1 << 17, 10),
]
CPU_LADDER = [(1 << 14, 1 << 14, 20)] + LADDER[:1]
TARGET_S_PER_ENTRY = 1e-3 / float(1 << 17)  # 1 ms / 131072 entries


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _emit(out: dict) -> None:
    print(json.dumps(out), flush=True)


def _host_identity() -> dict:
    """Measured host-speed token: a fixed hash + spin calibration plus
    the cpu count. Two VMs can read identically as
    ("cpu", jax_version) yet differ ~5x in real speed — exactly the
    r09→r10 re-anchor hole where the gate went red on a hardware
    identity change, not a code regression. tools/benchgate.py folds
    this token into baseline matching so a cross-box comparison SKIPs
    with a reason instead of gating red. Best-of-3 (min) against
    scheduler noise; the work is fixed, so the number is a property of
    the box, not the workload."""
    import zlib

    buf = b"\xa5" * (1 << 20)
    zlib.crc32(buf)  # warm the buffer through the cache once
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(4):
            zlib.crc32(buf)
        n = 0
        while n < 100_000:
            n += 1
        best = min(best, time.perf_counter() - t0)
    return {
        "host_cpu_count": os.cpu_count() or 0,
        "host_spin_ms": round(best * 1e3, 3),
    }


def _probe_once(timeout_s: float) -> str | None:
    """One probe attempt: run a real (tiny) computation in a subprocess
    — round 1 showed init can 'succeed' and then wedge on first use.
    Returns the platform, or None on failure/timeout."""
    code = (
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((256,256), jnp.bfloat16);"
        "(x @ x).block_until_ready();"
        "print(jax.default_backend())"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        _log(f"backend probe timed out after {timeout_s:.0f}s")
        return None
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:] or ["?"]
        _log(f"backend probe failed rc={r.returncode} ({tail[0]})")
        return None
    lines = r.stdout.strip().splitlines()
    return lines[-1] if lines else None


def _transport_exists() -> bool:
    """Under the axon loopback relay, the tunnel is a local stdio
    relay process; when it's dead, no probe can EVER succeed this
    session (round-4 diagnosis, PERF_NOTES.md) — don't burn 15 min of
    retries proving it. On any other backend layout, assume yes."""
    if os.environ.get("AXON_LOOPBACK_RELAY") != "1":
        return True
    try:
        out = subprocess.run(
            ["ps", "-eo", "args"], capture_output=True, text=True, timeout=10
        ).stdout
    except Exception:
        return True  # can't tell — probe normally
    # Match the relay invocation itself (".relay.py"), not diagnostic
    # greps/watches that merely mention it ("ps aux | grep relay.py").
    return any(
        ".relay.py" in line and "grep" not in line for line in out.splitlines()
    )


def _probe_backend(attempts: int, timeouts: list[float]) -> str:
    """Probe with retries: 'TPU unreachable right now' is a transient
    tunnel condition, not a fact about the hardware (round-3 lesson:
    ONE 120 s attempt turned a wedge into a round of CPU-only
    evidence). Falls back to 'cpu' only after every attempt fails —
    except when the transport provably doesn't exist, which no retry
    can fix."""
    if not _transport_exists():
        _log("axon relay process not found — transport dead, one short probe only")
        attempts, timeouts = 1, [60.0]
    for i in range(attempts):
        t = timeouts[min(i, len(timeouts) - 1)]
        _log(f"backend probe attempt {i + 1}/{attempts} (timeout {t:.0f}s)")
        platform = _probe_once(t)
        if platform:
            _log(f"backend probe OK: {platform}")
            return platform
        if i + 1 < attempts:
            time.sleep(min(15.0 * (i + 1), 60.0))
    _log("all probe attempts failed — falling back to CPU (weak evidence)")
    return "cpu"


def _run_mixed_stage(n_rules: int, n_entries: int, iters: int) -> dict:
    """Mixed-workload stage: flow (k=2, incl. rate-limiter shaping) +
    degrade breakers + hot-param buckets + exits, all in one flush —
    "the slot chain at scale", not just the k=1 DEFAULT kernel
    (round-2 weak #5). Reported alongside the headline metric.
    """
    import jax
    import jax.numpy as jnp

    from sentinel_tpu.metrics.nodes import make_stats
    from sentinel_tpu.models import constants as C
    from sentinel_tpu.models.rules import DegradeRule
    from sentinel_tpu.rules.degrade_table import DegradeIndex
    from sentinel_tpu.rules.flow_table import FlowRuleDynState, FlowTableDevice
    from sentinel_tpu.rules.param_table import ParamBatch, make_param_state
    from sentinel_tpu.rules.shaping import ShapingBatch
    from sentinel_tpu.runtime.flush import SystemDevice, flush_step_full_jit
    from __graft_entry__ import _example_batch

    rng = __import__("numpy").random.default_rng(1)
    np_ = __import__("numpy")
    n_rows = n_rules
    k = 2
    nd = min(1024, n_rules)  # degrade rules (real bean layer at this size)
    _log(f"mixed stage rules={n_rules} entries={n_entries}: building state")
    stats = make_stats(n_rows)
    dindex = DegradeIndex(
        [DegradeRule(resource=f"r{i}", grade=1, count=0.5, time_window=10)
         for i in range(nd)]
    )
    inf = float("inf")
    sysdev = SystemDevice(
        qps=jnp.float32(inf), max_thread=jnp.float32(inf), max_rt=jnp.float32(inf),
        load_threshold=jnp.float32(-1.0), cpu_threshold=jnp.float32(-1.0),
        cur_load=jnp.float32(-1.0), cur_cpu=jnp.float32(-1.0),
    )
    # Rule table: 1/8 of rules are rate-limiter shaped, the rest DEFAULT.
    gids = np_.arange(n_rules)
    is_shaping = (gids % 8) == 7
    dev = FlowTableDevice(
        grade=jnp.ones(n_rules, dtype=jnp.int32),
        count=jnp.full(n_rules, 20.0, dtype=jnp.float32),
        behavior=jnp.asarray(
            np_.where(is_shaping, C.CONTROL_BEHAVIOR_RATE_LIMITER,
                      C.CONTROL_BEHAVIOR_DEFAULT).astype(np_.int32)
        ),
        max_queueing_time_ms=jnp.full(n_rules, 500, dtype=jnp.int32),
        cost1_ms=jnp.full(n_rules, 50, dtype=jnp.int32),
        warmup_warning_token=jnp.zeros(n_rules, dtype=jnp.int32),
        warmup_max_token=jnp.zeros(n_rules, dtype=jnp.int32),
        warmup_slope=jnp.zeros(n_rules, dtype=jnp.float32),
        warmup_refill_threshold=jnp.zeros(n_rules, dtype=jnp.int32),
    )
    dyn = FlowRuleDynState(
        latest_passed_time=jnp.full(n_rules, -(10**9), dtype=jnp.int32),
        stored_tokens=jnp.zeros(n_rules, dtype=jnp.float32),
        last_filled_time=jnp.full(n_rules, -(10**9), dtype=jnp.int32),
    )
    batch = _example_batch(n_entries, n_rows, n_rules, k)
    res = np_.asarray(batch.e_rows)[:, 0]
    # Slot 1: a shaping rule for every 8th entry.
    idx = np_.arange(n_entries)
    sh_mask = (idx % 8) == 7
    sh_gid = (res // 8) * 8 + 7  # nearest shaping gid
    gid2 = np_.asarray(batch.e_rule_gid).copy()
    crow2 = np_.asarray(batch.e_check_row).copy()
    gid2[sh_mask, 1] = sh_gid[sh_mask] % n_rules
    crow2[sh_mask, 1] = sh_gid[sh_mask] % n_rules
    # Per-entry breaker check + exits completing breakers.
    dg = (res % nd).astype(np_.int32).reshape(-1, 1)
    m = np_.asarray(batch.x_valid).shape[0]
    x_rows = np_.full((m, 4), -1, dtype=np_.int32)
    x_rows[:, 0] = res[:m]
    batch = batch._replace(
        e_rule_gid=jnp.asarray(gid2),
        e_check_row=jnp.asarray(crow2),
        e_dgid=jnp.asarray(dg),
        x_valid=jnp.ones(m, dtype=bool),
        x_rows=jnp.asarray(x_rows),
        x_count=jnp.ones(m, dtype=jnp.int32),
        x_rt=jnp.full(m, 10, dtype=jnp.int32),
        x_thr=jnp.full(m, -1, dtype=jnp.int32),
        x_dgid=jnp.asarray((res[:m] % nd).astype(np_.int32).reshape(-1, 1)),
    )
    # Shaping batch (the lax.scan path).
    s = int(sh_mask.sum())
    sb = ShapingBatch(
        valid=jnp.ones(s, dtype=bool),
        gid=jnp.asarray((sh_gid[sh_mask] % n_rules).astype(np_.int32)),
        row=jnp.asarray((sh_gid[sh_mask] % n_rules).astype(np_.int32)),
        eidx=jnp.asarray(idx[sh_mask].astype(np_.int32)),
        flat_pos=jnp.asarray((idx[sh_mask] * k + 1).astype(np_.int32)),
        ts=batch.e_ts[jnp.asarray(idx[sh_mask])],
        acquire=jnp.ones(s, dtype=jnp.int32),
    )
    # Hot-param batch: every 4th entry checks one param bucket row.
    p_mask = (idx % 4) == 0
    p = int(p_mask.sum())
    prows = 1 << 14
    pdyn = make_param_state(prows)
    pb = ParamBatch(
        valid=jnp.ones(p, dtype=bool),
        prow=jnp.asarray((rng.integers(0, prows, p)).astype(np_.int32)),
        eidx=jnp.asarray(idx[p_mask].astype(np_.int32)),
        ts=batch.e_ts[jnp.asarray(idx[p_mask])],
        acquire=jnp.ones(p, dtype=jnp.int32),
        grade=jnp.full(p, C.FLOW_GRADE_QPS, dtype=jnp.int32),
        behavior=jnp.zeros(p, dtype=jnp.int32),
        token_count=jnp.full(p, 100, dtype=jnp.int32),
        burst=jnp.zeros(p, dtype=jnp.int32),
        duration_ms=jnp.full(p, 1000, dtype=jnp.int32),
        maxq=jnp.zeros(p, dtype=jnp.int32),
        cost_ms=jnp.zeros(p, dtype=jnp.int32),
        reset_rows=jnp.full(8, -1, dtype=jnp.int32),
        exit_rows=jnp.full(8, -1, dtype=jnp.int32),
    )

    # The same host-known rounds bounds the Engine computes: max items
    # per rule / per value row, pow2-bucketed (engine._rounds_bucket).
    from sentinel_tpu.runtime.engine import _rounds_bucket

    sh_rounds = _rounds_bucket((sh_gid[sh_mask] % n_rules).astype(np_.int32))
    p_rounds = _rounds_bucket(np_.asarray(pb.prow))
    _log(f"mixed: compiling + warm-up (sh_rounds={sh_rounds} p_rounds={p_rounds})")
    t0 = time.perf_counter()
    out = flush_step_full_jit(
        stats, dev, dyn, dindex.device, dindex.make_dyn_state(), pdyn, sysdev,
        batch, sb, pb, shaping_rounds=sh_rounds, param_rounds=p_rounds,
    )
    stats, dyn, ddyn, pdyn, _sk, result = out
    jax.block_until_ready(result.admitted)
    _log(f"mixed: compile+first-run {time.perf_counter() - t0:.1f}s; timing {iters} iters")
    t0 = time.perf_counter()
    for _ in range(iters):
        stats, dyn, ddyn, pdyn, _sk, result = flush_step_full_jit(
            stats, dev, dyn, dindex.device, ddyn, pdyn, sysdev, batch, sb, pb,
            shaping_rounds=sh_rounds, param_rounds=p_rounds,
        )
    jax.block_until_ready(result.admitted)
    dt = (time.perf_counter() - t0) / iters
    checks = n_entries / dt
    _log(f"mixed stage done: {dt*1e3:.3f} ms/flush, {checks:,.0f} entries/sec")
    return {
        "mixed_checks_per_sec": round(checks, 1),
        "mixed_flush_ms": round(dt * 1e3, 4),
        "mixed_n_rules": n_rules,
        "mixed_n_entries": n_entries,
    }


def _run_engine_stage(n_rules: int, n_ops: int, iters: int) -> dict:
    """Engine-level deferred-mode throughput: submit_many + flush through
    the real host path (string interning, slot resolution, encode,
    kernel, verdict fill) — the end-to-end ops/sec a product user sees
    (round-1 #7 bench case). Also measures the columnar bulk path
    (``submit_bulk``: one resolution per group, numpy-slice encode,
    array verdicts) at a proportionally larger op count."""
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.runtime.engine import Engine

    _log(f"engine stage rules={n_rules} ops={n_ops}")
    eng = Engine(initial_rows=max(1024, n_rules * 2))
    eng.set_flow_rules([FlowRule(resource=f"r{i}", count=1e9) for i in range(n_rules)])
    reqs = [{"resource": f"r{i % n_rules}"} for i in range(n_ops)]
    ops = eng.submit_many(reqs)  # warm-up: interning + compile
    eng.flush()
    assert all(op.verdict is not None for op in ops if op is not None)
    t0 = time.perf_counter()
    for _ in range(iters):
        eng.submit_many(reqs)
        eng.flush()
    dt = (time.perf_counter() - t0) / iters
    ops_per_sec = n_ops / dt
    _log(f"engine stage done: {ops_per_sec:,.0f} ops/sec end-to-end")

    # Bulk path: the same end-to-end surface, columnar. 64 resources
    # per flush, bulk_n entries each.
    groups = 64
    bulk_n = max(1024, min(eng.max_batch // groups, 4096))
    gs = [eng.submit_bulk(f"r{i % n_rules}", bulk_n) for i in range(groups)]
    eng.flush()
    assert all(g.admitted is not None for g in gs)
    t0 = time.perf_counter()
    for _ in range(iters):
        for i in range(groups):
            eng.submit_bulk(f"r{i % n_rules}", bulk_n)
        eng.flush()
    dtb = (time.perf_counter() - t0) / iters
    bulk_ops_per_sec = groups * bulk_n / dtb
    _log(f"engine bulk done: {bulk_ops_per_sec:,.0f} ops/sec end-to-end")

    # Adapter (gateway) columnar path: gateway-shaped traffic — param
    # extraction per request + per-value hot-param admission — through
    # gateway_submit_bulk onto the same bulk surface. Verdict target:
    # ≥ bulk/2 (the adapter layer must not give back the bulk win).
    from sentinel_tpu.adapters.gateway import (
        GatewayFlowRule,
        GatewayParamFlowItem,
        GatewayRequestBatch,
        GatewayRequestInfo,
        PARAM_PARSE_STRATEGY_CLIENT_IP,
        gateway_rule_manager,
        gateway_submit_bulk,
    )
    from sentinel_tpu.models.rules import ParamFlowRule

    route = "gw_route"
    gateway_rule_manager.load_rules(
        [GatewayFlowRule(route, count=1e9,
                         param_item=GatewayParamFlowItem(
                             parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP))]
    )
    eng.set_param_rules(
        {route: [ParamFlowRule(route, param_idx=0, count=1e9)]}
    )
    eng.set_flow_rules(
        [FlowRule(resource=f"r{i}", count=1e9) for i in range(n_rules)]
        + [FlowRule(resource=route, count=1e9)]
    )
    # One columnar group per flush — the gateway batching-window shape —
    # sized just under max_batch so the explicit flush() below does the
    # work (at exactly max_batch, flush-on-size fires inside submit and
    # the submit/flush breakdown splits in the wrong place).
    adapter_n = min(groups * bulk_n, eng.max_batch) - 1
    # Heavy-hitter mix (~256 requests per distinct value): same-ts
    # uniform-acquire batches take the closed-form rank path
    # (param_rounds = −1), so per-value multiplicity no longer forces
    # the sequential scan.
    n_ips = max(256, adapter_n // 256)
    infos = [
        GatewayRequestInfo(
            path="/api/x",
            client_ip=f"10.{(i % n_ips) >> 16 & 255}.{(i % n_ips) >> 8 & 255}.{i % n_ips & 255}",
        )
        for i in range(adapter_n)
    ]
    g = gateway_submit_bulk(route, infos, engine=eng)
    eng.flush()  # warm-up: interning + param-kernel compile
    assert g is not None and g.admitted is not None
    # Timed loop with host-side breakdown: parse_ms is the per-window
    # column extraction (the true ingest floor — one attribute read per
    # request into a GatewayRequestBatch column), submit_ms the
    # gateway parse + bulk enqueue, encode_ms / kernel_ms from the
    # engine's own flush attribution.
    t_parse = t_submit = t_encode = t_kernel = 0.0
    t0 = time.perf_counter()
    for _ in range(iters):
        ta = time.perf_counter()
        batch = GatewayRequestBatch(
            n=adapter_n, client_ip=[i.client_ip for i in infos]
        )
        tb = time.perf_counter()
        gateway_submit_bulk(route, batch, engine=eng)
        tc = time.perf_counter()
        eng.flush()
        ft = eng.last_flush_host_ms
        t_parse += tb - ta
        t_submit += tc - tb
        t_encode += ft["encode_ms"]
        t_kernel += ft["kernel_ms"]
    dta = (time.perf_counter() - t0) / iters
    adapter_ops_per_sec = adapter_n / dta
    _log(
        f"engine adapter (gateway bulk) done: {adapter_ops_per_sec:,.0f} ops/sec"
        f" ({adapter_ops_per_sec / bulk_ops_per_sec:.2f}x of bulk; "
        f"parse {t_parse / iters * 1e3:.1f} submit {t_submit / iters * 1e3:.1f} "
        f"encode {t_encode / iters:.1f} kernel {t_kernel / iters:.1f} ms)"
    )

    # Pipelined bulk: flush_async keeps up to max_inflight device
    # round-trips in flight, so host encode of flush N+1 overlaps the
    # fetch latency of flush N — the remote-tunnel RTT amortizes.
    n_flushes = max(iters * 4, 8)
    for i in range(groups):
        eng.submit_bulk(f"r{i % n_rules}", bulk_n)
    eng.flush_async()
    eng.drain()  # warm the async path
    t0 = time.perf_counter()
    for _ in range(n_flushes):
        for i in range(groups):
            eng.submit_bulk(f"r{i % n_rules}", bulk_n)
        eng.flush_async()
    eng.drain()
    dtp = (time.perf_counter() - t0) / n_flushes
    pipe_ops_per_sec = groups * bulk_n / dtp
    _log(f"engine pipelined done: {pipe_ops_per_sec:,.0f} ops/sec end-to-end")

    # Depth-2 flush pipeline through the ADAPTER surface: the same
    # gateway window loop as above, but flush() now keeps 2 flushes in
    # flight (sentinel.tpu.host.pipeline.depth semantics) with one
    # coalesced verdict fetch per drain. dispatch_ms is the
    # host-blocking part of a pipelined flush — host/device overlap is
    # visible as dispatch_ms < the sync loop's kernel_ms; occupancy is
    # mean in-flight depth / 2.
    eng.pipeline_depth = 2
    gateway_submit_bulk(route, batch, engine=eng, flush=True)
    eng.drain()  # warm the pipelined path
    eng.pipeline_stats(reset=True)
    t_p_dispatch = t_p_drain = 0.0
    t0 = time.perf_counter()
    for _ in range(iters):
        batch = GatewayRequestBatch(
            n=adapter_n, client_ip=[i.client_ip for i in infos]
        )
        gateway_submit_bulk(route, batch, engine=eng, flush=True)
        ft = eng.last_flush_host_ms
        t_p_dispatch += ft["dispatch_ms"]
        t_p_drain += ft["drain_ms"]
    eng.drain()
    # The trailing drain of the last `depth` in-flight flushes lands in
    # the final flush's breakdown AFTER its ft read above — add the
    # delta or drain_ms under-reports by the pipeline's tail.
    t_p_drain += eng.last_flush_host_ms["drain_ms"] - ft["drain_ms"]
    dtap = (time.perf_counter() - t0) / iters
    ps = eng.pipeline_stats(reset=True)
    occupancy = ps["mean_inflight"] / 2.0
    adapter_pipe_ops_per_sec = adapter_n / dtap
    eng.pipeline_depth = 0
    _log(
        f"engine adapter pipelined (depth 2) done:"
        f" {adapter_pipe_ops_per_sec:,.0f} ops/sec"
        f" (dispatch {t_p_dispatch / iters:.1f} drain {t_p_drain / iters:.1f} ms,"
        f" occupancy {occupancy:.2f})"
    )

    # Flight-recorder (runtime/capture.py) overhead: same-run A/B on
    # the bulk surface at pipeline depths {0, 2}. The journal's spill
    # path is one vectorized encode_entries_columns frame per bulk
    # group, so the ratio should sit near 1.0. The full-size bulk
    # flush is the wrong sample here — per-flush wall time on a shared
    # box drifts ±20%, far more than the effect — so the A/B uses a
    # smaller window (16 groups × 1024 rows, ~10× cheaper per sample),
    # INTERLEAVES off/on pairs (settle + 2 timed flushes per arm per
    # rep), and takes min(on)/min(off): the box noise is one-sided
    # additive, and min over 5 reps filters it.
    import shutil
    import tempfile

    from sentinel_tpu.runtime.capture import CaptureJournal

    cap_groups, cap_bulk_n = 16, 1024

    def _cap_timed_unit():
        for _j in range(2):
            for i in range(cap_groups):
                eng.submit_bulk(f"r{i % n_rules}", cap_bulk_n)
            eng.flush()
            eng.drain()

    cap_cols = {}
    cap_tmp = tempfile.mkdtemp(prefix="bench-capture-")
    try:
        for depth in (0, 2):
            eng.pipeline_depth = depth
            off_s, on_s = [], []
            cap = None
            for _rep in range(5):
                if cap is not None:
                    cap.close()
                    eng.capture = None
                    cap = None
                _cap_timed_unit()  # settle
                t0 = time.perf_counter()
                _cap_timed_unit()
                off_s.append(time.perf_counter() - t0)
                cap = CaptureJournal(eng, directory=cap_tmp)
                cap.segment_bytes = 1 << 30  # no rollover I/O in the loop
                eng.capture = cap
                _cap_timed_unit()  # settle
                t0 = time.perf_counter()
                _cap_timed_unit()
                on_s.append(time.perf_counter() - t0)
            cap_bytes = (
                cap.snapshot()["counters"]["bytes"] if cap is not None else 0
            )
            if cap is not None:
                cap.close()
                eng.capture = None
            ratio = min(on_s) / min(off_s)
            cap_cols[f"engine_capture_overhead_d{depth}"] = round(ratio, 4)
            if depth == 0:
                # Journal growth per armed flush (KiB) — the disk-rate
                # context for the overhead ratio. The last rep's
                # journal saw exactly 4 armed flushes (settle + timed).
                cap_cols["engine_capture_kb_per_flush"] = round(
                    cap_bytes / 4 / 1024.0, 1
                )
            _log(
                f"engine capture overhead depth {depth}: "
                f"{(ratio - 1) * 100:+.2f}% "
                f"(off {min(off_s) * 1e3:.0f} ms on {min(on_s) * 1e3:.0f} ms)"
            )
    finally:
        eng.pipeline_depth = 0
        shutil.rmtree(cap_tmp, ignore_errors=True)
    partial = {
        "engine_ops_per_sec": round(ops_per_sec, 1),
        "engine_n_rules": n_rules,
        "engine_n_ops": n_ops,
        "engine_bulk_ops_per_sec": round(bulk_ops_per_sec, 1),
        "engine_bulk_n_ops": groups * bulk_n,
        "engine_adapter_ops_per_sec": round(adapter_ops_per_sec, 1),
        "engine_adapter_vs_bulk": round(adapter_ops_per_sec / bulk_ops_per_sec, 3),
        # Host-side adapter breakdown (per flush, ms) — attributes the
        # adapter-vs-bulk gap for the next TPU window.
        "parse_ms": round(t_parse / iters * 1e3, 3),
        "submit_ms": round(t_submit / iters * 1e3, 3),
        "encode_ms": round(t_encode / iters, 3),
        "kernel_ms": round(t_kernel / iters, 3),
        "engine_pipelined_ops_per_sec": round(pipe_ops_per_sec, 1),
        "engine_pipelined_flushes": n_flushes,
        # Depth-2 flush pipeline (adapter surface): host-blocking
        # dispatch vs the sync loop's kernel_ms above shows the
        # host/device overlap directly for the next TPU capture.
        "engine_adapter_pipelined_ops_per_sec": round(adapter_pipe_ops_per_sec, 1),
        "dispatch_ms": round(t_p_dispatch / iters, 3),
        "drain_ms": round(t_p_drain / iters, 3),
        "pipeline_occupancy": round(occupancy, 3),
        # Flight-recorder arming cost (same-run on/off median ratio on
        # the bulk loop): ~1.0 means capture is free at flush scale.
        **cap_cols,
        # Flight-recorder view of the whole stage (metrics/telemetry.py):
        # latency tails + arena hit rate + blocked sketch — the numbers
        # the /metrics scrape and the telemetry command would serve.
        "telemetry": eng.telemetry.bench_summary(),
    }
    # Emit the completed measurements NOW: the latency block below
    # compiles one more (1-op, pad-8) kernel shape, and through a
    # wedgy tunnel that compile can outlive the stage timeout — the
    # parent salvages the last JSON line from a timed-out child.
    print(json.dumps(partial), flush=True)

    # Sync-mode latency: one entry, one flush, one verdict — the
    # worst-case interactive path (on TPU this is dominated by the
    # per-dispatch + fetch round-trip, not the kernel).
    lat_n = 20
    op = eng.submit_entry("r0")
    eng.flush()  # warm the 1-op shape
    t0 = time.perf_counter()
    for _ in range(lat_n):
        op = eng.submit_entry("r0")
        eng.flush()
    sync_ms = (time.perf_counter() - t0) / lat_n * 1e3
    assert op is not None and op.verdict is not None
    _log(f"engine sync latency: {sync_ms:.2f} ms/entry")
    return {"engine_sync_latency_ms": round(sync_ms, 3), **partial}


def _run_speculative_stage(n_rules: int, n_ops: int, iters: int) -> dict:
    """Speculative admission tier (runtime/speculative.py): per-entry
    wall latency of the host fast path (p50/p99 — the sub-100 µs story
    the ROADMAP targets, vs the ~ms sync device round-trip) plus the
    measured per-window drift after settlement reconciles the same ops
    against device truth."""
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.runtime.engine import Engine
    from sentinel_tpu.utils.config import config

    n_rules, n_ops, iters = max(1, n_rules), max(1, n_ops), max(1, iters)
    _log(f"speculative stage rules={n_rules} ops={n_ops}")
    config.set(config.SPECULATIVE_ENABLED, "true")
    config.set(config.SPECULATIVE_FLUSH_BATCH, "256")
    eng = Engine(initial_rows=max(1024, n_rules * 2))
    # Production shape: the background flusher owns settlement, so the
    # admission thread never pays a device dispatch (engine.
    # _spec_maybe_settle skips when the auto-flusher runs).
    eng.start_auto_flush()
    # Thresholds sized so roughly half the stream blocks — both verdict
    # paths (admit and block) are on the timed path, like production.
    eng.set_flow_rules(
        [FlowRule(resource=f"r{i}", count=float(max(1, n_ops // (2 * n_rules))))
         for i in range(n_rules)]
    )
    names = [f"r{i % n_rules}" for i in range(n_ops)]
    for name in names[:256]:
        eng.entry_sync(name)  # warm: interning + first settle compile
    eng.flush()
    eng.drain()
    lat: list[float] = []
    t0 = time.perf_counter()
    for _ in range(iters):
        for name in names:
            ta = time.perf_counter()
            eng.entry_sync(name)
            lat.append(time.perf_counter() - ta)
        eng.flush()  # settle + reconcile between rounds
    eng.stop_auto_flush()
    eng.flush()
    eng.drain()
    dt = (time.perf_counter() - t0) / iters
    lat.sort()
    p50 = lat[len(lat) // 2] * 1e6
    p99 = lat[int(len(lat) * 0.99)] * 1e6

    # --- system-gate column (PR 7): the same timed loop with a system
    # rule configured — a wide-open QPS threshold, so the number is the
    # host gate's OVERHEAD on the fast path, not blocking behavior.
    from sentinel_tpu.models import constants as _C
    from sentinel_tpu.rules.system_manager import SystemConfig

    eng.start_auto_flush()
    eng.set_system_config(SystemConfig(qps=float(n_ops) * 100.0))
    lat_sys: list[float] = []
    for name in names:
        ta = time.perf_counter()
        eng.entry_sync(name, entry_type=_C.EntryType.IN)
        lat_sys.append(time.perf_counter() - ta)
    eng.stop_auto_flush()
    eng.flush()
    eng.drain()
    eng.set_system_config(None)
    lat_sys.sort()
    sys_p50 = lat_sys[len(lat_sys) // 2] * 1e6
    sys_p99 = lat_sys[int(len(lat_sys) * 0.99)] * 1e6

    # --- shed column (PR 7): verdict latency of the ingest valve's
    # BLOCK_SHED fast path (runtime/ingest.py) — the "fast distinct
    # verdict under saturation" number.
    from sentinel_tpu.runtime.ingest import IngestValve

    config.set(config.INGEST_DEADLINE_MS, "1")
    eng.ingest = IngestValve(eng)
    eng.ingest.force_latency_ms(1000.0)  # everything sheds
    lat_shed: list[float] = []
    for name in names[: max(1, min(len(names), 4096))]:
        ta = time.perf_counter()
        _op, v = eng.entry_sync(name)
        lat_shed.append(time.perf_counter() - ta)
    config.set(config.INGEST_DEADLINE_MS, "0")
    shed_total = eng.ingest.counters["shed_entries"]
    eng.ingest = IngestValve(eng)
    lat_shed.sort()
    shed_p50 = lat_shed[len(lat_shed) // 2] * 1e6
    shed_p99 = lat_shed[int(len(lat_shed) * 0.99)] * 1e6

    snap = eng.speculative.snapshot()
    c = snap["counters"]
    _log(
        f"speculative stage done: p50 {p50:.1f} µs p99 {p99:.1f} µs "
        f"(system-gated p50 {sys_p50:.1f} µs, shed p50 {shed_p50:.1f} µs; "
        f"{n_ops / dt:,.0f} ops/s incl. settles; "
        f"over {c['over_admits']} under {c['under_admits']} "
        f"across {c['windows']} windows, max/window "
        f"{snap['max_over_admit_window']})"
    )
    return {
        "spec_entry_p50_us": round(p50, 2),
        "spec_entry_p99_us": round(p99, 2),
        "spec_ops_per_sec": round(n_ops / dt, 1),
        "spec_over_admits": c["over_admits"],
        "spec_under_admits": c["under_admits"],
        "spec_reconciled": c["reconciled"],
        "spec_windows": c["windows"],
        "spec_max_over_admit_window": snap["max_over_admit_window"],
        "spec_declined": c["spec_declined"],
        "spec_shaped": c["spec_shaped"],
        "spec_system_blocks": c["spec_system_blocks"],
        "spec_entry_sys_p50_us": round(sys_p50, 2),
        "spec_entry_sys_p99_us": round(sys_p99, 2),
        "shed_entry_p50_us": round(shed_p50, 2),
        "shed_entry_p99_us": round(shed_p99, 2),
        "shed_total": shed_total,
    }


def _run_sketch_stage(n_rules: int, n_ops: int, iters: int) -> dict:
    """Sketch tier (runtime/sketch.py): engine flush throughput over a
    high-cardinality param-value stream with the tier ON (cold values
    pass via the fixed-size device sketch) vs OFF (today: every value
    interns a dense row, LRU churning) — the update-cost A/B — plus a
    promotion-storm latency (wall ms until 16 simultaneous hot keys all
    hold exact dense rows) and the candidate-table occupancy."""
    from sentinel_tpu.models.rules import ParamFlowRule
    from sentinel_tpu.runtime.engine import Engine
    from sentinel_tpu.utils.config import config

    n_ops, iters = max(64, n_ops), max(1, iters)
    _log(f"sketch stage ops={n_ops}")
    rule = ParamFlowRule(
        resource="api", param_idx=0, count=1e9, sketch_mode=True
    )

    def _stream(eng, warm_decay: bool = False) -> float:
        """Flush ``iters`` batches of n_ops distinct-per-batch values;
        returns ops/sec. ``warm_decay`` warms BOTH decay-flag kernel
        variants before timing (sleep past one decay window, flush
        again): the decay=True variant otherwise compiles INSIDE the
        timed loop and a ~1 s one-time XLA compile swamps the 3-iter
        measurement — BENCH_r07's ON number was exactly that artifact.
        """
        uid = [0]

        def batch():
            col = [(f"v{uid[0] + j}",) for j in range(n_ops)]
            uid[0] += n_ops
            return col

        eng.submit_bulk("api", n=n_ops, args_column=batch())
        eng.flush()  # compile + warm (decay=False variant)
        if warm_decay:
            time.sleep(1.05)  # roll one real decay window
            eng.submit_bulk("api", n=n_ops, args_column=batch())
            eng.flush()  # compile + warm (decay=True variant)
        t0 = time.perf_counter()
        for _ in range(iters):
            eng.submit_bulk("api", n=n_ops, args_column=batch())
            eng.flush()
        eng.drain()
        return n_ops * iters / (time.perf_counter() - t0)

    try:
        config.set(config.SKETCH_ENABLED, "false")
        eng_off = Engine()
        eng_off.set_param_rules({"api": [rule]})
        off_ops = _stream(eng_off)
        eng_off.close()

        config.set(config.SKETCH_ENABLED, "true")
        config.set(config.SKETCH_PROMOTE_QPS, "50")
        config.set(config.SKETCH_WINDOW_MS, "1000")
        eng_on = Engine()
        eng_on.set_param_rules({"api": [rule]})
        on_ops = _stream(eng_on, warm_decay=True)

        # Promotion storm: 16 hot keys appear at once; wall time until
        # every one holds an exact dense row (bounded-flushes contract).
        hot = [f"hot{i}" for i in range(16)]
        t0 = time.perf_counter()
        storm_flushes = 0
        storm_ms = None
        for step in range(40):
            col = [(h,) for h in hot for _ in range(16)]
            eng_on.submit_bulk("api", n=len(col), args_column=col)
            eng_on.flush()
            eng_on.drain()
            storm_flushes += 1
            promoted = eng_on.sketch.promoted_values.get("api", frozenset())
            if all(h in promoted for h in hot):
                storm_ms = (time.perf_counter() - t0) * 1e3
                break
            time.sleep(0.06)  # real clock: let decay windows roll
        occupancy = eng_on.sketch.occupancy
        promoted_n = eng_on.sketch.promoted_count
        eng_on.close()
    finally:
        for key in (config.SKETCH_ENABLED, config.SKETCH_PROMOTE_QPS,
                    config.SKETCH_WINDOW_MS):
            config.set(key, config.DEFAULTS[key])

    import jax

    _log(
        f"sketch stage done: on {on_ops:,.0f} ops/s vs off {off_ops:,.0f}"
        f" ops/s; storm "
        + (f"{storm_ms:.0f} ms" if storm_ms is not None else "INCOMPLETE")
        + f" / {storm_flushes} flushes, promoted {promoted_n},"
        f" occupancy {occupancy:.2f}"
    )
    out = {
        "sketch_n_ops": n_ops,
        "sketch_ops_per_sec_on": round(on_ops, 1),
        "sketch_ops_per_sec_off": round(off_ops, 1),
        "sketch_promote_storm_flushes": storm_flushes,
        "sketch_promoted": promoted_n,
        "sketch_occupancy": round(occupancy, 4),
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
    }
    if storm_ms is not None:
        # An incomplete storm (promotion never converged — noisy box
        # or a regression) OMITS the metric rather than recording a
        # bogus 0.0 a later benchgate baseline would gate against.
        out["sketch_promote_storm_ms"] = round(storm_ms, 1)
    return out


def _run_adapters_stage(n_rules: int, n_ops: int, iters: int) -> dict:
    """Adapter matrix (runtime/window.py): per-adapter ops/s with the
    batch window OFF (today's per-request submit+flush) vs ON (columnar
    windows), p50/p99 request latency in both modes, plus two same-run
    references: ``gateway_bulk`` (gateway_submit_bulk + columnar exit
    accounting — the columnar ceiling) and ``spine`` (the window
    machinery batch-driven: join + group + columnar submit + fan-out +
    bulk exits, no per-request concurrency harness — the adapter-edge
    cost the ≥0.8x-of-bulk acceptance bounds; the per-adapter
    concurrency numbers additionally pay driver + GIL cost, which is
    the 1-core box's tax, not the spine's).

    Adapters whose framework is not installed (flask/fastapi) are
    skipped with a log line — their metrics are simply absent and the
    gate treats them as not comparable."""
    import asyncio
    import threading

    import numpy as np  # noqa: F401

    from sentinel_tpu.core import api
    from sentinel_tpu.models import constants as KC
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.rules.flow_manager import flow_rule_manager
    from sentinel_tpu.utils.config import config

    n_ops, iters = max(256, n_ops), max(1, iters)
    _log(f"adapters stage ops={n_ops}")
    out: dict = {"adapters_n_ops": n_ops}

    RES = "GET:/bench"
    OFF_OPS = max(128, n_ops // 8)  # off mode is ~one flush per request

    def _reset(window: bool):
        config.set(config.INGEST_BATCH_WINDOW_MS, "2" if window else "0")
        config.set(config.INGEST_BATCH_MAX, "256")
        eng = api.reset()
        flow_rule_manager.load_rules(
            [FlowRule(RES, count=1e9), FlowRule("route", count=1e9)]
        )
        return eng

    def _pcts(lat):
        lat.sort()
        return (
            lat[len(lat) // 2] * 1e6,
            lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e6,
        )

    # ---- same-run gateway-bulk reference (with exit accounting) ----
    from sentinel_tpu.adapters.gateway import (
        GatewayRequestBatch,
        gateway_submit_bulk,
    )

    eng = _reset(window=False)
    nb = 256
    batch = GatewayRequestBatch(n=nb, client_ip=["1.2.3.4"] * nb)

    def _bulk_once():
        op = gateway_submit_bulk("route", batch, flush=True)
        if op is not None:
            adm = op.admitted
            eng.submit_exit_bulk(
                op.rows, max(1, int(adm.sum())), rt=1, resource="route"
            )

    for _ in range(8):
        _bulk_once()
    eng.flush()
    eng.drain()
    rounds = max(1, n_ops // nb)
    bulk_best = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(rounds):
            _bulk_once()
        eng.flush()
        eng.drain()
        bulk_best = max(bulk_best, rounds * nb / (time.perf_counter() - t0))
    out["adapters_gateway_bulk_ops_per_sec"] = round(bulk_best, 1)
    _log(f"adapters: gateway-bulk {bulk_best:,.0f} ops/s")

    # ---- the spine, batch-driven (window machinery cost per request) ----
    from sentinel_tpu.runtime.window import WindowRequest

    eng = _reset(window=True)
    w = eng.ingest_window

    def _spine_round(total):
        reqs = []
        now = eng.clock.now_ms()
        for _ in range(total):
            r = WindowRequest(
                RES, KC.CONTEXT_DEFAULT_NAME, "", 1, KC.EntryType.IN, (),
                now, None,
            )
            w.join(r)
            reqs.append(r)
        for r in reqs:
            if r.verdict is None and r.error is None:
                r.event.wait(60)
        for r in reqs:
            v = r.verdict
            if v is not None and v.admitted:
                w.note_exit(r.rows, RES, 1, 1, 0, bool(v.speculative))

    for _ in range(3):
        _spine_round(n_ops // 2)  # warm every window-size pad bucket
    spine_best = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        _spine_round(n_ops)
        spine_best = max(spine_best, n_ops / (time.perf_counter() - t0))
    eng.flush()
    eng.drain()
    out["adapters_spine_on_ops_per_sec"] = round(spine_best, 1)
    out["adapters_spine_vs_bulk"] = round(spine_best / max(bulk_best, 1e-9), 4)
    _log(
        f"adapters: spine {spine_best:,.0f} ops/s "
        f"({out['adapters_spine_vs_bulk']:.2f}x of bulk)"
    )

    # ---- per-adapter drivers ----
    def _sync_driver(call, total, threads=64):
        lat: list = []
        lock = threading.Lock()
        per = max(1, total // threads)

        def worker():
            mine = []
            for _ in range(per):
                t0 = time.perf_counter()
                call()
                mine.append(time.perf_counter() - t0)
            with lock:
                lat.extend(mine)

        ths = [threading.Thread(target=worker) for _ in range(threads)]
        t0 = time.perf_counter()
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        dt = time.perf_counter() - t0
        return per * threads / dt, lat

    def _async_driver(acall, total, conc=256):
        lat: list = []

        async def _main():
            sem = asyncio.Semaphore(conc)

            async def one():
                async with sem:
                    t0 = time.perf_counter()
                    await acall()
                    lat.append(time.perf_counter() - t0)

            await asyncio.gather(*[one() for _ in range(total)])

        t0 = time.perf_counter()
        asyncio.run(_main())
        return total / (time.perf_counter() - t0), lat

    def _measure(name, driver, call, on_total, off_total):
        nonlocal eng
        for window in (False, True):
            eng = _reset(window)
            total = on_total if window else off_total
            if window:
                # Four full warm rounds: this driver's window sizes
                # set the padded kernel shapes (entry × exit pad-bucket
                # PAIRS each compile once), and an XLA compile inside a
                # timed round would swamp it (the r07 lesson).
                for _ in range(4):
                    driver(call, total)
            else:
                driver(call, max(128, total // 4))
            best, best_lat = 0.0, []
            # Window-on gets extra rounds: a ragged TAIL window whose
            # padded shape was never warmed costs a ~1.6 s XLA compile
            # in whichever round first sees it — best-of over more
            # rounds makes one clean round near-certain.
            for _ in range(iters + (4 if window else 0)):
                ops, lat = driver(call, total)
                if ops > best:
                    best, best_lat = ops, lat
            eng.flush()
            eng.drain()
            mode = "on" if window else "off"
            p50, p99 = _pcts(best_lat)
            out[f"adapters_{name}_{mode}_ops_per_sec"] = round(best, 1)
            out[f"adapters_{name}_{mode}_p50_us"] = round(p50, 1)
            out[f"adapters_{name}_{mode}_p99_us"] = round(p99, 1)
            _log(
                f"adapters: {name} window-{mode} {best:,.0f} ops/s "
                f"p50 {p50:,.0f}us p99 {p99:,.0f}us"
            )

    # WSGI (stands in for Flask's WSGI mount when flask is absent).
    from sentinel_tpu.adapters import (
        SentinelASGIMiddleware,
        SentinelWSGIMiddleware,
    )

    def _wsgi_inner(environ, start_response):
        start_response("200 OK", [])
        return [b"ok"]

    wapp = SentinelWSGIMiddleware(_wsgi_inner, total_resource=None)

    def _wsgi_call():
        environ = {"PATH_INFO": "/bench", "REQUEST_METHOD": "GET"}
        b"".join(wapp(environ, lambda s, h: None))

    _measure("wsgi", _sync_driver, _wsgi_call, n_ops, OFF_OPS)

    # ASGI (stands in for FastAPI's app-wide mount when absent).
    async def _asgi_inner(scope, receive, send):
        await send({"type": "http.response.start", "status": 200,
                    "headers": []})
        await send({"type": "http.response.body", "body": b"ok"})

    aapp = SentinelASGIMiddleware(_asgi_inner, total_resource=None)
    _scope = {"type": "http", "method": "GET", "path": "/bench"}

    async def _recv():
        return {"type": "http.request"}

    async def _send(msg):
        pass

    async def _asgi_call():
        await aapp(_scope, _recv, _send)

    _measure("asgi", _async_driver, _asgi_call, n_ops, OFF_OPS)

    # aiohttp middleware (gated on the framework being importable).
    try:
        from aiohttp.test_utils import make_mocked_request

        from sentinel_tpu.adapters.aiohttp_adapter import sentinel_middleware

        mw = sentinel_middleware()

        async def _handler(request):
            from aiohttp import web

            return web.Response(text="ok")

        # One shared mocked request: building one costs ~2 ms — that
        # would be the driver benching aiohttp's test kit, not the
        # adapter. The middleware only READS it (method/path/headers).
        _aio_req = make_mocked_request("GET", "/bench")

        async def _aio_call():
            await mw(_aio_req, _handler)

        _measure("aiohttp", _async_driver, _aio_call, n_ops, OFF_OPS)
    except ImportError:
        _log("adapters: aiohttp not installed — skipped")

    # gRPC server interceptor (no sockets: fake call details, real
    # grpc handler objects).
    try:
        import grpc  # noqa: F401

        from sentinel_tpu.adapters.grpc_adapter import (
            SentinelServerInterceptor,
        )

        class _Details:
            method = "/svc/Bench"
            invocation_metadata = ()

        interceptor = SentinelServerInterceptor()

        def _continuation(details):
            import grpc as _g

            return _g.unary_unary_rpc_method_handler(lambda req, ctx: "ok")

        class _Ctx:
            def abort(self, code, details):
                raise RuntimeError("aborted")

        def _grpc_call():
            handler = interceptor.intercept_service(_continuation, _Details())
            if handler is not None and handler.unary_unary is not None:
                try:
                    handler.unary_unary(None, _Ctx())
                except RuntimeError:
                    pass  # blocked → abort; still one admission decided

        _measure("grpc", _sync_driver, _grpc_call, n_ops, OFF_OPS)
    except ImportError:
        _log("adapters: grpcio not installed — skipped")

    # Flask / FastAPI ride the same spine through their own hooks; when
    # installed they get first-class rows, otherwise the WSGI/ASGI rows
    # above are their stand-ins (identical windowed entry path).
    for name, mod in (("flask", "flask"), ("fastapi", "fastapi")):
        try:
            __import__(mod)
        except ImportError:
            _log(f"adapters: {mod} not installed — skipped "
                 f"({'wsgi' if name == 'flask' else 'asgi'} row is the "
                 "stand-in; same windowed entry path)")

    import jax

    api.reset()
    for key in (config.INGEST_BATCH_WINDOW_MS, config.INGEST_BATCH_MAX):
        config.set(key, config.DEFAULTS[key])
    out.update(
        {
            "platform": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "jax_version": jax.__version__,
        }
    )
    return out


def _run_autotune_stage(n_rules: int, n_ops: int, iters: int) -> dict:
    """Self-tuning control plane (runtime/autotune.py): converge-from-
    cold A/B. First measure a pipelined bulk workload at each static
    pipeline depth (the hand-tuning an operator would do per box), then
    run the SAME workload autotune-on starting cold at depth 0 and
    report the chosen depth/window trajectory and steady-state ops/s
    against the best static setting. PR-12 acceptance: steady-state
    >= 0.9x static-best on this box, and the decision log is a monotone
    settle (no knob reversal under the steady stream)."""
    import jax

    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.runtime.engine import Engine
    from sentinel_tpu.utils.config import config

    n_rules, n_ops, iters = max(1, n_rules), max(256, n_ops), max(1, iters)
    groups = 16
    bulk_n = max(64, n_ops // groups)
    per_flush = groups * bulk_n
    _log(f"autotune stage rules={n_rules} ops/flush={per_flush}")

    def _mk() -> Engine:
        eng = Engine(initial_rows=max(1024, n_rules * 2))
        eng.set_flow_rules(
            [FlowRule(resource=f"r{i}", count=1e9) for i in range(n_rules)]
        )
        return eng

    def _workload(eng, rounds: int) -> None:
        for _ in range(rounds):
            for i in range(groups):
                eng.submit_bulk(f"r{i % n_rules}", bulk_n)
            eng.flush()
        eng.drain()

    def _measure(eng, rounds: int) -> float:
        t0 = time.perf_counter()
        _workload(eng, rounds)
        return per_flush * rounds / (time.perf_counter() - t0)

    rounds = max(8, iters * 8)
    tuned_keys = (
        config.PIPELINE_DEPTH, config.AUTOTUNE_ENABLED,
        config.AUTOTUNE_INTERVAL_MS, config.AUTOTUNE_COOLDOWN_MS,
        config.AUTOTUNE_MIN_FLUSHES, config.AUTOTUNE_DEPTH_MAX,
    )
    try:
        # --- static sweep: the hand-tuned baselines.
        static: dict[int, float] = {}
        config.set(config.AUTOTUNE_ENABLED, "false")
        for depth in (0, 1, 2):
            config.set(config.PIPELINE_DEPTH, str(depth))
            eng = _mk()
            _workload(eng, 2)  # warm: interning + kernel compile
            static[depth] = _measure(eng, rounds)
            eng.close()
            _log(f"autotune static depth={depth}: {static[depth]:,.0f} ops/s")
        best_depth = max(static, key=static.__getitem__)
        best_ops = static[best_depth]

        # --- converge from cold: depth 0, controller on, fast cadence
        # (real-clock ticks ride every drain; the decision interval is
        # shortened so convergence fits the bench budget).
        config.set(config.PIPELINE_DEPTH, "0")
        config.set(config.AUTOTUNE_ENABLED, "true")
        config.set(config.AUTOTUNE_INTERVAL_MS, "25")
        config.set(config.AUTOTUNE_COOLDOWN_MS, "50")
        # At this stage's big-flush cadence (one multi-ms flush per
        # tick window) a single settled span is already a large sample
        # — the production default of 8 is sized for kHz flush rates.
        config.set(config.AUTOTUNE_MIN_FLUSHES, "1")
        config.set(config.AUTOTUNE_DEPTH_MAX, "4")
        eng = _mk()
        _workload(eng, 2)  # warm compile (depth may move mid-round)
        converge_ops = _measure(eng, rounds)  # the cold->settled span
        # Best-of-2 steady measurement (the adapters stage's defense
        # against the box's tenancy noise — a single later-in-run
        # sample loses ~10% to drift alone).
        steady_ops = max(_measure(eng, rounds), _measure(eng, rounds))
        traj = [
            {"knob": d["knob"], "from": d["from"], "to": d["to"],
             "reason": d["reason"]}
            for d in eng.autotune.decisions
        ]
        final_depth = eng.pipeline_depth
        ticks = eng.autotune.counters["ticks"]
        eng.close()
    finally:
        for key in tuned_keys:
            config.set(key, config.DEFAULTS[key])

    ratio = steady_ops / best_ops if best_ops > 0 else 0.0
    depth_moves = [d["to"] for d in traj if d["knob"] == "depth"]
    monotone = all(b >= a for a, b in zip(depth_moves, depth_moves[1:]))
    _log(
        f"autotune stage done: steady {steady_ops:,.0f} ops/s vs static-best "
        f"depth={best_depth} {best_ops:,.0f} ({ratio:.2f}x, accept >=0.9); "
        f"final depth {final_depth}, {len(traj)} decisions over {ticks} "
        f"ticks, monotone={monotone}"
    )
    return {
        "autotune_n_rules": n_rules,
        "autotune_n_ops": per_flush,
        "autotune_static_ops_per_sec": {
            str(d): round(v, 1) for d, v in static.items()
        },
        "autotune_static_best_depth": best_depth,
        "autotune_static_best_ops_per_sec": round(best_ops, 1),
        "autotune_converge_ops_per_sec": round(converge_ops, 1),
        "autotune_steady_ops_per_sec": round(steady_ops, 1),
        "autotune_vs_static_best": round(ratio, 4),
        "autotune_final_depth": final_depth,
        "autotune_decisions": len(traj),
        "autotune_monotone": monotone,
        "autotune_trajectory": traj,
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
    }


def _ipc_bench_worker(
    channel, wid, resources, rows_total, group, go_warm, go_timed, out_q
):
    """One bench worker process: attach, signal ready, run one full
    WARM quota round (interning, frame shapes, the engine-side settle
    compiles), then the timed round — so the measured span is steady-
    state transport, not XLA compiles. Top-level so the spawn child can
    import it by name."""
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)

    def _round() -> int:
        admitted = 0
        done = 0
        i = 0
        while done < rows_total:
            n = min(group, rows_total - done)
            a, _r, _w, _f = cli.bulk(resources[i % len(resources)], n)
            admitted += int(a.sum())
            done += n
            i += 1
        return admitted

    try:
        out_q.put(("ready", wid, 0))
        go_warm.wait(timeout=120)
        _round()
        out_q.put(("warm", wid, 0))
        go_timed.wait(timeout=300)
        admitted = _round()
        out_q.put(("done", wid, admitted))
    finally:
        cli.close()


def _ipc_sweep_worker(
    channel, wid, resources, quota, threads, cfg, go, out_q
):
    """One sweep worker process: ``threads`` concurrent entry() loops
    totaling ``quota`` admissions. ``cfg`` replays the mode under test
    into the child (micro-window on/off) — spawn children start from
    config defaults. Top-level so the spawn child imports it by name."""
    import threading as _th

    from sentinel_tpu.utils.config import config as _cfg

    for k, v in cfg.items():
        _cfg.set(k, v)
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)
    try:
        out_q.put(("ready", wid, 0))
        go.wait(timeout=300)
        per = max(1, quota // threads)

        def loop():
            for i in range(per):
                cli.entry(resources[i % len(resources)], timeout_ms=120000)

        ts = [_th.Thread(target=loop) for _ in range(threads)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.perf_counter() - t0
        out_q.put(("done", wid, (per * threads, dt, dict(cli.counters))))
    finally:
        cli.close()


def _bench_restart_setup(engine) -> None:
    """Supervised-engine setup for the restart-outage measurement
    (top-level so multiprocessing spawn children import it by name)."""
    from sentinel_tpu.models.rules import FlowRule

    engine.set_flow_rules([FlowRule(resource="r0", count=1e9)])


def _run_ipc_stage(n_rules: int, n_ops: int, iters: int) -> dict:
    """Multi-process ingest plane (sentinel_tpu/ipc): N-worker vs
    in-process A/B. The same bulk workload is pushed (a) by N real
    worker processes through the shared-memory rings and (b) by an
    in-process driver straight into submit_bulk — the delta is the
    plane's frame + ring cost, the ratio is the scale-out story's
    baseline number. Plus the single-entry shared-memory round-trip
    percentiles from an in-process client (frame encode -> ring ->
    plane decode -> columnar submit -> verdict frame)."""
    import jax

    from sentinel_tpu.ipc.plane import IngestPlane
    from sentinel_tpu.ipc.worker import IngestClient
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.runtime.engine import Engine
    from sentinel_tpu.utils.config import config

    n_rules = max(1, min(n_rules, 64))
    n_ops = max(512, n_ops)
    # One bulk call = one frame: keep the group inside the slot's
    # entry-frame budget so a call never splits into two round trips.
    group = 224
    n_workers = 2
    resources = [f"r{i}" for i in range(n_rules)]
    _log(f"ipc stage rules={n_rules} ops={n_ops} workers={n_workers}")

    config.set(config.SPECULATIVE_ENABLED, "true")
    config.set(config.SPECULATIVE_FLUSH_BATCH, "4096")
    # No mid-measure reaps: the workers do not exit their admissions
    # (the rule is wide open), and a dead-worker sweep firing between
    # phases would run exit-bulk compiles inside the timed spans.
    config.set(config.IPC_WORKER_DEAD_MS, "120000")
    try:
        eng = Engine(initial_rows=max(1024, n_rules * 2))
        eng.set_flow_rules(
            [FlowRule(resource=r, count=1e9) for r in resources]
        )

        # --- in-process baseline: the same bulk cadence, no plane.
        def _inproc(total: int) -> float:
            t0 = time.perf_counter()
            done = 0
            i = 0
            while done < total:
                n = min(group, total - done)
                eng.submit_bulk(resources[i % n_rules], n)
                done += n
                i += 1
            eng.flush()
            eng.drain()
            return total / (time.perf_counter() - t0)

        _inproc(group * 4)  # warm: compile + interning
        inproc_ops = max(_inproc(n_ops), _inproc(n_ops))

        # --- the plane + N spawned workers, quota split evenly; one
        # full warm round before the timed one (see _ipc_bench_worker).
        plane = IngestPlane(eng)
        ctx = plane.spawn_context()
        go_warm = ctx.Event()
        go_timed = ctx.Event()
        out_q = ctx.Queue()
        quota = n_ops // n_workers
        procs = [
            ctx.Process(
                target=_ipc_bench_worker,
                args=(plane.channel(w), w, resources, quota, group,
                      go_warm, go_timed, out_q),
                daemon=True,
            )
            for w in range(n_workers)
        ]
        for p in procs:
            p.start()
        workers_ops = 0.0
        admitted = 0
        try:
            def _await(tag, timeout):
                seen = 0
                total = 0
                while seen < n_workers:
                    msg = out_q.get(timeout=timeout)
                    if msg[0] == tag:
                        seen += 1
                        total += msg[2]
                return total

            _await("ready", 120)
            go_warm.set()
            _await("warm", 300)
            t0 = time.perf_counter()
            go_timed.set()
            admitted = _await("done", 300)
            workers_ops = quota * n_workers / (time.perf_counter() - t0)
        finally:
            for p in procs:
                p.join(timeout=10)
                if p.is_alive():
                    p.terminate()

        # --- single-entry shared-memory round trip (in-process client).
        cli = IngestClient(plane.channel(n_workers), n_workers)
        for i in range(64):
            cli.entry(resources[i % n_rules])
        lats = []
        for i in range(1024):
            t0 = time.perf_counter()
            cli.entry(resources[i % n_rules])
            lats.append(time.perf_counter() - t0)
        eng.flush()
        lats.sort()
        p50 = lats[len(lats) // 2] * 1e6
        p99 = lats[int(len(lats) * 0.99)] * 1e6

        # --- span-armed decomposition (PR 18): the same round trip
        # with the fleet span journal recording, decomposing the e2e
        # verdict wait into client encode+push vs engine drain — plus
        # the armed/unarmed p50 ratio, the bench-side twin of the
        # slow-tier ≤2% overhead guard. Armed AFTER the headline
        # percentiles above, so those stay span-free.
        from sentinel_tpu.metrics.spans import get_journal as _get_spj

        spj = _get_spj()
        spans_before = len(spj.spans())
        spj.enabled = True
        lats_sp = []
        try:
            for i in range(1024):
                t0 = time.perf_counter()
                cli.entry(resources[i % n_rules])
                lats_sp.append(time.perf_counter() - t0)
        finally:
            spj.enabled = False
        eng.flush()
        lats_sp.sort()
        sp_p50 = lats_sp[len(lats_sp) // 2] * 1e6

        def _span_pcts(vals):
            vals = sorted(vals)
            if not vals:
                return 0.0, 0.0
            return (vals[len(vals) // 2], vals[int(len(vals) * 0.99)])

        new_spans = spj.spans()[spans_before:]
        admits_sp = [s for s in new_spans if s["name"] == "admit"]
        drains_sp = [s for s in new_spans if s["name"] == "drain"]
        e2e_p50, e2e_p99 = _span_pcts([s["dur"] for s in admits_sp])
        push_p50, _ = _span_pcts([s.get("push_ms", 0.0) for s in admits_sp])
        drain_p50, drain_p99 = _span_pcts([s["dur"] for s in drains_sp])
        span_overhead = sp_p50 / p50 if p50 > 0 else 0.0
        _log(
            f"ipc span decomposition: e2e p50 {e2e_p50 * 1e3:.0f} µs "
            f"(push {push_p50 * 1e3:.0f} µs, engine drain "
            f"{drain_p50 * 1e3:.0f} µs), armed/unarmed p50 ratio "
            f"{span_overhead:.3f}"
        )

        # --- concurrency sweep: 1/2/4 workers x per-call vs
        # micro-window (ISSUE 14). Per-call = PR-13 framing (one frame
        # per entry); window = the client-side micro-window coalescing
        # each worker's 8 concurrent request threads. Same plane, same
        # engine, same quota — the deltas are the frame amortization
        # story, the frames-per-entry columns its direct evidence.
        sweep_threads = 8
        sweep_quota = max(256, min(4096, n_ops // 2))
        window_cfg = {
            config.IPC_CLIENT_WINDOW_MS: "0.5",
            config.IPC_CLIENT_WINDOW_MAX: "256",
        }

        def _sweep_round(nw: int, mode_cfg: dict):
            ctx2 = plane.spawn_context()
            go = ctx2.Event()
            q2 = ctx2.Queue()
            procs2 = [
                ctx2.Process(
                    target=_ipc_sweep_worker,
                    args=(plane.channel(3 + w), 3 + w, resources,
                          sweep_quota, sweep_threads, mode_cfg, go, q2),
                    daemon=True,
                )
                for w in range(nw)
            ]
            for p in procs2:
                p.start()
            try:
                seen = 0
                while seen < nw:
                    if q2.get(timeout=300)[0] == "ready":
                        seen += 1
                go.set()
                total_ops = 0
                max_dt = 0.0
                frames = 0
                reqs = 0
                policy = 0
                sheds = 0
                seen = 0
                while seen < nw:
                    msg = q2.get(timeout=600)
                    if msg[0] != "done":
                        continue
                    ops, dt, c = msg[2]
                    seen += 1
                    total_ops += ops
                    max_dt = max(max_dt, dt)
                    frames += c.get("frames", 0)
                    reqs += c.get("entries", 0)
                    policy += c.get("policy_served", 0)
                    sheds += c.get("sheds", 0)
                ops_s = total_ops / max_dt if max_dt > 0 else 0.0
                fpe = frames / reqs if reqs else 0.0
                return ops_s, fpe, policy, sheds
            finally:
                for p in procs2:
                    p.join(timeout=15)
                    if p.is_alive():
                        p.terminate()

        sweep: dict = {"ipc_sweep_quota": sweep_quota}
        fpe_percall = fpe_window = 0.0
        sweep_policy = sweep_sheds = 0
        for mode, mode_cfg in (("percall", {}), ("window", window_cfg)):
            for nw in (1, 2, 4):
                ops_s, fpe, policy, sheds = _sweep_round(nw, mode_cfg)
                sweep[f"ipc_{mode}_w{nw}_ops_per_sec"] = round(ops_s, 1)
                sweep_policy += policy
                sweep_sheds += sheds
                if nw == 1:
                    if mode == "percall":
                        fpe_percall = fpe
                    else:
                        fpe_window = fpe
                _log(
                    f"ipc sweep {mode} w{nw}: {ops_s:,.0f} ops/s "
                    f"(frames/entry {fpe:.3f}, policy {policy}, "
                    f"sheds {sheds})"
                )
        # The sweep's honesty columns (the single-entry A/B's
        # ipc_client_policy_served twin): ops/s rows where workers fell
        # to the local policy path or shed are measuring fallbacks, not
        # transport — a nonzero count flags the round as suspect.
        sweep["ipc_sweep_policy_served"] = sweep_policy
        sweep["ipc_sweep_sheds"] = sweep_sheds
        sweep["ipc_frames_per_entry_percall"] = round(fpe_percall, 4)
        sweep["ipc_frames_per_entry_window"] = round(fpe_window, 4)
        sweep["ipc_window_amortization"] = round(
            fpe_percall / fpe_window, 2
        ) if fpe_window > 0 else 0.0

        plane_counters = dict(plane.snapshot()["counters"])
        cli_counters = dict(cli.counters)
        cli.close()
        plane.close()

        # --- adaptive-wakeup A/B: the same single-entry round trip
        # with spin-then-park ring waits (a fresh plane — doorbells
        # exist only when the plane is built under wakeup=adaptive).
        # Same-run, same box: the ratio is immune to the host-identity
        # hazard the benchgate token guards against.
        config.set(config.IPC_WAKEUP, "adaptive")
        plane2 = IngestPlane(eng)
        cli2 = IngestClient(plane2.channel(0), 0)
        for i in range(64):
            cli2.entry(resources[i % n_rules])
        lats2 = []
        for i in range(1024):
            t0 = time.perf_counter()
            cli2.entry(resources[i % n_rules])
            lats2.append(time.perf_counter() - t0)
        eng.flush()
        lats2.sort()
        ad_p50 = lats2[len(lats2) // 2] * 1e6
        ad_p99 = lats2[int(len(lats2) * 0.99)] * 1e6
        cli2_policy = cli2.counters.get("policy_served", 0)
        cli2.close()
        plane2.close()
        eng.close()

        # --- engine hot-restart outage (PR 15): supervised engine on
        # named rings, kill -9 the engine child, time until the probing
        # client is served device-backed verdicts again (includes the
        # dead-ms detection window, the restart backoff, the child's
        # cold boot and the durable warm restore). Failure omits the
        # column instead of poisoning the gate with a fake number.
        import os as _os
        import tempfile as _tempfile

        restart_cols: dict = {}
        ckpt = _os.path.join(
            "/dev/shm" if _os.path.isdir("/dev/shm")
            else _tempfile.gettempdir(),
            f"stpu-bench-ckpt-{_os.getpid()}.bin",
        )
        try:
            config.set(config.IPC_WAKEUP, config.DEFAULTS[config.IPC_WAKEUP])
            config.set(config.IPC_HEARTBEAT_MS, "50")
            config.set(config.IPC_ENGINE_DEAD_MS, "2000")
            config.set(config.SUPERVISE_BACKOFF_MS, "200")
            config.set(config.FAILOVER_ENABLED, "true")
            config.set(config.FAILOVER_CHECKPOINT_EVERY, "2")
            config.set(config.FAILOVER_CKPT_PATH, ckpt)
            from sentinel_tpu.ipc.supervise import measure_restart_outage

            out = measure_restart_outage(
                _bench_restart_setup, "r0", timeout_s=240
            )
            restart_cols = {
                "ipc_restart_outage_ms": round(out["outage_ms"], 1),
                "ipc_restart_reconnects": out["reconnects"],
                "ipc_restarts": out["restarts"],
            }
            _log(
                f"ipc restart outage {out['outage_ms']:.0f} ms "
                f"({out['restarts']} restart, {out['reconnects']} "
                "reconnect)"
            )
        except Exception as e:
            _log(f"ipc restart measurement failed ({e}) — column omitted")
        finally:
            try:
                _os.unlink(ckpt)
            except OSError:
                pass
            for key in (
                config.IPC_HEARTBEAT_MS, config.IPC_ENGINE_DEAD_MS,
                config.SUPERVISE_BACKOFF_MS, config.FAILOVER_ENABLED,
                config.FAILOVER_CHECKPOINT_EVERY, config.FAILOVER_CKPT_PATH,
                config.IPC_SHM_PREFIX,
            ):
                config.set(key, config.DEFAULTS[key])

        # --- warm standby + planned handoff (PR 20): the same kill -9
        # with a pre-forked compile-warmed standby armed (the outage
        # should be ≈ the detection window, the cold-boot term gone),
        # then one operator handoff cycle (zero policy-served is the
        # acceptance bit; the column is the worst held-verdict gap).
        standby_cols: dict = {}
        ckpt_sb = _os.path.join(
            "/dev/shm" if _os.path.isdir("/dev/shm")
            else _tempfile.gettempdir(),
            f"stpu-bench-sb-{_os.getpid()}.bin",
        )
        try:
            config.set(config.IPC_HEARTBEAT_MS, "50")
            config.set(config.IPC_ENGINE_DEAD_MS, "2000")
            config.set(config.IPC_ENGINE_DEAD_CONFIRM_MS, "1000")
            config.set(config.IPC_HANDOFF_WAIT_MS, "30000")
            config.set(config.SUPERVISE_BACKOFF_MS, "200")
            config.set(config.SUPERVISE_STANDBY, "true")
            config.set(config.SUPERVISE_STANDBY_WARM_MS, "500")
            config.set(config.FAILOVER_ENABLED, "true")
            config.set(config.FAILOVER_CHECKPOINT_EVERY, "2")
            config.set(config.FAILOVER_CKPT_PATH, ckpt_sb)
            from sentinel_tpu.ipc.supervise import (
                measure_handoff_outage,
                measure_standby_outage,
            )

            out = measure_standby_outage(
                _bench_restart_setup, "r0", timeout_s=240
            )
            standby_cols = {
                "ipc_standby_outage_ms": round(out["outage_ms"], 1),
                "ipc_standby_warm_boot_ms": round(
                    out["standby_warm_boot_ms"] or 0.0, 1
                ),
                "ipc_standby_takeovers": out["standby_takeovers"],
            }
            _log(
                f"ipc standby outage {out['outage_ms']:.0f} ms "
                f"(warm boot {out['standby_warm_boot_ms']:.0f} ms off "
                f"the outage path, {out['standby_takeovers']} takeover)"
            )
            out = measure_handoff_outage(
                _bench_restart_setup, "r0", timeout_s=240
            )
            standby_cols["ipc_handoff_outage_ms"] = round(
                out["handoff_outage_ms"], 1
            )
            standby_cols["ipc_handoff_policy_served"] = out["policy_served"]
            _log(
                f"ipc handoff worst verdict gap "
                f"{out['handoff_outage_ms']:.0f} ms "
                f"({out['policy_served']} policy-served, "
                f"{out['handoffs']} handoff)"
            )
        except Exception as e:
            _log(f"ipc standby measurement failed ({e}) — columns omitted")
        finally:
            try:
                _os.unlink(ckpt_sb)
            except OSError:
                pass
            for key in (
                config.IPC_HEARTBEAT_MS, config.IPC_ENGINE_DEAD_MS,
                config.IPC_ENGINE_DEAD_CONFIRM_MS, config.IPC_HANDOFF_WAIT_MS,
                config.SUPERVISE_BACKOFF_MS, config.SUPERVISE_STANDBY,
                config.SUPERVISE_STANDBY_WARM_MS, config.FAILOVER_ENABLED,
                config.FAILOVER_CHECKPOINT_EVERY, config.FAILOVER_CKPT_PATH,
                config.IPC_SHM_PREFIX,
            ):
                config.set(key, config.DEFAULTS[key])
    finally:
        for key in (
            config.SPECULATIVE_ENABLED, config.SPECULATIVE_FLUSH_BATCH,
            config.IPC_WORKER_DEAD_MS, config.IPC_WAKEUP,
        ):
            config.set(key, config.DEFAULTS[key])

    ratio = workers_ops / inproc_ops if inproc_ops > 0 else 0.0
    wakeup_speedup = p50 / ad_p50 if ad_p50 > 0 else 0.0
    _log(
        f"ipc stage done: {n_workers} workers {workers_ops:,.0f} ops/s vs "
        f"in-process {inproc_ops:,.0f} ({ratio:.2f}x); entry rt p50 "
        f"{p50:.0f} µs p99 {p99:.0f} µs (adaptive p50 {ad_p50:.0f} µs = "
        f"{wakeup_speedup:.2f}x); window amortization "
        f"{sweep['ipc_window_amortization']:.1f}x; admitted {admitted}; "
        f"client policy_served={cli_counters.get('policy_served', 0)} "
        f"sheds={cli_counters.get('sheds', 0)}"
    )
    return {
        "ipc_n_ops": n_ops,
        "ipc_n_workers": n_workers,
        "ipc_workers_ops_per_sec": round(workers_ops, 1),
        "ipc_inproc_ops_per_sec": round(inproc_ops, 1),
        "ipc_vs_inproc": round(ratio, 4),
        "ipc_entry_p50_us": round(p50, 1),
        "ipc_entry_p99_us": round(p99, 1),
        # Adaptive-wakeup same-run A/B (spin-then-park vs sleep-poll).
        "ipc_entry_adaptive_p50_us": round(ad_p50, 1),
        "ipc_entry_adaptive_p99_us": round(ad_p99, 1),
        "ipc_wakeup_speedup": round(wakeup_speedup, 3),
        # Span-journal decomposition of the entry round trip (ms -> µs):
        # e2e = the worker admit span (join -> verdict), push = its
        # client encode + ring-push leg, drain = the engine-side
        # dequeue -> decide -> respond span. The overhead ratio is the
        # armed/unarmed p50 A/B (same client, same run).
        "ipc_span_e2e_p50_us": round(e2e_p50 * 1e3, 1),
        "ipc_span_e2e_p99_us": round(e2e_p99 * 1e3, 1),
        "ipc_span_push_p50_us": round(push_p50 * 1e3, 1),
        "ipc_span_drain_p50_us": round(drain_p50 * 1e3, 1),
        "ipc_span_drain_p99_us": round(drain_p99 * 1e3, 1),
        "ipc_span_overhead": round(span_overhead, 3),
        **sweep,
        "ipc_frames": plane_counters.get("frames", 0),
        "ipc_admitted": admitted,
        # Honesty columns: a policy-served latency sample would mean
        # the measured number was the DEAD-ENGINE fallback, not the
        # ring round trip.
        "ipc_client_policy_served": cli_counters.get("policy_served", 0),
        "ipc_client_sheds": cli_counters.get("sheds", 0),
        "ipc_adaptive_policy_served": cli2_policy,
        **restart_cols,
        **standby_cols,
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
        **_host_identity(),
    }


def _run_cluster_stage(n_rules: int, n_ops: int, iters: int) -> dict:
    """Batched cluster token plane (cluster/client.py + server.py):
    frames-per-token-decision and ops/s for the three wire stances
    against one real TCP token server — (a) per-call (the PR-15
    default: one frame per decision), (b) client micro-window
    (concurrent callers coalesce into FLOW_REQUEST_BATCH frames), and
    (c) micro-window + local quota leases (hot-flow admissions served
    with ZERO frames in steady state). Honesty columns count FAIL-
    family fallback serves per mode — a nonzero means that mode's
    number includes local-stance verdicts, not server verdicts."""
    import threading as _threading

    import jax

    from sentinel_tpu.cluster import (
        cluster_flow_rule_manager,
        cluster_server_config_manager,
    )
    from sentinel_tpu.cluster.client import ClusterTokenClient, client_stats
    from sentinel_tpu.cluster.server import SentinelTokenServer
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.models import constants as C
    from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule
    from sentinel_tpu.utils.config import config

    n_ops = max(256, n_ops)
    n_threads = 8
    per_thread = n_ops // n_threads
    n_ops = per_thread * n_threads
    flow_id = 42
    _log(f"cluster stage ops={n_ops} threads={n_threads}")

    # One wide-open rule: the stage measures the WIRE cost of a
    # decision, not admission math (the differential tests pin that).
    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=1e12
    )
    cluster_flow_rule_manager.load_rules(
        "default",
        [FlowRule(
            "r", count=1e9, cluster_mode=True,
            cluster_config=ClusterFlowConfig(
                flow_id=flow_id,
                threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            ),
        )],
    )
    server = SentinelTokenServer(port=0, service=DefaultTokenService())
    server.start()
    out: dict = {"cluster_n_ops": n_ops}

    def drive(mode: str) -> None:
        if mode == "percall":
            config.set(config.CLUSTER_CLIENT_WINDOW_MS, "0")
            config.set(config.CLUSTER_LEASE_ENABLED, "false")
        elif mode == "window":
            config.set(config.CLUSTER_CLIENT_WINDOW_MS, "2")
            config.set(config.CLUSTER_CLIENT_WINDOW_MAX, "64")
            config.set(config.CLUSTER_LEASE_ENABLED, "false")
        else:  # lease
            config.set(config.CLUSTER_CLIENT_WINDOW_MS, "2")
            config.set(config.CLUSTER_CLIENT_WINDOW_MAX, "64")
            config.set(config.CLUSTER_LEASE_ENABLED, "true")
            config.set(config.CLUSTER_LEASE_TTL_MS, "1000")
        client_stats.reset()
        client = ClusterTokenClient("127.0.0.1", server.port).start()
        try:
            client.request_token(flow_id)  # connect + warm outside the clock
            client_stats.reset()
            barrier = _threading.Barrier(n_threads + 1)

            def worker():
                barrier.wait()
                for _ in range(per_thread):
                    client.request_token(flow_id)

            threads = [
                _threading.Thread(target=worker) for _ in range(n_threads)
            ]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
        finally:
            client.stop()
        snap = client_stats.snapshot()
        # Frames actually sent: batch frames plus per-call RPCs (the
        # rpc histogram counts every awaited round trip; batched rows
        # share one sample per frame, so subtract the double count).
        frames = (
            snap["batch_frames"]
            if snap["batch_frames"]
            else snap["rpc"]["count"]
        )
        fpo = frames / n_ops if n_ops else 0.0
        out[f"cluster_{mode}_ops_per_sec"] = round(n_ops / dt, 1)
        out[f"cluster_frames_per_op_{mode}"] = round(fpo, 4)
        out[f"cluster_{mode}_fallbacks"] = snap["fallbacks"]
        if mode == "lease":
            out["cluster_lease_hit_rate"] = round(
                snap["lease_admits"] / max(1, snap["requests"]), 4
            )
        _log(
            f"cluster {mode}: {n_ops / dt:,.0f} ops/s, "
            f"{fpo:.3f} frames/op, fallbacks={snap['fallbacks']}"
        )
        _emit_partial = dict(out)
        print(json.dumps(_emit_partial), flush=True)

    try:
        for mode in ("percall", "window", "lease"):
            drive(mode)

        # --- span decomposition (PR 18): one single-threaded per-call
        # round with the fleet span journal armed — the client rpc
        # span (send -> response) against the shard's serve span
        # (decode -> decide -> reply, stamped by the same-process
        # server). rpc − serve ≈ the wire + reader-dispatch share.
        # Armed AFTER the headline modes, so their ops/s stay
        # span-free.
        from sentinel_tpu.metrics.spans import get_journal as _get_spj

        config.set(config.CLUSTER_CLIENT_WINDOW_MS, "0")
        config.set(config.CLUSTER_LEASE_ENABLED, "false")
        spj = _get_spj()
        spans_before = len(spj.spans())
        dec_ops = min(1024, n_ops)
        client = ClusterTokenClient("127.0.0.1", server.port).start()
        try:
            client.request_token(flow_id)  # connect outside the spans
            spj.enabled = True
            for _ in range(dec_ops):
                client.request_token(flow_id)
        finally:
            spj.enabled = False
            client.stop()

        def _pcts_ms(vals):
            vals = sorted(vals)
            if not vals:
                return 0.0, 0.0
            return (vals[len(vals) // 2], vals[int(len(vals) * 0.99)])

        new_spans = spj.spans()[spans_before:]
        rpc_p50, rpc_p99 = _pcts_ms(
            [s["dur"] for s in new_spans
             if s["cat"] == "client" and s["name"] == "rpc"]
        )
        srv_p50, srv_p99 = _pcts_ms(
            [s["dur"] for s in new_spans
             if s["cat"] == "shard" and s["name"] == "serve"]
        )
        out["cluster_rpc_p50_ms"] = round(rpc_p50, 4)
        out["cluster_rpc_p99_ms"] = round(rpc_p99, 4)
        out["cluster_serve_p50_ms"] = round(srv_p50, 4)
        out["cluster_serve_p99_ms"] = round(srv_p99, 4)
        out["cluster_wire_share"] = round(
            (rpc_p50 - srv_p50) / rpc_p50, 4
        ) if rpc_p50 > 0 else 0.0
        _log(
            f"cluster span decomposition: rpc p50 {rpc_p50:.3f} ms, "
            f"serve p50 {srv_p50:.3f} ms "
            f"(wire share {out['cluster_wire_share']:.2f})"
        )
    finally:
        server.stop()
        cluster_flow_rule_manager.clear()
        for key in (
            config.CLUSTER_CLIENT_WINDOW_MS, config.CLUSTER_CLIENT_WINDOW_MAX,
            config.CLUSTER_LEASE_ENABLED, config.CLUSTER_LEASE_TTL_MS,
        ):
            config.set(key, config.DEFAULTS[key])

    amort = (
        out.get("cluster_frames_per_op_percall", 1.0)
        / max(1e-9, out.get("cluster_frames_per_op_window", 1.0))
    )
    out["cluster_window_amortization"] = round(amort, 3)
    _log(
        f"cluster stage done: window amortization {amort:.1f}x, lease "
        f"hit rate {out.get('cluster_lease_hit_rate', 0.0):.2f}"
    )

    # ---- shard sweep (PR 17): 1/2/4 hash-partitioned shards, batched
    # rows, window vs lease stance. Wall-clock ops/s is RECORDED but
    # not the gate — this is typically a 1-core box, so aggregate
    # decision capacity is measured from the servers' own work clocks
    # (Σ per-shard decisions/busy_s), alongside frames/op and the
    # parallel-issue honesty counter (fraction of windows whose rows
    # spanned >1 shard and were issued concurrently).
    from sentinel_tpu.cluster.shards import (
        ShardMap,
        ShardedTokenClient,
        shard_of,
    )

    shard_flows = list(range(500, 532))
    cluster_flow_rule_manager.load_rules(
        "default",
        [FlowRule(
            "sr%d" % f, count=1e9, cluster_mode=True,
            cluster_config=ClusterFlowConfig(
                flow_id=f, threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            ),
        ) for f in shard_flows],
    )
    batch_rows = [
        (shard_flows[i % len(shard_flows)], 1, False) for i in range(256)
    ]
    shard_threads = 4
    per_thread_batches = max(6, min(24, n_ops // (256 * shard_threads)))
    shard_ops = per_thread_batches * shard_threads * len(batch_rows)
    out["cluster_shard_ops"] = shard_ops

    def drive_shards(n_shards: int, stance: str) -> None:
        config.set(config.CLUSTER_CLIENT_WINDOW_MS, "0")
        config.set(
            config.CLUSTER_LEASE_ENABLED,
            "true" if stance == "lease" else "false",
        )
        config.set(config.CLUSTER_LEASE_TTL_MS, "1000")
        servers = [
            SentinelTokenServer(port=0, service=DefaultTokenService()).start()
            for _ in range(n_shards)
        ]
        client = ShardedTokenClient(
            ShardMap(0, [("127.0.0.1", s.port) for s in servers])
        ).start()
        capacity = 0.0
        try:
            client.request_tokens_batch(batch_rows)  # warm every shard
            for s in servers:
                s.reset_work_stats()
            client_stats.reset()
            barrier = _threading.Barrier(shard_threads + 1)

            def worker():
                barrier.wait()
                for _ in range(per_thread_batches):
                    client.request_tokens_batch(batch_rows)

            threads = [
                _threading.Thread(target=worker)
                for _ in range(shard_threads)
            ]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            plane = client.plane_snapshot()
            snap = client_stats.snapshot()
            if stance == "window":
                # Aggregate decision capacity = Σ per-shard standalone
                # saturation (decisions/busy_s from each server's own
                # work clock), measured one shard at a time: a 1-core
                # box serializes concurrent handlers through the GIL,
                # which would charge every shard's busy clock with the
                # others' contention — while each deployment shard is
                # its own machine. The PARALLEL run above (recorded as
                # wall ops/s + parallel_issue) is the honesty column
                # showing the client really issues shards concurrently.
                for s in servers:
                    s.reset_work_stats()
                for i in range(n_shards):
                    rows_i = [
                        r for r in batch_rows
                        if shard_of(r[0], n_shards) == i
                    ] or batch_rows[:8]
                    rows_i = (rows_i * (256 // len(rows_i) + 1))[:256]
                    for _ in range(6):
                        client.clients[i].request_tokens_batch(rows_i)
                capacity = sum(
                    w["decisions"] / w["busy_s"]
                    for w in (s.work_stats() for s in servers)
                    if w["busy_s"] > 0
                )
        finally:
            client.stop()
            for s in servers:
                s.stop()
        frames = snap["batch_frames"] or snap["rpc"]["count"]
        tag = f"cluster_shard{n_shards}_{stance}"
        out[f"{tag}_ops_per_sec"] = round(shard_ops / dt, 1)
        out[f"{tag}_frames_per_op"] = round(frames / shard_ops, 4)
        out[f"{tag}_fallbacks"] = snap["fallbacks"]
        if stance == "window":
            out[f"cluster_shard{n_shards}_capacity_per_sec"] = round(
                capacity, 1
            )
            issued = plane["parallel_batches"] + plane["single_batches"]
            out[f"cluster_shard{n_shards}_parallel_issue"] = round(
                plane["parallel_batches"] / max(1, issued), 4
            )
        else:
            out[f"{tag}_hit_rate"] = round(
                snap["lease_admits"] / max(1, snap["requests"]), 4
            )
        _log(
            f"cluster shard{n_shards}/{stance}: {shard_ops / dt:,.0f} "
            f"ops/s wall, capacity {capacity:,.0f}/s, "
            f"{frames / shard_ops:.4f} frames/op"
        )
        print(json.dumps(dict(out)), flush=True)

    for _n in (1, 2, 4):
        for _stance in ("window", "lease"):
            drive_shards(_n, _stance)
    cap1 = out.get("cluster_shard1_capacity_per_sec", 0.0)
    cap4 = out.get("cluster_shard4_capacity_per_sec", 0.0)
    out["cluster_shard_capacity_ratio_4x"] = round(cap4 / max(1e-9, cap1), 3)
    _log(
        f"shard sweep done: 4-shard aggregate capacity "
        f"{out['cluster_shard_capacity_ratio_4x']:.2f}x single-shard"
    )

    # ---- gossip merge cost: merge_remote + fleet-view query in
    # isolation (the wire is one small compressed frame; the cost that
    # scales with fleet size is the saturating vector add + the union
    # key query, so that is what gets a column).
    import numpy as _np

    from sentinel_tpu.runtime.sketch import SketchTier

    saved_g = {
        k: config.get(k)
        for k in (config.SKETCH_ENABLED, config.GOSSIP_ENABLED)
    }
    config.set(config.SKETCH_ENABLED, "true")
    config.set(config.GOSSIP_ENABLED, "true")
    try:
        class _Tele:
            enabled = False

        class _Eng:
            telemetry = _Tele()

        t_a, t_b = SketchTier(_Eng()), SketchTier(_Eng())
        t_b._host_cm[:] = 7
        for k in range(64):
            t_b.host_mirror.offer("\x01sr%d" % k, 50)
        wid, cm, cands = t_b.gossip_snapshot()
        reps = 50
        t0 = time.perf_counter()
        for i in range(reps):
            t_a.merge_remote("peer%d" % (i % 4), wid, cm, cands)
            t_a._fleet_by_key({})
        out["cluster_gossip_merge_ms"] = round(
            (time.perf_counter() - t0) / reps * 1e3, 3
        )
        _log(f"gossip merge cost: {out['cluster_gossip_merge_ms']:.2f} ms")
    finally:
        for k, v in saved_g.items():
            config.set(k, v if v is not None else config.DEFAULTS[k])
    out.update({
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
        **_host_identity(),
    })
    return out


def _run_stage(n_rules: int, n_entries: int, iters: int) -> dict:
    """Child-process body: build state, compile, time. Prints one JSON
    line with the stage result (including the platform ACTUALLY used)."""
    import jax
    import jax.numpy as jnp

    from sentinel_tpu.metrics.nodes import make_stats
    from sentinel_tpu.rules.degrade_table import DegradeIndex
    from sentinel_tpu.rules.flow_table import FlowRuleDynState, FlowTableDevice
    from sentinel_tpu.rules.param_table import make_param_state
    from sentinel_tpu.runtime.flush import SystemDevice, flush_step_jit
    from __graft_entry__ import _example_batch

    n_rows = n_rules
    k = 1
    _log(f"stage rules={n_rules} entries={n_entries}: building state")
    stats = make_stats(n_rows)
    dindex = DegradeIndex([])
    ddev, ddyn = dindex.device, dindex.make_dyn_state()
    inf = float("inf")
    sysdev = SystemDevice(
        qps=jnp.float32(inf),
        max_thread=jnp.float32(inf),
        max_rt=jnp.float32(inf),
        load_threshold=jnp.float32(-1.0),
        cpu_threshold=jnp.float32(-1.0),
        cur_load=jnp.float32(-1.0),
        cur_cpu=jnp.float32(-1.0),
    )
    # Build the device rule table directly (bypasses the Python bean
    # layer, which is not the hot path being measured).
    dev = FlowTableDevice(
        grade=jnp.ones(n_rules, dtype=jnp.int32),
        count=jnp.full(n_rules, 20.0, dtype=jnp.float32),
        behavior=jnp.zeros(n_rules, dtype=jnp.int32),
        max_queueing_time_ms=jnp.zeros(n_rules, dtype=jnp.int32),
        cost1_ms=jnp.full(n_rules, 50, dtype=jnp.int32),
        warmup_warning_token=jnp.zeros(n_rules, dtype=jnp.int32),
        warmup_max_token=jnp.zeros(n_rules, dtype=jnp.int32),
        warmup_slope=jnp.zeros(n_rules, dtype=jnp.float32),
        warmup_refill_threshold=jnp.zeros(n_rules, dtype=jnp.int32),
    )
    dyn = FlowRuleDynState(
        latest_passed_time=jnp.full(n_rules, -(10**9), dtype=jnp.int32),
        stored_tokens=jnp.zeros(n_rules, dtype=jnp.float32),
        last_filled_time=jnp.full(n_rules, -(10**9), dtype=jnp.int32),
    )
    batch = _example_batch(n_entries, n_rows, n_rules, k)
    pdyn = make_param_state(8)

    # The same host-known specialization the Engine picks for this
    # workload: no prioritized entries, no system/degrade rules, no
    # exits in the batch (runtime/engine._run_chunk `flags`).
    flags = dict(
        with_occupy=False, with_system=False, with_degrade=False, with_exits=False
    )
    _log("compiling + warm-up")
    t0 = time.perf_counter()
    stats, dyn, ddyn, pdyn, _sk, result = flush_step_jit(
        stats, dev, dyn, ddev, ddyn, pdyn, sysdev, batch, **flags
    )
    jax.block_until_ready(result.admitted)
    _log(f"compile+first-run {time.perf_counter() - t0:.1f}s; timing {iters} iters")

    t0 = time.perf_counter()
    for _ in range(iters):
        stats, dyn, ddyn, pdyn, _sk, result = flush_step_jit(
            stats, dev, dyn, ddev, ddyn, pdyn, sysdev, batch, **flags
        )
    jax.block_until_ready(result.admitted)
    dt = (time.perf_counter() - t0) / iters

    checks_per_sec = n_entries / dt
    vs = TARGET_S_PER_ENTRY / (dt / n_entries)
    _log(
        f"stage done: {dt * 1e3:.3f} ms/flush, {checks_per_sec:,.0f} entries/sec, "
        f"vs_baseline {vs:.3f}"
    )
    return {
        "metric": "batched_entry_checks_per_sec_per_chip",
        "value": round(checks_per_sec, 1),
        "unit": "entries/sec",
        "vs_baseline": round(vs, 4),
        "platform": jax.default_backend(),
        # Hardware-truth header: the BENCH trajectory must be able to
        # tell CPU liveness runs from real TPU numbers without reading
        # the log (round-3 lesson, hardened here). The host token
        # (_host_identity) extends it to same-silicon different-speed
        # boxes.
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
        **_host_identity(),
        "n_rules": n_rules,
        "n_entries": n_entries,
        "flush_ms": round(dt * 1e3, 4),
    }


def _child_main(args) -> None:
    if args.child_platform == "cpu":
        from sentinel_tpu.utils.backend import force_cpu

        force_cpu()
    fn = {
        "kernel": _run_stage,
        "mixed": _run_mixed_stage,
        "engine": _run_engine_stage,
        "speculative": _run_speculative_stage,
        "sketch": _run_sketch_stage,
        "adapters": _run_adapters_stage,
        "autotune": _run_autotune_stage,
        "ipc": _run_ipc_stage,
        "cluster": _run_cluster_stage,
    }[args.kind]
    print(json.dumps(fn(args.rules, args.entries, args.iters)), flush=True)


def _last_json_line(out) -> dict | None:
    """Last parseable non-error JSON object in a child's stdout (str,
    bytes, or None) — the salvage contract for killed stages."""
    if not out:
        return None
    if isinstance(out, bytes):
        out = out.decode("utf-8", errors="replace")
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "error" not in rec:
            return rec
    return None


def _spawn_stage(
    n_rules: int, n_entries: int, iters: int, platform: str, timeout_s: float,
    kind: str = "kernel",
) -> dict | None:
    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        "--run-stage",
        "--kind", kind,
        "--rules", str(n_rules),
        "--entries", str(n_entries),
        "--iters", str(iters),
        "--child-platform", platform,
    ]
    try:
        r = subprocess.run(
            cmd, stdout=subprocess.PIPE, text=True, timeout=timeout_s
        )  # stderr passes through for live progress
    except subprocess.TimeoutExpired as exc:
        _log(f"stage rules={n_rules} timed out after {timeout_s:.0f}s")
        # Salvage any JSON the child printed before the kill: stages
        # emit completed sub-measurements incrementally for exactly
        # this case.
        rec = _last_json_line(exc.stdout)
        if rec is not None:
            _log(f"stage rules={n_rules}: salvaged partial results")
        return rec
    if r.returncode != 0:
        _log(f"stage rules={n_rules} failed rc={r.returncode}")
        return None
    rec = _last_json_line(r.stdout)
    if rec is None:
        # Distinguish "child reported an error record" from "no JSON
        # at all" in the log; either way the stage yields nothing.
        if '"error"' in (r.stdout or ""):
            _log(f"stage rules={n_rules} reported an error")
        else:
            _log(f"stage rules={n_rules} produced no JSON")
    return rec


def _env_budget() -> float:
    try:
        return float(os.environ.get("SENTINEL_BENCH_BUDGET_S", 1080))
    except ValueError:
        return 1080.0


PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_partial.jsonl")


def _stage_done(out: dict, label: str) -> None:
    """Append a completed stage's JSON to BENCH_partial.jsonl so a
    mid-run wedge still leaves every finished stage's hardware data on
    disk (round-3 lesson: the round's only TPU numbers died in a
    wedged process)."""
    rec = {"stage": label, "t": round(time.time(), 1), **out}
    try:
        with open(PARTIAL_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as exc:  # never let bookkeeping kill the bench
        _log(f"could not append partial record: {exc}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget-s", type=float, default=_env_budget())
    ap.add_argument("--probe-attempts", type=int,
                    default=int(os.environ.get("SENTINEL_BENCH_PROBE_ATTEMPTS", 5)))
    ap.add_argument("--platform", default=None, help="skip the probe and force a platform")
    ap.add_argument(
        "--gate", action="store_true",
        help="after the run, compare against the newest committed "
             "BENCH_*.json with the same device_kind+jax_version "
             "(tools/benchgate.py) and exit non-zero on regression",
    )
    ap.add_argument("--run-stage", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--kind", default="kernel", help=argparse.SUPPRESS)
    ap.add_argument("--rules", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--entries", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--iters", type=int, default=10, help=argparse.SUPPRESS)
    ap.add_argument("--child-platform", default="cpu", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.run_stage:
        _child_main(args)
        return

    # Probe BEFORE starting the stage clock: waiting out a transient
    # tunnel wedge must not eat the measurement budget.
    probe_fell_back = False
    if args.platform:
        platform = args.platform
    else:
        platform = _probe_backend(
            args.probe_attempts, [60.0, 120.0, 180.0, 240.0, 300.0]
        )
        probe_fell_back = platform == "cpu"
    requested_platform = platform
    # Fresh partial file per run: interleaved records from different
    # runs are indistinguishable to consumers.
    try:
        open(PARTIAL_PATH, "w").close()
    except OSError:
        pass
    deadline = time.monotonic() + args.budget_s

    def spawn(n_rules, n_entries, iters, plat, timeout_s, kind="kernel"):
        out = _spawn_stage(n_rules, n_entries, iters, plat, timeout_s, kind=kind)
        if out is None and plat != "cpu":
            # A TPU stage death/timeout is retryable exactly once: the
            # tunnel may have hiccuped rather than the stage being too
            # big. Re-probe cheaply first so a hard wedge fails fast —
            # and stay inside the remaining budget: the first attempt
            # already spent its timeout, so the retry gets only what is
            # left (skipped entirely when nothing is).
            retry_budget = min(timeout_s, deadline - time.monotonic() - 95.0)
            if retry_budget > 30 and _transport_exists() and _probe_once(90.0):
                _log(f"stage {kind}/rules={n_rules} failed on {plat}; retrying once")
                out = _spawn_stage(
                    n_rules, n_entries, iters, plat, retry_budget, kind=kind
                )
        if out is not None:
            _stage_done(out, f"{kind}:{n_rules}x{n_entries}")
        return out

    def walk(platform: str) -> dict | None:
        best: dict | None = None
        ladder = CPU_LADDER if platform == "cpu" else LADDER
        for n_rules, n_entries, iters in ladder:
            remaining = deadline - time.monotonic()
            if remaining < 30 or (best is not None and remaining < 90):
                _log(f"skipping rules={n_rules}: only {remaining:.0f}s of budget left")
                break
            # Cap per-stage time so one wedged stage can't eat the whole
            # budget (a backend can pass the tiny probe yet wedge on the
            # first real compile — leave room for the CPU retry below).
            timeout_s = remaining if platform == "cpu" else min(remaining, 240.0)
            out = spawn(n_rules, n_entries, iters, platform, timeout_s)
            if out is None:
                break
            best = out
            if out.get("platform") == "cpu" and platform != "cpu":
                # The child silently landed on CPU despite a non-cpu
                # request (plugin failure / env override): don't scale
                # the remaining ladder for hardware that isn't there.
                _log("child ran on cpu despite requested platform; stopping ladder")
                break
        return best

    best = walk(platform)
    if best is None and platform != "cpu" and deadline - time.monotonic() > 30:
        _log(f"no {platform} stage completed; retrying ladder on cpu")
        best = walk("cpu")

    # Secondary metrics (merged into the one JSON line): the mixed
    # slot-chain workload and the engine-level deferred path.
    if best is not None:
        run_platform = best.get("platform", "cpu")
        # The mixed/engine kernels are the biggest compiles in the repo
        # (~2-4 min through the remote-compile tunnel even after the
        # fori_loop rounds fix): killing one mid-compile both loses the
        # stage AND leaves the remote compile server busy, poisoning
        # every later stage. So on hardware each stage is only
        # attempted with enough headroom to finish, never with a
        # scrap of leftover budget.
        min_mixed = 90.0 if run_platform == "cpu" else 330.0
        min_engine = 45.0 if run_platform == "cpu" else 330.0
        remaining = deadline - time.monotonic()
        # Reserve the engine stage's floor when both still fit; when
        # they don't, the mixed chain (the headline verdict metric)
        # gets the room and the engine skip is logged. Either way a
        # stage's actual timeout is NEVER below its floor — a
        # sub-floor spawn is exactly the kill-mid-compile case.
        mixed_t = min(remaining - min_engine, 420.0)
        if mixed_t < min_mixed:
            mixed_t = min(remaining - 45, 420.0)
        if mixed_t >= min_mixed:
            mr, me = (
                ((1 << 20), (1 << 17)) if run_platform != "cpu" else ((1 << 14), (1 << 13))
            )
            mixed = spawn(mr, me, 5, run_platform, mixed_t, kind="mixed")
            if mixed:
                best.update(mixed)
        else:
            _log(f"skipping mixed stage: {remaining:.0f}s left gives timeout "
                 f"{mixed_t:.0f}s < {min_mixed:.0f}s floor")
        remaining = deadline - time.monotonic()
        # Reserve the speculative stage's floor the same way the mixed
        # stage reserves the engine's: it is small (one 64-op shape
        # compile) but it is the per-request latency headline.
        min_spec = 40.0 if run_platform == "cpu" else 240.0
        engine_t = min(remaining - 15 - min_spec, 420.0)
        if engine_t < min_engine:
            engine_t = min(remaining - 15, 420.0)
        if engine_t >= min_engine:
            engine = spawn(1024, 8192, 3, run_platform, engine_t, kind="engine")
            if engine:
                best.update(engine)
        else:
            _log(f"skipping engine stage: {remaining:.0f}s left gives timeout "
                 f"{engine_t:.0f}s < {min_engine:.0f}s floor")
        remaining = deadline - time.monotonic()
        # Reserve the sketch stage's floor like the engine stage
        # reserves the speculative's.
        min_sketch = 40.0 if run_platform == "cpu" else 240.0
        spec_t = min(remaining - 10 - min_sketch, 300.0)
        if spec_t < min_spec:
            spec_t = min(remaining - 10, 300.0)
        if spec_t >= min_spec:
            spec = spawn(64, 4096, 3, run_platform, spec_t, kind="speculative")
            if spec:
                best.update(spec)
        else:
            _log(f"skipping speculative stage: {remaining:.0f}s left gives "
                 f"timeout {spec_t:.0f}s < {min_spec:.0f}s floor")
        remaining = deadline - time.monotonic()
        # Reserve the adapters stage's floor like the speculative stage
        # reserves the sketch's.
        min_adapters = 90.0 if run_platform == "cpu" else 240.0
        sketch_t = min(remaining - 10 - min_adapters, 300.0)
        if sketch_t < min_sketch:
            sketch_t = min(remaining - 10, 300.0)
        if sketch_t >= min_sketch:
            sketch = spawn(64, 8192, 3, run_platform, sketch_t, kind="sketch")
            if sketch:
                best.update(sketch)
        else:
            _log(f"skipping sketch stage: {remaining:.0f}s left gives "
                 f"timeout {sketch_t:.0f}s < {min_sketch:.0f}s floor")
        remaining = deadline - time.monotonic()
        # Reserve the autotune stage's floor like the sketch stage
        # reserves the adapters'.
        min_autotune = 60.0 if run_platform == "cpu" else 240.0
        adapters_t = min(remaining - 10 - min_autotune, 300.0)
        if adapters_t < min_adapters:
            adapters_t = min(remaining - 10, 300.0)
        if adapters_t >= min_adapters:
            adapters = spawn(
                64, 2048, 3, run_platform, adapters_t, kind="adapters"
            )
            if adapters:
                best.update(adapters)
        else:
            _log(f"skipping adapters stage: {remaining:.0f}s left gives "
                 f"timeout {adapters_t:.0f}s < {min_adapters:.0f}s floor")
        remaining = deadline - time.monotonic()
        # Reserve the ipc stage's floor like the adapters stage
        # reserves the autotune's.
        min_ipc = 60.0 if run_platform == "cpu" else 240.0
        autotune_t = min(remaining - 10 - min_ipc, 300.0)
        if autotune_t < min_autotune:
            autotune_t = min(remaining - 10, 300.0)
        if autotune_t >= min_autotune:
            att = spawn(
                64, 8192, 3, run_platform, autotune_t, kind="autotune"
            )
            if att:
                best.update(att)
        else:
            _log(f"skipping autotune stage: {remaining:.0f}s left gives "
                 f"timeout {autotune_t:.0f}s < {min_autotune:.0f}s floor")
        remaining = deadline - time.monotonic()
        # Reserve the cluster stage's floor like the autotune stage
        # reserves the ipc's. The cluster stage is pure host TCP — no
        # device compile — so its floor is small even on hardware.
        min_cluster = 45.0
        ipc_t = min(remaining - 10 - min_cluster, 300.0)
        if ipc_t < min_ipc:
            ipc_t = min(remaining - 10, 300.0)
        if ipc_t >= min_ipc:
            ipc = spawn(8, 16384, 3, run_platform, ipc_t, kind="ipc")
            if ipc:
                best.update(ipc)
        else:
            _log(f"skipping ipc stage: {remaining:.0f}s left gives "
                 f"timeout {ipc_t:.0f}s < {min_ipc:.0f}s floor")
        remaining = deadline - time.monotonic()
        cluster_t = min(remaining - 10, 120.0)
        if cluster_t >= min_cluster:
            cl = spawn(1, 8192, 1, run_platform, cluster_t, kind="cluster")
            if cl:
                best.update(cl)
        else:
            _log(f"skipping cluster stage: {remaining:.0f}s left gives "
                 f"timeout {cluster_t:.0f}s < {min_cluster:.0f}s floor")

    if best is None:
        _emit(
            {
                "metric": "batched_entry_checks_per_sec_per_chip",
                "value": 0.0,
                "unit": "entries/sec",
                "vs_baseline": 0.0,
                "error": "no ladder stage completed (backend unavailable or budget exhausted)",
            }
        )
        if args.gate:
            sys.exit(1)  # nothing measured: the gate must not read as green
        return
    if best.get("platform") == "cpu" and (
        probe_fell_back or requested_platform != "cpu"
    ):
        # A CPU number is a harness-liveness check, not perf evidence —
        # label it so nobody headline-quotes it (round-3 lesson). Both
        # fallback paths are labeled: probe exhausted all retries, or
        # the probe passed and the stages then died/landed on CPU.
        best["evidence"] = "weak: cpu fallback, tpu unreachable after retries"
    _emit(best)
    if args.gate:
        # Regression gate against the committed BENCH trajectory
        # (tools/benchgate.py): report on stderr — the one-JSON-line
        # stdout contract above must survive a gated run.
        sys.path.insert(
            0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
        )
        import contextlib

        import benchgate

        with contextlib.redirect_stdout(sys.stderr):
            rc = benchgate.gate(
                best, os.path.dirname(os.path.abspath(__file__))
            )
        if rc:
            sys.exit(rc)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException as exc:
        if "--run-stage" in sys.argv:
            raise  # children must fail loudly (rc != 0) for the parent
        # Parent: the ONE-JSON-line contract holds even here.
        _emit(
            {
                "metric": "batched_entry_checks_per_sec_per_chip",
                "value": 0.0,
                "unit": "entries/sec",
                "vs_baseline": 0.0,
                "error": f"bench crashed: {type(exc).__name__}: {exc}",
            }
        )
        sys.exit(0)
