"""Sharded Engine mode: the real product API (rules → submit → flush →
verdicts → stats) running over the 8-device CPU mesh — the deployable
cluster unit, ≙ the reference's standalone token server
(SentinelDefaultTokenServer.java:37) collapsed into ICI collectives.
"""

import pytest

pytestmark = pytest.mark.slow


@pytest.fixture()
def mesh_engine(manual_clock, engine):
    engine.enable_mesh(8)
    return engine


@pytest.mark.mesh
class TestEngineMesh:
    def test_budget_conserved_through_engine_api(self, mesh_engine):
        """128 same-window entries against count=20 admit exactly 20 —
        end to end through rules manager, submit_many and verdicts."""
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules([st.FlowRule("res", count=20)])
        now = mesh_engine.clock.now_ms()
        ops = mesh_engine.submit_many(
            [{"resource": "res", "ts": now} for _ in range(128)]
        )
        mesh_engine.flush()
        admitted = [op.verdict.admitted for op in ops]
        assert sum(admitted) == 20
        stats = mesh_engine.cluster_node_stats("res")
        assert stats["pass_qps"] == pytest.approx(20.0)
        assert stats["total_block_minute"] == 108

    def test_thread_grade_and_exits_on_mesh(self, mesh_engine, manual_clock):
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules(
            [st.FlowRule("svc", grade=0, count=4)]  # THREAD grade
        )
        ops = mesh_engine.submit_many([{"resource": "svc"} for _ in range(16)])
        mesh_engine.flush()
        assert sum(op.verdict.admitted for op in ops) == 4
        stats = mesh_engine.cluster_node_stats("svc")
        assert stats["cur_thread_num"] == 4
        # Release two slots; two more fit.
        first = next(op for op in ops if op.verdict.admitted)
        for _ in range(2):
            mesh_engine.submit_exit(first.rows, rt=5, resource="svc")
        ops2 = mesh_engine.submit_many([{"resource": "svc"} for _ in range(8)])
        mesh_engine.flush()
        assert sum(op.verdict.admitted for op in ops2) == 2

    def test_breaker_trips_and_recovers_on_mesh(self, mesh_engine, manual_clock):
        """Degrade slot exercised end-to-end in sharded mode: error
        completions trip the breaker on whichever chips carried them;
        the merged OPEN state blocks everywhere; the HALF_OPEN probe
        recovers it."""
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules([st.FlowRule("d", count=1000)])
        st.degrade_rule_manager.load_rules(
            [st.DegradeRule(resource="d", grade=1, count=0.5, time_window=2,
                            min_request_amount=5)]
        )
        manual_clock.set_ms(1000)
        ops = mesh_engine.submit_many([{"resource": "d"} for _ in range(8)])
        mesh_engine.flush()
        assert all(op.verdict.admitted for op in ops)
        for op in ops:
            mesh_engine.submit_exit(op.rows, rt=5, err=1, resource="d")
        mesh_engine.flush()
        manual_clock.set_ms(1100)
        blocked = mesh_engine.submit_many([{"resource": "d"} for _ in range(8)])
        mesh_engine.flush()
        assert not any(op.verdict.admitted for op in blocked)
        # After the retry window one probe goes through (HALF_OPEN).
        manual_clock.set_ms(3200)
        probe = mesh_engine.submit_many([{"resource": "d"} for _ in range(8)])
        mesh_engine.flush()
        assert sum(op.verdict.admitted for op in probe) == 1

    def test_breaker_counts_survive_multi_chip_window_roll(self, mesh_engine, manual_clock):
        """Several chips rolling the same breaker window in one flush
        must merge to the true counts (a naive old+psum(new-old) merge
        goes negative when 2+ chips roll), and the merged window must
        trip."""
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules([st.FlowRule("w", count=1000)])
        st.degrade_rule_manager.load_rules(
            [st.DegradeRule(resource="w", grade=1, count=0.5, time_window=5,
                            min_request_amount=4)]
        )
        # Window 1: 4 completions, 2 errors — under min_request? No:
        # 4 >= 4 but ratio 0.5 is not > 0.5 → stays CLOSED.
        manual_clock.set_ms(500)
        ops = mesh_engine.submit_many([{"resource": "w", "ts": 500} for _ in range(4)])
        mesh_engine.flush()
        for i, op in enumerate(ops):
            mesh_engine.submit_exit(op.rows, rt=5, err=1 if i < 2 else 0,
                                    resource="w", ts=500)
        mesh_engine.flush()
        # Window 2 (rolls on every chip carrying an exit): 4 errors
        # spread across chips → merged 4/4 must read exactly 4/4, trip.
        manual_clock.set_ms(1500)
        ops2 = mesh_engine.submit_many([{"resource": "w", "ts": 1500} for _ in range(4)])
        mesh_engine.flush()
        for op in ops2:
            mesh_engine.submit_exit(op.rows, rt=5, err=1, resource="w", ts=1500)
        mesh_engine.flush()
        manual_clock.set_ms(1600)
        blocked = mesh_engine.submit_many([{"resource": "w"} for _ in range(8)])
        mesh_engine.flush()
        assert not any(op.verdict.admitted for op in blocked)

    def test_occupy_borrows_conserved_on_mesh_engine(self, mesh_engine, manual_clock):
        """Prioritized entries on the mesh borrow at most maxCount in
        total across all chips."""
        import sentinel_tpu as st
        from sentinel_tpu.utils.config import config

        config.set(config.OCCUPY_TIMEOUT_MS, "1000")
        try:
            mesh_engine.enable_mesh(8)  # recompile with the new timeout
            st.flow_rule_manager.load_rules([st.FlowRule("p", count=4)])
            manual_clock.set_ms(1000)
            ops = mesh_engine.submit_many(
                [{"resource": "p", "ts": 1000} for _ in range(4)]
            )
            mesh_engine.flush()
            assert sum(op.verdict.admitted for op in ops) == 4
            manual_clock.set_ms(1100)
            prio = mesh_engine.submit_many(
                [{"resource": "p", "ts": 1100, "prio": True} for _ in range(16)]
            )
            mesh_engine.flush()
            granted = [op for op in prio if op.verdict.admitted]
            assert len(granted) == 4  # borrow budget == maxCount
            assert all(op.verdict.wait_ms > 0 for op in granted)
            stats = mesh_engine.cluster_node_stats("p")
            assert stats["waiting"] == 4
        finally:
            config.set(config.OCCUPY_TIMEOUT_MS, "500")

    def test_rate_limiter_parity_with_single_chip(self, mesh_engine, manual_clock):
        """The pacer scan on the mesh sees the GLOBAL (rule, ts)-ordered
        stream: verdicts and queue waits match a single-chip engine on
        the identical op stream exactly (a chip-local pacer would admit
        up to n_chips× the configured rate)."""
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C
        from sentinel_tpu.runtime.engine import Engine

        rules = [
            st.FlowRule(
                "rl", count=10,
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=500,
            )
        ]
        mesh_engine.set_flow_rules(rules)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(rules)
        manual_clock.set_ms(1000)
        reqs = [{"resource": "rl", "ts": 1000 + 7 * i} for i in range(24)]
        ops_m = mesh_engine.submit_many([dict(r) for r in reqs])
        mesh_engine.flush()
        ops_r = ref.submit_many([dict(r) for r in reqs])
        ref.flush()
        got = [(o.verdict.admitted, o.verdict.wait_ms) for o in ops_m]
        want = [(o.verdict.admitted, o.verdict.wait_ms) for o in ops_r]
        assert got == want
        # cost=100ms, maxq=500ms: 1 immediate + queued while wait ≤ 500.
        assert 1 < sum(a for a, _ in got) < len(reqs)

    def test_warmup_parity_with_single_chip(self, mesh_engine, manual_clock):
        """Warm-up token ramp on the mesh: cold-start admission across
        two flushes matches single-chip exactly (replicated syncToken +
        global intra-batch charge)."""
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C
        from sentinel_tpu.runtime.engine import Engine

        rules = [
            st.FlowRule(
                "wu", count=100,
                control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                warm_up_period_sec=10,
            )
        ]
        mesh_engine.set_flow_rules(rules)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(rules)
        for t in (1000, 2500):
            manual_clock.set_ms(t)
            reqs = [{"resource": "wu", "ts": t} for _ in range(64)]
            ops_m = mesh_engine.submit_many([dict(r) for r in reqs])
            mesh_engine.flush()
            ops_r = ref.submit_many([dict(r) for r in reqs])
            ref.flush()
            got = [o.verdict.admitted for o in ops_m]
            want = [o.verdict.admitted for o in ops_r]
            assert got == want
            # Cold system: some but not all of the burst is admitted.
            assert 0 < sum(got) < len(reqs)

    def test_warmup_parity_with_upstream_blocked_entries(self, mesh_engine, manual_clock):
        """Upstream-blocked (authority) entries still charge the
        warm-up passQps input on both paths — the mesh rebuild uses the
        same unmasked charge population as flow_admission, so verdicts
        stay identical even when the batch mixes blocked origins in."""
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C
        from sentinel_tpu.models.rules import AuthorityRule
        from sentinel_tpu.runtime.engine import Engine

        rules = [
            st.FlowRule(
                "wb", count=100,
                control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                warm_up_period_sec=10,
            )
        ]
        auth = {"wb": AuthorityRule(resource="wb", limit_app="bad",
                                    strategy=C.AUTHORITY_BLACK)}
        mesh_engine.set_flow_rules(rules)
        mesh_engine.set_authority_rules(auth)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(rules)
        ref.set_authority_rules(auth)
        manual_clock.set_ms(1000)
        reqs = [
            {"resource": "wb", "ts": 1000, "origin": "bad" if i % 3 == 0 else "ok"}
            for i in range(48)
        ]
        ops_m = mesh_engine.submit_many([dict(r) for r in reqs])
        mesh_engine.flush()
        ops_r = ref.submit_many([dict(r) for r in reqs])
        ref.flush()
        got = [(o.verdict.admitted, o.verdict.reason) for o in ops_m]
        want = [(o.verdict.admitted, o.verdict.reason) for o in ops_r]
        assert got == want
        assert any(not a for a, _ in got)

    def test_param_bucket_conserved_and_parity_on_mesh(self, mesh_engine, manual_clock):
        """One hot value's token bucket spans all chips: exactly
        ``count`` admissions globally, verdict-for-verdict equal to
        single-chip."""
        import sentinel_tpu as st
        from sentinel_tpu.runtime.engine import Engine

        rules = {"pp": [st.ParamFlowRule(resource="pp", param_idx=0, count=5)]}
        mesh_engine.set_param_rules(rules)
        ref = Engine(clock=manual_clock)
        ref.set_param_rules(rules)
        manual_clock.set_ms(1000)
        reqs = [
            {"resource": "pp", "ts": 1000, "args": ("user-1",)} for _ in range(16)
        ]
        ops_m = mesh_engine.submit_many([dict(r) for r in reqs])
        mesh_engine.flush()
        ops_r = ref.submit_many([dict(r) for r in reqs])
        ref.flush()
        got = [o.verdict.admitted for o in ops_m]
        assert got == [o.verdict.admitted for o in ops_r]
        assert sum(got) == 5

    def test_param_thread_grade_with_exits_on_mesh(self, mesh_engine, manual_clock):
        """Per-value concurrency on the mesh: the global gauge caps at
        the threshold; exits release slots for the next flush."""
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C

        mesh_engine.set_param_rules(
            {"tg": [st.ParamFlowRule(resource="tg", param_idx=0, count=3,
                                     grade=C.FLOW_GRADE_THREAD)]}
        )
        ops = mesh_engine.submit_many(
            [{"resource": "tg", "args": ("v",)} for _ in range(8)]
        )
        mesh_engine.flush()
        assert sum(op.verdict.admitted for op in ops) == 3
        winner = next(op for op in ops if op.verdict.admitted)
        for _ in range(2):
            mesh_engine.submit_exit(
                winner.rows, rt=5, resource="tg",
                param_rows=winner.param_thread_rows,
            )
        ops2 = mesh_engine.submit_many(
            [{"resource": "tg", "args": ("v",)} for _ in range(8)]
        )
        mesh_engine.flush()
        assert sum(op.verdict.admitted for op in ops2) == 2

    def test_shaping_and_default_budget_together_on_mesh(self, mesh_engine, manual_clock):
        """A DEFAULT rule and a rate-limiter rule on one resource: the
        cross-chip budget demotion and the global pacer compose — and
        match single-chip verdict-for-verdict."""
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C
        from sentinel_tpu.runtime.engine import Engine

        rules = [
            st.FlowRule("mix", count=20),
            st.FlowRule(
                "mix", count=50,
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=500,
            ),
        ]
        mesh_engine.set_flow_rules(rules)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(rules)
        manual_clock.set_ms(1000)
        reqs = [{"resource": "mix", "ts": 1000} for _ in range(128)]
        ops_m = mesh_engine.submit_many([dict(r) for r in reqs])
        mesh_engine.flush()
        ops_r = ref.submit_many([dict(r) for r in reqs])
        ref.flush()
        got = [o.verdict.admitted for o in ops_m]
        assert got == [o.verdict.admitted for o in ops_r]
        # DEFAULT budget (20) binds tighter than the pacer here.
        assert sum(got) == 20

    def test_origin_split_budget_is_exact(self, mesh_engine, manual_clock):
        """One rule checked against several origin rows in a batch: the
        sharded budget is keyed per check ROW with per-slot caps
        (parallel/ici._split_and_spend), the same key the single-chip
        rank math segments on — so origin-split admits EXACTLY what
        single-chip does (earlier rounds MIN-capped the rule across
        rows, over-blocking the lightly-loaded origin)."""
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C
        from sentinel_tpu.runtime.engine import Engine

        rules = [st.FlowRule("os", count=10, limit_app=C.LIMIT_APP_OTHER)]
        mesh_engine.set_flow_rules(rules)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(rules)
        manual_clock.set_ms(1000)
        pre = [{"resource": "os", "origin": "o1", "ts": 1000} for _ in range(6)]
        a = mesh_engine.submit_many([dict(r) for r in pre])
        mesh_engine.flush()
        b = ref.submit_many([dict(r) for r in pre])
        ref.flush()
        assert sum(o.verdict.admitted for o in a) == 6
        assert sum(o.verdict.admitted for o in b) == 6
        manual_clock.set_ms(1100)
        reqs = [
            {"resource": "os", "origin": "o1" if i % 2 == 0 else "o2", "ts": 1100}
            for i in range(16)
        ]
        gm = mesh_engine.submit_many([dict(r) for r in reqs])
        mesh_engine.flush()
        gr = ref.submit_many([dict(r) for r in reqs])
        ref.flush()
        adm_m = sum(o.verdict.admitted for o in gm)
        adm_r = sum(o.verdict.admitted for o in gr)
        # Single-chip (row-exact): o1 admits its remaining 4, o2 all 8.
        assert adm_r == 12
        # Mesh, row-keyed: identical — o1 its remaining 4, o2 all 8.
        assert adm_m == 12
        # Per-origin verdicts match single-chip exactly.
        assert [o.verdict.admitted for o in gm] == [o.verdict.admitted for o in gr]
        # Never over any single row's cap.
        for origin in ("o1", "o2"):
            adm_o = sum(
                o.verdict.admitted for o, r in zip(gm, reqs) if r["origin"] == origin
            )
            assert adm_o <= 10

class TestMeshLifecycle:
    """Capability-independent mesh API edges: enable/disable plumbing
    that never builds a sharded kernel, so these run (and must keep
    passing) even where ``jax.shard_map`` is absent — deliberately NOT
    ``mesh``-marked."""

    def test_non_pow2_mesh_rejected(self, manual_clock, engine):
        with pytest.raises(ValueError, match="power of two"):
            engine.enable_mesh(3)

    def test_disable_mesh_returns_to_single_chip(self, mesh_engine):
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C

        mesh_engine.disable_mesh()
        mesh_engine.set_flow_rules(
            [st.FlowRule("s", count=10,
                         control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER)]
        )
        op = mesh_engine.submit_entry("s")
        mesh_engine.flush()
        assert op.verdict.admitted
