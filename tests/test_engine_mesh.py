"""Sharded Engine mode: the real product API (rules → submit → flush →
verdicts → stats) running over the 8-device CPU mesh — the deployable
cluster unit, ≙ the reference's standalone token server
(SentinelDefaultTokenServer.java:37) collapsed into ICI collectives.
"""

import pytest


@pytest.fixture()
def mesh_engine(manual_clock, engine):
    engine.enable_mesh(8)
    return engine


class TestEngineMesh:
    def test_budget_conserved_through_engine_api(self, mesh_engine):
        """128 same-window entries against count=20 admit exactly 20 —
        end to end through rules manager, submit_many and verdicts."""
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules([st.FlowRule("res", count=20)])
        now = mesh_engine.clock.now_ms()
        ops = mesh_engine.submit_many(
            [{"resource": "res", "ts": now} for _ in range(128)]
        )
        mesh_engine.flush()
        admitted = [op.verdict.admitted for op in ops]
        assert sum(admitted) == 20
        stats = mesh_engine.cluster_node_stats("res")
        assert stats["pass_qps"] == pytest.approx(20.0)
        assert stats["total_block_minute"] == 108

    def test_thread_grade_and_exits_on_mesh(self, mesh_engine, manual_clock):
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules(
            [st.FlowRule("svc", grade=0, count=4)]  # THREAD grade
        )
        ops = mesh_engine.submit_many([{"resource": "svc"} for _ in range(16)])
        mesh_engine.flush()
        assert sum(op.verdict.admitted for op in ops) == 4
        stats = mesh_engine.cluster_node_stats("svc")
        assert stats["cur_thread_num"] == 4
        # Release two slots; two more fit.
        first = next(op for op in ops if op.verdict.admitted)
        for _ in range(2):
            mesh_engine.submit_exit(first.rows, rt=5, resource="svc")
        ops2 = mesh_engine.submit_many([{"resource": "svc"} for _ in range(8)])
        mesh_engine.flush()
        assert sum(op.verdict.admitted for op in ops2) == 2

    def test_breaker_trips_and_recovers_on_mesh(self, mesh_engine, manual_clock):
        """Degrade slot exercised end-to-end in sharded mode: error
        completions trip the breaker on whichever chips carried them;
        the merged OPEN state blocks everywhere; the HALF_OPEN probe
        recovers it."""
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules([st.FlowRule("d", count=1000)])
        st.degrade_rule_manager.load_rules(
            [st.DegradeRule(resource="d", grade=1, count=0.5, time_window=2,
                            min_request_amount=5)]
        )
        manual_clock.set_ms(1000)
        ops = mesh_engine.submit_many([{"resource": "d"} for _ in range(8)])
        mesh_engine.flush()
        assert all(op.verdict.admitted for op in ops)
        for op in ops:
            mesh_engine.submit_exit(op.rows, rt=5, err=1, resource="d")
        mesh_engine.flush()
        manual_clock.set_ms(1100)
        blocked = mesh_engine.submit_many([{"resource": "d"} for _ in range(8)])
        mesh_engine.flush()
        assert not any(op.verdict.admitted for op in blocked)
        # After the retry window one probe goes through (HALF_OPEN).
        manual_clock.set_ms(3200)
        probe = mesh_engine.submit_many([{"resource": "d"} for _ in range(8)])
        mesh_engine.flush()
        assert sum(op.verdict.admitted for op in probe) == 1

    def test_breaker_counts_survive_multi_chip_window_roll(self, mesh_engine, manual_clock):
        """Several chips rolling the same breaker window in one flush
        must merge to the true counts (a naive old+psum(new-old) merge
        goes negative when 2+ chips roll), and the merged window must
        trip."""
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules([st.FlowRule("w", count=1000)])
        st.degrade_rule_manager.load_rules(
            [st.DegradeRule(resource="w", grade=1, count=0.5, time_window=5,
                            min_request_amount=4)]
        )
        # Window 1: 4 completions, 2 errors — under min_request? No:
        # 4 >= 4 but ratio 0.5 is not > 0.5 → stays CLOSED.
        manual_clock.set_ms(500)
        ops = mesh_engine.submit_many([{"resource": "w", "ts": 500} for _ in range(4)])
        mesh_engine.flush()
        for i, op in enumerate(ops):
            mesh_engine.submit_exit(op.rows, rt=5, err=1 if i < 2 else 0,
                                    resource="w", ts=500)
        mesh_engine.flush()
        # Window 2 (rolls on every chip carrying an exit): 4 errors
        # spread across chips → merged 4/4 must read exactly 4/4, trip.
        manual_clock.set_ms(1500)
        ops2 = mesh_engine.submit_many([{"resource": "w", "ts": 1500} for _ in range(4)])
        mesh_engine.flush()
        for op in ops2:
            mesh_engine.submit_exit(op.rows, rt=5, err=1, resource="w", ts=1500)
        mesh_engine.flush()
        manual_clock.set_ms(1600)
        blocked = mesh_engine.submit_many([{"resource": "w"} for _ in range(8)])
        mesh_engine.flush()
        assert not any(op.verdict.admitted for op in blocked)

    def test_occupy_borrows_conserved_on_mesh_engine(self, mesh_engine, manual_clock):
        """Prioritized entries on the mesh borrow at most maxCount in
        total across all chips."""
        import sentinel_tpu as st
        from sentinel_tpu.utils.config import config

        config.set(config.OCCUPY_TIMEOUT_MS, "1000")
        try:
            mesh_engine.enable_mesh(8)  # recompile with the new timeout
            st.flow_rule_manager.load_rules([st.FlowRule("p", count=4)])
            manual_clock.set_ms(1000)
            ops = mesh_engine.submit_many(
                [{"resource": "p", "ts": 1000} for _ in range(4)]
            )
            mesh_engine.flush()
            assert sum(op.verdict.admitted for op in ops) == 4
            manual_clock.set_ms(1100)
            prio = mesh_engine.submit_many(
                [{"resource": "p", "ts": 1100, "prio": True} for _ in range(16)]
            )
            mesh_engine.flush()
            granted = [op for op in prio if op.verdict.admitted]
            assert len(granted) == 4  # borrow budget == maxCount
            assert all(op.verdict.wait_ms > 0 for op in granted)
            stats = mesh_engine.cluster_node_stats("p")
            assert stats["waiting"] == 4
        finally:
            config.set(config.OCCUPY_TIMEOUT_MS, "500")

    def test_shaping_rules_rejected_on_mesh(self, mesh_engine):
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C

        with pytest.raises(ValueError, match="shaping"):
            mesh_engine.set_flow_rules(
                [st.FlowRule("s", count=10,
                             control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER)]
            )

    def test_param_rules_rejected_on_mesh(self, mesh_engine):
        import sentinel_tpu as st

        with pytest.raises(ValueError, match="param"):
            mesh_engine.set_param_rules(
                {"p": [st.ParamFlowRule(resource="p", param_idx=0, count=5)]}
            )

    def test_enable_mesh_rejects_existing_shaping_rules(self, manual_clock, engine):
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C

        engine.set_flow_rules(
            [st.FlowRule("s", count=10,
                         control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER)]
        )
        with pytest.raises(ValueError, match="shaping"):
            engine.enable_mesh(8)

    def test_non_pow2_mesh_rejected(self, manual_clock, engine):
        with pytest.raises(ValueError, match="power of two"):
            engine.enable_mesh(3)

    def test_disable_mesh_returns_to_single_chip(self, mesh_engine):
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C

        mesh_engine.disable_mesh()
        # Shaping rules load fine again off-mesh.
        mesh_engine.set_flow_rules(
            [st.FlowRule("s", count=10,
                         control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER)]
        )
        op = mesh_engine.submit_entry("s")
        mesh_engine.flush()
        assert op.verdict.admitted
