"""Multi-chip dryrun stays green on the virtual 8-device CPU mesh."""


def test_dryrun_multichip_8():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_graft_entry_compiles():
    import jax

    from __graft_entry__ import entry

    fn, args = entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out[0])
