"""Multi-chip sharded flush on the virtual 8-device CPU mesh.

The key property (reference analog: a single token server serializing
all grants, ClusterFlowChecker.java:55-112): a flow rule's budget is
conserved ACROSS the mesh within one flush — N chips × M entries
against count=K admit exactly K in total, not N×K.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow


def _sharded_fixture(n_devices=8, n_rules=4, n_rows=16, per_chip=16, count=20.0,
                     acquire=1, grade=None, n_exits=0, threads0=0,
                     degrade_rule_on_r0=False, exits_complete_dgid0=False):
    from sentinel_tpu.metrics.nodes import make_stats
    from sentinel_tpu.models.rules import DegradeRule, FlowRule
    from sentinel_tpu.rules.degrade_table import DegradeIndex
    from sentinel_tpu.rules.flow_table import FlowIndex
    from sentinel_tpu.rules.param_table import make_param_state
    from sentinel_tpu.runtime.flush import FlushBatch, SystemDevice
    from sentinel_tpu.parallel import make_mesh, make_sharded_flush

    from sentinel_tpu.models import constants as C

    n = per_chip * n_devices
    stats = make_stats(n_rows)
    if threads0:
        stats = stats._replace(threads=stats.threads.at[0].set(threads0))
    index = FlowIndex(
        [
            FlowRule(
                resource=f"r{i}",
                count=count,
                grade=grade if grade is not None else C.FLOW_GRADE_QPS,
            )
            for i in range(n_rules)
        ]
    )
    dindex = DegradeIndex([DegradeRule(resource="r0", grade=1, count=0.5, time_window=10)])
    inf = float("inf")
    sysdev = SystemDevice(
        qps=jnp.float32(inf), max_thread=jnp.float32(inf), max_rt=jnp.float32(inf),
        load_threshold=jnp.float32(-1.0), cpu_threshold=jnp.float32(-1.0),
        cur_load=jnp.float32(-1.0), cur_cpu=jnp.float32(-1.0),
    )
    # All entries hit rule 0 on row 0.
    rows = np.zeros((n, 4), dtype=np.int32)
    rows[:, 1:] = -1
    gid = np.zeros((n, 1), dtype=np.int32)
    crow = np.zeros((n, 1), dtype=np.int32)
    m = max(n_devices, ((n_exits + n_devices - 1) // n_devices) * n_devices)
    x_valid = np.zeros(m, dtype=bool)
    x_rows = np.full((m, 4), -1, dtype=np.int32)
    x_thr = np.zeros(m, dtype=np.int32)
    if n_exits:
        # n_exits thread releases on row 0 in the same batch.
        x_valid[:n_exits] = True
        x_rows[:n_exits, 0] = 0
        x_thr[:n_exits] = -1
    batch = FlushBatch(
        now=jnp.int32(1000),
        e_valid=jnp.ones(n, dtype=bool),
        e_ts=jnp.asarray(600 + np.arange(n, dtype=np.int32) % 400),
        e_acquire=jnp.full(n, acquire, dtype=jnp.int32),
        e_rows=jnp.asarray(rows),
        e_rule_gid=jnp.asarray(gid),
        e_check_row=jnp.asarray(crow),
        e_prio=jnp.zeros(n, dtype=bool),
        e_auth_ok=jnp.ones(n, dtype=bool),
        e_cluster_ok=jnp.ones(n, dtype=bool),
        e_dgid=jnp.full((n, 1), -1, dtype=jnp.int32),
        x_valid=jnp.asarray(x_valid),
        x_ts=jnp.full(m, 700, dtype=jnp.int32),
        x_count=jnp.zeros(m, dtype=jnp.int32),
        x_rows=jnp.asarray(x_rows),
        x_rt=jnp.zeros(m, dtype=jnp.int32),
        x_err=jnp.zeros(m, dtype=jnp.int32),
        x_thr=jnp.asarray(x_thr),
        x_dgid=jnp.full((m, 1), -1, dtype=jnp.int32),
    )
    if degrade_rule_on_r0:
        dg = np.full((n, 1), -1, dtype=np.int32)
        dg[:, 0] = 0  # every entry checks breaker gid 0
        batch = batch._replace(e_dgid=jnp.asarray(dg))
    if exits_complete_dgid0 and n_exits:
        xd = np.full((m, 1), -1, dtype=np.int32)
        xd[:n_exits, 0] = 0  # exits complete breaker gid 0
        batch = batch._replace(x_dgid=jnp.asarray(xd))
    mesh = make_mesh(n_devices)
    jitted = make_sharded_flush(mesh)
    state = (stats, index.device, index.make_dyn_state(), dindex.device,
             dindex.make_dyn_state(), make_param_state(8), sysdev)
    return jitted, state, batch


@pytest.mark.mesh
class TestClusterBudgetConservation:
    def test_8x16_entries_count20_admit_exactly_20(self):
        from sentinel_tpu.metrics.events import MetricEvent

        jitted, state, batch = _sharded_fixture(count=20.0)
        stats2, fdyn, ddyn, pdyn, result = jitted(*state, batch)
        admitted = np.asarray(result.admitted)
        assert admitted.shape[0] == 128
        assert int(admitted.sum()) == 20, (
            f"budget not conserved across mesh: {int(admitted.sum())} != 20"
        )
        # Accounting agrees: merged PASS on row 0 is exactly 20, BLOCK 108.
        counts = np.asarray(stats2.second.counts)[0].sum(axis=0)
        assert int(counts[MetricEvent.PASS]) == 20
        assert int(counts[MetricEvent.BLOCK]) == 108

    def test_second_flush_sees_spent_budget(self):
        jitted, state, batch = _sharded_fixture(count=20.0)
        stats2, fdyn, ddyn, pdyn, r1 = jitted(*state, batch)
        assert int(np.asarray(r1.admitted).sum()) == 20
        # Same batch again in the same window: budget exhausted → 0.
        state2 = (stats2, state[1], fdyn, state[3], ddyn, pdyn, state[6])
        _, _, _, _, r2 = jitted(*state2, batch._replace(now=jnp.int32(1200)))
        assert int(np.asarray(r2.admitted).sum()) == 0

    def test_acquire_units_respected(self):
        jitted, state, batch = _sharded_fixture(count=20.0, acquire=3)
        _, _, _, _, result = jitted(*state, batch)
        # 6 entries × 3 tokens = 18 ≤ 20; a 7th would need 21.
        assert int(np.asarray(result.admitted).sum()) == 6

    def test_under_capacity_all_admitted(self):
        jitted, state, batch = _sharded_fixture(count=1000.0)
        _, _, _, _, result = jitted(*state, batch)
        assert int(np.asarray(result.admitted).sum()) == 128


@pytest.mark.mesh
class TestThreadGradeConservation:
    def test_thread_grade_counts_entries_not_acquire(self):
        """THREAD grade spends 1 budget unit per entry (the gauge rises
        by 1 regardless of acquire), per DefaultController.avgUsedTokens:
        with count=20, 128 entries of acquire=3 admit 18 (17 prior
        threads + 3 ≤ 20), not 6."""
        from sentinel_tpu.models import constants as C

        jitted, state, batch = _sharded_fixture(
            count=20.0, acquire=3, grade=C.FLOW_GRADE_THREAD
        )
        _, _, _, _, result = jitted(*state, batch)
        assert int(np.asarray(result.admitted).sum()) == 18

    def test_same_batch_releases_count(self):
        """20 threads in flight + 20 releases in the same batch: the
        sequential reference admits 20 new entries; the sharded path
        must too (capacity computed post-exit, psum'd across chips)."""
        from sentinel_tpu.models import constants as C

        jitted, state, batch = _sharded_fixture(
            count=20.0, grade=C.FLOW_GRADE_THREAD, threads0=20, n_exits=20
        )
        stats2, _, _, _, result = jitted(*state, batch)
        assert int(np.asarray(result.admitted).sum()) == 20
        # Gauge balances: 20 - 20 released + 20 acquired.
        assert int(np.asarray(stats2.threads)[0]) == 20

    def test_no_release_no_capacity(self):
        from sentinel_tpu.models import constants as C

        jitted, state, batch = _sharded_fixture(
            count=20.0, grade=C.FLOW_GRADE_THREAD, threads0=20
        )
        _, _, _, _, result = jitted(*state, batch)
        assert int(np.asarray(result.admitted).sum()) == 0


@pytest.mark.mesh
class TestBudgetWithBreaker:
    def test_half_open_probe_stays_within_grant(self):
        """Budget is allocated at the flow level, so a breaker in
        HALF_OPEN admitting only probes can never push total admissions
        beyond the flow grant (the probe-shift hole)."""
        from sentinel_tpu.rules import degrade_table as dt

        jitted, state, batch = _sharded_fixture(count=2.0, degrade_rule_on_r0=True)
        stats, fdev, fdyn, ddev, ddyn, pdyn, sysdev = state
        ddyn = ddyn._replace(
            state=ddyn.state.at[0].set(dt.OPEN),
            next_retry=ddyn.next_retry.at[0].set(500),  # past retry at now=1000
        )
        _, _, ddyn2, _, result = jitted(stats, fdev, fdyn, ddev, ddyn, pdyn, sysdev, batch)
        total = int(np.asarray(result.admitted).sum())
        assert total <= 2, f"admitted {total} > flow grant 2"

    def test_half_open_probe_success_closes_across_mesh(self):
        """The probe's successful exit lands in ONE chip's exit shard;
        the merged breaker state must become CLOSED — a plain pmax merge
        would keep HALF_OPEN (2 > 0) and wedge the resource forever."""
        from sentinel_tpu.rules import degrade_table as dt

        jitted, state, batch = _sharded_fixture(
            count=1000.0, n_exits=1, exits_complete_dgid0=True
        )
        stats, fdev, fdyn, ddev, ddyn, pdyn, sysdev = state
        ddyn = ddyn._replace(state=ddyn.state.at[0].set(dt.HALF_OPEN))
        # Entries must not touch the breaker (e_dgid = -1 in fixture).
        _, _, ddyn2, _, _ = jitted(stats, fdev, fdyn, ddev, ddyn, pdyn, sysdev, batch)
        assert int(np.asarray(ddyn2.state)[0]) == dt.CLOSED, (
            "HALF_OPEN→CLOSED transition lost in the mesh merge"
        )

    def test_half_open_probe_failure_reopens_across_mesh(self):
        from sentinel_tpu.rules import degrade_table as dt

        jitted, state, batch = _sharded_fixture(
            count=1000.0, n_exits=1, exits_complete_dgid0=True
        )
        stats, fdev, fdyn, ddev, ddyn, pdyn, sysdev = state
        ddyn = ddyn._replace(state=ddyn.state.at[0].set(dt.HALF_OPEN))
        batch = batch._replace(x_err=batch.x_err.at[0].set(1))  # probe failed
        _, _, ddyn2, _, _ = jitted(stats, fdev, fdyn, ddev, ddyn, pdyn, sysdev, batch)
        assert int(np.asarray(ddyn2.state)[0]) == dt.OPEN, (
            "HALF_OPEN→OPEN transition lost in the mesh merge"
        )


@pytest.mark.mesh
def test_dryrun_multichip_8():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_graft_entry_compiles():
    import jax

    from __graft_entry__ import entry

    fn, args = entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out[0])
