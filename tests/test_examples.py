"""Every runnable demo in examples/ must stay runnable — each is a
documented drive of a product surface (the sentinel-demo analog), and a
silent bit-rot there is a broken front door. Each demo self-terminates
and runs on the CPU backend via examples/_bootstrap.py.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")
DEMOS = sorted(
    f for f in os.listdir(EXAMPLES_DIR)
    if f.endswith(".py") and not f.startswith("_")
)


@pytest.mark.parametrize("demo", DEMOS)
def test_example_runs_clean(demo):
    if demo == "mesh_demo.py":
        from sentinel_tpu.parallel import mesh_unavailable_reason

        reason = mesh_unavailable_reason(8)
        if reason:
            pytest.skip(reason)
    env = dict(os.environ)
    env.pop("SENTINEL_DEMO_REAL_DEVICES", None)  # force the CPU path
    env["SENTINEL_DEMO_PORT"] = "0"  # ephemeral ports: no collisions
    env["SENTINEL_DEMO_DURATION"] = "2"  # shorten long traffic loops
    r = subprocess.run(
        [sys.executable, demo],
        cwd=EXAMPLES_DIR,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert r.returncode == 0, (
        f"{demo} exited {r.returncode}\n--- stdout ---\n{r.stdout[-2000:]}"
        f"\n--- stderr ---\n{r.stderr[-2000:]}"
    )
    assert "Traceback" not in r.stderr, r.stderr[-2000:]
