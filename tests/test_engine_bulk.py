"""Columnar bulk submission — the TPU-idiomatic throughput path.

One slot resolution per group, numpy-slice encoding, array verdicts —
no per-op Python objects. The reference has no analog (its API is one
CAS-racing call per request); semantically a bulk group must decide
exactly like the same entries submitted one-by-one through
``submit_many``, which these tests pin.
"""

import numpy as np
import pytest


class TestBulkEntries:
    def test_bulk_parity_with_submit_many(self, manual_clock, engine):
        """Verdicts of a bulk group equal the same stream through
        submit_many (fresh engines so state matches)."""
        import sentinel_tpu as st
        from sentinel_tpu.runtime.engine import Engine

        rules = [st.FlowRule("res", count=20)]
        engine.set_flow_rules(rules)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(rules)
        manual_clock.set_ms(1000)
        ts = np.full(64, 1000, dtype=np.int32)
        g = engine.submit_bulk("res", 64, ts=ts)
        engine.flush()
        ops = ref.submit_many([{"resource": "res", "ts": 1000} for _ in range(64)])
        ref.flush()
        want = [o.verdict.admitted for o in ops]
        assert g.admitted.tolist() == want
        assert g.admitted_count == 20
        assert (g.reason[~g.admitted] > 0).all()

    def test_bulk_budget_and_stats(self, manual_clock, engine):
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("b", count=10)])
        g = engine.submit_bulk("b", 32)
        engine.flush()
        assert g.admitted_count == 10
        stats = engine.cluster_node_stats("b")
        assert stats["pass_qps"] == pytest.approx(10.0)
        assert stats["total_block_minute"] == 22

    def test_bulk_thread_grade_with_bulk_exits(self, manual_clock, engine):
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("t", grade=0, count=4)])
        g = engine.submit_bulk("t", 8)
        engine.flush()
        assert g.admitted_count == 4
        engine.submit_exit_bulk(g.rows, 2, rt=5, resource="t")
        g2 = engine.submit_bulk("t", 8)
        engine.flush()
        assert g2.admitted_count == 2

    def test_bulk_error_exits_trip_breaker(self, manual_clock, engine):
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("d", count=1000)])
        engine.set_degrade_rules(
            [st.DegradeRule(resource="d", grade=1, count=0.5, time_window=2,
                            min_request_amount=5)]
        )
        manual_clock.set_ms(1000)
        g = engine.submit_bulk("d", 8, ts=1000)
        engine.flush()
        assert g.admitted_count == 8
        engine.submit_exit_bulk(g.rows, 8, rt=5, err=1, ts=1000, resource="d")
        engine.flush()
        manual_clock.set_ms(1100)
        g2 = engine.submit_bulk("d", 8, ts=1100)
        engine.flush()
        assert g2.admitted_count == 0
        assert (g2.reason == 0).sum() == 0

    def test_bulk_shaping_rule(self, manual_clock, engine):
        """A bulk group on a rate-limiter resource rides the pacer scan
        (cost=100ms, maxq=300 → 1 immediate + 3 queued)."""
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C

        engine.set_flow_rules(
            [st.FlowRule("rl", count=10,
                         control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                         max_queueing_time_ms=300)]
        )
        manual_clock.set_ms(1000)
        g = engine.submit_bulk("rl", 12, ts=1000)
        engine.flush()
        assert g.admitted_count == 4
        assert sorted(g.wait_ms[g.admitted].tolist()) == [0, 100, 200, 300]

    def test_bulk_rejects_cluster_rules(self, manual_clock, engine):
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ClusterFlowConfig

        engine.set_flow_rules(
            [st.FlowRule("c", count=10, cluster_mode=True,
                         cluster_config=ClusterFlowConfig(flow_id=7))]
        )
        with pytest.raises(ValueError, match="cluster"):
            engine.submit_bulk("c", 4)

    def test_bulk_block_log(self, manual_clock, engine, tmp_path):
        import sentinel_tpu as st
        from sentinel_tpu.metrics.block_log import BlockLogger

        engine.block_log = BlockLogger(base_dir=str(tmp_path), clock=engine.clock)
        engine.set_flow_rules([st.FlowRule("bl", count=5)])
        g = engine.submit_bulk("bl", 20)
        engine.flush()
        assert g.admitted_count == 5
        engine.block_log.flush()
        entries = engine.block_log.read_entries()
        assert entries
        (_, key, count), = [e for e in entries if e[1][0] == "bl"]
        assert key[1] == "FlowException"
        assert count == 15

    def test_bulk_mixed_with_singles(self, manual_clock, engine):
        """Singles and bulk in one flush share the same windows."""
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("mx", count=10)])
        manual_clock.set_ms(1000)
        ops = engine.submit_many([{"resource": "mx", "ts": 1000} for _ in range(6)])
        g = engine.submit_bulk("mx", 16, ts=1000)
        engine.flush()
        total = sum(o.verdict.admitted for o in ops) + g.admitted_count
        assert total == 10

    def test_bulk_reload_reresolves(self, manual_clock, engine):
        """A rule reload between submit and flush re-resolves the group
        against the new tables."""
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("rr", count=100)])
        g = engine.submit_bulk("rr", 8)
        # Reload swaps the index (drain-flush happens inside, deciding
        # the already-pending group with the OLD rules).
        engine.set_flow_rules([st.FlowRule("rr", count=0)])
        assert g.admitted_count == 8  # decided pre-reload
        g2 = engine.submit_bulk("rr", 8)
        engine.flush()
        assert g2.admitted_count == 0

    @pytest.mark.mesh
    def test_bulk_on_mesh(self, manual_clock, engine):
        import sentinel_tpu as st

        engine.enable_mesh(8)
        engine.set_flow_rules([st.FlowRule("m", count=20)])
        now = engine.clock.now_ms()
        g = engine.submit_bulk("m", 128, ts=now)
        engine.flush()
        assert g.admitted_count == 20

    def test_bulk_cols_do_not_alias_caller_arrays(self, manual_clock, engine):
        """The engine clamps/rebases its columns in place — caller
        arrays must never be mutated, and read-only arrays must work."""
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("al", count=100)])
        ts = np.full(4, 1000, dtype=np.int32)
        rt = np.full(4, 10_000_000, dtype=np.int32)
        g = engine.submit_bulk("al", 4, ts=ts)
        engine.flush()
        engine.submit_exit_bulk(g.rows, 4, rt=rt, resource="al")
        engine.flush()
        assert (rt == 10_000_000).all()  # clamp must not write through
        assert (ts == 1000).all()
        ro = np.broadcast_to(np.int32(1000), (4,))  # non-writeable view
        engine.submit_bulk("al", 4, ts=ro)
        engine.flush()

    def test_bulk_block_log_limit_app_attribution(self, manual_clock, engine, tmp_path):
        """Flow blocks in a bulk group log the blocking rule's limitApp,
        like the singles path."""
        import sentinel_tpu as st
        from sentinel_tpu.metrics.block_log import BlockLogger

        engine.block_log = BlockLogger(base_dir=str(tmp_path), clock=engine.clock)
        engine.set_flow_rules([st.FlowRule("la", count=2, limit_app="appA")])
        g = engine.submit_bulk("la", 8, origin="appA")
        engine.flush()
        assert g.admitted_count == 2
        engine.block_log.flush()
        (_, key, count), = [
            e for e in engine.block_log.read_entries() if e[1][0] == "la"
        ]
        assert key[1] == "FlowException"
        assert key[2] == "appA"
        assert count == 6

    def test_bulk_exits_apply_before_singles_entries(self, manual_clock, engine):
        """One flush mixing a bulk-exit group with singles entries:
        the exits release thread slots BEFORE admission, exactly like
        the unbatched path."""
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("ord", grade=0, count=4)])
        g = engine.submit_bulk("ord", 4)
        engine.flush()
        assert g.admitted_count == 4  # gauge now at 4
        engine.submit_exit_bulk(g.rows, 4, rt=5, resource="ord")
        ops = engine.submit_many([{"resource": "ord"} for _ in range(4)])
        engine.flush()  # one flush: bulk exits + singles entries
        assert sum(o.verdict.admitted for o in ops) == 4

    def test_bulk_exit_weighted_rt_no_overflow(self, manual_clock, engine):
        """Aggregated rt×count products overflow int32 — the callback
        must receive the true count-weighted mean."""
        import sentinel_tpu as st
        from sentinel_tpu.metrics.extension import MetricExtension, MetricExtensionProvider

        seen = []

        class Ext(MetricExtension):
            def add_rt(self, resource, rt_ms, *args):
                seen.append(("rt", resource, rt_ms))

            def add_success(self, resource, n, *args):
                seen.append(("success", resource, n))

        engine.set_flow_rules([st.FlowRule("w8", count=100)])
        MetricExtensionProvider.register(Ext())
        try:
            g = engine.submit_bulk("w8", 2)
            engine.flush()
            engine.submit_exit_bulk(
                g.rows, 2, rt=np.array([4000, 10], dtype=np.int32),
                count=np.array([600_000, 1], dtype=np.int32), resource="w8",
            )
            engine.flush()
            (rt,) = [v for k, r, v in seen if k == "rt" and r == "w8"]
            (count,) = [v for k, r, v in seen if k == "success" and r == "w8"]
            assert count == 600_001
            assert rt == (4000 * 600_000 + 10) // 600_001  # ≈ 3999, not negative
        finally:
            MetricExtensionProvider.clear()

    def test_bulk_custom_slot_vetoes_per_acquire(self, manual_clock, engine):
        """A custom slot that vetoes by acquire blocks exactly the
        matching entries of a mixed-acquire group."""
        import sentinel_tpu as st
        from sentinel_tpu.core import errors as E
        from sentinel_tpu.core.slots import ProcessorSlot, SlotChainRegistry

        class BigAcquireVeto(ProcessorSlot):
            name = "big-acquire"

            def entry(self, ctx):
                return "too-big" if ctx.acquire > 10 else None

        engine.set_flow_rules([st.FlowRule("cs", count=1000)])
        SlotChainRegistry.register(BigAcquireVeto())
        try:
            g = engine.submit_bulk(
                "cs", 3, acquire=np.array([1, 50, 50], dtype=np.int32)
            )
            engine.flush()
            assert g.admitted.tolist() == [True, False, False]
            assert g.reason[1] == E.BLOCK_CUSTOM
        finally:
            SlotChainRegistry.clear()

    def test_bulk_size_guards(self, manual_clock, engine):
        with pytest.raises(ValueError, match="n must be"):
            engine.submit_bulk("x", 0)
        with pytest.raises(ValueError, match="max_batch"):
            engine.submit_bulk("x", engine.max_batch + 1)
        with pytest.raises(ValueError, match="shape"):
            engine.submit_bulk("x", 4, ts=np.zeros(3, dtype=np.int32))

    def test_bulk_rejects_float_columns(self, manual_clock, engine):
        """A float ts/acquire column must fail as loudly as a shape
        mismatch — np.array(v, int32) used to truncate 1.9 -> 1."""
        with pytest.raises(TypeError, match="not integral"):
            engine.submit_bulk("x", 4, ts=np.array([1.0, 2.0, 3.0, 4.9]))
        with pytest.raises(TypeError, match="not integral"):
            engine.submit_bulk("x", 4, acquire=1.5)
        # Out-of-int32-range values must not silently wrap either.
        with pytest.raises(OverflowError, match="int32 range"):
            engine.submit_bulk("x", 4, ts=np.full(4, 1_700_000_000_000))
        # Integer dtypes of any width still pass when in range.
        g = engine.submit_bulk("x", 4, ts=np.arange(4, dtype=np.int64), acquire=2)
        assert g is not None


class TestBulkParamColumn:
    """QPS hot-param rules on the columnar path (args_column):
    per-value budgets must decide exactly like submit_many with the
    same args stream."""

    def test_param_column_parity_with_submit_many(self, manual_clock, engine):
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule
        from sentinel_tpu.runtime.engine import Engine

        flow = [st.FlowRule("gw", count=1000)]
        param = {"gw": [ParamFlowRule("gw", param_idx=0, count=3)]}
        engine.set_flow_rules(flow)
        engine.set_param_rules(param)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(flow)
        ref.set_param_rules(param)
        manual_clock.set_ms(1000)
        values = [f"ip-{i % 5}" for i in range(40)]
        g = engine.submit_bulk(
            "gw", 40, ts=np.full(40, 1000, dtype=np.int32),
            args_column=[(v,) for v in values],
        )
        engine.flush()
        ops = ref.submit_many(
            [{"resource": "gw", "ts": 1000, "args": (v,)} for v in values]
        )
        ref.flush()
        want = [o.verdict.admitted for o in ops]
        assert g.admitted.tolist() == want
        assert g.admitted_count == 15  # 5 values × count 3

    def test_param_column_hot_items_and_missing_values(self, manual_clock, engine):
        """Hot-item per-value thresholds apply on the columnar path;
        entries whose args carry no value for the rule pass the param
        check (ParamFlowChecker skips them)."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowItem, ParamFlowRule

        engine.set_flow_rules([st.FlowRule("h", count=1000)])
        engine.set_param_rules(
            {"h": [ParamFlowRule(
                "h", param_idx=0, count=1,
                param_flow_item_list=(ParamFlowItem(object="vip", count=4),),
            )]}
        )
        manual_clock.set_ms(1000)
        col = [("vip",)] * 6 + [("plain",)] * 3 + [(None,)] * 2
        g = engine.submit_bulk(
            "h", 11, ts=np.full(11, 1000, dtype=np.int32), args_column=col
        )
        engine.flush()
        adm = np.asarray(g.admitted)
        assert adm[:6].sum() == 4       # hot item threshold
        assert adm[6:9].sum() == 1      # default count
        assert adm[9:].all()            # no value -> param check passes

    def test_param_column_rejections(self, manual_clock, engine):
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import (
            ClusterFlowConfig,
            ParamFlowRule,
        )
        from sentinel_tpu.models import constants as C

        engine.set_flow_rules([st.FlowRule("rj", count=1000)])
        engine.set_param_rules(
            {"rj": [ParamFlowRule("rj", param_idx=0, count=1,
                                  grade=C.FLOW_GRADE_THREAD)]}
        )
        with pytest.raises(ValueError, match="THREAD"):
            engine.submit_bulk("rj", 2, args_column=[("a",), ("b",)])
        engine.set_param_rules(
            {"rj": [ParamFlowRule(
                "rj", param_idx=0, count=1, cluster_mode=True,
                cluster_config=ClusterFlowConfig(flow_id=1),
            )]}
        )
        with pytest.raises(ValueError, match="cluster"):
            engine.submit_bulk("rj", 2, args_column=[("a",), ("b",)])
        engine.set_param_rules(
            {"rj": [ParamFlowRule("rj", param_idx=0, count=1)]}
        )
        with pytest.raises(ValueError, match="collection"):
            engine.submit_bulk("rj", 2, args_column=[(["a", "b"],), ("c",)])
        with pytest.raises(ValueError, match="length"):
            engine.submit_bulk("rj", 3, args_column=[("a",)])

    def test_param_column_reload_semantics(self, manual_clock, engine):
        """A param-rule reload drain-flushes the pending group against
        the rules it was submitted under (same contract as the flow
        path); groups submitted after see the new index."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule

        engine.set_flow_rules([st.FlowRule("rr", count=1000)])
        engine.set_param_rules({"rr": [ParamFlowRule("rr", param_idx=0, count=5)]})
        manual_clock.set_ms(1000)
        g = engine.submit_bulk(
            "rr", 8, ts=np.full(8, 1000, dtype=np.int32),
            args_column=[("k",)] * 8,
        )
        engine.set_param_rules({"rr": [ParamFlowRule("rr", param_idx=0, count=2)]})
        assert np.asarray(g.admitted).sum() == 5  # decided pre-reload
        manual_clock.set_ms(3000)
        g2 = engine.submit_bulk(
            "rr", 8, ts=np.full(8, 3000, dtype=np.int32),
            args_column=[("k",)] * 8,
        )
        engine.flush()
        assert np.asarray(g2.admitted).sum() == 2  # new index's count

    def test_gateway_submit_bulk(self, manual_clock, engine):
        """The adapter fast path: gateway traffic through one bulk
        group, per-client-IP budgets."""
        from sentinel_tpu.adapters.gateway import (
            GatewayFlowRule,
            GatewayParamFlowItem,
            GatewayRequestInfo,
            PARAM_PARSE_STRATEGY_CLIENT_IP,
            PARAM_PARSE_STRATEGY_HEADER,
            gateway_rule_manager,
            gateway_submit_bulk,
        )
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("route", count=1000)])
        gateway_rule_manager.load_rules([
            GatewayFlowRule(
                "route", count=2,
                param_item=GatewayParamFlowItem(
                    parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP),
            ),
        ])
        manual_clock.set_ms(1000)
        infos = [
            GatewayRequestInfo(path="/x", client_ip=f"1.1.1.{i % 2}")
            for i in range(10)
        ]
        g = gateway_submit_bulk("route", infos, engine=engine,
                                ts=np.full(10, 1000, dtype=np.int32))
        engine.flush()
        assert np.asarray(g.admitted).sum() == 4  # 2 IPs × count 2

        # Generic (non-fast) parser path: header strategy.
        gateway_rule_manager.load_rules([
            GatewayFlowRule(
                "route", count=1,
                param_item=GatewayParamFlowItem(
                    parse_strategy=PARAM_PARSE_STRATEGY_HEADER,
                    field_name="X-K"),
            ),
        ])
        manual_clock.set_ms(3000)
        infos = [
            GatewayRequestInfo(path="/x", headers={"X-K": f"u{i % 3}"})
            for i in range(9)
        ]
        g2 = gateway_submit_bulk("route", infos, engine=engine,
                                 ts=np.full(9, 3000, dtype=np.int32))
        engine.flush()
        assert np.asarray(g2.admitted).sum() == 3  # 3 header values × 1

    def test_gateway_request_batch_parity(self, manual_clock, engine):
        """The columnar GatewayRequestBatch decides exactly like the
        same requests as a Sequence[GatewayRequestInfo] — both the
        fast-attr path (client IP, no pattern) and the generic parser
        (header strategy + prefix pattern)."""
        from sentinel_tpu.adapters.gateway import (
            GatewayFlowRule,
            GatewayParamFlowItem,
            GatewayRequestBatch,
            GatewayRequestInfo,
            PARAM_PARSE_STRATEGY_CLIENT_IP,
            PARAM_PARSE_STRATEGY_HEADER,
            PARAM_MATCH_STRATEGY_PREFIX,
            gateway_rule_manager,
            gateway_submit_bulk,
        )
        import sentinel_tpu as st
        from sentinel_tpu.runtime.engine import Engine

        flow = [st.FlowRule("route", count=1000)]
        engine.set_flow_rules(flow)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(flow)
        gateway_rule_manager.load_rules([
            GatewayFlowRule(
                "route", count=2,
                param_item=GatewayParamFlowItem(
                    parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP),
            ),
        ])
        # The gateway manager feeds the GLOBAL engine's param rules;
        # mirror them onto the reference engine by hand.
        from sentinel_tpu.rules.param_manager import param_flow_rule_manager

        ref.set_param_rules(dict(param_flow_rule_manager.by_resource))
        manual_clock.set_ms(1000)
        infos = [
            GatewayRequestInfo(path="/x", client_ip="1.1.1.%d" % (i % 3) if i % 5 else "")
            for i in range(20)
        ]
        ts = np.full(20, 1000, dtype=np.int32)
        g_i = gateway_submit_bulk("route", infos, engine=engine, ts=ts)
        g_b = gateway_submit_bulk(
            "route", GatewayRequestBatch.from_infos(infos), engine=ref, ts=ts
        )
        engine.flush()
        ref.flush()
        assert g_b.admitted.tolist() == g_i.admitted.tolist()
        # Empty client_ip → nothing to limit on → admitted.
        assert g_b.admitted[0]

        # Generic parser path: header strategy with a prefix pattern.
        gateway_rule_manager.load_rules([
            GatewayFlowRule(
                "route", count=1,
                param_item=GatewayParamFlowItem(
                    parse_strategy=PARAM_PARSE_STRATEGY_HEADER,
                    field_name="X-K", pattern="u",
                    match_strategy=PARAM_MATCH_STRATEGY_PREFIX),
            ),
        ])
        ref.set_param_rules(dict(param_flow_rule_manager.by_resource))
        manual_clock.set_ms(3000)
        infos = [
            GatewayRequestInfo(
                path="/x",
                headers={"X-K": ("u%d" % (i % 3)) if i % 4 else "other"},
            )
            for i in range(16)
        ]
        ts = np.full(16, 3000, dtype=np.int32)
        g_i = gateway_submit_bulk("route", infos, engine=engine, ts=ts)
        g_b = gateway_submit_bulk(
            "route", GatewayRequestBatch.from_infos(infos), engine=ref, ts=ts
        )
        engine.flush()
        ref.flush()
        assert g_b.admitted.tolist() == g_i.admitted.tolist()
        # "other" fails the prefix pattern → not limited → admitted.
        assert g_b.admitted[0] and g_b.admitted[4]

    def test_gateway_batch_column_validation(self):
        from sentinel_tpu.adapters.gateway import GatewayRequestBatch

        with pytest.raises(ValueError, match="client_ip"):
            GatewayRequestBatch(n=3, client_ip=["a", "b"])
