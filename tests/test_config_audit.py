"""tools/config_audit.py: every sentinel.tpu.* key referenced anywhere
in sentinel_tpu/ must be declared in utils/config.py DEFAULTS (ISSUE 4
CI satellite — the sentinel.tpu.trace.* family lands with this guard
in place), and every DECLARED key must appear in docs/ARCHITECTURE.md
(ISSUE 7 satellite — catches the sentinel.tpu.ingest.* /
speculative.shaping.* families and any future doc drift)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import config_audit  # noqa: E402

_PKG_ROOT = os.path.join(os.path.dirname(__file__), "..", "sentinel_tpu")
_DOC = os.path.join(
    os.path.dirname(__file__), "..", "docs", "ARCHITECTURE.md"
)


class TestConfigAudit:
    def test_tree_is_clean(self):
        missing, refs = config_audit.audit(_PKG_ROOT)
        assert missing == [], f"undeclared config keys referenced: {missing}"
        assert refs, "the scan must actually find key references"

    def test_new_trace_family_is_covered(self):
        """The guard actually sees this PR's keys — if the scan regex
        or walk broke, this catches it before a real miss slips by."""
        _missing, refs = config_audit.audit(_PKG_ROOT)
        assert any(k.startswith("sentinel.tpu.trace.") for k in refs)
        assert any(k.startswith("sentinel.tpu.telemetry.") for k in refs)

    def test_detects_undeclared_key(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            'X = config.get_bool("sentinel.tpu.notakey.enabled", True)\n'
        )
        missing, refs = config_audit.audit(str(tmp_path))
        assert missing == ["sentinel.tpu.notakey.enabled"]
        assert refs["sentinel.tpu.notakey.enabled"]

    def test_family_prefix_mention_passes(self, tmp_path):
        """Docstring family mentions (``sentinel.tpu.host.arena.*``)
        resolve as prefixes of declared keys, not as misses."""
        (tmp_path / "mod.py").write_text(
            '"""Tune via sentinel.tpu.host.arena.* keys."""\n'
        )
        missing, _refs = config_audit.audit(str(tmp_path))
        assert missing == []

    def test_rejects_negative_style_garbage(self, tmp_path):
        """A trailing dot / wildcard never widens the match into a
        false pass for a genuinely undeclared full key."""
        (tmp_path / "mod.py").write_text(
            'Y = config.get("sentinel.tpu.host.arena.bogus")\n'
        )
        missing, _refs = config_audit.audit(str(tmp_path))
        assert missing == ["sentinel.tpu.host.arena.bogus"]

    def test_cli_exit_status(self, tmp_path, capsys):
        (tmp_path / "ok.py").write_text(
            'K = "sentinel.tpu.flush.max.batch"\n'
        )
        old = sys.argv
        try:
            # --no-metrics keeps this a pure key audit (the metric
            # audit builds an Engine; its CLI path is covered below).
            sys.argv = [
                "config_audit.py", "--root", str(tmp_path), "--doc", _DOC,
                "--no-metrics",
            ]
            assert config_audit.main() == 0
            (tmp_path / "bad.py").write_text('K = "sentinel.tpu.zzz"\n')
            assert config_audit.main() == 1
            out = capsys.readouterr().out
            assert "sentinel.tpu.zzz" in out
        finally:
            sys.argv = old


class TestDocCoverage:
    def test_every_declared_key_is_documented(self):
        undocumented = config_audit.audit_docs(_DOC)
        assert undocumented == [], (
            f"declared keys missing from ARCHITECTURE.md: {undocumented}"
        )

    def test_detects_undocumented_key(self, tmp_path):
        """A doc that only mentions some keys reports the rest — and a
        family mention covers its members."""
        doc = tmp_path / "ARCH.md"
        doc.write_text(
            "All `sentinel.tpu.ingest.*` keys plus "
            "`sentinel.tpu.flush.max.batch` are documented here.\n"
        )
        undocumented = config_audit.audit_docs(str(doc))
        # The ingest family is covered by its prefix mention; the
        # explicit key is covered; everything else reports.
        assert "sentinel.tpu.ingest.max.pending" not in undocumented
        assert "sentinel.tpu.ingest.deadline.ms" not in undocumented
        assert "sentinel.tpu.flush.max.batch" not in undocumented
        assert "sentinel.tpu.speculative.enabled" in undocumented

    def test_missing_doc_reports_everything(self, tmp_path):
        undocumented = config_audit.audit_docs(str(tmp_path / "nope.md"))
        assert "sentinel.tpu.flush.max.batch" in undocumented


class TestMetricsAudit:
    """ISSUE 8 satellite: every Prometheus family the exporter emits
    and every TelemetryBus counter key must appear verbatim in
    ARCHITECTURE.md."""

    def test_repo_doc_is_clean(self):
        bad_fams, bad_ctrs = config_audit.audit_metrics(_DOC)
        assert bad_fams == [], f"undocumented families: {bad_fams}"
        assert bad_ctrs == [], f"undocumented counters: {bad_ctrs}"

    def test_live_introspection_sees_this_prs_families(self):
        fams = config_audit.prometheus_families()
        # Seed gauges, flight-recorder counters, histogram families,
        # and the PR-8 bounded per-resource export are all visible to
        # the introspection — a broken render path can't silently
        # shrink the audited surface.
        for f in (
            "sentinel_pass_qps",
            "sentinel_engine_flushes_total",
            "sentinel_engine_flush_duration_ms",
            "sentinel_resource_speculative_total",
            "sentinel_resource_drift",
        ):
            assert f in fams, f
        ctrs = config_audit.telemetry_counter_keys()
        assert {"flushes", "ingest_shed", "spec_admits"} <= ctrs

    def test_detects_undocumented_family_and_counter(self, tmp_path):
        doc = tmp_path / "ARCH.md"
        doc.write_text("Only `sentinel_engine_flushes_total` and "
                       "`flushes` are documented here.\n")
        bad_fams, bad_ctrs = config_audit.audit_metrics(
            str(doc),
            families={"sentinel_engine_flushes_total",
                      "sentinel_engine_nope_total"},
            counters={"flushes", "nope_counter"},
        )
        assert bad_fams == ["sentinel_engine_nope_total"]
        assert bad_ctrs == ["nope_counter"]

    def test_missing_doc_reports_everything(self, tmp_path):
        bad_fams, bad_ctrs = config_audit.audit_metrics(
            str(tmp_path / "nope.md"),
            families={"sentinel_x"}, counters={"c1"},
        )
        assert bad_fams == ["sentinel_x"] and bad_ctrs == ["c1"]

    def test_cli_includes_metric_audit(self, tmp_path, capsys):
        """The CLI runs the metric audit by default and reports a doc
        that dropped a family."""
        doc = tmp_path / "ARCH.md"
        # Every declared key documented via family mentions so ONLY the
        # metric audit can fail here.
        from sentinel_tpu.utils.config import SentinelConfig

        doc.write_text(
            " ".join(f"`{k}`" for k in SentinelConfig.DEFAULTS) + "\n"
        )
        old = sys.argv
        try:
            sys.argv = [
                "config_audit.py", "--root", str(tmp_path), "--doc",
                str(doc),
            ]
            assert config_audit.main() == 1
            out = capsys.readouterr().out
            assert "Prometheus families" in out
        finally:
            sys.argv = old


class TestCommandAudit:
    """The fourth pass: every @command_mapping name must be
    backtick-quoted in the architecture doc."""

    def test_tree_is_clean(self):
        missing = config_audit.audit_commands(_DOC)
        assert missing == [], (
            f"transport commands not backtick-documented: {missing}"
        )

    def test_registry_introspection_sees_new_commands(self):
        cmds = config_audit.transport_commands()
        assert {"metrics", "spans", "cluster/server/stats",
                "basicInfo", "tree"} <= cmds

    def test_backtick_quoting_required(self, tmp_path):
        doc = tmp_path / "ARCH.md"
        # `spans` is quoted (alone and with a ?arg suffix); metrics
        # appears only as prose and must NOT satisfy the audit.
        doc.write_text(
            "Hit `spans` (or `spans?spill=1`) for the journal; the "
            "metrics endpoint is documented elsewhere as prose.\n"
            "Grouped mentions count too: `tree, basicInfo`.\n"
        )
        missing = config_audit.audit_commands(
            str(doc),
            commands={"spans", "metrics", "tree", "basicInfo"},
        )
        assert missing == ["metrics"]

    def test_missing_doc_reports_every_command(self, tmp_path):
        missing = config_audit.audit_commands(
            str(tmp_path / "nope.md"), commands={"b", "a"}
        )
        assert missing == ["a", "b"]

    def test_cli_no_commands_flag_skips(self, tmp_path, capsys):
        doc = tmp_path / "ARCH.md"
        from sentinel_tpu.utils.config import SentinelConfig

        doc.write_text(
            " ".join(f"`{k}`" for k in SentinelConfig.DEFAULTS) + "\n"
        )
        old = sys.argv
        try:
            # Without the flag the undocumented registry fails the CLI
            # with the commands section...
            sys.argv = ["config_audit.py", "--root", str(tmp_path),
                        "--doc", str(doc), "--no-metrics"]
            assert config_audit.main() == 1
            assert "transport commands" in capsys.readouterr().out
            # ...and --no-commands skips exactly that pass.
            sys.argv = sys.argv + ["--no-commands"]
            assert config_audit.main() == 0
            assert "transport commands" not in capsys.readouterr().out
        finally:
            sys.argv = old
