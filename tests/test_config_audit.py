"""tools/config_audit.py: every sentinel.tpu.* key referenced anywhere
in sentinel_tpu/ must be declared in utils/config.py DEFAULTS (ISSUE 4
CI satellite — the sentinel.tpu.trace.* family lands with this guard
in place), and every DECLARED key must appear in docs/ARCHITECTURE.md
(ISSUE 7 satellite — catches the sentinel.tpu.ingest.* /
speculative.shaping.* families and any future doc drift)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import config_audit  # noqa: E402

_PKG_ROOT = os.path.join(os.path.dirname(__file__), "..", "sentinel_tpu")
_DOC = os.path.join(
    os.path.dirname(__file__), "..", "docs", "ARCHITECTURE.md"
)


class TestConfigAudit:
    def test_tree_is_clean(self):
        missing, refs = config_audit.audit(_PKG_ROOT)
        assert missing == [], f"undeclared config keys referenced: {missing}"
        assert refs, "the scan must actually find key references"

    def test_new_trace_family_is_covered(self):
        """The guard actually sees this PR's keys — if the scan regex
        or walk broke, this catches it before a real miss slips by."""
        _missing, refs = config_audit.audit(_PKG_ROOT)
        assert any(k.startswith("sentinel.tpu.trace.") for k in refs)
        assert any(k.startswith("sentinel.tpu.telemetry.") for k in refs)

    def test_detects_undeclared_key(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            'X = config.get_bool("sentinel.tpu.notakey.enabled", True)\n'
        )
        missing, refs = config_audit.audit(str(tmp_path))
        assert missing == ["sentinel.tpu.notakey.enabled"]
        assert refs["sentinel.tpu.notakey.enabled"]

    def test_family_prefix_mention_passes(self, tmp_path):
        """Docstring family mentions (``sentinel.tpu.host.arena.*``)
        resolve as prefixes of declared keys, not as misses."""
        (tmp_path / "mod.py").write_text(
            '"""Tune via sentinel.tpu.host.arena.* keys."""\n'
        )
        missing, _refs = config_audit.audit(str(tmp_path))
        assert missing == []

    def test_rejects_negative_style_garbage(self, tmp_path):
        """A trailing dot / wildcard never widens the match into a
        false pass for a genuinely undeclared full key."""
        (tmp_path / "mod.py").write_text(
            'Y = config.get("sentinel.tpu.host.arena.bogus")\n'
        )
        missing, _refs = config_audit.audit(str(tmp_path))
        assert missing == ["sentinel.tpu.host.arena.bogus"]

    def test_cli_exit_status(self, tmp_path, capsys):
        (tmp_path / "ok.py").write_text(
            'K = "sentinel.tpu.flush.max.batch"\n'
        )
        old = sys.argv
        try:
            sys.argv = [
                "config_audit.py", "--root", str(tmp_path), "--doc", _DOC,
            ]
            assert config_audit.main() == 0
            (tmp_path / "bad.py").write_text('K = "sentinel.tpu.zzz"\n')
            assert config_audit.main() == 1
            out = capsys.readouterr().out
            assert "sentinel.tpu.zzz" in out
        finally:
            sys.argv = old


class TestDocCoverage:
    def test_every_declared_key_is_documented(self):
        undocumented = config_audit.audit_docs(_DOC)
        assert undocumented == [], (
            f"declared keys missing from ARCHITECTURE.md: {undocumented}"
        )

    def test_detects_undocumented_key(self, tmp_path):
        """A doc that only mentions some keys reports the rest — and a
        family mention covers its members."""
        doc = tmp_path / "ARCH.md"
        doc.write_text(
            "All `sentinel.tpu.ingest.*` keys plus "
            "`sentinel.tpu.flush.max.batch` are documented here.\n"
        )
        undocumented = config_audit.audit_docs(str(doc))
        # The ingest family is covered by its prefix mention; the
        # explicit key is covered; everything else reports.
        assert "sentinel.tpu.ingest.max.pending" not in undocumented
        assert "sentinel.tpu.ingest.deadline.ms" not in undocumented
        assert "sentinel.tpu.flush.max.batch" not in undocumented
        assert "sentinel.tpu.speculative.enabled" in undocumented

    def test_missing_doc_reports_everything(self, tmp_path):
        undocumented = config_audit.audit_docs(str(tmp_path / "nope.md"))
        assert "sentinel.tpu.flush.max.batch" in undocumented
