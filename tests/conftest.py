"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE any backend use, so
the multi-chip sharding paths compile and run without TPU hardware —
the in-process analog of the reference's strategy of testing the
cluster token service directly in-JVM (SURVEY.md §4)."""

from sentinel_tpu.utils.backend import force_cpu

force_cpu(8)

import pytest  # noqa: E402


@pytest.fixture()
def manual_clock():
    """The fake-clock fixture — equivalent of the reference's
    AbstractTimeBasedTest (PowerMock-mocked TimeUtil). Installs a
    ManualClock as the process default, resets the global engine to use
    it, and restores afterwards."""
    from sentinel_tpu.core import api
    from sentinel_tpu.utils.clock import ManualClock, set_default_clock

    clock = ManualClock(start_ms=0)
    prev = set_default_clock(clock)
    api.reset(clock=clock)
    yield clock
    set_default_clock(prev)
    api.reset()


@pytest.fixture()
def engine(manual_clock):
    from sentinel_tpu.core import api

    return api.get_engine()
