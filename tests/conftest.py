"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE jax import, so the
multi-chip sharding paths compile and run without TPU hardware — the
in-process analog of the reference's strategy of testing the cluster
token service directly in-JVM (SURVEY.md §4)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's site hook may pre-register an accelerator plugin and
# pin jax_platforms before env vars are read; force CPU explicitly.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def manual_clock():
    """The fake-clock fixture — equivalent of the reference's
    AbstractTimeBasedTest (PowerMock-mocked TimeUtil). Installs a
    ManualClock as the process default, resets the global engine to use
    it, and restores afterwards."""
    from sentinel_tpu.core import api
    from sentinel_tpu.utils.clock import ManualClock, set_default_clock

    clock = ManualClock(start_ms=0)
    prev = set_default_clock(clock)
    api.reset(clock=clock)
    yield clock
    set_default_clock(prev)
    api.reset()


@pytest.fixture()
def engine(manual_clock):
    from sentinel_tpu.core import api

    return api.get_engine()
