"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE any backend use, so
the multi-chip sharding paths compile and run without TPU hardware —
the in-process analog of the reference's strategy of testing the
cluster token service directly in-JVM (SURVEY.md §4)."""

from sentinel_tpu.utils.backend import force_cpu

force_cpu(8)

import jax  # noqa: E402

# Long single-process runs accumulate XLA:CPU/LLVM JIT state until the
# compiler itself segfaults (observed deep into the slow tier: crash in
# backend_compile_and_load after ~45 min of compiles; any single test
# passes in isolation). Two-part mitigation: persist compiled
# executables on disk so recompiles skip LLVM entirely, and drop the
# in-memory executable caches periodically to bound JIT memory.
jax.config.update("jax_compilation_cache_dir", "/tmp/sentinel_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402

_TESTS_SINCE_CLEAR = {"n": 0}


@pytest.fixture(autouse=True)
def _bound_jit_state():
    yield
    _TESTS_SINCE_CLEAR["n"] += 1
    if _TESTS_SINCE_CLEAR["n"] % 25 == 0:
        jax.clear_caches()


@pytest.fixture()
def manual_clock():
    """The fake-clock fixture — equivalent of the reference's
    AbstractTimeBasedTest (PowerMock-mocked TimeUtil). Installs a
    ManualClock as the process default, resets the global engine to use
    it, and restores afterwards."""
    from sentinel_tpu.core import api
    from sentinel_tpu.utils.clock import ManualClock, set_default_clock

    clock = ManualClock(start_ms=0)
    prev = set_default_clock(clock)
    api.reset(clock=clock)
    yield clock
    set_default_clock(prev)
    api.reset()


@pytest.fixture()
def engine(manual_clock):
    from sentinel_tpu.core import api

    return api.get_engine()
