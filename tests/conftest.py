"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE any backend use, so
the multi-chip sharding paths compile and run without TPU hardware —
the in-process analog of the reference's strategy of testing the
cluster token service directly in-JVM (SURVEY.md §4)."""

import os

# Serialize XLA:CPU's LLVM codegen (default split 32 compiles modules on
# a thread pool): repeated pjit compiles in one long process segfaulted
# inside backend_compile_and_load / the executable serializer, which
# smells like concurrent-codegen state corruption — and a 1-core box
# gains nothing from parallel codegen anyway. Must be set before the
# first backend use.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_cpu_parallel_codegen_split_count=1"
).strip()

from sentinel_tpu.utils.backend import force_cpu

force_cpu(8)

import gc  # noqa: E402
import signal  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

# Hard wall-clock bound for one `mp`-marked test: generous against the
# 1-core box's spawn+import cost (each worker process re-imports jax),
# but finite — a wedged worker handshake must fail THIS test, never
# hang the whole tier.
MP_TEST_TIMEOUT_S = 240


@pytest.fixture(autouse=True)
def _mp_watchdog(request):
    """SIGALRM watchdog for tests that spawn real worker processes
    (the ``mp`` marker): the multi-process ingest plane blocks on
    cross-process handshakes (ready queues, verdict waits), and a hung
    worker would otherwise wedge tier-1 forever. The alarm raises in
    the test thread; test helpers terminate their children in
    ``finally`` blocks."""
    if "mp" not in request.keywords:
        yield
        return

    def _expired(signum, frame):
        raise TimeoutError(
            f"mp test exceeded {MP_TEST_TIMEOUT_S}s watchdog "
            "(hung worker process?)"
        )

    old = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(MP_TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)

# Long single-process runs accumulate XLA:CPU/LLVM JIT state until the
# native compiler eventually segfaults (observed twice deep into the
# slow tier: once in backend_compile_and_load after ~45 min of
# compiles, once in the persistent-cache executable serializer; any
# single test passes in isolation). Round-5 diagnosis: each compiled
# executable pins JIT code-page mmaps, and the process walks into
# vm.max_map_count (65530 default) — LLVM then reports "Cannot
# allocate memory" and segfaults; /proc/<pid>/maps showed ~30k maps
# after two differential streams, dropping to ~1k on clear_caches().
# Mitigation: periodically drop the in-memory executable caches and
# collect, bounding resident JIT state (diffbatch_worker does the same
# between streams). The persistent disk cache is deliberately NOT
# enabled — its serialize path was itself a crash site.
_TESTS_SINCE_CLEAR = {"n": 0}


@pytest.fixture(autouse=True)
def _bound_jit_state():
    yield
    _TESTS_SINCE_CLEAR["n"] += 1
    if _TESTS_SINCE_CLEAR["n"] % 15 == 0:
        jax.clear_caches()
        gc.collect()


@pytest.fixture()
def manual_clock():
    """The fake-clock fixture — equivalent of the reference's
    AbstractTimeBasedTest (PowerMock-mocked TimeUtil). Installs a
    ManualClock as the process default, resets the global engine to use
    it, and restores afterwards."""
    from sentinel_tpu.core import api
    from sentinel_tpu.utils.clock import ManualClock, set_default_clock

    clock = ManualClock(start_ms=0)
    prev = set_default_clock(clock)
    api.reset(clock=clock)
    yield clock
    set_default_clock(prev)
    api.reset()


@pytest.fixture()
def engine(manual_clock):
    from sentinel_tpu.core import api

    return api.get_engine()


def pytest_collection_modifyitems(config, items):
    """Auto-skip ``mesh``-marked tests when the sharded flush
    capability is absent (parallel.mesh_unavailable_reason: older jax
    without stable jax.shard_map, or too few devices): a capability
    the environment lacks is a skip with a reason, not a wall of
    ImportError failures hiding real regressions."""
    from sentinel_tpu.parallel import mesh_unavailable_reason

    reason = mesh_unavailable_reason(8)
    if not reason:
        return
    skip = pytest.mark.skip(reason=reason)
    for item in items:
        if "mesh" in item.keywords:
            item.add_marker(skip)
