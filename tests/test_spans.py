"""Fleet span journal (metrics/spans.py) + the cross-process timeline.

The acceptance surface: the per-process SpanJournal is a bounded ring
with rolling jsonl spill whose loader survives crash-truncated tails;
worker admit spans and engine drain/frame spans land on the SAME
wall-ms ruler so a spawned worker's verdict stamp pins inside the
engine's frame-drain interval; ``sentinel.tpu.spans.enabled=false``
is one bool read per call site and verdicts are bit-identical either
way; the armed-on overhead stays ≤ 2% at pipeline depths {0, 2}
(slow tier — a wall-clock guard, not a tier-1 gate).
"""

from __future__ import annotations

import json
import os
import time

import pytest

from sentinel_tpu.ipc.plane import IngestPlane
from sentinel_tpu.ipc.worker import IngestClient
from sentinel_tpu.metrics.spans import (
    SpanJournal,
    get_journal,
    load_journal,
    reset_journal,
    wall_ms,
)
from sentinel_tpu.models.rules import FlowRule
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.utils.config import config

import ipc_procs


@pytest.fixture(autouse=True)
def _sandbox():
    """Config sandbox + journal singleton reset: span tests flip
    sentinel.tpu.spans.* and must not leak an armed journal into the
    rest of the tier."""
    with config._lock:
        saved = dict(config._runtime)
    reset_journal()
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)
    reset_journal()


class TestSpanJournal:
    def test_record_rounds_and_drops_none_fields(self):
        spj = SpanJournal(role="t", enabled=True, ring=64, spill_every=0)
        spj.record("admit", "worker", 1000.12349, 2.5, wid=3, seq=7,
                   trace=None, adm=1)
        (sp,) = spj.spans()
        assert sp["name"] == "admit" and sp["cat"] == "worker"
        assert sp["t0"] == 1000.123  # 3dp
        assert sp["dur"] == 2.5
        assert sp["wid"] == 3 and sp["seq"] == 7 and sp["adm"] == 1
        assert "trace" not in sp  # None fields dropped, not serialized

    def test_negative_duration_clamps_to_zero(self):
        spj = SpanJournal(role="t", enabled=True, ring=64, spill_every=0)
        spj.record("x", "worker", 10.0, -3.0)
        assert spj.spans()[0]["dur"] == 0.0

    def test_ring_bound_floor_is_16(self):
        spj = SpanJournal(role="t", enabled=True, ring=4, spill_every=0)
        for i in range(40):
            spj.record("x", "worker", float(i), 0.1, seq=i)
        spans = spj.spans()
        assert len(spans) == 16  # max(16, cap)
        assert spans[0]["seq"] == 24 and spans[-1]["seq"] == 39

    def test_cat_filter(self):
        spj = SpanJournal(role="t", enabled=True, ring=64, spill_every=0)
        spj.record("a", "worker", 1.0, 0.1)
        spj.record("b", "engine", 2.0, 0.1)
        assert [s["name"] for s in spj.spans(cat="engine")] == ["b"]

    def test_snapshot_counters(self):
        spj = SpanJournal(role="probe", enabled=True, ring=32,
                          spill_every=0)
        for i in range(5):
            spj.record("x", "worker", float(i), 0.1)
        snap = spj.snapshot()
        assert snap["role"] == "probe" and snap["pid"] == os.getpid()
        assert snap["enabled"] is True and snap["ring"] == 32
        assert snap["buffered"] == 5 and snap["recorded_total"] == 5
        assert snap["spilled_total"] == 0

    def test_spill_load_roundtrip_with_ruler_offset(self, tmp_path):
        spj = SpanJournal(role="worker", enabled=True, ring=64,
                          spill_every=0, base_dir=str(tmp_path))
        spj.record("admit", "worker", 500.0, 1.25, wid=1, seq=9)
        # A ruler beat 40ms behind the local clock -> spill meta must
        # carry the (local - ruler) delta fleetdump subtracts.
        spj.note_ruler(wall_ms() - 40.0)
        path = spj.spill()
        assert path is not None
        assert os.path.basename(path).startswith(
            f"{config.app_name}-spans-worker-"
        ) and path.endswith(f"{os.getpid()}.jsonl")
        loaded = load_journal(path)
        assert loaded["meta"]["role"] == "worker"
        assert loaded["meta"]["pid"] == os.getpid()
        assert 35.0 <= loaded["meta"]["ruler_off_ms"] <= 45.0
        assert loaded["spans"] == [
            {"name": "admit", "cat": "worker", "t0": 500.0, "dur": 1.25,
             "wid": 1, "seq": 9}
        ]
        # Spill drained the ring; nothing to write twice.
        assert spj.spans() == [] and spj.spill() is None
        assert spj.snapshot()["spilled_total"] == 1

    def test_spill_appends_and_last_meta_wins(self, tmp_path):
        spj = SpanJournal(role="w", enabled=True, ring=64, spill_every=0,
                          base_dir=str(tmp_path))
        spj.record("a", "worker", 1.0, 0.1)
        path = spj.spill()
        spj.note_ruler(wall_ms() - 10.0)
        spj.record("b", "worker", 2.0, 0.1)
        assert spj.spill() == path  # same file, appended
        loaded = load_journal(path)
        assert [s["name"] for s in loaded["spans"]] == ["a", "b"]
        # First batch's meta had no ruler; the LAST meta (which does)
        # is the freshest skew estimate and must win.
        assert 5.0 <= loaded["meta"]["ruler_off_ms"] <= 15.0

    def test_load_skips_malformed_tail(self, tmp_path):
        path = tmp_path / "crash.jsonl"
        path.write_text(
            json.dumps({"meta": 1, "role": "w", "pid": 1}) + "\n"
            + json.dumps({"name": "a", "cat": "worker", "t0": 1.0,
                          "dur": 0.1}) + "\n"
            + '["not a span"]\n'
            + '{"no_name": 1}\n'
            + '{"name": "trunc", "t0": 2.'  # crash mid-write
        )
        loaded = load_journal(str(path))
        assert loaded["meta"]["role"] == "w"
        assert [s["name"] for s in loaded["spans"]] == ["a"]

    def test_spill_every_auto_spills(self, tmp_path):
        spj = SpanJournal(role="w", enabled=True, ring=64, spill_every=3,
                          base_dir=str(tmp_path))
        for i in range(3):
            spj.record("x", "worker", float(i), 0.1)
        assert spj.snapshot()["spilled_total"] == 3
        assert spj.snapshot()["buffered"] == 0

    def test_get_journal_first_role_wins_and_reset_rereads_config(self):
        assert get_journal("shard").role == "shard"
        assert get_journal("worker").role == "shard"  # singleton
        assert get_journal().enabled is False  # default config
        reset_journal()
        config.set(config.SPANS_ENABLED, "true")
        config.set(config.SPANS_RING, "32")
        spj = get_journal("worker")
        assert spj.role == "worker" and spj.enabled is True
        assert spj.snapshot()["ring"] == 32


class TestInProcessSpans:
    """Worker + engine span recording through a real IngestPlane, all
    in one process (the in-process journal carries both cats)."""

    def _plane(self):
        eng = Engine(initial_rows=256)
        eng.set_flow_rules([FlowRule(resource="span-res", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        return eng, plane, cli

    def test_admit_and_frame_spans_correlate(self):
        config.set(config.SPANS_ENABLED, "true")
        eng, plane, cli = self._plane()
        try:
            for _ in range(4):
                v = cli.entry("span-res", acquire=1)
                assert v.admitted
            spj = get_journal()
            admits = [s for s in spj.spans(cat="worker")
                      if s["name"] == "admit"]
            frames = [s for s in spj.spans(cat="engine")
                      if s["name"] == "frame"]
            drains = [s for s in spj.spans(cat="engine")
                      if s["name"] == "drain"]
            assert len(admits) == 4 and drains and frames
            for a in admits:
                assert a["wid"] == 0 and a["adm"] == 1 and a["win"] == 0
                assert a["push_ms"] >= 0.0
                # The verdict stamp lands inside (or a rounding hair
                # past) the admit interval itself.
                assert a["t0"] <= a["v"] <= a["t0"] + a["dur"] + 0.002
                # ...and pins against an engine frame span carrying
                # this (wid, seq): dequeue at/after join, verdict
                # at/after dequeue.
                owner = [f for f in frames
                         if f["wid"] == 0
                         and f["seq_lo"] <= a["seq"] <= f["seq_hi"]]
                assert len(owner) == 1, (a, frames)
                f = owner[0]
                assert a["t0"] <= f["t0"] + 0.002
                assert a["v"] >= f["t0"] - 0.002
            for d in drains:
                assert d["frames"] >= 1 and d["rows"] >= 1
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_traceparent_rides_the_admit_span(self):
        config.set(config.SPANS_ENABLED, "true")
        from sentinel_tpu.core.context import ContextUtil
        from sentinel_tpu.metrics.admission_trace import parse_traceparent

        tp = "00-0123456789abcdef0123456789abcdef-00f067aa0ba902b7-01"
        eng, plane, cli = self._plane()
        try:
            ContextUtil.set_trace(parse_traceparent(tp))
            cli.entry("span-res")
            ContextUtil.set_trace(None)
            (a,) = [s for s in get_journal().spans(cat="worker")
                    if s["name"] == "admit"]
            assert a["trace"] == "0123456789abcdef0123456789abcdef"
        finally:
            ContextUtil.set_trace(None)
            cli.close()
            plane.close()
            eng.close()

    def test_disabled_records_nothing_and_verdicts_bit_identical(self):
        """The off→on differential: the span plane only observes —
        the verdict stream (admitted, reason, wait_ms, limit_type,
        degraded, speculative) must be bit-identical armed or not."""
        def drive():
            eng = Engine(initial_rows=256)
            eng.set_flow_rules([FlowRule(resource="span-res", count=3)])
            plane = IngestPlane(eng)
            cli = IngestClient(plane.channel(0), 0)
            out = []
            try:
                for i in range(6):
                    v = cli.entry("span-res", acquire=1)
                    out.append((v.admitted, int(v.reason), v.wait_ms,
                                v.limit_type, v.degraded, v.speculative))
                a, r, w, f = cli.bulk("span-res", 4)
                out.append((a.tolist(), r.tolist(), w.tolist(),
                            f.tolist()))
            finally:
                cli.close()
                plane.close()
                eng.close()
            return out

        config.set(config.SPANS_ENABLED, "false")
        reset_journal()
        off = drive()
        # One bool read, no stamps ever taken.
        assert get_journal().snapshot()["recorded_total"] == 0

        config.set(config.SPANS_ENABLED, "true")
        config.set(config.SPANS_DIR, "/tmp")
        reset_journal()
        on = drive()
        assert get_journal().snapshot()["recorded_total"] > 0
        assert on == off


@pytest.mark.slow
class TestSpanOverhead:
    """Armed-on wall-clock guard: spans add ≤ 2% to the worker entry
    path at pipeline depths {0, 2}. Interleaved A/B batches with the
    best-of-rounds ratio keep the bound honest on a noisy 1-core box
    (noise is one-sided: a clean round exists if the code is clean)."""

    @pytest.mark.parametrize("depth", [0, 2])
    def test_armed_overhead_within_2pct(self, depth):
        eng = Engine(initial_rows=1024)
        eng.pipeline_depth = depth
        eng.set_flow_rules([FlowRule(resource="ovh-res", count=1e18)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        spj = get_journal()
        try:
            def batch(n=160):
                t0 = time.perf_counter()
                for _ in range(n):
                    cli.entry("ovh-res", acquire=1)
                return (time.perf_counter() - t0) / n

            for _ in range(2):
                batch()  # warm: compile + intern
            ratios = []
            for _ in range(5):
                spj.enabled = False
                off = min(batch(), batch())
                spj.enabled = True
                on = min(batch(), batch())
                ratios.append(on / off)
            assert min(ratios) <= 1.02, ratios
        finally:
            spj.enabled = False
            cli.close()
            plane.close()
            eng.close()


@pytest.mark.mp
class TestFleetAlignment:
    """A REAL spawned worker's admit spans align with this engine's
    frame spans on the shared wall-ms ruler — the property fleetdump's
    merged timeline rests on."""

    def test_worker_span_pins_inside_engine_frame(self, tmp_path):
        config.set(config.SPANS_ENABLED, "true")
        config.set(config.SPANS_DIR, str(tmp_path))
        eng = Engine(initial_rows=256)
        eng.set_flow_rules([FlowRule(resource="mp-span-res", count=1e9)])
        plane = IngestPlane(eng)
        cfg = {
            config.SPANS_ENABLED: "true",
            config.SPANS_DIR: str(tmp_path),
        }
        ctx = plane.spawn_context()
        q = ctx.Queue()
        p = ctx.Process(
            target=ipc_procs.run_entries_spanned,
            args=(plane.channel(0), 0, cfg, "mp-span-res", 6, q),
            daemon=True,
        )
        p.start()
        try:
            tag, wid, verdicts, child_path = q.get(timeout=180)
            assert tag == "done" and wid == 0
            assert all(adm and not deg for adm, _r, deg in verdicts)
            p.join(timeout=60)
            child = load_journal(child_path)
            assert child["meta"]["role"] == "worker"
            # Same machine, same epoch clock: the worker's observed
            # ruler skew is bounded by one heartbeat read.
            assert abs(child["meta"].get("ruler_off_ms", 0.0)) < 5000.0
            admits = [s for s in child["spans"] if s["name"] == "admit"]
            assert len(admits) == 6
            frames = [s for s in get_journal().spans(cat="engine")
                      if s["name"] == "frame" and s["wid"] == 0]
            assert frames
            beat_ms = 1000.0  # >> the ~100ms heartbeat cadence
            for a in admits:
                owner = [f for f in frames
                         if f["seq_lo"] <= a["seq"] <= f["seq_hi"]]
                assert len(owner) == 1, (a, frames)
                f = owner[0]
                # Join precedes dequeue; the verdict stamp lands in
                # [dequeue, dequeue + dur + wakeup-latency] on the
                # SHARED ruler even though the stamps were taken in
                # two different processes.
                assert a["t0"] <= f["t0"] + 2.0
                assert f["t0"] - 2.0 <= a["v"] <= (
                    f["t0"] + f["dur"] + beat_ms
                )
        finally:
            if p.is_alive():
                p.terminate()
            p.join(timeout=30)
            plane.close()
            eng.close()
