"""Fuzz-hardening for the ipc frame codec (sentinel_tpu/ipc/frames.py).

The codec is no longer just ring-slot transport: capture segments
(runtime/capture.py) persist these exact bytes as the DURABLE black-box
format, so a torn tail or a corrupted byte must fail as one clean
``ValueError`` — never a ``struct.error``, a silently short
``np.frombuffer`` view that misaligns every later column, or a decode
that fabricates rows. Seeded randomized roundtrips over every frame
kind, plus adversarial truncation/garbage sweeps, pin that contract.
"""

import random

import numpy as np
import pytest

from sentinel_tpu.ipc import frames


def _rand_value(rng: random.Random, depth: int = 0):
    kinds = ["none", "bool", "int", "float", "str", "bytes"]
    if depth < 2:
        kinds.append("tuple")
    k = rng.choice(kinds)
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    if k == "int":
        return rng.randint(-(2**62), 2**62)
    if k == "float":
        return rng.uniform(-1e12, 1e12)
    if k == "str":
        n = rng.choice([0, 1, 7, 255, 4096])
        return "".join(rng.choice("abcdefg é中") for _ in range(n))
    if k == "bytes":
        return bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 64)))
    return tuple(
        _rand_value(rng, depth + 1) for _ in range(rng.randint(0, 4))
    )


def _rand_args(rng: random.Random):
    return tuple(_rand_value(rng) for _ in range(rng.randint(0, 6)))


def _rand_interns(rng: random.Random, max_name: int = 8192):
    """Intern table with ragged name sizes up to ``max_name`` (the
    max-size-name class: one segment-scoped id can carry a huge
    resource string)."""
    out = []
    for iid in range(1, rng.randint(1, 6)):
        n = rng.choice([1, 8, 300, max_name])
        out.append((iid, bytes(rng.getrandbits(7) | 1 for _ in range(n))))
    return out


def _rand_entry_rows(rng: random.Random, n: int):
    rows = []
    for i in range(n):
        traced = rng.random() < 0.3
        trace = (
            frames.pack_trace("ab" * 16, "cd" * 8, True)
            if traced else frames.EMPTY_TRACE
        )
        rows.append(frames.EntryRow(
            seq=rng.randint(0, 2**63),
            resource_id=rng.randint(0, 2**31 - 1),
            context_id=rng.randint(0, 2**31 - 1),
            origin_id=rng.randint(0, 2**31 - 1),
            entry_type=rng.randint(-128, 127),
            acquire=rng.randint(-(2**31), 2**31 - 1),
            ts=rng.randint(-1, 2**62),
            trace=trace,
            args=frames.encode_args(_rand_args(rng)),
        ))
    return rows


def _rand_exit_rows(rng: random.Random, n: int):
    return [
        frames.ExitRow(
            seq=rng.randint(0, 2**63),
            resource_id=rng.randint(-1, 2**31 - 1),
            context_id=rng.randint(-1, 2**31 - 1),
            origin_id=rng.randint(-1, 2**31 - 1),
            entry_type=rng.randint(-128, 127),
            ts=rng.randint(0, 2**62),
            rt=rng.randint(0, 2**31 - 1),
            count=rng.randint(0, 2**31 - 1),
            err=rng.randint(0, 2**31 - 1),
            spec=rng.randint(0, 2),
        )
        for _ in range(n)
    ]


class TestArgsCodec:
    def test_roundtrip_randomized(self):
        rng = random.Random(0xA465)
        for _ in range(300):
            args = _rand_args(rng)
            assert frames.decode_args(frames.encode_args(args)) == args

    def test_empty(self):
        assert frames.encode_args(()) == b""
        assert frames.decode_args(b"") == ()

    def test_truncated_tails_raise_cleanly(self):
        rng = random.Random(0xBEEF)
        for _ in range(40):
            blob = frames.encode_args(_rand_args(rng) or ("pad",))
            for cut in range(1, len(blob)):
                try:
                    frames.decode_args(blob[:cut])
                except ValueError:
                    continue
                # A prefix that still parses must be a whole value
                # boundary artifact — never a crash, never a non-
                # ValueError (struct.error, IndexError...).

    def test_bad_tag_raises(self):
        with pytest.raises(ValueError):
            frames.decode_args(b"\x01\x00Z")


class TestFrameRoundtrips:
    def test_entry_and_bulk_randomized(self):
        rng = random.Random(0xC0DE)
        for kind in (frames.KIND_ENTRY, frames.KIND_BULK):
            for _ in range(25):
                n = rng.choice([1, 2, 17, 128])
                rows = _rand_entry_rows(rng, n)
                interns = _rand_interns(rng)
                meta = (
                    bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 40)))
                    if kind == frames.KIND_BULK else None
                )
                payload = frames.encode_entries(
                    rng.randint(0, 65535), rows, interns,
                    rng.randint(0, 2**31), rng.randint(0, 2**31),
                    kind=kind, group_meta=meta,
                )
                df = frames.decode_frame(payload)
                assert df.kind == kind and df.n == n
                assert df.interns == interns
                for i, r in enumerate(rows):
                    assert int(df.columns["seq"][i]) == r.seq
                    assert int(df.columns["ts"][i]) == r.ts
                    assert int(df.columns["acquire"][i]) == r.acquire
                    assert int(df.columns["entry_type"][i]) == r.entry_type
                    assert int(df.columns["resource_id"][i]) == r.resource_id
                    a0 = int(df.columns["args_off"][i])
                    a1 = a0 + int(df.columns["args_len"][i])
                    assert frames.decode_args(df.varbytes[a0:a1]) == \
                        frames.decode_args(r.args)
                    t = df.traces[i * 26:(i + 1) * 26]
                    assert t == r.trace

    def test_exit_randomized_with_extras(self):
        rng = random.Random(0xE417)
        for _ in range(40):
            n = rng.choice([1, 3, 64])
            rows = _rand_exit_rows(rng, n)
            extras = frames.encode_args(
                [tuple(rng.randint(0, 100) for _ in range(rng.randint(0, 3)))
                 for _ in range(n)]
            ) if rng.random() < 0.5 else b""
            payload = frames.encode_exits(
                rng.randint(0, 65535), rows, _rand_interns(rng),
                rng.randint(0, 2**31), 0, extras=extras,
            )
            df = frames.decode_frame(payload)
            assert df.kind == frames.KIND_EXIT and df.n == n
            assert df.varbytes == extras
            for i, r in enumerate(rows):
                assert int(df.columns["seq"][i]) == r.seq
                assert int(df.columns["rt"][i]) == r.rt
                assert int(df.columns["err"][i]) == r.err
                assert int(df.columns["spec"][i]) == r.spec

    def test_verdict_randomized(self):
        rng = np.random.default_rng(0x7E4D)
        for _ in range(25):
            n = int(rng.integers(1, 200))
            seqs = rng.integers(0, 2**63, n, dtype=np.uint64)
            adm = rng.integers(0, 2, n, dtype=np.uint8)
            rea = rng.integers(-9, 10, n, dtype=np.int16)
            wait = rng.integers(0, 10_000, n, dtype=np.int32)
            flags = rng.integers(0, 256, n, dtype=np.uint8)
            df = frames.decode_frame(
                frames.encode_verdicts(3, seqs, adm, rea, wait, flags)
            )
            assert df.kind == frames.KIND_VERDICT and df.n == n
            np.testing.assert_array_equal(df.columns["seq"], seqs)
            np.testing.assert_array_equal(df.columns["admitted"], adm)
            np.testing.assert_array_equal(df.columns["reason"], rea)
            np.testing.assert_array_equal(df.columns["wait_ms"], wait)
            np.testing.assert_array_equal(df.columns["flags"], flags)

    def test_reassert_randomized(self):
        rng = random.Random(0x4EA5)
        for head in (False, True):
            rows = [
                frames.ReassertRow(
                    resource_id=rng.randint(0, 1000),
                    context_id=rng.randint(0, 1000),
                    origin_id=rng.randint(0, 1000),
                    entry_type=rng.randint(-1, 1),
                    spec=rng.randint(0, 1),
                    acquire=rng.randint(1, 8),
                    count=rng.randint(1, 10_000),
                )
                for _ in range(rng.randint(1, 40))
            ]
            df = frames.decode_frame(
                frames.encode_reasserts(1, rows, [], 0, 0, head=head)
            )
            assert df.kind == frames.KIND_REASSERT
            assert bool(df.flags & frames.F_FRAME_RECONNECT) is head
            assert [int(x) for x in df.columns["count"]] == \
                [r.count for r in rows]

    def test_columnar_encoder_is_byte_identical(self):
        """encode_entries_columns (the capture journal's vectorized
        bulk spill) must produce the EXACT bytes of encode_entries over
        the equivalent row list — same decoder, same durable format."""
        rng = random.Random(0xB01C)
        for n in (0, 1, 7, 333):
            base = rng.randint(0, 2**40)
            ts = [rng.randint(0, 2**40) for _ in range(n)]
            acq = [rng.randint(1, 100) for _ in range(n)]
            interns = _rand_interns(rng)
            rows = [
                frames.EntryRow(
                    seq=base + j, resource_id=3, context_id=2, origin_id=1,
                    entry_type=0x41, acquire=acq[j], ts=ts[j],
                    trace=frames.EMPTY_TRACE, args=b"",
                )
                for j in range(n)
            ]
            want = frames.encode_entries(
                5, rows, interns, 9, 0, kind=frames.KIND_BULK
            )
            got = frames.encode_entries_columns(
                5, base, np.array(ts, np.int64), np.array(acq, np.int32),
                0x41, 3, 2, 1, interns, 9,
            )
            assert got == want

    def test_zero_row_preambles(self):
        """Zero-row frames are legal (a chunk can be exits-only or a
        fresh connection's intern-only preamble) and must decode."""
        for payload in (
            frames.encode_entries(1, [], [(1, b"res")], 7, 0),
            frames.encode_entries(1, [], [], 0, 0, kind=frames.KIND_BULK),
            frames.encode_exits(1, [], [], 0, 0),
            frames.encode_exits(1, [], [], 0, 0, extras=b"xx"),
            frames.encode_verdicts(
                1, *(np.empty(0, d) for d in
                     (np.uint64, np.uint8, np.int16, np.int32, np.uint8))
            ),
            frames.encode_reasserts(1, [], [], 0, 0),
        ):
            df = frames.decode_frame(payload)
            assert df.n == 0


class TestFrameAdversarial:
    def _samples(self):
        rng = random.Random(0x7541)
        out = [
            frames.encode_entries(
                2, _rand_entry_rows(rng, 9), _rand_interns(rng), 1, 0
            ),
            frames.encode_entries(
                2, _rand_entry_rows(rng, 4), [], 1, 0,
                kind=frames.KIND_BULK, group_meta=b"metameta",
            ),
            frames.encode_exits(
                2, _rand_exit_rows(rng, 6), _rand_interns(rng), 1, 0,
                extras=frames.encode_args([(1, 2)] * 6),
            ),
            frames.encode_verdicts(
                2,
                np.arange(5, dtype=np.uint64),
                np.ones(5, np.uint8),
                np.zeros(5, np.int16),
                np.zeros(5, np.int32),
                np.zeros(5, np.uint8),
            ),
            frames.encode_reasserts(
                2,
                [frames.ReassertRow(1, 2, 3, 0, 0, 1, 5)] * 3,
                [(1, b"r")], 0, 0, head=True,
            ),
        ]
        return out

    def test_every_truncated_tail_raises_valueerror(self):
        """EVERY strict prefix of every frame kind must raise ONE clean
        ValueError — the torn-segment-tail contract the capture journal
        leans on."""
        for payload in self._samples():
            for cut in range(0, len(payload)):
                with pytest.raises(ValueError):
                    frames.decode_frame(payload[:cut])

    def test_unknown_kind_raises(self):
        payload = bytearray(self._samples()[0])
        payload[0] = 99
        with pytest.raises(ValueError):
            frames.decode_frame(bytes(payload))

    def test_random_garbage_never_crashes(self):
        """Pure garbage either raises ValueError or decodes to an empty
        /consistent frame — never any other exception type."""
        rng = random.Random(0x6A4B)
        for _ in range(400):
            blob = bytes(rng.getrandbits(8)
                         for _ in range(rng.randint(0, 200)))
            try:
                df = frames.decode_frame(blob)
            except ValueError:
                continue
            assert df.n >= 0

    def test_corrupt_intern_count_raises(self):
        payload = bytearray(self._samples()[0])
        # n_interns field lives at header offset 24 (<BBHIQIIII): blow
        # it up so the intern loop runs off the payload.
        import struct
        struct.pack_into("<I", payload, 24, 2**31)
        with pytest.raises(ValueError):
            frames.decode_frame(bytes(payload))
