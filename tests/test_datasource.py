"""Datasource tests: property wiring, file refresh, writable registry,
end-to-end rule reload through a manager (the reference's
FileRefreshableDataSource + register2Property path)."""

import json
import os
import time

import sentinel_tpu as st
from sentinel_tpu.datasource import (
    FileRefreshableDataSource,
    FileWritableDataSource,
    InMemoryDataSource,
    WritableDataSourceRegistry,
    json_converter,
)
from sentinel_tpu.models.rules import FlowRule


class TestConverters:
    def test_json_converter_camel_case(self):
        conv = json_converter(FlowRule)
        rules = conv('[{"resource": "r", "count": 5, "controlBehavior": 2, "maxQueueingTimeMs": 100}]')
        assert rules[0].resource == "r"
        assert rules[0].control_behavior == 2
        assert rules[0].max_queueing_time_ms == 100

    def test_json_converter_empty(self):
        conv = json_converter(FlowRule)
        assert conv("") == []
        assert conv("[]") == []


class TestFileSource:
    def test_refresh_on_change(self, tmp_path, manual_clock, engine):
        path = tmp_path / "flow.json"
        path.write_text(json.dumps([{"resource": "fs", "count": 1}]))
        src = FileRefreshableDataSource(str(path), json_converter(FlowRule), 999)
        st.flow_rule_manager.register_property(src.get_property())
        assert src.refresh() is True
        assert st.try_entry("fs") is not None
        assert st.try_entry("fs") is None  # count=1 enforced

        # Update the file; force distinct mtime; manual refresh (poll tick).
        path.write_text(json.dumps([{"resource": "fs", "count": 100}]))
        os.utime(path, (time.time() + 5, time.time() + 5))
        assert src.refresh() is True
        manual_clock.advance(2000)  # new window
        for _ in range(5):
            e = st.try_entry("fs")
            assert e is not None
            e.exit()

    def test_unmodified_skips(self, tmp_path):
        path = tmp_path / "f.json"
        path.write_text("[]")
        src = FileRefreshableDataSource(str(path), json_converter(FlowRule), 999)
        assert src.refresh() is False or src.refresh() is False  # second poll no-op

    def test_writable_roundtrip(self, tmp_path):
        path = tmp_path / "w.json"
        w = FileWritableDataSource(
            str(path), lambda rules: json.dumps([r.to_dict() for r in rules])
        )
        w.write([FlowRule("wr", count=7)])
        r = FileRefreshableDataSource(str(path), json_converter(FlowRule), 999)
        r.refresh()
        rules = r.get_property().value
        assert rules[0].resource == "wr" and rules[0].count == 7


class TestWritableRegistry:
    def test_registry(self, tmp_path):
        WritableDataSourceRegistry.clear()
        path = tmp_path / "reg.json"
        w = FileWritableDataSource(str(path), lambda v: json.dumps(v))
        WritableDataSourceRegistry.register("flow", w)
        assert WritableDataSourceRegistry.try_write("flow", [{"resource": "x"}])
        assert json.loads(path.read_text())[0]["resource"] == "x"
        assert not WritableDataSourceRegistry.try_write("degrade", [])
        WritableDataSourceRegistry.clear()


class TestInMemorySource:
    def test_push_updates_manager(self, manual_clock, engine):
        src = InMemoryDataSource(json_converter(FlowRule))
        st.flow_rule_manager.register_property(src.get_property())
        src.write(json.dumps([{"resource": "mem", "count": 2}]))
        assert len(st.flow_rule_manager.get_rules()) == 1
        assert st.try_entry("mem") is not None
        assert st.try_entry("mem") is not None
        assert st.try_entry("mem") is None
