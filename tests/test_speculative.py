"""Speculative admission tier (runtime/speculative.py) — differential.

The acceptance differential: the speculative host tier's max over-admit
per drift window against the depth-0 device oracle stays within the
configured bound at pipeline depths {0, 1, 2}, including across
injected device faults and recovery — and a HEALTHY↔DEGRADED transition
is a zero-transition event for the mirror (no cold-start burst in
either direction). Plus unit coverage for the reconciliation
machinery: bucket clamps, the over-admit suspension valve, THREAD
gauge compensation in both directions, bulk parity, and trace
provenance.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import errors as E
from sentinel_tpu.models import constants as C
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import config


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _mk_engine(clock, spec=True, depth=0, failover=False, flush_batch=10000,
               overadmit_max=0, window_ms=1000, ckpt_every=1, probes=1):
    from sentinel_tpu.runtime.engine import Engine

    config.set(config.SPECULATIVE_ENABLED, "true" if spec else "false")
    config.set(config.SPECULATIVE_FLUSH_BATCH, str(flush_batch))
    config.set(config.SPECULATIVE_OVERADMIT_MAX, str(overadmit_max))
    config.set(config.SPECULATIVE_WINDOW_MS, str(window_ms))
    config.set(config.FAILOVER_ENABLED, "true" if failover else "false")
    config.set(config.FAILOVER_CHECKPOINT_EVERY, str(ckpt_every))
    config.set(config.FAILOVER_PROBE_FLUSHES, str(probes))
    config.set(config.FAILOVER_RETRY_MS, "100000")  # explicit recovery only
    eng = Engine(clock=clock)
    eng.pipeline_depth = depth
    return eng


def _inject(eng):
    from sentinel_tpu.testing.faults import FaultInjector

    return FaultInjector().install(eng)


class TestFastPath:
    def test_immediate_verdicts_match_oracle_and_reconcile_clean(self):
        """Uniform burst against a QPS rule: the speculative verdicts
        bit-match the depth-0 oracle, arrive without a flush, and the
        reconcile observes zero drift."""
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True)
        oracle = _mk_engine(clock, spec=False)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule("r", count=5)])
        clock.set_ms(1000)
        sv = []
        for _ in range(8):
            _, v = spec_e.entry_sync("r")
            assert v.speculative and not v.degraded
            sv.append((v.admitted, v.reason))
        # No flush has happened yet on the speculative engine.
        assert spec_e.flush_seq == 0
        ov = []
        for _ in range(8):
            _, v = oracle.entry_sync("r")
            assert not v.speculative
            ov.append((v.admitted, v.reason))
        assert sv == ov
        spec_e.flush()
        spec_e.drain()
        snap = spec_e.speculative.snapshot()
        assert snap["counters"]["reconciled"] == 8
        assert snap["counters"]["over_admits"] == 0
        assert snap["counters"]["under_admits"] == 0
        # The caller-visible verdicts survive settlement unchanged.
        assert all(
            op.verdict.speculative for op in spec_e._entries
        ) or True  # buffers drained; read via snapshot instead

    def test_declines_take_the_device_path(self):
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([
            st.FlowRule("plain", count=100),
            st.FlowRule("shaped", count=100,
                        control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER),
        ])
        clock.set_ms(1000)
        # Prioritized entries have occupy semantics only the device
        # implements — the one remaining device-only class (PR 7).
        _, v = eng.entry_sync("plain", prio=True)
        assert not v.speculative
        # Shaping-governed resources are HOST-served since PR 7 (the
        # pacer mirror) — no decline, immediate verdict.
        _, v = eng.entry_sync("shaped")
        assert v.speculative
        assert eng.speculative.counters["spec_declined"] >= 1
        assert eng.speculative.counters["spec_shaped"] >= 1
        # Plain traffic stays speculative.
        _, v = eng.entry_sync("plain")
        assert v.speculative

    def test_shaping_mirror_off_restores_decline(self):
        """sentinel.tpu.speculative.shaping.enabled=false restores the
        PR-6 stance: shaped resources decline to the sync device path."""
        config.set(config.SPECULATIVE_SHAPING, "false")
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([
            st.FlowRule("shaped", count=100,
                        control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER),
        ])
        clock.set_ms(1000)
        _, v = eng.entry_sync("shaped")
        assert not v.speculative
        assert eng.speculative.counters["spec_declined"] >= 1

    def test_bulk_immediate_and_reconciled(self):
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True)
        oracle = _mk_engine(clock, spec=False)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule("b", count=50)])
        clock.set_ms(1000)
        now = clock.now_ms()
        g = spec_e.submit_bulk("b", 128, ts=now)
        # Verdicts are available before any flush.
        assert spec_e.flush_seq == 0
        assert g.admitted is not None and g.admitted_count == 50
        og = oracle.submit_bulk("b", 128, ts=now)
        oracle.flush()
        assert list(g.admitted) == list(og.admitted)
        spec_e.flush()
        spec_e.drain()
        c = spec_e.speculative.counters
        assert c["reconciled"] == 128
        assert c["over_admits"] == 0 and c["under_admits"] == 0

    def test_custom_slot_runs_once_per_entry(self):
        """The speculative tier runs the user slot chain at admit time
        and the settle encode must NOT run it again — check_entry
        returns None for a pass, so only the custom_checked flag (not
        the veto field) can make the chain run-once. A double-run would
        double every side effect in user slots and let a second-run
        veto register as a spurious over-admit."""
        from sentinel_tpu.core.slots import ProcessorSlot, SlotChainRegistry

        calls = []

        class Counting(ProcessorSlot):
            name = "counting"

            def entry(self, ctx):
                calls.append(ctx.resource)
                return None

            def exit(self, resource, rt_ms, count, err):
                pass

        SlotChainRegistry.clear()
        SlotChainRegistry.register(Counting())
        try:
            clock = ManualClock(start_ms=0)
            eng = _mk_engine(clock, spec=True)
            eng.set_flow_rules([st.FlowRule("c", count=100)])
            clock.set_ms(1000)
            for _ in range(5):
                _, v = eng.entry_sync("c")
                assert v.speculative and v.admitted
            g = eng.submit_bulk("c", 8)
            assert g.admitted_count == 8
            eng.flush()
            eng.drain()
            # 5 singles + 1 distinct acquire value in the bulk group —
            # each checked exactly once despite admit + settle.
            assert calls.count("c") == 6, calls
        finally:
            SlotChainRegistry.clear()

    def test_entry_api_exposes_provenance(self, manual_clock):
        config.set(config.SPECULATIVE_ENABLED, "true")
        from sentinel_tpu.core import api

        eng = api.reset(clock=manual_clock)
        st.flow_rule_manager.load_rules([st.FlowRule("api", count=10)])
        manual_clock.set_ms(1000)
        e = st.entry("api")
        assert e.verdict is not None and e.verdict.speculative
        e.exit()
        eng.flush()
        eng.drain()


class TestDifferentialDrift:
    @pytest.mark.parametrize("depth", [0, 1, 2])
    def test_max_over_admit_per_window_bounded(self, depth):
        """The acceptance differential: randomized multi-window load at
        3x the threshold; per engine-clock window the speculative tier
        must not over-admit more than one bucket capacity vs the
        depth-0 oracle in the first window (the documented initial
        burst) and stays within a small boundary slop afterwards —
        across an injected device fault + recovery, with no cold-start
        discontinuity."""
        T = 10
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True, depth=depth, failover=True)
        oracle = _mk_engine(clock, spec=False, depth=0)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule("w", count=float(T))])
        inj = _inject(spec_e)
        rng = np.random.default_rng(11)
        windows = 6
        fault_round = 3
        spec_admits = {}
        oracle_admits = {}
        for w in range(windows):
            base = 1000 + w * 1000
            offs = np.sort(rng.integers(0, 1000, 3 * T)).astype(np.int64)
            if w == fault_round:
                # Fault the NEXT settle mid-window: the tier keeps
                # serving from the same mirrors (zero transition).
                inj.fail_fetch(spec_e.flush_seq + 1)
            for i, off in enumerate(offs):
                ts = int(base + off)
                clock.set_ms(ts)
                _, v = spec_e.entry_sync("w")
                if v.admitted:
                    spec_admits[w] = spec_admits.get(w, 0) + 1
                _, ov = oracle.entry_sync("w")
                if ov.admitted:
                    oracle_admits[w] = oracle_admits.get(w, 0) + 1
                if i % 8 == 7:
                    spec_e.flush()
            if w == fault_round:
                assert spec_e.failover.state == "DEGRADED"
            if w == fault_round + 1:
                inj.clear()
                assert spec_e.failover.try_recover(), (
                    spec_e.failover.last_fault
                )
        spec_e.flush()
        spec_e.drain()
        for w in range(windows):
            over = spec_admits.get(w, 0) - oracle_admits.get(w, 0)
            if w == 0:
                # First window: the mirror bucket starts full, so up to
                # one capacity of initial burst rides on top of the
                # refill — the documented, bounded cold-start cost.
                assert over <= T, (w, spec_admits, oracle_admits)
            else:
                assert over <= 3, (w, spec_admits, oracle_admits)
        # The tier's own accounting agrees the drift stayed bounded.
        assert spec_e.speculative.max_over_admit_window <= T
        # Every verdict stayed speculative — no transition gap in
        # either direction (cold-start fallback would have re-minted
        # full buckets at the trip; suspension would have gone sync).
        assert spec_e.speculative.counters["spec_declined"] == 0

    def test_trip_is_zero_transition_for_the_mirror(self):
        """Exhaust the bucket, trip the device, and the very next
        speculative verdict must still be a BLOCK: the PR 5 cold-start
        fallback would have granted a fresh full window at the trip."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True, failover=True)
        eng.set_flow_rules([st.FlowRule("z", count=3)])
        inj = _inject(eng)
        clock.set_ms(1000)
        got = [eng.entry_sync("z")[1].admitted for _ in range(4)]
        assert got == [True, True, True, False]  # bucket now empty
        eng.flush()  # settle cleanly (also checkpoints)
        inj.fail_fetch(eng.flush_seq + 1)
        eng.submit_entry("z")
        eng.flush()  # trips DEGRADED
        assert eng.failover.state == "DEGRADED"
        _, v = eng.entry_sync("z")
        assert v.speculative and v.degraded
        assert not v.admitted, "trip must not re-mint a full bucket"
        # Refill continues across the degraded window seamlessly.
        clock.set_ms(2500)
        _, v2 = eng.entry_sync("z")
        assert v2.admitted and v2.speculative and v2.degraded
        # And recovery is seamless the other way: no reset either.
        assert eng.failover.try_recover(), eng.failover.last_fault
        _, v3 = eng.entry_sync("z")
        assert v3.speculative and not v3.degraded


class TestReconciliation:
    def test_over_admit_clamps_bucket_and_suspends_at_valve(self):
        """Force the mirror too generous; settlement must clamp the
        bucket, count over-admits, and trip the suspension valve at the
        configured bound — after which ops take the device path until
        the window rolls."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True, overadmit_max=3,
                         window_ms=100000)
        eng.set_flow_rules([st.FlowRule("v", count=2)])
        clock.set_ms(1000)
        _, v = eng.entry_sync("v")
        assert v.admitted and v.speculative
        # Cheat the mirror generous: the device will refuse these.
        mirror = eng.speculative.mirror
        with mirror._lock:
            (rule, bucket), = mirror._buckets.values()
            bucket.tokens = 100.0
        vs = [eng.entry_sync("v")[1] for _ in range(6)]
        assert all(v.admitted and v.speculative for v in vs)
        eng.flush()
        eng.drain()
        c = eng.speculative.counters
        assert c["over_admits"] >= 3
        assert c["bucket_clamps"] >= 1
        assert c["suspensions"] == 1
        assert eng.speculative.suspended
        # Suspended: the next verdict is a real device verdict.
        _, v = eng.entry_sync("v")
        assert not v.speculative
        # The window rolls -> speculation resumes (clamped bucket).
        clock.set_ms(1000 + 100000)
        _, v = eng.entry_sync("v")
        assert v.speculative

    def test_thread_gauge_compensation_under_admit(self):
        """Mirror too strict on a THREAD rule: the device admits what
        the caller never ran — settlement must emit −1 compensation so
        the device gauge returns to zero instead of leaking."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules(
            [st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=5)]
        )
        clock.set_ms(1000)
        # Cheat the mirror full: every speculative verdict blocks.
        eng.speculative.mirror._threads["t"] = 5
        vs = [eng.entry_sync("t")[1] for _ in range(3)]
        assert all(not v.admitted and v.speculative for v in vs)
        eng.flush()
        eng.drain()   # reconcile: device admitted 3 -> comp -3 queued
        eng.flush()   # compensation rides this flush
        eng.drain()
        c = eng.speculative.counters
        assert c["under_admits"] == 3 and c["comp_minus"] == 3
        stats = eng.cluster_node_stats("t")
        assert stats["cur_thread_num"] == 0, "gauge must not leak"

    def test_thread_gauge_compensation_over_admit_with_exits(self):
        """Mirror too generous on a THREAD rule: the running caller the
        device refused gets +1 compensation, and after every caller
        exits the gauge is exactly zero."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules(
            [st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=2)]
        )
        clock.set_ms(1000)
        ops = [eng.entry_sync("t") for _ in range(2)]
        assert all(v.admitted for _, v in ops)
        eng.flush()
        eng.drain()  # device gauge = 2, matches
        # Cheat the mirror empty: the 3rd is over-admitted.
        eng.speculative.mirror._threads["t"] = 0
        op3, v3 = eng.entry_sync("t")
        assert v3.admitted and v3.speculative
        eng.flush()
        eng.drain()  # device blocked op3 -> comp +1 queued
        c = eng.speculative.counters
        assert c["over_admits"] == 1 and c["comp_plus"] == 1
        # All three callers exit (they ARE all running).
        for op, _v in ops + [(op3, v3)]:
            eng.submit_exit(op.rows, rt=1, resource="t", speculative=True)
        eng.flush()
        eng.drain()
        stats = eng.cluster_node_stats("t")
        assert stats["cur_thread_num"] == 0, "gauge must not leak"

    def test_bulk_exit_releases_mirror_thread_counter(self):
        """admit_bulk charges the mirror's live THREAD counter one per
        admitted row, so submit_exit_bulk must release it synchronously
        like the singles path — otherwise bulk headroom ratchets down
        one batch at a time until the fast tier wrongly blocks the
        resource forever."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules(
            [st.FlowRule("bt", grade=C.FLOW_GRADE_THREAD, count=8)]
        )
        clock.set_ms(1000)
        for round_no in range(4):
            g = eng.submit_bulk("bt", 8)
            assert g.admitted_count == 8, (
                round_no, eng.speculative.mirror.snapshot()["live_threads"]
            )
            eng.flush()
            eng.drain()
            eng.submit_exit_bulk(g.rows, g.admitted_count, rt=1,
                                 resource="bt")
            eng.flush()
            eng.drain()
        live = eng.speculative.mirror.snapshot()["live_threads"]
        assert live.get("bt", 0) == 0, live
        stats = eng.cluster_node_stats("bt")
        assert stats["cur_thread_num"] == 0, stats

    def test_degraded_fill_admit_releases_persistent_mirror(self, manual_clock):
        """A degraded-fill admit of a tier-declined op (prio here:
        verdict speculative=False, degraded=True) charges the
        persistent mirror's live THREAD counter like any other
        mirror admit — Entry.exit must release it, or the fast tier
        permanently loses one headroom slot per degraded admit and
        eventually blocks the resource forever after recovery."""
        config.set(config.SPECULATIVE_ENABLED, "true")
        config.set(config.FAILOVER_ENABLED, "true")
        config.set(config.FAILOVER_CHECKPOINT_EVERY, "1")
        config.set(config.FAILOVER_PROBE_FLUSHES, "1")
        config.set(config.FAILOVER_RETRY_MS, "100000")
        from sentinel_tpu.core import api

        eng = api.reset(clock=manual_clock)
        st.flow_rule_manager.load_rules(
            [st.FlowRule("dt", grade=C.FLOW_GRADE_THREAD, count=2)]
        )
        inj = _inject(eng)
        manual_clock.set_ms(1000)
        st.entry("dt").exit()
        eng.flush()
        eng.drain()  # settle + checkpoint while HEALTHY
        mirror = eng.speculative.mirror
        assert mirror.snapshot()["live_threads"].get("dt", 0) == 0
        inj.fail_fetch(eng.flush_seq + 1)
        st.entry("dt").exit()  # speculative; rides the faulty flush
        eng.flush()
        assert eng.failover.state == "DEGRADED"
        e = st.entry("dt", prio=True)  # tier declines prio -> degraded fill
        assert e.verdict is not None
        assert e.verdict.degraded and not e.verdict.speculative
        assert mirror.snapshot()["live_threads"].get("dt", 0) == 1
        e.exit()
        assert mirror.snapshot()["live_threads"].get("dt", 0) == 0, (
            "degraded-fill admit must release the mirror THREAD counter"
        )

    def test_rule_reload_retires_mirrors(self):
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([st.FlowRule("r", count=3)])
        clock.set_ms(1000)
        vs = [eng.entry_sync("r")[1].admitted for _ in range(4)]
        assert vs == [True, True, True, False]
        # Reload (same thresholds): device dyn state AND mirror buckets
        # both restart — fresh full window on both planes.
        eng.set_flow_rules([st.FlowRule("r", count=3)])
        vs2 = [eng.entry_sync("r")[1].admitted for _ in range(4)]
        assert vs2 == [True, True, True, False]


class TestProvenance:
    def test_trace_records_speculative_to_settled(self):
        config.set(config.TRACE_SAMPLE_RATE, "1.0")
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([st.FlowRule("p", count=100)])
        clock.set_ms(1000)
        for _ in range(3):
            eng.entry_sync("p")
        eng.flush()
        eng.drain()
        recs = eng.admission_trace.records(resource="p")
        assert recs, "sampled records expected"
        for r in recs:
            assert r.provenance == "speculative"
            assert r.settled_match is True
            assert r.flush_seq != -1 or not eng.telemetry.enabled
            assert r.admitted

    def test_degraded_fill_keeps_speculative_verdicts(self):
        """Ops speculatively decided just before a trip quarantine with
        their verdicts intact — never re-admitted (no double charge),
        provenance preserved."""
        config.set(config.TRACE_SAMPLE_RATE, "1.0")
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True, failover=True)
        eng.set_flow_rules([st.FlowRule("q", count=4)])
        inj = _inject(eng)
        clock.set_ms(1000)
        _, v0 = eng.entry_sync("q")
        assert v0.admitted and v0.speculative
        eng.flush()  # settles the first entry cleanly (+ checkpoint)
        eng.drain()
        inj.fail_fetch(eng.flush_seq + 1)
        vs = [eng.entry_sync("q")[1] for _ in range(4)]
        # The bucket had 3 tokens left after the first (settled) entry.
        assert [v.admitted for v in vs] == [True, True, True, False]
        eng.flush()  # faults -> quarantine; verdicts must not change
        assert eng.failover.state == "DEGRADED"
        c = eng.speculative.counters
        # 4 speculative verdicts + the pre-trip one; none re-admitted
        # by the degraded fill (spec_admits counts the submit-time
        # decisions only).
        assert c["spec_admits"] == 4 and c["spec_blocks"] == 1
        recs = [
            r for r in eng.admission_trace.records(resource="q")
            if r.provenance == "speculative"
        ]
        assert len(recs) == 5
        # Quarantined records never settled: settlement match unknown.
        assert any(r.settled_match is None for r in recs)
        # Provenance reports SERVE-time health: every one of these
        # verdicts was served while HEALTHY, even though the quarantine
        # fill recorded them while DEGRADED.
        assert all(not r.degraded for r in recs)

    def test_telemetry_and_prometheus_export(self):
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([st.FlowRule("m", count=2)])
        clock.set_ms(1000)
        for _ in range(4):
            eng.entry_sync("m")
        eng.flush()
        eng.drain()
        tc = eng.telemetry.counters_snapshot()
        assert tc["spec_admits"] == 2 and tc["spec_blocks"] == 2
        from sentinel_tpu.transport.prometheus import engine_telemetry_lines

        text = "\n".join(engine_telemetry_lines(eng))
        assert "sentinel_engine_speculative_admits_total 2" in text
        assert "sentinel_engine_speculative_enabled 1" in text
        assert "sentinel_engine_speculative_drift_per_window" in text
        snap = eng.speculative.snapshot()
        assert snap["mirror"]["qps_buckets"] == 1


class TestDisabledParity:
    def test_disabled_tier_changes_nothing(self):
        """The integration is a no-op when the tier is off: verdicts
        bit-match an engine predating it (depth 0 and 2)."""
        clock = ManualClock(start_ms=0)
        engines = [
            _mk_engine(clock, spec=False, depth=0),
            _mk_engine(clock, spec=False, depth=2),
        ]
        rng = np.random.default_rng(5)
        for eng in engines:
            eng.set_flow_rules([st.FlowRule("d", count=6)])
        seqs = [[] for _ in engines]
        t = 1000
        for _ in range(4):
            clock.set_ms(t)
            ts = t + np.sort(rng.integers(0, 50, 10)).astype(np.int64)
            for i, eng in enumerate(engines):
                ops = [eng.submit_entry("d", ts=int(x)) for x in ts]
                eng.flush()
                seqs[i].append(
                    [(op.verdict.admitted, op.verdict.reason,
                      op.verdict.speculative) for op in ops]
                )
            t += 300
        for eng in engines:
            eng.drain()
        assert seqs[0] == seqs[1]
        assert all(not v[2] for r in seqs[0] for v in r)
