"""Engine ingest self-protection (runtime/ingest.py) — the shed valve.

The acceptance saturation test: with settlement stalled, pending
queues stay within the configured bound, callers receive fast distinct
BLOCK_SHED verdicts (never indefinite blocking, never unbounded queue
growth), and after recovery everything drains with thread gauges
exactly 0. Plus provenance coverage: trace records, block-log rows,
telemetry/Prometheus counters.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import errors as E
from sentinel_tpu.models import constants as C
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import config


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _mk_engine(clock, spec=False, max_pending=0, max_pending_bulk=0,
               deadline_ms=0):
    from sentinel_tpu.runtime.engine import Engine

    config.set(config.SPECULATIVE_ENABLED, "true" if spec else "false")
    config.set(config.SPECULATIVE_FLUSH_BATCH, "100000")
    config.set(config.INGEST_MAX_PENDING, str(max_pending))
    config.set(config.INGEST_MAX_PENDING_BULK, str(max_pending_bulk))
    config.set(config.INGEST_DEADLINE_MS, str(deadline_ms))
    return Engine(clock=clock)


class TestQueueBound:
    def test_saturation_sheds_and_recovers_with_zero_gauges(self):
        """The acceptance test: settlement stalled (nothing flushes),
        the entry queue saturates at the bound, every further caller
        gets BLOCK_SHED immediately, and after the stall lifts the
        backlog settles + exits drain both gauges to exactly 0."""
        bound = 16
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True, max_pending=bound)
        eng.set_flow_rules(
            [st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=100)]
        )
        clock.set_ms(1000)
        live, shed = [], 0
        for _ in range(100):
            op, v = eng.entry_sync("t")
            assert v is not None
            if v.reason == E.BLOCK_SHED:
                shed += 1
                assert not v.admitted
            elif v.admitted:
                live.append(op)
            # The hard bound: the pending queue never exceeds it.
            assert len(eng._entries) <= bound
        assert shed == 100 - bound, shed
        assert len(live) == bound
        assert eng.ingest.counters["shed_entries"] == shed
        assert eng.ingest.counters["shed_queue"] == shed
        # Stall lifts: settle the backlog, exit every live caller.
        eng.flush()
        eng.drain()
        for op in live:
            eng.submit_exit(op.rows, rt=1, resource="t", speculative=True)
        eng.flush()
        eng.drain()
        stats = eng.cluster_node_stats("t")
        assert stats["cur_thread_num"] == 0, "device gauge must be 0"
        mirror = eng.speculative.mirror.snapshot()["live_threads"]
        assert mirror.get("t", 0) == 0, "mirror gauge must be 0"
        # Queue drained: admission resumes without shedding.
        _, v = eng.entry_sync("t")
        assert v.reason != E.BLOCK_SHED and v.admitted
        eng.flush()
        eng.drain()

    def test_bulk_bound(self):
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, max_pending_bulk=64)
        eng.set_flow_rules([st.FlowRule("b", count=1e9)])
        clock.set_ms(1000)
        g1 = eng.submit_bulk("b", 48)
        assert g1 is not None
        # 48 + 32 > 64: the group sheds whole (dense arrays, no queue).
        g2 = eng.submit_bulk("b", 32)
        assert (g2.reason == E.BLOCK_SHED).all()
        assert g2.admitted_count == 0
        assert eng.ingest.counters["shed_rows"] == 32
        eng.flush()
        eng.drain()
        assert g1.admitted_count == 48
        # Drained: the next group admits.
        g3 = eng.submit_bulk("b", 32)
        assert (g3.reason != E.BLOCK_SHED).all() if g3.reason is not None else True
        eng.flush()
        eng.drain()
        assert g3.admitted_count == 32

    def test_exits_are_never_shed(self):
        """Completions must drain even under a saturated entry queue —
        shedding them would leak the thread gauge forever."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True, max_pending=2)
        eng.set_flow_rules(
            [st.FlowRule("x", grade=C.FLOW_GRADE_THREAD, count=10)]
        )
        clock.set_ms(1000)
        ops = []
        for _ in range(4):
            op, v = eng.entry_sync("x")
            if v.admitted and v.reason != E.BLOCK_SHED:
                ops.append(op)
        assert len(ops) == 2
        for op in ops:
            eng.submit_exit(op.rows, rt=1, resource="x", speculative=True)
        assert len(eng._exits) == 2, "exits must enqueue regardless"
        eng.flush()
        eng.drain()
        assert eng.cluster_node_stats("x")["cur_thread_num"] == 0

    def test_submit_many_sheds_only_the_overflow(self):
        """A batch on an idle engine admits up to the bound and sheds
        exactly the overflow — the per-op path would behave the same,
        so batch submission must not over-shed (flush-on-size drains
        the queue mid-batch; only live depth matters)."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, max_pending=4)
        eng.set_flow_rules([st.FlowRule("m", count=1e9)])
        clock.set_ms(1000)
        ops = eng.submit_many([{"resource": "m"} for _ in range(8)])
        shed = [op for op in ops
                if op._verdict is not None
                and op._verdict.reason == E.BLOCK_SHED]
        assert len(shed) == 4 and len(eng._entries) == 4
        eng.flush()
        eng.drain()
        assert all(
            op.verdict is not None and op.verdict.admitted
            for op in ops if op not in shed
        )
        # Saturated queue: the whole batch sheds immediately.
        for _ in range(4):
            eng.submit_entry("m")
        ops2 = eng.submit_many([{"resource": "m"} for _ in range(3)])
        assert all(
            op._verdict is not None
            and op._verdict.reason == E.BLOCK_SHED
            for op in ops2
        )
        eng.flush()
        eng.drain()


class TestDeadline:
    def test_deadline_shed_and_recovery(self):
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, deadline_ms=50)
        eng.set_flow_rules([st.FlowRule("d", count=1e9)])
        clock.set_ms(1000)
        eng.ingest.force_latency_ms(200.0)
        op, v = eng.entry_sync("d")
        assert v.reason == E.BLOCK_SHED and v.limit_type == "deadline"
        assert eng.ingest.counters["shed_deadline"] == 1
        eng.ingest.force_latency_ms(None)
        _, v2 = eng.entry_sync("d")
        assert v2.reason != E.BLOCK_SHED and v2.admitted
        eng.flush()
        eng.drain()

    def test_settle_latency_feeds_the_ewma(self):
        """Real flushes feed the estimate — the valve reads the PR-3
        flight-recorder signal, not a synthetic knob."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, deadline_ms=100000)  # armed, huge
        eng.set_flow_rules([st.FlowRule("e", count=1e9)])
        clock.set_ms(1000)
        for _ in range(4):
            eng.submit_entry("e")
        eng.flush()
        eng.drain()
        assert eng.ingest.snapshot()["settle_ewma_ms"] > 0.0


class TestProvenance:
    def test_trace_and_blocklog_and_prometheus(self):
        config.set(config.TRACE_SAMPLE_RATE, "1.0")
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, max_pending=1)
        eng.set_flow_rules([st.FlowRule("p", count=1e9)])
        clock.set_ms(1000)
        eng.submit_entry("p")      # fills the queue
        op, v = eng.entry_sync("p")  # shed
        assert v.reason == E.BLOCK_SHED
        recs = [
            r for r in eng.admission_trace.records(resource="p")
            if r.provenance == "shed"
        ]
        assert recs and not recs[0].admitted
        assert recs[0].reason_name == "IngestShedException"
        eng.block_log.flush()
        names = {k[1] for _, k, _ in eng.block_log.read_entries()}
        assert "IngestShedException" in names
        assert eng.telemetry.counters_snapshot()["ingest_shed"] == 1
        from sentinel_tpu.transport.prometheus import engine_telemetry_lines

        text = "\n".join(engine_telemetry_lines(eng))
        assert "sentinel_engine_ingest_shed_total 1" in text
        assert "sentinel_engine_ingest_armed 1" in text
        eng.flush()
        eng.drain()

    def test_api_entry_raises_ingest_shed_error(self, manual_clock):
        config.set(config.INGEST_MAX_PENDING, "1")
        from sentinel_tpu.core import api

        eng = api.reset(clock=manual_clock)
        st.flow_rule_manager.load_rules([st.FlowRule("api", count=1e9)])
        manual_clock.set_ms(1000)
        eng.submit_entry("api")  # fills the queue
        with pytest.raises(E.IngestShedError):
            st.entry("api")
        eng.flush()
        eng.drain()

    def test_disarmed_is_free_and_unchanged(self):
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock)
        assert not eng.ingest.armed
        eng.set_flow_rules([st.FlowRule("z", count=5)])
        clock.set_ms(1000)
        vs = [eng.entry_sync("z")[1].admitted for _ in range(7)]
        assert vs == [True] * 5 + [False] * 2
        assert eng.ingest.counters["shed_entries"] == 0
