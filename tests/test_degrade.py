"""Circuit breaker tests — mirroring the reference's
ExceptionCircuitBreakerTest / ResponseTimeCircuitBreakerTest semantics
under the fake clock, plus randomized oracle parity."""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.testing.oracle import OracleCircuitBreaker


def exc_ratio_rule(resource, ratio=0.5, tw=5, min_req=5):
    return st.DegradeRule(
        resource,
        grade=C.DEGRADE_GRADE_EXCEPTION_RATIO,
        count=ratio,
        time_window=tw,
        min_request_amount=min_req,
    )


def run_one(clock, resource, rt=0, error=False):
    """One entry/exit cycle; returns admitted?"""
    e = st.try_entry(resource)
    if e is None:
        return False
    if rt:
        clock.advance(rt)
    if error:
        e.set_error(RuntimeError("biz"))
    e.exit()
    return True


class TestExceptionBreaker:
    def test_opens_on_error_ratio(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("svc", 0.5, tw=5)])
        # 5 requests, 4 errors -> ratio 0.8 > 0.5 after min_request reached.
        for i in range(5):
            manual_clock.set_ms(i * 10)
            assert run_one(manual_clock, "svc", error=(i > 0))
        # breaker now OPEN
        manual_clock.set_ms(100)
        assert st.try_entry("svc") is None

    def test_min_request_amount_gate(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("g", 0.1, min_req=10)])
        for i in range(9):
            manual_clock.set_ms(i)
            assert run_one(manual_clock, "g", error=True)  # all errors, below min
        manual_clock.set_ms(20)
        assert st.try_entry("g") is not None  # still CLOSED (9 < 10)

    def test_half_open_probe_recovers(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("rec", 0.4, tw=2)])
        for i in range(5):
            manual_clock.set_ms(i)
            run_one(manual_clock, "rec", error=True)
        manual_clock.set_ms(100)
        assert st.try_entry("rec") is None  # OPEN
        # After the 2s recovery window: one probe allowed.
        manual_clock.set_ms(2010)
        e = st.try_entry("rec")
        assert e is not None
        # Concurrent second request while HALF_OPEN: blocked.
        assert st.try_entry("rec") is None
        e.exit()  # success -> CLOSED
        manual_clock.set_ms(2050)
        assert run_one(manual_clock, "rec")

    def test_half_open_probe_failure_reopens(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("bad", 0.4, tw=1)])
        for i in range(5):
            manual_clock.set_ms(i)
            run_one(manual_clock, "bad", error=True)
        manual_clock.set_ms(1100)
        e = st.try_entry("bad")
        assert e is not None
        e.set_error(RuntimeError("still failing"))
        e.exit()  # probe failed -> OPEN again
        manual_clock.set_ms(1200)
        assert st.try_entry("bad") is None
        # next retry only after another full time window
        manual_clock.set_ms(2150)
        assert st.try_entry("bad") is not None

    def test_exception_count_grade(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules(
            [
                st.DegradeRule(
                    "cnt",
                    grade=C.DEGRADE_GRADE_EXCEPTION_COUNT,
                    count=3,
                    time_window=5,
                    min_request_amount=1,
                )
            ]
        )
        for i in range(4):
            manual_clock.set_ms(i)
            assert run_one(manual_clock, "cnt", error=True)
        # 4 errors > 3 -> OPEN
        assert st.try_entry("cnt") is None


class TestResponseTimeBreaker:
    def test_opens_on_slow_ratio(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules(
            [
                st.DegradeRule(
                    "slow",
                    grade=C.DEGRADE_GRADE_RT,
                    count=50,  # max RT 50ms
                    slow_ratio_threshold=0.6,
                    time_window=3,
                    min_request_amount=3,
                )
            ]
        )
        # All-slow completions (100ms > 50ms): the breaker opens as soon
        # as min_request_amount=3 completions are in the window with
        # ratio 1.0 > 0.6 — so requests 1-3 pass, request 4 is blocked.
        for i in range(3):
            manual_clock.set_ms(i * 200)
            assert run_one(manual_clock, "slow", rt=100)
        manual_clock.set_ms(600)
        assert st.try_entry("slow") is None

    def test_fast_requests_keep_closed(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules(
            [
                st.DegradeRule(
                    "fast",
                    grade=C.DEGRADE_GRADE_RT,
                    count=50,
                    slow_ratio_threshold=0.5,
                    time_window=3,
                    min_request_amount=3,
                )
            ]
        )
        for i in range(10):
            manual_clock.set_ms(i * 20)
            assert run_one(manual_clock, "fast", rt=5)


class TestOracleParity:
    @pytest.mark.parametrize("grade", [C.DEGRADE_GRADE_RT, C.DEGRADE_GRADE_EXCEPTION_RATIO])
    def test_randomized_stream(self, manual_clock, engine, grade):
        if grade == C.DEGRADE_GRADE_RT:
            rule = st.DegradeRule(
                "r",
                grade=grade,
                count=30,
                slow_ratio_threshold=0.5,
                time_window=2,
                min_request_amount=4,
            )
            ob = OracleCircuitBreaker(0, 30, 2, 4, 0.5)
        else:
            rule = st.DegradeRule(
                "r", grade=grade, count=0.5, time_window=2, min_request_amount=4
            )
            ob = OracleCircuitBreaker(1, 0.5, 2, 4)
        st.degrade_rule_manager.load_rules([rule])
        rng = np.random.default_rng(5)
        t = 0
        for step in range(150):
            t += int(rng.choice([5, 40, 300, 1200], p=[0.4, 0.3, 0.2, 0.1]))
            manual_clock.set_ms(t)
            e = st.try_entry("r")
            want = ob.try_pass(t)
            assert (e is not None) == want, f"step {step} t={t}"
            if e is not None:
                rt = int(rng.choice([5, 80]))
                err = bool(rng.random() < 0.4)
                manual_clock.advance(rt)
                if err:
                    e.set_error(RuntimeError("x"))
                e.exit()
                ob.on_complete(manual_clock.now_ms(), rt=rt, error=err)


class TestStateChangeObservers:
    """EventObserverRegistry + CircuitBreakerStateChangeObserver parity
    (reference: .../circuitbreaker/EventObserverRegistry.java): opt-in
    host-side edge detection over the device state, one event per
    transition, observer failures contained."""

    @pytest.fixture(autouse=True)
    def _clean_registry(self):
        from sentinel_tpu.rules import breaker_events

        breaker_events.clear()
        yield
        breaker_events.clear()

    def test_open_halfopen_closed_cycle_events(self, manual_clock, engine):
        from sentinel_tpu.rules import breaker_events
        from sentinel_tpu.rules.degrade_table import CLOSED, HALF_OPEN, OPEN

        events = []
        breaker_events.add_state_change_observer(
            "t", lambda prev, new, rule, res: events.append((prev, new, res))
        )
        st.degrade_rule_manager.load_rules([exc_ratio_rule("obs", 0.5, tw=2)])
        for i in range(5):
            manual_clock.set_ms(i * 10)
            run_one(manual_clock, "obs", error=(i > 0))
        engine.flush()  # settle the tripping exit
        assert events == [(CLOSED, OPEN, "obs")]

        # Retry window passes -> probe admits (OPEN->HALF_OPEN), its
        # success closes the breaker (HALF_OPEN->CLOSED).
        manual_clock.set_ms(3000)
        assert run_one(manual_clock, "obs", error=False)
        engine.flush()  # settle the recovering exit
        assert events[1][:2] == (OPEN, HALF_OPEN)
        assert events[2][:2] == (HALF_OPEN, CLOSED)
        assert all(res == "obs" for _, _, res in events)

    def test_observer_exception_contained_and_removal(self, manual_clock, engine):
        from sentinel_tpu.rules import breaker_events

        calls = []

        def bad(prev, new, rule, res):
            raise RuntimeError("alert hook down")

        breaker_events.add_state_change_observer("bad", bad)
        breaker_events.add_state_change_observer(
            "good", lambda *a: calls.append(a)
        )
        st.degrade_rule_manager.load_rules([exc_ratio_rule("ox", 0.5, tw=5)])
        for i in range(5):
            manual_clock.set_ms(i * 10)
            assert run_one(manual_clock, "ox", error=(i > 0))
        engine.flush()  # the fill with the raising observer survives
        assert len(calls) == 1  # good observer still notified
        assert breaker_events.remove_state_change_observer("bad") is True
        assert breaker_events.remove_state_change_observer("bad") is False

    def test_rule_reload_resets_mirror_without_events(self, manual_clock, engine):
        from sentinel_tpu.rules import breaker_events

        events = []
        breaker_events.add_state_change_observer(
            "t", lambda *a: events.append(a)
        )
        st.degrade_rule_manager.load_rules([exc_ratio_rule("r1", 0.5, tw=5)])
        for i in range(5):
            manual_clock.set_ms(i * 10)
            run_one(manual_clock, "r1", error=(i > 0))
        engine.flush()
        assert len(events) == 1  # tripped
        # Reload with a CHANGED rule list: fresh breakers (the
        # reference builds new CircuitBreaker objects per load; an
        # IDENTICAL list short-circuits in DynamicSentinelProperty's
        # equals check and is a no-op there as here) — and the mirror
        # resets silently: no phantom OPEN->CLOSED event.
        st.degrade_rule_manager.load_rules([exc_ratio_rule("r1", 0.6, tw=5)])
        manual_clock.set_ms(200)
        assert run_one(manual_clock, "r1", error=False)
        engine.flush()
        assert len(events) == 1


class TestObserverMirrorDiscipline:
    """The mirror's epoch/seq/validity rules: stale deferred fetches
    across reloads never fire; unobserved gaps resync silently."""

    @pytest.fixture(autouse=True)
    def _clean_registry(self):
        from sentinel_tpu.rules import breaker_events

        breaker_events.clear()
        yield
        breaker_events.clear()

    def test_reload_with_inflight_async_fires_no_phantoms(
        self, manual_clock, engine
    ):
        from sentinel_tpu.rules import breaker_events

        events = []
        breaker_events.add_state_change_observer(
            "t", lambda *a: events.append(a)
        )
        st.degrade_rule_manager.load_rules([exc_ratio_rule("ph", 0.5, tw=5)])
        # Trip the breaker with the final exit still IN FLIGHT
        # (flush_async), then reload a same-length rule list before
        # draining: the stale fetch is from the old epoch and must not
        # diff against the rebuilt mirror.
        for i in range(5):
            manual_clock.set_ms(i * 10)
            run_one(manual_clock, "ph", error=(i > 0))
        engine.flush_async()
        st.degrade_rule_manager.load_rules([exc_ratio_rule("ph", 0.6, tw=5)])
        engine.drain()
        manual_clock.set_ms(500)
        assert run_one(manual_clock, "ph", error=False)  # fresh breaker
        engine.flush()
        # The pre-reload trip may or may not have settled before the
        # reload drained it; either way NO event may reference the new
        # epoch's all-CLOSED world incorrectly: allowed outcomes are
        # the genuine old-epoch trip (fired before the reload) or
        # nothing — never an OPEN->CLOSED phantom afterwards.
        assert all(e[:2] != (1, 0) for e in events), events

    def test_unobserved_gap_resyncs_silently(self, manual_clock, engine):
        from sentinel_tpu.rules import breaker_events
        from sentinel_tpu.rules.degrade_table import CLOSED, OPEN

        events = []

        def obs(*a):
            events.append(a)

        breaker_events.add_state_change_observer("t", obs)
        st.degrade_rule_manager.load_rules([exc_ratio_rule("gap", 0.5, tw=2)])
        for i in range(5):
            manual_clock.set_ms(i * 10)
            run_one(manual_clock, "gap", error=(i > 0))
        engine.flush()
        assert [e[:2] for e in events] == [(CLOSED, OPEN)]
        # Observer leaves; the breaker recovers during the gap.
        breaker_events.remove_state_change_observer("t")
        manual_clock.set_ms(3000)
        assert run_one(manual_clock, "gap", error=False)
        engine.flush()  # OPEN->HALF_OPEN->CLOSED, unobserved
        # Observer returns: the next flush resyncs the mirror without
        # replaying the missed transitions at the wrong time.
        breaker_events.add_state_change_observer("t", obs)
        manual_clock.set_ms(3500)
        assert run_one(manual_clock, "gap", error=False)
        engine.flush()
        assert [e[:2] for e in events] == [(CLOSED, OPEN)]  # nothing new
