"""Circuit breaker tests — mirroring the reference's
ExceptionCircuitBreakerTest / ResponseTimeCircuitBreakerTest semantics
under the fake clock, plus randomized oracle parity."""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.testing.oracle import OracleCircuitBreaker


def exc_ratio_rule(resource, ratio=0.5, tw=5, min_req=5):
    return st.DegradeRule(
        resource,
        grade=C.DEGRADE_GRADE_EXCEPTION_RATIO,
        count=ratio,
        time_window=tw,
        min_request_amount=min_req,
    )


def run_one(clock, resource, rt=0, error=False):
    """One entry/exit cycle; returns admitted?"""
    e = st.try_entry(resource)
    if e is None:
        return False
    if rt:
        clock.advance(rt)
    if error:
        e.set_error(RuntimeError("biz"))
    e.exit()
    return True


class TestExceptionBreaker:
    def test_opens_on_error_ratio(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("svc", 0.5, tw=5)])
        # 5 requests, 4 errors -> ratio 0.8 > 0.5 after min_request reached.
        for i in range(5):
            manual_clock.set_ms(i * 10)
            assert run_one(manual_clock, "svc", error=(i > 0))
        # breaker now OPEN
        manual_clock.set_ms(100)
        assert st.try_entry("svc") is None

    def test_min_request_amount_gate(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("g", 0.1, min_req=10)])
        for i in range(9):
            manual_clock.set_ms(i)
            assert run_one(manual_clock, "g", error=True)  # all errors, below min
        manual_clock.set_ms(20)
        assert st.try_entry("g") is not None  # still CLOSED (9 < 10)

    def test_half_open_probe_recovers(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("rec", 0.4, tw=2)])
        for i in range(5):
            manual_clock.set_ms(i)
            run_one(manual_clock, "rec", error=True)
        manual_clock.set_ms(100)
        assert st.try_entry("rec") is None  # OPEN
        # After the 2s recovery window: one probe allowed.
        manual_clock.set_ms(2010)
        e = st.try_entry("rec")
        assert e is not None
        # Concurrent second request while HALF_OPEN: blocked.
        assert st.try_entry("rec") is None
        e.exit()  # success -> CLOSED
        manual_clock.set_ms(2050)
        assert run_one(manual_clock, "rec")

    def test_half_open_probe_failure_reopens(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("bad", 0.4, tw=1)])
        for i in range(5):
            manual_clock.set_ms(i)
            run_one(manual_clock, "bad", error=True)
        manual_clock.set_ms(1100)
        e = st.try_entry("bad")
        assert e is not None
        e.set_error(RuntimeError("still failing"))
        e.exit()  # probe failed -> OPEN again
        manual_clock.set_ms(1200)
        assert st.try_entry("bad") is None
        # next retry only after another full time window
        manual_clock.set_ms(2150)
        assert st.try_entry("bad") is not None

    def test_exception_count_grade(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules(
            [
                st.DegradeRule(
                    "cnt",
                    grade=C.DEGRADE_GRADE_EXCEPTION_COUNT,
                    count=3,
                    time_window=5,
                    min_request_amount=1,
                )
            ]
        )
        for i in range(4):
            manual_clock.set_ms(i)
            assert run_one(manual_clock, "cnt", error=True)
        # 4 errors > 3 -> OPEN
        assert st.try_entry("cnt") is None


class TestResponseTimeBreaker:
    def test_opens_on_slow_ratio(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules(
            [
                st.DegradeRule(
                    "slow",
                    grade=C.DEGRADE_GRADE_RT,
                    count=50,  # max RT 50ms
                    slow_ratio_threshold=0.6,
                    time_window=3,
                    min_request_amount=3,
                )
            ]
        )
        # All-slow completions (100ms > 50ms): the breaker opens as soon
        # as min_request_amount=3 completions are in the window with
        # ratio 1.0 > 0.6 — so requests 1-3 pass, request 4 is blocked.
        for i in range(3):
            manual_clock.set_ms(i * 200)
            assert run_one(manual_clock, "slow", rt=100)
        manual_clock.set_ms(600)
        assert st.try_entry("slow") is None

    def test_fast_requests_keep_closed(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules(
            [
                st.DegradeRule(
                    "fast",
                    grade=C.DEGRADE_GRADE_RT,
                    count=50,
                    slow_ratio_threshold=0.5,
                    time_window=3,
                    min_request_amount=3,
                )
            ]
        )
        for i in range(10):
            manual_clock.set_ms(i * 20)
            assert run_one(manual_clock, "fast", rt=5)


class TestOracleParity:
    @pytest.mark.parametrize("grade", [C.DEGRADE_GRADE_RT, C.DEGRADE_GRADE_EXCEPTION_RATIO])
    def test_randomized_stream(self, manual_clock, engine, grade):
        if grade == C.DEGRADE_GRADE_RT:
            rule = st.DegradeRule(
                "r",
                grade=grade,
                count=30,
                slow_ratio_threshold=0.5,
                time_window=2,
                min_request_amount=4,
            )
            ob = OracleCircuitBreaker(0, 30, 2, 4, 0.5)
        else:
            rule = st.DegradeRule(
                "r", grade=grade, count=0.5, time_window=2, min_request_amount=4
            )
            ob = OracleCircuitBreaker(1, 0.5, 2, 4)
        st.degrade_rule_manager.load_rules([rule])
        rng = np.random.default_rng(5)
        t = 0
        for step in range(150):
            t += int(rng.choice([5, 40, 300, 1200], p=[0.4, 0.3, 0.2, 0.1]))
            manual_clock.set_ms(t)
            e = st.try_entry("r")
            want = ob.try_pass(t)
            assert (e is not None) == want, f"step {step} t={t}"
            if e is not None:
                rt = int(rng.choice([5, 80]))
                err = bool(rng.random() < 0.4)
                manual_clock.advance(rt)
                if err:
                    e.set_error(RuntimeError("x"))
                e.exit()
                ob.on_complete(manual_clock.now_ms(), rt=rt, error=err)
