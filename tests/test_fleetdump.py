"""tools/fleetdump.py: merging per-process span journals into one
Perfetto trace — the golden-merge regression for the fleet timeline.

Fixture journals (hand-built, no processes) pin the exact merge
contract: one Perfetto process per journal with ``sentinel-<role>``
naming, one thread per span category, µs timestamp math including the
per-journal ruler-offset shift, admission flow arrows matched on
(wid, seq ∈ [seq_lo, seq_hi]) with the traceparent hex as flow id
when present, rpc arrows matched on (port, xid), and the ``f`` anchor
clamped forward so residual skew can never make Perfetto drop the
arrow. The spawned-fleet demo itself runs in ci_check 2d."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import fleetdump  # noqa: E402

from sentinel_tpu.metrics.spans import SpanJournal  # noqa: E402


def _worker_journal(off_ms=0.0):
    spans = [
        {"name": "admit", "cat": "worker", "t0": 1000.0, "dur": 2.0,
         "wid": 0, "seq": 5, "push_ms": 0.2, "v": 1001.8, "win": 0,
         "adm": 1, "trace": "0123456789abcdef0123456789abcdef"},
        {"name": "admit", "cat": "worker", "t0": 1004.0, "dur": 1.0,
         "wid": 0, "seq": 6, "push_ms": 0.1, "v": 1004.9, "win": 0,
         "adm": 1},
        {"name": "admit.bulk", "cat": "worker", "t0": 1010.0, "dur": 1.5,
         "wid": 0, "seq": 7, "rows": 4, "v": 1011.4},
    ]
    meta = {"meta": 1, "role": "worker", "pid": 42, "app": "test-app"}
    if off_ms:
        meta["ruler_off_ms"] = off_ms
    return {"meta": meta, "spans": spans}


def _engine_journal():
    return {
        "meta": {"meta": 1, "role": "engine", "pid": 43, "app": "test-app"},
        "spans": [
            {"name": "drain", "cat": "engine", "t0": 1001.0, "dur": 0.5,
             "frames": 2, "rows": 6},
            {"name": "frame", "cat": "engine", "t0": 1001.0, "dur": 0.5,
             "wid": 0, "seq_lo": 5, "seq_hi": 6, "rows": 2},
            {"name": "frame", "cat": "engine", "t0": 1010.5, "dur": 0.3,
             "wid": 0, "seq_lo": 7, "seq_hi": 7, "rows": 4},
            # The engine process also hosts the cluster client leg:
            {"name": "rpc", "cat": "client", "t0": 1002.0, "dur": 1.2,
             "xid": 9, "port": 7070, "rows": 4},
            {"name": "rpc", "cat": "client", "t0": 1003.5, "dur": 1.0,
             "xid": 10, "port": 7070, "rows": 4},
        ],
    }


def _shard_journal():
    return {
        "meta": {"meta": 1, "role": "shard", "pid": 44, "app": "test-app"},
        "spans": [
            {"name": "serve", "cat": "shard", "t0": 1002.4, "dur": 0.6,
             "xid": 9, "mt": 4, "rows": 4, "port": 7070},
            # xid 11 was never sent by the client above -> no arrow.
            {"name": "serve", "cat": "shard", "t0": 1009.0, "dur": 0.2,
             "xid": 11, "mt": 4, "rows": 1, "port": 7070},
        ],
    }


def _merge(*journals):
    return fleetdump.merge_journals(list(journals))["traceEvents"]


def _by(evs, **kv):
    return [e for e in evs
            if all(e.get(k) == v for k, v in kv.items())]


class TestMergeJournals:
    def test_process_and_thread_metadata(self):
        evs = _merge(_worker_journal(), _engine_journal(), _shard_journal())
        names = {(e["pid"], e["args"]["name"])
                 for e in _by(evs, ph="M", name="process_name")}
        assert names == {(42, "sentinel-worker"), (43, "sentinel-engine"),
                         (44, "sentinel-shard")}
        threads = {(e["pid"], e["tid"], e["args"]["name"])
                   for e in _by(evs, ph="M", name="thread_name")}
        # One track per category; the engine process hosts TWO (its
        # own drain/frame track plus the cluster-client leg).
        assert (42, 1, "worker") in threads
        assert (43, 2, "engine") in threads and (43, 3, "client") in threads
        assert (44, 4, "shard") in threads

    def test_slice_timestamp_math_and_ruler_shift(self):
        # 7.5ms of observed skew: every worker slice lands 7500µs
        # earlier on the merged (ruler) timeline.
        evs = _merge(_worker_journal(off_ms=7.5))
        sl = _by(evs, ph="X", name="admit")
        assert [e["ts"] for e in sl] == [992500, 996500]
        assert [e["dur"] for e in sl] == [2000, 1000]
        # Span payload fields ride into args (minus the slice keys).
        assert sl[0]["args"]["seq"] == 5 and sl[0]["args"]["adm"] == 1
        assert "t0" not in sl[0]["args"]

    def test_zero_duration_clamps_to_one_us(self):
        j = {"meta": {"meta": 1, "role": "w", "pid": 9},
             "spans": [{"name": "x", "cat": "worker", "t0": 1.0,
                        "dur": 0.0}]}
        (sl,) = _by(_merge(j), ph="X")
        assert sl["dur"] == 1

    def test_admission_arrows_span_worker_to_engine(self):
        evs = _merge(_worker_journal(), _engine_journal())
        starts = _by(evs, ph="s", name="admission")
        finishes = _by(evs, ph="f", name="admission")
        assert len(starts) == len(finishes) == 3
        # Traced admission uses the traceparent hex as flow id; the
        # untraced ones fall back to the (wid, seq) synthetic id.
        ids = {e["id"] for e in starts}
        assert ids == {"0123456789abcdef0123456789abcdef",
                       "adm-0-6", "adm-0-7"}
        for s in starts:
            assert s["pid"] == 42
        for f in finishes:
            assert f["pid"] == 43 and f["bp"] == "e"
        # seq 7 rode the admit.bulk span into the second frame.
        (bulk_f,) = [e for e in finishes if e["id"] == "adm-0-7"]
        assert bulk_f["ts"] == 1010500

    def test_finish_anchor_clamped_forward(self):
        # Residual skew put the frame's dequeue stamp BEFORE the
        # worker's join: the f anchor clamps to the s timestamp so
        # Perfetto keeps the arrow.
        w = {"meta": {"meta": 1, "role": "worker", "pid": 1},
             "spans": [{"name": "admit", "cat": "worker", "t0": 1000.0,
                        "dur": 1.0, "wid": 0, "seq": 1}]}
        e = {"meta": {"meta": 1, "role": "engine", "pid": 2},
             "spans": [{"name": "frame", "cat": "engine", "t0": 999.0,
                        "dur": 0.5, "wid": 0, "seq_lo": 1,
                        "seq_hi": 1}]}
        evs = _merge(w, e)
        (s,) = _by(evs, ph="s")
        (f,) = _by(evs, ph="f")
        assert s["ts"] == 1000000 and f["ts"] == 1000000  # clamped

    def test_no_arrow_without_matching_frame(self):
        w = _worker_journal()
        e = _engine_journal()
        e["spans"] = [sp for sp in e["spans"] if sp["name"] != "frame"]
        evs = _merge(w, e)
        assert _by(evs, ph="s", name="admission") == []

    def test_rpc_arrows_match_on_port_and_xid(self):
        evs = _merge(_engine_journal(), _shard_journal())
        starts = _by(evs, ph="s", name="rpc")
        # xid 9 matches; xid 10 has no serve, shard xid 11 no rpc.
        assert [e["id"] for e in starts] == ["rpc-7070-9"]
        (f,) = _by(evs, ph="f", name="rpc")
        assert f["pid"] == 44 and f["ts"] == 1002400

    def test_rpc_port_disambiguates(self):
        e = _engine_journal()
        shard = _shard_journal()
        for sp in shard["spans"]:
            sp["port"] = 7071  # same xids, different shard
        evs = _merge(e, shard)
        assert _by(evs, ph="s", name="rpc") == []


class TestMergeFiles:
    def test_spill_then_merge_roundtrip(self, tmp_path):
        spj = SpanJournal(role="worker", enabled=True, ring=64,
                          spill_every=0, base_dir=str(tmp_path))
        spj.record("admit", "worker", 100.0, 1.0, wid=0, seq=1)
        path = spj.spill()
        trace = fleetdump.merge_files([path])
        evs = trace["traceEvents"]
        (proc,) = _by(evs, ph="M", name="process_name")
        assert proc["args"]["name"] == "sentinel-worker"
        assert proc["pid"] == os.getpid()
        (sl,) = _by(evs, ph="X")
        assert sl["name"] == "admit" and sl["dur"] == 1000


class TestSmokeChecks:
    def test_full_fixture_is_green(self):
        # Distinct pids per journal, the way a real run has them.
        js = [_worker_journal(), _worker_journal(), _engine_journal(),
              _shard_journal(), _shard_journal()]
        for i, j in enumerate(js):
            j["meta"]["pid"] = 50 + i
        trace = fleetdump.merge_journals(js)
        assert fleetdump.smoke_checks(trace) == []

    def test_degenerate_traces_report_failures(self):
        fails = fleetdump.smoke_checks({"traceEvents": []})
        assert any("worker" in f for f in fails)
        assert any("shard" in f for f in fails)
        assert any("admission" in f for f in fails)
        # Worker-only merge: tracks missing + no arrows.
        fails = fleetdump.smoke_checks(
            fleetdump.merge_journals([_worker_journal()])
        )
        assert any("engine" in f for f in fails)
        assert any(">=5 processes" in f for f in fails)
