"""Self-tuning control plane (runtime/autotune.py) — contracts.

* The decision functions are PURE functions of a sampled snapshot:
  synthetic-snapshot unit tests pin every rule in the ARCHITECTURE
  signal->decision table (raise/lower/hold, hysteresis, dead bands).
* Closed-loop convergence is structural: a steady synthetic workload
  settles MONOTONICALLY to a fixed depth and never oscillates.
* Runtime depth changes are SAFE: ``Engine.set_depth`` lowering drains
  the excess in-flight flushes first, and a 2->0->2 mid-stream flip is
  bit-identical to the depth-0 oracle.
* Path-selection accounting: every encoded param batch increments
  exactly one of the ``param_closed_form``/``param_scan`` telemetry
  counters, and a mixed-ts batch past ``PARAM_CLOSED_MAX_SEGMENTS``
  routes to scan (the eligibility rule autotune must never override).
* Autotune OFF (the default) is verdict- and behavior-parity; ON is
  verdict-parity (it may only move schedule knobs, never verdicts).
"""

import json

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ParamFlowRule
from sentinel_tpu.runtime.autotune import (
    PATH_CLOSED,
    PATH_SCAN,
    ParamPathMemo,
    PathStats,
    TuneLimits,
    TuneSnapshot,
    decide_depth,
    decide_window,
    pick_path,
)
from sentinel_tpu.utils.config import config


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


LIM = TuneLimits()  # the documented defaults


def _snap(**kw):
    base = dict(
        now_ms=1000, depth=0, flushes=16, mean_inflight=0.0,
        encode_ms=16.0, dispatch_ms=4.0, settle_ms=0.0, drain_ms=0.0,
        shed=0,
    )
    base.update(kw)
    return TuneSnapshot(**base)


# ----------------------------------------------------------------------
# pure depth decisions
# ----------------------------------------------------------------------
class TestDecideDepth:
    def test_insufficient_samples_holds(self):
        d, reason, _ = decide_depth(_snap(flushes=2, settle_ms=100.0), LIM)
        assert (d, reason) == (0, "insufficient-samples")

    def test_raise_from_zero_on_device_wait(self):
        # Sync settles (device wait) worth hiding -> 0 -> 1.
        d, reason, _ = decide_depth(_snap(settle_ms=10.0), LIM)
        assert (d, reason) == (1, "hide-device-wait")

    def test_no_raise_without_device_wait(self):
        # Pure host-bound at depth 0: nothing to overlap.
        d, reason, _ = decide_depth(_snap(settle_ms=0.5), LIM)
        assert (d, reason) == (0, "steady")

    def test_raise_requires_occupancy_at_depth(self):
        # Unhidden drain wait but a half-empty pipeline: hold.
        s = _snap(depth=1, mean_inflight=0.5, drain_ms=10.0)
        d, reason, _ = decide_depth(s, LIM)
        assert (d, reason) == (1, "steady")
        # Occupied pipeline with the same wait: raise.
        s = _snap(depth=1, mean_inflight=0.95, drain_ms=10.0)
        d, reason, _ = decide_depth(s, LIM)
        assert (d, reason) == (2, "hide-device-wait")

    def test_depth_max_bound(self):
        s = _snap(depth=4, mean_inflight=4.0, drain_ms=10.0)
        d, reason, _ = decide_depth(s, LIM)
        assert (d, reason) == (4, "at-max")

    def test_drain_stall_steps_down(self):
        # Device fell behind by more than stall.frac x host work.
        s = _snap(depth=3, mean_inflight=3.0, drain_ms=100.0)
        d, reason, _ = decide_depth(s, LIM)
        assert (d, reason) == (2, "drain-stall")

    def test_stall_floor_is_depth_one(self):
        # Device-bound at depth 1: stall lowering never de-pipelines
        # completely (any overlap still beats none).
        s = _snap(depth=1, mean_inflight=1.0, drain_ms=100.0)
        d, reason, _ = decide_depth(s, LIM)
        assert d == 1

    def test_shed_pressure_halves(self):
        s = _snap(depth=4, mean_inflight=4.0, shed=5)
        d, reason, _ = decide_depth(s, LIM)
        assert (d, reason) == (2, "ingest-pressure")

    def test_underutilized_needs_consecutive_ticks(self):
        s = _snap(depth=2, mean_inflight=0.1)
        streak = 0
        for i in range(LIM.idle_ticks - 1):
            d, reason, streak = decide_depth(s, LIM, streak)
            assert (d, reason) == (2, "underutilized-wait")
        d, reason, streak = decide_depth(s, LIM, streak)
        assert (d, reason, streak) == (1, "underutilized", 0)

    def test_busy_tick_resets_idle_streak(self):
        s_idle = _snap(depth=2, mean_inflight=0.1)
        _, _, streak = decide_depth(s_idle, LIM, 0)
        assert streak == 1
        s_busy = _snap(depth=2, mean_inflight=1.9)
        _, _, streak = decide_depth(s_busy, LIM, streak)
        assert streak == 0


class TestConvergence:
    """Closed-loop synthetic steady workloads: the depth trajectory is
    monotone to a fixed point and never leaves it — the 'decision log
    shows monotone settle' acceptance, deterministically."""

    @staticmethod
    def _steady(depth, host_ms=1.0, dev_ms=3.0, n=16):
        """Model: per flush the host does host_ms of encode work and
        the device dev_ms of compute; a depth-K pipeline hides K x
        host_ms of it, the rest shows up as drain wait."""
        unhidden = max(0.0, dev_ms - depth * host_ms)
        return _snap(
            depth=depth, flushes=n,
            encode_ms=host_ms * n * 0.8, dispatch_ms=host_ms * n * 0.2,
            settle_ms=unhidden * n if depth == 0 else 0.0,
            drain_ms=unhidden * n if depth > 0 else 0.0,
            mean_inflight=float(depth),  # steady pipeline runs full
        )

    @pytest.mark.parametrize("dev_ms,expect", [(3.0, 3), (0.05, 0), (10.0, 1)])
    def test_monotone_settle_no_oscillation(self, dev_ms, expect):
        # dev=3x host: settles at 3 (wait fully hidden). dev ~ 0:
        # stays at 0. dev >> host (device-bound): settles at 1 — the
        # stall ceiling blocks raises past the first overlap step.
        d, streak = 0, 0
        traj = [d]
        for _ in range(30):
            nd, _reason, streak = decide_depth(self._steady(d, dev_ms=dev_ms), LIM, streak)
            traj.append(nd)
            d = nd
        assert d == expect, traj
        # Monotone: never decreases, and once it repeats it stays.
        assert all(b >= a for a, b in zip(traj, traj[1:])), traj
        fixed = traj.index(d)
        assert all(v == d for v in traj[fixed:]), traj


# ----------------------------------------------------------------------
# pure window decisions
# ----------------------------------------------------------------------
class TestDecideWindow:
    @staticmethod
    def _wsnap(**kw):
        base = dict(
            window_armed=True, window_reqs=400, window_flushes=10,
            window_ms=2.0, window_batch_max=64, window_fanout_ms=1.0,
        )
        base.update(kw)
        return _snap(**base)

    def test_inactive_without_window(self):
        ms, bm, reason = decide_window(self._wsnap(window_armed=False), LIM)
        assert reason == "inactive"

    def test_full_windows_grow_batch_max(self):
        s = self._wsnap(window_reqs=640, window_flushes=10)  # fill 1.0
        ms, bm, reason = decide_window(s, LIM)
        assert (ms, bm, reason) == (2.0, 128, "windows-capping")

    def test_batch_max_capped(self):
        s = self._wsnap(
            window_reqs=40960, window_flushes=10, window_batch_max=4096
        )
        ms, bm, reason = decide_window(s, LIM)
        assert (bm, reason) == (4096, "steady")

    def test_fanout_pressure_shrinks_window(self):
        s = self._wsnap(window_reqs=300, window_fanout_ms=20.0)
        ms, bm, reason = decide_window(s, LIM)
        assert (ms, reason) == (1.0, "fanout-latency")

    def test_window_floor(self):
        lim = TuneLimits(window_ms_min=1.5)
        s = self._wsnap(window_ms=2.0, window_reqs=300, window_fanout_ms=50.0)
        ms, _bm, _ = decide_window(s, lim)
        assert ms == 1.5

    def test_sparse_windows_widen(self):
        s = self._wsnap(window_reqs=100, window_flushes=10,
                        window_fanout_ms=0.5)  # fill 0.16, fan-out cheap
        ms, bm, reason = decide_window(s, LIM)
        assert (ms, reason) == (3.0, "coalesce-more")

    def test_widen_capped_and_dead_band(self):
        lim = TuneLimits(window_ms_max=2.5)
        s = self._wsnap(window_reqs=100, window_flushes=10,
                        window_fanout_ms=0.5)
        ms, _bm, _ = decide_window(s, lim)
        assert ms == 2.5
        # Between the widen bound (fanout <= window) and the shrink
        # bound (fanout > 4x window): hold.
        s = self._wsnap(window_reqs=100, window_fanout_ms=5.0)
        ms, bm, reason = decide_window(s, LIM)
        assert (ms, bm, reason) == (2.0, 64, "steady")


# ----------------------------------------------------------------------
# param-path cost memo
# ----------------------------------------------------------------------
class TestPathMemo:
    def test_explores_then_commits_to_cheaper(self):
        memo = ParamPathMemo(explore=2, margin=0.15)
        b = ParamPathMemo.bucket_of(12, 2)
        assert b == (16, 2)
        picks = []
        for _ in range(4):
            path, _ = memo.pick(b)
            picks.append(path)
            memo.note(b, path, 1.0 if path == PATH_CLOSED else 5.0)
        assert picks == [PATH_CLOSED, PATH_CLOSED, PATH_SCAN, PATH_SCAN]
        # Exploration left `current` on the last explored path (scan);
        # the first cost-based pick switches to the cheaper closed form
        # and every later pick holds there.
        path, reason = memo.pick(b)
        assert (path, reason) == (PATH_CLOSED, "cost-switch")
        path, reason = memo.pick(b)
        assert (path, reason) == (PATH_CLOSED, "cost-hold")

    def test_margin_hysteresis_blocks_marginal_flips(self):
        closed = PathStats(n=5, ewma_ms=1.0)
        scan = PathStats(n=5, ewma_ms=0.95)  # only 5% better
        path, reason = pick_path(closed, scan, PATH_CLOSED, 3, 0.15)
        assert (path, reason) == (PATH_CLOSED, "cost-hold")
        scan_fast = PathStats(n=5, ewma_ms=0.5)  # 50% better: switch
        path, reason = pick_path(closed, scan_fast, PATH_CLOSED, 3, 0.15)
        assert (path, reason) == (PATH_SCAN, "cost-switch")
        # And the switch is sticky the other way round too.
        path, reason = pick_path(closed, scan_fast, PATH_SCAN, 3, 0.15)
        assert (path, reason) == (PATH_SCAN, "cost-hold")

    def test_seed_skips_exploration(self):
        memo = ParamPathMemo(explore=3, margin=0.15)
        b = ParamPathMemo.bucket_of(100, 1)
        memo.seed(b, closed_ms=5.0, scan_ms=1.0)
        path, reason = memo.pick(b)
        assert (path, reason) == (PATH_SCAN, "cost-switch")


# ----------------------------------------------------------------------
# runtime depth safety (Engine.set_depth) — satellite 1
# ----------------------------------------------------------------------
def _mk_engine(clock, depth=0):
    from sentinel_tpu.runtime.engine import Engine

    eng = Engine(clock=clock)
    eng.pipeline_depth = depth
    return eng


def _load_rules(engines):
    for eng in engines:
        eng.set_flow_rules(
            [st.FlowRule("pp", count=6.0), st.FlowRule("qq", count=1e9)]
        )
        eng.set_param_rules(
            {"qq": [ParamFlowRule("qq", param_idx=0, count=3)]}
        )


class TestSetDepthRuntime:
    def test_flip_2_0_2_matches_depth0_oracle(self, manual_clock):
        """Mid-stream depth flips 2->0->2: lowering drains the excess
        in-flight flushes synchronously (the FIFO settle + arena
        contracts), and the whole stream stays bit-identical to the
        always-depth-0 oracle."""
        engines = [_mk_engine(manual_clock, 0), _mk_engine(manual_clock, 2)]
        _load_rules(engines)
        rng = np.random.default_rng(12)
        collected = [[] for _ in engines]
        t = 1000
        for r in range(8):
            manual_clock.set_ms(t)
            n_pp = 16
            ts_pp = np.sort(t + rng.integers(0, 40, n_pp).astype(np.int32))
            acq = rng.integers(1, 3, n_pp).astype(np.int32)
            n_qq = 12
            vals = [f"v{int(rng.integers(0, 3))}" for _ in range(n_qq)]
            ts_qq = np.where(
                np.arange(n_qq) < rng.integers(1, n_qq),
                np.int32(t), np.int32(t + 700),
            ).astype(np.int32)
            for eng, coll in zip(engines, collected):
                g1 = eng.submit_bulk("pp", n_pp, ts=ts_pp, acquire=acq)
                g2 = eng.submit_bulk(
                    "qq", n_qq, ts=ts_qq, args_column=[(v,) for v in vals]
                )
                eng.flush()
                assert len(eng._pending_fetches) <= eng.pipeline_depth
                coll.extend([g1, g2])
            if r == 2:
                engines[1].set_depth(0)
                # The shrink drained every in-flight flush BEFORE the
                # bound moved — nothing outstanding above the new depth.
                assert len(engines[1]._pending_fetches) == 0
                assert engines[1].pipeline_depth == 0
            elif r == 4:
                engines[1].set_depth(2)
                assert engines[1].pipeline_depth == 2
            t += int(rng.integers(100, 900))
        for eng in engines:
            eng.drain()
        for go, gp in zip(collected[0], collected[1]):
            assert gp.admitted.tolist() == go.admitted.tolist()
            assert gp.reason.tolist() == go.reason.tolist()
            assert gp.wait_ms.tolist() == go.wait_ms.tolist()
        for res in ("pp", "qq"):
            assert engines[1].cluster_node_stats(res) == engines[
                0
            ].cluster_node_stats(res), res
        for eng in engines:
            eng.close()

    def test_set_depth_raise_resizes_arena(self, manual_clock):
        eng = _mk_engine(manual_clock, 0)
        eng.set_depth(3)
        assert eng.pipeline_depth == 3
        assert eng._arena.per_key >= 4  # depth + 1
        eng.close()


# ----------------------------------------------------------------------
# path-selection counters — satellite 2
# ----------------------------------------------------------------------
class TestParamPathCounters:
    def _setup(self, engine):
        engine.set_flow_rules([st.FlowRule("mx", count=1e9)])
        engine.set_param_rules(
            {"mx": [ParamFlowRule("mx", param_idx=0, count=3)]}
        )

    def test_past_max_segments_routes_to_scan_and_counts(
        self, manual_clock, engine
    ):
        from sentinel_tpu.rules.param_table import PARAM_CLOSED_MAX_SEGMENTS

        self._setup(engine)
        manual_clock.set_ms(1000)
        n = 12
        assert n > PARAM_CLOSED_MAX_SEGMENTS
        ts = (1000 + np.arange(n) * 100).astype(np.int32)  # 12 distinct ts
        engine.submit_bulk("mx", n, ts=ts, args_column=[("k",)] * n)
        c0 = engine.telemetry.counters_snapshot()
        engine.flush()
        engine.drain()
        c1 = engine.telemetry.counters_snapshot()
        assert c1["param_scan"] == c0["param_scan"] + 1
        assert c1["param_closed_form"] == c0["param_closed_form"]

    def test_uniform_batch_counts_closed_form(self, manual_clock, engine):
        self._setup(engine)
        manual_clock.set_ms(1000)
        engine.submit_bulk(
            "mx", 8, ts=np.full(8, 1000, np.int32),
            args_column=[("k",)] * 8,
        )
        c0 = engine.telemetry.counters_snapshot()
        engine.flush()
        engine.drain()
        c1 = engine.telemetry.counters_snapshot()
        assert c1["param_closed_form"] == c0["param_closed_form"] + 1
        assert c1["param_scan"] == c0["param_scan"]


# ----------------------------------------------------------------------
# controller integration
# ----------------------------------------------------------------------
class TestAutoTunerIntegration:
    def test_disabled_by_default(self, manual_clock):
        eng = _mk_engine(manual_clock)
        assert eng.autotune.enabled is False
        assert eng.autotune.param_active is False
        eng.set_flow_rules([st.FlowRule("d", count=10.0)])
        for _ in range(3):
            eng.submit_entry("d")
            eng.flush()
        eng.drain()
        snap = eng.autotune.snapshot()
        assert snap["counters"]["ticks"] == 0
        assert snap["decisions"] == []
        assert eng.telemetry.counters_snapshot()["autotune_decisions"] == 0
        eng.close()

    def test_enabled_is_verdict_parity(self, manual_clock):
        """Autotune may move schedule knobs (depth, window, path) but
        NEVER a verdict: the same stream through a tuned engine and a
        static one is bit-identical."""
        config.set(config.AUTOTUNE_ENABLED, "false")
        static = _mk_engine(manual_clock, 0)
        config.set(config.AUTOTUNE_ENABLED, "true")
        config.set(config.AUTOTUNE_INTERVAL_MS, "1")
        config.set(config.AUTOTUNE_COOLDOWN_MS, "1")
        config.set(config.AUTOTUNE_MIN_FLUSHES, "1")
        config.set(config.AUTOTUNE_PARAM_EXPLORE, "1")
        tuned = _mk_engine(manual_clock, 0)
        assert tuned.autotune.enabled
        engines = [static, tuned]
        _load_rules(engines)
        rng = np.random.default_rng(7)
        collected = [[] for _ in engines]
        t = 1000
        for _ in range(10):
            manual_clock.set_ms(t)
            n_qq = 12
            vals = [f"v{int(rng.integers(0, 3))}" for _ in range(n_qq)]
            ts_qq = np.where(
                np.arange(n_qq) < rng.integers(1, n_qq),
                np.int32(t), np.int32(t + 700),
            ).astype(np.int32)
            ts_pp = np.sort(t + rng.integers(0, 40, 16).astype(np.int32))
            for eng, coll in zip(engines, collected):
                g1 = eng.submit_bulk("pp", 16, ts=ts_pp)
                g2 = eng.submit_bulk(
                    "qq", n_qq, ts=ts_qq, args_column=[(v,) for v in vals]
                )
                eng.flush()
                coll.extend([g1, g2])
            t += int(rng.integers(100, 900))
        for eng in engines:
            eng.drain()
        assert tuned.autotune.counters["ticks"] > 0
        for go, gp in zip(collected[0], collected[1]):
            assert gp.admitted.tolist() == go.admitted.tolist()
            assert gp.reason.tolist() == go.reason.tolist()
            assert gp.wait_ms.tolist() == go.wait_ms.tolist()
        for res in ("pp", "qq"):
            assert tuned.cluster_node_stats(res) == static.cluster_node_stats(
                res
            ), res
        for eng in engines:
            eng.close()

    def test_apply_depth_moves_engine_and_logs(self, manual_clock):
        config.set(config.AUTOTUNE_ENABLED, "true")
        eng = _mk_engine(manual_clock, 0)
        at = eng.autotune
        snap = _snap(now_ms=5000, depth=0, settle_ms=30.0, encode_ms=10.0)
        at._apply_depth(snap)
        assert eng.pipeline_depth == 1
        dec = list(at.decisions)[-1]
        assert dec["knob"] == "depth" and (dec["from"], dec["to"]) == (0, 1)
        assert dec["reason"] == "hide-device-wait"
        assert eng.telemetry.counters_snapshot()["autotune_decisions"] == 1
        # Cooldown: an immediate second apply holds even though the
        # snapshot still argues for a raise.
        at._apply_depth(_snap(now_ms=5001, depth=1, mean_inflight=1.0,
                              drain_ms=30.0, encode_ms=10.0))
        assert eng.pipeline_depth == 1
        # Past the cooldown it moves again (drain wait inside the
        # stall ceiling, pipeline occupied).
        at._apply_depth(_snap(now_ms=5000 + at.cooldown_ms, depth=1,
                              mean_inflight=1.0, drain_ms=15.0,
                              encode_ms=10.0))
        assert eng.pipeline_depth == 2
        eng.close()

    def test_blind_without_telemetry(self, manual_clock):
        config.set(config.AUTOTUNE_ENABLED, "true")
        config.set(config.TELEMETRY_ENABLED, "false")
        eng = _mk_engine(manual_clock)
        assert eng.autotune.blind is True
        assert eng.autotune.param_active is False
        eng.autotune.maybe_tick(10_000)
        assert eng.autotune.counters["ticks"] == 0
        assert eng.autotune.snapshot()["blind"] is True
        eng.close()

    def test_window_retune_applies(self, manual_clock):
        eng = _mk_engine(manual_clock)
        w = eng.ingest_window
        w.retune(window_ms=4.0, batch_max=512)
        assert (w.window_ms, w.batch_max) == (4.0, 512)
        w.retune(window_ms=0.0)  # refused: arming is config, not tuning
        assert w.window_ms == 4.0
        eng.close()

    def test_autotune_command_and_prometheus(self, manual_clock, engine):
        from sentinel_tpu.transport import handlers
        from sentinel_tpu.transport.command_center import CommandRequest
        from sentinel_tpu.transport.prometheus import render_metrics

        resp = handlers.autotune_handler(
            CommandRequest(path="autotune", params={}, body="")
        )
        assert resp.success
        d = json.loads(resp.result)
        assert d["enabled"] is False
        assert "decisions" in d and "param_memo" in d
        text = render_metrics(engine)
        for fam in (
            "sentinel_engine_autotune_enabled",
            "sentinel_engine_autotune_decisions_total",
            "sentinel_engine_autotune_depth",
            "sentinel_engine_autotune_window_ms",
            "sentinel_engine_autotune_window_batch_max",
            "sentinel_engine_param_closed_form_total",
            "sentinel_engine_param_scan_total",
        ):
            assert fam in text, fam

    def test_tick_does_not_reset_pipeline_stats(self, manual_clock):
        """Regression: the sampler reads pipeline stats via private
        delta baselines — NOT pipeline_stats(reset=True), which would
        turn the exported sentinel_engine_pipeline_dispatches_total
        into a perpetually-resetting counter whenever autotune is on."""
        config.set(config.AUTOTUNE_ENABLED, "true")
        config.set(config.AUTOTUNE_INTERVAL_MS, "1")
        config.set(config.AUTOTUNE_MIN_FLUSHES, "1")
        config.set(config.AUTOTUNE_DEPTH_MAX, "2")
        eng = _mk_engine(manual_clock, 2)
        eng.set_flow_rules([st.FlowRule("ps", count=1e9)])
        t = 1000
        for _ in range(6):
            manual_clock.set_ms(t)
            eng.submit_bulk("ps", 32, ts=np.full(32, t, np.int32))
            eng.flush()
            t += 500
        eng.drain()
        assert eng.autotune.counters["ticks"] > 1
        # The shared accumulator kept every dispatch across all ticks.
        assert eng.pipeline_stats()["dispatches"] >= 6
        eng.close()

    def test_enabled_tick_converges_on_live_engine(self, manual_clock):
        """Live closed loop: a tuned engine driving real flushes takes
        depth decisions off the drain tick and the decision log is a
        monotone settle (no knob ever reverses under the steady
        stream)."""
        config.set(config.AUTOTUNE_ENABLED, "true")
        config.set(config.AUTOTUNE_INTERVAL_MS, "1")
        config.set(config.AUTOTUNE_COOLDOWN_MS, "1")
        config.set(config.AUTOTUNE_MIN_FLUSHES, "2")
        config.set(config.AUTOTUNE_DEPTH_MAX, "2")
        eng = _mk_engine(manual_clock, 0)
        eng.set_flow_rules([st.FlowRule("cv", count=1e9)])
        t = 1000
        for _ in range(30):
            manual_clock.set_ms(t)
            eng.submit_bulk("cv", 64, ts=np.full(64, t, np.int32))
            eng.flush()
            t += 500
        eng.drain()
        depths = [d["to"] for d in eng.autotune.decisions
                  if d["knob"] == "depth"]
        assert eng.autotune.counters["ticks"] > 0
        # Monotone settle: depth never decreases under the steady
        # stream (raises only, bounded by depth.max).
        assert all(b >= a for a, b in zip(depths, depths[1:])), depths
        assert eng.pipeline_depth <= 2
        eng.close()


# ----------------------------------------------------------------------
# param-path seed file (ISSUE 13 satellite)
# ----------------------------------------------------------------------
class TestParamSeedFile:
    """sentinel.tpu.autotune.param.seed.file: k2probe-measured
    closed-vs-scan timings load at engine start, so the memo starts
    COMMITTED instead of exploring."""

    def _seed_file(self, tmp_path, buckets):
        p = tmp_path / "seed.json"
        p.write_text(json.dumps(
            {"format": "sentinel-param-seed-v1", "buckets": buckets}
        ))
        return str(p)

    def test_seeded_memo_starts_committed(self, manual_clock, tmp_path):
        path = self._seed_file(tmp_path, [
            {"rows_bucket": 256, "segments": 1,
             "closed_ms": 1.0, "scan_ms": 5.0},   # closed wins
            {"rows_bucket": 1024, "segments": 2,
             "closed_ms": 9.0, "scan_ms": 2.0},   # scan wins
        ])
        config.set(config.AUTOTUNE_ENABLED, "true")
        config.set(config.AUTOTUNE_PARAM_SEED_FILE, path)
        eng = _mk_engine(manual_clock)
        try:
            at = eng.autotune
            assert at.seeded_buckets == 2
            # No explore phase: the first pick is already the measured
            # winner, with commit (not explore-*) reasoning.
            path_pick, reason = at.memo.pick((256, 1))
            assert path_pick == PATH_CLOSED and reason == "cost-hold"
            path_pick, reason = at.memo.pick((1024, 2))
            assert path_pick == PATH_SCAN and reason == "cost-switch"
            # And the commit sticks (hysteresis holds it).
            path_pick, reason = at.memo.pick((1024, 2))
            assert path_pick == PATH_SCAN and reason == "cost-hold"
            # UNSEEDED buckets still explore normally.
            _, reason = at.memo.pick((64, 1))
            assert reason.startswith("explore")
            assert at.snapshot()["param_seed_buckets"] == 2
        finally:
            eng.close()

    def test_bad_or_missing_file_is_ignored(self, manual_clock, tmp_path):
        config.set(config.AUTOTUNE_ENABLED, "true")
        config.set(config.AUTOTUNE_PARAM_SEED_FILE,
                   str(tmp_path / "nope.json"))
        eng = _mk_engine(manual_clock)
        try:
            assert eng.autotune.seeded_buckets == 0
        finally:
            eng.close()
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        config.set(config.AUTOTUNE_PARAM_SEED_FILE, str(bad))
        eng = _mk_engine(manual_clock)
        try:
            assert eng.autotune.seeded_buckets == 0
        finally:
            eng.close()
        # Malformed entries are skipped, valid ones load.
        mixed = self._seed_file(tmp_path, [
            {"rows_bucket": 8, "segments": 1, "closed_ms": 1.0,
             "scan_ms": 2.0},
            {"rows_bucket": "x"}, {"closed_ms": -1},
        ])
        config.set(config.AUTOTUNE_PARAM_SEED_FILE, mixed)
        eng = _mk_engine(manual_clock)
        try:
            assert eng.autotune.seeded_buckets == 1
        finally:
            eng.close()

    def test_force_path_seam_pins_attribution(self, manual_clock):
        """The k2probe measurement seam: param_force_path='scan' routes
        a closed-form-ELIGIBLE batch to the scan family (and counts
        it), 'closed' keeps the rank path."""
        eng = _mk_engine(manual_clock)
        try:
            eng.set_param_rules(
                {"mx": [ParamFlowRule("mx", param_idx=0, count=3)]}
            )
            manual_clock.set_ms(1000)
            for force, key in (("scan", "param_scan"),
                               ("closed", "param_closed_form")):
                eng.param_force_path = force
                eng.submit_bulk(
                    "mx", 8, ts=np.full(8, 1000, np.int32),
                    args_column=[("k",)] * 8,
                )
                c0 = eng.telemetry.counters_snapshot()
                eng.flush()
                eng.drain()
                c1 = eng.telemetry.counters_snapshot()
                assert c1[key] == c0[key] + 1, force
            eng.param_force_path = None
        finally:
            eng.close()
