"""Cluster flow control tests: token service decisions
(ClusterFlowChecker semantics), TCP server/client round trip, engine
integration (passClusterCheck/applyTokenResult), ICI allocation."""

import threading

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import (
    ClusterStateManager,
    DefaultTokenService,
    EmbeddedClusterTokenServerProvider,
    TokenClientProvider,
    cluster_flow_rule_manager,
    cluster_server_config_manager,
)
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule
from sentinel_tpu.utils.clock import ManualClock


def cluster_rule(resource, count, flow_id, threshold_type=C.FLOW_THRESHOLD_GLOBAL,
                 fallback=True):
    return FlowRule(
        resource,
        count=count,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id, threshold_type=threshold_type,
            fallback_to_local_when_fail=fallback,
        ),
    )


@pytest.fixture()
def cluster_env():
    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=30000.0
    )
    yield
    cluster_flow_rule_manager.clear()
    ClusterStateManager.stop()
    TokenClientProvider.clear()
    EmbeddedClusterTokenServerProvider.clear()


class TestTokenService:
    def test_global_threshold(self, cluster_env):
        clock = ManualClock(0)
        svc = DefaultTokenService(clock=clock)
        cluster_flow_rule_manager.load_rules(
            "default", [cluster_rule("r", 5, flow_id=101)]
        )
        results = [svc.request_token(101) for _ in range(7)]
        assert [r.ok for r in results] == [True] * 5 + [False] * 2
        assert results[-1].status == C.TokenResultStatus.BLOCKED

    def test_no_rule(self, cluster_env):
        svc = DefaultTokenService(clock=ManualClock(0))
        assert svc.request_token(999).status == C.TokenResultStatus.NO_RULE_EXISTS

    def test_avg_local_scales_with_connections(self, cluster_env):
        clock = ManualClock(0)
        svc = DefaultTokenService(clock=clock)
        svc.set_connected_count(3)
        cluster_flow_rule_manager.load_rules(
            "default",
            [cluster_rule("r", 2, flow_id=7, threshold_type=C.FLOW_THRESHOLD_AVG_LOCAL)],
        )
        # threshold = 2 * 3 connections = 6
        results = [svc.request_token(7) for _ in range(8)]
        assert sum(r.ok for r in results) == 6

    def test_window_slides(self, cluster_env):
        clock = ManualClock(0)
        svc = DefaultTokenService(clock=clock)
        cluster_flow_rule_manager.load_rules("default", [cluster_rule("r", 2, flow_id=1)])
        assert svc.request_token(1).ok
        assert svc.request_token(1).ok
        assert not svc.request_token(1).ok
        clock.set_ms(1101)  # pass counts at t=0 fall out of the 1s window
        assert svc.request_token(1).ok

    def test_namespace_guard(self, cluster_env):
        clock = ManualClock(0)
        cluster_server_config_manager.load_global_flow_config(max_allowed_qps=3.0)
        svc = DefaultTokenService(clock=clock)
        cluster_flow_rule_manager.load_rules("default", [cluster_rule("r", 100, flow_id=2)])
        results = [svc.request_token(2) for _ in range(5)]
        assert sum(r.ok for r in results) == 3
        assert results[-1].status == C.TokenResultStatus.TOO_MANY_REQUEST

    def test_batched_requests(self, cluster_env):
        svc = DefaultTokenService(clock=ManualClock(0))
        cluster_flow_rule_manager.load_rules("default", [cluster_rule("r", 4, flow_id=3)])
        results = svc.request_tokens([(3, 1, False)] * 6)
        assert [r.ok for r in results] == [True] * 4 + [False] * 2


class TestTcpRoundTrip:
    def test_client_server(self, cluster_env):
        cluster_flow_rule_manager.load_rules("default", [cluster_rule("r", 3, flow_id=42)])
        server = SentinelTokenServer(port=0, service=DefaultTokenService(clock=ManualClock(0)))
        server.start()
        try:
            client = ClusterTokenClient("127.0.0.1", server.port).start()
            results = [client.request_token(42) for _ in range(5)]
            assert [r.ok for r in results] == [True] * 3 + [False] * 2
            assert client.request_token(777).status == C.TokenResultStatus.NO_RULE_EXISTS
            client.stop()
        finally:
            server.stop()

    def test_client_fail_when_no_server(self, cluster_env):
        client = ClusterTokenClient("127.0.0.1", 1)  # nothing listens
        assert client.request_token(1).status == C.TokenResultStatus.FAIL

    def test_concurrent_clients(self, cluster_env):
        cluster_flow_rule_manager.load_rules("default", [cluster_rule("r", 50, flow_id=9)])
        server = SentinelTokenServer(port=0, service=DefaultTokenService(clock=ManualClock(0)))
        server.start()
        try:
            client = ClusterTokenClient("127.0.0.1", server.port).start()
            oks = []
            lock = threading.Lock()

            def worker():
                r = client.request_token(9)
                with lock:
                    oks.append(r.ok)

            threads = [threading.Thread(target=worker) for _ in range(60)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sum(oks) == 50
            client.stop()
        finally:
            server.stop()


class TestEngineIntegration:
    def test_embedded_server_mode(self, cluster_env, manual_clock, engine):
        """Engine entries route cluster rules through the embedded token
        service; BLOCKED maps to FlowBlockError."""
        rule = cluster_rule("svc", 2, flow_id=55)
        cluster_flow_rule_manager.load_rules("default", [rule])
        service = DefaultTokenService(clock=manual_clock)
        server = SentinelTokenServer(port=0, service=service)  # not started: embedded
        EmbeddedClusterTokenServerProvider.register(server)
        ClusterStateManager.set_to_server()
        st.flow_rule_manager.load_rules([rule])
        assert st.try_entry("svc") is not None
        assert st.try_entry("svc") is not None
        assert st.try_entry("svc") is None  # token server says BLOCKED
        with pytest.raises(st.FlowBlockError) as ei:
            st.entry("svc")
        assert ei.value.rule == rule

    def test_fallback_to_local_when_no_service(self, cluster_env, manual_clock, engine):
        rule = cluster_rule("fb", 1, flow_id=66, fallback=True)
        st.flow_rule_manager.load_rules([rule])
        ClusterStateManager.stop()
        # no client/server -> local check applies count=1
        assert st.try_entry("fb") is not None
        assert st.try_entry("fb") is None

    def test_pass_when_no_service_and_no_fallback(self, cluster_env, manual_clock, engine):
        rule = cluster_rule("nf", 1, flow_id=67, fallback=False)
        st.flow_rule_manager.load_rules([rule])
        ClusterStateManager.stop()
        for _ in range(5):
            e = st.try_entry("nf")
            assert e is not None
            e.exit()


class TestIciAllocation:
    @pytest.mark.mesh
    def test_cluster_allocate_conserves_capacity(self):
        import jax
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from sentinel_tpu.parallel import cluster_allocate, make_mesh

        mesh = make_mesh(8)
        demands = jnp.asarray(np.array([5, 3, 7, 0, 2, 9, 1, 4], dtype=np.int32))

        def alloc(d):
            return cluster_allocate("data", d, jnp.int32(10))

        fn = shard_map(alloc, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
        grants = np.asarray(jax.jit(fn)(demands))
        assert grants.sum() == 10  # exactly the capacity
        # Greedy by chip index: 5, 3, 2, 0, 0, ...
        assert list(grants) == [5, 3, 2, 0, 0, 0, 0, 0]
        assert (grants <= np.asarray(demands)).all()


class TestStatsWire:
    """The `stats` wire command (MSG_TYPE_STATS): codec roundtrip and
    fetch_server_stats ↔ stats_snapshot parity against a live shard."""

    def test_request_codec_roundtrip(self):
        from sentinel_tpu.cluster import protocol

        payload = protocol.pack_stats_request(7)[protocol._LEN.size:]
        assert protocol.peek_msg_type(payload) == C.MSG_TYPE_STATS
        assert protocol.unpack_request(payload) == (7, C.MSG_TYPE_STATS, ())
        with pytest.raises(ValueError, match="trailing bytes"):
            protocol.unpack_request(payload + b"\x00")

    def test_response_codec_roundtrip(self):
        from sentinel_tpu.cluster import protocol

        snap = {"port": 7070, "work": {"frames": 3}, "connections": 1}
        payload = protocol.pack_stats_response(9, snap)[protocol._LEN.size:]
        assert protocol.unpack_stats_response(payload) == (9, snap)

    def test_response_version_guard(self):
        import struct as _struct

        from sentinel_tpu.cluster import protocol

        payload = bytearray(
            protocol.pack_stats_response(9, {})[protocol._LEN.size:]
        )
        payload[protocol._REQ_HDR.size] = protocol.BATCH_VERSION + 1
        with pytest.raises(protocol.UnsupportedBatchVersion) as ei:
            protocol.unpack_stats_response(bytes(payload))
        assert ei.value.version == protocol.BATCH_VERSION + 1
        # Body must be an object, not any JSON value.
        bad = (
            protocol._REQ_HDR.pack(9, C.MSG_TYPE_STATS)
            + _struct.pack("<B", protocol.BATCH_VERSION)
            + b"[1,2]"
        )
        with pytest.raises(ValueError, match="not an object"):
            protocol.unpack_stats_response(bad)

    def test_fetch_matches_server_snapshot(self, cluster_env):
        from sentinel_tpu.cluster import stat_log
        from sentinel_tpu.cluster.client import fetch_server_stats

        stat_log.reset_counters()
        cluster_flow_rule_manager.load_rules(
            "default", [cluster_rule("r", 3, flow_id=42)]
        )
        server = SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=ManualClock(0))
        )
        server.start()
        try:
            client = ClusterTokenClient("127.0.0.1", server.port).start()
            for _ in range(5):
                client.request_token(42)  # 3 PASS + 2 BLOCKED
            client.stop()
            over = fetch_server_stats("127.0.0.1", server.port)
            local = server.stats_snapshot()
            assert over["port"] == server.port == local["port"]
            # The wire view and the in-process view agree on the work
            # clocks (the fetch's own socket may still show in
            # `connections`, so pin work + stat_log, not the transient
            # connection gauge). The snapshot is taken WHILE serving
            # the stats frame, so over sees ping + 5 flow frames and
            # the local read afterwards sees the stats frame too.
            assert over["work"]["frames"] == 6
            assert local["work"]["frames"] == 7
            # The stats frame itself is introspection: decisions must
            # not have moved between the two views.
            assert over["work"]["decisions"] == local["work"]["decisions"]
            assert over["work"]["lease_grants"] == 0
            assert over["stat_log"] == local["stat_log"]
        finally:
            server.stop()

    def test_fetch_connection_refused_raises(self):
        from sentinel_tpu.cluster.client import fetch_server_stats

        with pytest.raises(OSError):
            fetch_server_stats("127.0.0.1", 1, timeout_sec=0.5)
