"""EurekaDataSource and ConfigServerDataSource against fake HTTP
servers (registry JSON / config-server environment JSON).
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from sentinel_tpu.datasource.base import json_converter
from sentinel_tpu.datasource.config_server_source import ConfigServerDataSource
from sentinel_tpu.datasource.eureka_source import EurekaDataSource
from sentinel_tpu.models.rules import FlowRule


class FakeHttp(ThreadingHTTPServer):
    """Serves a path→JSON map; paths not in the map get 404. A server
    can be marked down to exercise failover."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _Handler)
        self.port = self.server_address[1]
        self.routes = {}
        self.down = False
        self.hits = 0


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        srv: FakeHttp = self.server
        srv.hits += 1
        if srv.down:
            self.send_response(503)
            self.end_headers()
            return
        obj = srv.routes.get(self.path)
        if obj is None:
            self.send_response(404)
            self.end_headers()
            return
        body = json.dumps(obj).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def fake_http():
    servers = []

    def make():
        srv = FakeHttp()
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return srv

    yield make
    for srv in servers:
        srv.shutdown()
        srv.server_close()


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _rules_json(count):
    return json.dumps([{"resource": "r", "count": count}])


def _eureka_payload(count):
    return {"instance": {"metadata": {"flowRules": _rules_json(count)}}}


def _env(*sources):
    return {"propertySources": [{"name": f"s{i}", "source": s}
                                for i, s in enumerate(sources)]}


class TestEurekaDataSource:
    def test_poll_updates(self, fake_http):
        srv = fake_http()
        srv.routes["/apps/app1/inst1"] = _eureka_payload(7)
        src = EurekaDataSource(
            json_converter(FlowRule), "app1", "inst1",
            [f"http://127.0.0.1:{srv.port}"], "flowRules",
            refresh_interval_sec=0.1,
        ).start()
        try:
            assert _wait(lambda: (src.get_property().value or [None])[0]
                         and src.get_property().value[0].count == 7)
            srv.routes["/apps/app1/inst1"] = _eureka_payload(9)
            assert _wait(lambda: src.get_property().value[0].count == 9)
        finally:
            src.close()

    def test_failover_to_second_server(self, fake_http):
        down, up = fake_http(), fake_http()
        down.down = True
        up.routes["/apps/app1/inst1"] = _eureka_payload(4)
        src = EurekaDataSource(
            json_converter(FlowRule), "app1", "inst1",
            [f"http://127.0.0.1:{down.port}", f"http://127.0.0.1:{up.port}"],
            "flowRules", refresh_interval_sec=0.1,
        )
        # Every read lands on the healthy server regardless of shuffle;
        # loop until the shuffle has provably tried (and skipped) the
        # down server at least once, so failover itself is exercised.
        for _ in range(50):
            assert json.loads(src.read_source())[0]["count"] == 4
            if down.hits > 0:
                break
        assert down.hits > 0, "shuffle never routed through the down server"

    def test_all_servers_down_raises(self, fake_http):
        down = fake_http()
        down.down = True
        src = EurekaDataSource(
            json_converter(FlowRule), "app1", "inst1",
            [f"http://127.0.0.1:{down.port}"], "flowRules",
        )
        with pytest.raises(RuntimeError):
            src.read_source()

    def test_missing_metadata_key_is_none(self, fake_http):
        srv = fake_http()
        srv.routes["/apps/app1/inst1"] = {"instance": {"metadata": {}}}
        src = EurekaDataSource(
            json_converter(FlowRule), "app1", "inst1",
            [f"http://127.0.0.1:{srv.port}"], "flowRules",
        )
        assert src.read_source() is None


class TestConfigServerDataSource:
    def test_poll_and_refresh(self, fake_http):
        srv = fake_http()
        srv.routes["/myapp/default"] = _env({"flowRules": _rules_json(5)})
        src = ConfigServerDataSource(
            json_converter(FlowRule), "myapp", "flowRules",
            endpoint=f"http://127.0.0.1:{srv.port}",
            refresh_interval_sec=30.0,  # polling effectively off
        ).start()
        try:
            assert _wait(lambda: (src.get_property().value or [None])[0]
                         and src.get_property().value[0].count == 5)
            srv.routes["/myapp/default"] = _env({"flowRules": _rules_json(8)})
            src.refresh()  # the git-webhook analog
            assert src.get_property().value[0].count == 8
        finally:
            src.close()

    def test_first_property_source_wins(self, fake_http):
        srv = fake_http()
        srv.routes["/myapp/prod/main"] = _env(
            {"flowRules": _rules_json(1)}, {"flowRules": _rules_json(99)}
        )
        src = ConfigServerDataSource(
            json_converter(FlowRule), "myapp", "flowRules",
            profile="prod", label="main",
            endpoint=f"http://127.0.0.1:{srv.port}",
        )
        assert json.loads(src.read_source())[0]["count"] == 1

    def test_non_string_value_is_json_encoded(self, fake_http):
        srv = fake_http()
        srv.routes["/myapp/default"] = _env(
            {"flowRules": [{"resource": "r", "count": 3}]}
        )
        src = ConfigServerDataSource(
            json_converter(FlowRule), "myapp", "flowRules",
            endpoint=f"http://127.0.0.1:{srv.port}",
        )
        assert src.load_config()[0].count == 3

    def test_missing_key_is_none(self, fake_http):
        srv = fake_http()
        srv.routes["/myapp/default"] = _env({"other": "x"})
        src = ConfigServerDataSource(
            json_converter(FlowRule), "myapp", "flowRules",
            endpoint=f"http://127.0.0.1:{srv.port}",
        )
        assert src.read_source() is None

class TestGarbageConfigNeverClobbers:
    """A corrupted payload must leave the last good rules in place —
    the reference's converter exceptions are swallowed by the listener
    (AutoRefreshDataSource.java:53-69 logs and keeps the old value);
    same stance across every new source's error path."""

    def test_eureka_garbage_keeps_rules(self, fake_http):
        srv = fake_http()
        srv.routes["/apps/a/i"] = _eureka_payload(7)
        src = EurekaDataSource(
            json_converter(FlowRule), "a", "i",
            [f"http://127.0.0.1:{srv.port}"], "flowRules",
            refresh_interval_sec=0.05,
        ).start()
        try:
            assert _wait(lambda: (src.get_property().value or [None])[0]
                         and src.get_property().value[0].count == 7)
            # Metadata turns to garbage: converter raises every poll.
            hits = srv.hits
            srv.routes["/apps/a/i"] = {
                "instance": {"metadata": {"flowRules": "{not json"}}}
            # Provably at least two garbage polls happened...
            assert _wait(lambda: srv.hits >= hits + 2)
            assert src.get_property().value[0].count == 7  # ...unchanged
            # Recovery: good payload lands again.
            srv.routes["/apps/a/i"] = _eureka_payload(9)
            assert _wait(lambda: src.get_property().value[0].count == 9)
        finally:
            src.close()

    def test_config_server_garbage_keeps_rules(self, fake_http):
        srv = fake_http()
        srv.routes["/myapp/default"] = {
            "propertySources": [{"name": "s", "source": {"flowRules": _rules_json(5)}}]
        }
        src = ConfigServerDataSource(
            json_converter(FlowRule), "myapp", "flowRules",
            endpoint=f"http://127.0.0.1:{srv.port}",
            refresh_interval_sec=0.05,
        ).start()
        try:
            assert _wait(lambda: (src.get_property().value or [None])[0]
                         and src.get_property().value[0].count == 5)
            hits = srv.hits
            srv.routes["/myapp/default"] = _env({"flowRules": "]["})
            assert _wait(lambda: srv.hits >= hits + 2)
            assert src.get_property().value[0].count == 5  # unchanged
            # And the source is not stuck: a good payload recovers it.
            srv.routes["/myapp/default"] = _env({"flowRules": _rules_json(6)})
            assert _wait(lambda: src.get_property().value[0].count == 6)
        finally:
            src.close()
