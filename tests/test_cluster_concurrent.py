"""Cluster concurrent (in-flight) flow control: the held-token protocol
(MSG_TYPE_CONCURRENT_FLOW_ACQUIRE=3 / RELEASE=4) against the reference's
ConcurrentClusterFlowChecker + CurrentConcurrencyManager semantics
(sentinel-cluster-server-default/.../flow/ConcurrentClusterFlowChecker.
java:30-100) — direct service calls, TCP round trips, engine
integration with release-on-exit, connected-count scaling, and the
resourceTimeout sweep.
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import (
    ClusterStateManager,
    DefaultTokenService,
    EmbeddedClusterTokenServerProvider,
    TokenClientProvider,
    cluster_flow_rule_manager,
    cluster_server_config_manager,
)
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule
from sentinel_tpu.utils.clock import ManualClock


def concurrent_rule(resource, count, flow_id,
                    threshold_type=C.FLOW_THRESHOLD_GLOBAL,
                    resource_timeout=2000, fallback=False):
    return FlowRule(
        resource,
        count=count,
        grade=C.FLOW_GRADE_THREAD,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id,
            threshold_type=threshold_type,
            fallback_to_local_when_fail=fallback,
            resource_timeout=resource_timeout,
        ),
    )


@pytest.fixture()
def cluster_env():
    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=30000.0
    )
    yield
    cluster_flow_rule_manager.clear()
    ClusterStateManager.stop()
    TokenClientProvider.clear()
    EmbeddedClusterTokenServerProvider.clear()


class TestConcurrentService:
    def test_acquire_until_threshold_then_block(self, cluster_env):
        svc = DefaultTokenService(clock=ManualClock(0))
        cluster_flow_rule_manager.load_rules(
            "default", [concurrent_rule("c", 3, flow_id=10)]
        )
        tokens = []
        for _ in range(3):
            r = svc.request_concurrent_token(10)
            assert r.ok and r.token_id != 0
            tokens.append(r.token_id)
        assert svc.request_concurrent_token(10).status == C.TokenResultStatus.BLOCKED
        assert svc.concurrent.now_calls(10) == 3
        # Releasing one frees one slot.
        assert (
            svc.release_concurrent_token(tokens[0]).status
            == C.TokenResultStatus.RELEASE_OK
        )
        assert svc.request_concurrent_token(10).ok
        # Double release of the same token.
        assert (
            svc.release_concurrent_token(tokens[0]).status
            == C.TokenResultStatus.ALREADY_RELEASE
        )

    def test_unknown_flow_fails(self, cluster_env):
        svc = DefaultTokenService(clock=ManualClock(0))
        assert svc.request_concurrent_token(999).status == C.TokenResultStatus.FAIL

    def test_acquire_count_batches(self, cluster_env):
        svc = DefaultTokenService(clock=ManualClock(0))
        cluster_flow_rule_manager.load_rules(
            "default", [concurrent_rule("c", 5, flow_id=11)]
        )
        r = svc.request_concurrent_token(11, acquire_count=4)
        assert r.ok
        assert svc.request_concurrent_token(11, acquire_count=2).status \
            == C.TokenResultStatus.BLOCKED
        assert svc.request_concurrent_token(11, acquire_count=1).ok

    def test_connected_count_scales_avg_local(self, cluster_env):
        """AVG_LOCAL: threshold = count × connectedCount
        (calcGlobalThreshold, java:33-45)."""
        svc = DefaultTokenService(clock=ManualClock(0))
        cluster_flow_rule_manager.load_rules(
            "default",
            [concurrent_rule("c", 2, flow_id=12,
                             threshold_type=C.FLOW_THRESHOLD_AVG_LOCAL)],
        )
        svc.set_connected_count(1)
        assert svc.request_concurrent_token(12).ok
        assert svc.request_concurrent_token(12).ok
        assert svc.request_concurrent_token(12).status == C.TokenResultStatus.BLOCKED
        svc.set_connected_count(3)  # capacity now 6, 2 held
        for _ in range(4):
            assert svc.request_concurrent_token(12).ok
        assert svc.request_concurrent_token(12).status == C.TokenResultStatus.BLOCKED

    def test_resource_timeout_sweep(self, cluster_env):
        """Tokens held past resourceTimeout are force-freed — the
        client-died story (TokenCacheNode.resourceTimeout)."""
        clock = ManualClock(0)
        svc = DefaultTokenService(clock=clock)
        cluster_flow_rule_manager.load_rules(
            "default", [concurrent_rule("c", 1, flow_id=13, resource_timeout=500)]
        )
        r = svc.request_concurrent_token(13)
        assert r.ok
        assert svc.request_concurrent_token(13).status == C.TokenResultStatus.BLOCKED
        clock.set_ms(600)
        assert svc.concurrent.sweep_expired() == 1
        assert svc.request_concurrent_token(13).ok
        # The swept token's late release is ALREADY_RELEASE, not a
        # double decrement.
        assert (
            svc.release_concurrent_token(r.token_id).status
            == C.TokenResultStatus.ALREADY_RELEASE
        )
        assert svc.concurrent.now_calls(13) == 1


    def test_expired_token_freed_at_capacity_without_explicit_sweep(self, cluster_env):
        """acquire() at capacity force-sweeps: an expired token must not
        keep the flow blocked until the next throttled sweep."""
        clock = ManualClock(0)
        svc = DefaultTokenService(clock=clock)
        cluster_flow_rule_manager.load_rules(
            "default", [concurrent_rule("c", 1, flow_id=14, resource_timeout=500)]
        )
        assert svc.request_concurrent_token(14).ok
        clock.set_ms(600)  # token expired; throttled sweep not due yet
        assert svc.request_concurrent_token(14).ok

    def test_deferred_exit_releases_tokens(self, cluster_env, manual_clock, engine):
        """Deferred-mode callers pass op.cluster_tokens to submit_exit."""
        rule = concurrent_rule("dfr", 2, flow_id=32)
        cluster_flow_rule_manager.load_rules("default", [rule])
        svc = DefaultTokenService(clock=manual_clock)
        EmbeddedClusterTokenServerProvider.register(
            SentinelTokenServer(port=0, service=svc)
        )
        ClusterStateManager.set_to_server()
        st.flow_rule_manager.load_rules([rule])
        ops = engine.submit_many([{"resource": "dfr"} for _ in range(2)])
        engine.flush()
        assert all(op.verdict.admitted for op in ops)
        assert svc.concurrent.now_calls(32) == 2
        for op in ops:
            engine.submit_exit(op.rows, rt=5, resource="dfr",
                               cluster_tokens=op.cluster_tokens)
        engine.flush()
        assert svc.concurrent.now_calls(32) == 0


class TestConcurrentTcp:
    def test_acquire_release_round_trip(self, cluster_env):
        cluster_flow_rule_manager.load_rules(
            "default", [concurrent_rule("c", 2, flow_id=20)]
        )
        server = SentinelTokenServer(port=0, service=DefaultTokenService(ManualClock(0)))
        server.start()
        try:
            client = ClusterTokenClient(port=server.port).start()
            r1 = client.request_concurrent_token(20)
            r2 = client.request_concurrent_token(20)
            assert r1.ok and r2.ok and r1.token_id != r2.token_id
            assert (
                client.request_concurrent_token(20).status
                == C.TokenResultStatus.BLOCKED
            )
            assert (
                client.release_concurrent_token(r1.token_id).status
                == C.TokenResultStatus.RELEASE_OK
            )
            assert client.request_concurrent_token(20).ok
            client.stop()
        finally:
            server.stop()

    def test_client_disconnect_frees_held_tokens(self, cluster_env):
        """The server eagerly frees a vanished client's held tokens
        (clientOfflineTime / ConnectionManager story)."""
        import time

        cluster_flow_rule_manager.load_rules(
            "default", [concurrent_rule("c", 1, flow_id=21)]
        )
        svc = DefaultTokenService(ManualClock(0))
        server = SentinelTokenServer(port=0, service=svc)
        server.start()
        try:
            client = ClusterTokenClient(port=server.port).start()
            assert client.request_concurrent_token(21).ok
            assert svc.concurrent.now_calls(21) == 1
            client.stop()  # connection drops without release
            deadline = time.time() + 5
            while time.time() < deadline and svc.concurrent.now_calls(21) != 0:
                time.sleep(0.02)
            assert svc.concurrent.now_calls(21) == 0
        finally:
            server.stop()


class TestEngineConcurrentIntegration:
    def test_entry_acquires_and_exit_releases(self, cluster_env, manual_clock, engine):
        """A cluster THREAD-grade rule routes through the concurrent
        token API; Entry.exit hands the token back."""
        rule = concurrent_rule("svc", 2, flow_id=30)
        cluster_flow_rule_manager.load_rules("default", [rule])
        svc = DefaultTokenService(clock=manual_clock)
        server = SentinelTokenServer(port=0, service=svc)  # embedded
        EmbeddedClusterTokenServerProvider.register(server)
        ClusterStateManager.set_to_server()
        st.flow_rule_manager.load_rules([rule])

        e1 = st.try_entry("svc")
        e2 = st.try_entry("svc")
        assert e1 is not None and e2 is not None
        assert svc.concurrent.now_calls(30) == 2
        assert st.try_entry("svc") is None  # concurrency exhausted
        e1.exit()
        assert svc.concurrent.now_calls(30) == 1
        e3 = st.try_entry("svc")
        assert e3 is not None
        e2.exit()
        e3.exit()
        assert svc.concurrent.now_calls(30) == 0
        assert svc.concurrent.held_tokens() == 0

    def test_blocked_entry_returns_its_token(self, cluster_env, manual_clock, engine):
        """An entry that acquired a concurrency token but was blocked by
        another rule releases the token immediately."""
        rule = concurrent_rule("mix", 5, flow_id=31)
        local = FlowRule("mix", count=0)  # always blocks locally
        cluster_flow_rule_manager.load_rules("default", [rule])
        svc = DefaultTokenService(clock=manual_clock)
        EmbeddedClusterTokenServerProvider.register(
            SentinelTokenServer(port=0, service=svc)
        )
        ClusterStateManager.set_to_server()
        st.flow_rule_manager.load_rules([rule, local])
        assert st.try_entry("mix") is None
        assert svc.concurrent.now_calls(31) == 0  # token handed back
        assert svc.concurrent.held_tokens() == 0
