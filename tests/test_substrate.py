"""Substrate tests: clock, config, registry (SPI), interner, property."""

import pytest

from sentinel_tpu.core.property import DynamicSentinelProperty, FuncListener
from sentinel_tpu.utils.clock import ManualClock, SystemClock
from sentinel_tpu.utils.config import SentinelConfig
from sentinel_tpu.utils.interner import Interner, PairInterner
from sentinel_tpu.utils.registry import Registry, provider


class TestClock:
    def test_manual_clock(self):
        c = ManualClock(start_ms=100)
        assert c.now_ms() == 100
        c.advance(50)
        assert c.now_ms() == 150
        c.sleep_ms(10)
        assert c.now_ms() == 160
        assert c.wall_ms() == c.epoch_wall_ms + 160

    def test_system_clock_monotone(self):
        c = SystemClock()
        a = c.now_ms()
        b = c.now_ms()
        assert b >= a >= 0
        assert c.rebase_headroom_ms() > 0

    def test_system_clock_rebase(self):
        c = SystemClock()
        wall_before = c.wall_ms()
        off = c.rebase()
        assert off >= 0
        assert c.now_ms() <= 1
        assert abs(c.wall_ms() - wall_before) <= 50


class TestConfig:
    def test_defaults(self):
        cfg = SentinelConfig(load_env=False)
        assert cfg.cold_factor == 3
        assert cfg.statistic_max_rt == 4900
        assert cfg.get_int(SentinelConfig.TOTAL_METRIC_FILE_COUNT) == 6

    def test_layering_and_types(self):
        cfg = SentinelConfig(load_env=False)
        cfg.set(SentinelConfig.COLD_FACTOR, "5")
        assert cfg.cold_factor == 5
        cfg.set(SentinelConfig.COLD_FACTOR, "1")  # clamped back to 3
        assert cfg.cold_factor == 3
        cfg.set("x.bool", "true")
        assert cfg.get_bool("x.bool") is True
        assert cfg.get_float("missing", 1.5) == 1.5

    def test_env_layer(self, monkeypatch):
        monkeypatch.setenv("CSP_SENTINEL_FLOW_COLD_FACTOR", "5")
        monkeypatch.setenv("SENTINEL_TPU_FLUSH_MAX_BATCH", "999")
        cfg = SentinelConfig()
        assert cfg.cold_factor == 5
        assert cfg.get_int(SentinelConfig.FLUSH_MAX_BATCH) == 999

    def test_properties_file(self, tmp_path):
        f = tmp_path / "sentinel.properties"
        f.write_text("project.name=my-app\n# comment\ncsp.sentinel.flow.cold.factor: 7\n")
        cfg = SentinelConfig(config_file=str(f))
        assert cfg.app_name == "my-app"
        assert cfg.cold_factor == 7


class TestRegistry:
    def test_order_and_default(self):
        class Iface:
            pass

        @provider(Iface, order=10)
        class B:
            pass

        @provider(Iface, order=-10)
        class A:
            pass

        @provider(Iface, order=50, default=True)
        class D:
            pass

        insts = Registry.of(Iface).load_instance_list_sorted()
        assert [type(i).__name__ for i in insts] == ["A", "B", "D"]
        assert type(Registry.of(Iface).load_highest_priority_instance()).__name__ == "A"
        assert type(Registry.of(Iface).load_default()).__name__ == "D"

    def test_singleton_semantics(self):
        reg = Registry("test.singleton")

        class X:
            pass

        reg.register(X, name="x", singleton=True)
        assert reg.load_by_name("x") is reg.load_by_name("x")
        reg2 = Registry("test.proto")
        reg2.register(X, name="x", singleton=False)
        assert reg2.load_by_name("x") is not reg2.load_by_name("x")


class TestInterner:
    def test_dense_ids_and_cap(self):
        it = Interner(capacity=2)
        assert it.intern("a") == 0
        assert it.intern("b") == 1
        assert it.intern("a") == 0
        assert it.intern("c") is None  # over cap -> pass-through signal
        assert it.name_of(1) == "b"
        assert len(it) == 2

    def test_pair_interner(self):
        it = PairInterner()
        assert it.intern(1, 2) == 0
        assert it.intern(1, 3) == 1
        assert it.intern(1, 2) == 0
        assert it.pair_of(1) == (1, 3)


class TestProperty:
    def test_listener_fires_on_change_only(self):
        prop = DynamicSentinelProperty()
        seen = []
        prop.add_listener(FuncListener(seen.append))
        assert seen == [None]  # config_load on registration
        assert prop.update_value([1, 2]) is True
        assert prop.update_value([1, 2]) is False  # unchanged -> no fan-out
        assert prop.update_value([3]) is True
        assert seen == [None, [1, 2], [3]]
