"""Pipelined flushes: ``Engine.flush_async`` + lazy materialization.

``flush()`` blocks on the device→host fetch of its own results; on a
remote-tunnel backend that serializes every flush behind a full
round-trip (PERF_NOTES.md: ~0.3-0.4 ms dispatch floor). ``flush_async``
dispatches and returns; results materialize on first access, at the
next ``flush()``/``drain()``, or when the in-flight bound is hit. The
reference has no analog (every entry is a synchronous CAS race,
sentinel-core SphU.java:84); this is the batch-inversion's pipelining
dividend. These tests pin:

- verdict/bulk-result laziness and materialize-on-access,
- exact sync/async verdict equality on a shared random stream,
- FIFO in-flight bounding (``max_inflight``),
- block-log delivery riding with materialization,
- rule reloads between dispatch and materialization keeping the
  dispatched tables' attribution.
"""

from __future__ import annotations

import numpy as np
import pytest

from sentinel_tpu.models.rules import DegradeRule, FlowRule
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.utils.clock import ManualClock


def _engine(rules, clock=None):
    eng = Engine(initial_rows=1024, clock=clock or ManualClock(0))
    eng.set_flow_rules(rules)
    return eng


def test_flush_async_defers_and_materializes_on_access():
    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=50)], clock)
    ops = [eng.submit_entry("r", ts=clock.now_ms()) for _ in range(100)]
    ret = eng.flush_async()
    assert len(ret) == 100
    # Not yet fetched: raw slots are empty, one record queued.
    assert ops[0]._verdict is None
    assert len(eng._pending_fetches) == 1
    # First access materializes the whole chunk.
    assert ops[0].verdict is not None
    assert all(o._verdict is not None for o in ops)
    assert len(eng._pending_fetches) == 0
    assert sum(o.verdict.admitted for o in ops) == 50


def test_bulk_async_lazy_arrays():
    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=30)], clock)
    g = eng.submit_bulk("r", 100, ts=clock.now_ms())
    eng.flush_async()
    assert g._admitted is None
    assert g.admitted_count == 30  # property materializes
    assert g._admitted is not None and g._reason is not None
    assert int((~g.admitted).sum()) == 70


def test_drain_and_sync_flush_materialize_everything():
    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=10)], clock)
    o1 = [eng.submit_entry("r", ts=clock.now_ms()) for _ in range(20)]
    eng.flush_async()
    eng.drain()
    assert all(o._verdict is not None for o in o1)
    # sync flush after async: drains pendings first, keeps window state.
    o2 = [eng.submit_entry("r", ts=clock.now_ms()) for _ in range(20)]
    eng.flush_async()
    o3 = [eng.submit_entry("r", ts=clock.now_ms()) for _ in range(5)]
    eng.flush()
    assert all(o._verdict is not None for o in o2 + o3)
    admitted = sum(o.verdict.admitted for o in o1 + o2 + o3)
    assert admitted == 10  # one second-window, count=10, same ts


def test_inflight_bound_fifo():
    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=1e9)], clock)
    eng.max_inflight = 2
    groups = []
    for _ in range(5):
        groups.append(eng.submit_bulk("r", 64, ts=clock.now_ms()))
        eng.flush_async()
    # Only the newest 2 remain unfetched; the first 3 were forced FIFO.
    assert len(eng._pending_fetches) == 2
    assert all(g._admitted is not None for g in groups[:3])
    assert all(g._admitted is None for g in groups[3:])
    eng.drain()
    assert all(g.admitted_count == 64 for g in groups)


def test_async_equals_sync_on_random_stream():
    """Differential: the same submit/exit stream through flush_async
    must produce bit-identical verdicts to sync flushes."""
    rules = [
        FlowRule(resource="a", count=7),
        FlowRule(resource="b", count=3, grade=0),  # thread grade
        FlowRule(resource="c", count=20),
    ]
    def run(async_mode: bool):
        # Fresh rng per run: the exit-choice draws below must be
        # identical across both modes.
        rng = np.random.default_rng(42)
        stream = []
        t = 1000
        for _ in range(300):
            t += int(rng.integers(0, 40))
            stream.append((rng.choice(["a", "b", "c"]), t))
        clock = ManualClock(0)
        eng = _engine(rules, clock)
        eng.set_degrade_rules(
            [DegradeRule(resource="a", grade=1, count=0.5, time_window=5)]
        )
        verdicts = []
        ops = []
        for i, (res, ts) in enumerate(stream):
            clock.set_ms(ts)
            op = eng.submit_entry(res, ts=ts)
            ops.append(op)
            if i % 7 == 3:
                (eng.flush_async() if async_mode else eng.flush())
            if i % 11 == 5 and ops:
                # Exit a random earlier admitted op (thread release).
                # o.verdict (not _verdict) so the async run materializes
                # here too and both modes submit identical exits.
                j = int(rng.integers(0, len(ops)))
                o = ops[j]
                if o is not None and o.verdict is not None and o.verdict.admitted:
                    eng.submit_exit(o.rows, ts=ts, count=1, rt=5)
        eng.flush() if not async_mode else (eng.flush_async(), eng.drain())
        return [
            (o.verdict.admitted, o.verdict.reason, o.verdict.wait_ms)
            for o in ops
            if o is not None
        ]

    assert run(False) == run(True)


def test_block_log_rides_with_materialization(tmp_path, monkeypatch):
    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=0)], clock)
    logged = []
    monkeypatch.setattr(
        eng.block_log, "log_batch", lambda items: logged.extend(items)
    )
    for _ in range(4):
        eng.submit_entry("r", ts=clock.now_ms())
    eng.flush_async()
    assert logged == []  # nothing fetched yet
    eng.drain()
    assert len(logged) == 4
    assert all(item[0] == "r" and item[1] == "FlowException" for item in logged)


def test_reload_between_dispatch_and_materialize_keeps_attribution():
    clock = ManualClock(1000)
    rule = FlowRule(resource="r", count=0)
    eng = _engine([rule], clock)
    op = eng.submit_entry("r", ts=clock.now_ms())
    eng.flush_async()
    # Reload swaps the tables; the dispatched chunk still attributes
    # against the index it was checked with.
    eng.set_flow_rules([FlowRule(resource="r", count=100)])
    v = op.verdict
    assert v is not None and not v.admitted
    assert v.blocked_rule is not None and v.blocked_rule.count == 0


def test_failed_fetch_raises_to_every_reader(monkeypatch):
    """A device failure during the deferred fetch must surface on every
    later result read — never as 'nothing admitted' (admitted_count 0
    / verdict None)."""
    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=5)], clock)
    g = eng.submit_bulk("r", 16, ts=clock.now_ms())
    op = eng.submit_entry("r", ts=clock.now_ms())
    eng.flush_async()

    boom = RuntimeError("tunnel wedged")

    def broken_fill(*a, **kw):
        raise boom

    monkeypatch.setattr(eng, "_fill_results", broken_fill)
    with pytest.raises(RuntimeError, match="tunnel wedged"):
        eng.drain()
    # Subsequent reads keep raising the stored failure.
    with pytest.raises(RuntimeError, match="tunnel wedged"):
        g.admitted_count
    with pytest.raises(RuntimeError, match="tunnel wedged"):
        op.verdict
    # The queue is not stranded: later flushes work once fills succeed.
    monkeypatch.undo()
    g2 = eng.submit_bulk("r", 8, ts=clock.now_ms())
    eng.flush_async()
    assert g2.admitted_count >= 0
    eng.drain()


def test_reset_settles_pending_async_flushes(monkeypatch):
    """reset() must settle dispatched-but-unfetched chunks: their
    block-log records belong to the pre-reset engine, and a stored
    fetch failure must not surface into the first post-reset flush."""
    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=0)], clock)
    logged = []
    monkeypatch.setattr(
        eng.block_log, "log_batch", lambda items: logged.extend(items)
    )
    ops = [eng.submit_entry("r", ts=clock.now_ms()) for _ in range(4)]
    eng.flush_async()
    assert logged == []
    eng.reset()
    # Settled during reset, not delivered into post-reset traffic.
    assert len(logged) == 4
    assert all(o._verdict is not None for o in ops)
    assert len(eng._pending_fetches) == 0
    # A post-reset flush sees a clean engine.
    op = eng.submit_entry("r", ts=clock.now_ms())
    eng.flush()
    assert op.verdict.admitted  # the count=0 rule was cleared by reset

    # Failed pre-reset fetch: reset logs and completes; the error does
    # not leak into post-reset flushes (readers of the old ops still
    # see it).
    eng2 = _engine([FlowRule(resource="q", count=5)], clock)
    op2 = eng2.submit_entry("q", ts=clock.now_ms())
    eng2.flush_async()
    monkeypatch.setattr(
        eng2, "_fill_results",
        lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("wedged")),
    )
    eng2.reset()  # swallows + logs
    monkeypatch.undo()
    op3 = eng2.submit_entry("q", ts=clock.now_ms())
    eng2.flush()  # must NOT raise the pre-reset failure
    assert op3.verdict is not None
    with pytest.raises(RuntimeError, match="wedged"):
        op2.verdict


def test_flush_async_on_empty_engine_is_noop():
    eng = _engine([FlowRule(resource="r", count=5)])
    assert eng.flush_async() == []
    assert len(eng._pending_fetches) == 0
    eng.drain()


@pytest.mark.slow
@pytest.mark.mesh
def test_flush_async_on_mesh_conserves_budget():
    """Deferred fetch over the sharded (multi-chip) kernel: budgets
    still conserved across chips, lazily materialized."""
    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=20)], clock)
    eng.enable_mesh(8)
    ops = [eng.submit_entry("r", ts=clock.now_ms()) for _ in range(128)]
    eng.flush_async()
    assert ops[0]._verdict is None
    assert sum(o.verdict.admitted for o in ops) == 20
    eng.drain()
    eng.disable_mesh()


@pytest.mark.slow
def test_async_pipeline_under_thread_contention():
    """Concurrent submitters + async flusher + readers: no deadlock,
    exact totals."""
    import threading

    clock = ManualClock(1000)
    eng = _engine([FlowRule(resource="r", count=1e9)], clock)
    groups: list = []
    glock = threading.Lock()
    stop = threading.Event()

    def submitter():
        while not stop.is_set():
            g = eng.submit_bulk("r", 128, ts=clock.now_ms())
            with glock:
                groups.append(g)
            eng.flush_async()

    def reader():
        while not stop.is_set():
            with glock:
                g = groups[-1] if groups else None
            if g is not None:
                g.admitted_count  # may materialize concurrently

    threads = [threading.Thread(target=submitter) for _ in range(2)] + [
        threading.Thread(target=reader)
    ]
    for t in threads:
        t.start()
    import time

    time.sleep(2.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "deadlocked thread"
    eng.drain()
    assert all(g.admitted_count == 128 for g in groups)
