"""Zero-outage engine lifecycle (PR 20): warm standby, planned live
handoff, and sub-second death detection.

The acceptance surface: a planned handoff drains the old world and
re-homes the SAME client ledgers onto a successor plane with ZERO
policy-served verdicts (workers HOLD on the HANDOFF control word
instead of failing over — verdict parity vs a never-killed oracle at
pipeline depths {0, 2}, device AND mirror THREAD gauges exactly 0
after quiesce); the capture journal files an orderly drain as
``frozen-close-*``, never as a crash (and a stale marker cannot
whitewash a LATER crash); sub-second ``ipc.engine.dead.ms`` with the
confirmation step armed never declares a pegged-but-alive engine dead
(counted false-alarm episodes, pid probe) while a provably dead pid is
still declared within the probe window; and the `mp`-marked chaos
tests drive the real thing — ``kill -9`` with a warm standby armed is
a takeover, not a cold respawn, and a config-push handoff cycle
completes with zero policy-served verdicts.

Every standby/handoff key defaults off: the entire file arms them
explicitly, and the confirmation-off test pins the PR-15 behavior.
"""

from __future__ import annotations

import os
import threading
import time
import uuid

import pytest

from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import FlowRule
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.utils.config import config


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _wait_for(pred, timeout_s=30.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _handoff_config(depth: int) -> str:
    prefix = f"stpu-ho-{uuid.uuid4().hex[:8]}"
    config.set(config.IPC_SHM_PREFIX, prefix)
    config.set(config.IPC_HEARTBEAT_MS, "50")
    config.set(config.IPC_ENGINE_DEAD_MS, "300")
    config.set(config.IPC_HANDOFF_WAIT_MS, "30000")
    config.set(config.SPECULATIVE_ENABLED, "true")
    config.set(config.PIPELINE_DEPTH, str(depth))
    return prefix


# ---------------------------------------------------------------------------
# planned handoff, in-process (the protocol core; real processes are
# the mp class below)
# ---------------------------------------------------------------------------
class TestPlannedHandoffInProcess:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_handoff_holds_then_successor_parity_and_gauges(self, depth):
        """Old plane publishes HANDOFF and drains; a NEW admission
        arriving mid-handoff is HELD (not policy-served) across the
        detach->attach gap; the successor plane re-homes the client's
        live THREAD ledger; post-handoff verdicts match a never-killed
        oracle; gauges drain to exactly 0. policy_served stays 0 for
        the whole cycle — the zero-outage bit."""
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient

        _handoff_config(depth)
        rule = lambda: [  # noqa: E731
            FlowRule("tr", count=3, grade=C.FLOW_GRADE_THREAD)
        ]
        a = Engine(initial_rows=256)
        a.set_flow_rules(rule())
        plane_a = IngestPlane(a)
        cli = IngestClient(plane_a.channel(0), 0)
        b = plane_b = None
        held: dict = {}
        try:
            for _ in range(2):
                v = cli.entry("tr", timeout_ms=60000)
                assert v.admitted and not v.degraded
            a.flush()
            a.drain()
            assert a.cluster_node_stats("tr")["cur_thread_num"] == 2

            stats = plane_a.handoff()
            assert stats["drained"] is True
            assert a.ipc_plane is None
            a.close()

            # An admission in the handoff window: the client sees the
            # HANDOFF word (stale wall included — the old world already
            # detached) and HOLDS instead of serving policy.
            def _held_entry():
                held["verdict"] = cli.entry("tr", timeout_ms=60000)

            t = threading.Thread(target=_held_entry, daemon=True)
            t.start()
            _wait_for(
                lambda: cli.counters["handoff_holds"] >= 1,
                what="handoff hold",
            )
            assert "verdict" not in held  # held, not answered

            b = Engine(initial_rows=256)
            b.set_flow_rules(rule())
            plane_b = IngestPlane(b)
            assert plane_b.attached and plane_b.engine_epoch == 2
            t.join(60.0)
            assert not t.is_alive(), "held entry never released"
            # 2 re-asserted live + this one = 3 <= count: admitted by
            # the SUCCESSOR, device-backed, zero policy verdicts.
            v = held["verdict"]
            assert v.admitted and not v.degraded
            assert cli.counters["policy_served"] == 0
            assert cli.counters["reconnects"] == 1
            snap = plane_b.snapshot()
            assert snap["counters"]["reasserts"] == 2
            b.flush()
            b.drain()
            assert b.cluster_node_stats("tr")["cur_thread_num"] == 3

            # Oracle differential: never-killed engine holding the same
            # 3 live admissions sees the same verdict stream.
            config.set(config.IPC_SHM_PREFIX, "")
            oracle = Engine(initial_rows=256)
            oracle.set_flow_rules(rule())
            for _ in range(3):
                oracle.submit_entry("tr")
            oracle.flush()
            oracle.drain()
            want = []
            for _ in range(3):
                op = oracle.submit_entry("tr")
                oracle.flush()
                oracle.drain()
                want.append((op.verdict.admitted, op.verdict.reason))
            got = []
            for _ in range(3):
                v = cli.entry("tr", timeout_ms=60000)
                got.append((v.admitted, int(v.reason)))
            assert got == want, (got, want)
            assert [g[0] for g in got] == [False, False, False]
            oracle.close()

            # Quiesce: exit the 3 live; device AND mirror exactly 0.
            for _ in range(3):
                cli.exit("tr")
            _wait_for(
                lambda: plane_b.snapshot()["counters"]["exits"] >= 3,
                what="exits drained",
            )
            b.flush()
            b.drain()
            assert b.cluster_node_stats("tr")["cur_thread_num"] == 0
            assert (
                b.speculative.mirror.snapshot()["live_threads"].get("tr", 0)
                == 0
            )
            assert cli.counters["policy_served"] == 0
        finally:
            cli.close()
            for o in (plane_b, b):
                if o is not None:
                    o.close()

    def test_handoff_hold_expires_to_policy_when_no_successor(self):
        """The bound on the hold: no successor ever attaches ->
        ``handoff.wait.ms`` expires and the caller gets an HONEST
        policy verdict (degraded), not an eternal block."""
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient

        _handoff_config(0)
        config.set(config.IPC_HANDOFF_WAIT_MS, "400")
        a = Engine(initial_rows=256)
        a.set_flow_rules([FlowRule("r", count=1e9)])
        plane_a = IngestPlane(a)
        cli = IngestClient(plane_a.channel(0), 0)
        try:
            assert cli.entry("r", timeout_ms=60000).admitted
            assert cli.exit("r")
            plane_a.handoff()
            a.close()
            t0 = time.monotonic()
            v = cli.entry("r", timeout_ms=60000)
            waited_ms = (time.monotonic() - t0) * 1e3
            assert v.degraded  # policy-served, honestly marked
            assert cli.counters["handoff_holds"] == 1
            assert cli.counters["policy_served"] == 1
            assert waited_ms >= 300  # actually held to the bound
        finally:
            cli.close()
            # plane_a/a already detached+closed by the handoff.


# ---------------------------------------------------------------------------
# capture-journal handoff semantics (satellite: orderly-close marker)
# ---------------------------------------------------------------------------
class TestCaptureOrderlyClose:
    def _boot(self, rules=True):
        eng = Engine(initial_rows=256)
        if rules:
            eng.set_flow_rules([FlowRule("cap-r", count=1e9)])
        return eng

    def test_orderly_marker_files_close_not_death(self, tmp_path):
        """A planned handoff's segments must survive as
        ``frozen-close-*`` — PR 19's next-boot death sweep must NOT
        misfile an orderly drain as a crash."""
        d = str(tmp_path / "cap")
        config.set(config.CAPTURE_ENABLED, "true")
        config.set(config.CAPTURE_DIR, d)
        eng1 = self._boot()
        assert eng1.capture is not None
        op = eng1.submit_entry("cap-r")
        eng1.flush()
        eng1.drain()
        assert op.verdict.admitted
        eng1.capture.mark_orderly_close("handoff")
        eng1.close()
        assert any(
            f.startswith("closed-") and f.endswith(".marker")
            for f in os.listdir(d)
        )

        eng2 = self._boot()  # successor: runs the preservation sweep
        try:
            names = os.listdir(d)
            assert any(n.startswith("frozen-close-") for n in names)
            assert not any(n.startswith("frozen-death-") for n in names)
            # Marker consumed: it must not whitewash a FUTURE crash.
            assert not any(n.endswith(".marker") for n in names)
        finally:
            eng2.close()

    def test_stale_marker_does_not_whitewash_later_crash(self, tmp_path):
        """Boot 1 drains orderly; boot 2 CRASHES (no marker). Boot 3's
        sweep must file boot 2's segments as death — the consumed
        marker from boot 1 grants no amnesty."""
        d = str(tmp_path / "cap")
        config.set(config.CAPTURE_ENABLED, "true")
        config.set(config.CAPTURE_DIR, d)
        eng1 = self._boot()
        eng1.capture.mark_orderly_close("handoff")
        eng1.close()

        eng2 = self._boot()
        op = eng2.submit_entry("cap-r")
        eng2.flush()
        eng2.drain()
        assert op.verdict.admitted
        # The crash: no mark_orderly_close — segments stay seg-*.cap
        # with no marker, exactly what kill -9 leaves behind.
        eng2.close()

        eng3 = self._boot()
        try:
            names = os.listdir(d)
            assert any(n.startswith("frozen-close-") for n in names)
            assert any(n.startswith("frozen-death-") for n in names)
        finally:
            eng3.close()

    def test_close_record_decodes(self, tmp_path):
        """The RK_CLOSE record is part of the stream (a reader that
        stops at unknown kinds would truncate everything after it)."""
        from sentinel_tpu.runtime import capture as cap_mod

        d = str(tmp_path / "cap")
        config.set(config.CAPTURE_ENABLED, "true")
        config.set(config.CAPTURE_DIR, d)
        eng = self._boot()
        boot_id = eng.capture.snapshot()["boot_id"]
        eng.capture.mark_orderly_close("recompile")
        eng.close()
        paths = cap_mod.capture_paths(d)
        decoded = cap_mod.decode_capture(paths)
        closes = [dat for kind, dat in decoded["stream"] if kind == "close"]
        assert closes and closes[0]["reason"] == "recompile"
        assert closes[0]["boot_id"] == boot_id


# ---------------------------------------------------------------------------
# sub-second death detection: the false-positive story
# ---------------------------------------------------------------------------
class TestDeathConfirmation:
    def _plane(self, dead_ms, confirm_ms):
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient

        config.set(config.IPC_SHM_PREFIX, f"stpu-fp-{uuid.uuid4().hex[:8]}")
        config.set(config.IPC_HEARTBEAT_MS, "50")
        config.set(config.IPC_ENGINE_DEAD_MS, str(dead_ms))
        config.set(config.IPC_ENGINE_DEAD_CONFIRM_MS, str(confirm_ms))
        eng = Engine(initial_rows=256)
        eng.set_flow_rules([FlowRule("fp", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        return eng, plane, cli

    def test_pegged_alive_engine_never_declared_dead(self):
        """Satellite: sub-second ``dead.ms`` + confirmation -> a
        busy-but-alive engine (heartbeat publisher starved, process
        fine, drainer fine) is NEVER flipped to the policy path. The
        poll-don't-snapshot stance from ``ipc_launch --smoke``: every
        single poll must say alive, not just the last one."""
        eng, plane, cli = self._plane(dead_ms=150, confirm_ms=10000)
        try:
            _wait_for(cli.engine_alive, what="first heartbeat")
            # Starve the heartbeat publisher (the pegged-box stand-in:
            # control thread not scheduled; process + drainer alive).
            plane._publish_control = lambda *a, **k: None
            # Poll through the stale window: alive on EVERY read (the
            # suspicion accounting moves only when a caller polls —
            # exactly the worker-side reality).
            deadline = time.monotonic() + 10.0
            while cli.counters["dead_suspicions"] == 0:
                assert cli.engine_alive(), "pegged-but-alive declared dead"
                assert time.monotonic() < deadline, "wall never went stale"
                time.sleep(0.01)
            for _ in range(50):
                assert cli.engine_alive(), "pegged-but-alive declared dead"
                time.sleep(0.005)
            assert cli.counters["dead_declared"] == 0
            # The drainer is untouched: verdicts stay device-backed.
            v = cli.entry("fp", timeout_ms=60000)
            assert v.admitted and not v.degraded
            assert cli.exit("fp")
            assert cli.counters["policy_served"] == 0
            # Heartbeat resumes: the episode closes as a COUNTED
            # would-have-been false positive.
            del plane._publish_control  # restore the class method
            deadline = time.monotonic() + 10.0
            while cli.counters["dead_false_alarms"] == 0:
                cli.engine_alive()
                assert time.monotonic() < deadline, "false alarm lost"
                time.sleep(0.01)
            assert cli.engine_alive()
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_confirmation_off_is_pr15_stale_wall_death(self):
        """Default-off pin: ``dead.confirm.ms=0`` (the default) keeps
        the PR-15 rule — stale wall IS death, no pid probe, no
        suspicion machinery."""
        eng, plane, cli = self._plane(dead_ms=150, confirm_ms=0)
        try:
            _wait_for(cli.engine_alive, what="first heartbeat")
            plane._publish_control = lambda *a, **k: None
            _wait_for(
                lambda: not cli.engine_alive(), what="stale-wall death"
            )
            assert cli.counters["dead_suspicions"] == 0
            assert cli.counters["dead_false_alarms"] == 0
        finally:
            del plane._publish_control
            cli.close()
            plane.close()
            eng.close()

    def test_dead_pid_declared_within_probe_window(self):
        """Confirmation must not DELAY detection of a really-dead
        engine: the pid probe fails -> declared on the first confirm
        pass, long before ``dead.ms + confirm.ms`` expires."""
        import subprocess

        # A pid that provably does not exist: spawn-and-reap.
        p = subprocess.Popen(["true"])
        p.wait()
        dead_pid = p.pid
        eng, plane, cli = self._plane(dead_ms=150, confirm_ms=60000)
        try:
            _wait_for(cli.engine_alive, what="first heartbeat")
            plane.control.set_engine_pid(dead_pid)
            plane.abandon()  # kill -9 surrogate: wall goes stale
            eng.close()
            t0 = time.monotonic()
            _wait_for(
                lambda: not cli.engine_alive(),
                timeout_s=10.0,
                what="confirmed death",
            )
            assert (time.monotonic() - t0) < 5.0  # not confirm-bounded
            assert cli.counters["dead_declared"] >= 1
        finally:
            cli.close()


# ---------------------------------------------------------------------------
# real processes: standby takeover + planned handoff (mp)
# ---------------------------------------------------------------------------
def _standby_config(tmp_path, depth):
    # Detection thresholds here are CI-loose, not product-tight: under
    # a full-suite run every process timeshares one loaded core, and a
    # sub-second dead.ms + the bounded confirm grace will (correctly)
    # declare a starved-but-alive engine dead — these tests pin the
    # takeover/handoff PROTOCOL, not the detection latency, which the
    # in-process TestDeathConfirmation covers with a frozen publisher.
    # worker.dead.ms is pinned high for the same reason: a descheduled
    # client beat thread must not get reaped mid-test (an auto-exit
    # would silently drop the re-asserted live admissions the parity
    # oracle expects).
    config.set(config.IPC_HEARTBEAT_MS, "50")
    config.set(config.IPC_ENGINE_DEAD_MS, "2000")
    config.set(config.IPC_ENGINE_DEAD_CONFIRM_MS, "1000")
    config.set(config.IPC_WORKER_DEAD_MS, "60000")
    config.set(config.IPC_HANDOFF_WAIT_MS, "30000")
    config.set(config.SUPERVISE_BACKOFF_MS, "200")
    config.set(config.SUPERVISE_STANDBY, "true")
    config.set(config.SUPERVISE_STANDBY_WARM_MS, "500")
    config.set(config.SPECULATIVE_ENABLED, "true")
    config.set(config.PIPELINE_DEPTH, str(depth))
    config.set(config.FAILOVER_ENABLED, "true")
    config.set(config.FAILOVER_CHECKPOINT_EVERY, "2")
    config.set(config.FAILOVER_CKPT_PATH, str(tmp_path / "ck.bin"))


@pytest.mark.mp
class TestStandbyChaos:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_kill9_standby_takeover_parity(self, depth, tmp_path):
        """kill -9 the PRIMARY with a warm standby armed: the watcher
        PROMOTES (takeover, not a cold restart — ``restarts`` stays 0),
        the client reconnects onto the standby's world, post-takeover
        THREAD verdicts match a never-killed oracle, and the behavioral
        gauges-are-0 probe (exactly ``count`` admits after quiesce)
        passes — at pipeline depths {0, 2}."""
        import ipc_procs
        from sentinel_tpu.ipc.supervise import EngineSupervisor
        from sentinel_tpu.ipc.worker import IngestClient

        _standby_config(tmp_path, depth)
        # Device-settled verdicts only: whether an old-world admission
        # was mirror-charged (spec) is timing-dependent, and the
        # successor's mirror is rebuilt from exactly the mirror-charged
        # reasserts (ipc/plane.py _apply_reasserts) — a spec-served
        # post-takeover verdict is settlement-reconciled optimism, not
        # the device truth the oracle computes. Exact parity is the
        # DEVICE contract; the speculative reassert seam is pinned by
        # the in-process mirror asserts in test_restart/this file.
        config.set(config.SPECULATIVE_ENABLED, "false")
        sup = EngineSupervisor(setup=ipc_procs.standby_setup, n_workers=1)
        cli = None
        try:
            assert sup.wait_engine_up(180), "primary never up"
            assert sup.wait_standby_ready(180), "standby never warm"
            cli = IngestClient(sup.handles.channel(0), 0)
            deadline = time.monotonic() + 120
            while True:
                v = cli.entry("chaos-res", timeout_ms=3000)
                if v.admitted and not v.degraded:
                    cli.exit("chaos-res")
                    break
                assert time.monotonic() < deadline, "no live verdict"
                time.sleep(0.02)
            # Two live THREAD admissions the takeover must carry. A
            # policy-served (degraded) verdict under a loaded box never
            # touches the ledger — retry until the ENGINE decided two
            # (the invariant is what the takeover carries, not that a
            # starved box never serves a policy verdict).
            charged, deadline = 0, time.monotonic() + 120
            while charged < 2:
                v = cli.entry("sb-thread", timeout_ms=30000)
                if v.admitted and not v.degraded:
                    charged += 1
                    continue
                assert not v.admitted or v.degraded
                assert time.monotonic() < deadline, "live charge stalled"
                time.sleep(0.02)

            assert sup.kill_engine() is not None
            # Probe until device-backed verdicts resume.
            deadline = time.monotonic() + 120
            while True:
                v = cli.entry("chaos-res", timeout_ms=3000)
                if v.admitted and not v.degraded:
                    cli.exit("chaos-res")
                    break
                assert time.monotonic() < deadline, "no takeover"
                time.sleep(0.002)
            _wait_for(
                lambda: sup.standby_takeovers >= 1,
                timeout_s=30,
                what="takeover accounting",
            )
            assert sup.restarts == 0, "cold respawn on the standby path"
            assert sup.standby_warm_boot_ms is not None
            _wait_for(
                lambda: cli.counters["reconnects"] >= 1,
                what="client reconnect",
            )

            # Oracle parity: never-killed engine, same 2 live THREADs.
            config.set(config.IPC_SHM_PREFIX, "")
            oracle = Engine(initial_rows=256)
            oracle.set_flow_rules(
                [FlowRule("sb-thread", count=3, grade=C.FLOW_GRADE_THREAD)]
            )
            for _ in range(2):
                oracle.submit_entry("sb-thread")
            oracle.flush()
            oracle.drain()
            want = []
            for _ in range(3):
                op = oracle.submit_entry("sb-thread")
                oracle.flush()
                oracle.drain()
                want.append((op.verdict.admitted, op.verdict.reason))
            # Engine-decided verdicts only: a transient policy verdict
            # on a starved box charges nothing and proves nothing —
            # retry it; the device sees exactly 3 decided probes.
            got, deadline = [], time.monotonic() + 120
            while len(got) < 3:
                v = cli.entry("sb-thread", timeout_ms=30000)
                if v.degraded:
                    assert time.monotonic() < deadline, "parity stalled"
                    time.sleep(0.02)
                    continue
                got.append((v.admitted, int(v.reason)))
            assert got == want, (got, want)
            assert [g[0] for g in got] == [True, False, False]
            oracle.close()

            # Quiesce (2 re-asserted + 1 admitted probe), then the
            # behavioral gauges-are-0 check: a remote engine whose
            # device or mirror gauge held residue would admit fewer
            # than count=3 here.
            for _ in range(3):
                cli.exit("sb-thread")
            deadline = time.monotonic() + 120
            while True:
                vs = [
                    cli.entry("sb-thread", timeout_ms=30000)
                    for _ in range(4)
                ]
                admits = [v.admitted for v in vs]
                for v in vs:
                    if v.admitted and not v.degraded:
                        cli.exit("sb-thread")
                if any(v.degraded for v in vs):
                    # A starved round proves nothing about gauges —
                    # only engine-decided rounds count.
                    admits = None
                elif admits == [True, True, True, False]:
                    break
                assert time.monotonic() < deadline, admits
                time.sleep(0.1)
        finally:
            if cli is not None:
                cli.close()
            sup.stop()

    def test_planned_handoff_soak_zero_policy_served(self, tmp_path):
        """The config-push cycle: continuous probing through an
        operator-triggered handoff — the standby takes over with ZERO
        policy-served / non-admitted verdicts (callers were held, never
        failed) and the supervisor counts it as a handoff, not a crash
        takeover or restart."""
        import ipc_procs
        from sentinel_tpu.ipc.supervise import measure_handoff_outage

        _standby_config(tmp_path, 0)
        config.set(config.IPC_CLIENT_WINDOW_MS, "0.5")
        out = measure_handoff_outage(
            ipc_procs.standby_setup, "chaos-res", timeout_s=200
        )
        assert out["handoffs"] == 1, out
        assert out["policy_served"] == 0, out
        assert out["not_admitted"] == 0, out
        assert out["reconnects"] >= 1, out
        assert out["handoff_outage_ms"] < 150_000, out
