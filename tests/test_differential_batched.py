"""Batched randomized differential: engine vs oracle on random-size flushes.

The sequential suite (test_differential.py) removes intra-batch
ordering from the picture; production mode is batched. This suite
replays the same kind of random streams grouped into random-size
flushes and asserts EXACT verdict equality against the sequential
oracle processing the flush in the engine's documented intra-batch
order:

* exits apply before entry checks (flush.py phase 1 vs phase 2) — on
  the mesh this holds across chips: the sharded step merges the
  post-exit stats globally and runs the breaker completion machine on
  the all-gathered completion set before any admission;
* entries touching a node are ordered by (ts, arrival index) — here
  all ops of one flush share a timestamp (a flush spans a few ms in
  production), so arrival order decides;
* per-node rank math is exact for uniform acquire + a node's own rule
  set (flush.py module docstring "Intra-batch sequencing").

The streams deliberately contain NO documented-deviation pattern: no
RELATE/cross-resource rules, no multi-origin split, no prioritized
(occupy) entries, and uniform acquire=1. Under those conditions any
divergence — in either direction — is a real intra-batch bug, which
is exactly what this suite exists to catch (it caught two on the mesh
in round 4: same-flush cross-chip thread releases invisible to
admission, and breaker trips whose crossing prefix spanned chips).

Execution: the streams run in fresh SUBPROCESSES (tests/
diffbatch_worker.py) because they are the suite's heaviest compile
generators and the toolchain segfaults on accumulated XLA:CPU LLVM
state (conftest.py) — a fresh process per engine mode keeps them well
under the horizon while the oracle logic stays importable here.

Reference analog: the partial-integration tests exercising the real
chain (sentinel-core/src/test/java/com/alibaba/csp/sentinel/slots/
block/flow/FlowPartialIntegrationTest.java).
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_models(kinds, rng):
    import dataclasses

    from tests.test_differential import _Model

    models = {}
    for kind in kinds:
        m = _Model(kind, rng)
        res = f"res-{kind}"
        if m.rule is not None:
            m.rule = dataclasses.replace(m.rule, resource=res)
        if m.prule is not None:
            m.prule = dataclasses.replace(m.prule, resource=res)
        models[res] = m
    return models


def _run_batched_stream(engine, models, rng, steps, ctx):
    """Random flushes of buffered ops; the oracle replays each flush in
    the engine's documented order (exits first, then entries by
    arrival) and every verdict + wait must match exactly."""
    resources = list(models)
    t = 1000
    open_entries = []
    checked = 0
    for step in range(steps):
        t += int(rng.integers(1, 900))
        engine.clock.set_ms(t)
        for m in models.values():
            m.node.materialize(t)

        # Sizes drawn from a fixed ladder: every value of a pow2 pad
        # bucket is reachable, but the number of DISTINCT compiled
        # shapes stays bounded — with fully random 1..64 sizes the
        # (entries, exits, shaping, param) pad-bucket product forces
        # dozens of one-off XLA compiles and the stream becomes
        # compile-bound.
        flush_n = int(rng.choice([1, 6, 14, 30, 62]))
        entries = []  # (res, op, value)
        exits = []  # (res, rt, err)
        for _ in range(flush_n):
            if rng.random() < 0.72 or not open_entries:
                res = resources[int(rng.integers(0, len(resources)))]
                m = models[res]
                value = f"v{int(rng.integers(0, 2))}"
                args = (value,) if m.prule is not None else ()
                op = engine.submit_entry(res, ts=t, args=args)
                entries.append((res, op, value))
            else:
                idx = int(rng.integers(0, len(open_entries)))
                res, op = open_entries.pop(idx)
                rt = int(rng.integers(1, 60))
                err = int(rng.random() < 0.35)
                engine.submit_exit(op.rows, rt=rt, ts=t, err=err, resource=res)
                exits.append((res, rt, err))
        engine.flush()

        # Oracle replay, engine order: all exits first, then entries in
        # arrival order. All ops share ts=t, so arrival order IS the
        # engine's (ts, arrival) sort order per node.
        for res, rt, err in exits:
            m = models[res]
            if m.breaker is not None:
                m.breaker.on_complete(t, rt, error=bool(err))
            m.account_exit(t, rt)
        for i, (res, op, value) in enumerate(entries):
            m = models[res]
            want, want_wait = m.decide(t, False, value)
            if want and m.breaker is not None:
                if not m.breaker.try_pass(t):
                    want, want_wait = False, 0
            assert op.verdict is not None, f"{ctx} step={step} op#{i}: undecided"
            assert op.verdict.admitted == want, (
                f"{ctx} step={step} op#{i} res={res} t={t} flush_n={flush_n}: "
                f"engine={op.verdict.admitted} oracle={want}"
            )
            assert op.verdict.wait_ms == want_wait, (
                f"{ctx} step={step} op#{i} res={res} t={t}: "
                f"wait engine={op.verdict.wait_ms} oracle={want_wait}"
            )
            m.account_entry(t, want, 0)
            if want:
                open_entries.append((res, op))
            checked += 1
    assert checked > steps  # flushes averaged > 1 entry

    # Window/gauge agreement at the end: a batching bug that cancels
    # out verdict-wise would still skew the accounting.
    for res, m in models.items():
        stats = engine.cluster_node_stats(res, flush=False)
        assert stats["block_qps"] == pytest.approx(m.node.block_qps(t), abs=1e-6), res
        assert stats["cur_thread_num"] == m.node.cur_thread_num, res


def _run_worker(mode: str, timeout_s: float) -> None:
    r = subprocess.run(
        [sys.executable, "-m", "tests.diffbatch_worker", mode],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=timeout_s,
    )
    tail = (r.stdout[-4000:] + "\n" + r.stderr[-4000:]).strip()
    assert r.returncode == 0, f"worker mode={mode} rc={r.returncode}:\n{tail}"


def test_random_batched_streams_match_oracle():
    """Five random single-chip streams, fresh process."""
    _run_worker("single", timeout_s=1800)


@pytest.mark.mesh
def test_random_batched_streams_match_oracle_on_mesh():
    """Two random mesh streams, fresh process."""
    _run_worker("mesh", timeout_s=1800)


def test_dense_serializing_streams_match_oracle():
    """Two streams concentrated on the serializing kinds (rate-limiter
    pacer + param throttle): large flushes over two resources drive the
    per-key recurrence through all three execution schedules (unroll,
    fori_loop, scan fallback) against the oracle."""
    _run_worker("dense", timeout_s=1800)
