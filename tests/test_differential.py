"""Randomized differential test: engine vs the Python oracle.

Sequential streams (one flush per op) must match the reference-model
oracle verdict-for-verdict — across random rule kinds (QPS / THREAD /
rate-limiter / warm-up / warm-up-rate-limiter / hot-param token bucket
/ hot-param throttle, plus an exception-ratio circuit breaker tripped
by random erroring exits), random clock advances spanning window
rolls, exits releasing threads, and prioritized (occupy) entries.
Sequential submission removes intra-batch ordering from the picture,
so any divergence is a real semantic bug, not a documented batching
conservatism.
"""

import dataclasses

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.testing.oracle import (
    OracleCircuitBreaker,
    OracleDefaultController,
    OracleNode,
    OracleParamBucket,
    OracleParamThrottle,
    OracleRateLimiter,
    OracleWarmUp,
    OracleWarmUpRateLimiter,
)


class _Model:
    """One resource's oracle: node + controller + accounting rules."""

    def __init__(self, kind: str, rng) -> None:
        self.kind = kind
        self.node = OracleNode()
        self.breaker = None
        self.drule = None
        self.prule = None
        if kind == "qps":
            self.count = int(rng.integers(1, 8))
            self.rule = st.FlowRule(resource="", count=self.count)
            self.ctrl = OracleDefaultController(self.count, grade=1)
            # The QPS resource also carries an exception-ratio breaker:
            # random erroring exits trip it mid-stream. The oracle is
            # built FROM the rule bean so the two cannot skew.
            self.drule = st.DegradeRule(
                resource="", grade=1, count=0.4, time_window=2,
                min_request_amount=4,
            )
            self.breaker = OracleCircuitBreaker(
                grade=self.drule.grade,
                count=self.drule.count,
                time_window_sec=self.drule.time_window,
                min_request=self.drule.min_request_amount,
            )
        elif kind == "thread":
            self.count = int(rng.integers(1, 5))
            self.rule = st.FlowRule(resource="", grade=0, count=self.count)
            self.ctrl = OracleDefaultController(self.count, grade=0)
        elif kind == "rl":
            self.count = int(rng.integers(2, 20))
            maxq = int(rng.integers(0, 600))
            self.rule = st.FlowRule(
                resource="", count=self.count,
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=maxq,
            )
            self.ctrl = OracleRateLimiter(self.count, maxq)
        elif kind == "warmup":
            self.count = int(rng.integers(10, 60))
            warmup = int(rng.integers(2, 8))
            self.rule = st.FlowRule(
                resource="", count=self.count,
                control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                warm_up_period_sec=warmup,
            )
            self.ctrl = OracleWarmUp(self.count, warmup)
        elif kind == "wurl":
            self.count = int(rng.integers(10, 60))
            warmup = int(rng.integers(2, 8))
            maxq = int(rng.integers(0, 800))
            self.rule = st.FlowRule(
                resource="", count=self.count,
                control_behavior=C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER,
                warm_up_period_sec=warmup,
                max_queueing_time_ms=maxq,
            )
            self.ctrl = OracleWarmUpRateLimiter(self.count, warmup, maxq)
        elif kind == "pbucket":
            self.count = int(rng.integers(1, 6))
            self.rule = None
            self.prule = st.ParamFlowRule(
                resource="", param_idx=0, count=self.count,
                burst_count=int(rng.integers(0, 4)),
                duration_in_sec=int(rng.integers(1, 4)),
            )
            self._values = {}
        else:  # pthrottle
            self.count = int(rng.integers(2, 12))
            self.rule = None
            self.prule = st.ParamFlowRule(
                resource="", param_idx=0, count=self.count,
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=int(rng.integers(0, 600)),
                duration_in_sec=int(rng.integers(1, 3)),
            )
            self._values = {}

    def param_model(self, value: str):
        """Per-value oracle, built FROM the rule bean so the two cannot
        skew (like the breaker)."""
        m = self._values.get(value)
        if m is None:
            r = self.prule
            if self.kind == "pbucket":
                m = OracleParamBucket(
                    int(r.count), int(r.burst_count), int(r.duration_in_sec) * 1000
                )
            else:
                m = OracleParamThrottle(
                    int(r.count), int(r.duration_in_sec), int(r.max_queueing_time_ms)
                )
            self._values[value] = m
        return m

    def decide(self, t: int, prio: bool, value: str = "") -> tuple:
        """Returns (admitted, wait_ms)."""
        if self.kind == "rl":
            return self.ctrl.can_pass(t)
        if self.kind == "wurl":
            return self.ctrl.can_pass_pacer(self.node, t)
        if self.kind == "warmup":
            return self.ctrl.can_pass(self.node, t), 0
        if self.kind == "pbucket":
            return self.param_model(value).check(t), 0
        if self.kind == "pthrottle":
            return self.param_model(value).check(t)
        if prio and self.kind == "qps":
            ok, wait, occupied = self.ctrl.can_pass_prio(self.node, t)
            return (ok, wait) if occupied else (ok, 0)
        return self.ctrl.can_pass(self.node, t), 0

    def account_entry(self, t: int, admitted: bool, occupied_wait: int) -> None:
        # Mirrors OracleFlowEngine.entry_prio's StatisticSlot branches
        # (testing/oracle.py) — that method is the authoritative model;
        # keep the two in sync if the PriorityWaitException accounting
        # ever changes (this copy exists because _Model also drives
        # shaping controllers OracleFlowEngine doesn't hold).
        if not admitted:
            self.node.add_block(t, 1)
            return
        self.node.cur_thread_num += 1
        if occupied_wait > 0:
            # can_pass_prio already recorded addWaitingRequest +
            # addOccupiedPass (the PriorityWaitException outcome).
            return
        self.node.add_pass(t, 1)

    def account_exit(self, t: int, rt: int) -> None:
        self.node.cur_thread_num -= 1
        self.node.add_rt_and_success(t, rt, 1)


def _load_rules(models):
    """Load flow/degrade/param rules for the models (keyed by resource)."""
    st.flow_rule_manager.load_rules(
        [m.rule for m in models.values() if m.rule is not None]
    )
    st.degrade_rule_manager.load_rules(
        [
            dataclasses.replace(m.drule, resource=res)
            for res, m in models.items()
            if m.drule is not None
        ]
    )
    st.param_flow_rule_manager.load_rules(
        [m.prule for m in models.values() if m.prule is not None]
    )


def _step_entry(engine, m, res, t, rng, allow_prio, ctx):
    """One entry op: oracle decision (flow → breaker, with the occupied
    bypass) vs engine verdict. Returns the op when admitted."""
    prio = allow_prio and m.kind == "qps" and rng.random() < 0.3
    value = f"v{int(rng.integers(0, 2))}"
    args = (value,) if m.prule is not None else ()
    want, want_wait = m.decide(t, prio, value)
    occupied = prio and want and want_wait > 0
    if want and m.breaker is not None and not occupied:
        # DegradeSlot runs last; occupied entries bypass it
        # (PriorityWaitException aborts the chain first).
        if not m.breaker.try_pass(t):
            want, want_wait = False, 0
    op = engine.submit_entry(res, ts=t, prio=prio, args=args)
    engine.flush()
    assert op.verdict.admitted == want, (
        f"{ctx} res={res} t={t} prio={prio}: "
        f"engine={op.verdict.admitted} oracle={want}"
    )
    assert op.verdict.wait_ms == want_wait, (
        f"{ctx} res={res} t={t}: wait engine={op.verdict.wait_ms} oracle={want_wait}"
    )
    m.account_entry(t, want, want_wait if prio else 0)
    return op if want else None


def _step_exit(engine, m, res, op, t, rng):
    """One exit op with a random RT and error bit, fed to both sides."""
    rt = int(rng.integers(1, 60))
    err = int(rng.random() < 0.35)
    engine.submit_exit(op.rows, rt=rt, ts=t, err=err, resource=res)
    engine.flush()
    if m.breaker is not None:
        m.breaker.on_complete(t, rt, error=bool(err))
    m.account_exit(t, rt)


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_random_sequential_stream_matches_oracle(seed, manual_clock, engine):
    rng = np.random.default_rng(seed)
    kinds = ["qps", "thread", "rl", "warmup", "wurl", "pbucket", "pthrottle"]
    rng.shuffle(kinds)
    models = {}
    for kind in kinds:
        m = _Model(kind, rng)
        res = f"res-{kind}"
        if m.rule is not None:
            m.rule = dataclasses.replace(m.rule, resource=res)
        if m.prule is not None:
            m.prule = dataclasses.replace(m.prule, resource=res)
        models[res] = m
    _load_rules(models)
    resources = list(models)

    t = 1000
    manual_clock.set_ms(t)
    open_entries = []
    checked = 0
    for step in range(200):
        t += int(rng.integers(0, 400))
        manual_clock.set_ms(t)
        # The engine materializes matured borrows at every flush; the
        # oracle must do the same where a flush happens.
        for m in models.values():
            m.node.materialize(t)
        if rng.random() < 0.72 or not open_entries:
            res = resources[int(rng.integers(0, len(resources)))]
            op = _step_entry(
                engine, models[res], res, t, rng, True, f"seed={seed} step={step}"
            )
            checked += 1
            if op is not None:
                open_entries.append((res, op))
        else:
            idx = int(rng.integers(0, len(open_entries)))
            res, op = open_entries.pop(idx)
            _step_exit(engine, models[res], res, op, t, rng)
    assert checked > 100

    # Final gauge + block-window stats agree too (pass windows involve
    # borrow-maturation bookkeeping asserted by tests/test_occupy.py).
    for res, m in models.items():
        stats = engine.cluster_node_stats(res, flush=False)
        assert stats["block_qps"] == pytest.approx(m.node.block_qps(t), abs=1e-6), res
        assert stats["cur_thread_num"] == m.node.cur_thread_num, res


@pytest.mark.mesh
def test_random_sequential_stream_matches_oracle_on_mesh(manual_clock, engine):
    """The same differential harness against the SHARDED engine: a
    sequential stream on the 8-device mesh must still match the oracle
    exactly (merges, demotion passes and the global scans collapse to
    the single-chip semantics when one op flushes at a time)."""
    engine.enable_mesh(8)
    rng = np.random.default_rng(7)
    models = {}
    for kind in ["qps", "thread", "rl"]:
        m = _Model(kind, rng)
        res = f"res-{kind}"
        m.rule = dataclasses.replace(m.rule, resource=res)
        models[res] = m
    _load_rules(models)
    resources = list(models)

    t = 1000
    manual_clock.set_ms(t)
    open_entries = []
    for step in range(60):
        t += int(rng.integers(0, 400))
        manual_clock.set_ms(t)
        for m in models.values():
            m.node.materialize(t)
        if rng.random() < 0.72 or not open_entries:
            res = resources[int(rng.integers(0, len(resources)))]
            op = _step_entry(
                engine, models[res], res, t, rng, False, f"mesh step={step}"
            )
            if op is not None:
                open_entries.append((res, op))
        else:
            idx = int(rng.integers(0, len(open_entries)))
            res, op = open_entries.pop(idx)
            _step_exit(engine, models[res], res, op, t, rng)

    # The merged (all-reduced) gauges and block windows must match too —
    # a merge that double-counted per device would pass every
    # sequential-stream verdict and only show up here.
    for res, m in models.items():
        stats = engine.cluster_node_stats(res, flush=False)
        assert stats["block_qps"] == pytest.approx(m.node.block_qps(t), abs=1e-6), res
        assert stats["cur_thread_num"] == m.node.cur_thread_num, res
