"""HTTP datasource family (Consul/Apollo/Eureka/Spring-Cloud-Config
shapes): conditional-GET polling and blocking-query long-polls against
an in-process HTTP config server.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource import HttpDataSource, HttpLongPollDataSource, json_converter


class ConfigServer:
    """Serves /config with ETag + Consul-style blocking on ?index."""

    def __init__(self):
        self.value = "[]"
        self.index = 1
        self.cond = threading.Condition()
        self.get_count = 0
        self.not_modified_count = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                parsed = urlparse(self.path)
                params = dict(parse_qsl(parsed.query))
                with outer.cond:
                    outer.get_count += 1
                    want_index = params.get("index")
                    if want_index is not None and int(want_index) >= outer.index:
                        # blocking query: hold until change or wait expiry
                        wait_s = float(params.get("wait", "30s").rstrip("s"))
                        outer.cond.wait_for(
                            lambda: outer.index > int(want_index), timeout=wait_s
                        )
                    body = outer.value.encode()
                    etag = f'"{outer.index}"'
                    if self.headers.get("If-None-Match") == etag:
                        outer.not_modified_count += 1
                        self.send_response(304)
                        self.end_headers()
                        return
                    self.send_response(200)
                    self.send_header("ETag", etag)
                    self.send_header("X-Consul-Index", str(outer.index))
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self._srv.server_address[1]}/config"

    def set_value(self, v):
        with self.cond:
            self.value = v
            self.index += 1
            self.cond.notify_all()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def _rules(count):
    return json.dumps([{"resource": "res", "count": count, "grade": 1}])


@pytest.fixture()
def config_server():
    s = ConfigServer()
    yield s
    s.stop()


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class TestHttpPolling:
    def test_poll_and_conditional_get(self, config_server):
        config_server.set_value(_rules(2))
        src = HttpDataSource(
            json_converter(st.FlowRule), config_server.url, refresh_interval_sec=0.05
        ).start()
        try:
            assert _wait(lambda: src.get_property().value
                         and src.get_property().value[0].count == 2)
            # Unchanged polls come back 304 (ETag round-trip).
            assert _wait(lambda: config_server.not_modified_count >= 2)
            config_server.set_value(_rules(7))
            assert _wait(lambda: src.get_property().value[0].count == 7)
        finally:
            src.close()


class TestHttpLongPoll:
    def test_blocking_query_pushes_on_change(self, config_server):
        config_server.set_value(_rules(1))
        src = HttpLongPollDataSource(
            json_converter(st.FlowRule), config_server.url, wait="1s",
            timeout_sec=5.0, retry_interval_sec=0.1,
        ).start()
        try:
            assert _wait(lambda: src.get_property().value
                         and src.get_property().value[0].count == 1)
            before = config_server.get_count
            config_server.set_value(_rules(9))
            assert _wait(lambda: src.get_property().value[0].count == 9)
            # The change arrived via a held blocking query, not a poll
            # storm: only a couple of requests were needed.
            assert config_server.get_count - before <= 3
        finally:
            src.close()

    def test_drives_rule_manager(self, config_server, manual_clock, engine):
        config_server.set_value(_rules(1))
        src = HttpLongPollDataSource(
            json_converter(st.FlowRule), config_server.url, wait="1s",
            timeout_sec=5.0, retry_interval_sec=0.1,
        ).start()
        try:
            st.flow_rule_manager.register_property(src.get_property())
            manual_clock.set_ms(100)
            assert st.try_entry("res") is not None
            assert st.try_entry("res") is None  # count=1 live
            config_server.set_value(_rules(3))
            assert _wait(lambda: any(
                r.count == 3 for r in (st.flow_rule_manager.get_rules() or [])
            ))
            manual_clock.set_ms(2000)
            admitted = sum(1 for _ in range(5) if st.try_entry("res") is not None)
            assert admitted == 3
        finally:
            src.close()
