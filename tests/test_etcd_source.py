"""EtcdDataSource against an in-process fake etcd v3 HTTP gateway —
same approach as the Redis RESP tests (fake server, real wire bytes).

Reference parity target: sentinel-extension/sentinel-datasource-etcd/
.../EtcdDataSource.java:41 (initial get + watch push), plus
WritableDataSource semantics.
"""

import base64
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource.base import json_converter
from sentinel_tpu.datasource.etcd_source import EtcdDataSource


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


class FakeEtcd(ThreadingHTTPServer):
    """kv/range + kv/put + watch (streaming, with start_revision
    replay from a retained event log)."""

    daemon_threads = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _Handler)
        self.port = self.server_address[1]
        self.lock = threading.Lock()
        self.data = {}  # key -> (value, mod_revision)
        self.revision = 0
        self.events = []  # (rev, key, type, value|None)
        self.watchers = []  # (key, queue)
        self.garbage_next_watch = False

    def put(self, key: str, value: str):
        with self.lock:
            self.revision += 1
            self.data[key] = (value, self.revision)
            ev = (self.revision, key, "PUT", value)
            self.events.append(ev)
            for k, q in self.watchers:
                if k == key:
                    q.put(ev)

    def delete(self, key: str):
        with self.lock:
            self.revision += 1
            self.data.pop(key, None)
            ev = (self.revision, key, "DELETE", None)
            self.events.append(ev)
            for k, q in self.watchers:
                if k == key:
                    q.put(ev)

    def kill_watchers(self):
        with self.lock:
            for _, q in self.watchers:
                q.put(None)  # poison: handler closes the stream


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.0"  # close-delimited: streams readline fine

    def log_message(self, *a):
        pass

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n) or b"{}")

    def _json(self, obj):
        raw = json.dumps(obj).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def do_POST(self):
        srv: FakeEtcd = self.server
        if self.path == "/v3/kv/range":
            key = _unb64(self._body()["key"])
            with srv.lock:
                hit = srv.data.get(key)
                rev = srv.revision
            kvs = []
            if hit:
                kvs = [{"key": _b64(key), "value": _b64(hit[0]),
                        "mod_revision": str(hit[1])}]
            self._json({"header": {"revision": str(rev)}, "kvs": kvs})
        elif self.path == "/v3/kv/put":
            b = self._body()
            srv.put(_unb64(b["key"]), _unb64(b["value"]))
            with srv.lock:
                rev = srv.revision
            self._json({"header": {"revision": str(rev)}})
        elif self.path == "/v3/watch":
            self._watch(srv)
        else:
            self.send_error(404)

    def _watch(self, srv: FakeEtcd):
        req = self._body()["create_request"]
        key = _unb64(req["key"])
        start_rev = int(req.get("start_revision", 0))
        q: queue.Queue = queue.Queue()
        with srv.lock:
            srv.watchers.append((key, q))
            replay = [e for e in srv.events
                      if e[1] == key and start_rev and e[0] >= start_rev]
            rev = srv.revision
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self._line({"result": {"created": True,
                                   "header": {"revision": str(rev)}}})
            if srv.garbage_next_watch:
                srv.garbage_next_watch = False
                self.wfile.write(b"{not json at all\n")
                self.wfile.flush()
                return
            for ev in replay:
                self._event(ev)
            while True:
                ev = q.get()
                if ev is None:
                    return  # killed
                self._event(ev)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with srv.lock:
                srv.watchers[:] = [(k, w) for k, w in srv.watchers if w is not q]

    def _line(self, obj):
        self.wfile.write(json.dumps(obj).encode() + b"\n")
        self.wfile.flush()

    def _event(self, ev):
        rev, key, typ, value = ev
        kv = {"key": _b64(key), "mod_revision": str(rev)}
        if value is not None:
            kv["value"] = _b64(value)
        self._line({"result": {
            "header": {"revision": str(rev)},
            "events": [{"type": typ, "kv": kv}],
        }})


def _rules_json(count):
    return json.dumps([{"resource": "res", "count": count}])


@pytest.fixture()
def fake_etcd():
    srv = FakeEtcd()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _wait(predicate, timeout=5.0):
    end = time.time() + timeout
    while time.time() < end:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _src(fake_etcd, **kw):
    kw.setdefault("reconnect_interval_sec", 0.05)
    return EtcdDataSource(
        json_converter(st.FlowRule), "sentinel.rules",
        endpoint=f"http://127.0.0.1:{fake_etcd.port}", **kw,
    )


class TestEtcdDataSource:
    def test_initial_load_and_watch_push(self, fake_etcd, manual_clock, engine):
        """Range seeds the rules; a put streams through the watch and
        live-swaps the engine table: push → converter → manager →
        engine."""
        fake_etcd.put("sentinel.rules", _rules_json(1))
        src = _src(fake_etcd).start()
        try:
            st.flow_rule_manager.register_property(src.get_property())
            manual_clock.set_ms(100)
            assert st.try_entry("res") is not None
            assert st.try_entry("res") is None  # count=1 enforced

            fake_etcd.put("sentinel.rules", _rules_json(5))
            assert _wait(
                lambda: any(
                    r.count == 5 for r in (st.flow_rule_manager.get_rules() or [])
                )
            ), "watched put never reached the manager"
            manual_clock.set_ms(2000)
            admitted = sum(1 for _ in range(8) if st.try_entry("res") is not None)
            assert admitted == 5
        finally:
            src.close()

    def test_write_round_trips(self, fake_etcd):
        src = _src(fake_etcd)
        src.write(_rules_json(7))
        assert json.loads(src.read_source())[0]["count"] == 7
        # And the write is visible to a second (watching) source.
        other = _src(fake_etcd).start()
        try:
            assert _wait(
                lambda: other.get_property().value
                and other.get_property().value[0].count == 7
            )
        finally:
            other.close()

    def test_reconnect_resumes_from_revision(self, fake_etcd):
        """Updates during a watch outage are replayed (start_revision
        resume) or recovered by the catch-up read — either way nothing
        is lost."""
        fake_etcd.put("sentinel.rules", _rules_json(1))
        src = _src(fake_etcd).start()
        try:
            assert _wait(lambda: fake_etcd.watchers)
            fake_etcd.kill_watchers()
            fake_etcd.put("sentinel.rules", _rules_json(9))
            assert _wait(
                lambda: src.get_property().value
                and src.get_property().value[0].count == 9
            ), "update during outage was lost"
        finally:
            src.close()

    def test_corrupted_stream_recovers(self, fake_etcd):
        """A garbage line on the watch stream drops the connection; the
        next stream (plus catch-up read) keeps applying updates."""
        fake_etcd.put("sentinel.rules", _rules_json(2))
        fake_etcd.garbage_next_watch = True
        src = _src(fake_etcd).start()
        try:
            fake_etcd.put("sentinel.rules", _rules_json(4))
            assert _wait(
                lambda: src.get_property().value
                and src.get_property().value[0].count == 4
            ), "source did not recover from a corrupted stream"
        finally:
            src.close()

    def test_delete_clears_value(self, fake_etcd):
        fake_etcd.put("sentinel.rules", _rules_json(3))
        src = _src(fake_etcd).start()
        try:
            assert _wait(lambda: src.get_property().value)
            fake_etcd.delete("sentinel.rules")
            assert _wait(lambda: src.get_property().value is None)
        finally:
            src.close()
