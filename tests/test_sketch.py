"""Sketch tier: count-min error bounds, candidate table, promotion/
demotion, O(1) device memory, failover mirror, exports.

The acceptance contract (ISSUE 9): a workload with >=100k distinct
unconfigured keys runs with O(1) device memory; its top hot keys are
auto-promoted to exact dense rows within a bounded number of flushes;
a promoted key's verdicts are bit-identical to a manually configured
dense rule from the promotion flush onward (pipeline depths {0, 2});
and the tier disabled is verdict-parity with today.
"""

from __future__ import annotations

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.models.rules import FlowRule, ParamFlowRule
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.runtime.sketch import (
    SketchBatch,
    cm_estimate,
    key_id,
    make_sketch_state,
    sketch_fold,
)
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import config


def _fold_stream(
    weights_by_key, width=1024, depth=4, cands=16, batch_size=256, decay_at=(),
):
    """Feed {key: weight} through the device fold in batches; returns
    (state, exact dict keyed by id)."""
    import jax.numpy as jnp

    state = make_sketch_state(depth, width, cands)
    items = [(key_id(k), w) for k, w in weights_by_key.items()]
    exact = {}
    step = 0
    for off in range(0, len(items), batch_size):
        chunk = items[off : off + batch_size]
        n = 1
        while n < max(len(chunk), 8):
            n <<= 1
        ids = np.full(n, -1, dtype=np.int32)
        w = np.zeros(n, dtype=np.int32)
        for j, (i, wt) in enumerate(chunk):
            ids[j] = i
            w[j] = wt
        decay = step in decay_at
        if decay:
            for i in list(exact):
                exact[i] >>= 1
        state = sketch_fold(
            state, SketchBatch(ids=jnp.asarray(ids), w=jnp.asarray(w)),
            decay=decay,
        )
        for i, wt in chunk:
            exact[i] = exact.get(i, 0) + wt
        step += 1
    return state, exact


class TestCountMinBounds:
    """Property tests of the device fold against exact host counts:
    the estimate is ALWAYS >= exact and within eps*N (eps = 8/width —
    loose vs the probabilistic 2/width-per-row bound, but deterministic
    for the fixed seeds) on adversarial distributions."""

    def _assert_bounds(self, weights_by_key, width=1024):
        state, exact = _fold_stream(weights_by_key, width=width)
        cm = np.asarray(state.cm)
        ids = np.asarray(sorted(exact), dtype=np.int64)
        est = cm_estimate(cm, ids)
        total = sum(exact.values())
        eps_n = max(1, (8 * total) // width)
        for i, e in zip(ids.tolist(), est.tolist()):
            assert e >= exact[i], f"count-min under-estimated id {i}"
            assert e - exact[i] <= eps_n, (
                f"id {i}: est {e} vs exact {exact[i]} exceeds eps*N={eps_n}"
            )

    def test_zipf(self):
        rng = np.random.default_rng(7)
        draws = rng.zipf(1.3, size=20000)
        weights = {}
        for v in draws.tolist():
            k = f"z{v}"
            weights[k] = weights.get(k, 0) + 1
        self._assert_bounds(weights)

    def test_all_distinct(self):
        self._assert_bounds({f"d{j}": 1 for j in range(5000)})

    def test_single_key(self):
        self._assert_bounds({"only": 123456})

    def test_candidate_table_holds_true_heavy_hitters(self):
        weights = {f"cold{j}": 1 for j in range(2000)}
        hot = {f"hot{j}": 500 + j for j in range(8)}
        weights.update(hot)
        state, exact = _fold_stream(weights, cands=16)
        ids = np.asarray(state.cand_ids).tolist()
        cnts = np.asarray(state.cand_cnt).tolist()
        by_id = {i: c for i, c in zip(ids, cnts) if i >= 0}
        for k, w in hot.items():
            i = key_id(k)
            assert i in by_id, f"heavy hitter {k} missing from candidates"
            assert by_id[i] >= w  # estimate >= exact
        # Candidate counts are count-min estimates as of the key's last
        # touch (the CM+candidate design): never above the current
        # point query (cells only grow between touches), and never
        # below the key's exact count when the key rode the final batch.
        cm = np.asarray(state.cm)
        for i, c in by_id.items():
            assert c <= int(cm_estimate(cm, np.asarray([i]))[0])

    def test_decay_halves_counts(self):
        import jax.numpy as jnp

        state = make_sketch_state(2, 64, 4)
        ids = np.full(8, -1, dtype=np.int32)
        w = np.zeros(8, dtype=np.int32)
        ids[0] = key_id("k")
        w[0] = 1000
        sb = SketchBatch(ids=jnp.asarray(ids), w=jnp.asarray(w))
        state = sketch_fold(state, sb, decay=False)
        empty = SketchBatch(
            ids=jnp.full((8,), -1, dtype=jnp.int32),
            w=jnp.zeros((8,), dtype=jnp.int32),
        )
        state = sketch_fold(state, empty, decay=True)
        est = int(cm_estimate(np.asarray(state.cm), np.asarray([key_id("k")]))[0])
        assert est == 500
        assert int(np.asarray(state.cand_cnt).max()) == 500


@pytest.fixture()
def sketch_config():
    """Arm the sketch tier with fast promotion for engine tests; the
    tier reads config at Engine construction."""
    config.set(config.SKETCH_ENABLED, "true")
    config.set(config.SKETCH_PROMOTE_QPS, "5")
    config.set(config.SKETCH_RESOURCE_QPS, "50")
    config.set(config.SKETCH_WINDOW_MS, "1000")
    config.set(config.SKETCH_DEMOTE_WINDOWS, "2")
    try:
        yield
    finally:
        for key in (
            config.SKETCH_ENABLED, config.SKETCH_PROMOTE_QPS,
            config.SKETCH_RESOURCE_QPS, config.SKETCH_WINDOW_MS,
            config.SKETCH_DEMOTE_WINDOWS,
        ):
            config.set(key, config.DEFAULTS[key])


def _sketch_rule(count=3.0):
    return ParamFlowRule(
        resource="api", param_idx=0, count=count, sketch_mode=True
    )


def _drive_until_promoted(eng, clk, hot="HOT", max_windows=6):
    """Feed hot+cold traffic until the tier promotes ``hot``; returns
    the number of flushes it took (bounded — that IS the assertion)."""
    flushes = 0
    for step in range(max_windows * 4):
        col = [(f"cold{step}_{j}",) for j in range(32)] + [(hot,)] * 32
        eng.submit_bulk("api", n=64, args_column=col)
        eng.flush()
        eng.drain()
        flushes += 1
        if hot in eng.sketch.promoted_values.get("api", ()):
            return flushes
        clk.advance(250)
    raise AssertionError(f"{hot} not promoted within {flushes} flushes")


class TestParamPromotion:
    def test_promoted_within_bounded_flushes(self, sketch_config):
        clk = ManualClock()
        eng = Engine(clock=clk)
        eng.set_param_rules({"api": [_sketch_rule()]})
        flushes = _drive_until_promoted(eng, clk)
        assert flushes <= 16
        # Cold values never interned a dense row; the promoted one does
        # at its first post-promotion resolve.
        assert eng.param_index.n_rows == 0
        eng.submit_bulk("api", n=4, args_column=[("HOT",)] * 4)
        eng.flush()
        eng.drain()
        assert eng.param_index.n_rows == 1
        eng.close()

    @pytest.mark.parametrize("depth", [0, 2])
    def test_promoted_key_matches_configured_dense_rule(
        self, sketch_config, depth
    ):
        """The acceptance differential: from the promotion flush
        onward, the promoted key's verdicts are BIT-IDENTICAL to a
        manually configured dense rule seeing the same stream."""
        clk = ManualClock()
        eng_a = Engine(clock=clk)
        eng_a.pipeline_depth = depth
        eng_a.set_param_rules({"api": [_sketch_rule(count=3.0)]})
        _drive_until_promoted(eng_a, clk)
        eng_a.drain()

        # Engine B: plain dense rule configured AT the promotion
        # boundary (pre-boundary history is all-pass on both sides, so
        # the comparison stream starts from identical rule state).
        config.set(config.SKETCH_ENABLED, "false")
        eng_b = Engine(clock=clk)
        eng_b.pipeline_depth = depth
        eng_b.set_param_rules(
            {"api": [ParamFlowRule(resource="api", param_idx=0, count=3.0)]}
        )
        config.set(config.SKETCH_ENABLED, "true")

        groups = []
        for step in range(12):
            col = [("HOT",)] * 4 + [(f"post{step}_{j}",) for j in range(4)]
            ga = eng_a.submit_bulk("api", n=8, args_column=col)
            gb = eng_b.submit_bulk("api", n=8, args_column=col)
            eng_a.flush()
            eng_b.flush()
            groups.append((ga, gb))
            clk.advance(170)
        eng_a.drain()
        eng_b.drain()
        for ga, gb in groups:
            # Only the promoted key's rows are comparable (cold rows
            # pass in A by design, are dense-checked in B).
            np.testing.assert_array_equal(
                ga.admitted[:4], gb.admitted[:4]
            )
            np.testing.assert_array_equal(ga.reason[:4], gb.reason[:4])
        eng_a.close()
        eng_b.close()

    def test_demotion_releases_dense_row(self, sketch_config):
        clk = ManualClock()
        eng = Engine(clock=clk)
        eng.set_param_rules({"api": [_sketch_rule()]})
        _drive_until_promoted(eng, clk)
        eng.submit_bulk("api", n=4, args_column=[("HOT",)] * 4)
        eng.flush()
        eng.drain()
        assert eng.param_index.n_rows == 1
        # Go cold: windows pass with no HOT traffic at all (the
        # promoted count decays geometrically, then demote.windows
        # consecutive cold windows must accumulate).
        for _ in range(12):
            eng.submit_bulk("api", n=8, args_column=[("c",)] * 8)
            eng.flush()
            eng.drain()
            clk.advance(1100)
        assert "HOT" not in eng.sketch.promoted_values.get("api", ())
        # The row was released back to the recycle pool.
        eng.flush()
        assert "HOT" not in eng.param_index._values[0]
        c = eng.telemetry.counters_snapshot()
        assert c["sketch_promotions"] >= 1
        assert c["sketch_demotions"] >= 1
        eng.close()


class TestUnboundedCardinality:
    def test_100k_distinct_keys_o1_device_memory(self, sketch_config):
        """>=100k distinct unconfigured keys: device state stays at the
        sketch's fixed capacity, no dense rows materialize for cold
        keys, and the hot key still promotes out of the noise."""
        clk = ManualClock()
        eng = Engine(clock=clk)
        eng.set_param_rules({"api": [_sketch_rule()]})
        tier = eng.sketch
        cm_shape = np.asarray(tier.dev_state.cm).shape
        stats_rows = eng.stats.n_rows
        seen = 0
        step = 0
        while seen < 100_000:
            n = 25_000
            col = [(f"u{seen + j}",) for j in range(n - 50)] + [("HOT",)] * 50
            eng.submit_bulk("api", n=n, args_column=col)
            eng.flush()
            eng.drain()
            seen += n - 50
            step += 1
            clk.advance(400)
        assert seen >= 100_000
        # O(1) device growth: sketch shape fixed, stats rows untouched,
        # param rows = promoted keys only (0 or 1), not 100k.
        assert np.asarray(tier.dev_state.cm).shape == cm_shape
        assert eng.stats.n_rows == stats_rows
        assert eng.param_index.n_rows <= 1
        assert eng.param_dyn.tokens.shape[0] == 8  # initial, never grown
        assert "HOT" in tier.promoted_values.get("api", ())
        # Host side stays bounded too: the id->name LRU obeys its cap.
        assert len(tier._names) <= tier.names_cap
        eng.close()


class TestResourcePromotion:
    def test_unconfigured_resource_gets_synthetic_rule_and_demotes(
        self, sketch_config
    ):
        clk = ManualClock()
        eng = Engine(clock=clk)
        for _ in range(6):
            eng.submit_bulk("burst", n=256)
            eng.flush()
            eng.drain()
            clk.advance(400)
        rules = {r.resource: r for r in eng.flow_index.get_rules()}
        assert "burst" in rules and rules["burst"].from_sketch
        assert rules["burst"].count == 50.0
        g = eng.submit_bulk("burst", n=200)
        eng.flush()
        eng.drain()
        assert int(g.admitted.sum()) <= 50  # the synthetic guard bites
        # Demotion: the decayed count must fall below the floor, then
        # demote.windows consecutive cold windows accumulate.
        for _ in range(10):
            eng.submit_bulk("other", n=8)
            eng.flush()
            eng.drain()
            clk.advance(1100)
        eng.flush()
        assert "burst" not in {r.resource for r in eng.flow_index.get_rules()}
        eng.close()

    def test_over_cap_resource_promotes_past_the_cap(self, sketch_config):
        clk = ManualClock()
        eng = Engine(clock=clk)
        eng.nodes.max_resources = 2
        eng.submit_bulk("r1", n=1)
        eng.submit_bulk("r2", n=1)  # cap reached (+ the entry node)
        assert eng.submit_bulk("capped-hot", n=64) is None  # pass-through
        for _ in range(6):
            # Pass-through (None) until the promotion grants the row
            # mid-loop; after that, ops flow normally.
            eng.submit_bulk("capped-hot", n=256)
            eng.flush()
            eng.drain()
            clk.advance(400)
        # Promotion granted the row the cap refused: ops now flow and
        # the synthetic rule guards them.
        assert "capped-hot" in {
            r.resource for r in eng.flow_index.get_rules()
        }
        g = eng.submit_bulk("capped-hot", n=200)
        assert g is not None
        eng.flush()
        eng.drain()
        assert int(g.admitted.sum()) <= 50
        eng.close()

    def test_past_cap_grants_are_cumulatively_budgeted(self, sketch_config):
        """Registry rows granted past the cap are permanent, so a churn
        of distinct over-cap heavy hitters must stop drawing new rows
        at the cumulative budget (8x promote.max) instead of regrowing
        unbounded per-key state through the promotion door."""
        from sentinel_tpu.models.rules import FlowRule as FR

        clk = ManualClock()
        eng = Engine(clock=clk)
        tier = eng.sketch
        eng.nodes.max_resources = 0  # everything is over-cap
        tier.promote_max = 1  # budget = 8
        with tier._lock:
            for i in range(12):
                tier._promoted_res[f"churn{i}"] = FR(
                    resource=f"churn{i}", count=50.0, from_sketch=True
                )
            tier._actions.append(("flow", None))
        tier.apply_actions()
        installed = {r.resource for r in eng.flow_index.get_rules()}
        assert len(installed) == 8  # budget, not all 12
        assert len(tier._cap_grants) == 8
        # Dropped promotions were evicted from the promoted set too.
        assert len(tier._promoted_res) == 8
        eng.close()

    def test_user_reload_reasserts_synthetics(self, sketch_config):
        clk = ManualClock()
        eng = Engine(clock=clk)
        for _ in range(6):
            eng.submit_bulk("burst", n=256)
            eng.flush()
            eng.drain()
            clk.advance(400)
        assert "burst" in {r.resource for r in eng.flow_index.get_rules()}
        # A user reload wipes synthetics; the controller re-asserts on
        # its next pass.
        eng.set_flow_rules([FlowRule("user-res", count=100)])
        assert "burst" not in {r.resource for r in eng.flow_index.get_rules()}
        for _ in range(3):
            eng.submit_bulk("burst", n=256)
            eng.flush()
            eng.drain()
            clk.advance(400)
        names = {r.resource for r in eng.flow_index.get_rules()}
        assert "burst" in names and "user-res" in names
        eng.close()


class TestDisabledParity:
    def test_sketch_mode_rule_is_dense_when_tier_disabled(self):
        """With the tier off, sketch_mode is ignored: the rule
        dense-tracks every value exactly like a plain rule (verdict
        parity with today)."""
        clk = ManualClock()
        eng_a = Engine(clock=clk)
        eng_a.set_param_rules({"api": [_sketch_rule(count=2.0)]})
        eng_b = Engine(clock=clk)
        eng_b.set_param_rules(
            {"api": [ParamFlowRule(resource="api", param_idx=0, count=2.0)]}
        )
        for step in range(6):
            col = [("x",)] * 4 + [(f"v{step}",)] * 2
            ga = eng_a.submit_bulk("api", n=6, args_column=col)
            gb = eng_b.submit_bulk("api", n=6, args_column=col)
            eng_a.flush()
            eng_b.flush()
            np.testing.assert_array_equal(ga.admitted, gb.admitted)
            clk.advance(300)
        eng_a.close()
        eng_b.close()

    def test_disarmed_engine_has_no_sketch_state(self):
        eng = Engine(clock=ManualClock())
        assert not eng.sketch.armed
        assert eng.sketch.dev_state is None
        eng.submit_bulk("res", n=8)
        eng.flush()
        eng.close()


class TestFailoverMirror:
    def test_degraded_folds_into_host_mirror(self, sketch_config):
        from sentinel_tpu.testing.faults import FaultInjector

        config.set(config.FAILOVER_ENABLED, "true")
        try:
            clk = ManualClock()
            eng = Engine(clock=clk)
            eng.set_param_rules({"api": [_sketch_rule()]})
            eng.submit_bulk("api", n=8, args_column=[("warm",)] * 8)
            eng.flush()
            faults = FaultInjector().install(eng)
            faults.fail_fetch(eng.flush_seq + 1)
            eng.submit_bulk("api", n=8, args_column=[("warm",)] * 8)
            eng.flush()  # trips DEGRADED (fetch fault, armed)
            assert not eng.failover.healthy
            # Degraded chunks feed the host mirror; promotion still
            # happens from it.
            for step in range(6):
                col = [(f"c{step}_{j}",) for j in range(16)] + [("HOT",)] * 48
                eng.submit_bulk("api", n=64, args_column=col)
                eng.flush()
                clk.advance(400)
            mirror_keys = {
                k.split("\x1f")[-1]
                for k in eng.sketch.host_mirror.counts
            }
            assert "HOT" in mirror_keys
            assert "HOT" in eng.sketch.promoted_values.get("api", ())
            c = eng.telemetry.counters_snapshot()
            assert c["sketch_host_folds"] >= 1
            eng.close()
        finally:
            config.set(
                config.FAILOVER_ENABLED,
                config.DEFAULTS[config.FAILOVER_ENABLED],
            )


class TestExports:
    def test_prometheus_families_and_command(self, sketch_config):
        from sentinel_tpu.transport.prometheus import render_metrics

        clk = ManualClock()
        eng = Engine(clock=clk)
        eng.set_param_rules({"api": [_sketch_rule()]})
        _drive_until_promoted(eng, clk)
        text = render_metrics(eng)
        for fam in (
            "sentinel_engine_sketch_enabled",
            "sentinel_engine_sketch_keys_total",
            "sentinel_engine_sketch_promotions_total",
            "sentinel_engine_sketch_demotions_total",
            "sentinel_engine_sketch_host_folds_total",
            "sentinel_engine_sketch_promoted",
            "sentinel_engine_sketch_occupancy",
            "sentinel_engine_sketch_est_error_ratio",
        ):
            assert fam in text, f"missing family {fam}"
        snap = eng.sketch.snapshot()
        assert snap["promoted_values"] == {"api": ["HOT"]}
        assert 0 < snap["occupancy"] <= 1.0
        assert snap["est_error_ratio"] >= 0.0
        assert any(
            c["key"] == "api|HOT" for c in snap["candidates_topk"]
        )
        eng.close()

    def test_telemetry_snapshot_carries_tier(self, sketch_config):
        clk = ManualClock()
        eng = Engine(clock=clk)
        eng.submit_bulk("res", n=8)
        eng.flush()
        out = eng.telemetry.snapshot(eng)
        assert "sketch_tier" in out
        eng.close()

    def test_export_topk_unified_default(self):
        """The former hand-rolled ``sketch_k or 10``: one config-backed
        home shared by every export."""
        from sentinel_tpu.metrics.telemetry import TelemetryBus

        bus = TelemetryBus(enabled=True, sketch_k=0)
        assert bus.export_topk_k == 10
        config.set(config.TELEMETRY_TOPK_EXPORT, "7")
        try:
            assert bus.export_topk_k == 7
        finally:
            config.set(
                config.TELEMETRY_TOPK_EXPORT,
                config.DEFAULTS[config.TELEMETRY_TOPK_EXPORT],
            )
        bus2 = TelemetryBus(enabled=True, sketch_k=5)
        assert bus2.export_topk_k == 5
        # Deprecated aliases still read the renamed fields.
        assert bus2.sketch_k == bus2.blocked_topk_k == 5
        assert bus2.sketch is bus2.blocked_sketch


class TestColumnarKeyPath:
    """The vectorized host key path (PR-9's named follow-up): columnar
    CRC32 ids bit-identical to zlib, the bounded id-memo, and encode
    parity with a per-key twin."""

    def test_crc32_batch_differential(self):
        import random
        import string
        import zlib

        from sentinel_tpu.runtime.sketch import crc32_batch

        rng = random.Random(11)
        keys = [""]
        for _ in range(2000):
            n = rng.randint(0, 48)
            keys.append(
                "".join(rng.choice(string.printable) for _ in range(n))
            )
        keys += ["é¿ሴ日本語", "\x01res\x1fval", "v" * 300]
        raw = [k.encode("utf-8", "surrogatepass") for k in keys]
        got = crc32_batch(raw)
        want = np.array([zlib.crc32(b) for b in raw], dtype=np.uint32)
        assert (got == want).all()
        # Prefix-seeded streaming (the per-column init state).
        pc = zlib.crc32(b"\x02api\x1f")
        got2 = crc32_batch(raw, init=pc)
        want2 = np.array([zlib.crc32(b, pc) for b in raw], dtype=np.uint32)
        assert (got2 == want2).all()

    def test_ids_match_key_id_and_memo_bounded(self):
        from sentinel_tpu.runtime.sketch import key_id

        config.set(config.SKETCH_ENABLED, "true")
        config.set(config.SKETCH_NAMES_CAP, "256")
        try:
            eng = Engine(clock=ManualClock(1000))
            tier = eng.sketch
            prefix = "\x02api\x1f"
            tails = [f"v{i}" for i in range(64)]
            with tier._lock:
                ids = tier._ids_for_locked(prefix, tails)
                # Memo hits return the identical ids.
                ids2 = tier._ids_for_locked(prefix, tails)
            assert (ids == ids2).all()
            for t, i in zip(tails, ids.tolist()):
                assert i == key_id(prefix + t)
            # Overflowing the bound clears the memo, never corrupts ids.
            with tier._lock:
                tier._ids_for_locked(
                    prefix, [f"x{i}" for i in range(300)]
                )
                assert tier._id_memo_n <= 300
                ids3 = tier._ids_for_locked(prefix, tails)
            assert (ids3 == ids).all()
            eng.close()
        finally:
            config.set(config.SKETCH_ENABLED, config.DEFAULTS[config.SKETCH_ENABLED])
            config.set(
                config.SKETCH_NAMES_CAP, config.DEFAULTS[config.SKETCH_NAMES_CAP]
            )

    def test_encode_chunk_aggregation_parity(self):
        """The columnar collect (np.unique/bincount + memoized CRC)
        aggregates bit-identically to a per-key hash twin over a mixed
        bulk stream (repeats, ints, Nones)."""
        from sentinel_tpu.runtime.sketch import key_id

        config.set(config.SKETCH_ENABLED, "true")
        config.set(config.SKETCH_PROMOTE_QPS, "100")
        try:
            eng = Engine(clock=ManualClock(1000))
            rule = ParamFlowRule(
                resource="api", param_idx=0, count=1e9, sketch_mode=True
            )
            eng.set_param_rules({"api": [rule]})
            vals = ["a", "b", "a", None, "c", "b", "a", 7, 7, "d"] * 3
            g = eng.submit_bulk("api", n=len(vals),
                                args_column=[(v,) for v in vals])
            assert g is not None
            ids, w = eng.sketch.encode_chunk(
                [], [g], eng.flow_index, eng.param_index
            )
            # Per-key twin.
            want = {}
            for v in vals:
                if v is None:
                    continue
                i = key_id("\x02api\x1f" + str(v))
                want[i] = want.get(i, 0) + 1
            got = {
                int(i): int(wt) for i, wt in zip(ids, w) if i >= 0
            }
            assert got == want
            eng.flush()
            eng.drain()
            eng.close()
        finally:
            for k in (config.SKETCH_ENABLED, config.SKETCH_PROMOTE_QPS):
                config.set(k, config.DEFAULTS[k])


@pytest.fixture()
def cold_config():
    """Arm the cold-key admission ceiling alone: promotion disarmed, so
    every decision comes from the count-min estimate (ISSUE 13
    satellite — the admit-by-estimate gap HashPipe leaves open)."""
    config.set(config.SKETCH_ENABLED, "true")
    config.set(config.SKETCH_WINDOW_MS, "1000")
    config.set(config.SKETCH_COLD_QPS, "10")
    try:
        yield
    finally:
        for key in (
            config.SKETCH_ENABLED, config.SKETCH_WINDOW_MS,
            config.SKETCH_COLD_QPS,
        ):
            config.set(key, config.DEFAULTS[key])


class TestColdKeyCeiling:
    """sentinel.tpu.sketch.cold.qps: estimated-QPS ceiling on
    unpromoted, unconfigured resources. Ceiling at qps=10, window 1 s
    -> the twin estimate blocks at >= 2 * 10 * 1 = 20."""

    def _hot(self, eng, clk, n=64):
        g = eng.submit_bulk("coldhot", n=n)
        eng.flush()
        eng.drain()
        return g

    def test_hot_cold_key_blocked_then_decays_back(self, cold_config):
        from sentinel_tpu.core import errors as E

        clk = ManualClock(1000)
        eng = Engine(clock=clk)
        # First batch: the twin has never seen the key — passes (and
        # feeds the estimate past the ceiling).
        g = self._hot(eng, clk)
        assert g is not None and g.admitted.all()
        # Now every submit is blocked at the door: bulk, single, and
        # the deferred batch path all route through the ceiling.
        g2 = eng.submit_bulk("coldhot", n=8)
        assert not g2.admitted.any()
        assert g2.reason.tolist() == [E.BLOCK_SKETCH] * 8
        op = eng.submit_entry("coldhot")
        assert op.verdict.reason == E.BLOCK_SKETCH
        assert op.verdict.limit_type == "cold"
        many = eng.submit_many([{"resource": "coldhot"}] * 3)
        assert all(o.verdict.reason == E.BLOCK_SKETCH for o in many)
        assert eng.sketch.cold_blocks >= 12
        c = eng.telemetry.counters_snapshot()
        assert c["sketch_cold_blocks"] == eng.sketch.cold_blocks
        # Nothing was enqueued for the blocked traffic.
        assert not eng.has_pending()
        # Blocked traffic is NOT counted, so per-window halving decays
        # the estimate back under the ceiling and admission resumes
        # (the duty-cycle that approximates the ceiling rate).
        for _ in range(3):
            clk.advance(1100)
            eng.submit_bulk("other", n=1)
            eng.flush()
            eng.drain()
        g3 = eng.submit_bulk("coldhot", n=4)
        assert g3 is not None, "ceiling must lift after decay"
        eng.flush()
        eng.drain()
        assert g3.admitted.all()
        eng.close()

    def test_configured_and_promoted_resources_exempt(self, cold_config):
        clk = ManualClock(1000)
        eng = Engine(clock=clk)
        eng.set_flow_rules([FlowRule(resource="ruled", count=1e9)])
        for _ in range(3):
            g = eng.submit_bulk("ruled", n=64)
            eng.flush()
            eng.drain()
            assert g.admitted.all()  # user rules exempt at any volume
        # A tier-promoted resource is exempt too: the exact dense row
        # owns it from the promotion on.
        eng.sketch._promoted_res["promoted"] = FlowRule(
            resource="promoted", count=1e9, from_sketch=True
        )
        assert not eng.sketch.cold_blocked(
            "promoted", eng.flow_index, eng.param_index
        )
        eng.close()

    def test_over_cap_class_is_covered(self, cold_config):
        from sentinel_tpu.core import errors as E

        clk = ManualClock(1000)
        eng = Engine(clock=clk)
        eng.nodes.max_resources = 1
        eng.submit_bulk("takes-cap", n=1)
        # Over the cap: pass-through while cold...
        assert eng.submit_bulk("capped", n=64) is None
        eng.flush()
        eng.drain()
        # ...but once the estimate crosses the ceiling, the formerly
        # zero-protection class gets blocked verdicts.
        g = eng.submit_bulk("capped", n=8)
        assert g is not None and not g.admitted.any()
        assert g.reason.tolist() == [E.BLOCK_SKETCH] * 8
        op = eng.submit_entry("capped")
        assert op.verdict.reason == E.BLOCK_SKETCH
        eng.close()

    def test_enforced_while_degraded_from_host_twin(self, cold_config):
        """DEGRADED keeps the ceiling: the twin is fed by the SAME
        _collect the host fold runs, so losing the device loses
        nothing."""
        from sentinel_tpu.core import errors as E
        from sentinel_tpu.testing.faults import FaultInjector

        config.set(config.FAILOVER_ENABLED, "true")
        try:
            clk = ManualClock(1000)
            eng = Engine(clock=clk)
            eng.submit_bulk("warm", n=1)
            eng.flush()
            faults = FaultInjector().install(eng)
            faults.fail_fetch(eng.flush_seq + 1)
            eng.submit_bulk("warm", n=1)
            eng.flush()  # trips DEGRADED
            assert not eng.failover.healthy
            g = eng.submit_bulk("degraded-hot", n=64)
            eng.flush()  # host fold feeds the twin
            assert g.admitted.all()
            g2 = eng.submit_bulk("degraded-hot", n=8)
            assert not g2.admitted.any()
            assert g2.reason.tolist() == [E.BLOCK_SKETCH] * 8
            eng.close()
        finally:
            config.set(
                config.FAILOVER_ENABLED,
                config.DEFAULTS[config.FAILOVER_ENABLED],
            )

    def test_default_off_is_cold_pass(self):
        config.set(config.SKETCH_ENABLED, "true")
        try:
            clk = ManualClock(1000)
            eng = Engine(clock=clk)
            assert not eng.sketch.cold_armed  # cold.qps default 0
            for _ in range(4):
                g = eng.submit_bulk("anything", n=256)
                eng.flush()
                eng.drain()
                assert g.admitted.all()  # today's cold-pass behavior
            assert eng.sketch.cold_blocks == 0
            eng.close()
        finally:
            config.set(config.SKETCH_ENABLED,
                       config.DEFAULTS[config.SKETCH_ENABLED])

    def test_degrade_only_resource_exempt(self, cold_config):
        """Regression (review): 'no user rule of any kind' includes
        degrade rules — a breaker-guarded resource must never be
        throttled by the approximate cold path."""
        from sentinel_tpu.models.rules import DegradeRule

        clk = ManualClock(1000)
        eng = Engine(clock=clk)
        eng.set_degrade_rules(
            [DegradeRule(resource="breakered", count=1e9,
                         time_window=1)]
        )
        for _ in range(3):
            g = eng.submit_bulk("breakered", n=64)
            eng.flush()
            eng.drain()
            assert g.admitted.all()
        assert eng.sketch.cold_blocks == 0
        eng.close()


class TestColdValueCeiling:
    """sentinel.tpu.sketch.cold.qps extended to sketch-mode param
    VALUES (ISSUE 14 satellite): an unpromoted cold value of a
    sketch_mode rule — which has NO dense row and previously passed
    unthrottled at any volume — blocks at the same admit-by-estimate
    ceiling, from the same host count-min twin (so DEGRADED keeps it),
    with default 0 = parity."""

    def _engine(self, clk):
        eng = Engine(clock=clk)
        eng.set_param_rules(
            {
                "api": [
                    ParamFlowRule(
                        resource="api", param_idx=0, count=1e9,
                        sketch_mode=True,
                    )
                ]
            }
        )
        return eng

    def _heat(self, eng, value, n=64):
        g = eng.submit_bulk("api", n=n, args_column=[(value,)] * n)
        eng.flush()
        eng.drain()
        return g

    def test_hot_cold_value_blocked_other_values_pass(self, cold_config):
        from sentinel_tpu.core import errors as E

        clk = ManualClock(1000)
        eng = self._engine(clk)
        # First batch passes (cold twin empty) and feeds the estimate
        # past the ceiling (2 * 10 qps * 1 s = 20).
        g = self._heat(eng, "hot-ip")
        assert g is not None and g.admitted.all()
        # Singles on the hot value now refuse at the door with the
        # distinct value-grade attribution; nothing is enqueued.
        op = eng.submit_entry("api", args=("hot-ip",))
        assert op.verdict.reason == E.BLOCK_SKETCH
        assert op.verdict.limit_type == "cold_value"
        assert not eng.has_pending()
        # A DIFFERENT cold value on the same rule is untouched.
        op2 = eng.submit_entry("api", args=("cold-ip",))
        assert op2.verdict is None or op2.verdict.reason != E.BLOCK_SKETCH
        eng.flush()
        eng.drain()
        assert eng.sketch.cold_value_blocks >= 1
        c = eng.telemetry.counters_snapshot()
        assert c["sketch_cold_blocks"] == eng.sketch.cold_blocks
        eng.close()

    def test_bulk_full_block_dense_and_partial_declines(self, cold_config):
        from sentinel_tpu.core import errors as E

        clk = ManualClock(1000)
        eng = self._engine(clk)
        assert self._heat(eng, "hot-ip").admitted.all()
        # Uniform hot-value group: refused dense, never enqueued.
        g = eng.submit_bulk("api", n=6, args_column=[("hot-ip",)] * 6)
        assert not g.admitted.any()
        assert g.reason.tolist() == [E.BLOCK_SKETCH] * 6
        assert not eng.has_pending()
        # Mixed group: per-row verdicts need per-entry routing — the
        # same decline contract as the other bulk-refusing rule
        # classes (the columnar spine falls back to submit_entry).
        with pytest.raises(ValueError):
            eng.submit_bulk(
                "api", n=2, args_column=[("hot-ip",), ("cold-ip",)]
            )
        # The submit_many routing enforces per-op: hot blocked, cold
        # passes, on the same call.
        ops = eng.submit_many(
            [
                {"resource": "api", "args": ("hot-ip",)},
                {"resource": "api", "args": ("cold-ip",)},
            ]
        )
        assert ops[0].verdict.reason == E.BLOCK_SKETCH
        assert ops[1]._verdict is None  # enqueued, not refused
        eng.flush()
        eng.drain()
        assert ops[1].verdict.admitted
        eng.close()

    def test_promoted_value_exempt_and_decay_lifts(self, cold_config):
        clk = ManualClock(1000)
        eng = self._engine(clk)
        assert self._heat(eng, "hot-ip").admitted.all()
        # Promotion grants the exact dense row: the approximate
        # ceiling must never touch a promoted value.
        eng.sketch.promoted_values = {"api": frozenset({"hot-ip"})}
        op = eng.submit_entry("api", args=("hot-ip",))
        assert op._verdict is None  # enqueued normally
        eng.flush()
        eng.drain()
        assert op.verdict.admitted
        eng.sketch.promoted_values = {}
        op = eng.submit_entry("api", args=("hot-ip",))
        assert op.verdict is not None and not op.verdict.admitted
        # Blocked traffic never feeds back: halving decay lifts the
        # ceiling again (the per-value duty cycle).
        for _ in range(3):
            clk.advance(1100)
            eng.submit_bulk("other", n=1)
            eng.flush()
            eng.drain()
        g = eng.submit_bulk("api", n=4, args_column=[("hot-ip",)] * 4)
        assert g is not None
        eng.flush()
        eng.drain()
        assert g.admitted.all()
        eng.close()

    def test_enforced_while_degraded(self, cold_config):
        from sentinel_tpu.core import errors as E
        from sentinel_tpu.testing.faults import FaultInjector

        config.set(config.FAILOVER_ENABLED, "true")
        try:
            clk = ManualClock(1000)
            eng = self._engine(clk)
            eng.submit_bulk("warm", n=1)
            eng.flush()
            faults = FaultInjector().install(eng)
            faults.fail_fetch(eng.flush_seq + 1)
            eng.submit_bulk("warm", n=1)
            eng.flush()  # trips DEGRADED
            assert not eng.failover.healthy
            g = self._heat(eng, "deg-ip")  # host fold feeds the twin
            assert g.admitted.all()
            g2 = eng.submit_bulk(
                "api", n=8, args_column=[("deg-ip",)] * 8
            )
            assert not g2.admitted.any()
            assert g2.reason.tolist() == [E.BLOCK_SKETCH] * 8
            eng.close()
        finally:
            config.set(
                config.FAILOVER_ENABLED,
                config.DEFAULTS[config.FAILOVER_ENABLED],
            )

    def test_default_zero_is_parity(self):
        config.set(config.SKETCH_ENABLED, "true")
        try:
            clk = ManualClock(1000)
            eng = self._engine(clk)
            assert not eng.sketch.cold_armed
            for _ in range(4):
                g = eng.submit_bulk(
                    "api", n=128, args_column=[("v",)] * 128
                )
                eng.flush()
                eng.drain()
                assert g.admitted.all()
            assert eng.sketch.cold_value_blocks == 0
            eng.close()
        finally:
            config.set(config.SKETCH_ENABLED,
                       config.DEFAULTS[config.SKETCH_ENABLED])
