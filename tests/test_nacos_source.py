"""NacosDataSource against an in-process fake Nacos config server —
fake server, real wire semantics: the 0x02/0x01-separated
Listening-Configs long poll with MD5 drift detection.

Reference parity target: sentinel-extension/sentinel-datasource-nacos/
.../NacosDataSource.java:42 (initial get + listener push), plus
WritableDataSource semantics.
"""

import hashlib
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource.base import json_converter
from sentinel_tpu.datasource.nacos_source import NacosDataSource


def _md5(s: str) -> str:
    return hashlib.md5(s.encode()).hexdigest()


class FakeNacos(ThreadingHTTPServer):
    """configs get/publish + the listener long poll."""

    daemon_threads = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _Handler)
        self.port = self.server_address[1]
        self.cond = threading.Condition()
        self.configs = {}  # (dataId, group) -> content
        self.fail_next_poll = False

    def publish(self, data_id: str, group: str, content: str):
        with self.cond:
            self.configs[(data_id, group)] = content
            self.cond.notify_all()

    def remove(self, data_id: str, group: str):
        with self.cond:
            self.configs.pop((data_id, group), None)
            self.cond.notify_all()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def handle(self):
        try:
            super().handle()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client killed a held poll (close()) — expected

    def _reply(self, code: int, body: bytes):
        self.send_response(code)
        self.send_header("Content-Type", "text/plain;charset=UTF-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv: FakeNacos = self.server
        parsed = urlparse(self.path)
        if parsed.path != "/nacos/v1/cs/configs":
            self.send_error(404)
            return
        q = parse_qs(parsed.query)
        key = (q["dataId"][0], q["group"][0])
        with srv.cond:
            content = srv.configs.get(key)
        if content is None:
            self._reply(404, b"config data not exist")
        else:
            self._reply(200, content.encode())

    def do_POST(self):
        srv: FakeNacos = self.server
        parsed = urlparse(self.path)
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n).decode()
        if parsed.path == "/nacos/v1/cs/configs":
            form = parse_qs(body)
            srv.publish(form["dataId"][0], form["group"][0], form["content"][0])
            self._reply(200, b"true")
        elif parsed.path == "/nacos/v1/cs/configs/listener":
            self._listener(srv, body)
        else:
            self.send_error(404)

    def _listener(self, srv: FakeNacos, body: str):
        with srv.cond:
            if srv.fail_next_poll:
                srv.fail_next_poll = False
                self.send_error(500)
                return
        # Body: Listening-Configs=<urlencoded dataId^2group^2md5[^2tenant]^1>
        listening = unquote(body.split("=", 1)[1])
        entry = listening.split("\x01")[0]
        parts = entry.split("\x02")
        data_id, group, md5 = parts[0], parts[1], parts[2]
        timeout_ms = int(self.headers.get("Long-Pulling-Timeout", "30000"))
        deadline = time.time() + min(timeout_ms / 1000.0, 2.0)  # capped for tests
        with srv.cond:
            while True:
                content = srv.configs.get((data_id, group))
                cur = _md5(content) if content is not None else ""
                if cur != md5:
                    changed = f"{data_id}\x02{group}\x01"
                    break
                remaining = deadline - time.time()
                if remaining <= 0:
                    changed = ""
                    break
                srv.cond.wait(remaining)
        from urllib.parse import quote

        self._reply(200, quote(changed).encode() if changed else b"")


def _rules_json(count):
    return json.dumps([{"resource": "res", "count": count}])


@pytest.fixture()
def fake_nacos():
    srv = FakeNacos()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _wait(predicate, timeout=5.0):
    end = time.time() + timeout
    while time.time() < end:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _src(fake_nacos, **kw):
    kw.setdefault("reconnect_interval_sec", 0.05)
    kw.setdefault("long_poll_timeout_ms", 1000)
    return NacosDataSource(
        json_converter(st.FlowRule), "sentinel-rules",
        endpoint=f"http://127.0.0.1:{fake_nacos.port}", **kw,
    )


class TestNacosDataSource:
    def test_initial_load_and_listener_push(self, fake_nacos, manual_clock, engine):
        """Get seeds the rules; a publish releases the long poll (MD5
        drift) and live-swaps the engine table."""
        fake_nacos.publish("sentinel-rules", "DEFAULT_GROUP", _rules_json(1))
        src = _src(fake_nacos).start()
        try:
            st.flow_rule_manager.register_property(src.get_property())
            manual_clock.set_ms(100)
            assert st.try_entry("res") is not None
            assert st.try_entry("res") is None  # count=1 enforced

            fake_nacos.publish("sentinel-rules", "DEFAULT_GROUP", _rules_json(5))
            assert _wait(
                lambda: any(
                    r.count == 5 for r in (st.flow_rule_manager.get_rules() or [])
                )
            ), "listener push never reached the manager"
            manual_clock.set_ms(2000)
            admitted = sum(1 for _ in range(8) if st.try_entry("res") is not None)
            assert admitted == 5
        finally:
            src.close()

    def test_write_round_trips(self, fake_nacos):
        src = _src(fake_nacos)
        src.write(_rules_json(9))
        rules = src.load_config()
        assert len(rules) == 1 and rules[0].count == 9
        src.close()

    def test_missing_config_reads_none(self, fake_nacos):
        src = _src(fake_nacos)
        assert src.read_source() is None
        src.close()

    def test_remove_pushes_none(self, fake_nacos):
        fake_nacos.publish("sentinel-rules", "DEFAULT_GROUP", _rules_json(2))
        src = _src(fake_nacos).start()
        try:
            assert _wait(lambda: src.get_property()._value)
            fake_nacos.remove("sentinel-rules", "DEFAULT_GROUP")
            assert _wait(lambda: not src.get_property()._value), (
                "removal never propagated"
            )
        finally:
            src.close()

    def test_outage_recovers_and_catches_up(self, fake_nacos):
        fake_nacos.publish("sentinel-rules", "DEFAULT_GROUP", _rules_json(1))
        src = _src(fake_nacos).start()
        try:
            assert _wait(lambda: src.get_property()._value)
            fake_nacos.fail_next_poll = True
            fake_nacos.publish("sentinel-rules", "DEFAULT_GROUP", _rules_json(7))
            assert _wait(
                lambda: any(r.count == 7 for r in (src.get_property()._value or []))
            ), "update during outage was lost"
        finally:
            src.close()

    def test_close_unblocks_inflight_poll_promptly(self, fake_nacos):
        fake_nacos.publish("sentinel-rules", "DEFAULT_GROUP", _rules_json(1))
        src = _src(fake_nacos, long_poll_timeout_ms=30000).start()
        try:
            assert _wait(lambda: src._poll_conn is not None), "poll never started"
        finally:
            t0 = time.time()
            src.close()
            assert time.time() - t0 < 1.5, "close blocked on the long poll"
        assert not src._thread.is_alive()

    def test_oversized_body_rejected(self, fake_nacos, monkeypatch):
        import sentinel_tpu.datasource.nacos_source as mod

        monkeypatch.setattr(mod, "MAX_BODY_BYTES", 64)
        fake_nacos.publish("sentinel-rules", "DEFAULT_GROUP", "x" * 200)
        src = _src(fake_nacos)
        with pytest.raises(ValueError, match="size cap"):
            src.read_source()
        src.close()

    def test_tenant_rides_in_listener_and_configs(self, fake_nacos):
        """Tenant-scoped source round-trips (the fake ignores tenant,
        but the request paths must stay well-formed)."""
        src = _src(fake_nacos, tenant="ns1")
        src.write(_rules_json(3))
        assert len(src.load_config()) == 1
        src.close()
