"""The bench harness's wedge-survival pieces are themselves tested:
the salvage parser (what the parent keeps from a killed child) and a
real timed-out subprocess exercising the full _spawn_stage path.
"""

import json
import subprocess
import sys

import bench


class TestLastJsonLine:
    def test_none_and_empty(self):
        assert bench._last_json_line(None) is None
        assert bench._last_json_line("") is None
        assert bench._last_json_line(b"") is None

    def test_picks_last_json(self):
        out = "\n".join(
            [json.dumps({"a": 1}), "[bench] progress noise", json.dumps({"b": 2})]
        )
        assert bench._last_json_line(out) == {"b": 2}

    def test_bytes_and_partial_garbage_tail(self):
        # The kill can truncate the last line mid-write; the previous
        # complete record must still be recovered.
        out = (json.dumps({"ok": 1}) + "\n" + '{"trunca').encode()
        assert bench._last_json_line(out) == {"ok": 1}

    def test_error_records_are_not_salvaged(self):
        assert bench._last_json_line(json.dumps({"error": "boom"})) is None
        # ...but an earlier good record still wins.
        out = json.dumps({"ok": 1}) + "\n" + json.dumps({"error": "x"})
        assert bench._last_json_line(out) == {"ok": 1}

    def test_non_dict_json_ignored(self):
        assert bench._last_json_line("[1, 2, 3]") is None


def test_spawn_timeout_salvages_partial(monkeypatch):
    """End to end through _spawn_stage: a child that prints one JSON
    line and then hangs is killed at the timeout, and its printed
    record comes back instead of None."""
    real_run = subprocess.run

    def fake_run(cmd, **kw):
        # Replace the bench child with a hang-after-print stub, keeping
        # the real subprocess+timeout machinery (incl. the kill path).
        stub = [
            sys.executable,
            "-c",
            "import json,sys,time;"
            "print(json.dumps({'engine_ops_per_sec': 42.0}), flush=True);"
            "time.sleep(60)",
        ]
        return real_run(stub, **kw)

    monkeypatch.setattr(subprocess, "run", fake_run)
    out = bench._spawn_stage(16, 16, 1, "cpu", timeout_s=3.0)
    assert out == {"engine_ops_per_sec": 42.0}


class TestTransportExists:
    def test_non_axon_layouts_assume_yes(self, monkeypatch):
        monkeypatch.delenv("AXON_LOOPBACK_RELAY", raising=False)
        assert bench._transport_exists() is True

    def test_axon_without_relay_process(self, monkeypatch):
        monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")

        def fake_ps(cmd, **kw):
            class R:
                # A diagnostic grep mentioning the relay must NOT count
                # as the relay being alive.
                stdout = "PID ARGS\npython somethingelse\ngrep .relay.py\n"
            return R()

        monkeypatch.setattr(subprocess, "run", fake_ps)
        assert bench._transport_exists() is False

    def test_axon_with_relay_process(self, monkeypatch):
        monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")

        def fake_ps(cmd, **kw):
            class R:
                stdout = "python3 -u /root/.relay.py\n"
            return R()

        monkeypatch.setattr(subprocess, "run", fake_ps)
        assert bench._transport_exists() is True

    def test_ps_failure_probes_normally(self, monkeypatch):
        monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")

        def boom(cmd, **kw):
            raise OSError("no ps")

        monkeypatch.setattr(subprocess, "run", boom)
        assert bench._transport_exists() is True
