"""Admission tracing (metrics/admission_trace.py): W3C trace-context
parse/propagate/inject, the sampled per-admission provenance ring, its
differential parity against settled verdicts at pipeline depths {0, 2},
the ``traces`` transport command (+ the shared validated-int fix for
``telemetry ?spans=``), and OpenMetrics exemplars on the e2e latency
buckets."""

import asyncio
import json
import threading
import time

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import errors as E
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.metrics.admission_trace import (
    AdmissionTracer,
    TraceContext,
    inject_trace_headers,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)
from sentinel_tpu.utils.config import config

TP = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"


class TestTraceparent:
    def test_parse_roundtrip(self):
        tc = parse_traceparent(TP, "vendor=x")
        assert tc is not None
        assert tc.trace_id == "0af7651916cd43dd8448eb211c80319c"
        assert tc.span_id == "b7ad6b7169203331"
        assert tc.sampled is True
        assert tc.tracestate == "vendor=x"
        assert tc.to_traceparent() == TP

    def test_unsampled_flag(self):
        tc = parse_traceparent(TP[:-2] + "00")
        assert tc is not None and tc.sampled is False
        assert tc.to_traceparent().endswith("-00")

    def test_child_keeps_trace_id_fresh_span(self):
        tc = parse_traceparent(TP)
        child = tc.child()
        assert child.trace_id == tc.trace_id
        assert child.span_id != tc.span_id
        assert len(child.span_id) == 16
        assert child.sampled == tc.sampled

    @pytest.mark.parametrize(
        "bad",
        [
            None,
            "",
            "garbage",
            "00-abc-def-01",  # short fields
            "00-" + "0" * 32 + "-b7ad6b7169203331-01",  # zero trace id
            "00-0af7651916cd43dd8448eb211c80319c-" + "0" * 16 + "-01",
            "ff-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",
            "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01-extra",
            "0x-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",
            "00-0af7651916cd43dd8448eb211c80319X-b7ad6b7169203331-01",
        ],
    )
    def test_invalid_rejected(self, bad):
        assert parse_traceparent(bad) is None

    def test_future_version_accepted_with_extra_fields(self):
        # W3C forward compatibility: parse the four base fields.
        tc = parse_traceparent(
            "cc-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01-future"
        )
        assert tc is not None and tc.sampled

    def test_id_generators_shape(self):
        assert len(new_trace_id()) == 32 and int(new_trace_id(), 16) > 0
        assert len(new_span_id()) == 16 and int(new_span_id(), 16) > 0


class TestContextCarrier:
    def test_ambient_set_get_reset(self):
        tc = parse_traceparent(TP)
        assert ContextUtil.get_trace() is None
        token = ContextUtil.set_trace(tc)
        try:
            assert ContextUtil.get_trace() is tc
        finally:
            ContextUtil.reset_trace(token)
        assert ContextUtil.get_trace() is None

    def test_context_object_carries_trace_across_threads(self, engine):
        """run_on_context hand-off: the Context OBJECT carries the
        trace, so a worker thread resuming the context sees it."""
        tc = parse_traceparent(TP)
        token = ContextUtil.set_trace(tc)
        ctx = ContextUtil.enter("trace_ctx_thread", "o")
        seen = []
        try:
            t = threading.Thread(
                target=lambda: ContextUtil.run_on_context(
                    ctx, lambda: seen.append(ContextUtil.get_trace())
                )
            )
            t.start()
            t.join()
        finally:
            ContextUtil.exit()
            ContextUtil.reset_trace(token)
        assert seen == [tc]

    def test_asyncio_tasks_inherit_trace(self):
        tc = parse_traceparent(TP)

        async def drive():
            token = ContextUtil.set_trace(tc)
            try:
                return await asyncio.gather(
                    *(_child() for _ in range(3))
                )
            finally:
                ContextUtil.reset_trace(token)

        async def _child():
            await asyncio.sleep(0)
            return ContextUtil.get_trace()

        assert asyncio.run(drive()) == [tc, tc, tc]

    def test_nested_set_reset_restores_context_trace(self, engine):
        """A nested set/reset pair (decorator inside an adapter) must
        RESTORE the Context's prior trace, not strip it — the Context
        object is the cross-thread carrier."""
        outer = parse_traceparent(TP)
        tok_outer = ContextUtil.set_trace(outer)
        ctx = ContextUtil.enter("nested_trace_ctx", "")
        try:
            tok_inner = ContextUtil.set_trace(None)  # extractor found none
            assert ContextUtil.get_trace() is None
            ContextUtil.reset_trace(tok_inner)
            assert ctx.trace is outer  # restored on the OBJECT
            assert ContextUtil.get_trace() is outer
        finally:
            ContextUtil.exit()
            ContextUtil.reset_trace(tok_outer)

    def test_inject_no_ambient_is_noop(self):
        hdrs = {}
        assert inject_trace_headers(hdrs) is None
        assert hdrs == {}

    def test_inject_creates_child(self):
        token = ContextUtil.set_trace(parse_traceparent(TP, "v=1"))
        try:
            hdrs = {}
            child = inject_trace_headers(hdrs)
        finally:
            ContextUtil.reset_trace(token)
        assert child is not None
        out = parse_traceparent(hdrs["traceparent"], hdrs.get("tracestate", ""))
        assert out.trace_id == "0af7651916cd43dd8448eb211c80319c"
        assert out.span_id != "b7ad6b7169203331"
        assert hdrs["tracestate"] == "v=1"


class TestSamplingModes:
    def _drive(self, engine, tracer, n=6, count=2.0):
        engine.admission_trace = tracer
        st.flow_rule_manager.load_rules([st.FlowRule("sm", count=count)])
        ops = engine.submit_many(
            [{"resource": "sm", "ts": 100} for _ in range(n)]
        )
        engine.flush()
        return ops

    def test_rate_zero_records_only_blocked(self, manual_clock, engine):
        ops = self._drive(engine, AdmissionTracer(sample_rate=0.0))
        blocked = sum(1 for op in ops if not op.verdict.admitted)
        recs = engine.admission_trace.records()
        assert blocked > 0
        assert len(recs) == blocked
        assert all(not r.admitted and not r.head_sampled for r in recs)
        assert all(r.reason_name == "FlowException" for r in recs)

    def test_rate_one_records_everything(self, manual_clock, engine):
        ops = self._drive(engine, AdmissionTracer(sample_rate=1.0), n=5)
        recs = engine.admission_trace.records()
        assert len(recs) == 5
        assert sum(r.admitted for r in recs) == sum(
            1 for op in ops if op.verdict.admitted
        )

    def test_blocked_mode_off_rate_zero_records_nothing(
        self, manual_clock, engine
    ):
        self._drive(
            engine, AdmissionTracer(sample_rate=0.0, sample_blocked=False)
        )
        assert engine.admission_trace.records() == []
        assert engine.admission_trace.counters_snapshot()["skipped"] > 0

    def test_disabled_tags_nothing(self, manual_clock, engine):
        engine.admission_trace = AdmissionTracer(enabled=False)
        st.flow_rule_manager.load_rules([st.FlowRule("dis", count=0)])
        op = engine.submit_entry("dis")
        assert op.trace is None  # one bool read, no tag allocation
        engine.flush()
        assert engine.admission_trace.records() == []

    def test_inbound_sampled_flag_is_the_head_decision(
        self, manual_clock, engine
    ):
        engine.admission_trace = AdmissionTracer(sample_rate=0.0)
        st.flow_rule_manager.load_rules([st.FlowRule("hd", count=1e9)])
        token = ContextUtil.set_trace(parse_traceparent(TP))  # flag 01
        try:
            engine.submit_entry("hd")
            engine.flush()
        finally:
            ContextUtil.reset_trace(token)
        recs = engine.admission_trace.records()
        assert len(recs) == 1 and recs[0].admitted and recs[0].head_sampled
        assert recs[0].trace_id == "0af7651916cd43dd8448eb211c80319c"
        assert recs[0].parent_span_id == "b7ad6b7169203331"
        # Flag 00 -> admitted traffic not recorded even at rate 1.
        engine.admission_trace = AdmissionTracer(sample_rate=1.0)
        token = ContextUtil.set_trace(parse_traceparent(TP[:-2] + "00"))
        try:
            engine.submit_entry("hd")
            engine.flush()
        finally:
            ContextUtil.reset_trace(token)
        assert engine.admission_trace.records() == []

    def test_ring_bounded(self, manual_clock, engine):
        engine.admission_trace = AdmissionTracer(sample_rate=1.0, ring=4)
        st.flow_rule_manager.load_rules([st.FlowRule("rb", count=1e9)])
        engine.submit_many([{"resource": "rb", "ts": 1} for _ in range(9)])
        engine.flush()
        assert len(engine.admission_trace.records()) == 4
        assert engine.admission_trace.counters_snapshot()["recorded"] == 9


class TestDifferentialParity:
    """Acceptance: for every sampled blocked admission, the recorded
    (reason, resource, flush seq) matches a recount from the settled
    verdicts — at pipeline depths 0 AND 2, where verdicts materialize
    only at a later flush's drain."""

    @pytest.mark.parametrize("depth", [0, 2])
    def test_records_match_settled_verdicts(self, manual_clock, depth):
        from sentinel_tpu.runtime.engine import Engine

        eng = Engine(clock=manual_clock)
        eng.admission_trace = AdmissionTracer(sample_rate=1.0)
        eng.pipeline_depth = depth
        eng.set_flow_rules(
            [st.FlowRule("hot", count=2), st.FlowRule("free", count=1e9)]
        )
        batches = []
        for b in range(4):
            t = 1000 + b * 1000  # fresh window per batch
            manual_clock.set_ms(t)
            reqs = [{"resource": "hot", "ts": t} for _ in range(4)] + [
                {"resource": "free", "ts": t} for _ in range(2)
            ]
            ops = eng.submit_many(reqs)
            eng.flush()
            batches.append(ops)
        eng.drain()
        recs = eng.admission_trace.records()
        assert len(recs) == sum(len(b) for b in batches)
        # Batch b's records all carry the SAME deciding flush seq, in
        # dispatch order, and that seq names a telemetry span whose row
        # count matches the batch.
        spans = {s.flush_id: s for s in eng.telemetry.spans()}
        by_seq = {}
        for r in recs:
            by_seq.setdefault(r.flush_seq, []).append(r)
        assert len(by_seq) == len(batches)
        for seq_group, ops in zip(
            (by_seq[s] for s in sorted(by_seq)), batches
        ):
            seq = seq_group[0].flush_seq
            assert seq >= 0 and all(r.flush_seq == seq for r in seq_group)
            assert spans[seq].n_entries == len(ops)
            assert spans[seq].settled
            # Exact recount parity: multiset of (resource, reason,
            # admitted) from the settled verdicts == the records'.
            want = sorted(
                (op.resource, op.verdict.reason, op.verdict.admitted)
                for op in ops
            )
            got = sorted((r.resource, r.reason, r.admitted) for r in seq_group)
            assert got == want
            blocked = [r for r in seq_group if not r.admitted]
            assert blocked, "flow rule must block part of every batch"
            assert all(
                r.reason == E.BLOCK_FLOW and r.reason_name == "FlowException"
                and r.resource == "hot"
                for r in blocked
            )
        eng.close()

    @pytest.mark.parametrize("depth", [0, 2])
    def test_bulk_blocked_records_bounded_and_exact(self, manual_clock, depth):
        from sentinel_tpu.runtime.engine import Engine

        eng = Engine(clock=manual_clock)
        eng.admission_trace = AdmissionTracer(sample_rate=0.0, bulk_cap=3)
        eng.pipeline_depth = depth
        eng.set_flow_rules([st.FlowRule("bk", count=4)])
        manual_clock.set_ms(1000)
        g = eng.submit_bulk("bk", 16, ts=np.full(16, 1000, np.int32))
        eng.flush()
        eng.drain()
        blocked_total = int((~g.admitted).sum())
        recs = eng.admission_trace.records()
        assert blocked_total > 3
        assert len(recs) == 3  # bounded by bulk_cap
        assert all(
            not r.admitted and r.resource == "bk"
            and r.reason == E.BLOCK_FLOW for r in recs
        )
        # Recount parity: every recorded reason exists in the group's
        # settled reason column.
        assert all(int(r.reason) in set(g.reason.tolist()) for r in recs)
        eng.close()


class TestAdapterRoundTrips:
    """Acceptance: traceparent round-trips inbound parse → context →
    outbound inject through ASGI, WSGI, gRPC and gateway."""

    def _assert_roundtrip(self, captured_headers, recs):
        out = parse_traceparent(captured_headers["traceparent"])
        assert out is not None
        assert out.trace_id == "0af7651916cd43dd8448eb211c80319c"
        assert out.span_id != "b7ad6b7169203331"  # child span, not echo
        assert recs, "inbound sampled flag must force a record"
        assert all(
            r.trace_id == "0af7651916cd43dd8448eb211c80319c" for r in recs
        )
        assert any(r.parent_span_id == "b7ad6b7169203331" for r in recs)

    def test_asgi_roundtrip(self, manual_clock, engine):
        from sentinel_tpu.adapters import SentinelASGIMiddleware

        engine.admission_trace = AdmissionTracer(sample_rate=0.0)
        st.flow_rule_manager.load_rules([st.FlowRule("GET:/a", count=1e9)])
        captured = {}

        async def app(scope, receive, send):
            inject_trace_headers(captured)
            await send({"type": "http.response.start", "status": 200,
                        "headers": []})
            await send({"type": "http.response.body", "body": b"ok"})

        mw = SentinelASGIMiddleware(app, total_resource=None)
        scope = {
            "type": "http", "method": "GET", "path": "/a",
            "headers": [(b"traceparent", TP.encode()),
                        (b"tracestate", b"v=1")],
        }
        sent = []

        async def send(msg):
            sent.append(msg)

        asyncio.run(mw(scope, None, send))
        assert sent[0]["status"] == 200
        self._assert_roundtrip(captured, engine.admission_trace.records())
        assert ContextUtil.get_trace() is None  # token reset after request

    def test_wsgi_roundtrip(self, manual_clock, engine):
        from sentinel_tpu.adapters import SentinelWSGIMiddleware

        engine.admission_trace = AdmissionTracer(sample_rate=0.0)
        st.flow_rule_manager.load_rules([st.FlowRule("GET:/w", count=1e9)])
        captured = {}

        def app(environ, start_response):
            inject_trace_headers(captured)
            start_response("200 OK", [])
            return [b"ok"]

        mw = SentinelWSGIMiddleware(app, total_resource=None)
        environ = {
            "PATH_INFO": "/w", "REQUEST_METHOD": "GET",
            "HTTP_TRACEPARENT": TP, "HTTP_TRACESTATE": "v=1",
        }
        statuses = []
        body = mw(environ, lambda s, h: statuses.append(s))
        assert statuses == ["200 OK"] and body == [b"ok"]
        self._assert_roundtrip(captured, engine.admission_trace.records())
        assert ContextUtil.get_trace() is None

    def test_grpc_roundtrip(self, manual_clock, engine):
        from sentinel_tpu.adapters.grpc_adapter import (
            metadata_with_trace,
            trace_from_metadata,
        )

        engine.admission_trace = AdmissionTracer(sample_rate=0.0)
        st.flow_rule_manager.load_rules([st.FlowRule("/Svc/M", count=1e9)])
        md = (("traceparent", TP), ("tracestate", "v=1"), ("other", "x"))
        tc = trace_from_metadata(md)
        assert tc is not None and tc.tracestate == "v=1"
        from sentinel_tpu.models import constants as C

        token = ContextUtil.set_trace(tc)
        try:
            with st.entry("/Svc/M", entry_type=C.EntryType.IN):
                out_md = metadata_with_trace((("k", "v"),))
        finally:
            ContextUtil.reset_trace(token)
        captured = dict(out_md)
        assert captured["k"] == "v"
        self._assert_roundtrip(captured, engine.admission_trace.records())

    def test_grpc_server_interceptor_parses_inbound(
        self, manual_clock, engine
    ):
        grpc = pytest.importorskip("grpc")
        from sentinel_tpu.adapters.grpc_adapter import (
            SentinelServerInterceptor,
        )

        engine.admission_trace = AdmissionTracer(sample_rate=0.0)
        st.flow_rule_manager.load_rules([st.FlowRule("/S/ok", count=1e9)])

        class Details:
            method = "/S/ok"
            invocation_metadata = (("traceparent", TP),)

        # continuation -> None handler: the interceptor admits, exits
        # the entry, and passes the handler through.
        out = SentinelServerInterceptor().intercept_service(
            lambda d: None, Details()
        )
        assert out is None
        recs = engine.admission_trace.records()
        assert recs and recs[0].trace_id == TP.split("-")[1]

    def test_gateway_roundtrip(self, manual_clock, engine):
        from sentinel_tpu.adapters.gateway import (
            GatewayFlowRule,
            GatewayRequestInfo,
            gateway_entry,
            gateway_rule_manager,
        )

        engine.admission_trace = AdmissionTracer(sample_rate=0.0)
        gateway_rule_manager.load_rules(
            [GatewayFlowRule(resource="route_t", count=1e9)]
        )
        try:
            info = GatewayRequestInfo(
                path="/x", client_ip="1.2.3.4",
                headers={"traceparent": TP, "tracestate": "v=1"},
            )
            captured = {}
            with gateway_entry("route_t", info):
                inject_trace_headers(captured)
            self._assert_roundtrip(
                captured, engine.admission_trace.records()
            )
            assert ContextUtil.get_trace() is None
        finally:
            gateway_rule_manager.load_rules([])

    def test_decorator_traceparent_extractor(self, manual_clock, engine):
        from sentinel_tpu.adapters import sentinel_resource

        engine.admission_trace = AdmissionTracer(sample_rate=0.0)
        st.flow_rule_manager.load_rules([st.FlowRule("deco_t", count=1e9)])
        captured = {}

        @sentinel_resource(
            "deco_t",
            traceparent_extractor=lambda msg: msg.get("traceparent"),
        )
        def consume(msg):
            inject_trace_headers(captured)
            return "done"

        assert consume({"traceparent": TP}) == "done"
        self._assert_roundtrip(captured, engine.admission_trace.records())
        assert ContextUtil.get_trace() is None

    def test_requests_adapter_injects_outbound(
        self, manual_clock, engine
    ):
        """Real hop: ambient trace -> SentinelHTTPAdapter writes a
        child traceparent on the wire (local HTTP server echoes it)."""
        requests = pytest.importorskip("requests")
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from sentinel_tpu.adapters import SentinelHTTPAdapter

        class Echo(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = (self.headers.get("traceparent") or "").encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        srv = ThreadingHTTPServer(("127.0.0.1", 0), Echo)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/d"
            st.flow_rule_manager.load_rules(
                [st.FlowRule(f"GET:{url}", count=1e9)]
            )
            s = requests.Session()
            s.mount("http://", SentinelHTTPAdapter())
            token = ContextUtil.set_trace(parse_traceparent(TP))
            try:
                echoed = s.get(url).text
            finally:
                ContextUtil.reset_trace(token)
            out = parse_traceparent(echoed)
            assert out is not None
            assert out.trace_id == TP.split("-")[1]
            assert out.span_id != TP.split("-")[2]
            # Untraced call: nothing injected.
            assert s.get(url).text == ""
        finally:
            srv.shutdown()

    def test_guarded_client_injects_kwargs_headers(
        self, manual_clock, engine
    ):
        from sentinel_tpu.adapters import GuardedClient

        seen = {}

        class Stub:
            def request(self, method, url, **kw):
                seen.update(kw.get("headers") or {})
                return "ok"

        st.flow_rule_manager.load_rules([st.FlowRule("GET:u", count=1e9)])
        token = ContextUtil.set_trace(parse_traceparent(TP))
        try:
            caller_headers = {"x": "1"}
            assert GuardedClient(Stub()).get("u", headers=caller_headers) == "ok"
        finally:
            ContextUtil.reset_trace(token)
        assert seen["x"] == "1"
        assert parse_traceparent(seen["traceparent"]).trace_id == TP.split("-")[1]
        assert "traceparent" not in caller_headers  # caller's dict untouched


class TestTransportExports:
    def test_traces_command_filters_and_validation(self, manual_clock, engine):
        from sentinel_tpu.transport import handlers
        from sentinel_tpu.transport.command_center import CommandRequest

        engine.admission_trace = AdmissionTracer(sample_rate=1.0)
        st.flow_rule_manager.load_rules(
            [st.FlowRule("ta", count=1), st.FlowRule("tb", count=1e9)]
        )
        manual_clock.set_ms(100)
        engine.submit_many(
            [{"resource": "ta", "ts": 100} for _ in range(3)]
            + [{"resource": "tb", "ts": 100} for _ in range(2)]
        )
        engine.flush()

        def call(params):
            return handlers.traces_handler(
                CommandRequest(path="traces", params=params, body="")
            )

        resp = call({})
        assert resp.success
        d = json.loads(resp.result)
        assert d["enabled"] and d["sample_rate"] == 1.0
        assert len(d["records"]) == 5
        # resource filter
        d = json.loads(call({"resource": "ta"}).result)
        assert {r["resource"] for r in d["records"]} == {"ta"}
        # reason filter by shared name and by code
        d = json.loads(call({"reason": "FlowException"}).result)
        assert len(d["records"]) == 2
        assert all(not r["admitted"] for r in d["records"])
        d2 = json.loads(call({"reason": str(E.BLOCK_FLOW)}).result)
        assert d2["records"] == d["records"]
        # n cap
        d = json.loads(call({"n": "2"}).result)
        assert len(d["records"]) == 2
        # validation: negative and garbage rejected
        assert not call({"n": "-3"}).success
        assert not call({"n": "x"}).success
        assert not call({"reason": "NopeException"}).success

    def test_telemetry_spans_negative_rejected(self, manual_clock, engine):
        """Satellite regression: ?spans=-5 used to int() fine and slice
        the ring from the wrong end — now it fails validation."""
        from sentinel_tpu.transport import handlers
        from sentinel_tpu.transport.command_center import CommandRequest

        st.flow_rule_manager.load_rules([st.FlowRule("tn", count=1e9)])
        st.try_entry("tn")
        bad = handlers.telemetry_handler(
            CommandRequest(path="telemetry", params={"spans": "-5"}, body="")
        )
        assert not bad.success
        ok = handlers.telemetry_handler(
            CommandRequest(path="telemetry", params={"spans": "1"}, body="")
        )
        assert ok.success and len(json.loads(ok.result)["spans"]) == 1

    def test_prometheus_e2e_exemplars_openmetrics_only(
        self, manual_clock, engine
    ):
        from sentinel_tpu.transport import handlers
        from sentinel_tpu.transport.command_center import CommandRequest
        from sentinel_tpu.transport.prometheus import render_metrics

        engine.admission_trace = AdmissionTracer(sample_rate=1.0)
        st.flow_rule_manager.load_rules([st.FlowRule("ex", count=1)])
        manual_clock.set_ms(50)
        for _ in range(3):
            st.try_entry("ex")
        text = render_metrics(engine, openmetrics=True)
        ex_lines = [
            l for l in text.splitlines()
            if l.startswith("sentinel_engine_admission_latency_ms_bucket")
            and '# {trace_id="' in l
        ]
        assert ex_lines, "admission latency buckets must carry exemplars"
        assert text.rstrip().endswith("# EOF")
        # Exemplars land on buckets that actually hold observations —
        # counts and exemplar values measure the same quantity.
        for l in ex_lines:
            assert int(l.split("} ", 1)[1].split(" ", 1)[0]) > 0
        # OpenMetrics counter families drop the _total suffix in
        # metadata while samples keep it (strict OM parsers reject the
        # classic shape under the OM content type).
        assert "# TYPE sentinel_engine_flushes counter" in text
        assert "\nsentinel_engine_flushes_total " in text
        assert "# TYPE sentinel_engine_flushes_total counter" not in text
        # The exemplar's trace id is a recorded one.
        known = {r.trace_id for r in engine.admission_trace.records()}
        tid = ex_lines[0].split('trace_id="')[1].split('"')[0]
        assert tid in known
        # Tracer counters exported.
        assert "sentinel_engine_trace_records_total" in text
        assert "sentinel_engine_trace_blocked_sampled_total" in text
        # The CLASSIC format must stay exemplar-free — the 0.0.4 text
        # parser rejects a mid-line '#', which would fail the whole
        # scrape — and the handler switches the content type with the
        # format.
        classic = render_metrics(engine)
        assert '# {trace_id="' not in classic
        assert "# EOF" not in classic
        assert "# TYPE sentinel_engine_flushes_total counter" in classic
        resp = handlers.prometheus_handler(
            CommandRequest(path="metrics", params={}, body="")
        )
        assert resp.content_type.startswith("text/plain; version=0.0.4")
        assert '# {trace_id="' not in resp.result
        resp_om = handlers.prometheus_handler(
            CommandRequest(
                path="metrics", params={"format": "openmetrics"}, body=""
            )
        )
        assert resp_om.content_type.startswith("application/openmetrics-text")
        assert '# {trace_id="' in resp_om.result

    def test_exemplar_bucket_matches_latency(self):
        tr = AdmissionTracer(sample_rate=1.0)
        from sentinel_tpu.metrics.admission_trace import TraceTag

        t0 = time.perf_counter()
        rec = tr.record_admission(
            TraceTag(None, True, t0), "r", "", "ctx", True, 0, 7,
            t0 + 0.004,  # ~4 ms
        )
        from sentinel_tpu.metrics.histogram import LatencyHistogram

        want_bucket = LatencyHistogram().bucket_of(rec.latency_ms)
        assert tr.exemplars() == {
            want_bucket: (rec.trace_id, rec.latency_ms)
        }


@pytest.mark.slow
class TestOverhead:
    def test_tracing_disabled_within_1pct(self, manual_clock):
        """Acceptance: tracing disabled costs <=1% vs the default-on
        tracer on the bench adapter stage's shape (gateway bulk loop) —
        i.e. the feature's always-on price at default sampling is
        within noise of its off position (median-of-repeats)."""
        from sentinel_tpu.adapters.gateway import (
            GatewayFlowRule,
            GatewayParamFlowItem,
            GatewayRequestBatch,
            gateway_rule_manager,
            gateway_submit_bulk,
        )
        from sentinel_tpu.runtime.engine import Engine

        n = 2048
        ips = [f"10.0.{i % 16}.{i % 251}" for i in range(n)]

        def run(enabled: bool) -> float:
            eng = Engine(clock=manual_clock)
            eng.admission_trace = AdmissionTracer(enabled=enabled)
            gateway_rule_manager.load_rules(
                [GatewayFlowRule(resource="ovr", count=1e9,
                                 param_item=GatewayParamFlowItem())]
            )
            batch = GatewayRequestBatch(n=n, client_ip=ips)
            gateway_submit_bulk("ovr", batch, engine=eng, ts=100, flush=True)
            eng.flush()  # warm-up/compile
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(10):
                    gateway_submit_bulk(
                        "ovr", batch, engine=eng, ts=100, flush=True
                    )
                best = min(best, time.perf_counter() - t0)
            eng.close()
            return best

        try:
            t_on = run(True)
            t_off = run(False)
        finally:
            gateway_rule_manager.load_rules([])
        assert t_off <= t_on * 1.01 + 0.01, (t_off, t_on)
        assert t_on <= t_off * 1.01 + 0.01, (t_on, t_off)
