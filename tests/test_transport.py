"""Transport + metric log + dashboard plane tests, including the full
observability loop: engine stats -> metric log -> command center /metric
-> dashboard fetcher -> in-memory repository (SURVEY.md §3.5)."""

import json
import time
import urllib.parse
import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.dashboard import DashboardServer, MachineInfo
from sentinel_tpu.metrics.metric_log import (
    MetricNodeLine,
    MetricSearcher,
    MetricTimer,
    MetricWriter,
)
from sentinel_tpu.transport.command_center import CommandCenter
from sentinel_tpu.transport.heartbeat import HeartbeatSender


def http_get(srv_port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{srv_port}/{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode("utf-8")


class TestMetricLog:
    def test_line_roundtrip(self):
        n = MetricNodeLine(
            timestamp=1700000000000, resource="api|x", pass_qps=5, block_qps=2,
            success_qps=5, exception_qps=1, rt=12.5, concurrency=3,
        )
        parsed = MetricNodeLine.from_line(n.to_line())
        assert parsed.resource == "api_x"  # separator sanitized
        assert parsed.pass_qps == 5 and parsed.rt == 12.5

    def test_writer_searcher_roundtrip(self, tmp_path):
        w = MetricWriter(base_dir=str(tmp_path), app_name="t")
        nodes = [
            MetricNodeLine(timestamp=1000_000, resource="a", pass_qps=1),
            MetricNodeLine(timestamp=1001_000, resource="b", pass_qps=2),
        ]
        w.write(1001_000, nodes)
        s = MetricSearcher(base_dir=str(tmp_path), app_name="t")
        found = s.find(999_000, 1002_000)
        assert len(found) == 2
        assert [n.resource for n in s.find(0, 2**60, resource="b")] == ["b"]
        assert s.find(2000_000, 3000_000) == []

    def test_rolling(self, tmp_path):
        w = MetricWriter(base_dir=str(tmp_path), app_name="r",
                         single_file_size=200, total_file_count=2)
        for i in range(20):
            w.write(i * 1000, [MetricNodeLine(timestamp=i * 1000, resource="x", pass_qps=i)])
        files = w._list_files()
        assert 1 <= len(files) <= 2  # rolled and pruned

    def test_metric_timer_collects_engine_seconds(self, manual_clock, engine, tmp_path):
        st.flow_rule_manager.load_rules([st.FlowRule("mt", count=100)])
        for sec in range(3):
            for i in range(5):
                manual_clock.set_ms(sec * 1000 + i * 10)
                with st.entry("mt"):
                    pass
        manual_clock.set_ms(3500)  # seconds 0..2 complete
        timer = MetricTimer(engine, writer=MetricWriter(base_dir=str(tmp_path), app_name="mt"))
        lines = timer.run_once()
        mt_lines = [l for l in lines if l.resource == "mt"]
        assert len(mt_lines) == 3
        assert all(l.pass_qps == 5 for l in mt_lines)
        # Incremental: a second run with no new complete seconds is empty.
        assert timer.run_once() == []


class TestCommandCenter:
    @pytest.fixture()
    def cc(self):
        center = CommandCenter(port=0).start()
        yield center
        center.stop()

    def test_version_and_api(self, cc, manual_clock, engine):
        assert http_get(cc.port, "version")[1] == st.__version__
        status, body = http_get(cc.port, "api")
        assert "getRules" in json.loads(body)

    def test_rules_roundtrip(self, cc, manual_clock, engine):
        rules = json.dumps([{"resource": "cc-r", "count": 3}])
        status, body = http_get(cc.port, "setRules", type="flow", data=rules)
        assert body == "success"
        status, body = http_get(cc.port, "getRules", type="flow")
        got = json.loads(body)
        assert got[0]["resource"] == "cc-r" and got[0]["count"] == 3
        # the rules are actually live
        for _ in range(3):
            st.try_entry("cc-r").exit()
        assert st.try_entry("cc-r") is None

    def test_unknown_command(self, cc):
        try:
            http_get(cc.port, "nope")
            assert False
        except urllib.error.HTTPError as e:
            assert e.code == 400

    def test_switch(self, cc, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("sw", count=0)])
        assert st.try_entry("sw") is None
        http_get(cc.port, "setSwitch", value="false")
        e = st.try_entry("sw")  # protection off -> pass-through
        assert e is not None and e.pass_through
        http_get(cc.port, "setSwitch", value="true")
        assert st.try_entry("sw") is None

    def test_tree_and_cluster_node(self, cc, manual_clock, engine):
        with st.entry("tree-res"):
            pass
        status, body = http_get(cc.port, "tree")
        assert "tree-res" in body
        status, body = http_get(cc.port, "clusterNode")
        nodes = json.loads(body)
        assert any(n["resourceName"] == "tree-res" for n in nodes)

    def test_system_status(self, cc, manual_clock, engine):
        status, body = http_get(cc.port, "systemStatus")
        data = json.loads(body)
        assert set(data) >= {"qps", "thread", "rt", "load", "cpu"}


class TestDashboard:
    def test_registry_and_apps(self):
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            status, body = http_get(
                dash.port, "registry/machine", app="my-app", ip="127.0.0.1", port=1234
            )
            assert json.loads(body)["code"] == 0
            status, body = http_get(dash.port, "apps")
            apps = json.loads(body)
            assert apps["my-app"][0]["port"] == 1234
        finally:
            dash.stop()

    def test_full_observability_loop(self, manual_clock, engine, tmp_path):
        """entry stats -> metric log -> command center -> dashboard repo."""
        import sentinel_tpu.transport.handlers as handlers
        from sentinel_tpu.metrics import metric_log as ml

        # Traffic for seconds 0..1.
        st.flow_rule_manager.load_rules([st.FlowRule("loop-res", count=100)])
        for sec in range(2):
            for i in range(4):
                manual_clock.set_ms(sec * 1000 + i * 10)
                with st.entry("loop-res"):
                    pass
        manual_clock.set_ms(2500)
        writer = MetricWriter(base_dir=str(tmp_path), app_name="loop-app")
        MetricTimer(engine, writer=writer).run_once()

        # Point the command center's searcher at our tmp dir.
        orig = ml.MetricSearcher.__init__
        ml.MetricSearcher.__init__ = (
            lambda self, base_dir=None, app_name=None: orig(self, str(tmp_path), "loop-app")
        )
        cc = CommandCenter(port=0).start()
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            http_get(dash.port, "registry/machine", app="loop-app", ip="127.0.0.1", port=cc.port)
            # The manual clock's wall epoch is in the past; widen the
            # fetcher's initial window to cover it.
            m = dash.apps.machines_of("loop-app")[0]
            dash.fetcher._last_fetch[m.key] = engine.clock.to_wall(0) - 1
            # Manual-clock timestamps are in the past relative to real
            # wall time; disable retention pruning for the assertion.
            dash.repo.RETENTION_MS = 1 << 62
            fetched = dash.fetcher.fetch_once()
            assert fetched > 0
            begin = engine.clock.to_wall(0)
            nodes = dash.repo.query("loop-app", "loop-res", begin, begin + 10_000)
            assert sum(n.pass_qps for n in nodes) == 8
        finally:
            ml.MetricSearcher.__init__ = orig
            cc.stop()
            dash.stop()


class TestHeartbeat:
    def test_heartbeat_registers(self):
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            hb = HeartbeatSender(f"127.0.0.1:{dash.port}", command_port=9999, app_name="hb-app")
            assert hb.heartbeat_once() is True
            assert any(m.port == 9999 for m in dash.apps.machines_of("hb-app"))
        finally:
            dash.stop()

    def test_heartbeat_failure(self):
        hb = HeartbeatSender("127.0.0.1:1", command_port=1, app_name="x")
        assert hb.heartbeat_once() is False


class TestCommandCenterRobustness:
    def test_malformed_posts_and_garbage(self, manual_clock, engine):
        """Garbage HTTP, bad Content-Length, non-UTF-8 bodies: the
        command center answers 4xx (or drops the line) and keeps
        serving."""
        import http.client
        import socket

        from sentinel_tpu.transport.command_center import CommandCenter

        cc = CommandCenter(port=0).start()
        try:
            port = cc.port

            def api_ok() -> bool:
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
                conn.request("GET", "/api")
                ok = conn.getresponse().status == 200
                conn.close()
                return ok

            assert api_ok()
            # Raw garbage request line.
            with socket.create_connection(("127.0.0.1", port), timeout=2) as s:
                s.sendall(b"\xff\xfe NOT HTTP\r\n\r\n")
            assert api_ok()
            # Garbage Content-Length.
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.putrequest("POST", "/setRules")
            conn.putheader("Content-Length", "abc")
            conn.endheaders()
            assert conn.getresponse().status == 400
            conn.close()
            # Non-UTF-8 body.
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.request("POST", "/setRules", body=b"\xff\xfe\xfd")
            assert conn.getresponse().status == 400
            conn.close()
            assert api_ok()
        finally:
            cc.stop()


class TestFleetHeartbeat:
    """PR-18 fleet fields: the heartbeat carries engine lifecycle
    provenance and the dashboard rolls it up per app."""

    def test_heartbeat_carries_engine_epoch_and_workers(
        self, manual_clock, engine
    ):
        from sentinel_tpu.ipc.plane import IngestPlane

        plane = IngestPlane(engine)
        engine.ipc_plane = plane
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            hb = HeartbeatSender(
                f"127.0.0.1:{dash.port}", command_port=9999,
                app_name="fleet-app", engine=engine,
            )
            assert hb.heartbeat_once() is True
            (m,) = dash.apps.machines_of("fleet-app")
            assert m.engine_epoch == 1
            assert m.restarts_total == 0
            assert m.workers == 0  # attached, nobody spawned
            _status, body = http_get(dash.port, "apps")
            (row,) = json.loads(body)["fleet-app"]
            assert row["engine_epoch"] == 1 and row["workers"] == 0
            assert row["restarts_total"] == 0
        finally:
            engine.ipc_plane = None
            plane.close()
            dash.stop()

    def test_fleet_rollup_and_stale_epochs(self):
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            # Two machines: one restarted twice (epoch 3), one stale
            # on epoch 1 with active shedding.
            http_get(dash.port, "registry/machine", app="a",
                     ip="10.0.0.1", port=1, engine_epoch=3,
                     restarts_total=2, workers=4)
            http_get(dash.port, "registry/machine", app="a",
                     ip="10.0.0.2", port=1, engine_epoch=1,
                     restarts_total=0, workers=2, shed_total=7,
                     shedding=1)
            _status, body = http_get(dash.port, "fleet")
            fleet = json.loads(body)["a"]
            assert fleet["machines"] == 2 and fleet["healthy"] == 2
            assert fleet["workers"] == 6
            assert fleet["restarts_total"] == 2
            assert fleet["shed_total"] == 7 and fleet["shedding"] == 1
            assert fleet["max_epoch"] == 3
            assert fleet["stale_epochs"] == 1
        finally:
            dash.stop()

    def test_fleet_rollup_empty_and_unreported_epochs(self):
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            _status, body = http_get(dash.port, "fleet")
            assert json.loads(body) == {}
            # A machine that never reported an epoch (pre-PR-18
            # sender) must not count as stale.
            http_get(dash.port, "registry/machine", app="b",
                     ip="10.0.0.3", port=1)
            _status, body = http_get(dash.port, "fleet")
            fleet = json.loads(body)["b"]
            assert fleet["max_epoch"] == 0 and fleet["stale_epochs"] == 0
        finally:
            dash.stop()


class TestSpansCommand:
    @pytest.fixture()
    def cc(self):
        center = CommandCenter(port=0).start()
        yield center
        center.stop()

    def test_snapshot_filter_and_spill(self, cc, manual_clock, engine,
                                       tmp_path):
        from sentinel_tpu.metrics import spans as spans_mod
        from sentinel_tpu.utils.config import config as _cfg

        _cfg.set(_cfg.SPANS_ENABLED, "true")
        _cfg.set(_cfg.SPANS_DIR, str(tmp_path))
        spans_mod.reset_journal()
        try:
            spj = spans_mod.get_journal("engine")
            spj.record("admit", "worker", 100.0, 1.0, wid=0, seq=1)
            spj.record("drain", "engine", 101.0, 0.5, frames=1, rows=1)
            _status, body = http_get(cc.port, "spans")
            out = json.loads(body)
            assert out["enabled"] is True and out["role"] == "engine"
            assert out["buffered"] == 2 and "spans" not in out
            _status, body = http_get(cc.port, "spans", n=10, cat="engine")
            out = json.loads(body)
            assert [s["name"] for s in out["spans"]] == ["drain"]
            _status, body = http_get(cc.port, "spans", spill=1)
            out = json.loads(body)
            assert out["spilled_to"]
            loaded = spans_mod.load_journal(out["spilled_to"])
            assert len(loaded["spans"]) == 2
        finally:
            _cfg.set(_cfg.SPANS_ENABLED, "false")
            _cfg.set(_cfg.SPANS_DIR, "")
            spans_mod.reset_journal()
