"""Cluster-mode hot-parameter flow control.

Reference semantics under test: ParamFlowChecker.passCheck delegating
QPS-grade cluster rules to the token service
(ParamFlowChecker.java:46-80), ClusterParamFlowChecker per-value global
windows + AVG_LOCAL threshold scaling
(ClusterParamFlowChecker.java:40-108), and
ConnectionManager/ConnectionGroup per-namespace connection accounting
(ConnectionManager.java:40-120) feeding those thresholds.
"""

import threading

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import (
    ClusterStateManager,
    DefaultTokenService,
    EmbeddedClusterTokenServerProvider,
    TokenClientProvider,
    cluster_flow_rule_manager,
    cluster_server_config_manager,
)
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.connection import ConnectionManager
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ClusterFlowConfig, ParamFlowRule
from sentinel_tpu.utils.clock import ManualClock


def cluster_param_rule(
    resource,
    count,
    flow_id,
    threshold_type=C.FLOW_THRESHOLD_GLOBAL,
    fallback=True,
    param_idx=0,
):
    return ParamFlowRule(
        resource,
        count=count,
        param_idx=param_idx,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id,
            threshold_type=threshold_type,
            fallback_to_local_when_fail=fallback,
        ),
    )


@pytest.fixture()
def cluster_env():
    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=30000.0
    )
    yield
    cluster_flow_rule_manager.clear()
    ClusterStateManager.stop()
    TokenClientProvider.clear()
    EmbeddedClusterTokenServerProvider.clear()


class TestConnectionManager:
    def test_bind_move_and_counts(self):
        cm = ConnectionManager()
        cm.on_connect("a:1")
        cm.on_connect("b:2")
        assert cm.count("default") == 2
        assert cm.bind("a:1", "ns1") == 1
        assert cm.count("default") == 1
        assert cm.count("ns1") == 1
        # Re-announcing the same namespace is idempotent.
        assert cm.bind("a:1", "ns1") == 1
        cm.on_disconnect("a:1")
        assert cm.count("ns1") == 0
        assert cm.total() == 1
        assert cm.snapshot() == {"default": 1}


class TestServerParamToken:
    def test_per_value_global_window(self, cluster_env):
        """Each param value gets its own global budget; conservation is
        exact across values."""
        svc = DefaultTokenService(clock=ManualClock(0))
        cluster_flow_rule_manager.load_rules(
            "default", [cluster_param_rule("r", 3, flow_id=201)]
        )
        oks_a = [svc.request_param_token(201, 1, ["a"]).ok for _ in range(5)]
        oks_b = [svc.request_param_token(201, 1, ["b"]).ok for _ in range(5)]
        assert oks_a == [True] * 3 + [False] * 2
        assert oks_b == [True] * 3 + [False] * 2

    def test_avg_local_scales_with_namespace_connections(self, cluster_env):
        """AVG_LOCAL threshold = count × the RULE NAMESPACE's connected
        count, not the global total (ClusterParamFlowChecker
        .calcGlobalThreshold + ConnectionManager.getConnectedCount)."""
        svc = DefaultTokenService(clock=ManualClock(0))
        cm = ConnectionManager()
        svc.connections = cm
        # ns1 has 3 clients, ns2 has 1 client (4 total).
        for i in range(3):
            cm.bind(f"c{i}:1", "ns1")
        cm.bind("d0:1", "ns2")
        cluster_flow_rule_manager.load_rules(
            "ns1",
            [cluster_param_rule("r1", 2, flow_id=301,
                                threshold_type=C.FLOW_THRESHOLD_AVG_LOCAL)],
        )
        cluster_flow_rule_manager.load_rules(
            "ns2",
            [cluster_param_rule("r2", 2, flow_id=302,
                                threshold_type=C.FLOW_THRESHOLD_AVG_LOCAL)],
        )
        got1 = sum(svc.request_param_token(301, 1, ["x"]).ok for _ in range(10))
        got2 = sum(svc.request_param_token(302, 1, ["x"]).ok for _ in range(10))
        assert got1 == 6  # 2 × 3 connections
        assert got2 == 2  # 2 × 1 connection

    def test_flow_avg_local_uses_namespace_count(self, cluster_env):
        """Plain FLOW tokens also use per-namespace counts."""
        from tests.test_cluster import cluster_rule

        svc = DefaultTokenService(clock=ManualClock(0))
        cm = ConnectionManager()
        svc.connections = cm
        cm.bind("a:1", "nsA")
        cm.bind("b:1", "nsA")
        cm.bind("c:1", "nsB")
        cluster_flow_rule_manager.load_rules(
            "nsA", [cluster_rule("fa", 3, flow_id=311,
                                 threshold_type=C.FLOW_THRESHOLD_AVG_LOCAL)]
        )
        cluster_flow_rule_manager.load_rules(
            "nsB", [cluster_rule("fb", 3, flow_id=312,
                                 threshold_type=C.FLOW_THRESHOLD_AVG_LOCAL)]
        )
        assert sum(svc.request_token(311).ok for _ in range(10)) == 6
        assert sum(svc.request_token(312).ok for _ in range(10)) == 3

    def test_no_rule(self, cluster_env):
        svc = DefaultTokenService(clock=ManualClock(0))
        r = svc.request_param_token(999, 1, ["v"])
        assert r.status == C.TokenResultStatus.NO_RULE_EXISTS

    def test_blocked_multi_value_charges_nothing(self, cluster_env):
        """Check-all-then-charge-all (ClusterParamFlowChecker): a
        request blocked on one value must not drain the budgets of its
        other values."""
        svc = DefaultTokenService(clock=ManualClock(0))
        cluster_flow_rule_manager.load_rules(
            "default", [cluster_param_rule("r", 3, flow_id=210)]
        )
        for _ in range(3):
            assert svc.request_param_token(210, 1, ["b"]).ok
        # 'b' exhausted: mixed requests block and must not charge 'a'.
        for _ in range(3):
            r = svc.request_param_token(210, 1, ["a", "b"])
            assert r.status == C.TokenResultStatus.BLOCKED
        assert [svc.request_param_token(210, 1, ["a"]).ok for _ in range(4)] == [
            True, True, True, False,
        ]


class TestWireNamespace:
    def test_ping_binds_namespace_and_counts(self, cluster_env):
        server = SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=ManualClock(0))
        ).start()
        try:
            c1 = ClusterTokenClient("127.0.0.1", server.port, namespace="nsX").start()
            c2 = ClusterTokenClient("127.0.0.1", server.port, namespace="nsX").start()
            c3 = ClusterTokenClient("127.0.0.1", server.port, namespace="nsY").start()
            # Ping is async after connect; wait for the groups to fill.
            deadline = threading.Event()
            for _ in range(100):
                snap = server.connections.snapshot()
                if snap.get("nsX") == 2 and snap.get("nsY") == 1:
                    break
                deadline.wait(0.02)
            snap = server.connections.snapshot()
            assert snap.get("nsX") == 2
            assert snap.get("nsY") == 1
            c1.stop(); c2.stop(); c3.stop()
            for _ in range(100):
                if server.connections.total() == 0:
                    break
                deadline.wait(0.02)
            assert server.connections.total() == 0
        finally:
            server.stop()


class TestEngineClusterParam:
    def test_embedded_param_conservation(self, cluster_env, manual_clock, engine):
        """cluster_mode ParamFlowRule through the engine against the
        embedded token service: per-value global conservation, BLOCKED →
        ParamFlowBlockError with the rule attributed."""
        rule = cluster_param_rule("psvc", 2, flow_id=401)
        cluster_flow_rule_manager.load_rules("default", [rule])
        service = DefaultTokenService(clock=manual_clock)
        server = SentinelTokenServer(port=0, service=service)  # embedded
        EmbeddedClusterTokenServerProvider.register(server)
        ClusterStateManager.set_to_server()
        st.param_flow_rule_manager.load_rules([rule])
        assert st.try_entry("psvc", args=("u1",)) is not None
        assert st.try_entry("psvc", args=("u1",)) is not None
        assert st.try_entry("psvc", args=("u1",)) is None  # server BLOCKED
        # Another value has its own global budget.
        assert st.try_entry("psvc", args=("u2",)) is not None
        with pytest.raises(st.ParamFlowBlockError) as ei:
            st.entry("psvc", args=("u1",))
        assert ei.value.rule == rule

    def test_engine_vs_live_tcp_server_conservation(self, cluster_env, manual_clock, engine):
        """Two token clients hammer one live TCP token server through
        engine entries; the global grant count is exactly the rule
        budget (the ClusterParamFlowChecker conservation story)."""
        rule = cluster_param_rule("tcp_psvc", 10, flow_id=402)
        cluster_flow_rule_manager.load_rules("default", [rule])
        server = SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=ManualClock(0))
        ).start()
        try:
            client = ClusterTokenClient("127.0.0.1", server.port).start()
            TokenClientProvider.register(client)
            ClusterStateManager.set_to_client()
            st.param_flow_rule_manager.load_rules([rule])
            granted = sum(
                st.try_entry("tcp_psvc", args=("hot",)) is not None
                for _ in range(25)
            )
            assert granted == 10
            client.stop()
        finally:
            server.stop()

    def test_fallback_to_local_when_no_service(self, cluster_env, manual_clock, engine):
        """FAIL → local param check (fallbackToLocalWhenFail), local
        window enforces the rule count."""
        rule = cluster_param_rule("pfb", 1, flow_id=403, fallback=True)
        st.param_flow_rule_manager.load_rules([rule])
        ClusterStateManager.stop()
        assert st.try_entry("pfb", args=("k",)) is not None
        assert st.try_entry("pfb", args=("k",)) is None  # local check blocks

    def test_pass_when_no_service_and_no_fallback(self, cluster_env, manual_clock, engine):
        rule = cluster_param_rule("pnf", 1, flow_id=404, fallback=False)
        st.param_flow_rule_manager.load_rules([rule])
        ClusterStateManager.stop()
        for _ in range(5):
            e = st.try_entry("pnf", args=("k",))
            assert e is not None
            e.exit()

    def test_thread_grade_stays_local(self, cluster_env, manual_clock, engine):
        """THREAD-grade param rules never consult the token server
        (ParamFlowChecker only clusters QPS grade)."""
        rule = ParamFlowRule(
            "pthr",
            count=1,
            param_idx=0,
            grade=C.FLOW_GRADE_THREAD,
            cluster_mode=True,
            cluster_config=ClusterFlowConfig(flow_id=405),
        )

        class ExplodingService:
            def request_param_token(self, *a, **k):
                raise AssertionError("THREAD-grade must not RPC")

        server = SentinelTokenServer(port=0, service=ExplodingService())
        EmbeddedClusterTokenServerProvider.register(server)
        ClusterStateManager.set_to_server()
        st.param_flow_rule_manager.load_rules([rule])
        e = st.try_entry("pthr", args=("k",))
        assert e is not None
        assert st.try_entry("pthr", args=("k",)) is None  # local thread gauge
        e.exit()
        assert st.try_entry("pthr", args=("k",)) is not None
