"""Closed-form heavy-hitter param path (rounds ≤ −1).

Pins the rank math against the sequential scan (rounds = 0, the
reference-semantics recurrence) on identical batches and state: same
verdicts, same post-state — for any per-value multiplicity, including
far past the 16-round unroll cap, and (rounds < −1) for
mixed-timestamp batches resolved by segmented rank math with
per-segment refill between the (row, ts) sub-segments.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sentinel_tpu.models import constants as C
from sentinel_tpu.rules.param_table import (
    PARAM_NEVER,
    ParamBatch,
    ParamDynState,
    make_param_state,
    run_param,
)


def _batch(rng, s, pr, ts_val, acq_val, max_tc=6):
    prow = rng.integers(0, pr, s).astype(np.int32)
    # Per-row constant tc/burst/duration (a row is one (rule, value)).
    row_tc = rng.integers(1, max_tc, pr).astype(np.int32)
    row_burst = rng.integers(0, 3, pr).astype(np.int32)
    row_dur = (rng.integers(1, 4, pr) * 500).astype(np.int32)
    tc = row_tc[prow]
    burst = row_burst[prow]
    dur = row_dur[prow]
    valid = rng.random(s) < 0.9
    ts = (
        jnp.asarray(rng.choice(ts_val, s).astype(np.int32))
        if isinstance(ts_val, np.ndarray)
        else jnp.full(s, ts_val, dtype=jnp.int32)
    )
    return ParamBatch(
        valid=jnp.asarray(valid),
        prow=jnp.asarray(prow),
        eidx=jnp.arange(s, dtype=jnp.int32),
        ts=ts,
        acquire=jnp.full(s, acq_val, dtype=jnp.int32),
        grade=jnp.full(s, C.FLOW_GRADE_QPS, dtype=jnp.int32),
        behavior=jnp.full(s, C.CONTROL_BEHAVIOR_DEFAULT, dtype=jnp.int32),
        token_count=jnp.asarray(tc),
        burst=jnp.asarray(burst),
        duration_ms=jnp.asarray(dur),
        maxq=jnp.zeros(s, dtype=jnp.int32),
        cost_ms=jnp.zeros(s, dtype=jnp.int32),
        reset_rows=jnp.full(8, -1, dtype=jnp.int32),
        exit_rows=jnp.full(8, -1, dtype=jnp.int32),
    )


def _rand_state(rng, pr):
    return ParamDynState(
        tokens=jnp.asarray(rng.integers(0, 8, pr).astype(np.int32)),
        last_add=jnp.asarray(
            np.where(
                rng.random(pr) < 0.3,
                PARAM_NEVER,
                rng.integers(0, 2000, pr),
            ).astype(np.int32)
        ),
        latest=jnp.full(pr, PARAM_NEVER, dtype=jnp.int32),
        threads=jnp.zeros(pr, dtype=np.int32),
    )


def _assert_same(dyn_a, ok_a, dyn_b, ok_b):
    assert np.array_equal(np.asarray(ok_a), np.asarray(ok_b))
    assert np.array_equal(np.asarray(dyn_a.tokens), np.asarray(dyn_b.tokens))
    assert np.array_equal(np.asarray(dyn_a.last_add), np.asarray(dyn_b.last_add))
    assert np.array_equal(np.asarray(dyn_a.latest), np.asarray(dyn_b.latest))


class TestClosedFormParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_random_batches_match_scan(self, seed):
        """Heavy multiplicity (s >> pr): closed form ≡ scan on verdicts
        AND post-state, across never/refill/steady rows."""
        rng = np.random.default_rng(seed)
        s, pr = 512, 9  # ~57 items per value — far past the rounds cap
        ts_val = int(rng.integers(500, 3000))
        acq = int(rng.integers(1, 3))
        pb = _batch(rng, s, pr, ts_val, acq)
        dyn0 = _rand_state(rng, pr)
        dyn_cf, ok_cf, wait_cf = run_param(dyn0, pb, rounds=-1)
        dyn_sc, ok_sc, wait_sc = run_param(dyn0, pb, rounds=0)
        _assert_same(dyn_cf, ok_cf, dyn_sc, ok_sc)
        assert np.array_equal(np.asarray(wait_cf), np.asarray(wait_sc))

    @pytest.mark.parametrize("seed", range(8))
    def test_mixed_ts_random_batches_match_scan(self, seed):
        """Segmented rank math (rounds < −1): mixed-timestamp batches
        with per-segment refill between (row, ts) sub-segments ≡ scan
        on verdicts AND post-state — across never/refill/steady rows
        and refill boundaries that open mid-batch."""
        rng = np.random.default_rng(1000 + seed)
        s, pr = 512, 9
        nts = int(rng.integers(2, 6))
        ts_vals = np.sort(
            rng.choice(np.arange(500, 6000), nts, replace=False)
        ).astype(np.int32)
        acq = int(rng.integers(1, 3))
        pb = _batch(rng, s, pr, ts_vals, acq)
        dyn0 = _rand_state(rng, pr)
        nseg = 1 << (nts - 1).bit_length()
        dyn_cf, ok_cf, wait_cf = run_param(dyn0, pb, rounds=-nseg)
        dyn_sc, ok_sc, wait_sc = run_param(dyn0, pb, rounds=0)
        _assert_same(dyn_cf, ok_cf, dyn_sc, ok_sc)
        assert np.array_equal(np.asarray(wait_cf), np.asarray(wait_sc))

    def test_acquire_zero_not_eligible(self, engine):
        """acquire<1 admits unconditionally in the recurrence
        (tokens−0 ≥ 0); the selector must not hand such batches to the
        rank path."""
        import numpy as np

        z = np.zeros(4, dtype=np.int32)
        assert engine._param_rounds_for(
            z, np.full(4, C.FLOW_GRADE_QPS, np.int32),
            np.full(4, C.CONTROL_BEHAVIOR_DEFAULT, np.int32),
            np.full(4, 1000, np.int32), np.zeros(4, np.int32),
        ) != -1
        assert engine._param_rounds_for(
            z, np.full(4, C.FLOW_GRADE_QPS, np.int32),
            np.full(4, C.CONTROL_BEHAVIOR_DEFAULT, np.int32),
            np.full(4, 1000, np.int32), np.ones(4, np.int32),
        ) == -1

    def test_second_flush_refill_boundary(self):
        """State chains correctly across flushes: spend the window,
        then at exactly dur+1 later the refill reopens the budget."""
        pr = 2
        dyn = make_param_state(pr)

        def batch(ts, n):
            rng = np.random.default_rng(0)
            return ParamBatch(
                valid=jnp.ones(n, dtype=bool),
                prow=jnp.zeros(n, dtype=jnp.int32),
                eidx=jnp.arange(n, dtype=jnp.int32),
                ts=jnp.full(n, ts, dtype=jnp.int32),
                acquire=jnp.ones(n, dtype=jnp.int32),
                grade=jnp.full(n, C.FLOW_GRADE_QPS, dtype=jnp.int32),
                behavior=jnp.full(n, C.CONTROL_BEHAVIOR_DEFAULT, dtype=jnp.int32),
                token_count=jnp.full(n, 3, dtype=jnp.int32),
                burst=jnp.zeros(n, dtype=jnp.int32),
                duration_ms=jnp.full(n, 1000, dtype=jnp.int32),
                maxq=jnp.zeros(n, dtype=jnp.int32),
                cost_ms=jnp.zeros(n, dtype=jnp.int32),
                reset_rows=jnp.full(8, -1, dtype=jnp.int32),
                exit_rows=jnp.full(8, -1, dtype=jnp.int32),
            )

        dyn, ok, _ = run_param(dyn, batch(1000, 40), rounds=-1)
        assert int(np.asarray(ok).sum()) == 3  # first fill: maxCount
        dyn, ok, _ = run_param(dyn, batch(1100, 40), rounds=-1)
        assert int(np.asarray(ok).sum()) == 0  # window spent
        dyn, ok, _ = run_param(dyn, batch(2101, 40), rounds=-1)
        assert int(np.asarray(ok).sum()) == 3  # refilled

    def test_engine_selects_closed_form_for_heavy_hitter_bulk(
        self, manual_clock, engine
    ):
        """A heavy-hitter bulk column (multiplicity way past the rounds
        cap) picks rounds=-1 on the host and still grants exactly the
        per-value budget."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule

        engine.set_flow_rules([st.FlowRule("hh", count=100000)])
        engine.set_param_rules({"hh": [ParamFlowRule("hh", param_idx=0, count=4)]})
        manual_clock.set_ms(1000)
        n = 600  # 300 per value — scan territory without the closed form
        col = [("a",) if i % 2 == 0 else ("b",) for i in range(n)]
        g = engine.submit_bulk(
            "hh", n, ts=np.full(n, 1000, dtype=np.int32), args_column=col
        )
        engine.flush()
        adm = np.asarray(g.admitted)
        assert adm[::2].sum() == 4 and adm[1::2].sum() == 4

    def test_mixed_ts_selects_segmented_mode(self, manual_clock, engine):
        """Mixed timestamps select the segmented closed-form (−S, one
        sub-segment per distinct ts) and stay correct (two windows'
        worth of grants across the ts gap)."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule
        from sentinel_tpu.models import constants as C2

        grades = np.array([C2.FLOW_GRADE_QPS], dtype=np.int32)
        ts = np.array([1000, 2500], dtype=np.int32)
        acq = np.array([1, 1], dtype=np.int32)
        beh = np.array([C2.CONTROL_BEHAVIOR_DEFAULT] * 2, dtype=np.int32)
        assert engine._param_rounds_for(
            np.array([0, 0], dtype=np.int32), np.repeat(grades, 2), beh, ts, acq
        ) == -2

        engine.set_flow_rules([st.FlowRule("mx", count=100000)])
        engine.set_param_rules({"mx": [ParamFlowRule("mx", param_idx=0, count=2)]})
        ops = engine.submit_many(
            [{"resource": "mx", "ts": 1000, "args": ("k",)} for _ in range(4)]
            + [{"resource": "mx", "ts": 2500, "args": ("k",)} for _ in range(4)]
        )
        engine.flush()
        adm = [op.verdict.admitted for op in ops]
        assert sum(adm[:4]) == 2 and sum(adm[4:]) == 2  # window rolled at 2500

    def test_throttle_items_not_eligible(self, manual_clock, engine):
        """RATE_LIMITER behavior must keep the exact pacer recurrence."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule
        from sentinel_tpu.models import constants as C2

        engine.set_flow_rules([st.FlowRule("th", count=100000)])
        engine.set_param_rules(
            {"th": [ParamFlowRule(
                "th", param_idx=0, count=10,
                control_behavior=C2.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=500,
            )]}
        )
        manual_clock.set_ms(1000)
        ops = engine.submit_many(
            [{"resource": "th", "ts": 1000, "args": ("k",)} for _ in range(8)]
        )
        engine.flush()
        grants = [op.verdict for op in ops]
        # 1 immediate + 4 queued (100 ms cost; wait must be STRICTLY
        # under maxQueueingTimeMs=500 — ParamFlowChecker.java:258).
        assert [v.admitted for v in grants] == [True] * 5 + [False] * 3
        assert [v.wait_ms for v in grants[:5]] == [0, 100, 200, 300, 400]
