"""Spawn targets for the multi-process ingest-plane tests.

``multiprocessing`` spawn children import these by module name (the
parent's ``sys.path`` travels in the spawn preparation data), so every
function here must stay top-level and self-importing. Workers touch
only the IngestClient surface — no engine, no device."""

from __future__ import annotations

import time


def run_script(channel, wid, script, q):
    """Run a scripted request sequence and report every verdict back.

    Steps: ``{"kind": "entry"|"bulk"|"exit"|"sleep", ...}``; results
    land on ``q`` as ``("done", wid, [per-step tuples])``."""
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)
    out = []
    try:
        for step in script:
            kind = step["kind"]
            if kind == "entry":
                v = cli.entry(
                    step["resource"],
                    origin=step.get("origin", ""),
                    acquire=step.get("acquire", 1),
                    entry_type=step.get("entry_type", 1),
                    args=tuple(step.get("args", ())),
                    ts=step.get("ts"),
                    timeout_ms=step.get("timeout_ms"),
                )
                out.append(
                    ("entry", v.admitted, v.reason, v.wait_ms,
                     v.speculative, v.degraded)
                )
            elif kind == "bulk":
                a, r, w, f = cli.bulk(
                    step["resource"], step["n"],
                    ts=step.get("ts"), acquire=step.get("acquire", 1),
                    args_column=step.get("args_column"),
                )
                out.append(
                    ("bulk", a.tolist(), r.tolist(), w.tolist(), f.tolist())
                )
            elif kind == "exit":
                cli.exit(
                    step["resource"],
                    rt=step.get("rt", 0), count=step.get("count", 1),
                    err=step.get("err", 0),
                    speculative=step.get("speculative"),
                )
                out.append(("exit",))
            elif kind == "sleep":
                time.sleep(step["s"])
        q.put(("done", wid, out))
    finally:
        cli.close()


def admit_and_hang(channel, wid, resource, n, q):
    """Admit ``n`` entries (charging THREAD gauges), report, then hang
    forever WITHOUT exiting them — the parent kills this process to
    simulate a crashed worker; the plane's heartbeat sweep must
    auto-exit the admissions."""
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)
    admitted = 0
    for _ in range(n):
        # Generous verdict timeout: on the contended 1-core CI box a
        # first-compile flush can exceed the 5 s default, and a policy
        # fallback here would admit WITHOUT charging the gauges the
        # test is about to assert on.
        v = cli.entry(resource, timeout_ms=120000)
        if v.admitted and not v.degraded:
            admitted += 1
    q.put(("admitted", wid, admitted))
    while True:
        time.sleep(1.0)


def entry_with_trace(channel, wid, resource, traceparent, q):
    """One traced admission: the inbound W3C context is set ambient in
    THIS process (the adapter's position) and must survive the frame
    boundary into the engine's admission-trace records."""
    from sentinel_tpu.core.context import ContextUtil
    from sentinel_tpu.ipc.worker import IngestClient
    from sentinel_tpu.metrics.admission_trace import parse_traceparent

    ContextUtil.set_trace(parse_traceparent(traceparent))
    cli = IngestClient(channel, wid)
    try:
        v = cli.entry(resource)
        q.put(("done", wid, (v.admitted, int(v.reason))))
    finally:
        cli.close()


def entries_until_dead(channel, wid, resource, q, max_n=2000):
    """Loop blocking entries until the engine reads dead (policy-served
    verdict), then report how the worker experienced the death."""
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)
    served = []
    try:
        for _ in range(max_n):
            v = cli.entry(resource, timeout_ms=2000)
            served.append((v.admitted, int(v.reason), v.degraded))
            if v.degraded:
                break
            time.sleep(0.01)
        q.put(("done", wid, served))
    finally:
        cli.close()
