"""Spawn targets for the multi-process ingest-plane tests.

``multiprocessing`` spawn children import these by module name (the
parent's ``sys.path`` travels in the spawn preparation data), so every
function here must stay top-level and self-importing. Workers touch
only the IngestClient surface — no engine, no device."""

from __future__ import annotations

import time


def run_script(channel, wid, script, q):
    """Run a scripted request sequence and report every verdict back.

    Steps: ``{"kind": "entry"|"bulk"|"exit"|"sleep", ...}``; results
    land on ``q`` as ``("done", wid, [per-step tuples])``."""
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)
    out = []
    try:
        for step in script:
            kind = step["kind"]
            if kind == "entry":
                v = cli.entry(
                    step["resource"],
                    origin=step.get("origin", ""),
                    acquire=step.get("acquire", 1),
                    entry_type=step.get("entry_type", 1),
                    args=tuple(step.get("args", ())),
                    ts=step.get("ts"),
                    timeout_ms=step.get("timeout_ms"),
                )
                out.append(
                    ("entry", v.admitted, v.reason, v.wait_ms,
                     v.speculative, v.degraded)
                )
            elif kind == "bulk":
                a, r, w, f = cli.bulk(
                    step["resource"], step["n"],
                    ts=step.get("ts"), acquire=step.get("acquire", 1),
                    args_column=step.get("args_column"),
                )
                out.append(
                    ("bulk", a.tolist(), r.tolist(), w.tolist(), f.tolist())
                )
            elif kind == "exit":
                cli.exit(
                    step["resource"],
                    rt=step.get("rt", 0), count=step.get("count", 1),
                    err=step.get("err", 0),
                    speculative=step.get("speculative"),
                )
                out.append(("exit",))
            elif kind == "sleep":
                time.sleep(step["s"])
        q.put(("done", wid, out))
    finally:
        cli.close()


def admit_and_hang(channel, wid, resource, n, q):
    """Admit ``n`` entries (charging THREAD gauges), report, then hang
    forever WITHOUT exiting them — the parent kills this process to
    simulate a crashed worker; the plane's heartbeat sweep must
    auto-exit the admissions."""
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)
    admitted = 0
    for _ in range(n):
        # Generous verdict timeout: on the contended 1-core CI box a
        # first-compile flush can exceed the 5 s default, and a policy
        # fallback here would admit WITHOUT charging the gauges the
        # test is about to assert on.
        v = cli.entry(resource, timeout_ms=120000)
        if v.admitted and not v.degraded:
            admitted += 1
    q.put(("admitted", wid, admitted))
    while True:
        time.sleep(1.0)


def entry_with_trace(channel, wid, resource, traceparent, q):
    """One traced admission: the inbound W3C context is set ambient in
    THIS process (the adapter's position) and must survive the frame
    boundary into the engine's admission-trace records."""
    from sentinel_tpu.core.context import ContextUtil
    from sentinel_tpu.ipc.worker import IngestClient
    from sentinel_tpu.metrics.admission_trace import parse_traceparent

    ContextUtil.set_trace(parse_traceparent(traceparent))
    cli = IngestClient(channel, wid)
    try:
        v = cli.entry(resource)
        q.put(("done", wid, (v.admitted, int(v.reason))))
    finally:
        cli.close()


def entries_until_dead(channel, wid, resource, q, max_n=2000):
    """Loop blocking entries until the engine reads dead (policy-served
    verdict), then report how the worker experienced the death."""
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)
    served = []
    try:
        for _ in range(max_n):
            v = cli.entry(resource, timeout_ms=2000)
            served.append((v.admitted, int(v.reason), v.degraded))
            if v.degraded:
                break
            time.sleep(0.01)
        q.put(("done", wid, served))
    finally:
        cli.close()


def run_script_cfg(channel, wid, cfg, script, q):
    """run_script with a config replay first — spawn children start
    from defaults, so micro-window / wakeup modes under test must ship
    in (the launcher's run_workers does the same for real deployments).
    """
    from sentinel_tpu.utils.config import config

    for k, v in (cfg or {}).items():
        config.set(k, v)
    run_script(channel, wid, script, q)


def run_entries_spanned(channel, wid, cfg, resource, n, q):
    """Span-armed worker leg for the fleet-timeline alignment test:
    replay ``cfg`` (spans enabled + spill dir travel in it), run ``n``
    blocking entries, then close — close spills the journal, and the
    parent loads it with ``load_journal`` to pin worker admit spans
    against the engine's frame spans on the shared wall-ms ruler."""
    from sentinel_tpu.utils.config import config

    for k, v in (cfg or {}).items():
        config.set(k, v)
    from sentinel_tpu.ipc.worker import IngestClient
    from sentinel_tpu.metrics.spans import get_journal

    cli = IngestClient(channel, wid)
    verdicts = []
    try:
        for _ in range(n):
            v = cli.entry(resource, timeout_ms=120000)
            verdicts.append((v.admitted, int(v.reason), v.degraded))
        q.put(("done", wid, verdicts, get_journal().spill_path()))
    finally:
        cli.close()


def worker_mode_serve(channel, wid, cfg, paths, q):
    """Worker-mode end-to-end: THIS process arms
    sentinel.tpu.ipc.worker.mode, attaches, and serves real adapter
    requests — the WSGI middleware and the ASGI middleware — whose
    admissions all ride the IngestClient to the engine process.
    ``paths`` is [(path, traceparent|None)]; reports
    [("wsgi"|"asgi", path, status)] per request."""
    import asyncio

    from sentinel_tpu.utils.config import config

    for k, v in (cfg or {}).items():
        config.set(k, v)
    config.set(config.IPC_WORKER_MODE, "true")
    from sentinel_tpu.ipc import worker_mode

    worker_mode.attach(channel, wid)
    try:
        from sentinel_tpu.adapters.asgi import SentinelASGIMiddleware
        from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware

        results = []

        def ok_app(environ, start_response):
            start_response("200 OK", [])
            return [b"ok"]

        wsgi = SentinelWSGIMiddleware(ok_app, total_resource=None)
        for path, tp in paths:
            statuses = []
            environ = {"PATH_INFO": path, "REQUEST_METHOD": "GET"}
            if tp:
                environ["HTTP_TRACEPARENT"] = tp
            list(wsgi(environ, lambda s, h: statuses.append(s)))
            results.append(("wsgi", path, statuses[0]))

        async def asgi_ok(scope, receive, send):
            await send({"type": "http.response.start", "status": 200,
                        "headers": []})
            await send({"type": "http.response.body", "body": b"ok"})

        asgi = SentinelASGIMiddleware(asgi_ok, total_resource=None)

        async def drive(path, tp):
            sent = []

            async def send(msg):
                sent.append(msg)

            async def receive():
                return {"type": "http.request"}

            headers = [(b"traceparent", tp.encode())] if tp else []
            await asgi(
                {"type": "http", "method": "GET", "path": path,
                 "headers": headers},
                receive, send,
            )
            return sent[0]["status"]

        for path, tp in paths:
            status = asyncio.run(drive(path, tp))
            results.append(("asgi", path, status))
        # The worker-mode contract: serving every request above must
        # never have lazily constructed an Engine in THIS process (no
        # device memory, no flush threads — and, with ipc.enabled
        # replayed, no second IngestPlane).
        from sentinel_tpu.core import api

        q.put(("done", wid, results, api.peek_engine() is None))
    finally:
        worker_mode.detach()


def restart_setup(engine):
    """Supervised-engine setup for the hot-restart chaos test
    (top-level so multiprocessing spawn children import it by name)."""
    from sentinel_tpu.models.rules import FlowRule

    engine.set_flow_rules([FlowRule(resource="chaos-res", count=1e9)])


def standby_setup(engine):
    """Supervised-engine setup for the warm-standby chaos tests: the
    open chaos resource plus a THREAD-grade rule whose gauge survives a
    takeover only if the reassert machinery carried it — the parity
    probes and the behavioral gauges-are-0 check both key off it."""
    from sentinel_tpu.models import constants as C
    from sentinel_tpu.models.rules import FlowRule

    engine.set_flow_rules(
        [
            FlowRule(resource="chaos-res", count=1e9),
            FlowRule(
                resource="sb-thread", count=3, grade=C.FLOW_GRADE_THREAD
            ),
        ]
    )


def worker_mode_admit_and_hang(channel, wid, resource_path, n, q):
    """Worker-mode kill -9 target: hold ``n`` admitted WSGI requests
    open (the app never returns, so their entries never exit) — the
    parent kills this process mid-serve and asserts the plane drains
    device AND mirror THREAD gauges to exactly 0."""
    import threading
    import time as _time

    from sentinel_tpu.utils.config import config

    config.set(config.IPC_WORKER_MODE, "true")
    from sentinel_tpu.ipc import worker_mode

    worker_mode.attach(channel, wid)
    from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware

    hold = threading.Event()
    admitted = []

    def hang_app(environ, start_response):
        start_response("200 OK", [])
        admitted.append(1)
        hold.wait()  # never set — entries stay live until kill -9
        return [b"ok"]

    mw = SentinelWSGIMiddleware(hang_app, total_resource=None)

    def call():
        try:
            list(mw({"PATH_INFO": resource_path, "REQUEST_METHOD": "GET"},
                    lambda s, h: None))
        except BaseException:
            pass

    for _ in range(n):
        threading.Thread(target=call, daemon=True).start()
    while len(admitted) < n:
        _time.sleep(0.05)
    q.put(("admitted", wid, len(admitted)))
    while True:
        _time.sleep(1.0)
