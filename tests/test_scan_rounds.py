"""The vectorized rounds path of the shaping/param recurrences must be
bit-identical to the sequential lax.scan on any batch whose
max-items-per-key fits the rounds bound — both resolve the same sorted
(rule, ts, arrival) stream; only the execution schedule differs.
"""

import numpy as np
import pytest


def _random_shaping_case(rng, s, n_rules):
    import jax.numpy as jnp

    from sentinel_tpu.models import constants as C
    from sentinel_tpu.rules.flow_table import FlowRuleDynState, FlowTableDevice
    from sentinel_tpu.rules.shaping import ShapingBatch

    beh = rng.choice(
        [C.CONTROL_BEHAVIOR_RATE_LIMITER, C.CONTROL_BEHAVIOR_WARM_UP,
         C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER],
        n_rules,
    ).astype(np.int32)
    count = rng.integers(1, 50, n_rules).astype(np.float32)
    dev = FlowTableDevice(
        grade=np.ones(n_rules, dtype=np.int32),
        count=jnp.asarray(count),
        behavior=jnp.asarray(beh),
        max_queueing_time_ms=jnp.asarray(rng.integers(0, 500, n_rules).astype(np.int32)),
        cost1_ms=jnp.asarray((1000.0 / count + 0.5).astype(np.int32)),
        warmup_warning_token=jnp.asarray(rng.integers(1, 100, n_rules).astype(np.int32)),
        warmup_max_token=jnp.asarray(rng.integers(100, 300, n_rules).astype(np.int32)),
        warmup_slope=jnp.asarray(rng.random(n_rules).astype(np.float32) * 1e-3),
        warmup_refill_threshold=jnp.asarray(rng.integers(1, 30, n_rules).astype(np.int32)),
    )
    dyn = FlowRuleDynState(
        latest_passed_time=jnp.asarray(rng.integers(-1000, 2000, n_rules).astype(np.int32)),
        stored_tokens=jnp.asarray(rng.integers(0, 200, n_rules).astype(np.float32)),
        last_filled_time=jnp.asarray(rng.integers(-1000, 2000, n_rules).astype(np.int32)),
    )
    gid = rng.integers(0, n_rules, s).astype(np.int32)
    valid = rng.random(s) < 0.9
    sb = ShapingBatch(
        valid=jnp.asarray(valid),
        gid=jnp.asarray(gid),
        row=jnp.asarray(gid),
        eidx=jnp.asarray(np.arange(s, dtype=np.int32)),
        flat_pos=jnp.asarray(np.arange(s, dtype=np.int32)),
        ts=jnp.asarray(np.sort(rng.integers(1000, 4000, s)).astype(np.int32)),
        acquire=jnp.asarray(rng.integers(1, 3, s).astype(np.int32)),
    )
    ppc = jnp.asarray(rng.integers(0, 40, s).astype(np.int32))
    prev = jnp.asarray(rng.integers(0, 40, s).astype(np.int32))
    max_per_rule = int(np.unique(gid[valid], return_counts=True)[1].max()) if valid.any() else 1
    return dev, dyn, sb, ppc, prev, max_per_rule


class TestRoundsParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_shaping_rounds_equals_scan(self, seed):
        import jax
        from sentinel_tpu.rules.shaping import run_shaping

        rng = np.random.default_rng(seed)
        dev, dyn, sb, ppc, prev, m = _random_shaping_case(rng, 64, 12)
        rounds = 1 << (max(m, 1) - 1).bit_length()
        d0, ok0, w0 = jax.jit(run_shaping, static_argnames=("rounds",))(
            dev, dyn, sb, ppc, prev, 1.0, rounds=0
        )
        d1, ok1, w1 = jax.jit(run_shaping, static_argnames=("rounds",))(
            dev, dyn, sb, ppc, prev, 1.0, rounds=rounds
        )
        assert np.array_equal(np.asarray(ok0), np.asarray(ok1))
        assert np.array_equal(np.asarray(w0), np.asarray(w1))
        for a, b in zip(d0, d1):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_param_rounds_equals_scan(self, seed):
        import jax
        import jax.numpy as jnp

        from sentinel_tpu.models import constants as C
        from sentinel_tpu.rules.param_table import ParamBatch, make_param_state, run_param

        rng = np.random.default_rng(seed + 100)
        s, pr = 64, 16
        dyn = make_param_state(pr)
        dyn = dyn._replace(
            tokens=jnp.asarray(rng.integers(0, 10, pr).astype(np.int32)),
            threads=jnp.asarray(rng.integers(0, 3, pr).astype(np.int32)),
        )
        prow = rng.integers(0, pr, s).astype(np.int32)
        valid = rng.random(s) < 0.9
        grade = rng.choice([C.FLOW_GRADE_QPS, C.FLOW_GRADE_THREAD], s).astype(np.int32)
        behavior = rng.choice([0, C.CONTROL_BEHAVIOR_RATE_LIMITER], s).astype(np.int32)
        pb = ParamBatch(
            valid=jnp.asarray(valid),
            prow=jnp.asarray(prow),
            eidx=jnp.asarray(np.arange(s, dtype=np.int32)),
            ts=jnp.asarray(np.sort(rng.integers(1000, 4000, s)).astype(np.int32)),
            acquire=jnp.asarray(rng.integers(1, 3, s).astype(np.int32)),
            grade=jnp.asarray(grade),
            behavior=jnp.asarray(behavior),
            token_count=jnp.asarray(rng.integers(1, 10, s).astype(np.int32)),
            burst=jnp.asarray(rng.integers(0, 3, s).astype(np.int32)),
            duration_ms=jnp.asarray(rng.integers(500, 2000, s).astype(np.int32)),
            maxq=jnp.asarray(rng.integers(0, 300, s).astype(np.int32)),
            cost_ms=jnp.asarray(rng.integers(10, 200, s).astype(np.int32)),
            reset_rows=jnp.asarray(np.array([1, -1, -1, -1], dtype=np.int32)),
            exit_rows=jnp.full(4, -1, dtype=np.int32),
        )
        m = int(np.unique(prow[valid], return_counts=True)[1].max()) if valid.any() else 1
        rounds = 1 << (max(m, 1) - 1).bit_length()
        d0, ok0, w0 = jax.jit(run_param, static_argnames=("rounds",))(dyn, pb, rounds=0)
        d1, ok1, w1 = jax.jit(run_param, static_argnames=("rounds",))(dyn, pb, rounds=rounds)
        assert np.array_equal(np.asarray(ok0), np.asarray(ok1))
        assert np.array_equal(np.asarray(w0), np.asarray(w1))
        for a, b in zip(d0, d1):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_engine_picks_scan_fallback_above_cap(self, manual_clock, engine):
        """More than 16 same-rule shaping items in one flush: the
        engine falls back to the scan (rounds=0) and still decides
        correctly."""
        import sentinel_tpu as st
        from sentinel_tpu.models import constants as C

        engine.set_flow_rules(
            [st.FlowRule("big", count=10,
                         control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                         max_queueing_time_ms=2000)]
        )
        manual_clock.set_ms(1000)
        g = engine.submit_bulk("big", 24, ts=1000)
        engine.flush()
        # cost=100ms, maxq=2000 → 1 immediate + 20 queued.
        assert g.admitted_count == 21

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_unrolled_equals_fori_loop(self, seed):
        """The two rounds schedules (trace-time unroll for rounds<=4,
        fori_loop above) must agree bit-for-bit on the same batch: any
        rounds bound >= the true max-items-per-key is valid, so rounds=4
        and rounds=8 run different code paths over identical work."""
        import jax
        from sentinel_tpu.rules.recurrence import UNROLL_MAX_ROUNDS
        from sentinel_tpu.rules.shaping import run_shaping

        rng = np.random.default_rng(seed + 500)
        # 64 items over 64 rules: max-per-rule stays small w.h.p.; skip
        # the seed otherwise rather than silently testing one path.
        dev, dyn, sb, ppc, prev, m = _random_shaping_case(rng, 64, 64)
        if m > UNROLL_MAX_ROUNDS:
            pytest.skip(f"seed landed max-per-rule {m} > {UNROLL_MAX_ROUNDS}")
        outs = [
            jax.jit(run_shaping, static_argnames=("rounds",))(
                dev, dyn, sb, ppc, prev, 1.0, rounds=r
            )
            for r in (UNROLL_MAX_ROUNDS, 2 * UNROLL_MAX_ROUNDS)
        ]
        (d4, ok4, w4), (d8, ok8, w8) = outs
        assert np.array_equal(np.asarray(ok4), np.asarray(ok8))
        assert np.array_equal(np.asarray(w4), np.asarray(w8))
        for a, b in zip(d4, d8):
            assert np.array_equal(np.asarray(a), np.asarray(b))
