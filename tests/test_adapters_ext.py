"""Python-ecosystem adapter breadth: requests transport adapter,
aiohttp server middleware + client session, async outbound guards,
Flask/FastAPI sugar (skipped where the framework isn't installed).

Reference analogs: okhttp/apache-httpclient interceptors for the
client side (SentinelOkHttpInterceptor.java:35-60), servlet/webmvc
interceptors for the server side
(AbstractSentinelInterceptor.java:60-110).
"""

import asyncio
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import sentinel_tpu as st
from sentinel_tpu.core.errors import BlockError


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        body = b"hello"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def http_server():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


class TestRequestsAdapter:
    def test_mounted_adapter_guards_and_blocks(self, manual_clock, engine, http_server):
        import requests

        from sentinel_tpu.adapters import SentinelHTTPAdapter

        port = http_server.server_address[1]
        url = f"http://127.0.0.1:{port}/x"
        st.flow_rule_manager.load_rules([st.FlowRule(f"GET:{url}", count=2)])
        s = requests.Session()
        s.mount("http://", SentinelHTTPAdapter())
        assert s.get(url + "?q=1").status_code == 200  # query dropped
        assert s.get(url).status_code == 200
        with pytest.raises(BlockError):
            s.get(url)
        stats = engine.cluster_node_stats(f"GET:{url}")
        assert stats["total_pass_minute"] == 2
        assert stats["total_block_minute"] == 1
        assert stats["cur_thread_num"] == 0

    def test_block_response_factory(self, manual_clock, engine, http_server):
        import requests

        from sentinel_tpu.adapters import SentinelHTTPAdapter

        port = http_server.server_address[1]
        url = f"http://127.0.0.1:{port}/y"

        def synth_429(request, error):
            resp = requests.Response()
            resp.status_code = 429
            resp.request = request
            return resp

        st.flow_rule_manager.load_rules([st.FlowRule(f"GET:{url}", count=0)])
        s = requests.Session()
        s.mount("http://", SentinelHTTPAdapter(block_response_factory=synth_429))
        assert s.get(url).status_code == 429


class TestAiohttpServer:
    def test_middleware_blocks_and_traces(self, manual_clock, engine):
        aiohttp = pytest.importorskip("aiohttp")
        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer

        from sentinel_tpu.adapters.aiohttp_adapter import sentinel_middleware

        async def hi(request):
            return web.Response(text="hi")

        async def boom(request):
            raise RuntimeError("kaput")

        app = web.Application(
            middlewares=[sentinel_middleware(total_resource="aio-total")]
        )
        app.router.add_get("/hi", hi)
        app.router.add_get("/boom", boom)
        st.flow_rule_manager.load_rules([st.FlowRule("GET:/hi", count=2)])

        async def drive():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                codes = [(await client.get("/hi")).status for _ in range(3)]
                boom_status = (await client.get("/boom")).status
                return codes, boom_status
            finally:
                await client.close()

        codes, boom_status = asyncio.run(drive())
        assert codes == [200, 200, 429]
        assert boom_status == 500
        stats = engine.cluster_node_stats("GET:/hi")
        assert stats["total_pass_minute"] == 2
        assert stats["total_block_minute"] == 1
        # The exception on /boom was traced to its resource.
        bstats = engine.cluster_node_stats("GET:/boom")
        assert bstats["total_exception_minute"] == 1
        # The app-total resource saw every request.
        tstats = engine.cluster_node_stats("aio-total")
        assert tstats["total_pass_minute"] == 4
        assert tstats["cur_thread_num"] == 0

    def test_client_session_guard(self, manual_clock, engine):
        aiohttp = pytest.importorskip("aiohttp")
        from aiohttp import web
        from aiohttp.test_utils import TestServer

        from sentinel_tpu.adapters.aiohttp_adapter import SentinelClientSession

        async def ok(request):
            return web.Response(text="ok")

        app = web.Application()
        app.router.add_get("/svc", ok)

        async def drive():
            server = TestServer(app)
            await server.start_server()
            url = server.make_url("/svc")
            resource = f"GET:{url}"
            st.flow_rule_manager.load_rules([st.FlowRule(resource, count=2)])
            async with SentinelClientSession() as s:
                # Both aiohttp idioms: bare await and async-with.
                r1 = await s.get(url)
                async with s.get(url) as r2:
                    assert r2.status == 200
                blocked = False
                try:
                    await s.get(url)
                except BlockError:
                    blocked = True
                return r1.status, blocked, resource

        status, blocked, resource = asyncio.run(drive())
        assert status == 200 and blocked
        stats = engine.cluster_node_stats(resource)
        assert stats["total_pass_minute"] == 2
        assert stats["total_block_minute"] == 1


class TestAsyncGuards:
    def test_guard_call_async_traces_errors(self, manual_clock, engine):
        from sentinel_tpu.adapters import guard_call_async

        async def failing():
            raise ValueError("x")

        async def drive():
            with pytest.raises(ValueError):
                await guard_call_async("dep", failing)

        asyncio.run(drive())
        stats = engine.cluster_node_stats("dep")
        assert stats["total_exception_minute"] == 1
        assert stats["cur_thread_num"] == 0

    def test_guarded_async_client(self, manual_clock, engine):
        from sentinel_tpu.adapters import GuardedAsyncClient

        class Stub:
            async def request(self, method, url, **kw):
                return f"{method} {url}"

        st.flow_rule_manager.load_rules([st.FlowRule("GET:http://a/b", count=1)])

        async def drive():
            c = GuardedAsyncClient(Stub())
            # Query string must not explode the resource space.
            first = await c.get("http://a/b?q=1")
            blocked = False
            try:
                await c.get("http://a/b")
            except BlockError:
                blocked = True

            async def async_fb(e):
                return "afb"

            fb = await GuardedAsyncClient(Stub(), fallback=lambda e: "fb").get(
                "http://a/b"
            )
            afb = await GuardedAsyncClient(Stub(), fallback=async_fb).put(
                "http://a/b"
            )
            return first, blocked, fb, afb

        st.flow_rule_manager.load_rules(
            [st.FlowRule("GET:http://a/b", count=1),
             st.FlowRule("PUT:http://a/b", count=0)]
        )
        first, blocked, fb, afb = asyncio.run(drive())
        assert first == "GET http://a/b?q=1" and blocked
        assert fb == "fb" and afb == "afb"  # sync + async fallbacks


class TestFrameworkSugar:
    def test_flask_extension(self, manual_clock, engine):
        pytest.importorskip("flask")
        from flask import Flask

        from sentinel_tpu.adapters import SentinelFlask

        app = Flask(__name__)
        SentinelFlask(app, total_resource="flask-total")

        @app.get("/u/<int:uid>")
        def user(uid):
            return "u"

        st.flow_rule_manager.load_rules([st.FlowRule("GET:/u/<int:uid>", count=1)])
        c = app.test_client()
        assert c.get("/u/1").status_code == 200
        assert c.get("/u/2").status_code == 429

    def test_fastapi_dependency(self, manual_clock, engine):
        pytest.importorskip("fastapi")
        from fastapi import Depends, FastAPI
        from fastapi.testclient import TestClient

        from sentinel_tpu.adapters import sentinel_guard

        app = FastAPI()

        @app.get("/g", dependencies=[Depends(sentinel_guard())])
        async def g():
            return {"ok": True}

        st.flow_rule_manager.load_rules([st.FlowRule("GET:/g", count=1)])
        c = TestClient(app)
        assert c.get("/g").status_code == 200
        assert c.get("/g").status_code == 429
