"""MetricArray semantics tests — the equivalent of the reference's
LeapArrayTest (window rollover, bucket reuse, deprecated-window reset)
plus randomized batch-vs-sequential-oracle parity."""

import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.metrics import (
    MetricArrayConfig,
    MetricEvent,
    NUM_EVENTS,
    make_state,
    update,
    window_min_rt,
    window_sums,
)
from sentinel_tpu.testing.oracle import OracleLeapArray

CFG = MetricArrayConfig(sample_count=2, interval_ms=1000)


def _upd(state, rows, ts, event, counts, rt=None):
    rows = jnp.asarray(rows, dtype=jnp.int32)
    ts = jnp.asarray(ts, dtype=jnp.int32)
    n = rows.shape[0]
    deltas = jnp.zeros((n, NUM_EVENTS), dtype=jnp.int32).at[:, event].set(
        jnp.asarray(counts, dtype=jnp.int32)
    )
    rt_arr = None if rt is None else jnp.asarray(rt, dtype=jnp.int32)
    return update(CFG, state, rows, ts, deltas, rt_arr)


def _pass_sum(state, now, row=0):
    return int(window_sums(CFG, state, jnp.int32(now))[row, MetricEvent.PASS])


class TestWindowBasics:
    def test_single_window_accumulates(self):
        s = make_state(4, CFG)
        s = _upd(s, [0, 0, 0], [0, 100, 499], MetricEvent.PASS, [1, 2, 3])
        assert _pass_sum(s, 499) == 6

    def test_two_buckets_within_interval(self):
        s = make_state(4, CFG)
        s = _upd(s, [0, 0], [0, 600], MetricEvent.PASS, [1, 10])
        # at t=900 both buckets valid
        assert _pass_sum(s, 900) == 11

    def test_old_bucket_deprecated_on_read(self):
        s = make_state(4, CFG)
        s = _upd(s, [0], [0], MetricEvent.PASS, [5])
        # At t=1400, bucket [0,500) is 1400ms old > 1000 -> deprecated.
        assert _pass_sum(s, 1400) == 0
        # At t=1000 exactly: age 1000, not > interval -> still counted
        # (LeapArray#isWindowDeprecated is strict).
        assert _pass_sum(s, 1000) == 5

    def test_rollover_resets_bucket(self):
        s = make_state(4, CFG)
        s = _upd(s, [0], [0], MetricEvent.PASS, [5])  # bucket idx 0, ws 0
        s = _upd(s, [0], [1000], MetricEvent.PASS, [7])  # idx 0 again, ws 1000
        # Old ws=0 content must be discarded, not merged.
        assert _pass_sum(s, 1000) == 7

    def test_stale_entry_in_same_batch_dropped(self):
        # Two entries a full interval apart in ONE batch hitting the same
        # slot: sequentially the newer resets the bucket after the older
        # wrote it, so only the newer survives.
        s = make_state(4, CFG)
        s = _upd(s, [0, 0], [0, 1000], MetricEvent.PASS, [5, 7])
        assert _pass_sum(s, 1000) == 7

    def test_rows_independent(self):
        s = make_state(4, CFG)
        s = _upd(s, [0, 1, 2], [0, 0, 0], MetricEvent.PASS, [1, 2, 3])
        sums = window_sums(CFG, s, jnp.int32(0))
        assert sums[0, MetricEvent.PASS] == 1
        assert sums[1, MetricEvent.PASS] == 2
        assert sums[2, MetricEvent.PASS] == 3

    def test_min_rt_tracking(self):
        s = make_state(2, CFG)
        s = _upd(s, [0, 0], [0, 1], MetricEvent.RT, [30, 12], rt=[30, 12])
        assert int(window_min_rt(CFG, s, jnp.int32(10))[0]) == 12
        # empty row keeps the max-RT default
        assert int(window_min_rt(CFG, s, jnp.int32(10))[1]) == CFG.max_rt
        # after expiry it resets
        assert int(window_min_rt(CFG, s, jnp.int32(5000))[0]) == CFG.max_rt

    def test_mask_drops_entries(self):
        s = make_state(2, CFG)
        rows = jnp.asarray([0, 1], dtype=jnp.int32)
        ts = jnp.asarray([0, 0], dtype=jnp.int32)
        deltas = jnp.ones((2, NUM_EVENTS), dtype=jnp.int32)
        s = update(CFG, s, rows, ts, deltas, mask=jnp.asarray([True, False]))
        sums = window_sums(CFG, s, jnp.int32(0))
        assert int(sums[0].sum()) == NUM_EVENTS
        assert int(sums[1].sum()) == 0


class TestOracleParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_batch_parity(self, seed):
        """Random (row, ts, count) streams: batched update must match the
        sequential oracle's window sums at every probe time, for both
        geometries' shapes of traffic."""
        rng = np.random.default_rng(seed)
        n_rows, n_ops = 5, 400
        rows = rng.integers(0, n_rows, n_ops)
        # Nondecreasing timestamps with occasional big jumps.
        ts = np.cumsum(rng.choice([0, 1, 3, 40, 700], n_ops, p=[0.3, 0.4, 0.2, 0.08, 0.02]))
        counts = rng.integers(1, 5, n_ops)

        oracles = [OracleLeapArray(2, 1000) for _ in range(n_rows)]
        for r, t, c in zip(rows, ts, counts):
            oracles[r].add(int(t), MetricEvent.PASS, int(c))

        s = make_state(n_rows, CFG)
        # Apply in flush-sized chunks (mixed-window batches included).
        for lo in range(0, n_ops, 64):
            hi = min(lo + 64, n_ops)
            s = _upd(s, rows[lo:hi], ts[lo:hi], MetricEvent.PASS, counts[lo:hi])

        now = int(ts[-1])
        got = window_sums(CFG, s, jnp.int32(now))
        for r in range(n_rows):
            want = oracles[r].values(now)[MetricEvent.PASS]
            assert int(got[r, MetricEvent.PASS]) == want, f"row {r}"
