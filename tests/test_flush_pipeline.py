"""Depth-K pipelined flush — differential guarantees.

The flush pipeline (``sentinel.tpu.host.pipeline.depth`` > 0) is a pure
host-side scheduling change: encode/dispatch of flush N+1 overlaps the
device execution of flush N, verdicts materialize lazily through one
coalesced device fetch per drain, and device state chains donated from
flush N into N+1 with no host round-trip. None of that may ever change
an admission verdict, a stat, or alias a verdict buffer. These tests
pin the pipelined engine bit-identically against the synchronous
(depth 0) oracle — including across interleaved rule reloads — and pin
the FIFO settle + non-aliasing contracts directly.
"""

import numpy as np
import pytest

from sentinel_tpu.models import constants as C


def _mk_engine(clock, depth):
    from sentinel_tpu.runtime.engine import Engine

    eng = Engine(clock=clock)
    eng.pipeline_depth = depth
    return eng


def _load_rules(engines, flow_count=6.0, param_count=3):
    import sentinel_tpu as st
    from sentinel_tpu.models.rules import ParamFlowRule

    for eng in engines:
        eng.set_flow_rules(
            [
                st.FlowRule("pp", count=flow_count),
                st.FlowRule("qq", count=1e9),
            ]
        )
        eng.set_param_rules(
            {"qq": [ParamFlowRule("qq", param_idx=0, count=param_count)]}
        )


def _run_stream(engines, manual_clock, rng, rounds, reload_at=None):
    """Drive an identical random op stream through every engine
    (flushing each per round WITHOUT reading verdicts — reads would
    force drains and collapse the pipeline); returns the collected
    (bulk groups, single ops) per engine for end-of-stream comparison.
    Shapes are kept constant across rounds so the jit cache is shared.
    """
    collected = [([], []) for _ in engines]
    t = 1000
    for r in range(rounds):
        manual_clock.set_ms(t)
        n_pp = 16
        ts_pp = t + rng.integers(0, 40, n_pp).astype(np.int32)
        ts_pp.sort()
        acq_pp = rng.integers(1, 3, n_pp).astype(np.int32)
        # Heavy-hitter args column with a ts column straddling two
        # values — the mixed-ts segmented closed-form path end-to-end.
        n_qq = 12
        vals = [f"v{int(rng.integers(0, 3))}" for _ in range(n_qq)]
        ts_qq = np.where(
            np.arange(n_qq) < rng.integers(1, n_qq),
            np.int32(t),
            np.int32(t + 700),
        ).astype(np.int32)
        singles = [
            {
                "resource": "qq",
                "ts": int(t + rng.integers(0, 50)),
                "args": (f"v{int(rng.integers(0, 3))}",),
            }
            for _ in range(4)
        ]
        for eng, (groups, ops) in zip(engines, collected):
            g1 = eng.submit_bulk("pp", n_pp, ts=ts_pp, acquire=acq_pp)
            g2 = eng.submit_bulk(
                "qq", n_qq, ts=ts_qq, args_column=[(v,) for v in vals]
            )
            ops.extend(eng.submit_many([dict(s) for s in singles]))
            rows = eng.resolve_entry_rows(
                "pp", C.CONTEXT_DEFAULT_NAME, "", C.EntryType.OUT
            )
            eng.submit_exit_bulk(rows, 4, rt=10, ts=np.full(4, t, np.int32))
            eng.flush()
            assert len(eng._pending_fetches) <= eng.pipeline_depth
            groups.extend([g1, g2])
        if reload_at is not None and r == reload_at:
            # Reload mid-stream while flushes are in flight: pending
            # fetches hold their own index snapshots; post-reload ops
            # resolve against the new tables on every engine alike.
            _load_rules(engines, flow_count=4.0, param_count=2)
        t += int(rng.integers(100, 900))
    for eng in engines:
        eng.drain()
    return collected


def _assert_streams_identical(engines, collected):
    oracle_groups, oracle_ops = collected[0]
    for eng, (groups, ops) in zip(engines[1:], collected[1:]):
        for go, gp in zip(oracle_groups, groups):
            assert gp.admitted.tolist() == go.admitted.tolist()
            assert gp.reason.tolist() == go.reason.tolist()
            assert gp.wait_ms.tolist() == go.wait_ms.tolist()
        for oo, op in zip(oracle_ops, ops):
            assert (op is None) == (oo is None)
            if op is None:
                continue
            vo, vp = oo.verdict, op.verdict
            assert (vp.admitted, vp.reason, vp.wait_ms) == (
                vo.admitted, vo.reason, vo.wait_ms,
            )
        for res in ("pp", "qq"):
            assert eng.cluster_node_stats(res) == engines[0].cluster_node_stats(
                res
            ), res


class TestPipelineParity:
    @pytest.mark.parametrize("depth", [1, 2])
    def test_depth_parity_with_reload(self, manual_clock, depth):
        """Random op streams (flow-limited bulk, mixed-ts hot-param
        bulk, deferred singles, bulk exits) at pipeline depth {1,2}
        produce bit-identical verdicts and node stats vs the
        synchronous depth-0 oracle, across a mid-stream rule reload."""
        engines = [_mk_engine(manual_clock, d) for d in (0, depth)]
        _load_rules(engines)
        rng = np.random.default_rng(depth)
        collected = _run_stream(
            engines, manual_clock, rng, rounds=5, reload_at=2
        )
        _assert_streams_identical(engines, collected)

    @pytest.mark.slow
    def test_depth4_soak(self, manual_clock):
        """Longer stream at depth 4 (queue deeper than max_inflight),
        two reloads, vs the synchronous oracle."""
        engines = [_mk_engine(manual_clock, d) for d in (0, 4)]
        _load_rules(engines)
        rng = np.random.default_rng(99)
        t = 1000
        collected = [([], []) for _ in engines]
        for phase, reload_at in ((0, 3), (1, 6)):
            part = _run_stream(
                engines, manual_clock, rng, rounds=8, reload_at=reload_at
            )
            for (g, o), (pg, po) in zip(collected, part):
                g.extend(pg)
                o.extend(po)
        _assert_streams_identical(engines, collected)


class TestPipelineMechanics:
    def test_flush_settles_queue_fifo(self, manual_clock):
        """A pipelined flush trims the in-flight queue oldest-first:
        at depth 1, the second flush materializes the first flush's
        verdicts without any explicit read."""
        import sentinel_tpu as st

        eng = _mk_engine(manual_clock, 1)
        eng.set_flow_rules([st.FlowRule("ff", count=8)])
        manual_clock.set_ms(1000)
        g1 = eng.submit_bulk("ff", 8, ts=np.full(8, 1000, np.int32))
        eng.flush()
        assert g1._admitted is None  # still in flight — lazily filled
        g2 = eng.submit_bulk("ff", 8, ts=np.full(8, 1000, np.int32))
        eng.flush()
        # The queue trim settled g1 (FIFO), g2 is the one in flight.
        assert g1._admitted is not None
        assert g2._admitted is None
        assert g1.admitted_count == 8
        assert g2.admitted_count == 0  # budget spent by g1; read drains
        assert len(eng._pending_fetches) == 0
        # Post-trim occupancy sampling: a saturated depth-1 pipeline
        # reads exactly 1.0, never depth+1.
        ps = eng.pipeline_stats()
        assert ps["dispatches"] == 2.0 and ps["mean_inflight"] == 1.0

    def test_verdict_buffers_do_not_alias_across_inflight(self, manual_clock):
        """With several flushes in flight sharing arena staging, the
        materialized verdict arrays must share no memory with each
        other or with the pooled staging buffers."""
        import sentinel_tpu as st

        eng = _mk_engine(manual_clock, 3)
        eng.set_flow_rules([st.FlowRule("al", count=10)])
        manual_clock.set_ms(1000)
        groups = []
        for _ in range(3):
            groups.append(eng.submit_bulk("al", 8, ts=np.full(8, 1000, np.int32)))
            eng.flush()
        assert len(eng._pending_fetches) == 3
        eng.drain()
        arrays = [a for g in groups for a in (g.admitted, g.reason, g.wait_ms)]
        for i, a in enumerate(arrays):
            for b in arrays[i + 1:]:
                assert not np.shares_memory(a, b)
        if eng._arena is not None:
            for sets in eng._arena._pool.values():
                for bufs in sets:
                    for buf in bufs:
                        for a in arrays:
                            assert not np.shares_memory(a, buf)
        # Verdicts survived the later in-flight flushes bit-for-bit
        # (count=10 budget: 8, then 2, then none).
        assert groups[0].admitted_count == 8
        assert groups[1].admitted_count == 2
        assert groups[2].admitted_count == 0

    def test_arena_sized_to_depth(self, manual_clock):
        """Raising the pipeline depth raises the arena per-key bound so
        deep pipelines keep reusing staging instead of silently
        allocating fresh buffers."""
        eng = _mk_engine(manual_clock, 0)
        if eng._arena is None:
            pytest.skip("fastpath off")
        base = eng._arena.per_key
        eng.pipeline_depth = 7
        assert eng._arena.per_key >= 8 and eng._arena.per_key >= base

    def test_empty_flush_settles_whole_queue(self, manual_clock):
        """A trailing flush() with nothing new to dispatch settles the
        in-flight queue completely — fire-and-forget callers must not
        have post work (block log, token releases) stranded behind the
        last ``depth`` flushes until the next traffic."""
        import sentinel_tpu as st

        eng = _mk_engine(manual_clock, 2)
        eng.set_flow_rules([st.FlowRule("ef", count=4)])
        manual_clock.set_ms(1000)
        g = eng.submit_bulk("ef", 8, ts=np.full(8, 1000, np.int32))
        eng.flush()
        assert len(eng._pending_fetches) == 1
        eng.flush()  # empty: drains fully instead of keeping depth
        assert len(eng._pending_fetches) == 0
        assert g._admitted is not None and g.admitted_count == 4

    def test_gateway_flush_on_size_keeps_pipeline(self, manual_clock):
        """gateway_submit_bulk(flush=True) on a window that trips the
        engine's flush-on-size must not follow up with an EMPTY flush —
        that would settle the whole queue and silently de-pipeline
        exactly the max_batch-sized windows."""
        import sentinel_tpu as st
        from sentinel_tpu.adapters.gateway import (
            GatewayFlowRule,
            GatewayRequestBatch,
            gateway_rule_manager,
            gateway_submit_bulk,
        )

        eng = _mk_engine(manual_clock, 2)
        eng.max_batch = 8
        route = "gwp"
        gateway_rule_manager.load_rules([GatewayFlowRule(route, count=1e9)])
        eng.set_flow_rules([st.FlowRule(route, count=5)])
        manual_clock.set_ms(1000)
        ts = np.full(8, 1000, np.int32)
        g = gateway_submit_bulk(
            route, GatewayRequestBatch(n=8), engine=eng, ts=ts, flush=True
        )
        # flush-on-size dispatched the window; the in-flight record
        # must still be queued (not drained by an empty follow-up).
        assert len(eng._pending_fetches) == 1
        assert g._admitted is None
        assert g.admitted_count == 5  # lazy materialization still works
        eng.close()
        """With breaker state-change observers registered, the deferred
        fetch holds a breaker-state snapshot — which must be a COPY:
        the next flush donates degrade_dyn into its kernel, deleting
        the live buffer before the deferred device_get runs ('Array
        has been deleted'). Several pipelined flushes with a breaker
        tripping must drain cleanly and fire the OPEN transition."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import DegradeRule
        from sentinel_tpu.rules import breaker_events

        eng = _mk_engine(manual_clock, 2)
        eng.set_flow_rules([st.FlowRule("bk", count=1e9)])
        eng.set_degrade_rules(
            [DegradeRule(resource="bk", grade=1, count=0.1, time_window=5,
                         min_request_amount=1, stat_interval_ms=1000)]
        )
        events = []
        breaker_events.add_state_change_observer(
            "t", lambda *a, **kw: events.append(a)
        )
        try:
            rows = eng.resolve_entry_rows(
                "bk", C.CONTEXT_DEFAULT_NAME, "", C.EntryType.OUT
            )
            for i in range(4):
                t = 1000 + i * 50
                manual_clock.set_ms(t)
                eng.submit_bulk("bk", 4, ts=np.full(4, t, np.int32))
                eng.submit_exit_bulk(
                    rows, 4, rt=10, err=1, ts=np.full(4, t, np.int32),
                    resource="bk",
                )
                eng.flush()
            eng.drain()  # must not raise "Array has been deleted"
            assert events  # the error-ratio breaker opened and fired
        finally:
            breaker_events.clear()

    def test_close_settles_pipeline(self, manual_clock):
        import sentinel_tpu as st

        eng = _mk_engine(manual_clock, 2)
        eng.set_flow_rules([st.FlowRule("cl", count=4)])
        manual_clock.set_ms(1000)
        g = eng.submit_bulk("cl", 8, ts=np.full(8, 1000, np.int32))
        eng.flush()
        eng.close()
        assert len(eng._pending_fetches) == 0
        assert g._admitted is not None and g.admitted_count == 4


class TestBreakerNetEdgeDepth2:
    """rules/breaker_events.py net-edge semantics under the depth-2
    pipeline (ISSUE 4 satellite): a transition DISPATCHED in flush i is
    observed only when flush i's record materializes — at the queue
    trim of flush i+2 (depth 2 keeps two in flight) or at drain — and
    fires exactly once, never replayed by later drains. Previously only
    exercised at depth 0 (tests/test_degrade.py)."""

    @pytest.fixture(autouse=True)
    def _clean_registry(self):
        from sentinel_tpu.rules import breaker_events

        breaker_events.clear()
        yield
        breaker_events.clear()

    def _mk(self, manual_clock):
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import DegradeRule

        eng = _mk_engine(manual_clock, 2)
        eng.set_flow_rules([st.FlowRule("ne", count=1e9)])
        eng.set_degrade_rules(
            [DegradeRule(resource="ne", grade=C.DEGRADE_GRADE_EXCEPTION_RATIO,
                         count=0.2, time_window=2, min_request_amount=1,
                         stat_interval_ms=1000)]
        )
        return eng

    def test_trip_observed_at_drain_of_later_flush_exactly_once(
        self, manual_clock
    ):
        from sentinel_tpu.rules import breaker_events
        from sentinel_tpu.rules.degrade_table import CLOSED, OPEN

        eng = self._mk(manual_clock)
        events = []
        breaker_events.add_state_change_observer(
            "t", lambda prev, new, rule, res: events.append((prev, new, res))
        )
        rows = eng.resolve_entry_rows(
            "ne", C.CONTEXT_DEFAULT_NAME, "", C.EntryType.OUT
        )
        # Flush i: entries + all-error exits trip the breaker on
        # device. Dispatched without fetching — NOT yet observed.
        manual_clock.set_ms(1000)
        eng.submit_bulk("ne", 4, ts=np.full(4, 1000, np.int32))
        eng.submit_exit_bulk(
            rows, 4, rt=5, err=1, ts=np.full(4, 1000, np.int32), resource="ne"
        )
        eng.flush()
        assert events == [], "transition still in flight after flush i"
        # Flush i+1: dispatches; queue holds (i, i+1) = depth 2 — the
        # trim settles nothing, so the transition stays unobserved.
        manual_clock.set_ms(1100)
        eng.submit_bulk("ne", 1, ts=np.full(1, 1100, np.int32))
        eng.flush()
        assert events == [], "depth-2 queue not yet over depth"
        # Flush i+2's trim materializes flush i's record: the
        # CLOSED->OPEN net edge fires HERE, at the drain of a later
        # flush, exactly once.
        manual_clock.set_ms(1200)
        eng.submit_bulk("ne", 1, ts=np.full(1, 1200, np.int32))
        eng.flush()
        assert events == [(CLOSED, OPEN, "ne")]
        # Draining the remaining in-flight records replays nothing:
        # their snapshots show the same OPEN state (newest-wins mirror).
        eng.drain()
        assert events == [(CLOSED, OPEN, "ne")]
        eng.close()

    def test_full_cycle_matches_depth0_sequence(self, manual_clock):
        """Differential against the depth-0 oracle: the same op stream
        produces the same observed transition SEQUENCE at depth 2 —
        only the observation time moves (to the drain)."""
        from sentinel_tpu.rules import breaker_events
        from sentinel_tpu.rules.degrade_table import CLOSED, HALF_OPEN, OPEN

        sequences = {}
        for depth in (0, 2):
            breaker_events.clear()
            eng = self._mk(manual_clock)
            eng.pipeline_depth = depth
            events = []
            breaker_events.add_state_change_observer(
                "t", lambda prev, new, rule, res: events.append((prev, new))
            )
            rows = eng.resolve_entry_rows(
                "ne", C.CONTEXT_DEFAULT_NAME, "", C.EntryType.OUT
            )
            # Trip.
            manual_clock.set_ms(1000)
            eng.submit_bulk("ne", 4, ts=np.full(4, 1000, np.int32))
            eng.submit_exit_bulk(
                rows, 4, rt=5, err=1, ts=np.full(4, 1000, np.int32),
                resource="ne",
            )
            eng.flush()
            # Past the retry window: a probe admission (OPEN->HALF_OPEN
            # on device in this flush), its success exit in the next
            # flush closes the breaker (HALF_OPEN->CLOSED).
            manual_clock.set_ms(4000)
            eng.submit_bulk("ne", 1, ts=np.full(1, 4000, np.int32))
            eng.flush()
            eng.submit_exit_bulk(
                rows, 1, rt=5, err=0, ts=np.full(1, 4050, np.int32),
                resource="ne",
            )
            manual_clock.set_ms(4100)
            eng.flush()
            eng.drain()
            sequences[depth] = list(events)
            eng.close()
        assert sequences[0] == sequences[2], sequences
        assert sequences[0] == [
            (CLOSED, OPEN), (OPEN, HALF_OPEN), (HALF_OPEN, CLOSED)
        ]


class TestMixedTsClosedForm:
    def test_engine_selects_segmented_mode(self, engine):
        """Mixed-timestamp QPS DEFAULT uniform-acquire batches select
        the segmented closed-form (negative rounds beyond −1); too many
        distinct timestamps per row falls back to rounds/scan."""
        prow = np.zeros(8, dtype=np.int32)
        grade = np.full(8, C.FLOW_GRADE_QPS, np.int32)
        beh = np.full(8, C.CONTROL_BEHAVIOR_DEFAULT, np.int32)
        acq = np.ones(8, np.int32)
        two_ts = np.where(np.arange(8) < 4, 1000, 2500).astype(np.int32)
        assert engine._param_rounds_for(prow, grade, beh, two_ts, acq) == -2
        prow12 = np.zeros(12, dtype=np.int32)
        grade12 = np.full(12, C.FLOW_GRADE_QPS, np.int32)
        beh12 = np.full(12, C.CONTROL_BEHAVIOR_DEFAULT, np.int32)
        many_ts = (1000 + np.arange(12) * 100).astype(np.int32)
        assert (
            engine._param_rounds_for(
                prow12, grade12, beh12, many_ts, np.ones(12, np.int32)
            )
            > 0
        )  # 12 distinct ts per row > PARAM_CLOSED_MAX_SEGMENTS → rounds/scan
        # Globally mixed but single-ts per row stays the plain −1 path.
        rows2 = np.arange(8, dtype=np.int32) % 2
        per_row_ts = np.where(rows2 == 0, 1000, 2500).astype(np.int32)
        assert engine._param_rounds_for(rows2, grade, beh, per_row_ts, acq) == -1

    def test_window_edge_bulk_matches_oracle(self, manual_clock, engine):
        """A bulk group whose ts column straddles a refill boundary:
        the segmented closed-form grants exactly what the sequential
        reference (OracleParamBucket) grants per value, per window."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule
        from sentinel_tpu.testing.oracle import OracleParamBucket

        count = 3
        engine.set_flow_rules([st.FlowRule("mx", count=1e9)])
        engine.set_param_rules(
            {"mx": [ParamFlowRule("mx", param_idx=0, count=count)]}
        )
        manual_clock.set_ms(1000)
        n = 24
        vals = [f"k{i % 2}" for i in range(n)]
        ts = np.where(np.arange(n) < n // 2, 1000, 2400).astype(np.int32)
        g = engine.submit_bulk(
            "mx", n, ts=ts, args_column=[(v,) for v in vals]
        )
        engine.flush()
        buckets = {}
        expect = []
        for v, t in zip(vals, ts):
            b = buckets.setdefault(v, OracleParamBucket(count, 0, 1000))
            expect.append(b.check(int(t)))
        assert g.admitted.tolist() == expect
        # Both windows granted: count per value per window.
        adm = np.asarray(g.admitted)
        assert int(adm[: n // 2].sum()) == 2 * count
        assert int(adm[n // 2:].sum()) == 2 * count
