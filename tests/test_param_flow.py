"""Hot-parameter flow control tests (reference:
ParamFlowChecker / ParameterMetric semantics)."""

import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ParamFlowItem


def qps_rule(resource, count, idx=0, burst=0, duration=1, items=()):
    return st.ParamFlowRule(
        resource,
        grade=C.FLOW_GRADE_QPS,
        param_idx=idx,
        count=count,
        burst_count=burst,
        duration_in_sec=duration,
        param_flow_item_list=tuple(items),
    )


class TestTokenBucket:
    def test_per_value_isolation(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules([qps_rule("api", 2)])
        # Value "a": 2 tokens then blocked; value "b" independent.
        assert st.try_entry("api", args=("a",)) is not None
        assert st.try_entry("api", args=("a",)) is not None
        assert st.try_entry("api", args=("a",)) is None
        assert st.try_entry("api", args=("b",)) is not None

    def test_refill_after_duration(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules([qps_rule("r", 2, duration=1)])
        manual_clock.set_ms(0)
        assert st.try_entry("r", args=("k",)) is not None  # tokens: 2-1=1
        assert st.try_entry("r", args=("k",)) is not None  # 0
        assert st.try_entry("r", args=("k",)) is None
        # passTime > 1000ms refills to maxCount then consumes.
        manual_clock.set_ms(1500)
        assert st.try_entry("r", args=("k",)) is not None
        assert st.try_entry("r", args=("k",)) is not None
        assert st.try_entry("r", args=("k",)) is None

    def test_burst_count(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules([qps_rule("b", 1, burst=2)])
        # maxCount = 1 + 2 = 3 on first fill.
        for _ in range(3):
            assert st.try_entry("b", args=("x",)) is not None
        assert st.try_entry("b", args=("x",)) is None

    def test_hot_item_override(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules(
            [qps_rule("h", 1, items=[ParamFlowItem(object="vip", count=5)])]
        )
        for _ in range(5):
            assert st.try_entry("h", args=("vip",)) is not None
        assert st.try_entry("h", args=("vip",)) is None
        assert st.try_entry("h", args=("pleb",)) is not None
        assert st.try_entry("h", args=("pleb",)) is None

    def test_zero_count_blocks(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules([qps_rule("z", 0)])
        assert st.try_entry("z", args=("v",)) is None

    def test_missing_param_passes(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules([qps_rule("m", 1, idx=2)])
        # args shorter than param_idx -> rule skipped.
        assert st.try_entry("m", args=("only-one",)) is not None
        assert st.try_entry("m", args=("only-one",)) is not None

    def test_collection_arg_checks_each(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules([qps_rule("c", 1)])
        # list arg -> every element checked; "u1" exhausted by first entry.
        assert st.try_entry("c", args=(["u1", "u2"],)) is not None
        assert st.try_entry("c", args=(["u3", "u1"],)) is None

    def test_batched_deferred(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules([qps_rule("d", 3)])
        ops = [
            engine.submit_entry("d", ts=0, args=("k",)) for _ in range(6)
        ]
        engine.flush()
        assert [op.verdict.admitted for op in ops] == [True] * 3 + [False] * 3


class TestThrottle:
    def test_paced_per_value(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules(
            [
                st.ParamFlowRule(
                    "t",
                    grade=C.FLOW_GRADE_QPS,
                    param_idx=0,
                    count=10,  # cost 100ms
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                    max_queueing_time_ms=250,
                )
            ]
        )
        manual_clock.set_ms(0)
        # First request for the value passes free (recorder created).
        assert st.try_entry("t", args=("v",)) is not None
        # Next at t=0: expected=100 -> wait 100 < 250 -> queued pass.
        e = st.try_entry("t", args=("v",))
        assert e is not None
        assert manual_clock.now_ms() == 100  # API slept the wait
        # expected=200, now=100 -> wait 100 -> pass (sleeps to 200)
        assert st.try_entry("t", args=("v",)) is not None
        # expected=300, now=200 -> wait=100 pass; then wait becomes >= 250
        assert st.try_entry("t", args=("v",)) is not None
        assert manual_clock.now_ms() == 300
        # expected=400, now=300: wait 100 pass -> now 400... keep pushing
        # until the queue bound: issue rapid requests at a frozen instant.
        manual_clock.set_ms(400)


class TestThreadGrade:
    def test_per_value_concurrency(self, manual_clock, engine):
        st.param_flow_rule_manager.load_rules(
            [
                st.ParamFlowRule(
                    "svc", grade=C.FLOW_GRADE_THREAD, param_idx=0, count=2
                )
            ]
        )
        e1 = st.try_entry("svc", args=("u",))
        e2 = st.try_entry("svc", args=("u",))
        assert e1 is not None and e2 is not None
        assert st.try_entry("svc", args=("u",)) is None  # 2 running for "u"
        assert st.try_entry("svc", args=("w",)) is not None  # other value free
        e1.exit()
        assert st.try_entry("svc", args=("u",)) is not None


class TestEviction:
    def test_lru_eviction_resets_state(self, manual_clock, engine):
        # Tiny cap via duration=1 -> cap = 4000; simulate eviction by
        # directly shrinking the per-rule cap.
        st.param_flow_rule_manager.load_rules([qps_rule("ev", 1)])
        engine.param_index._caps[0] = 2
        assert st.try_entry("ev", args=("a",)) is not None
        assert st.try_entry("ev", args=("b",)) is not None
        assert st.try_entry("ev", args=("a",)) is None  # a exhausted
        # Interning "c" evicts LRU ("b" was most recent... "a" touched last).
        assert st.try_entry("ev", args=("c",)) is not None
        # "b" was evicted; re-seen -> fresh bucket.
        assert st.try_entry("ev", args=("b",)) is not None


def test_manager_construction_applies_cleanly(caplog):
    """Constructing the manager must not run _apply on a half-built
    instance: DynamicSentinelProperty.add_listener fires config_load
    synchronously from the base __init__, so subclass fields _apply
    reads (here _gateway_rules) must be initialized first. The bug's
    signature was a 'Failed to apply rules' ERROR in the record log on
    every import."""
    import logging

    from sentinel_tpu.rules.param_manager import ParamFlowRuleManager

    with caplog.at_level(logging.ERROR, logger="sentinel_tpu.record"):
        mgr = ParamFlowRuleManager()
    assert mgr.by_resource == {}
    assert not [r for r in caplog.records if "Failed to apply" in r.message]
