"""Live retune of the second-window geometry.

Reference semantics: node/SampleCountProperty.java:33-52 +
node/IntervalProperty.java — updating either property rebuilds every
node's rolling second counter at runtime and RESETS its second-window
statistics; minute windows and thread gauges are untouched.
"""

import jax.numpy as jnp
import pytest

import sentinel_tpu as st
from sentinel_tpu.metrics import nodes
from sentinel_tpu.models import constants as C


def _admitted(n, resource="res"):
    return sum(st.try_entry(resource) is not None for _ in range(n))


class TestRetune:
    def test_geometry_swap_mid_stream(self, manual_clock, engine):
        """2×500 ms → 4×250 ms mid-stream: tensors rebuilt, enforcement
        continues on the new layout with a clean stats reset."""
        st.flow_rule_manager.load_rules([st.FlowRule("res", count=10)])
        assert _admitted(15) == 10
        assert engine.stats.second.counts.shape[1] == 2

        engine.retune_second_window(4, 1000)
        assert nodes.SECOND_CFG.sample_count == 4
        assert nodes.SECOND_CFG.window_len_ms == 250
        assert engine.stats.second.counts.shape[1] == 4
        assert engine.stats.future_pass.shape[1] == 4

        # Statistics reset (the reference's documented behavior): the
        # full budget is available again in the same wall-clock window.
        assert _admitted(15) == 10

        # The new 250 ms buckets roll correctly: after 750 ms, the
        # first ~3 buckets of spend age out across the window edge.
        manual_clock.advance(1001)
        assert _admitted(15) == 10

    def test_interval_only_change_retraces(self, manual_clock, engine):
        """Interval-only retune keeps every tensor shape; the win_key
        static arg must still force a re-trace so thresholds use the
        new interval (a stale cache would admit 5, not 10, per 2 s)."""
        st.flow_rule_manager.load_rules([st.FlowRule("res", count=5)])
        assert _admitted(10) == 5  # 5/s over the default 1 s window

        engine.retune_second_window(2, 2000)
        assert engine.stats.second.counts.shape[1] == 2  # same shape!
        # count=5 QPS over a 2 s window = 10 admissions per window.
        assert _admitted(20) == 10
        manual_clock.advance(2001)
        assert _admitted(20) == 10

    def test_minute_window_and_threads_survive(self, manual_clock, engine):
        """Only the second window resets — minute totals and live
        thread gauges carry over (the reference rebuilds
        rollingCounterInSecond alone)."""
        st.flow_rule_manager.load_rules([st.FlowRule("res", count=100)])
        e1 = st.entry("res")
        e2 = st.entry("res")
        for _ in range(10):
            ee = st.try_entry("res")
            if ee is not None:
                ee.exit()
        stats_before = engine.cluster_node_stats("res")
        assert stats_before["total_pass_minute"] >= 10

        engine.retune_second_window(4, 1000)
        stats_after = engine.cluster_node_stats("res")
        # Minute-window totals survive the retune.
        assert stats_after["total_pass_minute"] == stats_before["total_pass_minute"]
        # Thread gauge survives: both held entries still counted.
        assert stats_after["cur_thread_num"] == 2
        e1.exit()
        e2.exit()
        assert engine.cluster_node_stats("res")["cur_thread_num"] == 0

    def test_invalid_geometry_rejected(self, manual_clock, engine):
        with pytest.raises(ValueError):
            engine.retune_second_window(3, 1000)  # 3 does not divide 1000
        assert nodes.SECOND_CFG.sample_count == C.DEFAULT_SAMPLE_COUNT

    def test_noop_retune_keeps_state(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("res", count=5)])
        assert _admitted(3) == 3
        engine.retune_second_window(
            C.DEFAULT_SAMPLE_COUNT, C.DEFAULT_WINDOW_INTERVAL_MS
        )
        # Same geometry → no reset: only 2 of the budget remain.
        assert _admitted(5) == 2

    def test_properties_drive_retune(self, manual_clock, engine):
        """SampleCountProperty/IntervalProperty parity: pushing values
        through the exported properties retunes the live engine."""
        st.sample_count_property.update_value(4)
        assert nodes.SECOND_CFG.sample_count == 4
        assert st.get_engine().stats.second.counts.shape[1] == 4
        st.interval_property.update_value(2000)
        assert nodes.SECOND_CFG.interval_ms == 2000
        assert nodes.SECOND_CFG.window_len_ms == 500
        # Invalid combos are ignored, not raised (property path).
        st.sample_count_property.update_value(3)  # 3 ∤ 2000
        assert nodes.SECOND_CFG.sample_count == 4

    def test_reset_restores_default_geometry(self, manual_clock):
        from sentinel_tpu.core import api

        api.get_engine().retune_second_window(4, 2000)
        assert nodes.SECOND_CFG.sample_count == 4
        api.reset(clock=manual_clock)
        assert nodes.SECOND_CFG.sample_count == C.DEFAULT_SAMPLE_COUNT
        assert nodes.SECOND_CFG.interval_ms == C.DEFAULT_WINDOW_INTERVAL_MS

    def test_repush_same_value_after_reset(self, manual_clock):
        """reset() clears the property values too: re-delivering the
        SAME geometry after a reset must retune again, not be dropped
        by the property's equality check."""
        from sentinel_tpu.core import api

        st.sample_count_property.update_value(4)
        assert nodes.SECOND_CFG.sample_count == 4
        api.reset(clock=manual_clock)
        assert nodes.SECOND_CFG.sample_count == C.DEFAULT_SAMPLE_COUNT
        st.sample_count_property.update_value(4)  # same value as before
        assert nodes.SECOND_CFG.sample_count == 4
        assert api.get_engine().stats.second.counts.shape[1] == 4
