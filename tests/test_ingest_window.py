"""Adapter-edge batch window (runtime/window.py) — the columnar ingest
spine.

The acceptance tests: batched-window verdicts are bit-identical to the
sequential per-request path at pipeline depths {0, 2}; every adapter
rides the spine with window-off parity preserved; traceparent identity
and Verdict.speculative/provenance survive the batching boundary; the
shed valve applies BEFORE window assembly, queued window contents count
toward ``max.pending.bulk``, a whole window can shed at flush, and
exits are never shed.
"""

import asyncio
import threading

import pytest

import sentinel_tpu as st
from sentinel_tpu.core import api
from sentinel_tpu.core import errors as E
from sentinel_tpu.models import constants as C
from sentinel_tpu.runtime.window import WindowRequest
from sentinel_tpu.utils.config import config


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _windowed_engine(manual_clock, depth=0, window_ms="50", batch_max="64",
                     **extra):
    config.set(config.INGEST_BATCH_WINDOW_MS, window_ms)
    config.set(config.INGEST_BATCH_MAX, batch_max)
    config.set(config.PIPELINE_DEPTH, str(depth))
    for k, v in extra.items():
        config.set(k, v)
    eng = api.reset(clock=manual_clock)
    return eng


def _load_rules():
    st.flow_rule_manager.load_rules([st.FlowRule("win-res", count=3)])
    st.param_flow_rule_manager.load_rules(
        [st.ParamFlowRule("win-param", param_idx=0, count=2)]
    )


def _drive_spine(eng, reqs):
    """Join pre-built WindowRequests in order; returns them decided."""
    w = eng.ingest_window
    for r in reqs:
        w.join(r)
    for r in reqs:
        r.event.wait(30)
        assert r.error is None, r.error
        assert r.verdict is not None, "window fan-out missed a request"
    return reqs


class TestSpineParity:
    """Bit-identical verdicts vs the sequential per-request oracle."""

    @pytest.mark.parametrize("depth", [0, 2])
    def test_flow_and_param_bit_identical(self, manual_clock, depth):
        n = 12
        seq = [("win-res", ()) for _ in range(6)] + [
            ("win-param", (f"ip{i % 3}",)) for i in range(6)
        ]
        # --- sequential oracle (window off) ---
        config.set(config.PIPELINE_DEPTH, str(depth))
        eng = api.reset(clock=manual_clock)
        _load_rules()
        manual_clock.set_ms(1000)
        oracle = []
        for res, args in seq:
            _, v = eng.entry_sync(res, entry_type=C.EntryType.IN, args=args)
            oracle.append((v.admitted, v.reason, v.wait_ms))
        eng.flush()
        eng.drain()
        # --- one batched window, same order, same ts ---
        eng = _windowed_engine(manual_clock, depth=depth,
                               batch_max=str(n))
        _load_rules()
        manual_clock.set_ms(1000)
        reqs = [
            WindowRequest(res, C.CONTEXT_DEFAULT_NAME, "", 1,
                          C.EntryType.IN, args, eng.clock.now_ms(), None)
            for res, args in seq
        ]
        _drive_spine(eng, reqs)
        got = [(r.verdict.admitted, r.verdict.reason, r.verdict.wait_ms)
               for r in reqs]
        assert got == oracle, f"depth={depth}"
        eng.flush()
        eng.drain()

    @pytest.mark.parametrize("depth", [0, 2])
    def test_spine_parity_speculative(self, manual_clock, depth):
        """With the fast tier on, windowed verdicts carry
        Verdict.speculative and still match the sequential tier's
        decisions."""
        config.set(config.PIPELINE_DEPTH, str(depth))
        config.set(config.SPECULATIVE_ENABLED, "true")
        eng = api.reset(clock=manual_clock)
        _load_rules()
        manual_clock.set_ms(1000)
        oracle = []
        for _ in range(6):
            _, v = eng.entry_sync("win-res", entry_type=C.EntryType.IN)
            oracle.append((v.admitted, v.reason, v.speculative))
        eng.flush()
        eng.drain()
        eng = _windowed_engine(
            manual_clock, depth=depth, batch_max="6",
            **{config.SPECULATIVE_ENABLED: "true"},
        )
        _load_rules()
        manual_clock.set_ms(1000)
        reqs = [
            WindowRequest("win-res", C.CONTEXT_DEFAULT_NAME, "", 1,
                          C.EntryType.IN, (), eng.clock.now_ms(), None)
            for _ in range(6)
        ]
        _drive_spine(eng, reqs)
        got = [(r.verdict.admitted, r.verdict.reason, r.verdict.speculative)
               for r in reqs]
        assert got == oracle
        assert all(r.verdict.speculative for r in reqs)
        eng.flush()
        eng.drain()


def _wsgi_call(app, path="/x"):
    environ = {
        "PATH_INFO": path, "REQUEST_METHOD": "GET",
        "HTTP_TRACEPARENT":
            "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01",
    }
    status = {}

    def start_response(s, headers):
        status["s"] = s

    body = b"".join(app(environ, start_response))
    return status["s"], body


class TestAdapterParity:
    """Each adapter: window-on verdict counts match window-off, with
    the 3-of-6 QPS rule. Multiset parity (concurrent arrival order into
    the window is not deterministic; the per-index contract is pinned
    by TestSpineParity)."""

    N, LIMIT = 6, 3

    def _rules(self, resource):
        st.flow_rule_manager.load_rules(
            [st.FlowRule(resource, count=self.LIMIT)]
        )

    @pytest.mark.parametrize("depth", [0, 2])
    def test_wsgi(self, manual_clock, depth):
        from sentinel_tpu.adapters import SentinelWSGIMiddleware

        def inner(environ, start_response):
            start_response("200 OK", [])
            return [b"ok"]

        for window in (False, True):
            eng = _windowed_engine(
                manual_clock, depth=depth,
                window_ms="20" if window else "0", batch_max=str(self.N),
            )
            self._rules("GET:/x")
            manual_clock.set_ms(1000)
            app = SentinelWSGIMiddleware(inner, total_resource=None)
            results = []
            lock = threading.Lock()

            def call():
                s, _ = _wsgi_call(app)
                with lock:
                    results.append(s)

            ths = [threading.Thread(target=call) for _ in range(self.N)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(30)
            ok = sum(1 for s in results if s.startswith("200"))
            blocked = sum(1 for s in results if s.startswith("429"))
            assert (ok, blocked) == (self.LIMIT, self.N - self.LIMIT), (
                f"window={window} depth={depth}: {results}"
            )
            eng.flush()
            eng.drain()
            assert eng.cluster_node_stats("GET:/x")["cur_thread_num"] == 0

    @pytest.mark.parametrize("depth", [0, 2])
    def test_asgi(self, manual_clock, depth):
        from sentinel_tpu.adapters import SentinelASGIMiddleware

        async def inner(scope, receive, send):
            await send({"type": "http.response.start", "status": 200,
                        "headers": []})
            await send({"type": "http.response.body", "body": b"ok"})

        for window in (False, True):
            eng = _windowed_engine(
                manual_clock, depth=depth,
                window_ms="20" if window else "0", batch_max=str(self.N),
            )
            self._rules("GET:/a")
            manual_clock.set_ms(1000)
            app = SentinelASGIMiddleware(inner, total_resource=None)

            async def call():
                msgs = []

                async def send(msg):
                    msgs.append(msg)

                async def receive():
                    return {"type": "http.request"}

                await app(
                    {"type": "http", "method": "GET", "path": "/a",
                     "headers": []},
                    receive, send,
                )
                return msgs[0]["status"]

            async def main():
                return await asyncio.gather(*[call() for _ in range(self.N)])

            statuses = asyncio.run(main())
            assert sorted(statuses) == [200] * self.LIMIT + [429] * (
                self.N - self.LIMIT
            ), f"window={window} depth={depth}"
            eng.flush()
            eng.drain()

    @pytest.mark.parametrize("depth", [0, 2])
    def test_aiohttp(self, manual_clock, depth):
        aiohttp = pytest.importorskip("aiohttp")
        from aiohttp import web
        from aiohttp.test_utils import make_mocked_request

        from sentinel_tpu.adapters.aiohttp_adapter import sentinel_middleware

        async def handler(request):
            return web.Response(text="ok")

        for window in (False, True):
            eng = _windowed_engine(
                manual_clock, depth=depth,
                window_ms="20" if window else "0", batch_max=str(self.N),
            )
            self._rules("GET:/h")
            manual_clock.set_ms(1000)
            mw = sentinel_middleware()

            async def call():
                resp = await mw(make_mocked_request("GET", "/h"), handler)
                return resp.status

            async def main():
                return await asyncio.gather(*[call() for _ in range(self.N)])

            statuses = asyncio.run(main())
            assert sorted(statuses) == [200] * self.LIMIT + [429] * (
                self.N - self.LIMIT
            ), f"window={window} depth={depth}"
            eng.flush()
            eng.drain()

    @pytest.mark.parametrize("depth", [0, 2])
    def test_grpc(self, manual_clock, depth):
        grpc = pytest.importorskip("grpc")
        from sentinel_tpu.adapters.grpc_adapter import (
            SentinelServerInterceptor,
        )

        class Details:
            method = "/svc/M"
            invocation_metadata = ()

        class Ctx:
            def abort(self, code, details):
                raise RuntimeError("blocked")

        def continuation(details):
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: "ok"
            )

        for window in (False, True):
            eng = _windowed_engine(
                manual_clock, depth=depth,
                window_ms="20" if window else "0", batch_max=str(self.N),
            )
            self._rules("/svc/M")
            manual_clock.set_ms(1000)
            interceptor = SentinelServerInterceptor()
            results = []
            lock = threading.Lock()

            def call():
                handler = interceptor.intercept_service(
                    continuation, Details()
                )
                try:
                    out = handler.unary_unary(None, Ctx())
                except RuntimeError:
                    out = "blocked"
                with lock:
                    results.append(out)

            ths = [threading.Thread(target=call) for _ in range(self.N)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(30)
            assert sorted(results) == ["blocked"] * (
                self.N - self.LIMIT
            ) + ["ok"] * self.LIMIT, f"window={window} depth={depth}"
            eng.flush()
            eng.drain()

    @pytest.mark.parametrize("depth", [0, 2])
    def test_flask(self, manual_clock, depth):
        flask = pytest.importorskip("flask")
        from sentinel_tpu.adapters.flask_adapter import SentinelFlask

        for window in (False, True):
            eng = _windowed_engine(
                manual_clock, depth=depth,
                window_ms="20" if window else "0", batch_max=str(self.N),
            )
            self._rules("GET:/f")
            manual_clock.set_ms(1000)
            app = flask.Flask(__name__)
            SentinelFlask(app)

            @app.get("/f")
            def f():
                return "ok"

            client = app.test_client()
            results = []
            lock = threading.Lock()

            def call():
                r = client.get("/f")
                with lock:
                    results.append(r.status_code)

            ths = [threading.Thread(target=call) for _ in range(self.N)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(30)
            assert sorted(results) == [200] * self.LIMIT + [429] * (
                self.N - self.LIMIT
            ), f"window={window} depth={depth}"
            eng.flush()
            eng.drain()

    @pytest.mark.parametrize("depth", [0, 2])
    def test_fastapi(self, manual_clock, depth):
        fastapi = pytest.importorskip("fastapi")
        pytest.importorskip("fastapi.testclient")
        from fastapi import Depends, FastAPI
        from fastapi.testclient import TestClient

        from sentinel_tpu.adapters.fastapi_adapter import sentinel_guard

        for window in (False, True):
            eng = _windowed_engine(
                manual_clock, depth=depth,
                window_ms="20" if window else "0", batch_max=str(self.N),
            )
            self._rules("GET:/q")
            manual_clock.set_ms(1000)
            app = FastAPI()

            @app.get("/q", dependencies=[Depends(sentinel_guard())])
            async def q():
                return {"ok": True}

            client = TestClient(app)
            results = []
            lock = threading.Lock()

            def call():
                r = client.get("/q")
                with lock:
                    results.append(r.status_code)

            ths = [threading.Thread(target=call) for _ in range(self.N)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(30)
            assert sorted(results) == [200] * self.LIMIT + [429] * (
                self.N - self.LIMIT
            ), f"window={window} depth={depth}"
            eng.flush()
            eng.drain()

    def test_gateway_entry_rides_the_window(self, manual_clock):
        """gateway_entry's per-resource admissions (with extracted
        param args) coalesce through the window when armed."""
        from sentinel_tpu.adapters.gateway import (
            GatewayFlowRule,
            GatewayParamFlowItem,
            GatewayRequestInfo,
            PARAM_PARSE_STRATEGY_CLIENT_IP,
            gateway_entry,
            gateway_rule_manager,
        )

        eng = _windowed_engine(manual_clock, window_ms="20", batch_max="4")
        gateway_rule_manager.load_rules(
            [
                GatewayFlowRule(
                    "route-w", count=1,
                    param_item=GatewayParamFlowItem(
                        parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP
                    ),
                )
            ]
        )
        manual_clock.set_ms(1000)
        results = []
        lock = threading.Lock()

        def call(ip):
            try:
                with gateway_entry(
                    "route-w", GatewayRequestInfo(path="/svc", client_ip=ip)
                ):
                    with lock:
                        results.append("pass")
            except st.ParamFlowBlockError:
                with lock:
                    results.append("block")

        ths = [
            threading.Thread(target=call, args=(ip,))
            for ip in ("10.0.0.1", "10.0.0.1", "10.0.0.2")
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join(30)
        assert sorted(results) == ["block", "pass", "pass"]
        assert eng.ingest_window.counters["reqs"] >= 3
        gateway_rule_manager.load_rules([])
        eng.flush()
        eng.drain()


class TestTraceAcrossBoundary:
    def test_per_request_traceparent_and_provenance(self, manual_clock):
        """Each windowed request's admission record keeps ITS inbound
        trace identity (not a shared group tag), with speculative
        provenance when the fast tier serves the verdict."""
        from sentinel_tpu.adapters import SentinelWSGIMiddleware

        for spec, want_prov in (("false", "device"), ("true", "speculative")):
            config.set(config.TRACE_SAMPLE_RATE, "1.0")
            eng = _windowed_engine(
                manual_clock, window_ms="20", batch_max="4",
                **{config.SPECULATIVE_ENABLED: spec},
            )
            st.flow_rule_manager.load_rules([st.FlowRule("GET:/t", count=2)])
            manual_clock.set_ms(1000)

            def inner(environ, start_response):
                start_response("200 OK", [])
                return [b"ok"]

            app = SentinelWSGIMiddleware(inner, total_resource=None)
            trace_ids = [f"{i:032x}" for i in (0xA1, 0xA2, 0xA3, 0xA4)]

            def call(tid):
                environ = {
                    "PATH_INFO": "/t", "REQUEST_METHOD": "GET",
                    "HTTP_TRACEPARENT": f"00-{tid}-{'cd' * 8}-01",
                }
                b"".join(app(environ, lambda s, h: None))

            ths = [
                threading.Thread(target=call, args=(tid,))
                for tid in trace_ids
            ]
            for t in ths:
                t.start()
            for t in ths:
                t.join(30)
            recs = eng.admission_trace.records(resource="GET:/t")
            assert sorted(r.trace_id for r in recs) == sorted(trace_ids), (
                f"spec={spec}"
            )
            assert {r.provenance for r in recs} == {want_prov}
            n_adm = sum(1 for r in recs if r.admitted)
            assert n_adm == 2 and len(recs) == 4
            eng.flush()
            eng.drain()


class TestShedBeforeAssembly:
    def test_shed_at_join_counts_window_contents(self, manual_clock):
        """The valve sheds BEFORE a request occupies a window slot, and
        queued window contents count toward max.pending.bulk for any
        later bulk submit."""
        eng = _windowed_engine(
            manual_clock, window_ms="5000", batch_max="64",
            **{config.INGEST_MAX_PENDING_BULK: "4"},
        )
        st.flow_rule_manager.load_rules([st.FlowRule("s", count=1e9)])
        manual_clock.set_ms(1000)
        w = eng.ingest_window
        done = []

        def call():
            try:
                e = api.entry_windowed("s", entry_type=C.EntryType.IN,
                                       detached=True)
                done.append(e)
            except E.IngestShedError:
                done.append("shed")

        # 4 joins fill the bound; the 5th sheds at join (never queued).
        ths = [threading.Thread(target=call) for _ in range(4)]
        for t in ths:
            t.start()
        deadline = 50
        while w.pending_n < 4 and deadline:
            manual_clock  # no-op; real wait below
            deadline -= 1
            threading.Event().wait(0.05)
        assert w.pending_n == 4
        with pytest.raises(E.IngestShedError):
            api.entry_windowed("s", entry_type=C.EntryType.IN, detached=True)
        assert w.pending_n == 4, "a shed request must never join"
        assert eng.ingest.counters["shed_rows"] == 1
        # Queued window contents also bound a DIRECT bulk submit.
        g = eng.submit_bulk("s", 2)
        assert (g.reason == E.BLOCK_SHED).all()
        # Drain: trip the size trigger so the joined 4 settle.
        eng.ingest_window.batch_max = 4  # already-full window flushes
        with eng.ingest_window._cond:
            w2 = eng.ingest_window._open
            if w2 is not None and len(w2.reqs) >= 4:
                eng.ingest_window._open = None
                eng.ingest_window._ready.append(w2)
                eng.ingest_window._cond.notify_all()
        for t in ths:
            t.join(30)
        assert sum(1 for d in done if d != "shed") == 4
        for e in done:
            if e != "shed":
                e.exit()
        eng.flush()
        eng.drain()
        assert eng.cluster_node_stats("s")["cur_thread_num"] == 0

    def test_whole_window_shed_attribution(self, manual_clock):
        """A window assembled under the bound still sheds WHOLE at
        flush when the engine's bulk queue filled meanwhile — dense
        BLOCK_SHED arrays fan out per request with the
        test_ingest_shed.py provenance conventions."""
        config.set(config.TRACE_SAMPLE_RATE, "1.0")
        eng = _windowed_engine(
            manual_clock, window_ms="5000", batch_max="64",
            **{config.INGEST_MAX_PENDING_BULK: "6"},
        )
        st.flow_rule_manager.load_rules([st.FlowRule("ws", count=1e9)])
        manual_clock.set_ms(1000)
        w = eng.ingest_window
        from sentinel_tpu.runtime.window import _OpenWindow

        win = _OpenWindow(deadline=0.0)
        for _ in range(4):
            r = WindowRequest("ws", C.CONTEXT_DEFAULT_NAME, "", 1,
                              C.EntryType.IN, (), eng.clock.now_ms(), None)
            r.event = win.event
            win.reqs.append(r)
            w.pending_n += 1
        # The engine bulk queue fills AFTER assembly: 4 (queued) + 4
        # (window) > 6 would shed the direct submit, so fill with 4
        # then shrink the window's claim: 4 + 4 > 6 at flush.
        w.pending_n -= 4  # simulate the race: contents not yet counted
        g0 = eng.submit_bulk("ws", 4)
        assert g0 is not None
        w.pending_n += 4
        settled = w._dispatch_window(win)
        w._fan_out_window(win, settled)
        for r in win.reqs:
            assert r.verdict is not None
            assert r.verdict.reason == E.BLOCK_SHED
            assert not r.verdict.admitted
        assert eng.ingest.counters["shed_rows"] == 4
        recs = [
            rec
            for rec in eng.admission_trace.records(resource="ws")
            if rec.provenance == "shed"
        ]
        assert recs and all(
            rec.reason_name == "IngestShedException" for rec in recs
        )
        eng.flush()
        eng.drain()

    def test_exits_never_ride_the_valve(self, manual_clock):
        """Completions drain even when the bulk queue is saturated."""
        eng = _windowed_engine(
            manual_clock, window_ms="20", batch_max="2",
            **{config.INGEST_MAX_PENDING_BULK: "2",
               "sentinel.tpu.flush.interval.ms": "0"},
        )
        st.flow_rule_manager.load_rules(
            [st.FlowRule("x", grade=C.FLOW_GRADE_THREAD, count=10)]
        )
        manual_clock.set_ms(1000)
        entries = []

        def call():
            try:
                entries.append(
                    api.entry_windowed("x", entry_type=C.EntryType.IN,
                                       detached=True)
                )
            except E.BlockError:
                pass

        ths = [threading.Thread(target=call) for _ in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(30)
        assert len(entries) == 2
        # Saturate the bulk queue (another resource — its own thread
        # charge must not pollute the gauge under test), then exit:
        # exits must still land.
        eng.submit_bulk("other", 2)
        for e in entries:
            e.exit()
        eng.flush()
        eng.drain()
        eng.ingest_window.close()
        eng.flush()
        eng.drain()
        assert eng.cluster_node_stats("x")["cur_thread_num"] == 0


class TestCancellation:
    def test_cancelled_awaiter_releases_its_admitted_slot(
        self, manual_clock
    ):
        """A task cancelled while awaiting the window verdict must not
        leak its concurrency-gauge charge (client disconnect on every
        async adapter) — the window auto-exits the admitted slot."""
        eng = _windowed_engine(manual_clock, window_ms="30", batch_max="4")
        st.flow_rule_manager.load_rules(
            [st.FlowRule("c", grade=C.FLOW_GRADE_THREAD, count=10)]
        )
        manual_clock.set_ms(1000)

        async def main():
            tasks = [
                asyncio.ensure_future(
                    api.entry_windowed_async("c", entry_type=C.EntryType.IN)
                )
                for _ in range(4)
            ]
            await asyncio.sleep(0)  # let every task join the window
            tasks[0].cancel()
            tasks[1].cancel()
            done = []
            for t in tasks:
                try:
                    done.append(await t)
                except asyncio.CancelledError:
                    pass
            return done

        entries = asyncio.run(main())
        assert len(entries) == 2
        for e in entries:
            e.exit()
        eng.flush()
        eng.drain()
        # Both surviving exits AND both abandoned auto-releases landed.
        assert eng.cluster_node_stats("c")["cur_thread_num"] == 0
        eng.ingest_window.close()


class TestWindowLifecycle:
    def test_window_off_is_cold(self, manual_clock):
        """Default config: no window thread, no pending count, the
        per-request path untouched."""
        eng = api.reset(clock=manual_clock)
        assert not eng.ingest_window.armed
        assert eng.ingest_window._thread is None
        st.flow_rule_manager.load_rules([st.FlowRule("cold", count=1)])
        manual_clock.set_ms(1000)
        e = api.entry_windowed("cold", entry_type=C.EntryType.IN,
                               detached=True)
        e.exit()
        with pytest.raises(E.FlowBlockError):
            api.entry_windowed("cold", entry_type=C.EntryType.IN,
                               detached=True)
        assert eng.ingest_window._thread is None
        eng.flush()
        eng.drain()

    def test_close_serves_the_final_window(self, manual_clock):
        eng = _windowed_engine(manual_clock, window_ms="5000",
                               batch_max="64")
        st.flow_rule_manager.load_rules([st.FlowRule("fin", count=1e9)])
        manual_clock.set_ms(1000)
        got = []

        def call():
            got.append(api.entry_windowed("fin", entry_type=C.EntryType.IN,
                                          detached=True))

        t = threading.Thread(target=call)
        t.start()
        while eng.ingest_window.pending_n < 1:
            threading.Event().wait(0.01)
        eng.ingest_window.close()
        t.join(30)
        assert len(got) == 1 and got[0].verdict.admitted
        got[0].exit()
        eng.flush()
        eng.drain()
        assert eng.cluster_node_stats("fin")["cur_thread_num"] == 0
