"""Block log + metric-extension callbacks: blocked requests leave a
durable aggregated trace (LogSlot → sentinel-block.log, reference:
slots/logger/LogSlot.java:31-40 + EagleEyeLogUtil.java:20-40), and
registered MetricExtension callbacks observe every flush's pass/block/
complete events (metric/extension/callback/MetricEntryCallback.java:
33-56).
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.metrics.block_log import BlockLogger
from sentinel_tpu.metrics.extension import MetricExtension, MetricExtensionProvider


@pytest.fixture()
def block_env(manual_clock, engine, tmp_path):
    engine.block_log = BlockLogger(base_dir=str(tmp_path), clock=manual_clock)
    MetricExtensionProvider.clear()
    yield engine
    MetricExtensionProvider.clear()


class TestBlockLog:
    def test_blocked_entries_aggregate_per_second(self, block_env, manual_clock):
        engine = block_env
        st.flow_rule_manager.load_rules([st.FlowRule("res", count=0)])
        manual_clock.set_ms(100)
        for _ in range(5):
            assert st.try_entry("res") is None
        manual_clock.set_ms(1200)  # next interval: rolls the first out
        assert st.try_entry("res") is None
        engine.block_log.flush()
        entries = engine.block_log.read_entries()
        assert len(entries) == 2
        wall0 = manual_clock.epoch_wall_ms + 0  # second of ts=100
        ts0, key0, count0 = entries[0]
        assert ts0 == wall0
        assert key0 == ("res", "FlowException", "default", "")
        assert count0 == 5
        assert entries[1][2] == 1

    def test_exception_name_per_block_type(self, block_env, manual_clock):
        engine = block_env
        st.flow_rule_manager.load_rules([st.FlowRule("d", count=100)])
        st.degrade_rule_manager.load_rules(
            [st.DegradeRule(resource="d", grade=1, count=0.5, time_window=5,
                            min_request_amount=1)]
        )
        manual_clock.set_ms(500)
        e = st.entry("d")
        e.set_error(RuntimeError("boom"))
        e.exit()
        assert st.try_entry("d") is None  # breaker OPEN
        engine.block_log.flush()
        names = {k[1] for _, k, _ in engine.block_log.read_entries()}
        assert names == {"DegradeException"}

    def test_origin_and_limit_app_in_key(self, block_env, manual_clock):
        engine = block_env
        st.flow_rule_manager.load_rules([st.FlowRule("o", count=0, limit_app="appA")])
        manual_clock.set_ms(100)
        st.ContextUtil.enter("ctx", "appA")
        try:
            assert st.try_entry("o") is None
        finally:
            st.ContextUtil.exit()
        engine.block_log.flush()
        (_, key, _), = engine.block_log.read_entries()
        assert key == ("o", "FlowException", "appA", "appA")

    def test_rolling_keeps_backups(self, tmp_path, manual_clock):
        log = BlockLogger(base_dir=str(tmp_path), clock=manual_clock,
                          max_file_size=200, max_backup_index=2)
        for sec in range(30):
            log.log("r", "FlowException", now_wall_ms=manual_clock.epoch_wall_ms + sec * 1000)
        log.flush()
        files = sorted(p.name for p in tmp_path.iterdir())
        assert "sentinel-block.log" in files
        assert any(n.endswith(".1") for n in files)
        assert not any(n.endswith(".3") for n in files)  # backup cap


class Recorder(MetricExtension):
    def __init__(self):
        self.events = []

    def add_pass(self, resource, n, *args):
        self.events.append(("pass", resource, n))

    def add_block(self, resource, n, origin, block_error, *args):
        self.events.append(("block", resource, n, origin))

    def add_success(self, resource, n, *args):
        self.events.append(("success", resource, n))

    def add_rt(self, resource, rt, *args):
        self.events.append(("rt", resource, rt))

    def add_exception(self, resource, n, throwable):
        self.events.append(("exception", resource, n))

    def increase_thread_num(self, resource, *args):
        self.events.append(("thr+", resource))

    def decrease_thread_num(self, resource, *args):
        self.events.append(("thr-", resource))


class TestMetricExtension:
    def test_callbacks_observe_pass_block_complete(self, block_env, manual_clock):
        rec = Recorder()
        MetricExtensionProvider.register(rec)
        st.flow_rule_manager.load_rules([st.FlowRule("m", count=1)])
        manual_clock.set_ms(100)
        e = st.entry("m")
        assert st.try_entry("m") is None  # blocked
        manual_clock.set_ms(150)
        e.exit()
        block_env.flush()  # exit callbacks deliver with the exit's flush
        kinds = [ev[0] for ev in rec.events]
        assert kinds.count("pass") == 1
        assert kinds.count("thr+") == 1
        assert ("block", "m", 1, "") in rec.events
        assert ("rt", "m", 50) in rec.events
        assert ("success", "m", 1) in rec.events
        assert kinds.count("thr-") == 1

    def test_exception_counted_on_complete(self, block_env, manual_clock):
        rec = Recorder()
        MetricExtensionProvider.register(rec)
        st.flow_rule_manager.load_rules([st.FlowRule("x", count=10)])
        manual_clock.set_ms(100)
        e = st.entry("x")
        e.set_error(RuntimeError("boom"))
        e.exit()
        block_env.flush()
        assert ("exception", "x", 1) in rec.events

    def test_misbehaving_extension_does_not_break_flush(self, block_env, manual_clock):
        class Bad(MetricExtension):
            def add_pass(self, resource, n, *args):
                raise RuntimeError("broken extension")

        rec = Recorder()
        MetricExtensionProvider.register(Bad())
        MetricExtensionProvider.register(rec)
        st.flow_rule_manager.load_rules([st.FlowRule("b", count=10)])
        e = st.entry("b")  # must not raise
        e.exit()
        assert ("pass", "b", 1) in rec.events


class TestReasonNameParity:
    """ISSUE 4 satellite: block-log exception names and BLOCK_* reason
    codes share ONE mapping (core/errors.BLOCK_EXC_NAMES) — a new code
    added without a name (or a name spelled differently somewhere)
    fails here instead of silently logging as an unknown exception."""

    def test_every_block_code_has_a_distinct_name(self):
        from sentinel_tpu.core import errors as E

        codes = {
            name: val
            for name, val in vars(E).items()
            if name.startswith("BLOCK_") and isinstance(val, int)
        }
        assert codes, "reason codes must exist"
        for name, code in codes.items():
            assert code in E.BLOCK_EXC_NAMES, f"{name} has no exception name"
        names = list(E.BLOCK_EXC_NAMES.values())
        assert len(set(names)) == len(names), "names must be distinct"
        # And the mapping has no orphans: every named code is a BLOCK_*.
        assert set(E.BLOCK_EXC_NAMES) == set(codes.values())

    def test_every_block_code_builds_a_typed_error(self):
        """error_for_verdict must return a SUBCLASS for every code —
        a bare BlockError means a code was added without its error
        class wiring."""
        from sentinel_tpu.core import errors as E

        for code in E.BLOCK_EXC_NAMES:
            err = E.error_for_verdict(code, "r")
            assert type(err) is not E.BlockError, code

    def test_log_blocked_writes_the_shared_name(self, block_env, manual_clock):
        from sentinel_tpu.core import errors as E

        engine = block_env
        manual_clock.set_ms(100)
        for code, want in E.BLOCK_EXC_NAMES.items():
            engine.block_log.log_blocked("res", code)
        engine.block_log.log_blocked("res", 99)  # unknown -> base name
        engine.block_log.flush()
        names = {k[1] for _, k, _ in engine.block_log.read_entries()}
        assert names == set(E.BLOCK_EXC_NAMES.values()) | {"BlockException"}

    def test_engine_blocked_verdicts_log_mapped_names(
        self, block_env, manual_clock
    ):
        """End to end: a flow-blocked flush writes exactly the shared
        mapping's spelling (the engine path no longer owns a private
        name table)."""
        from sentinel_tpu.core import errors as E

        engine = block_env
        st.flow_rule_manager.load_rules([st.FlowRule("pw", count=0)])
        manual_clock.set_ms(100)
        assert st.try_entry("pw") is None
        engine.block_log.flush()
        names = {k[1] for _, k, _ in engine.block_log.read_entries()}
        assert names == {E.BLOCK_EXC_NAMES[E.BLOCK_FLOW]}
