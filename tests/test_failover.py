"""Device-failure domain (runtime/failover.py) — deterministic chaos.

Every transition of the health state machine is driven by the
deterministic fault injector (testing/faults.py), so no flaky device is
needed: a fetch fault (and separately a fetch hang timed out by the
watchdog) at a chosen flush seq yields policy-correct degraded verdicts
for the quarantined ops — no caller ever sees a raw device exception —
the engine reaches HEALTHY again within K probe flushes, and
post-recovery admission differentially matches an oracle engine whose
state equals the restored checkpoint. With no faults injected,
depth-{0,2} verdicts are bit-identical with failover armed vs disarmed.
"""

import threading
import time

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import errors as E
from sentinel_tpu.models import constants as C
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import config

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _config_sandbox():
    """Snapshot/restore runtime config: these tests flip failover keys
    that must never leak into the rest of the suite."""
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _mk_engine(clock, enabled=True, ckpt_every=1, probes=2, retry_ms=1000,
               depth=0, policy="open", timeout_ms=10000):
    from sentinel_tpu.runtime.engine import Engine

    config.set(config.FAILOVER_ENABLED, "true" if enabled else "false")
    config.set(config.FAILOVER_CHECKPOINT_EVERY, str(ckpt_every))
    config.set(config.FAILOVER_PROBE_FLUSHES, str(probes))
    config.set(config.FAILOVER_RETRY_MS, str(retry_ms))
    config.set(config.FAILOVER_POLICY, policy)
    config.set(config.FAILOVER_FETCH_TIMEOUT_MS, str(timeout_ms))
    eng = Engine(clock=clock)
    eng.pipeline_depth = depth
    return eng


def _inject(eng):
    from sentinel_tpu.testing.faults import FaultInjector

    return FaultInjector().install(eng)


def _submit_round(engines, resource, n, ts=None):
    """Identical singles into every engine; returns ops per engine."""
    out = []
    for eng in engines:
        out.append([eng.submit_entry(resource, ts=ts) for _ in range(n)])
    return out


def _verdict_tuples(ops):
    return [(op.verdict.admitted, op.verdict.reason, op.verdict.wait_ms)
            for op in ops]


class TestFetchFaultRecovery:
    def test_fetch_fault_policy_verdicts_and_oracle_parity(self, manual_clock):
        """The acceptance scenario at depth 0: fault at flush seq N →
        quarantined ops get policy verdicts (no raw exception), HEALTHY
        within K probes, and post-recovery admission bit-matches an
        oracle whose state equals the restored checkpoint."""
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1, probes=2)
        oracle = _mk_engine(manual_clock, enabled=False)
        for eng in (victim, oracle):
            eng.set_flow_rules([st.FlowRule("r", count=5)])
        inj = _inject(victim)

        manual_clock.set_ms(1000)
        v_ops, o_ops = _submit_round([victim, oracle], "r", 8)
        victim.flush()
        oracle.flush()
        assert _verdict_tuples(v_ops) == _verdict_tuples(o_ops)
        assert victim.failover.snapshot()["checkpoint"] is not None

        # Fault the NEXT flush's fetch: its ops are quarantined with
        # fail-open policy verdicts; the oracle never sees them.
        inj.fail_fetch(victim.flush_seq + 1)
        manual_clock.set_ms(1300)
        lost = [victim.submit_entry("r") for _ in range(4)]
        victim.flush()  # must not raise
        assert victim.failover.state == "DEGRADED"
        for op in lost:
            v = op.verdict
            assert v is not None and v.degraded and v.admitted

        assert victim.failover.try_recover()
        assert victim.failover.state == "HEALTHY"
        assert victim.failover.counters["probe_flushes"] == 2

        # Post-recovery parity: victim restored the checkpoint taken
        # after phase 1, which is exactly the oracle's state — the 1 s
        # window still overlaps, so restored counts are load-bearing.
        manual_clock.set_ms(1600)
        v2, o2 = _submit_round([victim, oracle], "r", 10)
        victim.flush()
        oracle.flush()
        assert _verdict_tuples(v2) == _verdict_tuples(o2)
        assert all(not op.verdict.degraded for op in v2)

    def test_fetch_hang_watchdog_bounds_the_flush(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1)
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("r") for _ in range(4)]
        victim.flush()  # healthy warm-up (and a checkpoint)

        victim.failover.fetch_timeout_ms = 300
        seq = victim.flush_seq + 1
        # The hang raises after its sleep so the abandoned waiter never
        # issues a stray device_get concurrent with recovery compiles.
        inj.hang_fetch(seq, seconds=1.0)
        inj.fail_fetch(seq)
        manual_clock.set_ms(1200)
        ops = [victim.submit_entry("r") for _ in range(4)]
        t0 = time.monotonic()
        victim.flush()
        elapsed = time.monotonic() - t0
        assert elapsed < 0.9, "watchdog must bound the wedged fetch"
        assert victim.failover.state == "DEGRADED"
        assert victim.failover.counters["fetch_timeouts"] == 1
        for op in ops:
            assert op.verdict is not None and op.verdict.degraded

        # Let the abandoned waiter finish, then recover.
        victim.failover.fetch_timeout_ms = 10000
        time.sleep(1.1)
        assert victim.failover.try_recover(), victim.failover.last_fault
        assert victim.failover.state == "HEALTHY"

    def test_dispatch_fault_and_failed_restore_stays_degraded(
        self, manual_clock
    ):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1)
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("r")]
        victim.flush()

        inj.fail_dispatch(victim.flush_seq + 1)
        ops = [victim.submit_entry("r") for _ in range(2)]
        victim.flush()
        assert victim.failover.state == "DEGRADED"
        assert all(op.verdict.degraded for op in ops)

        # A failed checkpoint restore keeps the engine DEGRADED; the
        # next attempt succeeds.
        inj.fail_restore()
        assert not victim.failover.try_recover()
        assert victim.failover.state == "DEGRADED"
        assert victim.failover.try_recover()
        assert victim.failover.state == "HEALTHY"

    def test_auto_recovery_from_flush_after_retry_gap(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1, retry_ms=500)
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("r")]
        victim.flush()
        inj.fail_fetch(victim.flush_seq + 1)
        [victim.submit_entry("r")]
        victim.flush()
        assert victim.failover.state == "DEGRADED"

        # Inside the retry gap: still served degraded.
        manual_clock.set_ms(1200)
        op = victim.submit_entry("r")
        victim.flush()
        assert victim.failover.state == "DEGRADED"
        assert op.verdict.degraded

        # Past the gap: flush() recovers first, then decides on-device.
        manual_clock.set_ms(1600)
        op2 = victim.submit_entry("r")
        victim.flush()
        assert victim.failover.state == "HEALTHY"
        assert not op2.verdict.degraded


class TestDepth2Pipeline:
    def test_inflight_queue_quarantined_no_raw_exception(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1, depth=2)
        victim.set_flow_rules([st.FlowRule("r", count=1000)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        batches = []
        for _ in range(3):
            batches.append([victim.submit_entry("r") for _ in range(4)])
            victim.flush()
        # Fault the newest in-flight record's fetch, then flush again:
        # the drain trips failover and the whole queue quarantines.
        inj.fail_fetch(victim.flush_seq)
        batches.append([victim.submit_entry("r") for _ in range(4)])
        victim.flush()
        victim.drain()  # must not raise
        assert victim.failover.state == "DEGRADED"
        for ops in batches:
            for op in ops:
                assert op.verdict is not None  # never poisoned
        degraded = [op for ops in batches for op in ops if op.verdict.degraded]
        assert degraded, "quarantined ops must carry degraded provenance"
        assert victim.failover.try_recover()
        assert victim.failover.state == "HEALTHY"
        # Post-recovery flushes decide on-device again.
        ops = [victim.submit_entry("r") for _ in range(4)]
        victim.flush()
        victim.drain()
        assert all(not op.verdict.degraded for op in ops)

    def test_no_fault_parity_depths_0_and_2(self, manual_clock):
        """Failover armed but never tripped changes nothing: verdicts
        bit-match a disarmed engine at depths 0 and 2 (checkpoints ride
        along silently)."""
        engines = [
            _mk_engine(manual_clock, enabled=True, ckpt_every=2, depth=0),
            _mk_engine(manual_clock, enabled=False, depth=0),
            _mk_engine(manual_clock, enabled=True, ckpt_every=2, depth=2),
        ]
        rng = np.random.default_rng(7)
        for eng in engines:
            eng.set_flow_rules([st.FlowRule("pp", count=6.0)])
        collected = [[] for _ in engines]
        t = 1000
        for r in range(6):
            manual_clock.set_ms(t)
            ts = t + np.sort(rng.integers(0, 40, 12)).astype(np.int32)
            for i, eng in enumerate(engines):
                ops = [eng.submit_entry("pp", ts=int(x)) for x in ts]
                collected[i].append(ops)
                eng.flush()
            t += 300
        for eng in engines:
            eng.drain()
        base = [
            _verdict_tuples(ops) for ops in collected[0]
        ]
        for i in (1, 2):
            assert [
                _verdict_tuples(ops) for ops in collected[i]
            ] == base
        assert engines[0].failover.state == "HEALTHY"
        assert engines[0].failover.counters["checkpoints"] > 0


class TestDegradedAdmission:
    def test_fail_closed_policy_sheds_with_distinct_reason(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1, policy="open,shed=closed")
        victim.set_flow_rules(
            [st.FlowRule("shed", count=100), st.FlowRule("keep", count=100)]
        )
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("keep")]
        victim.flush()
        inj.fail_fetch(victim.flush_seq + 1)
        [victim.submit_entry("keep")]
        victim.flush()
        assert victim.failover.state == "DEGRADED"

        shed = victim.submit_entry("shed")
        keep = victim.submit_entry("keep")
        victim.flush()
        assert not shed.verdict.admitted
        assert shed.verdict.reason == E.BLOCK_FAILOVER
        assert shed.verdict.degraded
        assert E.exc_name_for_code(E.BLOCK_FAILOVER) == "FailoverException"
        assert keep.verdict.admitted and keep.verdict.degraded

    def _degrade(self, victim, inj, resource="r"):
        [victim.submit_entry(resource)]
        victim.flush()
        inj.fail_fetch(victim.flush_seq + 1)
        [victim.submit_entry(resource)]
        victim.flush()
        assert victim.failover.state == "DEGRADED"

    def test_qps_token_bucket_approximation(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1)
        victim.set_flow_rules([st.FlowRule("r", count=3)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        self._degrade(victim, inj)
        # Bucket starts full (3 tokens); the degrade-entry flush above
        # consumed 1 — two more pass, then blocks.
        ops = [victim.submit_entry("r") for _ in range(4)]
        victim.flush()
        admitted = [op.verdict.admitted for op in ops]
        assert admitted == [True, True, False, False]
        blocked = ops[2].verdict
        assert blocked.reason == E.BLOCK_FLOW and blocked.degraded
        assert blocked.blocked_rule is not None
        # Refill: one second later the bucket is full again.
        manual_clock.set_ms(2100)
        ops2 = [victim.submit_entry("r") for _ in range(3)]
        victim.flush()
        assert all(op.verdict.admitted for op in ops2)

    def test_thread_counter_with_exits(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1)
        victim.set_flow_rules(
            [st.FlowRule("r", grade=C.FLOW_GRADE_THREAD, count=2)]
        )
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        self._degrade(victim, inj)
        # The degrade-entry fill already admitted one entry (counter 1
        # of 2): one more passes, then the gauge is full.
        ops = [victim.submit_entry("r") for _ in range(3)]
        victim.flush()
        assert [op.verdict.admitted for op in ops] == [True, False, False]
        assert ops[1].verdict.reason == E.BLOCK_FLOW
        # An exit releases one slot; the next entry passes.
        victim.submit_exit(ops[0].rows, rt=5, resource="r")
        victim.flush()
        op = victim.submit_entry("r")
        victim.flush()
        assert op.verdict.admitted and op.verdict.degraded

    def test_thread_release_replayed_after_failed_recovery(
        self, manual_clock
    ):
        """An exit that lands while DEGRADED must free its THREAD slot
        in the restored checkpoint even when the FIRST recovery attempt
        fails — the replay is cleared only on success."""
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1)
        victim.set_flow_rules([
            st.FlowRule("x", count=1e9),
            st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=1),
        ])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        op1 = victim.submit_entry("t")
        victim.flush()  # op1 holds the single slot; checkpointed
        assert op1.verdict.admitted
        # Trip via a DIFFERENT resource so no fallback THREAD admit on
        # "t" offsets op1's release in the net replay.
        inj.fail_fetch(victim.flush_seq + 1)
        victim.submit_entry("x")
        victim.flush()
        assert victim.failover.state == "DEGRADED"
        # The exit lands while degraded: device never sees it.
        victim.submit_exit(op1.rows, rt=1, resource="t")
        victim.flush()
        inj.fail_restore()
        assert not victim.failover.try_recover()
        assert victim.failover.try_recover(), victim.failover.last_fault
        manual_clock.set_ms(1100)
        op2, v2 = victim.entry_sync("t")
        assert v2.admitted, "replayed exit must free the THREAD slot"

    def test_quarantined_deferred_exit_releases_thread_slot(
        self, manual_clock
    ):
        """Depth-K: an exit riding a quarantined in-flight flush still
        records its gauge release for the restore replay."""
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1, depth=1)
        victim.set_flow_rules(
            [st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=1)]
        )
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        op1 = victim.submit_entry("t")
        victim.flush()
        victim.drain()  # settled + checkpointed: slot held on device
        assert op1.verdict.admitted
        # The exit's flush stays in flight, then its fetch faults: the
        # record quarantines WITH its exits.
        inj.fail_fetch(victim.flush_seq + 1)
        victim.submit_exit(op1.rows, rt=1, resource="t")
        victim.flush()
        victim.drain()  # must not raise; trips + quarantines
        assert victim.failover.state == "DEGRADED"
        assert victim.failover.try_recover(), victim.failover.last_fault
        manual_clock.set_ms(1100)
        op2, v2 = victim.entry_sync("t")
        assert v2.admitted, "quarantined exit's release must be replayed"

    def test_second_recovery_uses_reanchored_checkpoint(self, manual_clock):
        """Back-to-back faults with no clean flush in between: the
        first recovery replays op1's exit into the installed gauge and
        clears the ledger, so the stored checkpoint must be re-anchored
        to that post-replay world — restoring the stale pre-replay
        checkpoint again would resurrect the already-released slot and
        pin the THREAD gauge forever."""
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1)
        victim.set_flow_rules([
            st.FlowRule("x", count=1e9),
            st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=1),
        ])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        op1 = victim.submit_entry("t")
        victim.flush()  # op1 holds the single slot; checkpointed
        assert op1.verdict.admitted
        # The exit lands in a faulted window: device never sees it,
        # only the replay ledger does.
        inj.fail_fetch(victim.flush_seq + 1)
        victim.submit_exit(op1.rows, rt=1, resource="t")
        victim.flush()
        assert victim.failover.state == "DEGRADED"
        assert victim.failover.try_recover(), victim.failover.last_fault
        # Second fault BEFORE any clean flush stores a new checkpoint
        # (trip via a different resource so no fallback THREAD admit
        # on "t" offsets the picture).
        inj.fail_fetch(victim.flush_seq + 1)
        victim.submit_entry("x")
        victim.flush()
        assert victim.failover.state == "DEGRADED"
        assert victim.failover.try_recover(), victim.failover.last_fault
        manual_clock.set_ms(1100)
        op2, v2 = victim.entry_sync("t")
        assert v2.admitted, (
            "second restore must see the re-anchored post-replay gauge"
        )

    def test_fallback_thread_admit_seeds_restored_gauge(self, manual_clock):
        """A THREAD entry admitted by the fallback and still in flight
        at recovery must be seeded into the restored gauge: its
        post-recovery exit would otherwise drive the gauge negative and
        under-enforce the limit forever."""
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1)
        victim.set_flow_rules([
            st.FlowRule("x", count=1e9),
            st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=1),
        ])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        self._degrade(victim, inj, resource="x")
        opf = victim.submit_entry("t")
        victim.flush()
        assert opf.verdict.admitted and opf.verdict.degraded
        assert victim.failover.try_recover(), victim.failover.last_fault
        # The fallback-admitted entry exits AFTER recovery, through the
        # device path.
        victim.submit_exit(opf.rows, rt=1, resource="t")
        victim.flush()
        manual_clock.set_ms(1100)
        a = victim.submit_entry("t")
        b = victim.submit_entry("t")
        victim.flush()
        # Gauge must be exactly 0 again: one slot, one admit.
        assert [a.verdict.admitted, b.verdict.admitted] == [True, False]

    def test_param_thread_degraded_pair_cancels_in_restored_gauge(
        self, manual_clock
    ):
        """A hot-param THREAD entry admitted AND exited while DEGRADED
        must net to zero in the restored per-value gauge — subtracting
        the exit without seeding the admit would restore the gauge
        below the pre-fault in-flight count and over-admit the value
        until those older exits land."""
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1)
        victim.set_flow_rules([st.FlowRule("x", count=1e9)])
        victim.set_param_rules({"t": [st.ParamFlowRule(
            "t", grade=C.FLOW_GRADE_THREAD, param_idx=0, count=3,
        )]})
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        held = [victim.submit_entry("t", args=("u",)) for _ in range(3)]
        victim.flush()  # gauge("u") = 3 on device; checkpointed
        assert all(op.verdict.admitted for op in held)
        self._degrade(victim, inj, resource="x")
        # Fallback admit (THREAD param passes unchecked) + its exit,
        # both inside the degraded window: the pair must cancel.
        opf = victim.submit_entry("t", args=("u",))
        victim.flush()
        assert opf.verdict.admitted and opf.verdict.degraded
        victim.submit_exit(opf.rows, rt=1, resource="t",
                           param_rows=opf.param_thread_rows)
        victim.flush()
        assert victim.failover.try_recover(), victim.failover.last_fault
        manual_clock.set_ms(1100)
        op = victim.submit_entry("t", args=("u",))
        victim.flush()
        # The restored gauge must still hold the 3 pre-fault in-flight
        # entries: value "u" is full, the next entry blocks.
        assert not op.verdict.admitted
        assert op.verdict.reason == E.BLOCK_PARAM
        # ...and releasing one pre-fault entry frees exactly one slot.
        victim.submit_exit(held[0].rows, rt=1, resource="t",
                           param_rows=held[0].param_thread_rows)
        victim.flush()
        op2 = victim.submit_entry("t", args=("u",))
        victim.flush()
        assert op2.verdict.admitted

    def test_breaker_mirror_blocks_open_resource(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1)
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        victim.set_degrade_rules(
            [st.DegradeRule("r", grade=C.DEGRADE_GRADE_EXCEPTION_COUNT,
                            count=1, time_window=10)]
        )
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        self._degrade(victim, inj)
        # Freeze the last-known breaker state at OPEN (the mirror the
        # fallback consults).
        from sentinel_tpu.rules.degrade_table import OPEN

        with victim._breaker_mirror_lock:
            victim._breaker_state_host[:] = OPEN
            victim._breaker_mirror_valid = True
        op = victim.submit_entry("r")
        victim.flush()
        assert not op.verdict.admitted
        assert op.verdict.reason == E.BLOCK_DEGRADE and op.verdict.degraded

    def test_bulk_groups_get_array_verdicts(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1)
        victim.set_flow_rules([st.FlowRule("r", count=5)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        self._degrade(victim, inj)
        g = victim.submit_bulk("r", n=8, ts=manual_clock.now_ms())
        victim.flush()
        assert g.admitted is not None and g.admitted.shape == (8,)
        # Bucket had 5 tokens minus the 1 consumed at degrade entry.
        assert int(g.admitted.sum()) == 4
        assert set(np.asarray(g.reason)[~g.admitted]) == {E.BLOCK_FLOW}

    def test_trace_and_telemetry_provenance(self, manual_clock):
        config.set(config.TRACE_SAMPLE_RATE, "1.0")
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            policy="closed")
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("r")]
        victim.flush()
        inj.fail_fetch(victim.flush_seq + 1)
        [victim.submit_entry("r")]
        victim.flush()

        op = victim.submit_entry("r")
        victim.flush()
        assert op.verdict.reason == E.BLOCK_FAILOVER
        recs = [r for r in victim.admission_trace.records() if r.degraded]
        assert recs and recs[-1].reason == E.BLOCK_FAILOVER
        assert recs[-1].reason_name == "FailoverException"
        tc = victim.telemetry.counters_snapshot()
        assert tc["degraded_blocks"] >= 1
        assert tc["health_transitions"] >= 1

    def test_prometheus_and_health_snapshot(self, manual_clock):
        from sentinel_tpu.transport.prometheus import render_metrics

        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1)
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("r")]
        victim.flush()
        inj.fail_fetch(victim.flush_seq + 1)
        [victim.submit_entry("r")]
        victim.flush()
        text = render_metrics(victim)
        assert "sentinel_engine_health 1" in text
        assert "sentinel_engine_failover_trips_total 1" in text
        snap = victim.failover.snapshot()
        assert snap["state"] == "DEGRADED"
        assert snap["counters"]["trips"] == 1
        assert snap["events"] and snap["events"][-1]["to"] == "DEGRADED"
        assert "fetch@" in snap["last_fault"]


class TestMeshGate:
    def test_recovery_refuses_under_mesh_with_actionable_reason(
        self, manual_clock
    ):
        """Restore + probe are single-chip; under a live mesh recovery
        must fail CLEANLY (engine stays DEGRADED, fallback keeps
        serving) instead of installing unsharded states."""
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1, retry_ms=0)
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("r")]
        victim.flush()
        victim.enable_mesh(8)
        inj.fail_dispatch(victim.flush_seq + 1)
        op = victim.submit_entry("r")
        victim.flush()
        assert victim.failover.state == "DEGRADED"
        assert op.verdict is not None and op.verdict.degraded
        # Auto-recovery never fires under mesh; explicit recovery
        # refuses with an actionable reason.
        assert not victim.failover.recovery_due(manual_clock.now_ms())
        assert not victim.failover.try_recover()
        assert victim.failover.state == "DEGRADED"
        assert "disable_mesh" in victim.failover.last_fault
        # Degraded flushes keep serving.
        op2 = victim.submit_entry("r")
        victim.flush()
        assert op2.verdict is not None and op2.verdict.degraded
        victim.disable_mesh()
        assert victim.failover.try_recover(), victim.failover.last_fault
        assert victim.failover.state == "HEALTHY"


class TestEngineLifecycle:
    def test_reset_returns_to_healthy(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1)
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("r")]
        victim.flush()
        inj.fail_fetch(victim.flush_seq + 1)
        [victim.submit_entry("r")]
        victim.flush()
        assert victim.failover.state == "DEGRADED"
        victim.reset()
        assert victim.failover.state == "HEALTHY"
        assert victim.failover.snapshot()["checkpoint"] is None

    def test_close_while_degraded_does_not_raise(self, manual_clock):
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1)
        victim.set_flow_rules([st.FlowRule("r", count=100)])
        inj = _inject(victim)
        manual_clock.set_ms(1000)
        [victim.submit_entry("r")]
        victim.flush()
        inj.fail_fetch(victim.flush_seq + 1)
        ops = [victim.submit_entry("r") for _ in range(2)]
        victim.flush()
        victim.close()
        assert all(op.verdict is not None for op in ops)
        assert not victim.closed_dirty


@pytest.mark.slow
class TestChaosSoak:
    def test_random_fault_soak_depth4(self, manual_clock):
        """Depth-4 random-fault soak: seeded faults at random flush
        seqs over many rounds — no caller ever sees a raw device
        exception, every op gets a verdict, and the engine always
        recovers to HEALTHY."""
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=2,
                            probes=1, retry_ms=0, depth=4)
        victim.set_flow_rules(
            [st.FlowRule("a", count=20), st.FlowRule("b", count=5)]
        )
        inj = _inject(victim)
        rng = np.random.default_rng(1234)
        all_ops = []
        t = 1000
        for r in range(30):
            manual_clock.set_ms(t)
            if rng.random() < 0.3:
                kind = rng.integers(0, 3)
                seq = victim.flush_seq + int(rng.integers(1, 4))
                if kind == 0:
                    inj.fail_fetch(seq)
                elif kind == 1:
                    inj.fail_dispatch(seq)
                else:
                    inj.fail_fetch(seq)
                    inj.fail_dispatch(seq + 1)
            ops = [
                victim.submit_entry("a" if rng.random() < 0.7 else "b")
                for _ in range(int(rng.integers(1, 12)))
            ]
            all_ops.extend(ops)
            victim.flush()  # must never raise
            t += int(rng.integers(50, 400))
        victim.drain()
        for op in all_ops:
            assert op.verdict is not None
        # Final recovery always succeeds once faults stop firing.
        inj.clear()
        if victim.failover.state != "HEALTHY":
            assert victim.failover.try_recover(), victim.failover.last_fault
        assert victim.failover.state == "HEALTHY"
        ops = [victim.submit_entry("a") for _ in range(4)]
        victim.flush()
        victim.drain()
        assert all(op.verdict is not None and not op.verdict.degraded
                   for op in ops)

    def test_speculative_chaos_interleaved_faults_soak(self, manual_clock):
        """PR 6 chaos coverage: with the speculative tier ON and
        failover armed, dispatch/fetch faults injected mid-
        reconciliation (between speculative admits and their settles,
        at every health state) must never surface a raw exception,
        never push any drift window past the pinned bound, and never
        leak THREAD gauge entries — after quiesce the device
        concurrency gauge and the mirror's live counter are both
        exactly zero."""
        overadmit_max = 16
        flush_every = 6
        config.set(config.SPECULATIVE_ENABLED, "true")
        config.set(config.SPECULATIVE_FLUSH_BATCH, "10000")
        config.set(config.SPECULATIVE_OVERADMIT_MAX, str(overadmit_max))
        config.set(config.SPECULATIVE_WINDOW_MS, "1000")
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1, retry_ms=10**9, depth=1)
        victim.set_flow_rules([
            st.FlowRule("q", count=5),
            st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=3),
        ])
        inj = _inject(victim)
        rng = np.random.default_rng(23)
        live = []  # admitted THREAD entries not yet exited
        n_since_flush = 0
        t = 1000
        for r in range(30):
            manual_clock.set_ms(t)
            if rng.random() < 0.35:
                seq = victim.flush_seq + int(rng.integers(1, 3))
                if rng.random() < 0.5:
                    inj.fail_fetch(seq)
                else:
                    inj.fail_dispatch(seq)
            for _ in range(int(rng.integers(2, 7))):
                _op, v = victim.entry_sync("q")
                assert v is not None
                n_since_flush += 1
            for _ in range(int(rng.integers(1, 4))):
                op, v = victim.entry_sync("t")
                assert v is not None
                if v.admitted:
                    live.append((op, v))
                n_since_flush += 1
            # Exits of a random prefix of the live set interleave with
            # the faults — the reconciliation-mid-fault surface.
            n_exit = int(rng.integers(0, len(live) + 1))
            for op, v in live[:n_exit]:
                victim.submit_exit(op.rows, rt=1, resource="t",
                                   speculative=v.speculative)
            live = live[n_exit:]
            if n_since_flush >= flush_every or rng.random() < 0.5:
                victim.flush()  # must never raise
                n_since_flush = 0
            if victim.failover.state == "DEGRADED" and rng.random() < 0.5:
                inj.clear()
                assert victim.failover.try_recover(), (
                    victim.failover.last_fault
                )
            t += int(rng.integers(100, 500))
        # Quiesce: stop faults, recover, drain everything, exit the
        # stragglers, and give the compensation ops a settle flush.
        inj.clear()
        if victim.failover.state != "HEALTHY":
            assert victim.failover.try_recover(), victim.failover.last_fault
        for op, v in live:
            victim.submit_exit(op.rows, rt=1, resource="t",
                               speculative=v.speculative)
        victim.flush()
        victim.drain()
        victim.flush()
        victim.drain()
        # Pinned drift bound: the valve halts speculation at
        # overadmit_max observed over-admits per window; verdicts
        # already in flight can still settle as over-admits, bounded by
        # the flush cadence times the pipeline depth + 1.
        lag = flush_every * 2
        assert (
            victim.speculative.max_over_admit_window <= overadmit_max + lag
        ), victim.speculative.snapshot()
        # No THREAD gauge leak: device gauge and host mirror both zero.
        stats = victim.cluster_node_stats("t")
        assert stats["cur_thread_num"] == 0, stats
        mirror_threads = victim.speculative.mirror.snapshot()["live_threads"]
        assert mirror_threads.get("t", 0) == 0, mirror_threads

    def test_spec_chaos_with_system_rule_and_shed_valve(self, manual_clock):
        """PR 7 chaos coverage: the speculative tier ON with a system
        rule configured AND the ingest shed valve armed, under
        interleaved dispatch/fetch faults — no raw exceptions, the
        system rule narrows (never zeroes) the tier, pending queues
        stay bounded, drift stays within the valve, and after quiesce
        device + mirror THREAD gauges are exactly zero."""
        from sentinel_tpu.rules.system_manager import SystemConfig

        overadmit_max = 16
        bound = 64
        config.set(config.SPECULATIVE_ENABLED, "true")
        config.set(config.SPECULATIVE_FLUSH_BATCH, "10000")
        config.set(config.SPECULATIVE_OVERADMIT_MAX, str(overadmit_max))
        config.set(config.SPECULATIVE_WINDOW_MS, "1000")
        config.set(config.INGEST_MAX_PENDING, str(bound))
        victim = _mk_engine(manual_clock, enabled=True, ckpt_every=1,
                            probes=1, retry_ms=10**9, depth=1)
        victim.set_flow_rules([
            st.FlowRule("q", count=5),
            st.FlowRule("t", grade=C.FLOW_GRADE_THREAD, count=3),
        ])
        victim.set_system_config(SystemConfig(qps=40.0, max_thread=64))
        inj = _inject(victim)
        rng = np.random.default_rng(31)
        live = []
        n_shed = 0
        t = 1000
        for r in range(30):
            manual_clock.set_ms(t)
            if rng.random() < 0.35:
                seq = victim.flush_seq + int(rng.integers(1, 3))
                if rng.random() < 0.5:
                    inj.fail_fetch(seq)
                else:
                    inj.fail_dispatch(seq)
            for _ in range(int(rng.integers(2, 7))):
                _op, v = victim.entry_sync(
                    "q", entry_type=C.EntryType.IN
                )
                assert v is not None
                if v.reason == E.BLOCK_SHED:
                    n_shed += 1
            for _ in range(int(rng.integers(1, 4))):
                op, v = victim.entry_sync("t")
                assert v is not None
                if v.reason == E.BLOCK_SHED:
                    n_shed += 1
                elif v.admitted:
                    live.append((op, v))
            assert len(victim._entries) <= bound
            n_exit = int(rng.integers(0, len(live) + 1))
            for op, v in live[:n_exit]:
                victim.submit_exit(op.rows, rt=1, resource="t",
                                   speculative=v.speculative)
            live = live[n_exit:]
            if rng.random() < 0.6:
                victim.flush()  # must never raise
            if victim.failover.state == "DEGRADED" and rng.random() < 0.5:
                inj.clear()
                assert victim.failover.try_recover(), (
                    victim.failover.last_fault
                )
            t += int(rng.integers(100, 500))
        # Quiesce.
        inj.clear()
        if victim.failover.state != "HEALTHY":
            assert victim.failover.try_recover(), victim.failover.last_fault
        for op, v in live:
            victim.submit_exit(op.rows, rt=1, resource="t",
                               speculative=v.speculative)
        victim.flush()
        victim.drain()
        victim.flush()
        victim.drain()
        c = victim.speculative.counters
        # The system rule narrowed the tier, never zeroed it: zero
        # declines (only prio declines remain, none submitted here).
        assert c["spec_declined"] == 0, c
        # Drift bound: valve + in-flight detection lag (same margin as
        # the PR-6 soak).
        assert victim.speculative.max_over_admit_window <= overadmit_max + 12
        # No THREAD gauge leak despite faults + shed interleaving.
        stats = victim.cluster_node_stats("t")
        assert stats["cur_thread_num"] == 0, stats
        mirror_threads = victim.speculative.mirror.snapshot()["live_threads"]
        assert mirror_threads.get("t", 0) == 0, mirror_threads
        # Shed provenance rode through (queue pressure did occur) or
        # the queue never saturated — either way the counters agree.
        assert victim.ingest.counters["shed_entries"] == n_shed

    def test_failover_overhead_guard(self, manual_clock):
        """Armed-but-healthy overhead stays bounded (the disarmed
        position is one attribute read per flush/fetch — below timing
        noise, so the guard pins the armed path against the disarmed
        one; PERF_NOTES.md records the measured numbers)."""
        import timeit

        def run(enabled):
            eng = _mk_engine(manual_clock, enabled=enabled, ckpt_every=64)
            eng.set_flow_rules([st.FlowRule("r", count=1e9)])
            manual_clock.set_ms(1000)

            def once():
                [eng.submit_entry("r") for _ in range(64)]
                eng.flush()

            once()  # warm the jit cache
            n = 30
            return timeit.timeit(once, number=n) / n

        base = min(run(False) for _ in range(3))
        armed = min(run(True) for _ in range(3))
        # Generous CI bound; measured ~1.0x-1.1x locally (the watchdog
        # waiter thread per fetch is the whole cost).
        assert armed <= base * 1.8, (armed, base)
