"""MetricSearcher's ``.idx`` second→offset seek across ROLLED files.

The pre-existing tests only exercised a single live file; ISSUE 3 adds
coverage for the rolled case: every rolled file carries its own
``.idx``, the searcher must seek within each file (a late begin lands
at the last relevant batch, never past un-indexed trailing lines), and
stay exactly equal to a full linear scan (the index is an accelerator,
never a filter)."""

import os

from sentinel_tpu.metrics.metric_log import (
    MetricNodeLine,
    MetricSearcher,
    MetricWriter,
)


def _line(sec_ms: int, resource: str = "r", qps: int = 1) -> MetricNodeLine:
    return MetricNodeLine(timestamp=sec_ms, resource=resource, pass_qps=qps)


def _write_rolled(tmp_path, n_batches: int = 6, per_batch: int = 3):
    """Tiny single_file_size → every batch rolls to a new file; returns
    (writer, all_lines). Batch b covers seconds [b*per_batch,
    (b+1)*per_batch) at wall second granularity."""
    w = MetricWriter(
        base_dir=str(tmp_path),
        app_name="roll",
        single_file_size=1,  # roll on every write after the first byte
        total_file_count=100,  # keep everything
    )
    all_lines = []
    for b in range(n_batches):
        batch = [
            _line((b * per_batch + i) * 1000, qps=b * 10 + i)
            for i in range(per_batch)
        ]
        w.write(batch[-1].timestamp, batch)
        all_lines += batch
    return w, all_lines


class TestRolledIdxSearch:
    def test_rolled_files_each_have_idx(self, tmp_path):
        w, _ = _write_rolled(tmp_path)
        files = w._list_files()
        assert len(files) == 6  # one batch per file at size 1
        for f in files:
            assert os.path.exists(f + ".idx")

    def test_full_range_equals_linear_scan(self, tmp_path):
        _, all_lines = _write_rolled(tmp_path)
        s = MetricSearcher(base_dir=str(tmp_path), app_name="roll")
        got = s.find(0, 2**61)
        assert sorted(l.timestamp for l in got) == [
            l.timestamp for l in all_lines
        ]
        assert {(l.timestamp, l.pass_qps) for l in got} == {
            (l.timestamp, l.pass_qps) for l in all_lines
        }

    def test_late_range_spans_rolled_files(self, tmp_path):
        _, all_lines = _write_rolled(tmp_path)
        begin = 8 * 1000  # mid batch 2; batches 3..5 entirely inside
        end = 14 * 1000
        s = MetricSearcher(base_dir=str(tmp_path), app_name="roll")
        got = s.find(begin, end)
        want = [l for l in all_lines if begin <= l.timestamp <= end]
        assert sorted(l.timestamp for l in got) == [l.timestamp for l in want]

    def test_late_begin_seeks_to_last_batch(self, tmp_path):
        """A begin past every indexed second seeks to the LAST batch's
        offset (not past EOF, and never a whole-file skip — un-indexed
        trailing lines from a failed .idx append must stay reachable)."""
        w = MetricWriter(
            base_dir=str(tmp_path), app_name="late",
            single_file_size=1 << 30, total_file_count=10,
        )
        for b in range(3):
            w.write(b * 1000, [_line(b * 1000, qps=b)])
        (path,) = w._list_files()
        off = MetricSearcher._start_offset(path, 10_000)
        assert 0 < off < os.path.getsize(path)
        # And a range starting at 0 scans every file from byte 0.
        assert MetricSearcher._start_offset(path, 0) == 0

    def test_unindexed_trailing_lines_still_found(self, tmp_path):
        """Data append succeeded but the paired .idx append failed: the
        trailing lines are past the last index entry and must still be
        returned for a late range."""
        w = MetricWriter(
            base_dir=str(tmp_path), app_name="tail",
            single_file_size=1 << 30, total_file_count=10,
        )
        for b in range(3):
            w.write(b * 1000, [_line(b * 1000, qps=b)])
        (path,) = w._list_files()
        with open(path, "a", encoding="utf-8") as f:
            f.write(_line(50_000, qps=99).to_line() + "\n")  # no .idx entry
        s = MetricSearcher(base_dir=str(tmp_path), app_name="tail")
        got = s.find(40_000, 2**61)
        assert [l.timestamp for l in got] == [50_000]

    def test_seek_offset_within_multi_batch_file(self, tmp_path):
        """One large file, many indexed batches: a late ``begin`` seeks
        past the early batches' bytes but still returns every in-range
        line."""
        w = MetricWriter(
            base_dir=str(tmp_path), app_name="one",
            single_file_size=1 << 30, total_file_count=10,
        )
        all_lines = []
        for b in range(8):
            batch = [_line((b * 2 + i) * 1000, qps=b) for i in range(2)]
            w.write(batch[-1].timestamp, batch)
            all_lines += batch
        (path,) = w._list_files()
        begin = 9 * 1000
        off = MetricSearcher._start_offset(path, begin)
        assert off > 0  # actually seeks, not a full scan
        s = MetricSearcher(base_dir=str(tmp_path), app_name="one")
        got = s.find(begin, 2**61)
        want = [l for l in all_lines if l.timestamp >= begin]
        assert sorted(l.timestamp for l in got) == [l.timestamp for l in want]

    def test_missing_or_corrupt_idx_degrades_to_full_scan(self, tmp_path):
        _, all_lines = _write_rolled(tmp_path, n_batches=3)
        s = MetricSearcher(base_dir=str(tmp_path), app_name="roll")
        for f in s.writer_view._list_files():
            if os.path.exists(f + ".idx"):
                with open(f + ".idx", "w") as fh:
                    fh.write("not an index\n")
        got = s.find(0, 2**61)
        assert len(got) == len(all_lines)  # correctness survives
