"""Closed-form pacer path (shaping rounds = −1).

Pins the rank math against the sequential scan (rounds = 0, the
reference RateLimiterController recurrence) on identical batches and
state, for same-ts uniform-acquire RATE_LIMITER traffic of any
per-rule multiplicity.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.rules.flow_table import FlowIndex
from sentinel_tpu.rules.shaping import ShapingBatch, run_shaping


def _index(n_rules, rng):
    rules = [
        st.FlowRule(
            resource=f"r{i}",
            count=float(rng.integers(5, 80)),
            control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
            max_queueing_time_ms=int(rng.integers(0, 600)),
        )
        for i in range(n_rules)
    ]
    return FlowIndex(rules)


def _batch(rng, s, n_rules, ts_val, acq_val):
    gid = rng.integers(0, n_rules, s).astype(np.int32)
    valid = rng.random(s) < 0.9
    return ShapingBatch(
        valid=jnp.asarray(valid),
        gid=jnp.asarray(gid),
        row=jnp.asarray(gid),
        eidx=jnp.arange(s, dtype=jnp.int32),
        flat_pos=jnp.arange(s, dtype=jnp.int32),
        ts=jnp.full(s, ts_val, dtype=jnp.int32),
        acquire=jnp.full(s, acq_val, dtype=jnp.int32),
    )


class TestPacerClosedFormParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_random_batches_match_scan(self, seed):
        rng = np.random.default_rng(seed)
        n_rules, s = 7, 512  # ~73 items/rule — far past the rounds cap
        index = _index(n_rules, rng)
        dyn = index.make_dyn_state()
        # Random pre-state: some rules mid-pace, some never-seen.
        latest = np.where(
            rng.random(n_rules) < 0.3,
            -(10**9),
            rng.integers(500, 2500, n_rules),
        ).astype(np.int32)
        dyn = dyn._replace(latest_passed_time=jnp.asarray(latest))
        ts_val = int(rng.integers(1000, 3000))
        acq = int(rng.integers(1, 3))
        pb = _batch(rng, s, n_rules, ts_val, acq)
        zeros = jnp.zeros(s, dtype=jnp.int32)
        dyn_cf, ok_cf, wait_cf = run_shaping(
            index.device, dyn, pb, zeros, zeros, 1.0, rounds=-1
        )
        dyn_sc, ok_sc, wait_sc = run_shaping(
            index.device, dyn, pb, zeros, zeros, 1.0, rounds=0
        )
        assert np.array_equal(np.asarray(ok_cf), np.asarray(ok_sc))
        assert np.array_equal(np.asarray(wait_cf), np.asarray(wait_sc))
        assert np.array_equal(
            np.asarray(dyn_cf.latest_passed_time),
            np.asarray(dyn_sc.latest_passed_time),
        )

    def test_large_cost_times_rank_does_not_overflow(self):
        """count=1 + acquire=1000 → cost = 1,000,000 ms; rank×cost
        wraps int32 past ~2149 items. The cap-based admission must
        still admit exactly what the scan admits (1 item)."""
        rng = np.random.default_rng(0)
        index = FlowIndex([
            st.FlowRule(
                "r0", count=1.0,
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=500,
            )
        ])
        dyn = index.make_dyn_state()
        s = 4096
        pb = ShapingBatch(
            valid=jnp.ones(s, dtype=bool),
            gid=jnp.zeros(s, dtype=jnp.int32),
            row=jnp.zeros(s, dtype=jnp.int32),
            eidx=jnp.arange(s, dtype=jnp.int32),
            flat_pos=jnp.arange(s, dtype=jnp.int32),
            ts=jnp.full(s, 1000, dtype=jnp.int32),
            acquire=jnp.full(s, 1000, dtype=jnp.int32),
        )
        zeros = jnp.zeros(s, dtype=jnp.int32)
        dyn_cf, ok_cf, _ = run_shaping(index.device, dyn, pb, zeros, zeros, 1.0, rounds=-1)
        dyn_sc, ok_sc, _ = run_shaping(index.device, dyn, pb, zeros, zeros, 1.0, rounds=0)
        assert int(np.asarray(ok_cf).sum()) == int(np.asarray(ok_sc).sum()) == 1
        assert np.array_equal(
            np.asarray(dyn_cf.latest_passed_time),
            np.asarray(dyn_sc.latest_passed_time),
        )

    def test_engine_bulk_rate_limiter_ladder(self, manual_clock, engine):
        """A bulk group on a rate-limited resource (multiplicity far
        past the rounds cap → previously the scan): 1 immediate + the
        queueing ladder, exact waits."""
        engine.set_flow_rules([
            st.FlowRule(
                "paced", count=10.0,  # cost 100 ms
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=300,
            )
        ])
        manual_clock.set_ms(1000)
        n = 100
        g = engine.submit_bulk("paced", n, ts=np.full(n, 1000, dtype=np.int32))
        engine.flush()
        adm = np.asarray(g.admitted)
        waits = np.asarray(g.wait_ms)
        assert adm.sum() == 4  # immediate + 100/200/300ms queue slots
        assert waits[adm].tolist() == [0, 100, 200, 300]

        # Next flush chains off the advanced pacer state.
        manual_clock.set_ms(1050)
        g2 = engine.submit_bulk("paced", n, ts=np.full(n, 1050, dtype=np.int32))
        engine.flush()
        adm2 = np.asarray(g2.admitted)
        waits2 = np.asarray(g2.wait_ms)
        # latest = 1300; waits from 1300+100-1050=350 > 300 → none fit.
        assert adm2.sum() == 0, (adm2.sum(), waits2[adm2])

    def test_mixed_behavior_not_eligible(self, manual_clock, engine):
        """A WARM_UP rule in the batch keeps the exact recurrence (the
        selector must not pick the pacer-only closed form)."""
        import numpy as np
        from sentinel_tpu.rules.flow_table import FlowIndex as FI

        rules = [
            st.FlowRule("a", count=10.0,
                        control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER),
            st.FlowRule("b", count=10.0,
                        control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                        warm_up_period_sec=5),
        ]
        findex = FI(rules)
        gid = np.array([0, 1], dtype=np.int32)
        ts = np.array([1000, 1000], dtype=np.int32)
        acq = np.array([1, 1], dtype=np.int32)
        assert engine._shaping_rounds_for(gid, ts, acq, findex) != -1
        gid_rl = np.array([0, 0], dtype=np.int32)
        assert engine._shaping_rounds_for(gid_rl, ts, acq, findex) == -1
        # Mixed ts also disqualifies.
        ts2 = np.array([1000, 1200], dtype=np.int32)
        assert engine._shaping_rounds_for(gid_rl, ts2, acq, findex) != -1
