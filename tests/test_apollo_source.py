"""ApolloDataSource against a fake in-process Apollo config service
(real HTTP: /configs fetch with releaseKey 304s, /notifications/v2
long-poll) — same approach as the etcd/Consul/Nacos fakes.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

import pytest

from sentinel_tpu.datasource.apollo_source import ApolloDataSource
from sentinel_tpu.datasource.base import json_converter
from sentinel_tpu.models.rules import FlowRule


class FakeApollo(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _Handler)
        self.port = self.server_address[1]
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.configurations = {}  # namespace -> {key: value}
        self.release = 0
        self.notification_id = 0
        self.hold_sec = 10.0  # fake's max hold (kept short for tests)

    def set_prop(self, namespace: str, key: str, value: str):
        with self.cond:
            self.configurations.setdefault(namespace, {})[key] = value
            self.release += 1
            self.notification_id += 1
            self.cond.notify_all()

    def drop_namespace(self, namespace: str):
        with self.cond:
            self.configurations.pop(namespace, None)
            self.release += 1
            self.notification_id += 1
            self.cond.notify_all()


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _json(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv: FakeApollo = self.server
        u = urlsplit(self.path)
        parts = u.path.strip("/").split("/")
        if parts[0] == "configs" and len(parts) == 4:
            _, app_id, cluster, namespace = parts
            del app_id, cluster
            q = parse_qs(u.query)
            with srv.lock:
                cfg = srv.configurations.get(namespace)
                release_key = f"rk-{srv.release}"
            if cfg is None:
                self.send_response(404)
                self.end_headers()
                return
            if q.get("releaseKey", [""])[0] == release_key:
                self.send_response(304)
                self.end_headers()
                return
            self._json(
                {
                    "appId": parts[1],
                    "cluster": parts[2],
                    "namespaceName": namespace,
                    "configurations": cfg,
                    "releaseKey": release_key,
                }
            )
        elif parts[0] == "notifications":
            q = parse_qs(u.query)
            notifications = json.loads(q.get("notifications", ["[]"])[0])
            want = {n["namespaceName"]: n["notificationId"] for n in notifications}
            deadline = time.monotonic() + srv.hold_sec
            with srv.cond:
                while time.monotonic() < deadline:
                    if any(nid != srv.notification_id for nid in want.values()):
                        break
                    srv.cond.wait(timeout=0.1)
                else:
                    self.send_response(304)
                    self.end_headers()
                    return
                out = [
                    {"namespaceName": ns, "notificationId": srv.notification_id}
                    for ns in want
                ]
            self._json(out)
        else:
            self.send_response(404)
            self.end_headers()


def _rules_json(count):
    return json.dumps([{"resource": "apres", "count": count}])


@pytest.fixture()
def fake_apollo():
    srv = FakeApollo()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _src(fake_apollo, **kw):
    kw.setdefault("namespace_name", "application")
    kw.setdefault("rule_key", "flowRules")
    return ApolloDataSource(
        json_converter(FlowRule),
        endpoint=f"http://127.0.0.1:{fake_apollo.port}",
        reconnect_interval_sec=0.1,
        **kw,
    )


def _value_count(src):
    v = src.get_property().value
    return v[0].count if v else None


class TestApolloDataSource:
    def test_initial_load_and_notification_push(self, fake_apollo):
        fake_apollo.set_prop("application", "flowRules", _rules_json(7))
        src = _src(fake_apollo).start()
        try:
            assert _wait(lambda: _value_count(src) == 7)
            # A namespace release advances the notification id; the
            # long-poll returns early and the re-fetch lands the value.
            fake_apollo.set_prop("application", "flowRules", _rules_json(9))
            assert _wait(lambda: _value_count(src) == 9)
        finally:
            src.close()

    def test_missing_key_falls_back_to_default(self, fake_apollo):
        fake_apollo.set_prop("application", "otherKey", "x")
        src = _src(fake_apollo, default_rule_value=_rules_json(3)).start()
        try:
            assert _wait(lambda: _value_count(src) == 3)
        finally:
            src.close()

    def test_missing_namespace_falls_back_to_default(self, fake_apollo):
        src = _src(fake_apollo, default_rule_value=_rules_json(2)).start()
        try:
            assert _wait(lambda: _value_count(src) == 2)
            # Namespace appears later → notification → real value.
            fake_apollo.set_prop("application", "flowRules", _rules_json(5))
            assert _wait(lambda: _value_count(src) == 5)
        finally:
            src.close()

    def test_release_key_304_keeps_value(self, fake_apollo):
        fake_apollo.set_prop("application", "flowRules", _rules_json(4))
        src = _src(fake_apollo)
        assert src.read_source() == _rules_json(4)
        # Same releaseKey → 304 → the cached raw comes back unchanged.
        assert src.read_source() == _rules_json(4)

    def test_rules_flow_into_manager(self, fake_apollo, manual_clock, engine):
        import sentinel_tpu as st

        fake_apollo.set_prop(
            "application", "flowRules",
            json.dumps([{"resource": "apflow", "count": 0}]),
        )
        src = _src(fake_apollo).start()
        try:
            st.flow_rule_manager.register_property(src.get_property())
            assert _wait(
                lambda: any(r.resource == "apflow"
                            for r in st.flow_rule_manager.get_rules() or [])
            )
            with pytest.raises(st.FlowBlockError):
                with st.entry("apflow"):
                    pass
        finally:
            src.close()