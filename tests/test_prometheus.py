"""Prometheus exporter: /metrics on the command center exposes
per-resource pass/block/rt/thread gauges in the exposition format
(the JMXMetricExporter analog, reference:
sentinel-metric-exporter/.../jmx/JMXMetricExporter.java:31).
"""

import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.transport.command_center import CommandCenter
from sentinel_tpu.transport.prometheus import render_metrics


class TestRenderMetrics:
    def test_gauges_per_resource(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("api", count=2)])
        manual_clock.set_ms(100)
        e = st.entry("api")
        st.entry("api")
        assert st.try_entry("api") is None
        manual_clock.set_ms(150)
        e.exit()
        text = render_metrics(engine)
        assert '# TYPE sentinel_pass_qps gauge' in text
        assert 'sentinel_pass_qps{resource="api"} 2.0' in text
        assert 'sentinel_block_qps{resource="api"} 1.0' in text
        assert 'sentinel_cur_thread_num{resource="api"} 1' in text
        assert 'sentinel_block_total_minute{resource="api"} 1' in text
        assert "sentinel_engine_enabled 1" in text
        assert "sentinel_resources 1" in text

    def test_label_escaping(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule('we"ird', count=5)])
        st.entry('we"ird')
        text = render_metrics(engine)
        assert 'resource="we\\"ird"' in text


class TestMetricsEndpoint:
    def test_scrape_over_http(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("api", count=10)])
        st.entry("api")
        center = CommandCenter(port=0).start()
        try:
            url = f"http://127.0.0.1:{center.port}/metrics"
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                ctype = resp.headers.get("Content-Type", "")
                assert ctype.startswith("text/plain")
                body = resp.read().decode()
            assert 'sentinel_pass_qps{resource="api"} 1.0' in body
        finally:
            center.stop()
