"""Prometheus exporter: /metrics on the command center exposes
per-resource pass/block/rt/thread gauges in the exposition format
(the JMXMetricExporter analog, reference:
sentinel-metric-exporter/.../jmx/JMXMetricExporter.java:31).
"""

import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.transport.command_center import CommandCenter
from sentinel_tpu.transport.prometheus import render_metrics


class TestRenderMetrics:
    def test_gauges_per_resource(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("api", count=2)])
        manual_clock.set_ms(100)
        e = st.entry("api")
        st.entry("api")
        assert st.try_entry("api") is None
        manual_clock.set_ms(150)
        e.exit()
        text = render_metrics(engine)
        assert '# TYPE sentinel_pass_qps gauge' in text
        assert 'sentinel_pass_qps{resource="api"} 2.0' in text
        assert 'sentinel_block_qps{resource="api"} 1.0' in text
        assert 'sentinel_cur_thread_num{resource="api"} 1' in text
        assert 'sentinel_block_total_minute{resource="api"} 1' in text
        assert "sentinel_engine_enabled 1" in text
        assert "sentinel_resources 1" in text

    def test_label_escaping(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule('we"ird', count=5)])
        st.entry('we"ird')
        text = render_metrics(engine)
        assert 'resource="we\\"ird"' in text


class TestMetricsEndpoint:
    def test_scrape_over_http(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("api", count=10)])
        st.entry("api")
        center = CommandCenter(port=0).start()
        try:
            url = f"http://127.0.0.1:{center.port}/metrics"
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                ctype = resp.headers.get("Content-Type", "")
                assert ctype.startswith("text/plain")
                body = resp.read().decode()
            assert 'sentinel_pass_qps{resource="api"} 1.0' in body
        finally:
            center.stop()


class TestWorkerRender:
    """sentinel_worker_* federation: zero shape with no client, live
    values off IngestClient.snapshot()."""

    def test_none_renders_full_zero_shape(self):
        from sentinel_tpu.transport.prometheus import render_worker_metrics

        text = render_worker_metrics(None)
        for fam in ("sentinel_worker_entries_total",
                    "sentinel_worker_bulk_rows_total",
                    "sentinel_worker_sheds_total",
                    "sentinel_worker_policy_served_total",
                    "sentinel_worker_reconnects_total",
                    "sentinel_worker_frames_per_entry",
                    "sentinel_worker_engine_alive",
                    "sentinel_worker_live_admissions",
                    "sentinel_worker_pending_waits",
                    "sentinel_worker_buffered_exits"):
            assert f"# TYPE {fam} " in text, fam
        assert "sentinel_worker_entries_total 0" in text
        # No worker attached -> slot id is the -1 sentinel.
        assert "sentinel_worker_id -1" in text

    def test_live_client_values(self, manual_clock, engine):
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient
        from sentinel_tpu.transport.prometheus import render_worker_metrics

        st.flow_rule_manager.load_rules([st.FlowRule("wres", count=100)])
        plane = IngestPlane(engine)
        cli = IngestClient(plane.channel(0), 0)
        try:
            for _ in range(3):
                cli.entry("wres", acquire=1)
            cli.bulk("wres", 4)
            text = render_worker_metrics(cli)
        finally:
            cli.close()
            plane.close()
        assert "sentinel_worker_entries_total 3" in text
        assert "sentinel_worker_bulk_rows_total 4" in text
        assert "sentinel_worker_engine_alive 1" in text
        assert "sentinel_worker_id 0" in text
        # 3 per-call frames + 1 bulk frame over 7 admission rows.
        assert "sentinel_worker_frames_per_entry 0.5714" in text
        assert "sentinel_worker_live_admissions 7" in text

    def test_openmetrics_dialect(self):
        from sentinel_tpu.transport.prometheus import render_worker_metrics

        text = render_worker_metrics(None, openmetrics=True)
        assert text.endswith("# EOF\n")
        # Counter family names drop the _total suffix in OM metadata;
        # the sample line keeps it.
        assert "# TYPE sentinel_worker_entries counter" in text
        assert "sentinel_worker_entries_total 0" in text


class TestClusterServerRender:
    def test_none_renders_full_zero_shape(self):
        from sentinel_tpu.transport.prometheus import (
            render_cluster_server_metrics,
        )

        text = render_cluster_server_metrics(None)
        assert "sentinel_cluster_server_decisions_total 0" in text
        assert "sentinel_cluster_server_frames_total 0" in text
        assert "sentinel_cluster_server_busy_seconds_total 0" in text
        assert "sentinel_cluster_server_lease_grants_total 0" in text
        assert ('sentinel_cluster_server_connections{namespace="default"} 0'
                in text)
        assert ('sentinel_cluster_server_stat_total{category="flow",'
                'outcome="pass"} 0' in text)

    def test_live_server_values(self):
        from sentinel_tpu.cluster import stat_log
        from sentinel_tpu.cluster.server import SentinelTokenServer
        from sentinel_tpu.cluster.token_service import DefaultTokenService
        from sentinel_tpu.transport.prometheus import (
            render_cluster_server_metrics,
        )

        stat_log.reset_counters()
        srv = SentinelTokenServer(port=0, service=DefaultTokenService())
        srv._note_work(5, 0.25)
        srv._note_work(2, 0.125)
        srv.lease_grants = 3
        stat_log.log("flow", "pass", 1, 2)
        stat_log.log("flow", "block", 1)
        text = render_cluster_server_metrics(srv)
        assert "sentinel_cluster_server_decisions_total 7" in text
        assert "sentinel_cluster_server_frames_total 2" in text
        assert "sentinel_cluster_server_busy_seconds_total 0.375" in text
        assert "sentinel_cluster_server_lease_grants_total 3" in text
        assert 'outcome="pass"} 2' in text
        assert 'outcome="block"} 1' in text
        stat_log.reset_counters()

    def test_openmetrics_dialect(self):
        from sentinel_tpu.transport.prometheus import (
            render_cluster_server_metrics,
        )

        text = render_cluster_server_metrics(None, openmetrics=True)
        assert text.endswith("# EOF\n")
        assert "# TYPE sentinel_cluster_server_stat counter" in text
