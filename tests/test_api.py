"""Public API tests: entry/exit/trace, context, statistics accounting —
mirroring the reference's CtSphTest / StatisticSlot behaviors."""

import pytest

import sentinel_tpu as st
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.models import constants as C


class TestEntryExit:
    def test_entry_without_rules_passes(self, manual_clock, engine):
        e = st.entry("free")
        assert e.resource == "free"
        e.exit()

    def test_context_manager_and_stats(self, manual_clock, engine):
        manual_clock.set_ms(0)
        with st.entry("resA") as e:
            manual_clock.advance(25)  # RT = 25ms
        stats = engine.cluster_node_stats("resA")
        assert stats["pass_qps"] == 1
        assert stats["success_qps"] == 1
        assert stats["avg_rt"] == 25
        assert stats["min_rt"] == 25
        assert stats["cur_thread_num"] == 0

    def test_double_exit_is_noop(self, manual_clock, engine):
        e = st.entry("dbl")
        e.exit()
        e.exit()
        stats = engine.cluster_node_stats("dbl")
        assert stats["success_qps"] == 1

    def test_trace_records_exception_at_exit(self, manual_clock, engine):
        with pytest.raises(ValueError):
            with st.entry("exc"):
                raise ValueError("biz error")
        stats = engine.cluster_node_stats("exc")
        assert stats["exception_qps"] == 1
        assert stats["success_qps"] == 1  # success still counted (Java: rt+success recorded, plus exception)

    def test_manual_trace(self, manual_clock, engine):
        e = st.entry("exc2")
        st.trace(RuntimeError("x"))
        e.exit()
        stats = engine.cluster_node_stats("exc2")
        assert stats["exception_qps"] == 1

    def test_tracer_filters(self, manual_clock, engine):
        """Tracer.setExceptionsToTrace/Ignore/Predicate precedence
        (Tracer.java:129-225): predicate decides alone; ignore beats
        trace; a set trace-list is exhaustive; BlockError never."""

        def exc_count(res):
            return engine.cluster_node_stats(res)["total_exception_minute"]

        # Trace-list restricts: KeyError traced, ValueError not.
        st.set_exceptions_to_trace(KeyError)
        with st.entry("tf1") as e1:
            st.trace(ValueError("no"))
        assert exc_count("tf1") == 0
        with st.entry("tf1"):
            st.trace(KeyError("yes"))
        assert exc_count("tf1") == 1

        # Ignore wins over trace (subclass matching, isAssignableFrom).
        st.set_exceptions_to_ignore(LookupError)  # KeyError's base
        with st.entry("tf1"):
            st.trace(KeyError("now ignored"))
        assert exc_count("tf1") == 1

        # The auto-trace of the with-block respects the filters too
        # (the aspect path routes through Tracer).
        with pytest.raises(KeyError):
            with st.entry("tf2"):
                raise KeyError("ignored by LookupError")
        assert exc_count("tf2") == 0

        # Predicate overrides both lists.
        st.set_exception_predicate(lambda e: "count me" in str(e))
        with st.entry("tf3"):
            st.trace(KeyError("count me"))
        with st.entry("tf3"):
            st.trace(RuntimeError("not me"))
        assert exc_count("tf3") == 1

        # BlockError never traces, predicate or not.
        assert st.should_trace(st.FlowBlockError("r", None)) is False

    def test_raising_predicate_never_leaks_the_entry(self, manual_clock, engine):
        """A broken user predicate must not swallow exit(): the thread
        slot releases and the ORIGINAL exception propagates."""
        st.set_exception_predicate(lambda e: e.args[0].startswith("x"))
        with pytest.raises(KeyError):  # NOT IndexError from the predicate
            with st.entry("tfpred"):
                raise KeyError()  # empty args → predicate raises
        stats = engine.cluster_node_stats("tfpred")
        assert stats["cur_thread_num"] == 0  # slot released
        assert stats["total_exception_minute"] == 0  # fail-safe: not traced

    def test_filter_setters_reject_non_types(self):
        with pytest.raises(ValueError):
            st.set_exceptions_to_ignore("ValueError")
        with pytest.raises(ValueError):
            st.set_exceptions_to_trace(int)  # not an exception type

    def test_wsgi_adapter_respects_tracer_filters(self, manual_clock, engine):
        """Adapters funnel through the same set_error choke point, so
        the global filters hold there too (Java: every adapter routes
        via Tracer)."""
        from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware

        st.set_exceptions_to_ignore(ValueError)

        def app(environ, start_response):
            raise ValueError("ignored")

        wrapped = SentinelWSGIMiddleware(app)
        environ = {"PATH_INFO": "/w", "REQUEST_METHOD": "GET"}
        with pytest.raises(ValueError):
            wrapped(environ, lambda *a: None)
        stats = engine.cluster_node_stats("GET:/w")
        assert stats["total_exception_minute"] == 0

    def test_decorator_respects_tracer_filters(self, manual_clock, engine):
        from sentinel_tpu.adapters.decorator import sentinel_resource

        st.set_exceptions_to_ignore(ValueError)

        @sentinel_resource("tfdec", fallback=lambda *a, **k: "fb")
        def boom():
            raise ValueError("ignored")

        assert boom() == "fb"  # fallback still runs
        stats = engine.cluster_node_stats("tfdec")
        assert stats["total_exception_minute"] == 0

    def test_block_error_not_traced(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("blk", count=0)])
        with pytest.raises(st.BlockError):
            st.entry("blk")
        stats = engine.cluster_node_stats("blk")
        assert stats["block_qps"] == 1
        assert stats["exception_qps"] == 0
        assert stats["cur_thread_num"] == 0

    def test_entry_async_detached(self, manual_clock, engine):
        e = st.entry_async("async-res")
        assert ContextUtil.get_context() is None or e not in (
            ContextUtil.get_context().entry_stack
        )
        e.exit()
        stats = engine.cluster_node_stats("async-res")
        assert stats["success_qps"] == 1


class TestContext:
    def test_named_context_and_origin(self, manual_clock, engine):
        ctx = st.context_enter("api-gateway", origin="caller-1")
        assert ctx.name == "api-gateway"
        with st.entry("downstream"):
            pass
        st.context_exit()
        assert ContextUtil.get_context() is None

    def test_default_context_forbidden(self, manual_clock, engine):
        with pytest.raises(ValueError):
            st.context_enter(C.CONTEXT_DEFAULT_NAME)

    def test_nested_entries_stack(self, manual_clock, engine):
        ctx = st.context_enter("chain")
        e1 = st.entry("outer")
        e2 = st.entry("inner")
        assert ctx.cur_entry is e2
        e2.exit()
        assert ctx.cur_entry is e1
        e1.exit()
        st.context_exit()


class TestEntryNode:
    def test_inbound_counted_globally(self, manual_clock, engine):
        with st.entry("in1", entry_type=C.EntryType.IN):
            pass
        with st.entry("out1", entry_type=C.EntryType.OUT):
            pass
        g = engine.entry_node_stats()
        assert g["pass_qps"] == 1  # only the IN entry

    def test_origin_rows_tracked(self, manual_clock, engine):
        st.context_enter("up", origin="svc-a")
        with st.entry("shared", entry_type=C.EntryType.IN):
            pass
        st.context_exit()
        row = engine.nodes.origin_row("shared", "svc-a")
        assert row is not None
        assert engine._row_stats(row)["pass_qps"] == 1


class TestLimitAppRouting:
    def test_origin_specific_rule(self, manual_clock, engine):
        """A rule with limit_app=caller1 throttles only caller1."""
        st.flow_rule_manager.load_rules(
            [st.FlowRule("api", count=1, limit_app="caller1")]
        )
        # caller1 limited to 1
        st.context_enter("c1", origin="caller1")
        e = st.try_entry("api")
        assert e is not None
        assert st.try_entry("api") is None
        e.exit()
        st.context_exit()
        # caller2 unlimited (no matching rule)
        st.context_enter("c2", origin="caller2")
        for _ in range(5):
            e = st.try_entry("api")
            assert e is not None
            e.exit()
        st.context_exit()

    def test_other_rule(self, manual_clock, engine):
        """limit_app=other applies to origins not named by any rule."""
        st.flow_rule_manager.load_rules(
            [
                st.FlowRule("api", count=100, limit_app="vip"),
                st.FlowRule("api", count=1, limit_app=C.LIMIT_APP_OTHER),
            ]
        )
        st.context_enter("cv", origin="vip")
        for _ in range(3):
            e = st.try_entry("api")
            assert e is not None
            e.exit()
        st.context_exit()
        st.context_enter("cx", origin="rando")
        e = st.try_entry("api")
        assert e is not None
        e.exit()
        assert st.try_entry("api") is None
        st.context_exit()


class TestInvalidRules:
    def test_invalid_rules_ignored_not_crashed(self, manual_clock, engine):
        """Invalid beans (empty resource, negative counts, bad refs) are
        filtered with a warning — the valid remainder still loads and
        enforces (reference: FlowRuleUtil.buildFlowRuleMap validation)."""
        st.flow_rule_manager.load_rules([
            st.FlowRule("", count=5),                 # empty resource
            st.FlowRule("ok", count=-3),              # negative count
            st.FlowRule("ok", count=2),               # the one valid rule
        ])
        manual_clock.set_ms(100)
        # Only the valid count=2 rule is compiled into the engine: the
        # negative-count bean must neither block everything nor crash.
        admitted = sum(1 for _ in range(5) if st.try_entry("ok") is not None)
        assert admitted == 2
        st.degrade_rule_manager.load_rules([
            st.DegradeRule(resource="", grade=1, count=0.5, time_window=2),
            st.DegradeRule(resource="d", grade=1, count=0.5, time_window=-1),
        ])
        st.param_flow_rule_manager.load_rules([
            st.ParamFlowRule(resource="p", param_idx=None, count=5),
            st.ParamFlowRule(resource="", param_idx=0, count=5),
        ])
        # Nothing crashed; entries on those resources pass through.
        assert st.try_entry("d") is not None
        assert st.try_entry("p") is not None
