"""Fleet-visible two-tier admission metric plane (PR 8).

The tentpole contracts:

* MetricNodeLine v2 — versioned line format whose reader still parses
  seed-format files, round-trips through MetricWriter/MetricSearcher
  across a roll boundary mixing both formats;
* per-resource conservation differential — metric-log
  ``pass+block(+shed)`` equals engine verdict counts per resource at
  pipeline depths {0, 2} with the speculative tier on and off, and the
  speculative column reconciles exactly (serves == settled matches +
  drift mismatches);
* submit-ts attribution — a depth-K pipeline's in-flight ops land in
  their arrival second, finalized at the pull;
* the dashboard ``/metric`` aggregation returns the provenance
  columns, the enriched heartbeat flows into ``/apps`` + the machine
  table, and the bounded ``sentinel_resource_*`` Prometheus export
  folds unconfigured resources into ``other``.
"""

import json

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import errors as E
from sentinel_tpu.metrics.metric_log import (
    MetricNodeLine,
    MetricSearcher,
    MetricTimer,
    MetricWriter,
)
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import config


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _mk_engine(clock, spec=False, depth=0, deadline_ms=0, resource_metrics=True):
    from sentinel_tpu.runtime.engine import Engine

    config.set(config.SPECULATIVE_ENABLED, "true" if spec else "false")
    # No auto settle dispatch: the tests drive flush/drain explicitly.
    config.set(config.SPECULATIVE_FLUSH_BATCH, "100000")
    config.set(config.PIPELINE_DEPTH, str(depth))
    config.set(config.INGEST_DEADLINE_MS, str(deadline_ms))
    config.set(
        config.RESOURCE_METRICS_ENABLED,
        "true" if resource_metrics else "false",
    )
    return Engine(clock=clock)


def _timer(eng, tmp_path, app="plane"):
    return MetricTimer(
        eng, writer=MetricWriter(base_dir=str(tmp_path), app_name=app)
    )


SEED_LINE = "1000|1970-01-01 00:00:01|res|7|3|6|1|2.5|0|4|0"


class TestLineFormat:
    def test_v2_roundtrip(self):
        ln = MetricNodeLine(
            timestamp=5000, resource="r|a", pass_qps=9, block_qps=2,
            success_qps=8, exception_qps=1, rt=3.25, occupied_pass_qps=1,
            concurrency=4, classification=0, speculative_qps=11,
            degraded_qps=5, shed_qps=2, drift=-3,
        )
        text = ln.to_line()
        assert text.split("|")[11] == "2"  # the version tag field
        back = MetricNodeLine.from_line(text)
        assert back is not None
        assert back.resource == "r_a"  # separator sanitized, as seed
        assert (back.speculative_qps, back.degraded_qps,
                back.shed_qps, back.drift) == (11, 5, 2, -3)
        assert (back.pass_qps, back.block_qps, back.concurrency) == (9, 2, 4)

    def test_seed_format_still_parses(self):
        back = MetricNodeLine.from_line(SEED_LINE)
        assert back is not None
        assert (back.pass_qps, back.block_qps, back.concurrency) == (7, 3, 4)
        assert (back.speculative_qps, back.degraded_qps,
                back.shed_qps, back.drift) == (0, 0, 0, 0)

    def test_seed_reader_view_of_v2_line(self):
        """A v1 parser reads fields [0..10] — the v2 writer must keep
        them byte-identical in position."""
        ln = MetricNodeLine(
            timestamp=1000, resource="res", pass_qps=7, block_qps=3,
            success_qps=6, exception_qps=1, rt=2.5, occupied_pass_qps=0,
            concurrency=4, speculative_qps=99, shed_qps=9,
        )
        assert ln.to_line().split("|")[:11] == SEED_LINE.split("|")

    def test_malformed_tail_degrades_to_seed_view(self):
        bad = SEED_LINE + "|vX|1|2|3|4"
        back = MetricNodeLine.from_line(bad)
        assert back is not None and back.pass_qps == 7
        assert back.speculative_qps == 0 and back.drift == 0

    def test_mid_tail_corruption_degrades_atomically(self):
        """A valid tag with a corrupted later column must yield the
        pure seed view — never a half-applied hybrid where some v2
        fields stuck before the parse failed."""
        bad = SEED_LINE + "|2|9|x|11|-3"
        back = MetricNodeLine.from_line(bad)
        assert back is not None and back.pass_qps == 7
        assert (back.speculative_qps, back.degraded_qps,
                back.shed_qps, back.drift) == (0, 0, 0, 0)

    def test_future_version_tail_parses_v2_prefix(self):
        """Versioning rule: a v3 line (extra columns appended after
        v2's) still yields the v2 columns to this reader."""
        v3 = SEED_LINE + "|3|11|5|2|-3|42|43"
        back = MetricNodeLine.from_line(v3)
        assert (back.speculative_qps, back.degraded_qps,
                back.shed_qps, back.drift) == (11, 5, 2, -3)


class TestSearcherMixedRoll:
    def test_roundtrip_across_roll_boundary_mixing_formats(self, tmp_path):
        """A rolled file set where file .1 is seed-era (11-field lines
        + its .idx) and file .2 is written by the v2 writer: one
        find() call parses both, seed lines with zero provenance."""
        base = tmp_path / "mix-metrics.log.1"
        seed_lines = [
            f"{1000 + i * 1000}|1970-01-01 00:00:01|old|{i + 1}|0|1|0|1.0|0|0|0"
            for i in range(3)
        ]
        base.write_text("\n".join(seed_lines) + "\n")
        (tmp_path / "mix-metrics.log.1.idx").write_text("3000 0\n")
        # single_file_size=1: the next write() rolls to .2.
        writer = MetricWriter(
            base_dir=str(tmp_path), app_name="mix", single_file_size=1
        )
        v2 = [
            MetricNodeLine(
                timestamp=4000 + i * 1000, resource="new", pass_qps=5,
                block_qps=1, speculative_qps=4, degraded_qps=1,
                shed_qps=2, drift=1,
            )
            for i in range(2)
        ]
        writer.write(5000, v2)
        files = writer._list_files()
        assert len(files) == 2 and files[-1].endswith(".2")

        found = MetricSearcher(base_dir=str(tmp_path), app_name="mix").find(
            0, 10_000
        )
        by_res = {}
        for ln in found:
            by_res.setdefault(ln.resource, []).append(ln)
        assert len(by_res["old"]) == 3 and len(by_res["new"]) == 2
        assert all(l.speculative_qps == 0 for l in by_res["old"])
        assert all(
            (l.speculative_qps, l.shed_qps, l.drift) == (4, 2, 1)
            for l in by_res["new"]
        )
        # Range query starting past the seed file still uses the idx
        # seek path and returns only the v2 lines.
        tail = MetricSearcher(base_dir=str(tmp_path), app_name="mix").find(
            4000, 10_000
        )
        assert {l.resource for l in tail} == {"new"}


class TestConservation:
    @pytest.mark.parametrize("depth", [0, 2])
    @pytest.mark.parametrize("spec", [False, True])
    def test_per_resource_conservation(self, depth, spec, tmp_path):
        """pass+block per (resource) across the metric-log lines equals
        the engine's verdict count per resource — every op counted
        exactly once regardless of which tier served it — and the
        speculative/drift columns reconcile exactly against the tier's
        own counters."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=spec, depth=depth)
        eng.set_flow_rules(
            [st.FlowRule("ra", count=5), st.FlowRule("rb", count=1e9)]
        )
        counts = {}
        serves = 0
        for sec in (1, 2):
            for i in range(12):
                clock.set_ms(sec * 1000 + i * 10)
                res = "ra" if i % 2 == 0 else "rb"
                op, v = eng.entry_sync(res)
                assert op is not None and v is not None
                counts[res] = counts.get(res, 0) + 1
                serves += int(v.speculative)
            eng.flush()
        eng.flush()
        eng.drain()
        clock.set_ms(3100)
        lines = _timer(eng, tmp_path).collect()
        per_res = {}
        for ln in lines:
            if ln.resource.startswith("__"):
                continue
            agg = per_res.setdefault(ln.resource, [0, 0, 0])
            agg[0] += ln.pass_qps + ln.block_qps
            agg[1] += ln.speculative_qps
            agg[2] += ln.drift
        for res, n in counts.items():
            assert per_res[res][0] == n, (res, per_res)
        c = eng.speculative.counters
        total_spec = sum(v[1] for v in per_res.values())
        total_drift = sum(v[2] for v in per_res.values())
        if spec:
            assert serves == counts["ra"] + counts["rb"]
            assert total_spec == c["spec_admits"] + c["spec_blocks"] == serves
            # Every serve settled (flush+drain above): serves ==
            # settled matches + mismatches, and the drift column nets
            # the mismatch directions exactly.
            assert c["reconciled"] == serves
            assert total_drift == c["over_admits"] - c["under_admits"]
        else:
            assert serves == 0 and total_spec == 0 and total_drift == 0

    @pytest.mark.parametrize("spec", [False, True])
    def test_shed_column_closes_the_ledger(self, spec, tmp_path):
        """Shed ops never reach the device; pass+block+shed still
        equals the submitted op count per resource, and a shed-only
        resource gets its own line."""
        clock = ManualClock(start_ms=0)
        # Deadline far above any real CPU settle latency: only the
        # forced estimate below can trip the valve.
        eng = _mk_engine(clock, spec=spec, deadline_ms=100_000)
        eng.set_flow_rules([st.FlowRule("rs", count=1e9)])
        clock.set_ms(1000)
        for _ in range(4):
            _op, v = eng.entry_sync("rs")
            assert v.admitted
        eng.flush()
        eng.drain()
        eng.ingest.force_latency_ms(1e9)  # every further op sheds
        shed = 0
        for i in range(6):
            clock.set_ms(1100 + i * 10)
            _op, v = eng.entry_sync("rs")
            assert v.reason == E.BLOCK_SHED and not v.admitted
            shed += 1
        _op, v = eng.entry_sync("shed-only")
        assert v.reason == E.BLOCK_SHED
        eng.ingest.force_latency_ms(None)
        eng.flush()
        eng.drain()
        clock.set_ms(2100)
        lines = _timer(eng, tmp_path).collect()
        by_res = {}
        for ln in lines:
            if ln.resource.startswith("__"):
                continue
            agg = by_res.setdefault(ln.resource, [0, 0])
            agg[0] += ln.pass_qps + ln.block_qps
            agg[1] += ln.shed_qps
        assert by_res["rs"][0] + by_res["rs"][1] == 4 + shed
        assert by_res["rs"][1] == shed
        # The shed-only resource never touched the device, yet it is
        # visible per resource.
        assert by_res["shed-only"] == [0, 1]
        assert eng.ingest.counters["shed_entries"] == shed + 1

    def test_bulk_serves_and_sheds_attribute_by_row_ts(self, tmp_path):
        """Bulk groups: speculative serves split across each row's
        submit second; a shed group notes its rows too."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([st.FlowRule("rb", count=1e9)])
        clock.set_ms(1000)
        ts = np.array([1000] * 4 + [2000] * 6, dtype=np.int32)
        g = eng.submit_bulk("rb", 10, ts=ts)
        assert g is not None and g.speculative
        eng.flush()
        eng.drain()
        clock.set_ms(3100)
        lines = _timer(eng, tmp_path).collect()
        spec_by_sec = {
            ln.timestamp: ln.speculative_qps
            for ln in lines
            if ln.resource == "rb"
        }
        wall = eng.clock.to_wall
        assert spec_by_sec[wall(1000)] == 4
        assert spec_by_sec[wall(2000)] == 6


class TestSubmitTsAttribution:
    def test_depth2_inflight_ops_finalize_in_their_arrival_second(
        self, tmp_path
    ):
        """With depth-2 pipelining and NO explicit drain, the pull
        itself settles the in-flight flushes: the arrival second's line
        carries the full count + provenance, exactly once."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True, depth=2)
        eng.set_flow_rules([st.FlowRule("rp", count=1e9)])
        clock.set_ms(1500)
        for _ in range(8):
            eng.entry_sync("rp")
        eng.flush()  # dispatched, deliberately left in flight
        clock.set_ms(2100)
        timer = _timer(eng, tmp_path)
        lines = [l for l in timer.collect() if l.resource == "rp"]
        assert len(lines) == 1
        ln = lines[0]
        assert ln.timestamp == eng.clock.to_wall(1000)
        assert ln.pass_qps + ln.block_qps == 8
        assert ln.speculative_qps == 8
        # Finalized: a second pull re-reads nothing for that second.
        clock.set_ms(3100)
        again = [l for l in timer.collect() if l.resource == "rp"]
        assert again == []


class TestDashboardFlow:
    def test_metric_endpoint_returns_provenance_columns(self):
        from sentinel_tpu.dashboard.app import DashboardServer

        import time as _time

        now = int(_time.time() * 1000) // 1000 * 1000
        ds = DashboardServer()
        ds.repo.save_all(
            "app-x",
            [MetricNodeLine(
                timestamp=now, resource="r1", pass_qps=5, block_qps=1,
                speculative_qps=6, degraded_qps=2, shed_qps=3, drift=-1,
            )],
        )
        code, body = ds._handle(
            "/metric", {"app": "app-x", "identity": "r1"}
        )
        assert code == 200
        rows = json.loads(body)
        assert rows and rows[0]["speculative_qps"] == 6
        assert rows[0]["degraded_qps"] == 2
        assert rows[0]["shed_qps"] == 3
        assert rows[0]["drift"] == -1

    def test_apps_renders_enriched_heartbeat_and_flags_stale(self):
        from sentinel_tpu.dashboard.app import DashboardServer

        ds = DashboardServer()
        code, _ = ds._handle(
            "/registry/machine",
            {"app": "hb", "ip": "10.0.0.1", "port": "8719",
             "health": "DEGRADED", "spec_enabled": "1",
             "spec_suspended": "1", "ingest_armed": "1",
             "shed_total": "42", "shedding": "1"},
        )
        assert code == 200
        # Seed-era heartbeat (no enrichment fields) registers too.
        code, _ = ds._handle(
            "/registry/machine",
            {"app": "hb", "ip": "10.0.0.2", "port": "8719"},
        )
        assert code == 200
        # Junk enrichment values degrade to 0, never 400.
        code, _ = ds._handle(
            "/registry/machine",
            {"app": "hb", "ip": "10.0.0.3", "port": "8719",
             "shed_total": "notanumber"},
        )
        assert code == 200
        _, body = ds._handle("/apps", {})
        machines = {m["ip"]: m for m in json.loads(body)["hb"]}
        m1 = machines["10.0.0.1"]
        assert m1["health"] == "DEGRADED" and m1["spec_suspended"] == 1
        assert m1["shed_total"] == 42 and m1["shedding"] == 1
        assert m1["stale"] is False and m1["healthy"] is True
        assert machines["10.0.0.2"]["health"] == ""
        assert machines["10.0.0.3"]["shed_total"] == 0
        # Stale heartbeat → flagged.
        for info in ds.apps._machines.values():
            if info.ip == "10.0.0.1":
                info.last_heartbeat_ms -= 120_000
        _, body = ds._handle("/apps", {})
        machines = {m["ip"]: m for m in json.loads(body)["hb"]}
        assert machines["10.0.0.1"]["stale"] is True
        assert machines["10.0.0.2"]["stale"] is False

    def test_heartbeat_health_params_and_end_to_end(self):
        from sentinel_tpu.dashboard.app import DashboardServer
        from sentinel_tpu.transport.heartbeat import HeartbeatSender

        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        sender = HeartbeatSender("127.0.0.1:9", 1234, app_name="hb-e2e",
                                 engine=eng)  # port 9: refused fast
        p = sender._health_params()
        assert p["health"] == "HEALTHY"
        assert p["spec_enabled"] == 1 and p["spec_suspended"] == 0
        assert p["ingest_armed"] == 0 and p["shedding"] == 0
        # Sheds since the last DELIVERED heartbeat flip `shedding`.
        eng.ingest.counters["shed_entries"] += 3
        p = sender._health_params()
        assert p["shed_total"] == 3 and p["shedding"] == 1
        # An undelivered heartbeat must NOT clear the edge: the
        # unreachable-dashboard send fails, and the flag persists.
        assert sender.heartbeat_once() is False
        p = sender._health_params()
        assert p["shedding"] == 1
        # End-to-end over HTTP into the dashboard registry — a
        # DELIVERED heartbeat commits the baseline and clears the edge.
        ds = DashboardServer(port=0).start()
        try:
            sender.dashboard_addr = f"127.0.0.1:{ds.port}"
            assert sender.heartbeat_once() is True
            _, body = ds._handle("/apps", {})
            (m,) = json.loads(body)["hb-e2e"]
            assert m["health"] == "HEALTHY" and m["spec_enabled"] == 1
            assert m["shed_total"] == 3
            assert m["heartbeat_age_ms"] >= 0
            assert sender._health_params()["shedding"] == 0
            # Engine.reset() zeroes the valve counters: the edge
            # detector must re-anchor, not stay blind until cumulative
            # sheds re-exceed the pre-reset baseline.
            eng.ingest.reset()
            eng.ingest.counters["shed_entries"] += 1
            p = sender._health_params()
            assert p["shed_total"] == 1 and p["shedding"] == 1
        finally:
            ds.stop()

    def test_webui_renders_machine_table_and_provenance_columns(self):
        from sentinel_tpu.dashboard.webui import CONSOLE_HTML

        for needle in (
            'id="machines"', "renderMachines", "spec_suspended",
            "shed_total", "shedding", "stale", "speculative_qps",
            "shed_qps", "drift", "heartbeat_age_ms",
        ):
            assert needle in CONSOLE_HTML, needle


class TestPrometheusResourceExport:
    def test_bounded_labels_fold_unconfigured_into_other(self):
        from sentinel_tpu.transport.prometheus import render_metrics

        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([st.FlowRule("ra", count=1e9)])
        clock.set_ms(1000)
        for _ in range(3):
            eng.entry_sync("ra")
        for _ in range(2):
            eng.entry_sync("zz-unconfigured")
        eng.flush()
        eng.drain()
        text = render_metrics(eng)
        assert 'sentinel_resource_speculative_total{resource="ra"} 3' in text
        # No rules, not a blocked heavy hitter: folded into the
        # collision-proof "__other__" row within the sentinel_resource_*
        # families (the seed per-resource QPS gauges are a different,
        # unbounded-by-design family).
        assert 'sentinel_resource_speculative_total{resource="zz-unconfigured"}' not in text
        assert 'sentinel_resource_speculative_total{resource="__other__"} 2' in text
        for fam in ("sentinel_resource_degraded_total",
                    "sentinel_resource_shed_total",
                    "sentinel_resource_drift"):
            assert f"# TYPE {fam}" in text

    def test_disabled_ledger_emits_nothing_and_skips_noting(self):
        from sentinel_tpu.transport.prometheus import render_metrics

        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True, resource_metrics=False)
        eng.set_flow_rules([st.FlowRule("rd", count=1e9)])
        clock.set_ms(1000)
        eng.entry_sync("rd")
        eng.flush()
        eng.drain()
        assert eng.resource_metrics.enabled is False
        assert eng.resource_metrics.totals() == {}
        assert "sentinel_resource_" not in render_metrics(eng)


class TestLedgerUnit:
    def test_cardinality_folds_into_other_row(self):
        from sentinel_tpu.metrics.provenance import (
            OTHER_RESOURCE,
            ResourceProvenance,
        )

        rm = ResourceProvenance(enabled=True, capacity=8)
        for i in range(20):
            rm.note(1000, f"r{i}", shed=1)
        totals = rm.totals()
        assert len(totals) <= 8
        assert totals[OTHER_RESOURCE][2] == 20 - (8 - 1)
        assert sum(t[2] for t in totals.values()) == 20

    def test_drain_is_destructive_and_sorted(self):
        from sentinel_tpu.metrics.provenance import ResourceProvenance

        rm = ResourceProvenance(enabled=True, capacity=64)
        rm.note(2500, "b", spec=2, over=3, under=1)
        rm.note(1500, "a", degraded=4)
        rm.note(3500, "c", shed=5)  # not yet complete at upto=3000
        rows = rm.drain_seconds(3000)
        assert rows == [
            (1000, "a", 0, 4, 0, 0),
            (2000, "b", 2, 0, 0, 2),
        ]
        assert rm.drain_seconds(3000) == []
        assert rm.drain_seconds(10_000) == [(3000, "c", 0, 0, 5, 0)]

    def test_note_col_groups_by_second_with_weights(self):
        from sentinel_tpu.metrics.provenance import ResourceProvenance

        rm = ResourceProvenance(enabled=True, capacity=64)
        ts = np.array([1000, 1900, 2000, 2100], dtype=np.int32)
        w = np.array([1, 2, 3, 4], dtype=np.int32)
        rm.note_col("r", ts, weights=w, spec=True, degraded=True)
        rows = rm.drain_seconds(10_000)
        assert rows == [
            (1000, "r", 3, 3, 0, 0),
            (2000, "r", 7, 7, 0, 0),
        ]


@pytest.mark.slow
class TestOverhead:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_ledger_share_within_2pct(self, depth):
        """The ≤2% metric-plane budget, asserted on the PROFILED share
        of the admission loop rather than a wall-clock A/B: on the
        timeshared 1-core box, back-to-back wall-clock runs of
        IDENTICAL code swing ±10%+ (PERF_NOTES PR-8), so an A/B band
        at a 2% effect size is pure noise — a guard that cries wolf
        gets deleted. cProfile attributes the ledger's actual
        cumulative time (note/note_col and everything under them)
        against the loop total, which is stable run to run
        (measured share: ~0.6%)."""
        import cProfile
        import pstats

        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True, depth=depth)
        # A blocking rule too, so serve AND drift note paths profile.
        eng.set_flow_rules(
            [st.FlowRule("ov", count=500), st.FlowRule("ov2", count=1e9)]
        )
        clock.set_ms(1000)
        for _ in range(64):
            eng.entry_sync("ov")
        eng.flush()
        eng.drain()  # compile + warm
        pr = cProfile.Profile()
        pr.enable()
        for _ in range(10):
            for i in range(256):
                eng.entry_sync("ov" if i % 2 else "ov2")
            eng.flush()
        pr.disable()
        eng.drain()
        stats = pstats.Stats(pr)
        total = stats.total_tt
        # Top-level ledger entry points only: their CUMULATIVE time
        # already includes the cell plumbing beneath them (summing
        # every provenance.py frame would double-count it).
        ledger = sum(
            ct
            for (path, _ln, fn), (_cc, _nc, _tt, ct, _callers)
            in stats.stats.items()
            if path.endswith("metrics/provenance.py")
            and fn in ("note", "note_serves_batch", "note_col")
        )
        assert eng.resource_metrics.totals(), "ledger actually exercised"
        share = ledger / total
        assert share <= 0.02, f"ledger share {share:.4f} of loop at depth {depth}"
