"""Adapter tests: decorator, WSGI/ASGI middleware, guarded client,
gateway rules (reference: per-adapter tests with each framework's test
kit — here plain WSGI/ASGI callables)."""

import asyncio
import io

import pytest

import sentinel_tpu as st
from sentinel_tpu.adapters import (
    GuardedClient,
    SentinelASGIMiddleware,
    SentinelWSGIMiddleware,
    guard_call,
    sentinel_resource,
)
from sentinel_tpu.adapters.gateway import (
    ApiDefinition,
    ApiPredicateItem,
    GatewayFlowRule,
    GatewayParamFlowItem,
    GatewayRequestInfo,
    PARAM_PARSE_STRATEGY_CLIENT_IP,
    PARAM_PARSE_STRATEGY_HEADER,
    PARAM_MATCH_STRATEGY_PREFIX,
    URL_MATCH_STRATEGY_PREFIX,
    gateway_api_definition_manager,
    gateway_entry,
    gateway_rule_manager,
)
from sentinel_tpu.models import constants as C


class TestDecorator:
    def test_basic_protection(self, manual_clock, engine):
        calls = []

        @sentinel_resource("deco-res")
        def work(x):
            calls.append(x)
            return x * 2

        st.flow_rule_manager.load_rules([st.FlowRule("deco-res", count=2)])
        assert work(1) == 2
        assert work(2) == 4
        with pytest.raises(st.FlowBlockError):
            work(3)
        assert calls == [1, 2]

    def test_block_handler(self, manual_clock, engine):
        @sentinel_resource("bh-res", block_handler=lambda x, error: f"blocked:{x}")
        def work(x):
            return f"ok:{x}"

        st.flow_rule_manager.load_rules([st.FlowRule("bh-res", count=1)])
        assert work(1) == "ok:1"
        assert work(2) == "blocked:2"

    def test_fallback_on_error(self, manual_clock, engine):
        @sentinel_resource("fb-res", fallback=lambda x, error: f"fallback:{x}")
        def work(x):
            raise ValueError("boom")

        assert work(5) == "fallback:5"
        stats = engine.cluster_node_stats("fb-res")
        assert stats["exception_qps"] == 1

    def test_default_resource_name(self, manual_clock, engine):
        @sentinel_resource()
        def named_fn():
            return 1

        assert named_fn() == 1
        resources = [r for r, _ in engine.nodes.resources()]
        assert any("named_fn" in r for r in resources)

    def test_async_function(self, manual_clock, engine):
        @sentinel_resource("async-res", block_handler=lambda error: "blocked")
        async def awork():
            return "ok"

        st.flow_rule_manager.load_rules([st.FlowRule("async-res", count=1)])
        assert asyncio.run(awork()) == "ok"
        assert asyncio.run(awork()) == "blocked"

    def test_param_args(self, manual_clock, engine):
        @sentinel_resource("pa-res", param_args=True, block_handler=lambda uid, error: "limited")
        def get_user(uid):
            return f"user:{uid}"

        st.param_flow_rule_manager.load_rules(
            [st.ParamFlowRule("pa-res", param_idx=0, count=1)]
        )
        assert get_user("a") == "user:a"
        assert get_user("a") == "limited"
        assert get_user("b") == "user:b"

    def test_nested_block_exits_outer_entry(self, manual_clock, engine):
        """A nested guarded call blocking must not leak the OUTER
        entry's thread slot — the BlockError passthrough still exits."""

        @sentinel_resource("outer-res")
        def outer():
            with st.entry("inner-res"):
                return "in"

        st.flow_rule_manager.load_rules(
            [st.FlowRule("outer-res", count=1e9),
             st.FlowRule("inner-res", count=0)]
        )
        for _ in range(3):
            with pytest.raises(st.FlowBlockError):
                outer()
        stats = engine.cluster_node_stats("outer-res")
        assert stats["cur_thread_num"] == 0

    def test_nested_block_exits_outer_entry_async(self, manual_clock, engine):
        @sentinel_resource("aouter-res")
        async def outer():
            with st.entry("ainner-res"):
                return "in"

        st.flow_rule_manager.load_rules(
            [st.FlowRule("aouter-res", count=1e9),
             st.FlowRule("ainner-res", count=0)]
        )
        for _ in range(2):
            with pytest.raises(st.FlowBlockError):
                asyncio.run(outer())
        stats = engine.cluster_node_stats("aouter-res")
        assert stats["cur_thread_num"] == 0


def wsgi_call(app, path="/x", method="GET"):
    environ = {"PATH_INFO": path, "REQUEST_METHOD": method, "REMOTE_ADDR": "1.1.1.1"}
    status_headers = {}

    def start_response(status, headers):
        status_headers["status"] = status

    body = b"".join(app(environ, start_response))
    return status_headers["status"], body


class TestWSGI:
    def test_pass_and_block(self, manual_clock, engine):
        def inner(environ, start_response):
            start_response("200 OK", [])
            return [b"hello"]

        app = SentinelWSGIMiddleware(inner)
        st.flow_rule_manager.load_rules([st.FlowRule("GET:/x", count=1)])
        assert wsgi_call(app) == ("200 OK", b"hello")
        status, body = wsgi_call(app)
        assert status.startswith("429")
        # another URL not limited
        assert wsgi_call(app, path="/y")[0] == "200 OK"

    def test_total_resource_counted(self, manual_clock, engine):
        def inner(environ, start_response):
            start_response("200 OK", [])
            return [b"ok"]

        app = SentinelWSGIMiddleware(inner)
        wsgi_call(app, path="/a")
        wsgi_call(app, path="/b")
        stats = engine.cluster_node_stats("web-total")
        assert stats["pass_qps"] == 2

    def test_error_traced(self, manual_clock, engine):
        def inner(environ, start_response):
            raise RuntimeError("app failure")

        app = SentinelWSGIMiddleware(inner)
        with pytest.raises(RuntimeError):
            wsgi_call(app, path="/err")
        stats = engine.cluster_node_stats("GET:/err")
        assert stats["exception_qps"] == 1


class TestASGI:
    def test_pass_and_block(self, manual_clock, engine):
        sent = []

        async def inner(scope, receive, send):
            await send({"type": "http.response.start", "status": 200, "headers": []})
            await send({"type": "http.response.body", "body": b"ok"})

        app = SentinelASGIMiddleware(inner)
        st.flow_rule_manager.load_rules([st.FlowRule("GET:/a", count=1)])

        async def call(path):
            msgs = []

            async def send(msg):
                msgs.append(msg)

            async def receive():
                return {"type": "http.request"}

            await app({"type": "http", "method": "GET", "path": path}, receive, send)
            return msgs

        msgs = asyncio.run(call("/a"))
        assert msgs[0]["status"] == 200
        msgs = asyncio.run(call("/a"))
        assert msgs[0]["status"] == 429


class TestGuardedClient:
    def test_guard_call(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("GET:http://api/x", count=1)])

        class FakeClient:
            def request(self, method, url):
                return f"{method} {url} -> 200"

        client = GuardedClient(FakeClient())
        assert client.get("http://api/x").endswith("200")
        with pytest.raises(st.FlowBlockError):
            client.get("http://api/x")
        # with fallback
        client2 = GuardedClient(FakeClient(), fallback=lambda e: "degraded")
        assert client2.get("http://api/x") == "degraded"


class TestGateway:
    @pytest.fixture(autouse=True)
    def _clean(self, manual_clock, engine):
        yield
        gateway_rule_manager.load_rules([])
        gateway_api_definition_manager.load_api_definitions([])

    def test_route_limit_by_client_ip(self, manual_clock, engine):
        gateway_rule_manager.load_rules(
            [
                GatewayFlowRule(
                    "route-1",
                    count=1,
                    param_item=GatewayParamFlowItem(
                        parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP
                    ),
                )
            ]
        )
        info_a = GatewayRequestInfo(path="/svc", client_ip="10.0.0.1")
        info_b = GatewayRequestInfo(path="/svc", client_ip="10.0.0.2")
        with gateway_entry("route-1", info_a):
            pass
        with pytest.raises(st.ParamFlowBlockError):
            with gateway_entry("route-1", info_a):
                pass
        with gateway_entry("route-1", info_b):  # other client ip independent
            pass

    def test_header_prefix_match_only(self, manual_clock, engine):
        gateway_rule_manager.load_rules(
            [
                GatewayFlowRule(
                    "route-h",
                    count=0,  # matched values are fully blocked
                    param_item=GatewayParamFlowItem(
                        parse_strategy=PARAM_PARSE_STRATEGY_HEADER,
                        field_name="X-Tenant",
                        pattern="bad-",
                        match_strategy=PARAM_MATCH_STRATEGY_PREFIX,
                    ),
                )
            ]
        )
        bad = GatewayRequestInfo(path="/p", headers={"X-Tenant": "bad-guy"})
        good = GatewayRequestInfo(path="/p", headers={"X-Tenant": "good-guy"})
        with pytest.raises(st.ParamFlowBlockError):
            with gateway_entry("route-h", bad):
                pass
        with gateway_entry("route-h", good):  # unmatched -> not limited
            pass

    def test_custom_api_group(self, manual_clock, engine):
        gateway_api_definition_manager.load_api_definitions(
            [
                ApiDefinition(
                    "my-api",
                    (ApiPredicateItem("/api/", URL_MATCH_STRATEGY_PREFIX),),
                )
            ]
        )
        gateway_rule_manager.load_rules([GatewayFlowRule("my-api", count=1)])
        info = GatewayRequestInfo(path="/api/orders")
        with gateway_entry("some-route", info):
            pass
        with pytest.raises(st.ParamFlowBlockError):
            with gateway_entry("some-route", info):
                pass
        # non-matching path not limited by the api group
        with gateway_entry("some-route", GatewayRequestInfo(path="/other")):
            pass
