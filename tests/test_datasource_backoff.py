"""Shared capped-exponential-backoff-with-jitter (datasource/backoff.py)
and its wiring into the poll-error retry loops — before this helper
only the zookeeper source backed off; the rest re-polled at a fixed
cadence and could hammer a dying config server."""

import random
import threading
import time

import pytest


class TestBackoffUnit:
    def test_growth_cap_and_reset(self):
        from sentinel_tpu.datasource.backoff import Backoff

        b = Backoff(1.0, cap_s=8.0, factor=2.0, jitter=0.0)
        assert [b.next_delay() for _ in range(5)] == [1.0, 2.0, 4.0, 8.0, 8.0]
        # The exponent clamps at the cap (an unbounded factor**n would
        # OverflowError after ~1024 failures and kill the watcher).
        assert b.failures == 3
        b.reset()
        assert b.failures == 0
        assert b.next_delay() == 1.0

    def test_no_overflow_after_thousands_of_failures(self):
        from sentinel_tpu.datasource.backoff import Backoff

        b = Backoff(1.0, cap_s=30.0, factor=2.0, jitter=0.0)
        for _ in range(5000):
            d = b.next_delay()
        assert d == 30.0

    def test_jitter_reduces_never_exceeds(self):
        from sentinel_tpu.datasource.backoff import Backoff

        rng = random.Random(42)
        b = Backoff(1.0, cap_s=30.0, factor=2.0, jitter=0.5, rng=rng)
        raw = [1.0, 2.0, 4.0, 8.0, 16.0, 30.0, 30.0]
        for expect in raw:
            d = b.next_delay()
            # Subtractive jitter: never above the undithered delay,
            # never below half of it (jitter=0.5).
            assert expect * 0.5 <= d <= expect

    def test_deterministic_with_seeded_rng(self):
        from sentinel_tpu.datasource.backoff import Backoff

        a = Backoff(0.5, rng=random.Random(7))
        b = Backoff(0.5, rng=random.Random(7))
        assert [a.next_delay() for _ in range(6)] == [
            b.next_delay() for _ in range(6)
        ]

    def test_pathological_params_clamped(self):
        from sentinel_tpu.datasource.backoff import Backoff

        b = Backoff(-1.0, cap_s=0.0, factor=0.5, jitter=2.0)
        d = b.next_delay()
        assert 0.0 <= d <= b.cap
        assert b.factor >= 1.0 and b.base > 0.0


class TestSourcesShareTheHelper:
    def test_every_network_source_owns_a_backoff(self):
        """The unify satellite: http long-poll, the long-poll base
        (apollo/consul/nacos), etcd, redis and zookeeper all retry
        through datasource.backoff.Backoff."""
        from sentinel_tpu.datasource.backoff import Backoff
        from sentinel_tpu.datasource.etcd_source import EtcdDataSource
        from sentinel_tpu.datasource.http_source import HttpLongPollDataSource
        from sentinel_tpu.datasource.redis_source import RedisDataSource
        from sentinel_tpu.datasource.zookeeper_source import ZookeeperDataSource
        from sentinel_tpu.datasource.base import json_converter
        import sentinel_tpu as st

        conv = json_converter(st.FlowRule)
        sources = [
            HttpLongPollDataSource(conv, "http://127.0.0.1:1/x",
                                   retry_interval_sec=0.25),
            EtcdDataSource(conv, "k", reconnect_interval_sec=0.25),
            RedisDataSource(conv, rule_key="k", channel="c",
                            reconnect_interval_sec=0.25),
            ZookeeperDataSource(conv, path="/p",
                                server_addr="127.0.0.1:1",
                                reconnect_interval_sec=0.25),
        ]
        for src in sources:
            assert isinstance(src._backoff, Backoff), type(src).__name__
            assert src._backoff.base == 0.25
            assert src.closed_dirty is False

    def test_longpoll_base_backs_off_between_poll_errors(self):
        """Consecutive _poll_once failures wait Backoff delays (growing),
        and a success resets the streak — observed via an injected
        deterministic rng with zero jitter."""
        from sentinel_tpu.datasource.backoff import Backoff
        from sentinel_tpu.datasource.longpoll import LongPollPushDataSource

        polls = []
        stop_after = threading.Event()

        class FlakySource(LongPollPushDataSource):
            _thread_name = "flaky-test-watcher"

            def __init__(self):
                super().__init__(lambda raw: [], 1024)
                self._backoff = Backoff(0.01, cap_s=0.04, factor=2.0,
                                        jitter=0.0)

            def read_source(self):
                return None

            def _poll_once(self):
                polls.append(time.monotonic())
                if len(polls) >= 5:
                    stop_after.set()
                    self._stop.set()
                    return
                raise RuntimeError("flaky")

            def _on_poll_error(self, e):
                pass  # the base loop owns the wait now

        src = FlakySource()
        src._thread = threading.Thread(target=src._watch_loop, daemon=True)
        src._thread.start()
        assert stop_after.wait(5.0)
        src._thread.join(timeout=1)
        assert len(polls) == 5
        gaps = [b - a for a, b in zip(polls, polls[1:])]
        # Exponential growth: 0.01, 0.02, 0.04 (cap), 0.04 — each gap
        # at least the undithered delay (scheduling only adds).
        for gap, want in zip(gaps, [0.01, 0.02, 0.04, 0.04]):
            assert gap >= want * 0.9, (gaps,)
        # And strictly growing until the cap.
        assert gaps[1] > gaps[0]

    def test_http_source_resets_streak_on_success(self):
        from sentinel_tpu.datasource.base import json_converter
        from sentinel_tpu.datasource.http_source import HttpLongPollDataSource
        import sentinel_tpu as st

        src = HttpLongPollDataSource(
            json_converter(st.FlowRule), "http://127.0.0.1:1/x",
            retry_interval_sec=0.05,
        )
        src._backoff.next_delay()
        src._backoff.next_delay()
        assert src._backoff.failures == 2
        src._backoff.reset()
        assert src._backoff.failures == 0
