"""DefaultController flow-rule parity tests.

The acceptance bar from BASELINE.md: pass/block parity with the
reference's DefaultController — exercised here as (a) the FlowQpsDemo
scenario (QPS=20 rule pins passes at 20/s under open-loop load,
reference: sentinel-demo-basic FlowQpsDemo / README.md:108-118), (b)
thread-grade concurrency limiting, and (c) randomized batched-mode
parity against the sequential oracle."""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.testing.oracle import OracleFlowEngine


class TestFlowQpsDemo:
    def test_qps_rule_pins_pass_rate(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("demo", count=20)])
        passes = blocks = 0
        # Open-loop load: 100 requests/second for 5 seconds.
        for sec in range(5):
            sec_pass = 0
            for i in range(100):
                manual_clock.set_ms(sec * 1000 + i * 10)
                try:
                    e = st.entry("demo")
                    e.exit()
                    passes += 1
                    sec_pass += 1
                except st.FlowBlockError:
                    blocks += 1
            assert sec_pass == 20, f"second {sec}: expected 20 passes, got {sec_pass}"
        assert passes == 100
        assert blocks == 400

    def test_window_slide_refills(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("res", count=2)])
        manual_clock.set_ms(0)
        assert st.try_entry("res") is not None
        assert st.try_entry("res") is not None
        assert st.try_entry("res") is None  # 2 + 1 > 2
        # The t=0 bucket is deprecated when its age EXCEEDS the interval
        # (strict >, LeapArray#isWindowDeprecated): at exactly t=1000 it
        # still counts; at t=1001 it no longer does.
        manual_clock.set_ms(1000)
        assert st.try_entry("res") is None
        manual_clock.set_ms(1001)
        assert st.try_entry("res") is not None

    def test_blocked_rule_attribution(self, manual_clock, engine):
        rule = st.FlowRule("attrib", count=0)
        st.flow_rule_manager.load_rules([rule])
        with pytest.raises(st.FlowBlockError) as ei:
            st.entry("attrib")
        assert ei.value.rule == rule
        assert ei.value.resource == "attrib"


class TestThreadGrade:
    def test_concurrency_limit(self, manual_clock, engine):
        st.flow_rule_manager.load_rules(
            [st.FlowRule("svc", grade=C.FLOW_GRADE_THREAD, count=2)]
        )
        e1 = st.try_entry("svc")
        e2 = st.try_entry("svc")
        assert e1 is not None and e2 is not None
        assert st.try_entry("svc") is None  # 2 running + 1 > 2
        e1.exit()
        manual_clock.advance(1)
        e3 = st.try_entry("svc")
        assert e3 is not None
        e2.exit()
        e3.exit()

    def test_thread_gauge_reads(self, manual_clock, engine):
        st.flow_rule_manager.load_rules(
            [st.FlowRule("g", grade=C.FLOW_GRADE_THREAD, count=10)]
        )
        entries = [st.try_entry("g") for _ in range(3)]
        stats = engine.cluster_node_stats("g")
        assert stats["cur_thread_num"] == 3
        for e in entries:
            e.exit()
        stats = engine.cluster_node_stats("g")
        assert stats["cur_thread_num"] == 0


class TestMultiRuleSameNode:
    def test_two_rules_same_node_admit_min(self, manual_clock, engine):
        """Two default QPS rules on one resource: the tighter one governs
        and an entry must NOT charge its own acquire to itself (regression:
        second rule-slot on the same node once saw the entry's own
        contribution, under-admitting by one)."""
        st.flow_rule_manager.load_rules(
            [st.FlowRule("r", count=10), st.FlowRule("r", count=7)]
        )
        ops = [engine.submit_entry("r", ts=0) for _ in range(10)]
        engine.flush()
        admitted = [op.verdict.admitted for op in ops]
        assert sum(admitted) == 7
        assert admitted == [True] * 7 + [False] * 3


class TestBatchedParity:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_deferred_batch_matches_oracle(self, manual_clock, engine, seed):
        """Many entries submitted then flushed once must produce exactly
        the sequential oracle's pass/block pattern (uniform acquire,
        single rule per resource — the exact-parity regime)."""
        rng = np.random.default_rng(seed)
        st.flow_rule_manager.load_rules(
            [st.FlowRule("A", count=10), st.FlowRule("B", count=3)]
        )
        oracle = OracleFlowEngine()
        oracle.set_qps_rule("A", 10)
        oracle.set_qps_rule("B", 3)

        resources = rng.choice(["A", "B"], 80)
        ts = np.sort(rng.integers(0, 400, 80))  # all within bucket [0,500)
        manual_clock.set_ms(int(ts[-1]))

        ops = [
            engine.submit_entry(res, ts=int(t), entry_type=C.EntryType.IN)
            for res, t in zip(resources, ts)
        ]
        engine.flush()
        got = [op.verdict.admitted for op in ops]
        want = [oracle.entry(res, int(t)) for res, t in zip(resources, ts)]
        assert got == want

    def test_sync_stream_matches_oracle_across_windows(self, manual_clock, engine):
        """Sync (per-entry flush) stream over several windows."""
        st.flow_rule_manager.load_rules([st.FlowRule("S", count=5)])
        oracle = OracleFlowEngine()
        oracle.set_qps_rule("S", 5)
        rng = np.random.default_rng(3)
        t = 0
        for _ in range(300):
            t += int(rng.choice([1, 5, 30, 120], p=[0.4, 0.3, 0.2, 0.1]))
            manual_clock.set_ms(t)
            got = st.try_entry("S")
            want = oracle.entry("S", t)
            assert (got is not None) == want, f"t={t}"
            if got is not None:
                got.exit()
                oracle.exit("S", t, 0)
