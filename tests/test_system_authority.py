"""System-adaptive protection + authority rule tests (reference:
SystemRuleManager.checkSystem / AuthorityRuleChecker semantics)."""

import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.utils.system_status import sampler


class TestSystemRules:
    def test_qps_limit_inbound_only(self, manual_clock, engine):
        st.system_rule_manager.load_rules([st.SystemRule(qps=3)])
        # Inbound capped at 3.
        for i in range(3):
            with st.entry(f"in{i}", entry_type=C.EntryType.IN):
                pass
        with pytest.raises(st.SystemBlockError) as ei:
            st.entry("in4", entry_type=C.EntryType.IN)
        assert ei.value.limit_type == "qps"
        # Outbound unaffected.
        with st.entry("out", entry_type=C.EntryType.OUT):
            pass

    def test_thread_limit(self, manual_clock, engine):
        # checkSystem uses strict > on the PRE-increment gauge
        # (SystemRuleManager.java:321-324): with max_thread=2 the third
        # concurrent entry still passes (2 > 2 is false); the fourth is
        # blocked (3 > 2).
        st.system_rule_manager.load_rules([st.SystemRule(max_thread=2)])
        e1 = st.entry("a", entry_type=C.EntryType.IN)
        e2 = st.entry("b", entry_type=C.EntryType.IN)
        e3 = st.entry("c", entry_type=C.EntryType.IN)
        with pytest.raises(st.SystemBlockError) as ei:
            st.entry("d", entry_type=C.EntryType.IN)
        assert ei.value.limit_type == "thread"
        e1.exit()
        e2.exit()
        e3.exit()

    def test_avg_rt_limit(self, manual_clock, engine):
        st.system_rule_manager.load_rules([st.SystemRule(avg_rt=50)])
        manual_clock.set_ms(0)
        e = st.entry("slow", entry_type=C.EntryType.IN)
        manual_clock.advance(200)  # RT 200ms
        e.exit()
        with pytest.raises(st.SystemBlockError) as ei:
            st.entry("next", entry_type=C.EntryType.IN)
        assert ei.value.limit_type == "rt"

    def test_load_bbr(self, manual_clock, engine):
        st.system_rule_manager.load_rules([st.SystemRule(highest_system_load=1.0)])
        sampler.force(load=5.0, cpu=-1.0)
        try:
            # checkBbr blocks only when the PRE-increment concurrency
            # exceeds 1 AND the BBR capacity (maxSuccessQps*minRt/1000,
            # here 0 with an idle window): entries 1-2 pass (gauge 0,1),
            # the third (gauge 2 > 1) is blocked.
            e1 = st.entry("l1", entry_type=C.EntryType.IN)
            e2 = st.entry("l2", entry_type=C.EntryType.IN)
            with pytest.raises(st.SystemBlockError) as ei:
                st.entry("l3", entry_type=C.EntryType.IN)
            assert ei.value.limit_type == "load"
            e1.exit()
            e2.exit()
        finally:
            sampler.force(load=-1.0, cpu=-1.0)

    def test_cpu_limit(self, manual_clock, engine):
        st.system_rule_manager.load_rules([st.SystemRule(highest_cpu_usage=0.5)])
        sampler.force(load=-1.0, cpu=0.9)
        try:
            with pytest.raises(st.SystemBlockError) as ei:
                st.entry("c1", entry_type=C.EntryType.IN)
            assert ei.value.limit_type == "cpu"
        finally:
            sampler.force(load=-1.0, cpu=-1.0)

    def test_min_across_rules(self, manual_clock, engine):
        st.system_rule_manager.load_rules(
            [st.SystemRule(qps=100), st.SystemRule(qps=2)]
        )
        assert st.system_rule_manager.effective.qps == 2

    def test_system_block_counts_stats(self, manual_clock, engine):
        st.system_rule_manager.load_rules([st.SystemRule(qps=1)])
        with st.entry("s1", entry_type=C.EntryType.IN):
            pass
        with pytest.raises(st.SystemBlockError):
            st.entry("s2", entry_type=C.EntryType.IN)
        g = engine.entry_node_stats()
        assert g["pass_qps"] == 1
        assert g["block_qps"] == 1


class TestAuthorityRules:
    def test_white_list(self, manual_clock, engine):
        st.authority_rule_manager.load_rules(
            [st.AuthorityRule("api", limit_app="appA,appB", strategy=C.AUTHORITY_WHITE)]
        )
        st.context_enter("cw", origin="appA")
        with st.entry("api"):
            pass
        st.context_exit()
        st.context_enter("cw2", origin="appC")
        with pytest.raises(st.AuthorityBlockError):
            st.entry("api")
        st.context_exit()

    def test_black_list(self, manual_clock, engine):
        st.authority_rule_manager.load_rules(
            [st.AuthorityRule("api2", limit_app="evil", strategy=C.AUTHORITY_BLACK)]
        )
        st.context_enter("cb", origin="evil")
        with pytest.raises(st.AuthorityBlockError):
            st.entry("api2")
        st.context_exit()
        st.context_enter("cb2", origin="good")
        with st.entry("api2"):
            pass
        st.context_exit()

    def test_empty_origin_passes(self, manual_clock, engine):
        st.authority_rule_manager.load_rules(
            [st.AuthorityRule("api3", limit_app="appA", strategy=C.AUTHORITY_WHITE)]
        )
        with st.entry("api3"):  # no origin -> not checked
            pass
