"""Sharded token plane (PR 17) — differential + scoping pins.

The acceptance surface: flow-id hash routing is stable across
processes (pinned CRC values); verdicts through M shards are
BIT-IDENTICAL to the single-server oracle (wire level and through the
engine bulk seam at pipeline depths 0 and 2, leases on and off); a
dead shard degrades only ITS flows while other shards keep serving;
a shard bounce clears exactly the dead shard's leases (the PR-16
disconnect cleared ALL leases — the regression pinned here); and the
versioned shard map swaps the connection set when the operator moves
it.
"""

from __future__ import annotations

import threading
import time

import pytest

from sentinel_tpu.cluster import (
    ClusterStateManager,
    DefaultTokenService,
    EmbeddedClusterTokenServerProvider,
    ShardMap,
    ShardedTokenClient,
    TokenClientProvider,
    cluster_flow_rule_manager,
    cluster_server_config_manager,
    shard_of,
)
from sentinel_tpu.cluster.client import ClusterTokenClient, client_stats
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.cluster.state import ClusterClientConfigManager
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule, ParamFlowRule
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import SentinelConfig, config


def cluster_rule(resource, count, flow_id, fallback=True):
    return FlowRule(
        resource,
        count=count,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id,
            threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            fallback_to_local_when_fail=fallback,
        ),
    )


def concurrent_rule(resource, count, flow_id):
    return FlowRule(
        resource,
        count=count,
        grade=C.FLOW_GRADE_THREAD,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id,
            threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            fallback_to_local_when_fail=False,
        ),
    )


def cluster_param_rule(resource, count, flow_id, param_idx=0):
    return ParamFlowRule(
        resource,
        count=count,
        param_idx=param_idx,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id,
            threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            fallback_to_local_when_fail=True,
        ),
    )


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


@pytest.fixture(autouse=True)
def _stats_reset():
    client_stats.reset()
    yield
    client_stats.reset()


@pytest.fixture()
def cluster_env():
    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=30000.0
    )
    yield
    cluster_flow_rule_manager.clear()
    ClusterStateManager.stop()
    TokenClientProvider.clear()
    EmbeddedClusterTokenServerProvider.clear()


def _servers(n):
    return [
        SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=ManualClock(0))
        ).start()
        for _ in range(n)
    ]


def _sharded(servers, **kw):
    return ShardedTokenClient(
        ShardMap(0, [("127.0.0.1", s.port) for s in servers]), **kw
    ).start()


def _flow_on_shard(shard, n_shards, start=12000):
    """First flow id >= start that routes to ``shard`` of ``n_shards``."""
    fid = start
    while shard_of(fid, n_shards) != shard:
        fid += 1
    return fid


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class TestShardRouting:
    def test_shard_of_pinned_and_process_stable(self):
        """The routing hash is CRC32 over the LE i64 flow id — pinned
        values, because every engine in the fleet must agree (Python
        ``hash`` would split admission across interpreter runs)."""
        assert [shard_of(f, 4) for f in range(10)] == [
            1, 3, 0, 2, 3, 1, 2, 0, 0, 2,
        ]
        assert all(shard_of(f, 1) == 0 for f in range(10))
        assert 0 <= shard_of(-17, 4) < 4
        # Spread: 256 sequential flows land on every shard.
        seen = {shard_of(f, 4) for f in range(256)}
        assert seen == {0, 1, 2, 3}

    def test_shard_map_from_config(self):
        assert ShardMap.from_config() is None  # default shards=1
        config.set(SentinelConfig.CLUSTER_SHARDS, "2")
        config.set(
            SentinelConfig.CLUSTER_SHARDS_MAP,
            "127.0.0.1:1001,127.0.0.1:1002",
        )
        config.set(SentinelConfig.CLUSTER_SHARDS_MAP_VERSION, "3")
        m = ShardMap.from_config()
        assert m is not None and m.n_shards == 2 and m.version == 3
        assert m.endpoints == [("127.0.0.1", 1001), ("127.0.0.1", 1002)]
        # Incomplete map (fewer endpoints than shards): NOT sharded —
        # routing a flow to a nonexistent shard is worse than one
        # server.
        config.set(SentinelConfig.CLUSTER_SHARDS, "4")
        assert ShardMap.from_config() is None

    def test_build_client_picks_sharded(self, cluster_env):
        config.set(SentinelConfig.CLUSTER_SHARDS, "2")
        config.set(
            SentinelConfig.CLUSTER_SHARDS_MAP,
            "127.0.0.1:1001,127.0.0.1:1002",
        )
        client = ClusterClientConfigManager.build_client()
        assert isinstance(client, ShardedTokenClient)
        assert client.n_shards == 2


class TestShardedDifferential:
    @pytest.mark.parametrize("lease_on", [False, True])
    def test_wire_verdicts_match_single_server_oracle(
        self, cluster_env, lease_on
    ):
        """The same row stream through 3 shards returns the same status
        sequence as one server: sharding changes WHERE a flow's window
        lives, never its math."""
        flows = [12000 + k for k in range(6)]
        rules = [
            cluster_rule(f"r{k}", 4, flow_id=f) for k, f in enumerate(flows)
        ]
        rows = [(flows[i % 6], 1, False) for i in range(48)]

        def run(n_shards):
            cluster_flow_rule_manager.clear()
            cluster_server_config_manager.load_global_flow_config(
                exceed_count=1.0, max_allowed_qps=30000.0
            )
            cluster_flow_rule_manager.load_rules("default", rules)
            config.set(
                SentinelConfig.CLUSTER_LEASE_ENABLED,
                "true" if lease_on else "false",
            )
            servers = _servers(n_shards)
            try:
                if n_shards == 1:
                    client = ClusterTokenClient(
                        "127.0.0.1", servers[0].port
                    ).start()
                else:
                    client = _sharded(servers)
                out = []
                for _ in range(3):  # three windows of 16 rows
                    for i in range(0, 48, 16):
                        out.extend(
                            r.status
                            for r in client.request_tokens_batch(rows[i:i + 16])
                        )
                client.stop()
                return out
            finally:
                for s in servers:
                    s.stop()

        assert run(3) == run(1)

    def test_param_verdicts_match_single_server_oracle(self, cluster_env):
        flows = [12100, 12101]
        rules = [
            cluster_param_rule(f"p{k}", 2, flow_id=f)
            for k, f in enumerate(flows)
        ]
        rows = [
            (flows[i % 2], 1, ["v%d" % (i % 3)]) for i in range(24)
        ]

        def run(n_shards):
            cluster_flow_rule_manager.clear()
            cluster_server_config_manager.load_global_flow_config(
                exceed_count=1.0, max_allowed_qps=30000.0
            )
            cluster_flow_rule_manager.load_rules("default", rules)
            servers = _servers(n_shards)
            try:
                if n_shards == 1:
                    client = ClusterTokenClient(
                        "127.0.0.1", servers[0].port
                    ).start()
                else:
                    client = _sharded(servers)
                out = [
                    r.status
                    for r in client.request_param_tokens_batch(rows)
                ]
                client.stop()
                return out
            finally:
                for s in servers:
                    s.stop()

        assert run(2) == run(1)

    @pytest.mark.parametrize("depth", [0, 2])
    @pytest.mark.parametrize("lease_on", [False, True])
    def test_engine_sharded_matches_single_server(
        self, cluster_env, manual_clock, depth, lease_on
    ):
        """The engine's bulk seam over a ShardedTokenClient produces
        verdicts bit-identical to the single-server plane, at pipeline
        depths 0 and 2, leases on and off — the engine needs (and has)
        zero routing knowledge."""
        flows = [12200 + k for k in range(4)]
        rules = [
            cluster_rule(f"s{k}", 5, flow_id=f) for k, f in enumerate(flows)
        ]
        reqs = [
            {"resource": f"s{i % 4}", "ts": 1000} for i in range(32)
        ]

        def run(n_shards):
            cluster_flow_rule_manager.clear()
            cluster_server_config_manager.load_global_flow_config(
                exceed_count=1.0, max_allowed_qps=30000.0
            )
            cluster_flow_rule_manager.load_rules("default", rules)
            config.set(
                SentinelConfig.CLUSTER_LEASE_ENABLED,
                "true" if lease_on else "false",
            )
            servers = _servers(n_shards)
            try:
                if n_shards == 1:
                    client = ClusterTokenClient(
                        "127.0.0.1", servers[0].port
                    ).start()
                else:
                    client = _sharded(servers)
                TokenClientProvider.register(client)
                ClusterStateManager.set_to_client()
                eng = Engine(clock=manual_clock)
                eng.pipeline_depth = depth
                eng.set_flow_rules(rules)
                ops = eng.submit_many([dict(r) for r in reqs])
                eng.flush()
                eng.drain()
                out = [bool(op.verdict.admitted) for op in ops]
                eng.close()
                client.stop()
                return out
            finally:
                for s in servers:
                    s.stop()
                TokenClientProvider.clear()
                ClusterStateManager.stop()

        sharded = run(3)
        oracle = run(1)
        assert sharded == oracle
        # The budgets actually bound the run: 4 flows x count 5.
        assert sum(sharded) == 20


class TestDeadShardScoping:
    def test_dead_shard_degrades_only_its_flows(self, cluster_env):
        """Kill shard 0's server: its flows answer FAIL fast (honest
        per-shard fallback counters); shard 1's flows keep getting real
        server verdicts the whole time."""
        fid0 = _flow_on_shard(0, 2)
        fid1 = _flow_on_shard(1, 2)
        cluster_flow_rule_manager.load_rules(
            "default",
            [cluster_rule("a", 100, fid0), cluster_rule("b", 100, fid1)],
        )
        servers = _servers(2)
        # Compile the decision kernel before the 0.5s-timeout wire
        # traffic: conftest's periodic jax.clear_caches() can land
        # right before this test, and the ~1s cold compile would eat
        # the request timeout. acquire=0 charges nothing.
        servers[0].service.request_tokens([(fid0, 0, False)])
        client = _sharded(
            servers, request_timeout_sec=0.5, reconnect_interval_sec=0.05
        )
        try:
            rows = [(fid0, 1, False), (fid1, 1, False)] * 4
            assert all(
                r.status == C.TokenResultStatus.OK
                for r in client.request_tokens_batch(rows)
            )
            servers[0].stop()
            assert _wait(
                lambda: (
                    client.request_tokens_batch(rows) is not None
                    and not client.clients[0].connected
                )
            )
            out = client.request_tokens_batch(rows)
            s0 = [r.status for i, r in enumerate(out) if i % 2 == 0]
            s1 = [r.status for i, r in enumerate(out) if i % 2 == 1]
            assert all(s == C.TokenResultStatus.FAIL for s in s0)
            assert all(s == C.TokenResultStatus.OK for s in s1)
            rows_by_shard = {r["shard"]: r for r in client.shard_rows()}
            assert rows_by_shard[0]["fallbacks"] > 0
            assert rows_by_shard[1]["fallbacks"] == 0
            assert rows_by_shard[1]["connected"]
        finally:
            client.stop()
            for s in servers:
                s.stop()

    def test_shard_bounce_clears_only_its_leases(self, cluster_env):
        """THE lease-scoping regression: leases live per connection, so
        killing shard A voids exactly A's leases and unreported
        consumption — shard B's lease table survives and keeps serving
        zero-RPC admits at an unchanged hit rate."""
        config.set(SentinelConfig.CLUSTER_LEASE_ENABLED, "true")
        config.set(SentinelConfig.CLUSTER_LEASE_TTL_MS, "30000")
        fid0 = _flow_on_shard(0, 2)
        fid1 = _flow_on_shard(1, 2)
        cluster_flow_rule_manager.load_rules(
            "default",
            [
                cluster_rule("a", 10000, fid0),
                cluster_rule("b", 10000, fid1),
            ],
        )
        servers = _servers(2)
        client = _sharded(
            servers, request_timeout_sec=0.5, reconnect_interval_sec=30.0
        )
        try:
            # Drive both flows hot until BOTH shards hold leases.
            def both_leased():
                client.request_tokens_batch(
                    [(fid0, 1, False)] * 4 + [(fid1, 1, False)] * 4
                )
                return (
                    client.clients[0]._leases and client.clients[1]._leases
                )

            assert _wait(both_leased), "leases never granted"
            admits_before = client.clients[1].stats.snapshot()["lease_admits"]
            leases_b = dict(client.clients[1]._leases)
            assert leases_b

            servers[0].stop()
            assert _wait(
                lambda: (
                    client.request_tokens_batch([(fid0, 1, False)]) is not None
                    and not client.clients[0].connected
                )
            )
            # Shard 0's connection-scoped state is gone...
            assert client.clients[0]._leases == {}
            assert client.clients[0]._lease_reports == {}
            # ...and shard 1's lease table was NOT touched.
            assert client.clients[1]._leases == leases_b
            # Shard 1 keeps serving lease admits RPC-free.
            out = client.request_tokens_batch([(fid1, 1, False)] * 8)
            assert all(r.status == C.TokenResultStatus.OK for r in out)
            admits_after = client.clients[1].stats.snapshot()["lease_admits"]
            assert admits_after >= admits_before + 8
        finally:
            client.stop()
            for s in servers:
                s.stop()

    def test_reconnect_reasserts_dead_shard_only(self, cluster_env):
        """Restarting shard 0 on the same port re-admits its flows via
        the fresh server while shard 1's connection (and its windows)
        never blinked."""
        fid0 = _flow_on_shard(0, 2)
        fid1 = _flow_on_shard(1, 2)
        cluster_flow_rule_manager.load_rules(
            "default",
            [cluster_rule("a", 1000, fid0), cluster_rule("b", 1000, fid1)],
        )
        servers = _servers(2)
        port0 = servers[0].port
        client = _sharded(
            servers, request_timeout_sec=0.5, reconnect_interval_sec=0.05
        )
        try:
            client.request_tokens_batch([(fid0, 1, False), (fid1, 1, False)])
            shard1_frames = client.clients[1].stats.snapshot()["requests"]
            servers[0].stop()
            assert _wait(
                lambda: (
                    client.request_tokens_batch([(fid0, 1, False)]) is not None
                    and not client.clients[0].connected
                )
            )
            servers[0] = SentinelTokenServer(
                port=port0, service=DefaultTokenService(clock=ManualClock(0))
            ).start()

            def reconverged():
                out = client.request_tokens_batch([(fid0, 1, False)])
                return out[0].status == C.TokenResultStatus.OK

            assert _wait(reconverged, 10.0), "shard 0 never reconverged"
            # Shard 1 was never bounced: still the same connection,
            # still serving.
            assert client.clients[1].connected
            out = client.request_tokens_batch([(fid1, 1, False)])
            assert out[0].status == C.TokenResultStatus.OK
            assert (
                client.clients[1].stats.snapshot()["requests"]
                > shard1_frames
            )
        finally:
            client.stop()
            for s in servers:
                s.stop()


class TestShardMapAndTokens:
    def test_shard_map_version_swaps_connection_set(self, cluster_env):
        servers = _servers(2)
        config.set(SentinelConfig.CLUSTER_SHARDS, "2")
        config.set(
            SentinelConfig.CLUSTER_SHARDS_MAP,
            ",".join("127.0.0.1:%d" % s.port for s in servers),
        )
        config.set(SentinelConfig.CLUSTER_SHARDS_MAP_VERSION, "1")
        client = ClusterClientConfigManager.build_client().start()
        try:
            old_ports = [c.port for c in client.clients]
            assert client.maybe_reload() is False  # same version: no-op
            replacement = _servers(2)
            config.set(
                SentinelConfig.CLUSTER_SHARDS_MAP,
                ",".join("127.0.0.1:%d" % s.port for s in replacement),
            )
            config.set(SentinelConfig.CLUSTER_SHARDS_MAP_VERSION, "2")
            fid = _flow_on_shard(0, 2)
            cluster_flow_rule_manager.load_rules(
                "default", [cluster_rule("m", 100, fid)]
            )
            # Any entry point notices the moved version and rebuilds.
            out = client.request_tokens_batch([(fid, 1, False)])
            assert out[0].status == C.TokenResultStatus.OK
            assert client.shard_map.version == 2
            new_ports = [c.port for c in client.clients]
            assert new_ports == [s.port for s in replacement]
            assert new_ports != old_ports
            for s in replacement:
                s.stop()
        finally:
            client.stop()
            for s in servers:
                s.stop()

    def test_concurrent_token_release_routes_to_granting_shard(
        self, cluster_env
    ):
        fid = _flow_on_shard(1, 2)
        cluster_flow_rule_manager.load_rules(
            "default", [concurrent_rule("cc", 8, fid)]
        )
        servers = _servers(2)
        client = _sharded(servers)
        try:
            r = client.request_concurrent_token(fid, 1)
            assert r.status == C.TokenResultStatus.OK and r.token_id
            rel = client.release_concurrent_token(r.token_id)
            assert rel.status in (
                C.TokenResultStatus.OK, C.TokenResultStatus.RELEASE_OK
            )
            # Gauge scoping: the granting shard's service is back to 0.
            assert servers[1].service.concurrent.now_calls(fid) == 0
            assert servers[1].service.concurrent.held_tokens() == 0
        finally:
            client.stop()
            for s in servers:
                s.stop()


class TestShardedChaos:
    def test_kill_one_shard_mid_load_soak(self, cluster_env, manual_clock):
        """Two engines x two shards under threaded load; shard 0 dies
        mid-soak. Its flows degrade to the local-quota stance (bounded
        admission, honest fallbacks); shard 1 keeps true batch-frame
        parity; after quiesce every THREAD gauge reads exactly 0."""
        fid0 = _flow_on_shard(0, 2)
        fid1 = _flow_on_shard(1, 2)
        fidc = _flow_on_shard(1, 2, start=13000)
        rule_a = cluster_rule("sa", 30, fid0, fallback=True)
        rule_b = cluster_rule("sb", 10000, fid1, fallback=True)
        rule_c = concurrent_rule("sc", 64, fidc)
        cluster_flow_rule_manager.load_rules(
            "default", [rule_a, rule_b, rule_c]
        )
        servers = _servers(2)
        client = _sharded(
            servers, request_timeout_sec=2.0, reconnect_interval_sec=30.0
        )
        TokenClientProvider.register(client)
        ClusterStateManager.set_to_client()
        engines = [Engine(clock=manual_clock) for _ in range(2)]
        for eng in engines:
            eng.set_flow_rules([rule_a, rule_b, rule_c])
        stop_soak = threading.Event()

        def soak(eng):
            while not stop_soak.is_set():
                ops = eng.submit_many(
                    [{"resource": "sa", "ts": 1000},
                     {"resource": "sb", "ts": 1000}] * 4
                )
                eng.flush()
                eng.drain()
                del ops

        threads = [
            threading.Thread(target=soak, args=(eng,)) for eng in engines
        ]
        try:
            for t in threads:
                t.start()
            time.sleep(0.3)  # soak with both shards up
            servers[0].stop()  # mid-load kill
            # Soak through the outage until the dead shard actually
            # FAILed some rows (post-detection, behind the reconnect
            # gate) — a fixed sleep can end inside the first blocked
            # RPC's timeout.
            assert _wait(
                lambda: (
                    not client.clients[0].connected
                    and client.clients[0].stats.snapshot()["fallbacks"] > 0
                ),
                20.0,
            )
            stop_soak.set()
            for t in threads:
                t.join(timeout=10.0)
            assert not any(t.is_alive() for t in threads)

            rows = {r["shard"]: r for r in client.shard_rows()}
            # Dead shard: honest fallbacks, zero leases left.
            assert rows[0]["fallbacks"] > 0
            assert rows[0]["leases"] == 0
            # Live shard: still connected, zero fallbacks, and it kept
            # answering real frames through the outage.
            assert rows[1]["connected"]
            assert rows[1]["fallbacks"] == 0
            assert rows[1]["requests"] > 0
            # sb admission kept flowing on the live shard during the
            # outage (server-side window counted its grants).
            assert any(
                f["flowId"] == fid1 and f["currentQps"] > 0
                for f in servers[1].service.flow_stats()
            )
            # Bounded degrade: sa's local stance still admitted some
            # traffic but never unboundedly (local rule count caps it
            # per window; the fallback path was actually exercised).
            assert client_stats.snapshot()["fallbacks"] > 0

            # THREAD-grade gauges: grab + release through the live
            # shard, then quiesce — exactly 0 held.
            eng = engines[0]
            ops = eng.submit_many([{"resource": "sc"} for _ in range(4)])
            eng.flush()
            held = [op for op in ops if op.verdict.admitted]
            assert held
            for op in held:
                eng.submit_exit(
                    op.rows, rt=1, resource="sc",
                    cluster_tokens=op.cluster_tokens,
                )
            eng.flush()
            assert servers[1].service.concurrent.now_calls(fidc) == 0
            assert servers[1].service.concurrent.held_tokens() == 0
        finally:
            stop_soak.set()
            for t in threads:
                if t.is_alive():
                    t.join(timeout=5.0)
            for eng in engines:
                eng.close()
            client.stop()
            for s in servers:
                s.stop()
