"""ConsulDataSource against an in-process fake Consul agent — same
approach as the etcd/Redis tests (fake server, real wire semantics:
blocking queries with X-Consul-Index).

Reference parity target: sentinel-extension/sentinel-datasource-consul/
.../ConsulDataSource.java:38 (initial KV get + blocking-query watch),
plus WritableDataSource semantics.
"""

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource.base import json_converter
from sentinel_tpu.datasource.consul_source import ConsulDataSource


class FakeConsul(ThreadingHTTPServer):
    """KV get (with blocking-query support) + put."""

    daemon_threads = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _Handler)
        self.port = self.server_address[1]
        self.cond = threading.Condition()
        self.data = {}  # key -> value
        self.index = 1  # global modify index
        self.fail_next_poll = False

    def put(self, key: str, value: str):
        with self.cond:
            self.index += 1
            self.data[key] = value
            self.cond.notify_all()

    def delete(self, key: str):
        with self.cond:
            self.index += 1
            self.data.pop(key, None)
            self.cond.notify_all()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def handle(self):
        try:
            super().handle()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client killed a held poll (close()) — expected

    def _reply(self, code: int, body: bytes, index: int):
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("X-Consul-Index", str(index))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv: FakeConsul = self.server
        parsed = urlparse(self.path)
        if not parsed.path.startswith("/v1/kv/"):
            self.send_error(404)
            return
        key = parsed.path[len("/v1/kv/"):]
        q = parse_qs(parsed.query)
        want_index = int(q.get("index", ["0"])[0])
        wait_s = float(q.get("wait", ["0s"])[0].rstrip("s") or 0)
        deadline = time.time() + min(wait_s, 2.0)  # capped for tests
        with srv.cond:
            if srv.fail_next_poll and want_index:
                srv.fail_next_poll = False
                self.send_error(500)
                return
            # Blocking query: hold until index passes or wait expires.
            while want_index and srv.index <= want_index:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                srv.cond.wait(remaining)
            idx = srv.index
            value = srv.data.get(key)
        if value is None:
            self._reply(404, b"", idx)
            return
        body = json.dumps(
            [{
                "Key": key,
                "Value": base64.b64encode(value.encode()).decode(),
                "ModifyIndex": idx,
            }]
        ).encode()
        self._reply(200, body, idx)

    def do_PUT(self):
        srv: FakeConsul = self.server
        key = urlparse(self.path).path[len("/v1/kv/"):]
        n = int(self.headers.get("Content-Length", 0))
        srv.put(key, self.rfile.read(n).decode())
        self._reply(200, b"true", srv.index)


def _rules_json(count):
    return json.dumps([{"resource": "res", "count": count}])


@pytest.fixture()
def fake_consul():
    srv = FakeConsul()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _wait(predicate, timeout=5.0):
    end = time.time() + timeout
    while time.time() < end:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _src(fake_consul, **kw):
    kw.setdefault("reconnect_interval_sec", 0.05)
    kw.setdefault("wait_sec", 1.0)
    return ConsulDataSource(
        json_converter(st.FlowRule), "sentinel/rules",
        endpoint=f"http://127.0.0.1:{fake_consul.port}", **kw,
    )


class TestConsulDataSource:
    def test_initial_load_and_blocking_query_push(
        self, fake_consul, manual_clock, engine
    ):
        """KV get seeds the rules; a put releases the blocking query
        and live-swaps the engine table."""
        fake_consul.put("sentinel/rules", _rules_json(1))
        src = _src(fake_consul).start()
        try:
            st.flow_rule_manager.register_property(src.get_property())
            manual_clock.set_ms(100)
            assert st.try_entry("res") is not None
            assert st.try_entry("res") is None  # count=1 enforced

            fake_consul.put("sentinel/rules", _rules_json(5))
            assert _wait(
                lambda: any(
                    r.count == 5 for r in (st.flow_rule_manager.get_rules() or [])
                )
            ), "blocking-query push never reached the manager"
            manual_clock.set_ms(2000)
            admitted = sum(1 for _ in range(8) if st.try_entry("res") is not None)
            assert admitted == 5
        finally:
            src.close()

    def test_write_round_trips(self, fake_consul):
        src = _src(fake_consul)
        src.write(_rules_json(9))
        rules = src.load_config()
        assert len(rules) == 1 and rules[0].count == 9
        src.close()

    def test_missing_key_reads_none(self, fake_consul):
        src = _src(fake_consul)
        assert src.read_source() is None
        src.close()

    def test_delete_pushes_none(self, fake_consul):
        fake_consul.put("sentinel/rules", _rules_json(2))
        src = _src(fake_consul).start()
        try:
            assert _wait(lambda: src.get_property()._value)
            fake_consul.delete("sentinel/rules")
            assert _wait(lambda: not src.get_property()._value), (
                "delete never propagated"
            )
        finally:
            src.close()

    def test_outage_recovers_and_catches_up(self, fake_consul):
        fake_consul.put("sentinel/rules", _rules_json(1))
        src = _src(fake_consul).start()
        try:
            assert _wait(lambda: src.get_property()._value)
            fake_consul.fail_next_poll = True
            fake_consul.put("sentinel/rules", _rules_json(7))
            assert _wait(
                lambda: any(r.count == 7 for r in (src.get_property()._value or []))
            ), "update during outage was lost"
        finally:
            src.close()

    def test_close_unblocks_inflight_poll_promptly(self, fake_consul):
        """The blocking query's connection is published BEFORE the
        response blocks, so close() can kill it mid-hold instead of
        waiting out the server's window."""
        fake_consul.put("sentinel/rules", _rules_json(1))
        src = _src(fake_consul, wait_sec=30.0).start()
        try:
            assert _wait(lambda: src._poll_conn is not None), "poll never started"
        finally:
            t0 = time.time()
            src.close()
            assert time.time() - t0 < 1.5, "close blocked on the long poll"
        assert not src._thread.is_alive()

    def test_oversized_body_rejected(self, fake_consul, monkeypatch):
        import sentinel_tpu.datasource.consul_source as mod

        monkeypatch.setattr(mod, "MAX_BODY_BYTES", 64)
        fake_consul.put("sentinel/rules", "x" * 200)
        src = _src(fake_consul)
        with pytest.raises(ValueError, match="size cap"):
            src.read_source()
        src.close()
