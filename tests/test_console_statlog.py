"""Dashboard web console + cluster server stat log."""

import urllib.request

import pytest

from sentinel_tpu.cluster import (
    DefaultTokenService,
    cluster_flow_rule_manager,
    stat_log,
)
from sentinel_tpu.dashboard import DashboardServer
from sentinel_tpu.metrics.block_log import BlockLogger
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule
from sentinel_tpu.utils.clock import ManualClock


class TestWebConsole:
    def test_root_serves_console(self):
        srv = DashboardServer(port=0).start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/", timeout=5) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/html")
                body = r.read().decode()
            assert "Sentinel" in body and "Real-time metrics" in body
            assert "/metric?app=" in body  # wired to the JSON API
            # The JSON API remains reachable alongside the UI.
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/apps", timeout=5
            ) as r:
                assert r.headers["Content-Type"].startswith("application/json")
        finally:
            srv.stop()


class TestClusterStatLog:
    @pytest.fixture(autouse=True)
    def _sink(self, tmp_path):
        clock = ManualClock(0)
        logger = BlockLogger(base_dir=str(tmp_path), file_name="sentinel-cluster.log",
                             clock=clock)
        stat_log.set_logger(logger)
        cluster_flow_rule_manager.clear()
        yield logger
        stat_log.set_logger(None)
        cluster_flow_rule_manager.clear()

    def test_flow_decisions_logged(self, _sink):
        rule = FlowRule("r", count=1, cluster_mode=True,
                        cluster_config=ClusterFlowConfig(
                            flow_id=42, threshold_type=C.FLOW_THRESHOLD_GLOBAL))
        cluster_flow_rule_manager.load_rules("default", [rule])
        svc = DefaultTokenService(clock=ManualClock(0))
        assert svc.request_token(42).ok
        assert not svc.request_token(42).ok
        _sink.flush()
        entries = {k: c for _, k, c in _sink.read_entries()}
        assert entries[("flow", "pass", "42")] == 1
        assert entries[("flow", "block", "42")] == 1

    def test_concurrent_decisions_logged(self, _sink):
        rule = FlowRule("c", count=1, grade=C.FLOW_GRADE_THREAD, cluster_mode=True,
                        cluster_config=ClusterFlowConfig(
                            flow_id=77, threshold_type=C.FLOW_THRESHOLD_GLOBAL))
        cluster_flow_rule_manager.load_rules("default", [rule])
        svc = DefaultTokenService(clock=ManualClock(0))
        r = svc.request_concurrent_token(77)
        assert r.ok
        assert not svc.request_concurrent_token(77).ok
        svc.release_concurrent_token(r.token_id)
        _sink.flush()
        entries = {k: c for _, k, c in _sink.read_entries()}
        assert entries[("concurrent", "pass", "77")] == 1
        assert entries[("concurrent", "block", "77")] == 1
        assert entries[("concurrent", "release", "77")] == 1
