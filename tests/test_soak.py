"""Soak: concurrent submitters + auto-flusher + rule reloads + mesh
toggles, sustained for SENTINEL_SOAK_SEC (default 90s) of wall time.

Round-3 verdict #8: the auto-flusher/lock redesigns are exactly where
a rare interleaving bug would hide. The invariants checked are the
strong ones a race would break:

* liveness — no thread dies, every submitted op gets a verdict;
* accounting — for every resource, the engine's own window tensors
  agree exactly with the tally of verdicts handed back to callers
  (lost/double-counted rows under lock handoffs would skew one side);
* conservation — an unlimited resource admits everything submitted;
* memory — RSS stops growing once warm (no leak per flush).

The clock is a ManualClock advanced by a dedicated thread, so the
whole soak stays inside one minute window and the accounting check is
exact equality, not a rate estimate. Reference analog: the reference's
concurrency safety is by construction (CAS/LongAdder); this is the
empirical equivalent for the batched engine.
"""

import os
import threading
import time

import numpy as np
import pytest

import sentinel_tpu as st

pytestmark = pytest.mark.slow

SOAK_SEC = float(os.environ.get("SENTINEL_SOAK_SEC", "90"))


def _rss_mb() -> float:
    with open("/proc/self/statm") as f:
        pages = int(f.read().split()[1])
    return pages * os.sysconf("SC_PAGE_SIZE") / 1e6


def test_soak_concurrent_engine(manual_clock, engine):
    rules = [
        st.FlowRule("unlimited", count=1e9),
        st.FlowRule("limited", count=40),
        st.FlowRule("threads", grade=0, count=64),
    ]
    engine.set_flow_rules(rules)
    engine.start_auto_flush(interval_ms=2)
    manual_clock.set_ms(1000)

    stop = threading.Event()
    errors = []
    lock = threading.Lock()
    tallies = {"unlimited": 0, "limited": 0, "threads": 0}
    submitted = {"unlimited": 0, "limited": 0, "threads": 0}
    undecided = []

    def submitter(i):
        rng = np.random.default_rng(i)
        try:
            while not stop.is_set():
                res = ("unlimited", "limited", "threads")[int(rng.integers(0, 3))]
                if rng.random() < 0.5:
                    n = int(rng.integers(8, 64))
                    g = engine.submit_bulk(res, n)
                    t0 = time.time()
                    while g.admitted is None and time.time() - t0 < 10:
                        time.sleep(0.001)
                    if g.admitted is None:
                        # A mesh toggle's recompile can stall the
                        # auto-flusher well past 10s on small hosts; a
                        # synchronous flush settles it (and would hang
                        # here on a real deadlock, failing the join
                        # check below).
                        engine.flush()
                    if g.admitted is None:
                        undecided.append((res, "bulk"))
                        continue
                    adm = int(g.admitted_count)
                    with lock:
                        submitted[res] += n
                        tallies[res] += adm
                    if res == "threads" and adm:
                        engine.submit_exit_bulk(
                            g.rows, adm, rt=3, resource=res
                        )
                else:
                    ops = engine.submit_many(
                        [{"resource": res} for _ in range(int(rng.integers(1, 12)))]
                    )
                    engine.flush()
                    n_adm = 0
                    for op in ops:
                        if op.verdict is None:
                            undecided.append((res, "single"))
                        elif op.verdict.admitted:
                            n_adm += 1
                            if res == "threads":
                                engine.submit_exit(op.rows, rt=3, resource=res)
                    with lock:
                        submitted[res] += len(ops)
                        tallies[res] += n_adm
        except Exception as e:  # pragma: no cover - the failure path
            errors.append(e)

    def clock_advancer():
        # ~55s of virtual time over the whole soak — stays inside the
        # minute window so minute-window totals hold every event.
        try:
            step_ms = max(1, int(55_000 * 0.05 / max(SOAK_SEC, 1)))
            while not stop.is_set():
                time.sleep(0.05)
                manual_clock.advance(step_ms)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t_start = time.time()

    def churner():
        # Rule reloads while traffic flows; mesh toggles confined to
        # the first half — every enable_mesh builds fresh shard_map
        # closures whose pjit compiles legitimately grow the executable
        # cache, and the steady-state RSS check below must measure
        # flushing, not compiles. Toggles are capability-gated: without
        # jax.shard_map the soak still exercises everything else.
        from sentinel_tpu.parallel import mesh_unavailable_reason

        mesh_ok = mesh_unavailable_reason(8) is None
        try:
            toggles = 0
            while not stop.is_set():
                time.sleep(max(SOAK_SEC / 12, 1.0))
                engine.set_flow_rules(rules)
                if (
                    mesh_ok
                    and toggles < 2
                    and SOAK_SEC >= 60
                    and time.time() - t_start < SOAK_SEC * 0.4
                ):
                    engine.enable_mesh(8)
                    time.sleep(max(SOAK_SEC / 12, 1.0))
                    engine.disable_mesh()
                    toggles += 1
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=submitter, args=(i,)) for i in range(4)]
    threads += [threading.Thread(target=clock_advancer), threading.Thread(target=churner)]
    for t in threads:
        t.start()

    time.sleep(SOAK_SEC * 0.7)  # past the toggle window + its compiles
    rss_warm = _rss_mb()
    time.sleep(SOAK_SEC * 0.3)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "a soak thread deadlocked"
    engine.flush()
    engine.stop_auto_flush()
    rss_end = _rss_mb()

    assert not errors, errors
    assert not undecided, f"{len(undecided)} ops never decided: {undecided[:5]}"
    # Scale with duration: early iterations are compile-dominated on
    # small hosts (every fresh batch-size bucket jits once).
    assert sum(submitted.values()) > 8 * SOAK_SEC, "soak produced too little traffic"

    # Unlimited resource: everything admitted.
    assert tallies["unlimited"] == submitted["unlimited"]

    # The engine's own windows agree with the verdicts we were handed.
    for res in tallies:
        stats = engine.cluster_node_stats(res, flush=False)
        total = stats["total_pass_minute"]
        assert total == tallies[res], (
            f"{res}: window says {total}, verdict tally {tallies[res]}"
        )

    # No leak once warm: flushes must not accrete host memory.
    assert rss_end - rss_warm < 300, (
        f"RSS grew {rss_end - rss_warm:.0f} MB after warmup"
    )
