"""Quantify the batching conservatism (round-3 weak #5).

The suite's one-sided deviations say the engine may over-BLOCK
relative to the sequential reference, never over-admit. This test
measures the over-block *rate* under a realistic mixed workload —
multi-origin traffic on origin-split rules plus RELATE pairs, batched
into production-size flushes — against a sequential reference engine
(one flush per op; pinned exact vs the oracle by
tests/test_differential.py), and asserts the rate stays under 5%. A
conservatism bound users can feel is a bug with better marketing; this
pins it as a number.

Round-4 state of the deviations exercised here:
* origin-split mesh budgets — EXACT (row-keyed _split_and_spend);
  contributes zero.
* RELATE intra-batch over-charge — REMOVED (own-row charge gate in
  flow_admission); with ruled ref resources (as here) RELATE streams
  are exact, so the measured rate should be ~0. The <5% bound stays as
  the product promise this test enforces against regressions.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.runtime.engine import Engine


def _rules():
    return [
        # Plain QPS traffic, moderate headroom.
        st.FlowRule("r0", count=30),
        st.FlowRule("r1", count=24),
        st.FlowRule("r2", count=40),
        st.FlowRule("r3", count=18),
        # RELATE pairs: A guarded by B's QPS.
        st.FlowRule("A1", count=20, strategy=C.STRATEGY_RELATE, ref_resource="B1"),
        st.FlowRule("A2", count=15, strategy=C.STRATEGY_RELATE, ref_resource="B2"),
        st.FlowRule("B1", count=25),
        st.FlowRule("B2", count=20),
        # Origin-split (per-origin budget rows).
        st.FlowRule("os", count=25, limit_app=C.LIMIT_APP_OTHER),
    ]


_WEIGHTS = [
    ("r0", 3), ("r1", 2), ("r2", 3), ("r3", 2),
    ("A1", 3), ("A2", 2), ("B1", 2), ("B2", 2),
    ("os", 5),
]


def _run_workload(batched: Engine, clock, rng, steps: int, flush_mean: int):
    """Drive the same random op stream through ``batched`` (one flush
    per step) and a fresh sequential reference engine (one flush per
    op). Returns (admits_batched, admits_oracle, checked) per
    resource."""
    seq = Engine(clock=clock)
    seq.set_flow_rules(_rules())

    pool = [r for r, w in _WEIGHTS for _ in range(w)]
    origins = ["o1", "o2", "o3"]
    adm_b: dict = {}
    adm_o: dict = {}
    checked: dict = {}
    t = 1000
    for _ in range(steps):
        t += int(rng.integers(40, 180))
        clock.set_ms(t)
        n_ops = max(1, int(rng.poisson(flush_mean)))
        reqs = []
        for _ in range(n_ops):
            res = pool[int(rng.integers(0, len(pool)))]
            req = {"resource": res, "ts": t}
            if res == "os":
                req["origin"] = origins[int(rng.integers(0, len(origins)))]
            reqs.append(req)
        ops_b = batched.submit_many([dict(r) for r in reqs])
        batched.flush()
        for req, op in zip(reqs, ops_b):
            res = req["resource"]
            checked[res] = checked.get(res, 0) + 1
            adm_b[res] = adm_b.get(res, 0) + int(op.verdict.admitted)
        for req in reqs:
            op = seq.submit_entry(**req)
            seq.flush()
            res = req["resource"]
            adm_o[res] = adm_o.get(res, 0) + int(op.verdict.admitted)
    return adm_b, adm_o, checked


def _assert_rate(adm_b, adm_o, checked, ctx: str):
    tot_b, tot_o = sum(adm_b.values()), sum(adm_o.values())
    # One-sided: batching never admits more in aggregate.
    assert tot_b <= tot_o, f"{ctx}: batched admitted MORE than sequential"
    rate = (tot_o - tot_b) / max(tot_o, 1)
    per_res = {
        r: round((adm_o[r] - adm_b.get(r, 0)) / max(adm_o[r], 1), 4)
        for r in sorted(adm_o)
    }
    print(f"\n[{ctx}] over-block rate: {rate:.4f} "
          f"({tot_o - tot_b}/{tot_o} over {sum(checked.values())} checks); "
          f"per-resource: {per_res}")
    assert rate < 0.05, f"{ctx}: over-block rate {rate:.4f} >= 5%"
    return rate


def test_overblock_rate_single_chip(manual_clock, engine):
    engine.set_flow_rules(_rules())
    rng = np.random.default_rng(42)
    adm_b, adm_o, checked = _run_workload(engine, manual_clock, rng, 60, 24)
    _assert_rate(adm_b, adm_o, checked, "single-chip")


@pytest.mark.mesh
def test_overblock_rate_mesh(manual_clock, engine):
    """The mesh engine vs the sequential single-chip reference: the
    sharded budget split must not add measurable conservatism on top of
    the intra-batch math (origin-split is exact since round 4)."""
    engine.enable_mesh(8)
    engine.set_flow_rules(_rules())
    rng = np.random.default_rng(43)
    adm_b, adm_o, checked = _run_workload(engine, manual_clock, rng, 30, 24)
    _assert_rate(adm_b, adm_o, checked, "mesh")
