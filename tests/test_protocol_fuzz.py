"""Wire-protocol robustness: the cluster token server must survive
malformed, truncated, oversized, and random frames — the reference's
Netty pipeline drops bad frames at the LengthFieldBasedFrameDecoder and
keeps serving (NettyTransportServer.java:78-93); ours must not crash,
leak the connection gauge, or stop answering well-formed requests.
"""

import socket
import struct
import time

import numpy as np
import pytest

from sentinel_tpu.cluster import protocol
from sentinel_tpu.cluster.flow_rules import cluster_flow_rule_manager
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.cluster.token_service import (
    DefaultTokenService,
    cluster_server_config_manager,
)
from sentinel_tpu.models import constants as C
from sentinel_tpu.utils.clock import ManualClock


@pytest.fixture()
def server():
    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=30000.0
    )
    srv = SentinelTokenServer(port=0, service=DefaultTokenService(clock=ManualClock(0)))
    srv.start()
    yield srv
    srv.stop()
    cluster_flow_rule_manager.clear()


def _frame(payload: bytes) -> bytes:
    return struct.pack("<I", len(payload)) + payload


def _send_raw(port: int, data: bytes) -> None:
    with socket.create_connection(("127.0.0.1", port), timeout=2) as s:
        s.sendall(data)
        s.settimeout(0.5)
        try:
            while s.recv(4096):
                pass
        except (socket.timeout, ConnectionError):
            pass


def _ping_ok(port: int) -> bool:
    with socket.create_connection(("127.0.0.1", port), timeout=2) as s:
        s.sendall(protocol.pack_ping(1))
        payload = protocol.read_frame(s)
        if payload is None:
            return False
        xid, _, status, _, _, _ = protocol.unpack_response(payload)
        return xid == 1 and status == int(C.TokenResultStatus.OK)


class TestProtocolFuzz:
    def test_server_survives_garbage(self, server, capfd):
        """Every malformed shape is dropped GRACEFULLY: the server keeps
        answering and no handler thread dies with a traceback (a
        swallowed per-connection crash would keep serving too, but
        that's not the graceful-drop contract)."""
        rng = np.random.default_rng(0)
        port = server.port
        blobs = [
            b"",  # connect + close
            b"\x00",  # truncated length prefix
            struct.pack("<I", 2**30),  # oversized frame length
            struct.pack("<I", 100),  # length promising bytes that never come
            bytes(rng.integers(0, 256, 64, dtype=np.uint8)),
            bytes(rng.integers(0, 256, 4096, dtype=np.uint8)),
            _frame(b""),  # empty payload
            _frame(b"\x01"),  # payload shorter than any header
            _frame(bytes(rng.integers(0, 256, 32, dtype=np.uint8))),
            # Well-framed PARAM_FLOW whose param length field promises
            # 100 bytes but only 3 follow — must be dropped as a bad
            # frame, not rate-limit the truncated value.
            _frame(
                struct.pack("<IB", 5, C.MSG_TYPE_PARAM_FLOW)
                + struct.pack("<qiB", 1, 1, 0)
                + struct.pack("<H", 1)
                + struct.pack("<H", 100)
                + b"abc"
            ),
        ]
        for blob in blobs:
            _send_raw(port, blob)
            assert _ping_ok(port), f"server stopped answering after {blob[:16]!r}"
        err = capfd.readouterr().err
        assert "Traceback" not in err, err

    def test_unknown_message_type(self, server, capfd):
        """A well-framed request of an unknown type gets BAD_REQUEST
        through the channel and the connection stays usable — like the
        reference answering through TokenServerHandler rather than
        killing the socket."""
        port = server.port
        with socket.create_connection(("127.0.0.1", port), timeout=2) as s:
            s.sendall(_frame(struct.pack("<IB", 7, 99)))
            s.settimeout(2.0)
            payload = protocol.read_frame(s)
            assert payload is not None
            xid, _, status, _, _, _ = protocol.unpack_response(payload)
            assert xid == 7
            assert status == int(C.TokenResultStatus.BAD_REQUEST)
            # Same connection still serves well-formed requests.
            s.sendall(protocol.pack_ping(8))
            payload = protocol.read_frame(s)
            assert payload is not None and protocol.unpack_response(payload)[0] == 8
        err = capfd.readouterr().err
        assert "Traceback" not in err, err

    def test_connection_gauge_not_leaked(self, server):
        port = server.port
        before = server._conn_count
        for _ in range(5):
            _send_raw(port, struct.pack("<I", 2**30))
        deadline = time.monotonic() + 3
        while server._conn_count > before and time.monotonic() < deadline:
            time.sleep(0.05)
        assert server._conn_count == before
