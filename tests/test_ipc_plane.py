"""Multi-process ingest plane (sentinel_tpu/ipc).

The acceptance surface: worker-path verdicts are bit-identical to the
in-process ``submit_bulk`` oracle at pipeline depths {0, 2} (flow +
param + speculative on/off); per-request W3C traceparent identity
survives the process boundary; ring-full is a bounded local
``BLOCK_SHED`` (cause ``ipc_ring``) that still lands in the engine's
valve accounting; worker-kill chaos leaves device AND mirror THREAD
gauges exactly 0 after quiesce; engine death serves workers from the
policy snapshot; disabled is parity (no plane, no shared memory).

Real-process tests carry the ``mp`` marker — conftest arms a SIGALRM
watchdog so a hung worker can never wedge tier-1 — and terminate their
children in ``finally`` blocks.
"""

from __future__ import annotations

import os
import signal
import time

import numpy as np
import pytest

from sentinel_tpu.core import errors as E
from sentinel_tpu.ipc import frames as fr
from sentinel_tpu.ipc.plane import IngestPlane
from sentinel_tpu.ipc.ring import ControlBlock, ShmRing
from sentinel_tpu.ipc.worker import IngestClient
from sentinel_tpu.models.rules import FlowRule, ParamFlowRule
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.utils.config import config

import ipc_procs


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _engine(manual_clock=None, **cfg) -> Engine:
    for k, v in cfg.items():
        config.set(k, v)
    return Engine(clock=manual_clock, initial_rows=256)


def _rules(eng: Engine) -> None:
    eng.set_flow_rules([FlowRule(resource="flow-res", count=3)])
    eng.set_param_rules(
        {"param-res": [ParamFlowRule(resource="param-res", param_idx=0,
                                     count=2)]}
    )


def _wait_for(pred, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# transport units
# ---------------------------------------------------------------------------
class TestRing:
    def test_roundtrip_and_wraparound(self):
        ring = ShmRing(None, 4, 128, create=True)
        try:
            for lap in range(5):  # > slots: exercises seq recycling
                for i in range(3):
                    assert ring.try_push(f"p{lap}-{i}".encode())
                got = [p.decode() for p in ring.pop_all()]
                assert got == [f"p{lap}-{i}" for i in range(3)]
        finally:
            ring.destroy()

    def test_full_returns_false_and_occupancy(self):
        ring = ShmRing(None, 4, 64, create=True)
        try:
            for i in range(4):
                assert ring.try_push(b"x")
            assert not ring.try_push(b"overflow")
            assert ring.occupancy() == 1.0
            assert ring.pop_all()
            assert ring.try_push(b"again")
        finally:
            ring.destroy()

    def test_oversized_payload_refused(self):
        ring = ShmRing(None, 2, 16, create=True)
        try:
            assert not ring.try_push(b"x" * 17)
        finally:
            ring.destroy()

    def test_skip_stalled_claims(self):
        """A claimed-but-never-published slot (producer died mid-write)
        is stepped over after the stall age; published frames behind it
        survive."""
        ring = ShmRing(None, 4, 64, create=True)
        try:
            # Simulate a dead producer: claim advances head, no publish.
            pos = ring._claim()
            assert pos is not None
            assert ring.try_push(b"alive")
            assert ring.try_pop() is None  # blocked behind the corpse
            assert not ring.maybe_skip_stalled(0.05)  # first observation
            time.sleep(0.08)
            assert ring.maybe_skip_stalled(0.05)
            assert ring.try_pop() == b"alive"
        finally:
            ring.destroy()

    def test_control_block_policy_seqlock(self):
        ctrl = ControlBlock(None, 4, create=True)
        try:
            assert ctrl.read_policy() == ("open", {})  # never published
            assert ctrl.publish_policy("closed", {"a": "open"})
            assert ctrl.read_policy() == ("closed", {"a": "open"})
            # Oversized override sets drop largest-name-last, default kept.
            big = {f"r{'x' * i}": "closed" for i in range(200)}
            assert not ctrl.publish_policy("open", big)
            default, overrides = ctrl.read_policy()
            assert default == "open" and len(overrides) < len(big)
        finally:
            ctrl.destroy()


class TestFrames:
    def test_args_codec_roundtrip(self):
        cases = [
            (), (None,), (True, False), (42, -(1 << 40)), (3.5,),
            ("ip-1", ""), (b"\x00\xff",), (("a", 1, None), "tail"),
            ("unicode-☃",),
        ]
        for args in cases:
            assert fr.decode_args(fr.encode_args(args)) == args

    def test_entry_frame_roundtrip(self):
        rows = [
            fr.EntryRow(
                seq=100 + i, resource_id=1, context_id=2, origin_id=3,
                entry_type=1, acquire=i + 1, ts=5000 + i,
                trace=fr.pack_trace("ab" * 16, "cd" * 8, True),
                args=fr.encode_args((f"v{i}",)),
            )
            for i in range(4)
        ]
        payload = fr.encode_entries(
            3, rows, [(1, b"res"), (2, b"ctx")], intern_gen=7, shed_count=9
        )
        f = fr.decode_frame(payload)
        assert f.kind == fr.KIND_ENTRY and f.worker_id == 3 and f.n == 4
        assert f.intern_gen == 7 and f.shed_count == 9
        assert f.interns == [(1, b"res"), (2, b"ctx")]
        assert f.columns["ts"].tolist() == [5000, 5001, 5002, 5003]
        assert f.columns["acquire"].tolist() == [1, 2, 3, 4]
        tid, sid, sampled = fr.unpack_trace(f.traces[0:26])
        assert (tid, sid, sampled) == ("ab" * 16, "cd" * 8, True)
        for i in range(4):
            lo = int(f.columns["args_off"][i])
            ln = int(f.columns["args_len"][i])
            assert fr.decode_args(f.varbytes[lo : lo + ln]) == (f"v{i}",)

    def test_exit_and_verdict_frames(self):
        rows = [fr.ExitRow(1, 4, 0, 0, 0, 777, 12, 2, 1, 1)]
        f = fr.decode_frame(fr.encode_exits(2, rows, [], 1, 0))
        assert f.kind == fr.KIND_EXIT and f.n == 1
        assert f.columns["rt"].tolist() == [12]
        assert f.columns["spec"].tolist() == [1]
        v = fr.decode_frame(
            fr.encode_verdicts(
                2, np.array([9], np.uint64), np.array([1], np.uint8),
                np.array([0], np.int16), np.array([3], np.int32),
                np.array([fr.F_SPECULATIVE], np.uint8),
            )
        )
        assert v.kind == fr.KIND_VERDICT
        assert v.columns["seq"].tolist() == [9]
        assert v.columns["wait_ms"].tolist() == [3]

    def test_untraced_row_packs_empty(self):
        assert fr.unpack_trace(fr.EMPTY_TRACE) is None
        assert fr.unpack_trace(fr.pack_trace("zz", "bad", True)) is None


# ---------------------------------------------------------------------------
# differential parity vs the in-process submit_bulk oracle
# ---------------------------------------------------------------------------
def _oracle_decide(eng: Engine, res, n, ts_list, args_list):
    """EXACTLY the plane's group semantics, in-process: one columnar
    submit_bulk (per-request fallback on ValueError), speculative
    verdicts answered without waiting for settle, else a flush."""
    ts_col = np.asarray(ts_list, dtype=np.int32)
    args_col = None
    if any(args_list):
        args_col = list(args_list)
    try:
        op = eng.submit_bulk(res, n, ts=ts_col, args_column=args_col)
        if op is None:
            return [(True, E.PASS, 0)] * n
        if op.spec_admitted is not None:
            eng._spec_maybe_settle()
        else:
            eng.flush()
        return list(
            zip(
                op.admitted.tolist(), op.reason.tolist(),
                op.wait_ms.tolist(),
            )
        )
    except ValueError:
        ops = [
            eng.submit_entry(res, ts=ts_list[i], args=args_list[i])
            for i in range(n)
        ]
        eng.flush()
        return [
            (op.verdict.admitted, op.verdict.reason, op.verdict.wait_ms)
            for op in ops
        ]


def _stream():
    """The scripted request stream: flow-rule singles, param values
    (incl. repeats that must block at count=2), and bulk groups —
    explicit ts so both sides are deterministic."""
    reqs = []
    for i in range(6):
        reqs.append(("entry", "flow-res", 1000, ()))
    for i in range(7):
        reqs.append(("entry", "param-res", 1000, (f"ip{i % 2}",)))
    reqs.append(("bulk", "flow-res", 2200, 5))
    reqs.append(("bulk", "unknown-res", 2200, 3))
    return reqs


class TestPlaneParity:
    """Worker-path verdicts bit-identical to the in-process oracle.
    The client here lives in-process — the ENTIRE frame/ring/plane
    path still runs (shared memory is process-agnostic); the process
    boundary itself is covered by the mp-marked spot check below."""

    @pytest.mark.parametrize("depth", [0, 2])
    @pytest.mark.parametrize("spec", [False, True])
    def test_bit_identical(self, manual_clock, depth, spec):
        config.set(config.PIPELINE_DEPTH, str(depth))
        config.set(config.SPECULATIVE_ENABLED, "true" if spec else "false")
        manual_clock.set_ms(1000)
        oracle = _engine(manual_clock)
        _rules(oracle)
        eng = _engine(manual_clock)
        _rules(eng)
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            want = []
            got = []
            for req in _stream():
                if req[0] == "entry":
                    _, res, ts, args = req
                    want.extend(_oracle_decide(oracle, res, 1, [ts], [args]))
                    v = cli.entry(res, ts=ts, args=args, timeout_ms=30000)
                    got.append((v.admitted, v.reason, v.wait_ms))
                else:
                    _, res, ts, n = req
                    want.extend(
                        _oracle_decide(oracle, res, n, [ts] * n, [()] * n)
                    )
                    a, r, w, _f = cli.bulk(res, n, ts=ts, timeout_ms=30000)
                    got.extend(zip(a.tolist(), r.tolist(), w.tolist()))
            assert got == want, f"depth={depth} spec={spec}"
            oracle.flush()
            oracle.drain()
            eng.flush()
            eng.drain()
        finally:
            cli.close()
            plane.close()
            eng.close()
            oracle.close()

    def test_speculative_flag_carried(self, manual_clock):
        config.set(config.SPECULATIVE_ENABLED, "true")
        manual_clock.set_ms(1000)
        eng = _engine(manual_clock)
        _rules(eng)
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            v = cli.entry("flow-res", ts=1000, timeout_ms=30000)
            assert v.admitted and v.speculative and not v.degraded
        finally:
            cli.close()
            plane.close()
            eng.close()


# ---------------------------------------------------------------------------
# ring-full shed bounds + valve accounting
# ---------------------------------------------------------------------------
class TestRingFullShed:
    def test_shed_bounded_and_valve_accounted(self, manual_clock):
        config.set(config.IPC_RING_SLOTS, "2")
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="flow-res", count=1e9)])
        plane = IngestPlane(eng, start=False)  # beats only when started
        plane._publish_control(force=True)  # engine reads alive
        cli = IngestClient(plane.channel(0), 0)
        try:
            # Fill the 2-slot ring: nobody drains, each wait times out
            # into the policy path (NOT a shed — the frame is queued).
            for _ in range(2):
                v = cli.entry("flow-res", ts=1000, timeout_ms=50)
                assert v.degraded  # policy-served wait timeout
            assert plane.request.occupancy() == 1.0
            # The bound: every further submit is a FAST local shed with
            # the distinct cause, and the ring never grows.
            for _ in range(5):
                v = cli.entry("flow-res", ts=1000, timeout_ms=50)
                assert not v.admitted
                assert v.reason == E.BLOCK_SHED
                assert v.limit_type == "ipc_ring"
            assert cli.counters["sheds"] == 5
            assert plane.request.occupancy() == 1.0
            # Start the plane: queued frames drain, and the workers'
            # cumulative shed counts fold into the engine's valve
            # accounting (cause "ring") via the control header.
            plane.start()
            _wait_for(
                lambda: eng.ingest.counters["shed_ring"] >= 5,
                what="shed_ring fold",
            )
            assert eng.ingest.counters["shed_entries"] >= 5
            assert plane.snapshot()["counters"]["worker_sheds"] >= 5
            assert eng.telemetry.counters_snapshot()["ipc_sheds"] >= 5
        finally:
            cli.close()
            plane.close()
            eng.close()


# ---------------------------------------------------------------------------
# engine death -> policy snapshot; disabled parity; intern protocol
# ---------------------------------------------------------------------------
class TestEngineDeathPolicy:
    def test_closed_plane_serves_policy(self, manual_clock):
        config.set(config.FAILOVER_POLICY, "open,shut-res=closed")
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="flow-res", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            v = cli.entry("flow-res", ts=1000, timeout_ms=30000)
            assert v.admitted and not v.degraded
            plane.close()
            v = cli.entry("flow-res", ts=1000)
            assert v.admitted and v.degraded and v.reason == E.PASS
            v = cli.entry("shut-res", ts=1000)
            assert not v.admitted and v.degraded
            assert v.reason == E.BLOCK_FAILOVER
            a, r, _w, f = cli.bulk("shut-res", 3)
            assert not a.any()
            assert r.tolist() == [E.BLOCK_FAILOVER] * 3
            assert all(fl & fr.F_DEGRADED for fl in f.tolist())
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_disabled_is_parity(self, manual_clock):
        eng = _engine(manual_clock)
        try:
            assert eng.ipc_plane is None  # default off: no plane, no shm
        finally:
            eng.close()

    def test_config_enabled_autostarts(self):
        config.set(config.IPC_ENABLED, "true")
        eng = _engine()
        try:
            assert eng.ipc_plane is not None
            assert eng.ipc_plane.snapshot()["enabled"]
        finally:
            eng.close()
            # BEFORE any api.reset teardown can construct the next
            # global engine: a lingering "true" would auto-start (and
            # leak) a plane on it.
            config.set(config.IPC_ENABLED, "false")
        assert eng.ipc_plane is None  # close() tears the plane down


class TestLedgerPairing:
    def test_spec_off_exit_clears_ledger_no_reap_double_release(
        self, manual_clock
    ):
        """Regression (review): with the speculative tier OFF the
        admit-time ledger key carries spec=False while a worker's
        default exit reads as mirror-release True — the decrement must
        still pair them, or the dead-worker reap double-releases and
        drives the gauge negative."""
        eng = _engine(manual_clock)  # speculative defaults OFF
        eng.set_flow_rules([FlowRule(resource="pair-res", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            for _ in range(3):
                assert cli.entry(
                    "pair-res", ts=1000, timeout_ms=30000
                ).admitted
            for _ in range(3):
                assert cli.exit("pair-res")  # default speculative=None
            _wait_for(
                lambda: plane.snapshot()["counters"]["exits"] >= 3,
                what="exits drained",
            )
            with plane._lock:
                assert not plane._workers[0].live, "ledger must be empty"
            # A reap now must release NOTHING.
            plane._reap_worker(0, plane._workers[0])
            assert plane.snapshot()["counters"]["auto_exits"] == 0
            eng.flush()
            eng.drain()
            stats = eng.cluster_node_stats("pair-res")
            assert stats["cur_thread_num"] == 0, stats
        finally:
            cli.close()
            plane.close()
            eng.close()


class TestInternProtocol:
    def test_string_crosses_once_and_gen_bump_reinterns(self, manual_clock):
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="flow-res", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            for _ in range(3):
                assert cli.entry(
                    "flow-res", ts=1000, timeout_ms=30000
                ).admitted
            with cli._lock:
                interned = dict(cli._intern)
            assert "flow-res" in interned
            snap = plane.snapshot()
            assert snap["workers"][0]["interned"] >= 1
            # Generation bump (plane restart surrogate): the client's
            # table invalidates and the next frame re-interns.
            plane.control.bump_intern_gen()
            assert cli.entry("flow-res", ts=1000, timeout_ms=30000).admitted
            with cli._lock:
                assert cli._intern_gen == plane.control.intern_gen()
                assert "flow-res" in cli._intern
        finally:
            cli.close()
            plane.close()
            eng.close()


# ---------------------------------------------------------------------------
# real worker processes (the mp tier)
# ---------------------------------------------------------------------------
def _spawn(plane, target, wid, *args):
    ctx = plane.spawn_context()
    q = ctx.Queue()
    p = ctx.Process(
        target=target, args=(plane.channel(wid), wid, *args, q), daemon=True
    )
    p.start()
    return p, q


def _q_get(q, timeout_s=120):
    return q.get(timeout=timeout_s)


def _reap_proc(p):
    if p is None:
        return
    p.join(timeout=5)
    if p.is_alive():
        p.terminate()
        p.join(timeout=5)


@pytest.mark.mp
class TestMultiProcess:
    def test_parity_across_process_boundary(self, manual_clock):
        """The mp spot check of TestPlaneParity: the SAME stream from a
        real spawned worker produces the same verdicts as the oracle
        (depth 2, speculative on — the production shape)."""
        config.set(config.PIPELINE_DEPTH, "2")
        config.set(config.SPECULATIVE_ENABLED, "true")
        manual_clock.set_ms(1000)
        oracle = _engine(manual_clock)
        _rules(oracle)
        eng = _engine(manual_clock)
        _rules(eng)
        plane = IngestPlane(eng)
        script = []
        want = []
        for req in _stream():
            if req[0] == "entry":
                _, res, ts, args = req
                script.append(
                    {"kind": "entry", "resource": res, "ts": ts,
                     "args": list(args), "timeout_ms": 60000}
                )
                want.append(
                    ("entry",) + _oracle_decide(oracle, res, 1, [ts], [args])[0]
                )
            else:
                _, res, ts, n = req
                script.append(
                    {"kind": "bulk", "resource": res, "n": n, "ts": ts}
                )
                vs = _oracle_decide(oracle, res, n, [ts] * n, [()] * n)
                want.append(
                    ("bulk", [v[0] for v in vs], [v[1] for v in vs],
                     [v[2] for v in vs])
                )
        p = None
        try:
            p, q = _spawn(plane, ipc_procs.run_script, 0, script)
            tag, wid, out = _q_get(q)
            assert tag == "done" and wid == 0
            got = [
                ("entry", s[1], s[2], s[3]) if s[0] == "entry"
                else ("bulk", s[1], s[2], s[3])
                for s in out
            ]
            assert got == want
        finally:
            _reap_proc(p)
            plane.close()
            eng.close()
            oracle.close()

    def test_traceparent_identity_across_boundary(self, manual_clock):
        """PR-4 identity survives the frame: the record in the ENGINE
        process carries the worker's inbound trace id and parent span."""
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="flow-res", count=1e9)])
        plane = IngestPlane(eng)
        tid = "a1" * 16
        sid = "b2" * 8
        traceparent = f"00-{tid}-{sid}-01"
        p = None
        try:
            p, q = _spawn(
                plane, ipc_procs.entry_with_trace, 0, "flow-res", traceparent
            )
            tag, _wid, (admitted, _reason) = _q_get(q)
            assert tag == "done" and admitted
            _wait_for(
                lambda: any(
                    r.trace_id == tid and r.parent_span_id == sid
                    for r in eng.admission_trace.records()
                ),
                what="trace record with inbound identity",
            )
            rec = next(
                r for r in eng.admission_trace.records()
                if r.trace_id == tid
            )
            assert rec.resource == "flow-res"
            assert rec.head_sampled  # inbound sampled flag honored
        finally:
            _reap_proc(p)
            plane.close()
            eng.close()

    def test_worker_kill_gauges_exactly_zero(self):
        """kill -9 a worker holding live admissions: the heartbeat
        sweep auto-exits them and BOTH the device and mirror THREAD
        gauges read exactly 0 after quiesce."""
        config.set(config.SPECULATIVE_ENABLED, "true")
        config.set(config.IPC_HEARTBEAT_MS, "50")
        config.set(config.IPC_WORKER_DEAD_MS, "400")
        eng = _engine()  # real clock: heartbeat staleness is wall time
        eng.set_flow_rules([FlowRule(resource="kill-res", count=1e9)])
        plane = IngestPlane(eng)
        n = 5
        p = None
        try:
            p, q = _spawn(plane, ipc_procs.admit_and_hang, 0, "kill-res", n)
            tag, _wid, admitted = _q_get(q)
            assert tag == "admitted" and admitted == n
            eng.flush()
            eng.drain()
            stats = eng.cluster_node_stats("kill-res")
            assert stats["cur_thread_num"] == n  # charged while alive
            os.kill(p.pid, signal.SIGKILL)  # no exits, no cleanup
            _wait_for(
                lambda: plane.snapshot()["counters"]["worker_deaths"] >= 1,
                timeout_s=30,
                what="worker death sweep",
            )
            assert plane.snapshot()["counters"]["auto_exits"] == n
            eng.flush()
            eng.drain()
            stats = eng.cluster_node_stats("kill-res")
            assert stats["cur_thread_num"] == 0, "device gauge must be 0"
            mirror = eng.speculative.mirror.snapshot()["live_threads"]
            assert mirror.get("kill-res", 0) == 0, "mirror gauge must be 0"
            assert eng.telemetry.counters_snapshot()["ipc_worker_deaths"] == 1
        finally:
            _reap_proc(p)
            plane.close()
            eng.close()

    def test_engine_close_fails_over_and_quiesces(self):
        """Engine death mid-stream: the worker's NEXT verdict comes
        from the policy snapshot (degraded), and the closing engine's
        final sweep leaves its gauges exactly 0."""
        config.set(config.SPECULATIVE_ENABLED, "true")
        eng = _engine()
        eng.set_flow_rules([FlowRule(resource="die-res", count=1e9)])
        plane = IngestPlane(eng)
        p = None
        try:
            p, q = _spawn(plane, ipc_procs.entries_until_dead, 0, "die-res")
            # Let it serve a few live verdicts first.
            _wait_for(
                lambda: plane.snapshot()["counters"]["requests"] >= 3,
                what="live traffic",
            )
            plane.close()
            tag, _wid, served = _q_get(q)
            assert tag == "done"
            assert served, "worker observed no verdicts"
            live = [s for s in served if not s[2]]
            assert live and all(s[0] for s in live)
            # The death was observed as a policy-served verdict.
            assert served[-1][2] is True
            assert served[-1][0] is True  # fail-open default
            eng.flush()
            eng.drain()
            stats = eng.cluster_node_stats("die-res")
            assert stats["cur_thread_num"] == 0
            mirror = eng.speculative.mirror.snapshot()["live_threads"]
            assert mirror.get("die-res", 0) == 0
        finally:
            _reap_proc(p)
            plane.close()
            eng.close()


class TestFrameBudget:
    def test_args_heavy_bulk_splits_by_bytes_not_rows(self, manual_clock):
        """Regression (review): frame sizing must count args BYTES — an
        args-heavy group on an EMPTY ring previously built one
        oversized frame the ring could never accept and shed every row
        as phantom 'ring full' backpressure."""
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="argsy", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            big = "v" * 120
            a, r, _w, _f = cli.bulk(
                "argsy", 200, ts=1000, args_column=[(big,)] * 200,
                timeout_ms=60000,
            )
            assert a.all(), r[~a]
            assert cli.counters["sheds"] == 0
            # A single row that cannot fit ANY slot is the caller's
            # bug, not backpressure.
            with pytest.raises(ValueError):
                cli.bulk("argsy", 1, args_column=[("x" * 40000,)])
            with pytest.raises(ValueError):
                cli.entry("argsy", args=("x" * 40000,))
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_closed_plane_scrape_degrades(self, manual_clock):
        """Regression (review): a metrics scrape racing plane.close()
        must degrade to zeros, not fail the render."""
        from sentinel_tpu.transport.prometheus import render_metrics

        eng = _engine(manual_clock)
        plane = IngestPlane(eng)
        plane.close()
        assert plane.request.occupancy() == 0.0
        assert plane.control.intern_gen() == 0
        out = render_metrics(eng)
        assert "sentinel_engine_ipc_enabled 0" in out
        eng.close()

    def test_long_names_ship_via_intern_preamble(self, manual_clock):
        """Regression (review): fresh intern records past the frame
        reserve ship as a zero-row preamble frame instead of building
        an over-slot payload that reads as permanent ring
        backpressure."""
        config.set(config.IPC_SLOT_BYTES, "2048")
        eng = _engine(manual_clock)
        long_res = "r" + "x" * 1400  # intern record alone > reserve
        eng.set_flow_rules([FlowRule(resource=long_res, count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            v = cli.entry(long_res, ts=1000, timeout_ms=60000)
            assert v.admitted and cli.counters["sheds"] == 0
            # And a name no slot can ever carry is the caller's bug.
            with pytest.raises(ValueError):
                cli.entry("r" + "y" * 4000, ts=1000)
        finally:
            cli.close()
            plane.close()
            eng.close()
