"""Engine supervision & warm hot-restart (PR 15).

The acceptance surface: durable checkpoints spill atomically and load
back into a FRESH engine (corrupt/stale/mismatched files degrade to a
COUNTED cold start, never an exception); the PR-5 checkpoint now
carries the device SketchState so an engine trip no longer silently
drops heavy-hitter protection; a new engine process re-attaches to the
EXISTING named shared-memory rings (boot-epoch bump), workers
re-intern, re-assert their live-admission ledgers and replay buffered
dead-window completions — device AND mirror THREAD gauges exact in the
new world and exactly 0 after quiesce, verdict parity vs a never-killed
oracle (chaos-tested at depths {0, 2}); and the supervisor turns an
engine ``kill -9`` under load into a bounded-outage blip
(`mp`-marked, real processes).
"""

from __future__ import annotations

import os
import time
import uuid

import numpy as np
import pytest

from sentinel_tpu.core import errors as E
from sentinel_tpu.models.rules import FlowRule, ParamFlowRule
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import config


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _wait_for(pred, timeout_s=30.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# durable file format units (runtime/durable.py)
# ---------------------------------------------------------------------------
class TestDurableFile:
    def test_roundtrip(self, tmp_path):
        from sentinel_tpu.runtime import durable

        path = str(tmp_path / "ck.bin")
        leaves = [
            np.arange(12, dtype=np.int32).reshape(3, 4),
            np.ones(5, dtype=np.float32),
        ]
        n = durable.write_checkpoint(path, {"seq": 7, "wall_ms": 1}, leaves)
        assert n == os.path.getsize(path)
        header, got = durable.read_checkpoint(path)
        assert header["seq"] == 7 and header["version"] == durable.VERSION
        assert len(got) == 2
        np.testing.assert_array_equal(got[0], leaves[0])
        np.testing.assert_array_equal(got[1], leaves[1])

    def test_atomic_replace_keeps_previous_on_overwrite(self, tmp_path):
        from sentinel_tpu.runtime import durable

        path = str(tmp_path / "ck.bin")
        durable.write_checkpoint(path, {"seq": 1}, [np.zeros(2)])
        durable.write_checkpoint(path, {"seq": 2}, [np.ones(2)])
        header, _ = durable.read_checkpoint(path)
        assert header["seq"] == 2
        assert not [
            f for f in os.listdir(tmp_path) if f.startswith(".ck.bin.tmp")
        ]

    @pytest.mark.parametrize(
        "corrupt",
        ["magic", "truncate", "crc", "header"],
    )
    def test_corruption_raises_checkpoint_error(self, tmp_path, corrupt):
        from sentinel_tpu.runtime import durable

        path = str(tmp_path / "ck.bin")
        durable.write_checkpoint(path, {"seq": 3}, [np.arange(8)])
        blob = bytearray(open(path, "rb").read())
        if corrupt == "magic":
            blob[0] ^= 0xFF
        elif corrupt == "truncate":
            blob = blob[: len(blob) // 2]
        elif corrupt == "crc":
            blob[-1] ^= 0xFF
        elif corrupt == "header":
            blob[12] ^= 0xFF  # inside the header JSON
        open(path, "wb").write(bytes(blob))
        with pytest.raises(durable.DurableCheckpointError):
            durable.read_checkpoint(path)


# ---------------------------------------------------------------------------
# engine-level durable spill + warm load
# ---------------------------------------------------------------------------
def _mk_engine(clock, path="", every=1, stale_ms=0, rules=None, depth=0):
    config.set(config.FAILOVER_ENABLED, "true")
    config.set(config.FAILOVER_CHECKPOINT_EVERY, str(every))
    config.set(config.FAILOVER_CKPT_PATH, path)
    config.set(config.FAILOVER_CKPT_INTERVAL_MS, "0")
    config.set(config.FAILOVER_CKPT_STALE_MS, str(stale_ms))
    eng = Engine(clock=clock)
    eng.pipeline_depth = depth
    if rules is not None:
        eng.set_flow_rules(rules)
    return eng


def _wait_durable_write(eng, min_writes=1):
    _wait_for(
        lambda: eng.failover.counters["durable_writes"] >= min_writes,
        what="durable checkpoint write",
    )


class TestDurableCheckpoint:
    def test_unset_path_writes_nothing(self, manual_clock):
        config.set(config.FAILOVER_ENABLED, "true")
        config.set(config.FAILOVER_CHECKPOINT_EVERY, "1")
        eng = Engine(clock=manual_clock)
        eng.set_flow_rules([FlowRule("r", count=5)])
        manual_clock.set_ms(1000)
        eng.submit_entry("r")
        eng.flush()
        eng.drain()
        fo = eng.failover
        assert fo.counters["checkpoints"] >= 1
        assert fo.counters["durable_writes"] == 0
        assert fo._durable_thread is None  # no writer thread at all
        assert fo.snapshot()["durable"]["path"] == ""
        eng.close()

    def test_warm_restore_qps_window_and_thread_zero(
        self, manual_clock, tmp_path
    ):
        """The warm-start differential: engine A consumes a QPS rule's
        window and holds live THREAD gauges, spills, dies; engine B
        restores — the SAME second's window is still consumed (blocked,
        where a cold engine admits), but the THREAD gauges are ZERO
        (live concurrency is rebuilt from worker re-assertions, not
        the checkpoint)."""
        path = str(tmp_path / "ck.bin")
        manual_clock.set_ms(5000)
        a = _mk_engine(manual_clock, path, rules=[FlowRule("r", count=5)])
        ops = [a.submit_entry("r", ts=5000) for _ in range(8)]
        a.flush()
        a.drain()
        assert sum(1 for op in ops if op.verdict.admitted) == 5
        # Live THREAD gauge at capture time: 5 admitted, none exited.
        assert a.cluster_node_stats("r")["cur_thread_num"] == 5
        _wait_durable_write(a)
        a.close()

        b = _mk_engine(manual_clock, path, rules=[FlowRule("r", count=5)])
        assert b.failover.restore_durable() is True
        assert b.failover.state == "HEALTHY"
        assert b.failover.counters["durable_loads"] == 1
        assert b.failover.counters["durable_load_cold"] == 0
        # THREAD gauges restore as zero by contract.
        assert b.cluster_node_stats("r")["cur_thread_num"] == 0
        # The same second's QPS window is already consumed: a cold
        # engine would admit 5 more here; the warm one blocks them all.
        ops_b = [b.submit_entry("r", ts=5000) for _ in range(5)]
        b.flush()
        b.drain()
        assert all(not op.verdict.admitted for op in ops_b), [
            op.verdict for op in ops_b
        ]
        b.close()

        cold = _mk_engine(manual_clock, "", rules=[FlowRule("r", count=5)])
        ops_c = [cold.submit_entry("r", ts=5000) for _ in range(5)]
        cold.flush()
        cold.drain()
        assert all(op.verdict.admitted for op in ops_c)
        cold.close()

    def test_warm_restore_param_value_rows_survive(
        self, manual_clock, tmp_path
    ):
        """The PR-16 gap: param_dyn rows name dynamically-interned
        (rule, value) pairs, so earlier checkpoints spilled param as
        nothing and every hot-param window restarted cold. The spill
        now carries the ParamIndex value→row maps; a fresh process that
        adopts them sees the SAME value's window still consumed, while
        a value the dead process never saw interns fresh and admits."""
        path = str(tmp_path / "ck.bin")
        manual_clock.set_ms(5000)
        prules = {"p": [ParamFlowRule("p", param_idx=0, count=3)]}
        a = _mk_engine(manual_clock, path, rules=[FlowRule("p", count=1000)])
        a.set_param_rules(prules)
        ops = [a.submit_entry("p", ts=5000, args=("hot",)) for _ in range(5)]
        a.flush()
        a.drain()
        assert sum(1 for op in ops if op.verdict.admitted) == 3
        _wait_durable_write(a)
        a.close()

        b = _mk_engine(manual_clock, path, rules=[FlowRule("p", count=1000)])
        b.set_param_rules(prules)
        assert b.failover.restore_durable() is True
        assert b.failover.counters["durable_load_cold"] == 0
        # Same value, same second: window already consumed — a cold
        # engine would grant 3 more.
        hot = [b.submit_entry("p", ts=5000, args=("hot",)) for _ in range(3)]
        # A value the dead process never interned starts fresh.
        cold = [b.submit_entry("p", ts=5000, args=("new",)) for _ in range(3)]
        b.flush()
        b.drain()
        assert all(not op.verdict.admitted for op in hot), [
            op.verdict for op in hot
        ]
        assert all(op.verdict.admitted for op in cold)
        b.close()

    def test_param_rule_change_restores_param_cold(
        self, manual_clock, tmp_path
    ):
        """A different compiled param rule set fails the fingerprint:
        param restores cold (admits again) but the rest of the
        checkpoint still installs."""
        path = str(tmp_path / "ck.bin")
        manual_clock.set_ms(5000)
        a = _mk_engine(manual_clock, path, rules=[FlowRule("p", count=1000)])
        a.set_param_rules({"p": [ParamFlowRule("p", param_idx=0, count=3)]})
        for _ in range(5):
            a.submit_entry("p", ts=5000, args=("hot",))
        a.flush()
        a.drain()
        _wait_durable_write(a)
        a.close()

        b = _mk_engine(manual_clock, path, rules=[FlowRule("p", count=1000)])
        b.set_param_rules({"p": [ParamFlowRule("p", param_idx=0, count=4)]})
        assert b.failover.restore_durable() is True
        ops = [b.submit_entry("p", ts=5000, args=("hot",)) for _ in range(4)]
        b.flush()
        b.drain()
        assert all(op.verdict.admitted for op in ops)
        b.close()

    def test_corrupt_file_cold_start_counted(self, manual_clock, tmp_path):
        path = str(tmp_path / "ck.bin")
        with open(path, "wb") as f:
            f.write(b"this is not a checkpoint")
        b = _mk_engine(manual_clock, path, rules=[FlowRule("r", count=5)])
        assert b.failover.restore_durable() is False  # never an exception
        assert b.failover.counters["durable_load_cold"] == 1
        assert b.failover.state == "HEALTHY"  # untouched — serving
        op = b.submit_entry("r", ts=1000)
        b.flush()
        b.drain()
        assert op.verdict.admitted
        b.close()

    def test_missing_file_is_a_silent_cold_start(self, manual_clock, tmp_path):
        b = _mk_engine(
            manual_clock, str(tmp_path / "nope.bin"),
            rules=[FlowRule("r", count=5)],
        )
        assert b.failover.restore_durable() is False
        assert b.failover.counters["durable_load_cold"] == 0  # not an event
        b.close()

    def test_stale_file_cold_start_counted(self, manual_clock, tmp_path):
        path = str(tmp_path / "ck.bin")
        manual_clock.set_ms(1000)
        a = _mk_engine(manual_clock, path, rules=[FlowRule("r", count=5)])
        a.submit_entry("r", ts=1000)
        a.flush()
        a.drain()
        _wait_durable_write(a)
        a.close()
        time.sleep(0.05)  # age the file past the 1 ms staleness bound
        b = _mk_engine(
            manual_clock, path, stale_ms=1, rules=[FlowRule("r", count=5)]
        )
        assert b.failover.restore_durable() is False
        assert b.failover.counters["durable_load_cold"] == 1
        b.close()

    def test_window_geometry_mismatch_restores_stats_fresh(
        self, manual_clock, tmp_path
    ):
        """A tampered window-geometry header must NOT install the stats
        — the same second's window reads fresh (admits) instead of
        consumed."""
        from sentinel_tpu.runtime import durable

        path = str(tmp_path / "ck.bin")
        manual_clock.set_ms(5000)
        a = _mk_engine(manual_clock, path, rules=[FlowRule("r", count=5)])
        for _ in range(8):
            a.submit_entry("r", ts=5000)
        a.flush()
        a.drain()
        _wait_durable_write(a)
        a.close()
        header, leaves = durable.read_checkpoint(path)
        header["win"] = [4, 2000, 4900]  # not the live SECOND_CFG
        header.pop("version")
        header.pop("n_leaves")
        durable.write_checkpoint(path, header, leaves)

        b = _mk_engine(manual_clock, path, rules=[FlowRule("r", count=5)])
        assert b.failover.restore_durable() is True  # other components fine
        ops = [b.submit_entry("r", ts=5000) for _ in range(5)]
        b.flush()
        b.drain()
        assert all(op.verdict.admitted for op in ops)  # stats were fresh
        b.close()

    def test_snapshot_and_health_report_durable(self, manual_clock, tmp_path):
        path = str(tmp_path / "ck.bin")
        a = _mk_engine(manual_clock, path, rules=[FlowRule("r", count=5)])
        a.submit_entry("r", ts=1000)
        a.flush()
        a.drain()
        _wait_durable_write(a)
        snap = a.failover.snapshot()["durable"]
        assert snap["writes"] >= 1 and snap["path"] == path
        assert snap["last"] is not None and snap["last"]["bytes"] > 0
        assert snap["last"]["age_ms"] >= 0
        a.close()


# ---------------------------------------------------------------------------
# SketchState in the checkpoint (satellite regression)
# ---------------------------------------------------------------------------
@pytest.fixture()
def sketch_failover_config():
    config.set(config.SKETCH_ENABLED, "true")
    config.set(config.SKETCH_PROMOTE_QPS, "5")
    config.set(config.SKETCH_WINDOW_MS, "1000")
    config.set(config.SKETCH_DEMOTE_WINDOWS, "2")
    config.set(config.FAILOVER_ENABLED, "true")
    config.set(config.FAILOVER_CHECKPOINT_EVERY, "1")
    config.set(config.FAILOVER_PROBE_FLUSHES, "2")
    yield


def _drive_until_promoted(eng, clk, hot="HOT", max_windows=6):
    for step in range(max_windows * 4):
        col = [(f"cold{step}_{j}",) for j in range(32)] + [(hot,)] * 32
        eng.submit_bulk("api", n=64, args_column=col)
        eng.flush()
        eng.drain()
        if hot in eng.sketch.promoted_values.get("api", ()):
            return
        clk.advance(250)
    raise AssertionError("HOT never promoted")


class TestSketchCheckpointRestore:
    def test_promoted_key_survives_in_process_restore(
        self, sketch_failover_config
    ):
        """Regression (PR 15): an engine trip used to reset the device
        sketch — the candidate table lost every count, so the demotion
        clock tore promoted rules down within demote.windows. The
        checkpoint now CARRIES SketchState: post-restore the promoted
        key's rule is intact AND its candidate-table estimate is still
        there (no re-accumulation window)."""
        from sentinel_tpu.testing.faults import FaultInjector

        clk = ManualClock()
        clk.set_ms(1000)
        eng = Engine(clock=clk)
        eng.set_param_rules(
            {"api": [ParamFlowRule(resource="api", param_idx=0, count=3.0,
                                   sketch_mode=True)]}
        )
        inj = FaultInjector().install(eng)
        _drive_until_promoted(eng, clk)
        eng.drain()
        ck = eng.failover._ckpt
        assert ck is not None and len(ck.states) == 5
        assert ck.states[4] is not None, "checkpoint must carry the sketch"
        pre_cand = int(np.asarray(eng.sketch.dev_state.cand_cnt).max())
        assert pre_cand > 0

        inj.fail_fetch(eng.flush_seq + 1)
        eng.submit_bulk("api", n=4, args_column=[("HOT",)] * 4)
        eng.flush()
        assert eng.failover.state == "DEGRADED"
        assert eng.failover.try_recover()
        assert eng.failover.state == "HEALTHY"

        # The rule survives AND the candidate table was restored, not
        # reset (pre-PR behavior: cand_cnt all zeros here).
        assert "HOT" in eng.sketch.promoted_values.get("api", ())
        post_cand = int(np.asarray(eng.sketch.dev_state.cand_cnt).max())
        assert post_cand > 0, "candidate table must survive the restore"
        eng.close()

    def test_durable_checkpoint_carries_sketch(
        self, sketch_failover_config, tmp_path
    ):
        """Cross-process: the durable file carries the sketch leaves
        and a fresh engine restores them (same config shapes)."""
        path = str(tmp_path / "ck.bin")
        config.set(config.FAILOVER_CKPT_PATH, path)
        config.set(config.FAILOVER_CKPT_INTERVAL_MS, "0")
        clk = ManualClock()
        clk.set_ms(1000)
        a = Engine(clock=clk)
        a.set_param_rules(
            {"api": [ParamFlowRule(resource="api", param_idx=0, count=3.0,
                                   sketch_mode=True)]}
        )
        _drive_until_promoted(a, clk)
        a.drain()
        _wait_durable_write(a)
        a.close()
        from sentinel_tpu.runtime import durable

        header, _ = durable.read_checkpoint(path)
        assert header["components"]["sketch"] > 0

        b = Engine(clock=clk)
        b.set_param_rules(
            {"api": [ParamFlowRule(resource="api", param_idx=0, count=3.0,
                                   sketch_mode=True)]}
        )
        assert b.failover.restore_durable() is True
        assert int(np.asarray(b.sketch.dev_state.cand_cnt).max()) > 0
        b.close()


# ---------------------------------------------------------------------------
# in-process ring re-attach + worker reconnect (the chaos core)
# ---------------------------------------------------------------------------
def _reattach_config(depth: int) -> str:
    prefix = f"stpu-t-{uuid.uuid4().hex[:8]}"
    config.set(config.IPC_SHM_PREFIX, prefix)
    config.set(config.IPC_HEARTBEAT_MS, "50")
    config.set(config.IPC_ENGINE_DEAD_MS, "300")
    config.set(config.SPECULATIVE_ENABLED, "true")
    config.set(config.PIPELINE_DEPTH, str(depth))
    return prefix


class TestReattachReassert:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_kill_reattach_reassert_parity_and_gauges(self, depth):
        """The acceptance chaos core, in-process (real processes are
        the mp test below): engine A dies holding the client's live
        THREAD admissions; engine B re-attaches to the SAME rings,
        the client re-asserts its ledger and replays the buffered
        dead-window completion; post-restart verdicts on a THREAD rule
        match a never-killed oracle holding the same live set, and
        device AND mirror THREAD gauges drain to exactly 0."""
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient
        from sentinel_tpu.models import constants as C

        _reattach_config(depth)
        rule = lambda: [  # noqa: E731
            FlowRule("tr", count=3, grade=C.FLOW_GRADE_THREAD)
        ]
        a = Engine(initial_rows=256)
        a.set_flow_rules(rule())
        plane_a = IngestPlane(a)
        cli = IngestClient(plane_a.channel(0), 0)
        b = plane_b = None
        try:
            for _ in range(2):
                v = cli.entry("tr", timeout_ms=60000)
                assert v.admitted and not v.degraded
            a.flush()
            a.drain()
            assert a.cluster_node_stats("tr")["cur_thread_num"] == 2
            # kill -9 surrogate: threads stop, segments persist.
            plane_a.abandon()
            a.close()
            _wait_for(lambda: not cli.engine_alive(), what="engine death")
            # One completion in the dead window: buffered, not dropped.
            assert cli.exit("tr")
            assert cli.snapshot()["buffered_exits"] == 1
            # And a policy-served verdict marks the outage window.
            assert cli.entry("tr", timeout_ms=400).degraded

            b = Engine(initial_rows=256)
            b.set_flow_rules(rule())
            plane_b = IngestPlane(b)
            assert plane_b.attached and plane_b.engine_epoch == 2
            _wait_for(
                lambda: cli.counters["reconnects"] >= 1
                and plane_b.snapshot()["counters"]["exits"] >= 1,
                what="client reconnect + exit replay",
            )
            snap = plane_b.snapshot()
            assert snap["counters"]["worker_reconnects"] == 1
            assert snap["counters"]["reasserts"] == 2
            b.flush()
            b.drain()
            # 2 re-asserted − 1 replayed completion = exactly 1 live.
            assert b.cluster_node_stats("tr")["cur_thread_num"] == 1
            assert (
                b.speculative.mirror.snapshot()["live_threads"].get("tr", 0)
                == 1
            )

            # Oracle differential: a never-killed engine holding the
            # same ONE live admission sees the same verdict stream.
            config.set(config.IPC_SHM_PREFIX, "")
            oracle = Engine(initial_rows=256)
            oracle.set_flow_rules(rule())
            o_live = oracle.submit_entry("tr")
            oracle.flush()
            oracle.drain()
            want = []
            for _ in range(3):
                op = oracle.submit_entry("tr")
                oracle.flush()
                oracle.drain()
                want.append((op.verdict.admitted, op.verdict.reason))
            got = []
            for _ in range(3):
                v = cli.entry("tr", timeout_ms=60000)
                got.append((v.admitted, int(v.reason)))
            assert got == want, (got, want)
            # With THREAD count=3 and 1 live: admit, admit, block.
            assert [g[0] for g in got] == [True, True, False]

            # Quiesce: exit everything still live on both sides.
            for _ in range(3):
                cli.exit("tr")
            _wait_for(
                lambda: plane_b.snapshot()["counters"]["exits"] >= 4,
                what="exits drained",
            )
            b.flush()
            b.drain()
            assert b.cluster_node_stats("tr")["cur_thread_num"] == 0
            assert (
                b.speculative.mirror.snapshot()["live_threads"].get("tr", 0)
                == 0
            )
            assert cli.snapshot()["live_admissions"] == 0
            oracle.close()
        finally:
            cli.close()
            for o in (plane_b, b):
                if o is not None:
                    o.close()

    def test_idle_client_reconnect_counts_plane_side(self):
        """Regression (review): an idle client's zero-row head reassert
        never interned anything, so it used to ship the DEAD world's
        generation and get gen-gated as a stale frame — the plane's
        worker_reconnects stayed 0 while the client counted 1."""
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient

        _reattach_config(0)
        a = Engine(initial_rows=256)
        plane_a = IngestPlane(a)
        cli = IngestClient(plane_a.channel(0), 0)
        b = plane_b = None
        try:
            # ONE admission so the client's gen was ever the old one;
            # exit it so the ledger is empty (zero-row head frame).
            a.set_flow_rules([FlowRule("r", count=1e9)])
            assert cli.entry("r", timeout_ms=60000).admitted
            assert cli.exit("r")
            _wait_for(
                lambda: plane_a.snapshot()["counters"]["exits"] >= 1,
                what="exit drained",
            )
            plane_a.abandon()
            a.close()
            _wait_for(lambda: not cli.engine_alive(), what="engine death")
            b = Engine(initial_rows=256)
            plane_b = IngestPlane(b)
            _wait_for(
                lambda: plane_b.snapshot()["counters"]["worker_reconnects"]
                >= 1,
                what="plane-side reconnect count",
            )
            assert cli.counters["reconnects"] == 1
            assert plane_b.snapshot()["counters"]["stale_frames"] == 0
        finally:
            cli.close()
            for o in (plane_b, b):
                if o is not None:
                    o.close()

    def test_first_boot_observation_merges_new_world_ledger(self):
        """Regression (review): admits decided between the plane's boot
        bump and the client's first beat tick land in _live_new; the
        boot==0 early return must fold them into the main ledger or a
        LATER restart's reassert would miss them."""
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient

        _reattach_config(0)
        a = Engine(initial_rows=256)
        a.set_flow_rules([FlowRule("r", count=1e9)])
        plane_a = IngestPlane(a)
        cli = IngestClient(plane_a.channel(0), 0, heartbeat=False)
        try:
            # Simulate attach-before-first-boot: force the pre-bump view.
            with cli._lock:
                cli._boot = 0
            v = cli.entry("r", timeout_ms=60000)
            assert v.admitted
            with cli._lock:
                assert sum(cli._live_new.values()) == 1  # routed new-world
                assert sum(cli._live.values()) == 0
            cli._maybe_reconnect()  # the beat-tick body
            with cli._lock:
                assert cli._boot == plane_a.control.engine_boot()
                assert sum(cli._live.values()) == 1  # merged
                assert not cli._live_new
            assert cli.counters["reconnects"] == 0  # not a restart
        finally:
            cli.close()
            plane_a.close()
            a.close()

    def test_reconnect_disabled_is_pr14_behavior(self):
        """`sentinel.tpu.ipc.reconnect.enabled=false`: no ledger, no
        buffering (dead-window exits drop, counted), no reassert on an
        epoch bump — the PR-14 stance exactly."""
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient

        _reattach_config(0)
        config.set(config.IPC_RECONNECT, "false")
        a = Engine(initial_rows=256)
        a.set_flow_rules([FlowRule("r", count=1e9)])
        plane_a = IngestPlane(a)
        cli = IngestClient(plane_a.channel(0), 0)
        b = plane_b = None
        try:
            assert cli.entry("r", timeout_ms=60000).admitted
            assert cli.snapshot()["live_admissions"] == 0  # no ledger
            plane_a.abandon()
            a.close()
            _wait_for(lambda: not cli.engine_alive(), what="engine death")
            # PR-14 stance: the completion pushes into the (still
            # mapped) ring as dead-world backlog — never buffered for
            # replay; the NEW plane gen-gates it away.
            assert cli.exit("r") is True
            assert cli.snapshot()["buffered_exits"] == 0

            b = Engine(initial_rows=256)
            b.set_flow_rules([FlowRule("r", count=1e9)])
            plane_b = IngestPlane(b)
            _wait_for(lambda: cli.engine_alive(), what="engine up")
            time.sleep(0.3)  # several beat ticks: no reassert may fire
            assert cli.counters["reconnects"] == 0
            assert plane_b.snapshot()["counters"]["worker_reconnects"] == 0
        finally:
            cli.close()
            for o in (plane_b, b):
                if o is not None:
                    o.close()


# ---------------------------------------------------------------------------
# supervised kill -9 (real processes)
# ---------------------------------------------------------------------------
class TestSupervisorUnits:
    def test_create_segments_survives_stale_leftovers(self):
        """Regression (review): a crashed SUPERVISOR leaves its named
        segments in /dev/shm (destroy never ran, its fleet died with
        it) — a relaunch with the same fixed prefix must unlink the
        corpses and recreate, not die with FileExistsError."""
        import multiprocessing

        from sentinel_tpu.ipc.supervise import (
            create_segments,
            destroy_segments,
            make_handles,
        )

        ctx = multiprocessing.get_context("spawn")
        prefix = f"stpu-su-{uuid.uuid4().hex[:8]}"
        h = make_handles(ctx, prefix, n_workers=1)
        stale = create_segments(h)
        for s in stale:
            s.close()  # the crash: handles gone, segments left behind
        fresh = create_segments(h)  # must not raise
        try:
            assert len(fresh) == len(stale)
        finally:
            destroy_segments(fresh)


@pytest.mark.mp
class TestSupervisedChaos:
    def test_kill9_bounded_outage_and_reconnect(self, tmp_path):
        """The end-to-end loop with real processes and in-flight
        micro-windows: supervised engine, client micro-window armed,
        kill -9 mid-load → the supervisor restarts the engine on the
        SAME rings, the probing client's policy-served interval is
        bounded, it reconnects (ledger re-assert) and resumes
        device-backed verdicts."""
        import ipc_procs
        from sentinel_tpu.ipc.supervise import measure_restart_outage

        config.set(config.IPC_HEARTBEAT_MS, "50")
        config.set(config.IPC_ENGINE_DEAD_MS, "2000")
        config.set(config.IPC_CLIENT_WINDOW_MS, "0.5")  # in-flight windows
        config.set(config.SUPERVISE_BACKOFF_MS, "200")
        config.set(config.FAILOVER_ENABLED, "true")
        config.set(config.FAILOVER_CHECKPOINT_EVERY, "2")
        config.set(config.FAILOVER_CKPT_PATH, str(tmp_path / "ck.bin"))
        out = measure_restart_outage(
            ipc_procs.restart_setup, "chaos-res", timeout_s=200
        )
        assert out["restarts"] >= 1, out
        assert out["reconnects"] >= 1, out
        # Bounded outage: the policy-served interval ended (we got a
        # device-backed verdict again) — the wall-clock bound is the
        # measurement returning at all; sanity-cap it anyway.
        assert out["outage_ms"] < 150_000, out
