"""Engine flight recorder: spans, histograms, blocked sketch, exports.

Covers metrics/telemetry.py + metrics/histogram.py, the kernel's
device-side top-K blocked-resource fold (runtime/flush.py sketch_k),
the Prometheus ``sentinel_engine_*`` family, the ``telemetry``
transport command, ParamIndex intern-cache counters, and the
metric-log ``__engine__`` roll-in."""

import json
import time

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.metrics.histogram import LatencyHistogram
from sentinel_tpu.metrics.metric_log import MetricTimer, MetricWriter
from sentinel_tpu.metrics.telemetry import SpaceSaving, TelemetryBus
from sentinel_tpu.models.rules import ParamFlowRule
from sentinel_tpu.utils.config import config


class TestLatencyHistogram:
    def test_pow2_bucket_placement(self):
        h = LatencyHistogram(base_ms=1.0, n_buckets=4)  # bounds 1,2,4,8
        for ms, want in [(0.0, 0), (1.0, 0), (1.5, 1), (2.0, 1), (2.5, 2),
                         (4.0, 2), (7.9, 3), (8.0, 3), (8.1, 4), (1e9, 4)]:
            h2 = LatencyHistogram(base_ms=1.0, n_buckets=4)
            h2.record(ms)
            counts, _ = h2.snapshot_counts()
            assert counts[want] == 1, (ms, want, counts)
        assert h.count == 0

    def test_record_many_matches_record(self):
        vals = [0.01, 0.5, 1.7, 3.3, 100.0, 1e6, 0.0]
        a = LatencyHistogram()
        b = LatencyHistogram()
        for v in vals:
            a.record(v)
        b.record_many(vals)
        ca, sa = a.snapshot_counts()
        cb, sb = b.snapshot_counts()
        assert (ca == cb).all() and sa == pytest.approx(sb)

    def test_merge_and_percentile(self):
        a = LatencyHistogram(base_ms=1.0, n_buckets=8)
        b = LatencyHistogram(base_ms=1.0, n_buckets=8)
        for _ in range(99):
            a.record(1.0)  # bucket 0
        b.record(100.0)  # bucket 7
        a.merge(b)
        assert a.count == 100
        assert a.percentile(0.5) == 1.0
        assert a.percentile(0.995) == 128.0  # the tail observation's bound
        with pytest.raises(ValueError):
            a.merge(LatencyHistogram(base_ms=2.0, n_buckets=8))

    def test_prometheus_lines_cumulative(self):
        h = LatencyHistogram(base_ms=1.0, n_buckets=3)  # bounds 1,2,4
        for v in (0.5, 1.5, 3.0, 99.0):
            h.record(v)
        lines = h.prometheus_lines("x_ms", "help")
        assert "# TYPE x_ms histogram" in lines
        buckets = [l for l in lines if l.startswith("x_ms_bucket")]
        counts = [int(l.rsplit(" ", 1)[1]) for l in buckets]
        assert counts == [1, 2, 3, 4]  # cumulative, +Inf last
        assert 'le="+Inf"' in buckets[-1]
        assert any(l.startswith("x_ms_count") and l.endswith("4") for l in lines)


class TestSpaceSaving:
    def test_exact_below_capacity(self):
        s = SpaceSaving(capacity=8)
        for i in range(5):
            s.offer(f"k{i}", i + 1)
        s.offer("k4", 10)
        top = dict((k, c) for k, c, _ in s.topk(8))
        assert top["k4"] == 15 and top["k0"] == 1
        assert all(e == 0 for _, _, e in s.topk(8))

    def test_eviction_overestimates_bounded(self):
        s = SpaceSaving(capacity=2)
        s.offer("a", 100)
        s.offer("b", 1)
        s.offer("c", 50)  # evicts b (floor 1): count 51, error 1
        top = {k: (c, e) for k, c, e in s.topk(3)}
        assert "b" not in top
        assert top["c"] == (51, 1)
        assert top["a"] == (100, 0)


class TestFlightRecorder:
    def test_spans_per_flush_and_counters(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("fr", count=1e9)])
        engine.submit_many([{"resource": "fr"} for _ in range(10)])
        engine.flush()
        spans = engine.telemetry.spans()
        assert spans, "flush must record a span"
        s = spans[-1]
        assert s.n_entries == 10 and s.rows == 10
        assert s.settled and not s.deferred
        assert s.encode_ms >= 0.0 and s.dispatch_ms >= 0.0
        c = engine.telemetry.counters_snapshot()
        assert c["flushes"] >= 1 and c["ops"] >= 10
        assert engine.telemetry.hist_flush.count >= 1
        assert engine.telemetry.hist_e2e.count >= 1

    def test_ring_is_bounded(self, manual_clock, engine):
        engine.telemetry = TelemetryBus(ring=4)
        st.flow_rule_manager.load_rules([st.FlowRule("rb", count=1e9)])
        for _ in range(7):
            engine.submit_entry("rb")
            engine.flush()
        tele = engine.telemetry
        assert len(tele.spans()) == 4
        assert tele.counters_snapshot()["flushes"] == 7

    def test_pipelined_spans_settle_lazily(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("pp", count=1e9)])
        engine.pipeline_depth = 2
        try:
            for _ in range(5):
                engine.submit_bulk("pp", 16)
                engine.flush()
            tele = engine.telemetry
            deferred = [s for s in tele.spans() if s.deferred]
            assert deferred, "depth-2 flushes must record deferred spans"
            assert any(not s.settled for s in deferred), (
                "a depth-2 pipeline keeps unsettled spans in flight"
            )
            engine.drain()
            assert all(s.settled for s in tele.spans())
            # Every span's occupancy sample is within the depth bound.
            assert all(0 <= s.inflight <= 2 for s in deferred)
            assert tele.counters_snapshot()["deferred_flushes"] >= 5
        finally:
            engine.pipeline_depth = 0

    def test_disabled_records_nothing(self, manual_clock):
        from sentinel_tpu.runtime.engine import Engine

        config.set(config.TELEMETRY_ENABLED, "false")
        try:
            eng = Engine()
            assert not eng.telemetry.enabled
            assert eng._blk_topk_k == 0  # kernel top-K fold compiled away
            eng.set_flow_rules([st.FlowRule("off", count=1)])
            for _ in range(3):
                eng.submit_entry("off")
                eng.flush()
            assert eng.telemetry.spans() == []
            assert eng.telemetry.counters_snapshot()["flushes"] == 0
            assert eng.telemetry.sketch.topk(5) == []
        finally:
            config.set(config.TELEMETRY_ENABLED, "true")

    def test_arena_counter_deltas(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("ar", count=1e9)])
        for _ in range(3):
            engine.submit_many([{"resource": "ar"} for _ in range(8)])
            engine.flush()
        spans = [s for s in engine.telemetry.spans() if s.n_entries == 8]
        # Steady state: the repeated shape is served from the pool.
        assert spans[-1].arena_hits > 0
        c = engine.telemetry.counters_snapshot()
        assert c["arena_hits"] + c["arena_misses"] > 0


class TestBlockedSketch:
    def test_topk_matches_exact_recount(self, manual_clock, engine):
        """Differential: the device sketch must equal a host-side exact
        recount of blocked acquire weight per resource."""
        st.flow_rule_manager.load_rules(
            [
                st.FlowRule("s_hot", count=1),
                st.FlowRule("s_warm", count=3),
                st.FlowRule("s_free", count=1e9),
            ]
        )
        manual_clock.set_ms(100)
        reqs = (
            [{"resource": "s_hot", "ts": 100} for _ in range(6)]
            + [{"resource": "s_warm", "ts": 100} for _ in range(5)]
            + [{"resource": "s_free", "ts": 100} for _ in range(4)]
        )
        ops = engine.submit_many(reqs)
        engine.flush()
        exact = {}
        for op, req in zip(ops, reqs):
            v = op.verdict
            assert v is not None
            if not v.admitted:
                exact[req["resource"]] = exact.get(req["resource"], 0) + 1
        assert exact, "test must actually block something"
        got = dict(engine.telemetry.last_blocked_topk)
        assert got == exact
        # The running sketch agrees too (single flush, no merging yet).
        sk = {k: c for k, c, _ in engine.telemetry.sketch.topk(8)}
        for k, w in exact.items():
            assert sk[k] == w

    def test_host_recount_fallback_matches_device_fold(self, manual_clock, engine):
        """Flush paths without the kernel fold (the sharded mesh flush)
        feed the sketch via a host-side recount of the filled verdicts
        — it must agree with what the device fold produced for the same
        chunk."""
        st.flow_rule_manager.load_rules([st.FlowRule("hr", count=2)])
        manual_clock.set_ms(100)
        ops = engine.submit_many(
            [{"resource": "hr", "ts": 100} for _ in range(6)]
        )
        engine.flush()
        device_topk = list(engine.telemetry.last_blocked_topk)
        assert device_topk  # the kernel fold saw the blocks
        engine.telemetry.last_blocked_topk = []
        engine._fold_blocked_recount([op for op in ops if op is not None], [])
        assert engine.telemetry.last_blocked_topk == device_topk

    @pytest.mark.mesh
    def test_mesh_flush_feeds_sketch(self, manual_clock, engine):
        """The sharded path has no device fold; the host recount must
        still populate the sketch."""
        st.flow_rule_manager.load_rules([st.FlowRule("ms", count=4)])
        engine.enable_mesh(8)
        try:
            manual_clock.set_ms(100)
            g = engine.submit_bulk("ms", 64, ts=100)
            engine.flush()
            blocked = int((~g.admitted).sum())
            assert blocked > 0
            assert dict(engine.telemetry.last_blocked_topk)["ms"] == blocked
        finally:
            engine.disable_mesh()

    def test_bulk_acquire_weights(self, manual_clock, engine):
        """Weighted recount through the bulk path: blocked weight is the
        acquire sum, not the op count."""
        st.flow_rule_manager.load_rules([st.FlowRule("s_bulk", count=5)])
        manual_clock.set_ms(100)
        acquire = np.array([2, 2, 2, 3, 4], dtype=np.int32)
        g = engine.submit_bulk("s_bulk", 5, ts=100, acquire=acquire)
        engine.flush()
        blocked_w = int(acquire[~g.admitted].sum())
        assert blocked_w > 0
        assert dict(engine.telemetry.last_blocked_topk)["s_bulk"] == blocked_w


class TestInternCacheCounters:
    def _param_engine(self, engine):
        st.flow_rule_manager.load_rules([st.FlowRule("ic", count=1e9)])
        engine.set_param_rules(
            {"ic": [ParamFlowRule("ic", param_idx=0, count=1e9)]}
        )

    def test_bulk_hits_misses_and_reload_reset(self, manual_clock, engine):
        self._param_engine(engine)
        col = [f"ip{i % 4}" for i in range(16)]
        engine.submit_bulk("ic", 16, ts=100, args_column=[(v,) for v in col])
        engine.flush()
        stats1 = engine.param_index.cache_stats()
        assert stats1["misses"] == 4  # 4 distinct values resolve once
        engine.submit_bulk("ic", 16, ts=200, args_column=[(v,) for v in col])
        engine.flush()
        stats2 = engine.param_index.cache_stats()
        assert stats2["hits"] >= 16  # second window: all values cached
        assert stats2["misses"] == 4
        # Span attribution: the flush that drained the submissions
        # carries the intern delta.
        span = engine.telemetry.spans()[-1]
        assert span.intern_hits >= 16
        # Reload invalidates the cache wholesale — counters reset.
        self._param_engine(engine)
        stats3 = engine.param_index.cache_stats()
        assert stats3 == {"hits": 0, "misses": 0, "evictions": 0, "interned": 0}
        # Telemetry snapshot surfaces the live (post-reload) counters.
        snap = engine.telemetry.snapshot(engine)
        assert snap["param_cache"]["hits"] == 0


class TestExports:
    def test_prometheus_engine_series(self, manual_clock, engine):
        from sentinel_tpu.transport.prometheus import render_metrics

        st.flow_rule_manager.load_rules([st.FlowRule("pm", count=1)])
        manual_clock.set_ms(50)
        for _ in range(3):
            st.try_entry("pm")
        text = render_metrics(engine)
        for needle in (
            "sentinel_engine_flush_duration_ms_bucket",
            "sentinel_engine_drain_duration_ms_bucket",
            "sentinel_engine_e2e_duration_ms_bucket",
            "sentinel_engine_pipeline_occupancy",
            "sentinel_engine_pipeline_mean_inflight",
            "sentinel_engine_last_flush_encode_ms",
            "sentinel_engine_last_flush_dispatch_ms",
            "sentinel_engine_flushes_total",
            "sentinel_engine_coalesced_fallback_total",
            "sentinel_engine_param_cache_hits_total",
        ):
            assert needle in text, needle
        assert 'sentinel_engine_blocked_weight{resource="pm"}' in text
        # The flush histogram actually accumulated observations.
        count_line = [
            l for l in text.splitlines()
            if l.startswith("sentinel_engine_flush_duration_ms_count")
        ][0]
        assert int(count_line.rsplit(" ", 1)[1]) >= 1

    def test_pipeline_occupancy_gauge(self, manual_clock, engine):
        from sentinel_tpu.transport.prometheus import engine_telemetry_lines

        st.flow_rule_manager.load_rules([st.FlowRule("po", count=1e9)])
        engine.pipeline_depth = 2
        try:
            engine.pipeline_stats(reset=True)
            for _ in range(8):
                engine.submit_bulk("po", 8)
                engine.flush()
            lines = engine_telemetry_lines(engine)
        finally:
            engine.pipeline_depth = 0
            engine.drain()
        occ = [
            float(l.rsplit(" ", 1)[1])
            for l in lines
            if l.startswith("sentinel_engine_pipeline_occupancy ")
        ][0]
        assert 0.0 < occ <= 1.0

    def test_telemetry_command(self, manual_clock, engine):
        from sentinel_tpu.transport import handlers
        from sentinel_tpu.transport.command_center import CommandRequest

        st.flow_rule_manager.load_rules([st.FlowRule("tc", count=1)])
        for _ in range(3):
            st.try_entry("tc")
        resp = handlers.telemetry_handler(
            CommandRequest(path="telemetry", params={"spans": "2"}, body="")
        )
        assert resp.success
        d = json.loads(resp.result)
        assert d["enabled"] is True
        assert d["counters"]["flushes"] >= 3
        assert d["flush_ms"]["count"] >= 3
        assert len(d["spans"]) == 2
        assert {"resource": "tc", "weight": 1} in d["last_flush_blocked_topk"]
        assert d["pipeline_depth"] == 0
        bad = handlers.telemetry_handler(
            CommandRequest(path="telemetry", params={"spans": "x"}, body="")
        )
        assert not bad.success

    def test_metric_log_engine_rollin(self, manual_clock, engine, tmp_path):
        st.flow_rule_manager.load_rules([st.FlowRule("ml", count=1e9)])
        for sec in range(2):
            for i in range(4):
                manual_clock.set_ms(sec * 1000 + i * 10)
                with st.entry("ml"):
                    pass
        manual_clock.set_ms(2500)
        timer = MetricTimer(
            engine, writer=MetricWriter(base_dir=str(tmp_path), app_name="tele")
        )
        lines = timer.run_once()
        eng_lines = [l for l in lines if l.resource == "__engine__"]
        assert len(eng_lines) == 2  # seconds 0 and 1
        # entry() flushes per call: >= 4 flushes and >= 4 ops per second
        # (exits flush too).
        assert all(l.pass_qps >= 4 for l in eng_lines)
        assert all(l.success_qps >= 4 for l in eng_lines)
        # Sorted into the per-second stream, parseable from disk.
        ts = [l.timestamp for l in lines]
        assert ts == sorted(ts)


@pytest.mark.slow
class TestOverhead:
    def test_enabled_within_2pct_of_disabled(self, manual_clock):
        """Recorder overhead contract: the telemetry-enabled engine
        stays within 2% of telemetry-disabled on the deferred-mode
        loop (median of repeats; slow tier — wall-clock sensitive)."""
        from sentinel_tpu.runtime.engine import Engine

        def run(enabled: bool) -> float:
            config.set(config.TELEMETRY_ENABLED, "true" if enabled else "false")
            try:
                eng = Engine()
                eng.set_flow_rules([st.FlowRule("ov", count=1e9)])
                reqs = [{"resource": "ov", "ts": 100} for _ in range(2048)]
                eng.submit_many(reqs)
                eng.flush()  # warm-up/compile
                best = float("inf")
                for _ in range(5):
                    t0 = time.perf_counter()
                    for _ in range(10):
                        eng.submit_many(reqs)
                        eng.flush()
                    best = min(best, time.perf_counter() - t0)
                return best
            finally:
                config.set(config.TELEMETRY_ENABLED, "true")

        t_off = run(False)
        t_on = run(True)
        assert t_on <= t_off * 1.02 + 0.01, (t_on, t_off)
