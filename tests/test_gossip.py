"""Sketch gossip (PR 17) — frame, merge-bound and fleet-promotion pins.

The acceptance surface: the SKETCH_PUSH/SKETCH_MERGED frame pair
round-trips exactly; merged estimates obey the count-min merge bounds
(>= every per-engine estimate, == the sum on collision-free keys —
pinned against a numpy twin); a key spread thin across 3 engines (each
below the promote threshold, fleet-wide above) promotes ONLY with
gossip on — gossip off is bit-identical per-engine behavior; remote
views decay on the local window clock and silent origins expire; and a
foreign gossip version degrades to an empty merged frame, never a
connection drop.
"""

from __future__ import annotations

import socket
import struct

import numpy as np
import pytest

from sentinel_tpu.cluster import protocol
from sentinel_tpu.cluster.gossip import (
    GossipAgent,
    gossip_stats,
    parse_peers,
)
from sentinel_tpu.models import constants as C
from sentinel_tpu.runtime.sketch import (
    SketchTier,
    _KIND_VALUE,
    _SEP,
    _hash_np,
    cm_estimate,
    key_id,
)
from sentinel_tpu.utils.config import SentinelConfig, config


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


@pytest.fixture(autouse=True)
def _gossip_stats_reset():
    gossip_stats.reset()
    yield
    gossip_stats.reset()


class _FakeTelemetry:
    enabled = False


class _FakeEngine:
    telemetry = _FakeTelemetry()


def _tier(gossip=True, width=4096, **keys):
    config.set(SentinelConfig.SKETCH_ENABLED, "true")
    config.set(SentinelConfig.GOSSIP_ENABLED, "true" if gossip else "false")
    config.set(SentinelConfig.SKETCH_WIDTH, str(width))
    for k, v in keys.items():
        config.set(getattr(SentinelConfig, k), str(v))
    return SketchTier(_FakeEngine())


def _feed(tier, key, count):
    """Count one key directly into the tier's host twin + mirror (the
    unit seam _collect feeds in production)."""
    kid = key_id(key)
    ids = np.array([kid], dtype=np.int64)
    for di in range(tier.depth):
        tier._host_cm[di, _hash_np(ids, di, tier.width)[0]] += count
    tier.host_mirror.offer(key, count)


def _vkey(v):
    return _KIND_VALUE + "res" + _SEP + v


class TestFramePair:
    def test_roundtrip(self):
        cm = np.arange(12, dtype="<i4").reshape(3, 4)
        cands = [("a" * 3, 7), ("k\x1fv", 2 ** 40), ("", 1)]
        frame = protocol.pack_sketch_frame(
            42, C.MSG_TYPE_SKETCH_PUSH, "host:1:2", 99, 3, 4,
            cm.tobytes(), cands,
        )
        # Length framing holds.
        (length,) = struct.unpack_from("<I", frame, 0)
        assert length == len(frame) - 4
        out = protocol.unpack_sketch_frame(frame[4:])
        xid, mt, origin, wid, depth, width, cm_bytes, rcands = out
        assert (xid, mt, origin, wid, depth, width) == (
            42, C.MSG_TYPE_SKETCH_PUSH, "host:1:2", 99, 3, 4,
        )
        assert np.array_equal(
            np.frombuffer(cm_bytes, dtype="<i4").reshape(3, 4), cm
        )
        assert rcands == cands

    def test_empty_frame_shape(self):
        frame = protocol.pack_sketch_frame(
            1, C.MSG_TYPE_SKETCH_MERGED, "o", 0, 0, 0, b""
        )
        out = protocol.unpack_sketch_frame(frame[4:])
        assert out[4] == 0 and out[6] == b"" and out[7] == []

    def test_foreign_version_raises_typed(self):
        frame = bytearray(
            protocol.pack_sketch_frame(
                7, C.MSG_TYPE_SKETCH_PUSH, "o", 0, 0, 0, b""
            )
        )
        frame[4 + 4 + 1] = protocol.GOSSIP_VERSION + 1  # version byte
        with pytest.raises(protocol.UnsupportedBatchVersion) as ei:
            protocol.unpack_sketch_frame(bytes(frame[4:]))
        assert ei.value.xid == 7

    def test_trailing_garbage_raises(self):
        frame = protocol.pack_sketch_frame(
            1, C.MSG_TYPE_SKETCH_PUSH, "o", 0, 0, 0, b""
        )
        with pytest.raises(ValueError):
            protocol.unpack_sketch_frame(frame[4:] + b"junk")


class TestMergeBounds:
    def test_merged_estimates_pinned_vs_numpy_twin(self):
        """On collision-free keys (few keys, wide sketch) the merged
        estimate equals the vector-sum twin exactly — which implies
        both count-min merge bounds: >= max(per-engine), <= sum(
        per-engine)."""
        a = _tier()
        b = _tier()
        keys = [_vkey("k%d" % i) for i in range(8)]
        counts_a = [3 * i + 1 for i in range(8)]
        counts_b = [50 - 4 * i for i in range(8)]
        for k, ca, cb in zip(keys, counts_a, counts_b):
            _feed(a, k, ca)
            _feed(b, k, cb)
        wid, cm_b, cands_b = b.gossip_snapshot()
        assert a.merge_remote("B", wid, cm_b, cands_b)
        fleet = a._fleet_by_key({k: c for k, c in zip(keys, counts_a)})
        ids = np.array([key_id(k) for k in keys], dtype=np.int64)
        twin = cm_estimate(
            a._host_cm.astype(np.int64) + cm_b.astype(np.int64), ids
        )
        for k, ca, cb, tw in zip(keys, counts_a, counts_b, twin.tolist()):
            assert fleet[k] == tw == ca + cb
            assert fleet[k] >= max(ca, cb)
            assert fleet[k] <= ca + cb

    def test_merge_is_snapshot_replace_not_accumulate(self):
        """Re-merging the same origin's frame N times must not
        N-count its traffic (frames carry full decayed views)."""
        a, b = _tier(), _tier()
        _feed(b, _vkey("x"), 40)
        wid, cm_b, cands_b = b.gossip_snapshot()
        for _ in range(5):
            assert a.merge_remote("B", wid, cm_b, cands_b)
        assert a._fleet_by_key({})[_vkey("x")] == 40

    def test_geometry_mismatch_dropped(self):
        a = _tier()
        alien = np.ones((a.depth + 1, a.width), dtype=np.int32)
        assert not a.merge_remote("B", 0, alien, [])
        assert a._remote == {}

    def test_gossip_off_fleet_view_is_identity(self):
        t = _tier(gossip=False)
        assert t._host_cm is None  # not even armed
        by_key = {_vkey("x"): 3}
        assert t._fleet_by_key(by_key) is by_key
        assert not t.merge_remote("B", 0, np.zeros((4, 4096)), [])


class TestFleetPromotion:
    PROMOTE_QPS = 100.0  # threshold = 1.5 * 100 * 1s = 150

    def _tiers(self, gossip):
        return [
            _tier(
                gossip=gossip,
                SKETCH_PROMOTE_QPS=self.PROMOTE_QPS,
                SKETCH_WINDOW_MS=1000,
            )
            for _ in range(3)
        ]

    def test_thin_spread_key_promotes_only_with_gossip(self):
        """THE differential: 60/engine across 3 engines (< 150
        threshold each, 180 fleet-wide) promotes on EVERY engine with
        gossip on, on NO engine with gossip off."""
        key = _vkey("hot")

        def drive(gossip):
            tiers = self._tiers(gossip)
            agents = []
            if gossip:
                for i, t in enumerate(tiers):
                    _feed(t, key, 60)
                    agents.append(
                        GossipAgent(
                            t, origin="E%d" % i, port=0, peers=[]
                        ).start()
                    )
                for i, ga in enumerate(agents):
                    ga.peers = [
                        ("127.0.0.1", agents[j].port)
                        for j in range(3) if j != i
                    ]
                # Bounded rounds: ONE round per engine suffices for
                # full pairwise exchange.
                for ga in agents:
                    assert ga.run_round() == 2
            promoted = []
            for t in tiers:
                t._evaluate({key: 60}, now_ms=5000)
                promoted.append("hot" in t.promoted_values.get("res", ()))
            for ga in agents:
                ga.stop()
            return promoted

        assert drive(gossip=True) == [True, True, True]
        assert drive(gossip=False) == [False, False, False]

    def test_remote_only_key_still_promotes(self):
        """A key the local engine never saw in ITS candidate table
        (arrives only via remote candidates) is evaluated — the key
        universe is local ∪ remote."""
        tiers = self._tiers(gossip=True)
        key = _vkey("elsewhere")
        # Engines 1 and 2 see it at 90 each; engine 0 never does.
        for t in tiers[1:]:
            _feed(t, key, 90)
        agents = [
            GossipAgent(t, origin="E%d" % i, port=0, peers=[]).start()
            for i, t in enumerate(tiers)
        ]
        agents[0].peers = [
            ("127.0.0.1", agents[1].port), ("127.0.0.1", agents[2].port)
        ]
        assert agents[0].run_round() == 2
        tiers[0]._evaluate({}, now_ms=5000)
        assert "elsewhere" in tiers[0].promoted_values.get("res", ())
        for ga in agents:
            ga.stop()


class TestDecayAndExpiry:
    def test_remote_views_decay_on_local_clock(self):
        a, b = _tier(), _tier()
        _feed(b, _vkey("x"), 64)
        wid, cm_b, cands_b = b.gossip_snapshot()
        assert a.merge_remote("B", wid, cm_b, cands_b)
        a.decay_due(1000)  # arms the clock
        a.decay_due(2000)  # first real decay: halves local AND remote
        assert a._remote["B"][0].max() == 32
        assert a._remote["B"][1][_vkey("x")] == 32
        assert a._fleet_by_key({})[_vkey("x")] == 32

    def test_stale_origin_expires(self):
        a, b = _tier(GOSSIP_STALE_WINDOWS=2), _tier()
        _feed(b, _vkey("x"), 64)
        a.decay_due(1000)
        assert a.merge_remote("B", *b.gossip_snapshot())
        for w in range(2, 6):
            a.decay_due(w * 1000)
        assert "B" not in a._remote
        assert a._fleet_by_key({_vkey("y"): 1}) == {_vkey("y"): 1}

    def test_reset_clears_remote_state(self):
        a, b = _tier(), _tier()
        _feed(b, _vkey("x"), 8)
        a.merge_remote("B", *b.gossip_snapshot())
        assert a._remote
        a.reset()
        assert a._remote == {} and a.gossip_merges == 0


class TestAgentWire:
    def test_one_round_exchanges_both_directions(self):
        a, b = _tier(), _tier()
        _feed(a, _vkey("ka"), 11)
        _feed(b, _vkey("kb"), 22)
        ga = GossipAgent(a, origin="A", port=0, peers=[]).start()
        gb = GossipAgent(b, origin="B", port=0, peers=[]).start()
        ga.peers = [("127.0.0.1", gb.port)]
        try:
            assert ga.run_round() == 1
            # One round trip: B holds A's view AND A holds B's.
            assert sorted(a._remote) == ["B"]
            assert sorted(b._remote) == ["A"]
            assert a._fleet_by_key({})[_vkey("kb")] == 22
            assert b._fleet_by_key({})[_vkey("ka")] == 11
            snap = gossip_stats.snapshot()
            assert snap["merges"] == 2 and snap["errors"] == 0
        finally:
            ga.stop()
            gb.stop()

    def test_dead_peer_costs_one_error_not_a_wedge(self):
        a = _tier()
        ga = GossipAgent(
            a, origin="A", port=0,
            peers=[("127.0.0.1", 1)],  # nothing listens there
            timeout_sec=0.3,
        ).start()
        try:
            assert ga.run_round() == 0
            assert gossip_stats.snapshot()["errors"] == 1
            assert a._remote == {}
        finally:
            ga.stop()

    def test_foreign_version_gets_empty_merged_frame(self):
        """A pusher speaking a future GOSSIP_VERSION receives an EMPTY
        merged frame (honest degrade) and the tier stays untouched."""
        a = _tier()
        ga = GossipAgent(a, origin="A", port=0, peers=[]).start()
        try:
            frame = bytearray(
                protocol.pack_sketch_frame(
                    9, C.MSG_TYPE_SKETCH_PUSH, "alien", 0,
                    a.depth, a.width,
                    np.ones((a.depth, a.width), dtype="<i4").tobytes(),
                    [(_vkey("x"), 5)],
                )
            )
            frame[4 + 4 + 1] = protocol.GOSSIP_VERSION + 1
            with socket.create_connection(("127.0.0.1", ga.port), 2.0) as s:
                s.sendall(bytes(frame))
                payload = protocol.read_frame(s)
            out = protocol.unpack_sketch_frame(payload)
            assert out[0] == 9  # xid echoed
            assert out[1] == C.MSG_TYPE_SKETCH_MERGED
            assert out[4] == 0  # empty: nothing mergeable
            assert a._remote == {}
            assert gossip_stats.snapshot()["version_rejects"] == 1
        finally:
            ga.stop()

    def test_parse_peers_skips_garbage(self):
        assert parse_peers("h1:70, h2:71 ,bad,:9,h3:x,") == [
            ("h1", 70), ("h2", 71)
        ]


class TestEngineIntegration:
    def test_engine_arms_and_stops_gossip(self, manual_clock):
        from sentinel_tpu.runtime.engine import Engine

        config.set(SentinelConfig.SKETCH_ENABLED, "true")
        config.set(SentinelConfig.GOSSIP_ENABLED, "true")
        eng = Engine(clock=manual_clock)
        try:
            assert eng.gossip is not None
            assert eng.gossip.port > 0
            assert eng.sketch.gossip_armed
            assert eng.sketch._host_cm is not None
        finally:
            eng.close()
        assert eng.gossip._server is None  # listener stopped

    def test_engine_default_has_no_gossip(self, manual_clock):
        from sentinel_tpu.runtime.engine import Engine

        eng = Engine(clock=manual_clock)
        try:
            assert eng.gossip is None
        finally:
            eng.close()

    def test_prometheus_remote_origins_is_a_count(self, manual_clock):
        """gossip_info carries origin NAMES; the /metrics gauge must
        render their COUNT — a held remote view once rendered the
        Python list repr straight into the exposition line."""
        from sentinel_tpu.runtime.engine import Engine
        from sentinel_tpu.transport.prometheus import engine_telemetry_lines

        config.set(SentinelConfig.SKETCH_ENABLED, "true")
        config.set(SentinelConfig.GOSSIP_ENABLED, "true")
        eng = Engine(clock=manual_clock)
        try:
            tier = eng.sketch
            cm = np.ones_like(tier._host_cm)
            assert tier.merge_remote("peerX", 1, cm, [("\x01k", 5)])
            lines = [
                ln for ln in engine_telemetry_lines(eng)
                if ln.startswith("sentinel_engine_gossip_remote_origins ")
            ]
            assert lines == ["sentinel_engine_gossip_remote_origins 1"]
        finally:
            eng.close()
