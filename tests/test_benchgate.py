"""tools/benchgate.py — the bench regression gate on synthetic pairs
(ISSUE 8 satellite): same-hardware baselines compare with per-metric
tolerance bands, regressed stages fail with non-zero exit, and
hardware/jax mismatches skip with a reason instead of comparing apples
to TPUs."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import benchgate  # noqa: E402


def _rec(**over):
    base = {
        "metric": "batched_entry_checks_per_sec_per_chip",
        "value": 400_000.0,
        "unit": "entries/sec",
        "platform": "cpu",
        "device_kind": "cpu",
        "jax_version": "0.4.37",
        "n_rules": 131072,
        "n_entries": 32768,
        "flush_ms": 80.0,
        "mixed_checks_per_sec": 240_000.0,
        "mixed_flush_ms": 34.0,
        "mixed_n_rules": 16384,
        "mixed_n_entries": 8192,
        "engine_n_rules": 1024,
        "engine_n_ops": 8192,
        "engine_ops_per_sec": 78_000.0,
        "engine_bulk_ops_per_sec": 400_000.0,
        "engine_pipelined_ops_per_sec": 360_000.0,
        "engine_sync_latency_ms": 2.5,
        "spec_entry_p50_us": 20.0,
        "spec_entry_p99_us": 60.0,
        "shed_entry_p50_us": 25.0,
        "shed_entry_p99_us": 80.0,
    }
    base.update(over)
    return base


class TestCompare:
    def test_identical_runs_pass(self):
        regressions, compared, skipped = benchgate.compare(_rec(), _rec())
        assert regressions == []
        assert len(compared) >= 10
        assert skipped == []

    def test_box_noise_within_band_passes(self):
        """The observed back-to-back tenancy noise of the CPU dev box
        (PR-8 runs: throughput 1.8x swings, sync latency 2.7x, p99s
        5x) must NOT trip the gate — bands are sized from it."""
        fresh = _rec(
            value=400_000.0 * 0.64,              # worst throughput swing
            engine_sync_latency_ms=2.5 * 2.73,   # worst mean-latency swing
            spec_entry_p99_us=60.0 * 5.26,       # worst p99 swing
            shed_entry_p99_us=80.0 * 3.03,
        )
        regressions, _compared, _ = benchgate.compare(fresh, _rec())
        assert regressions == []

    def test_throughput_regression_fails(self):
        fresh = _rec(engine_ops_per_sec=78_000.0 * 0.3)
        regressions, _c, _s = benchgate.compare(fresh, _rec())
        assert len(regressions) == 1
        assert "engine_ops_per_sec" in regressions[0]

    def test_latency_regression_fails_and_improvement_passes(self):
        worse = _rec(engine_sync_latency_ms=2.5 * 4.0)
        regressions, _c, _s = benchgate.compare(worse, _rec())
        assert any("engine_sync_latency_ms" in r for r in regressions)
        better = _rec(engine_sync_latency_ms=0.5)
        regressions, _c, _s = benchgate.compare(better, _rec())
        assert regressions == []

    def test_stage_context_mismatch_skips_not_fails(self):
        """A budget-truncated ladder (different rung) must not read as
        a perf change."""
        fresh = _rec(n_rules=16384, n_entries=16384, value=100_000.0)
        regressions, compared, skipped = benchgate.compare(fresh, _rec())
        assert regressions == []
        assert any("value" in s for s in skipped)
        # Other stages (matching context) still compared.
        assert any("engine_ops_per_sec" in c for c in compared)

    def test_missing_stage_is_silently_not_comparable(self):
        fresh = _rec()
        for k in ("mixed_checks_per_sec", "mixed_flush_ms"):
            fresh.pop(k)
        regressions, compared, skipped = benchgate.compare(fresh, _rec())
        assert regressions == [] and skipped == []
        assert not any("mixed_checks_per_sec" in c for c in compared)

    def test_tolerance_scale_widens_and_tightens_bands(self):
        fresh = _rec(engine_ops_per_sec=78_000.0 * 0.3)  # -70%
        regressions, _c, _s = benchgate.compare(fresh, _rec())
        assert regressions
        regressions, _c, _s = benchgate.compare(
            fresh, _rec(), tolerance_scale=2.0
        )
        assert regressions == []
        # Steady-hardware mode: a tightened gate catches what the CPU
        # bands deliberately tolerate.
        mild = _rec(engine_ops_per_sec=78_000.0 * 0.7)
        regressions, _c, _s = benchgate.compare(mild, _rec())
        assert regressions == []
        regressions, _c, _s = benchgate.compare(
            mild, _rec(), tolerance_scale=0.2
        )
        assert any("engine_ops_per_sec" in r for r in regressions)


class TestBaselineSelection:
    def test_newest_matching_baseline_wins(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(_rec(engine_ops_per_sec=10.0))
        )
        (tmp_path / "BENCH_r02.json").write_text(
            json.dumps({"parsed": _rec(engine_ops_per_sec=20.0)})
        )
        path, rec, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37"
        )
        assert path.endswith("BENCH_r02.json") and reason == ""
        assert rec["engine_ops_per_sec"] == 20.0  # wrapper unwrapped

    def test_hardware_mismatch_skips_with_reason(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(_rec()))
        path, rec, reason = benchgate.find_baseline(
            str(tmp_path), "TPU v4", "0.4.37"
        )
        assert path is None and rec is None
        assert "TPU v4" in reason

    def test_pre_header_baseline_never_matches(self, tmp_path):
        old = _rec()
        del old["device_kind"], old["jax_version"]
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(old))
        path, _rec2, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37"
        )
        assert path is None and "no baseline" in reason

    def test_no_baselines_at_all(self, tmp_path):
        path, _r, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37"
        )
        assert path is None and "no BENCH_*.json" in reason


class TestGate:
    def test_gate_passes_and_fails(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(_rec()))
        assert benchgate.gate(_rec(), str(tmp_path)) == 0
        out = capsys.readouterr().out
        assert "benchgate OK" in out
        fresh = _rec(value=400_000.0 * 0.2, flush_ms=80.0 * 8)
        assert benchgate.gate(fresh, str(tmp_path)) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "value" in out

    def test_gate_skips_without_comparable_baseline(self, tmp_path, capsys):
        assert benchgate.gate(_rec(), str(tmp_path)) == 0
        assert "SKIP" in capsys.readouterr().out

    def test_gate_fails_on_error_record(self, tmp_path):
        assert benchgate.gate({"error": "no stage"}, str(tmp_path)) == 1

    def test_explicit_baseline_honors_hardware_header(self, tmp_path, capsys):
        base = tmp_path / "BENCH_tpu.json"
        base.write_text(json.dumps(_rec(device_kind="TPU v4")))
        assert benchgate.gate(_rec(), str(tmp_path), str(base)) == 0
        assert "SKIP" in capsys.readouterr().out

    def test_cli_roundtrip(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(_rec()))
        fresh_path = tmp_path / "fresh.json"
        fresh_path.write_text(json.dumps(_rec(engine_ops_per_sec=1.0)))
        old = sys.argv
        try:
            sys.argv = [
                "benchgate.py", "--fresh", str(fresh_path),
                "--repo-root", str(tmp_path),
            ]
            assert benchgate.main() == 1
            sys.argv = ["benchgate.py", "--fresh", str(tmp_path / "nope.json"),
                        "--repo-root", str(tmp_path)]
            assert benchgate.main() == 2
        finally:
            sys.argv = old

    def test_every_declared_metric_has_a_direction_and_band(self):
        for m, (direction, band) in benchgate.STAGE_METRICS.items():
            assert direction in ("higher", "lower"), m
            assert 0.0 < band <= 5.0, m
        grouped = {m for _ctx, ms in benchgate.STAGE_CONTEXT for m in ms}
        assert grouped == set(benchgate.STAGE_METRICS), (
            "every gated metric must belong to exactly one stage-context "
            "group"
        )


class TestHostIdentityToken:
    """The measured host-speed token (ISSUE 14 satellite): same
    device_kind+jax_version on a different-speed box must SKIP with a
    reason, never gate red — the r09→r10 re-anchor hole."""

    def _write(self, tmp_path, name, rec):
        (tmp_path / name).write_text(json.dumps(rec))

    def test_same_token_matches(self, tmp_path):
        rec = _rec(host_cpu_count=1, host_spin_ms=10.0)
        self._write(tmp_path, "BENCH_r01.json", rec)
        path, found, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37", fresh=rec
        )
        assert found is not None and reason == ""

    def test_spin_mismatch_skips_with_reason(self, tmp_path):
        self._write(
            tmp_path, "BENCH_r01.json",
            _rec(host_cpu_count=1, host_spin_ms=10.0),
        )
        fresh = _rec(host_cpu_count=1, host_spin_ms=52.0)  # ~5x slower box
        path, found, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37", fresh=fresh
        )
        assert found is None
        assert "host-identity token" in reason
        assert benchgate.gate(fresh, str(tmp_path)) == 0  # SKIP, not red

    def test_cpu_count_mismatch_skips(self, tmp_path):
        self._write(
            tmp_path, "BENCH_r01.json",
            _rec(host_cpu_count=8, host_spin_ms=10.0),
        )
        fresh = _rec(host_cpu_count=1, host_spin_ms=10.0)
        _path, found, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37", fresh=fresh
        )
        assert found is None and "cpu count" in reason

    def test_pre_token_baseline_still_matches(self, tmp_path):
        """Records predating the token (r10 and earlier) keep matching
        on the hardware header alone — the token narrows going
        forward, it does not orphan the committed trajectory."""
        self._write(tmp_path, "BENCH_r01.json", _rec())  # no token
        fresh = _rec(host_cpu_count=1, host_spin_ms=52.0)
        _path, found, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37", fresh=fresh
        )
        assert found is not None and reason == ""

    def test_explicit_baseline_honors_token(self, tmp_path, capsys):
        base = tmp_path / "BENCH_base.json"
        base.write_text(
            json.dumps(_rec(host_cpu_count=1, host_spin_ms=10.0))
        )
        fresh = _rec(host_cpu_count=1, host_spin_ms=52.0)
        rc = benchgate.gate(
            fresh, str(tmp_path), baseline_path=str(base)
        )
        assert rc == 0
        assert "different box" in capsys.readouterr().out

    def test_within_band_noise_still_matches(self, tmp_path):
        rec = _rec(host_cpu_count=1, host_spin_ms=10.0)
        self._write(tmp_path, "BENCH_r01.json", rec)
        fresh = _rec(host_cpu_count=1, host_spin_ms=18.0)  # 1.8x: noise
        _path, found, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37", fresh=fresh
        )
        assert found is not None

    def test_no_fallback_to_pre_token_behind_a_mismatch(self, tmp_path):
        """Once a NEWER same-header baseline's token says 'different
        box', older token-less records must not re-open the cross-box
        comparison — the scan refuses them too."""
        self._write(tmp_path, "BENCH_r01.json", _rec())  # pre-token
        self._write(
            tmp_path, "BENCH_r02.json",
            _rec(host_cpu_count=1, host_spin_ms=10.0),
        )
        fresh = _rec(host_cpu_count=1, host_spin_ms=52.0)
        _path, found, reason = benchgate.find_baseline(
            str(tmp_path), "cpu", "0.4.37", fresh=fresh
        )
        assert found is None
        assert "pre-token record behind a token mismatch" in reason
