"""Chaos tests for the cluster TCP plane.

Reference behaviors under test: the token client survives a token
server restart mid-load — scheduled reconnect
(NettyTransportClient.java:114-166) with FAIL→fallback-to-local
admissions during the outage (FlowRuleChecker.fallbackToLocalOrPass) —
and both sides survive torn/garbage frames on connections that were
previously healthy (LengthFieldBasedFrameDecoder drop semantics).
"""

import socket
import struct
import threading
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import (
    ClusterStateManager,
    DefaultTokenService,
    EmbeddedClusterTokenServerProvider,
    TokenClientProvider,
    cluster_flow_rule_manager,
    cluster_server_config_manager,
)
from sentinel_tpu.cluster import protocol
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule
from sentinel_tpu.utils.clock import ManualClock


def cluster_rule(resource, count, flow_id, fallback=True):
    return FlowRule(
        resource,
        count=count,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id, fallback_to_local_when_fail=fallback
        ),
    )


@pytest.fixture()
def cluster_env():
    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=30000.0
    )
    yield
    cluster_flow_rule_manager.clear()
    ClusterStateManager.stop()
    TokenClientProvider.clear()
    EmbeddedClusterTokenServerProvider.clear()


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class TestServerRestartUnderLoad:
    def test_outage_falls_back_then_reconverges(self, cluster_env, manual_clock, engine):
        """Kill the token server mid-load: admissions fall back to the
        LOCAL window during the outage; after a restart on the same
        port the client reconnects and the server grants again."""
        rule = cluster_rule("svc", 50, flow_id=700)
        cluster_flow_rule_manager.load_rules("default", [rule])
        service1 = DefaultTokenService(clock=ManualClock(0))
        # Compile the decision kernel before the 0.5s-timeout wire
        # traffic: conftest's periodic jax.clear_caches() can land
        # right before this test, and the ~1s cold compile would make
        # phase 1's first RPC time out into a local-window grant.
        # acquire=0 charges nothing, so granted_on_server stays exact.
        service1.request_tokens([(700, 0, False)])
        server = SentinelTokenServer(port=0, service=service1).start()
        port = server.port
        client = ClusterTokenClient(
            "127.0.0.1", port, request_timeout_sec=0.5,
            reconnect_interval_sec=0.05,
        ).start()
        TokenClientProvider.register(client)
        ClusterStateManager.set_to_client()
        st.flow_rule_manager.load_rules([rule])

        # Phase 1: server up — grants are token-server grants.
        assert sum(st.try_entry("svc") is not None for _ in range(10)) == 10
        granted_on_server = sum(
            f["currentQps"] for f in service1.flow_stats() if f["flowId"] == 700
        )
        assert granted_on_server == 10

        # Phase 2: outage — the server dies mid-load. FAILed token RPCs
        # fall back to the LOCAL window, which still enforces the rule.
        server.stop()
        assert _wait(lambda: not client.connected, 5.0)
        local_grants = sum(st.try_entry("svc") is not None for _ in range(60))
        # Local window: count=50 minus the 10 token-granted entries the
        # StatisticSlot already accounted this window (the reference
        # also bumps pass for cluster grants) → exactly 40.
        assert local_grants == 40, local_grants

        # Phase 3: restart on the SAME port — scheduled reconnect finds
        # it; grants come from the fresh server again.
        service2 = DefaultTokenService(clock=ManualClock(0))
        server2 = SentinelTokenServer(port=port, service=service2).start()
        try:
            def _reconnected():
                # A request drives _maybe_reconnect; FAIL until then.
                st.try_entry("svc")
                return client.connected and any(
                    f["flowId"] == 700 for f in service2.flow_stats()
                )

            assert _wait(_reconnected, 10.0), "client never reconverged"
            before = sum(
                f["currentQps"] for f in service2.flow_stats() if f["flowId"] == 700
            )
            n = sum(st.try_entry("svc") is not None for _ in range(5))
            after = sum(
                f["currentQps"] for f in service2.flow_stats() if f["flowId"] == 700
            )
            assert after - before >= n - 1  # fresh grants are server grants
            client.stop()
        finally:
            server2.stop()

    def test_no_fallback_rule_passes_during_outage(self, cluster_env, manual_clock, engine):
        """fallback_to_local_when_fail=False: during an outage entries
        PASS (the reference's fallbackToLocalOrPass else-branch), they
        are not blocked."""
        rule = cluster_rule("nf", 1, flow_id=701, fallback=False)
        cluster_flow_rule_manager.load_rules("default", [rule])
        server = SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=ManualClock(0))
        ).start()
        client = ClusterTokenClient(
            "127.0.0.1", server.port, request_timeout_sec=0.5,
            reconnect_interval_sec=0.05,
        ).start()
        TokenClientProvider.register(client)
        ClusterStateManager.set_to_client()
        st.flow_rule_manager.load_rules([rule])
        assert st.try_entry("nf") is not None
        server.stop()
        assert _wait(lambda: not client.connected, 5.0)
        for _ in range(5):
            e = st.try_entry("nf")
            assert e is not None  # pass-through, not local count=1
            e.exit()
        client.stop()


class TestTornFramesOnLiveConnections:
    @pytest.fixture()
    def server(self, cluster_env):
        srv = SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=ManualClock(0))
        ).start()
        yield srv
        srv.stop()

    def test_torn_frame_after_valid_traffic(self, server):
        """A connection that served valid requests then sends a torn
        frame (length prefix promising more than arrives) is dropped
        cleanly; other live connections keep working and the
        per-namespace connection accounting is not leaked."""
        cluster_flow_rule_manager.load_rules(
            "default", [cluster_rule("r", 100, flow_id=710)]
        )
        healthy = ClusterTokenClient("127.0.0.1", server.port, namespace="ns").start()
        assert healthy.request_token(710).ok

        evil = socket.create_connection(("127.0.0.1", server.port), timeout=2)
        # Valid request first — the connection is live and trusted.
        evil.sendall(protocol.pack_flow_request(1, 710, 1, False))
        assert protocol.read_frame(evil) is not None
        # Torn frame: promise 100 bytes, deliver 3, then die.
        evil.sendall(struct.pack("<I", 100) + b"\x01\x02\x03")
        evil.close()

        # The healthy client is unaffected.
        for _ in range(3):
            assert healthy.request_token(710).ok
        # The torn connection is reaped from the accounting.
        assert _wait(
            lambda: server.connections.total() == 1
        ), server.connections.snapshot()
        healthy.stop()

    def test_mid_stream_garbage_body(self, server):
        """A well-framed but garbage body mid-stream (after valid
        traffic) must not crash the handler thread; the connection is
        dropped or answered, and the server keeps serving."""
        cluster_flow_rule_manager.load_rules(
            "default", [cluster_rule("r", 100, flow_id=711)]
        )
        evil = socket.create_connection(("127.0.0.1", server.port), timeout=2)
        evil.sendall(protocol.pack_flow_request(1, 711, 1, False))
        assert protocol.read_frame(evil) is not None
        # Known type (FLOW) with a truncated body.
        bad = struct.pack("<IB", 2, C.MSG_TYPE_FLOW) + b"\x00\x00"
        evil.sendall(struct.pack("<I", len(bad)) + bad)
        evil.settimeout(1.0)
        try:
            while evil.recv(4096):
                pass
        except (socket.timeout, ConnectionError, OSError):
            pass
        evil.close()
        healthy = ClusterTokenClient("127.0.0.1", server.port).start()
        assert healthy.request_token(711).ok
        healthy.stop()

    def test_client_survives_garbage_response(self, cluster_env):
        """An evil 'server' answering a live client with a malformed
        response: the pending request resolves FAIL (no hang, no reader
        crash) and the client object survives to reconnect elsewhere."""
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        accepted = []

        def evil_server():
            conn, _ = lst.accept()
            accepted.append(conn)
            try:
                protocol.read_frame(conn)  # the client's ping
                protocol.read_frame(conn)  # the request
                # Reply with a well-framed but short (non-_RESP) body.
                conn.sendall(struct.pack("<I", 3) + b"\x01\x02\x03")
            except Exception:
                pass

        t = threading.Thread(target=evil_server, daemon=True)
        t.start()
        client = ClusterTokenClient(
            "127.0.0.1", port, request_timeout_sec=0.5,
            reconnect_interval_sec=0.05,
        ).start()
        r = client.request_token(42)
        assert r.status == C.TokenResultStatus.FAIL
        # Reader died on the garbage; the client closed the socket and
        # can still answer (FAIL) without hanging.
        r2 = client.request_token(42)
        assert r2.status == C.TokenResultStatus.FAIL
        client.stop()
        lst.close()
        for c in accepted:
            c.close()
