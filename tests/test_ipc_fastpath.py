"""IPC fast path (ISSUE 14): worker-side micro-windows, adaptive ring
wakeups, and worker mode.

The acceptance surface: micro-window verdicts are bit-identical to the
per-call frames AND the in-process oracle at pipeline depths {0, 2}
(flow + param, speculative on/off), in-process and across a real spawn
boundary; window off preserves PR-13 per-call framing exactly;
concurrent callers coalesce (frames-per-entry amortization); adaptive
spin-then-park wakeups keep verdict parity and burn bounded CPU when
idle; worker mode serves real adapters (WSGI + ASGI) from a spawned
process with verdict parity, trace identity, and kill -9 leaving
device AND mirror THREAD gauges exactly 0.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import pytest

from sentinel_tpu.core import errors as E
from sentinel_tpu.ipc.plane import IngestPlane
from sentinel_tpu.ipc.ring import ShmRing
from sentinel_tpu.ipc.worker import IngestClient
from sentinel_tpu.models.rules import FlowRule
from sentinel_tpu.utils.config import config

import ipc_procs
from test_ipc_plane import (  # noqa: F401 (shared ipc test helpers)
    _engine,
    _oracle_decide,
    _reap_proc,
    _rules,
    _spawn,
    _stream,
    _q_get,
    _wait_for,
)


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


# ---------------------------------------------------------------------------
# micro-window: differential pinning + framing
# ---------------------------------------------------------------------------
class TestMicroWindowParity:
    """The armed micro-window is bit-identical to the in-process
    oracle (and therefore to the per-call framing PR-13 pinned against
    the same oracle) at depths {0,2} x speculative on/off, flow +
    param rules."""

    @pytest.mark.parametrize("depth", [0, 2])
    @pytest.mark.parametrize("spec", [False, True])
    def test_bit_identical(self, manual_clock, depth, spec):
        config.set(config.PIPELINE_DEPTH, str(depth))
        config.set(config.SPECULATIVE_ENABLED, "true" if spec else "false")
        config.set(config.IPC_CLIENT_WINDOW_MS, "2")
        manual_clock.set_ms(1000)
        oracle = _engine(manual_clock)
        _rules(oracle)
        eng = _engine(manual_clock)
        _rules(eng)
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            assert cli.window_armed
            want = []
            got = []
            for req in _stream():
                if req[0] == "entry":
                    _, res, ts, args = req
                    want.extend(_oracle_decide(oracle, res, 1, [ts], [args]))
                    v = cli.entry(res, ts=ts, args=args, timeout_ms=30000)
                    got.append((v.admitted, v.reason, v.wait_ms))
                else:
                    _, res, ts, n = req
                    want.extend(
                        _oracle_decide(oracle, res, n, [ts] * n, [()] * n)
                    )
                    a, r, w, _f = cli.bulk(res, n, ts=ts, timeout_ms=30000)
                    got.extend(zip(a.tolist(), r.tolist(), w.tolist()))
            assert got == want, f"depth={depth} spec={spec}"
            oracle.flush()
            oracle.drain()
            eng.flush()
            eng.drain()
        finally:
            cli.close()
            plane.close()
            eng.close()
            oracle.close()

    def test_window_off_preserves_percall_framing(self, manual_clock):
        """window.ms=0 (the default) IS PR-13: no flusher thread, one
        frame per call."""
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="r", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            assert not cli.window_armed
            assert cli._win_thread is None
            for _ in range(5):
                assert cli.entry("r", ts=1000, timeout_ms=30000).admitted
            assert cli.counters["frames"] == 5
            assert cli.counters["window_flushes"] == 0
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_concurrent_callers_coalesce(self, manual_clock):
        """Concurrency 8: one frame carries many callers' rows — the
        amortization the bench pins at >=4x; the deterministic floor
        asserted here is 2x."""
        config.set(config.IPC_CLIENT_WINDOW_MS, "3")
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="c", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            f0 = cli.counters["frames"]

            def worker():
                for _ in range(10):
                    assert cli.entry("c", ts=1000, timeout_ms=30000).admitted

            ts = [threading.Thread(target=worker) for _ in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            frames = cli.counters["frames"] - f0
            assert cli.counters["entries"] == 80
            assert frames * 2 <= 80, f"no amortization: {frames} frames"
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_windowed_exits_coalesce_and_release_gauges(self, manual_clock):
        config.set(config.IPC_CLIENT_WINDOW_MS, "2")
        config.set(config.SPECULATIVE_ENABLED, "true")
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="g", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            for _ in range(5):
                assert cli.entry("g", ts=1000, timeout_ms=30000).admitted
            for _ in range(5):
                assert cli.exit("g")
            _wait_for(
                lambda: plane.snapshot()["counters"]["exits"] >= 5,
                what="windowed exits drained",
            )
            assert cli.counters["exits"] == 5
            eng.flush()
            eng.drain()
            assert eng.cluster_node_stats("g")["cur_thread_num"] == 0
            mirror = eng.speculative.mirror.snapshot()["live_threads"]
            assert mirror.get("g", 0) == 0
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_ring_full_sheds_whole_window(self, manual_clock):
        """A failed window push fans BLOCK_SHED (cause ipc_ring) to
        every caller in the window — per-call parity, never a stall."""
        config.set(config.IPC_RING_SLOTS, "2")
        config.set(config.IPC_CLIENT_WINDOW_MS, "1")
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="s", count=1e9)])
        plane = IngestPlane(eng, start=False)
        plane._publish_control(force=True)  # engine reads alive
        cli = IngestClient(plane.channel(0), 0)
        try:
            # Fill the 2-slot ring (waits time out into the policy
            # path — those frames are queued, not shed).
            for _ in range(2):
                v = cli.entry("s", ts=1000, timeout_ms=80)
                assert v.degraded
            for _ in range(4):
                v = cli.entry("s", ts=1000, timeout_ms=80)
                assert not v.admitted
                assert v.reason == E.BLOCK_SHED
                assert v.limit_type == "ipc_ring"
            assert cli.counters["sheds"] == 4
            # Per-call parity for the amortization ratio: entries
            # count on push success only — the 2 queued frames, never
            # the 4 shed rows (pre-counting them would understate
            # frames-per-entry exactly under the ring pressure the
            # window claims to help).
            assert cli.counters["entries"] == 2
            # The fold still reaches the engine's valve accounting.
            plane.start()
            _wait_for(
                lambda: eng.ingest.counters["shed_ring"] >= 4,
                what="shed_ring fold",
            )
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_unpaired_exit_never_applies(self, manual_clock):
        """An exit with no live ledger admission — a policy-served
        caller whose entry never reached the engine (transient
        engine-dead read at the client), or a dead-worker reap that
        already auto-exited it — is dropped and counted, never applied:
        applying it double-releases and drives THREAD gauges negative
        (reproduced on a loaded box where the first compile outlives
        the client timeout)."""
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="u", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            assert cli.exit("u", rt=5)  # no admission ever made
            _wait_for(
                lambda: plane.snapshot()["counters"]["exits_unpaired"] >= 1,
                what="unpaired exit dropped",
            )
            eng.flush()
            eng.drain()
            assert eng.cluster_node_stats("u")["cur_thread_num"] == 0
            # A real admit/completion pair still applies.
            assert cli.entry("u", ts=1000, timeout_ms=30000).admitted
            assert cli.exit("u")
            _wait_for(
                lambda: plane.snapshot()["counters"]["exits"] >= 1,
                what="paired exit applied",
            )
            eng.flush()
            eng.drain()
            assert eng.cluster_node_stats("u")["cur_thread_num"] == 0
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_partial_count_exit_releases_exit_count(self, manual_clock):
        """Entry.exit(count) releasing fewer than acquired keeps
        in-process parity: the exit's count releases NOW (the ledger
        pairing falls back to any-count for the same rows+resource
        instead of dropping it as unpaired), and the paired admission
        is forgotten so the dead-worker reap cannot re-release it."""
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="pc", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            v = cli.entry("pc", acquire=2, ts=1000, timeout_ms=30000)
            assert v.admitted
            assert cli.exit("pc", count=1)
            _wait_for(
                lambda: plane.snapshot()["counters"]["exits"] >= 1,
                what="partial-count exit applied",
            )
            assert plane.snapshot()["counters"]["exits_unpaired"] == 0
            eng.flush()
            eng.drain()
            # The THREAD gauge is per-op: one entry, one completion —
            # exactly 0 afterward (never negative), and the ledger
            # forgot the admission so the reap cannot re-release it.
            assert eng.cluster_node_stats("pc")["cur_thread_num"] == 0
            assert plane.snapshot()["workers"][0]["live_admissions"] == 0
        finally:
            cli.close()
            plane.close()
            eng.close()

    def test_claim_worker_slots_never_reuses_live_ids(self, manual_clock):
        """run_workers allocates ids through the plane: a second fleet
        on the same engine must never put two clients on one response
        ring (they would race its tail pointer and each steal half the
        other's verdicts)."""
        eng = _engine(manual_clock)
        plane = IngestPlane(eng)
        try:
            a = plane.claim_worker_slots(2)
            b = plane.claim_worker_slots(2)
            assert len(set(a) | set(b)) == 4, (a, b)
            with pytest.raises(ValueError):
                plane.claim_worker_slots(plane.workers_max)
        finally:
            plane.close()
            eng.close()

    def test_flusher_survives_unencodable_exit(self, manual_clock):
        """An exit the codec cannot encode (count outside int32) is
        dropped and counted — it must NOT kill the flusher thread,
        which would strand every future windowed caller while the
        heartbeat keeps the dead-worker reap away (gauges leak
        forever). The PR-11 batch-window hardening, client-side."""
        config.set(config.IPC_CLIENT_WINDOW_MS, "1")
        eng = _engine(manual_clock)
        eng.set_flow_rules([FlowRule(resource="x", count=1e9)])
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            assert cli.entry("x", ts=1000, timeout_ms=30000).admitted
            assert cli.exit("x", count=2 ** 40)  # buffered; encode fails
            _wait_for(
                lambda: cli.counters["exits_dropped"] >= 1,
                what="unencodable exit dropped",
            )
            # The flusher survived: later windowed traffic still serves
            # and later exits still drain.
            assert cli.entry("x", ts=1000, timeout_ms=30000).admitted
            assert cli.exit("x")
            _wait_for(
                lambda: cli.counters["exits"] >= 1,
                what="later exit drained",
            )
            assert cli._win_thread.is_alive()
        finally:
            cli.close()
            plane.close()
            eng.close()

    @pytest.mark.mp
    def test_parity_across_spawn_boundary(self, manual_clock):
        """The armed micro-window + adaptive doorbells across a REAL
        process boundary (production shape: depth 2, speculative on;
        the doorbell semaphores must travel the spawn like the claim
        lock does)."""
        config.set(config.PIPELINE_DEPTH, "2")
        config.set(config.SPECULATIVE_ENABLED, "true")
        config.set(config.IPC_WAKEUP, "adaptive")
        manual_clock.set_ms(1000)
        oracle = _engine(manual_clock)
        _rules(oracle)
        eng = _engine(manual_clock)
        _rules(eng)
        plane = IngestPlane(eng)
        script = []
        want = []
        for req in _stream():
            if req[0] == "entry":
                _, res, ts, args = req
                script.append(
                    {"kind": "entry", "resource": res, "ts": ts,
                     "args": list(args), "timeout_ms": 60000}
                )
                want.append(
                    ("entry",)
                    + _oracle_decide(oracle, res, 1, [ts], [args])[0]
                )
            else:
                _, res, ts, n = req
                script.append(
                    {"kind": "bulk", "resource": res, "n": n, "ts": ts}
                )
                vs = _oracle_decide(oracle, res, n, [ts] * n, [()] * n)
                want.append(
                    ("bulk", [v[0] for v in vs], [v[1] for v in vs],
                     [v[2] for v in vs])
                )
        cfg = {
            config.IPC_CLIENT_WINDOW_MS: "2",
            config.IPC_WAKEUP: "adaptive",
        }
        p = None
        try:
            assert plane.adaptive_wakeup
            p, q = _spawn(plane, ipc_procs.run_script_cfg, 0, cfg, script)
            tag, wid, out = _q_get(q)
            assert tag == "done" and wid == 0
            got = [
                ("entry", s[1], s[2], s[3]) if s[0] == "entry"
                else ("bulk", s[1], s[2], s[3])
                for s in out
            ]
            assert got == want
        finally:
            _reap_proc(p)
            plane.close()
            eng.close()
            oracle.close()


# ---------------------------------------------------------------------------
# adaptive wakeups
# ---------------------------------------------------------------------------
class TestAdaptiveWakeup:
    def test_doorbell_wakes_parked_consumer(self):
        """Ring unit: a producer's publish rings the doorbell of a
        parked consumer promptly (no 200 µs sleep quantum, no lost
        wakeup)."""
        import multiprocessing

        ctx = multiprocessing.get_context("spawn")
        bell = ctx.Semaphore(0)
        ring = ShmRing(None, 8, 64, create=True, doorbell=bell)
        try:
            woke = {}

            def consumer():
                t0 = time.monotonic()
                ok = ring.wait_readable(0.0, 5.0)
                woke["dt"] = time.monotonic() - t0
                woke["ok"] = ok

            t = threading.Thread(target=consumer)
            t.start()
            time.sleep(0.05)  # let it park
            assert ring.try_push(b"x")
            t.join(timeout=10)
            assert woke["ok"]
            assert woke["dt"] < 1.0
            assert ring.try_pop() == b"x"
            # Set-flag/publish race: payload published BEFORE the park
            # is seen without any doorbell.
            assert ring.try_push(b"y")
            assert ring.wait_readable(0.0, 0.001)
        finally:
            ring.destroy()

    def test_parity_with_adaptive_wakeups(self, manual_clock):
        """Wakeup strategy changes latency, never verdicts."""
        config.set(config.IPC_WAKEUP, "adaptive")
        config.set(config.SPECULATIVE_ENABLED, "true")
        manual_clock.set_ms(1000)
        oracle = _engine(manual_clock)
        _rules(oracle)
        eng = _engine(manual_clock)
        _rules(eng)
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0)
        try:
            assert plane.adaptive_wakeup and cli.adaptive_wakeup
            want = []
            got = []
            for req in _stream():
                if req[0] == "entry":
                    _, res, ts, args = req
                    want.extend(_oracle_decide(oracle, res, 1, [ts], [args]))
                    v = cli.entry(res, ts=ts, args=args, timeout_ms=30000)
                    got.append((v.admitted, v.reason, v.wait_ms))
                else:
                    _, res, ts, n = req
                    want.extend(
                        _oracle_decide(oracle, res, n, [ts] * n, [()] * n)
                    )
                    a, r, w, _f = cli.bulk(res, n, ts=ts, timeout_ms=30000)
                    got.extend(zip(a.tolist(), r.tolist(), w.tolist()))
            assert got == want
        finally:
            cli.close()
            plane.close()
            eng.close()
            oracle.close()

    def test_idle_cpu_burn_bounded(self, manual_clock):
        """The spin-then-park wait must not burn a core when idle: an
        armed adaptive plane + client sitting idle for 1 s consume a
        bounded fraction of one CPU (a spinning drainer would read
        ~1.0 on this 1-core box; parked waits read near 0)."""
        config.set(config.IPC_WAKEUP, "adaptive")
        eng = _engine(manual_clock)
        plane = IngestPlane(eng)
        cli = IngestClient(plane.channel(0), 0, heartbeat=False)
        try:
            # One round trip so every thread is warm, then idle.
            cli.entry("warm", ts=1000, timeout_ms=30000)
            time.sleep(0.1)
            cpu0 = time.process_time()
            t0 = time.monotonic()
            time.sleep(1.0)
            wall = time.monotonic() - t0
            cpu = time.process_time() - cpu0
            assert cpu < 0.5 * wall, (
                f"idle adaptive wait burned {cpu:.3f}s CPU over "
                f"{wall:.3f}s wall"
            )
        finally:
            cli.close()
            plane.close()
            eng.close()


# ---------------------------------------------------------------------------
# worker mode (in-process half; the mp half is below)
# ---------------------------------------------------------------------------
class TestWorkerModeInProcess:
    def test_api_surface_routes_through_client(self, manual_clock):
        from sentinel_tpu.core import api
        from sentinel_tpu.ipc import worker_mode

        config.set(config.IPC_WORKER_MODE, "true")
        config.set(config.SPECULATIVE_ENABLED, "true")
        eng = _engine(manual_clock)
        eng.set_flow_rules(
            [
                FlowRule(resource="open", count=1e9),
                FlowRule(resource="closed", count=0),
            ]
        )
        plane = IngestPlane(eng)
        cli = worker_mode.attach(plane.channel(0), 0)
        try:
            assert worker_mode.current() is cli
            e = api.entry("open")
            assert e.verdict.admitted and e.verdict.speculative
            e.exit()
            with pytest.raises(E.BlockError):
                api.entry("closed")
            assert api.try_entry("closed") is None
            # Prio (occupy) semantics cannot cross the wire — refused
            # loudly, never silently downgraded to a normal admission.
            with pytest.raises(ValueError):
                api.entry("open", prio=True)
            e2 = api.entry_windowed("open")
            e2.exit()
            e3 = api.entry_async("open")
            e3.exit()
            _wait_for(
                lambda: plane.snapshot()["counters"]["exits"] >= 3,
                what="worker-mode exits",
            )
            eng.flush()
            eng.drain()
            assert eng.cluster_node_stats("open")["cur_thread_num"] == 0
            mirror = eng.speculative.mirror.snapshot()["live_threads"]
            assert mirror.get("open", 0) == 0
        finally:
            worker_mode.detach()
            plane.close()
            eng.close()
        # Detach restores the normal engine-backed path.
        assert worker_mode.current() is None
        from sentinel_tpu.core.api import _worker_client

        assert _worker_client is None

    def test_worker_mode_off_is_parity(self, manual_clock):
        """Config key off: attach() creates a plain client and never
        installs the hook."""
        from sentinel_tpu.core import api
        from sentinel_tpu.ipc import worker_mode

        eng = _engine(manual_clock)
        plane = IngestPlane(eng)
        cli = worker_mode.attach(plane.channel(0), 0)  # mode defaults off
        try:
            assert worker_mode.current() is None
            assert api._worker_client is None
            cli.close()
        finally:
            worker_mode.detach()
            plane.close()
            eng.close()


def _oracle_statuses(paths, depth):
    """The in-process oracle: the SAME middleware stack served by a
    local engine (api-global), same rules — what the worker-mode
    verdicts must match."""
    import asyncio

    from sentinel_tpu.adapters.asgi import SentinelASGIMiddleware
    from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware
    from sentinel_tpu.core import api
    from sentinel_tpu.runtime.engine import Engine

    config.set(config.PIPELINE_DEPTH, str(depth))
    oracle = Engine(initial_rows=256)
    oracle.set_flow_rules(
        [
            FlowRule(resource="GET:/open", count=1e9),
            FlowRule(resource="GET:/closed", count=0),
        ]
    )
    prev = api.set_engine(oracle)
    try:
        out = []

        def ok_app(environ, start_response):
            start_response("200 OK", [])
            return [b"ok"]

        wsgi = SentinelWSGIMiddleware(ok_app, total_resource=None)
        for path, _tp in paths:
            statuses = []
            list(wsgi({"PATH_INFO": path, "REQUEST_METHOD": "GET"},
                      lambda s, h: statuses.append(s)))
            out.append(("wsgi", path, statuses[0]))

        async def asgi_ok(scope, receive, send):
            await send({"type": "http.response.start", "status": 200,
                        "headers": []})
            await send({"type": "http.response.body", "body": b"ok"})

        asgi = SentinelASGIMiddleware(asgi_ok, total_resource=None)

        async def drive(path):
            sent = []

            async def send(msg):
                sent.append(msg)

            async def receive():
                return {"type": "http.request"}

            await asgi({"type": "http", "method": "GET", "path": path,
                        "headers": []}, receive, send)
            return sent[0]["status"]

        for path, _tp in paths:
            out.append(("asgi", path, asyncio.run(drive(path))))
        return out
    finally:
        api.set_engine(prev)
        oracle.close()


@pytest.mark.mp
class TestWorkerModeMP:
    """The worker-mode satellite: a REAL spawned worker serving real
    adapters end-to-end."""

    PATHS = [("/open", None), ("/closed", None), ("/free", None),
             ("/open", "00-" + "a7" * 16 + "-" + "c3" * 8 + "-01")]

    @pytest.mark.parametrize("depth", [0, 2])
    def test_adapter_verdict_parity_and_trace_identity(
        self, manual_clock, depth
    ):
        config.set(config.PIPELINE_DEPTH, str(depth))
        config.set(config.SPECULATIVE_ENABLED, "true")
        want = _oracle_statuses(self.PATHS, depth)
        eng = _engine(manual_clock)
        eng.set_flow_rules(
            [
                FlowRule(resource="GET:/open", count=1e9),
                FlowRule(resource="GET:/closed", count=0),
            ]
        )
        plane = IngestPlane(eng)
        p = None
        try:
            p, q = _spawn(
                plane, ipc_procs.worker_mode_serve, 0, {}, self.PATHS
            )
            tag, _wid, got, engine_free = _q_get(q)
            assert tag == "done"
            assert got == want, f"depth={depth}"
            # 'No Engine ever constructed in the worker' is a pinned
            # contract, not prose: a lazy get_engine() (e.g. via
            # context true_enter) would build device state — and a
            # second IngestPlane — inside every worker.
            assert engine_free, "worker lazily constructed an Engine"
            # PR-4 identity: the traced request's inbound trace id
            # reaches the ENGINE process's admission records — from
            # the WSGI request AND the ASGI one (the async path runs
            # the client call in a pool thread; losing the calling
            # task's contextvars there ships EMPTY_TRACE).
            tid = "a7" * 16
            _wait_for(
                lambda: sum(
                    1
                    for r in eng.admission_trace.records()
                    if r.trace_id == tid and r.parent_span_id == "c3" * 8
                ) >= 2,
                what="worker-mode trace identity (wsgi + asgi)",
            )
        finally:
            _reap_proc(p)
            plane.close()
            eng.close()

    def test_kill9_mid_serve_drains_gauges_to_zero(self):
        config.set(config.SPECULATIVE_ENABLED, "true")
        config.set(config.IPC_HEARTBEAT_MS, "50")
        config.set(config.IPC_WORKER_DEAD_MS, "400")
        eng = _engine()  # real clock: heartbeat staleness is wall time
        eng.set_flow_rules([FlowRule(resource="GET:/hang", count=1e9)])
        plane = IngestPlane(eng)
        n = 4
        p = None
        try:
            p, q = _spawn(
                plane, ipc_procs.worker_mode_admit_and_hang, 0, "/hang", n
            )
            tag, _wid, admitted = _q_get(q)
            assert tag == "admitted" and admitted == n
            eng.flush()
            eng.drain()
            assert eng.cluster_node_stats("GET:/hang")["cur_thread_num"] == n
            os.kill(p.pid, signal.SIGKILL)  # mid-serve, no exits
            _wait_for(
                lambda: plane.snapshot()["counters"]["worker_deaths"] >= 1,
                timeout_s=30,
                what="worker death sweep",
            )
            assert plane.snapshot()["counters"]["auto_exits"] == n
            eng.flush()
            eng.drain()
            stats = eng.cluster_node_stats("GET:/hang")
            assert stats["cur_thread_num"] == 0, "device gauge must be 0"
            mirror = eng.speculative.mirror.snapshot()["live_threads"]
            assert mirror.get("GET:/hang", 0) == 0, "mirror gauge must be 0"
        finally:
            _reap_proc(p)
            plane.close()
            eng.close()
