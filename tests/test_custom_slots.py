"""Pluggable processor slots: a registered custom slot can veto entries
ahead of the device chain with full attribution, and observes exits —
the SPI-assembled chain extension point
(slots/DefaultSlotChainBuilder.java:36-57 + META-INF/services).
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.core.errors import CustomBlockError
from sentinel_tpu.core.slots import ProcessorSlot, SlotChainRegistry, SlotEntryContext


@pytest.fixture(autouse=True)
def clean_slots():
    SlotChainRegistry.clear()
    yield
    SlotChainRegistry.clear()


class PaywallSlot(ProcessorSlot):
    """Blocks a named resource unless the first arg is 'paid'."""

    name = "paywall"
    order = -100

    def __init__(self, protected="premium"):
        self.protected = protected
        self.exits = []

    def entry(self, ctx: SlotEntryContext):
        if ctx.resource == self.protected and (not ctx.args or ctx.args[0] != "paid"):
            return {"reason": "payment required"}
        return None

    def exit(self, resource, rt_ms, count, err):
        self.exits.append((resource, rt_ms, count, err))


class TestCustomSlots:
    def test_veto_blocks_with_attribution(self, manual_clock, engine):
        slot = PaywallSlot()
        SlotChainRegistry.register(slot)
        st.flow_rule_manager.load_rules([st.FlowRule("premium", count=100)])
        manual_clock.set_ms(100)
        with pytest.raises(CustomBlockError) as ei:
            st.entry("premium")
        assert ei.value.slot_name == "paywall"
        assert ei.value.rule == {"reason": "payment required"}
        # Accounted as a block in the windows like any slot's veto.
        stats = engine.cluster_node_stats("premium")
        assert stats["block_qps"] == pytest.approx(1.0)
        assert stats["pass_qps"] == 0.0

    def test_args_admit_and_exit_observed(self, manual_clock, engine):
        slot = PaywallSlot()
        SlotChainRegistry.register(slot)
        manual_clock.set_ms(100)
        e = st.entry("premium", args=("paid",))
        manual_clock.set_ms(130)
        e.exit()
        engine.flush()
        assert slot.exits == [("premium", 30, 1, 0)]

    def test_other_resources_unaffected(self, manual_clock, engine):
        SlotChainRegistry.register(PaywallSlot())
        assert st.try_entry("free") is not None

    def test_slot_order_first_veto_wins(self, manual_clock, engine):
        class A(ProcessorSlot):
            name, order = "a", 10

            def entry(self, ctx):
                return "a-veto"

        class B(ProcessorSlot):
            name, order = "b", -10

            def entry(self, ctx):
                return "b-veto"

        SlotChainRegistry.register(A())
        SlotChainRegistry.register(B())
        with pytest.raises(CustomBlockError) as ei:
            st.entry("x")
        assert ei.value.slot_name == "b"  # lower order runs first

    def test_raising_slot_fails_open(self, manual_clock, engine):
        class Broken(ProcessorSlot):
            name = "broken"

            def entry(self, ctx):
                raise RuntimeError("slot bug")

        SlotChainRegistry.register(Broken())
        assert st.try_entry("y") is not None  # fail open, like the chain

    def test_veto_appears_in_block_log(self, manual_clock, engine, tmp_path):
        from sentinel_tpu.metrics.block_log import BlockLogger

        engine.block_log = BlockLogger(base_dir=str(tmp_path), clock=manual_clock)
        SlotChainRegistry.register(PaywallSlot())
        manual_clock.set_ms(100)
        assert st.try_entry("premium") is None
        engine.block_log.flush()
        (_, key, count), = engine.block_log.read_entries()
        assert key[0] == "premium" and key[1] == "CustomBlockException"
        assert count == 1
