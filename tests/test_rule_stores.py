"""Dashboard rule persistence through Nacos / ZooKeeper / Apollo.

Reference: the dashboard's pluggable DynamicRuleProvider/Publisher
pairs for each config center (sentinel-dashboard/.../rule/nacos/
FlowRuleNacosProvider.java, rule/zookeeper/FlowRuleZookeeperPublisher
.java, rule/apollo/FlowRuleApolloPublisher.java). The console writes
the store; machines follow the same key with their datasource watch —
no direct machine push.
"""

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit

import pytest

import sentinel_tpu as st
from sentinel_tpu.dashboard import (
    ApolloRuleStore,
    DashboardServer,
    NacosRuleStore,
    ZookeeperRuleStore,
)


def _req(port, path, **params):
    from urllib.parse import urlencode

    url = f"http://127.0.0.1:{port}/{path}"
    if params:
        url += "?" + urlencode(params)
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class TestNacosRuleStore:
    def test_param_rule_console_to_machine(self, manual_clock, engine):
        """Console edit of a PARAM rule persisted through Nacos and
        enforced by a machine following the same dataId (the verdict's
        non-etcd end-to-end ask)."""
        from tests.test_nacos_source import FakeNacos
        from sentinel_tpu.datasource.base import json_converter
        from sentinel_tpu.datasource.nacos_source import NacosDataSource
        from sentinel_tpu.models.rules import ParamFlowRule

        fake = FakeNacos()
        t = threading.Thread(target=fake.serve_forever, daemon=True)
        t.start()
        store = NacosRuleStore(endpoint=f"http://127.0.0.1:{fake.port}")
        dash = DashboardServer(port=0, fetch_interval_sec=999, rule_store=store).start()
        machine_src = NacosDataSource(
            json_converter(ParamFlowRule),
            store.data_id_for("papp", "paramFlow"),
            group="SENTINEL_GROUP",
            endpoint=f"http://127.0.0.1:{fake.port}",
            reconnect_interval_sec=0.05,
        ).start()
        try:
            st.param_flow_rule_manager.register_property(machine_src.get_property())
            data = json.dumps([{"resource": "pres", "paramIdx": 0, "count": 2}])
            code, body = _req(dash.port, "rules", app="papp", type="paramFlow", data=data)
            assert code == 200 and json.loads(body)["code"] == 0
            # Store round-trip through the console.
            code, body = _req(dash.port, "rules", app="papp", type="paramFlow")
            assert json.loads(body)[0]["count"] == 2
            # Machine picked it up via its own watch and enforces it.
            assert _wait(
                lambda: any(
                    r.count == 2
                    for r in (st.param_flow_rule_manager.get_rules() or [])
                )
            ), "published param rules never reached the machine"
            manual_clock.set_ms(500)
            grants = sum(
                st.try_entry("pres", args=("k",)) is not None for _ in range(5)
            )
            assert grants == 2  # hot-param budget enforced
        finally:
            machine_src.close()
            dash.stop()
            fake.shutdown()


class TestZookeeperRuleStore:
    def test_flow_rule_console_to_machine(self, manual_clock, engine):
        from tests.test_zookeeper_source import FakeZk
        from sentinel_tpu.datasource.base import json_converter
        from sentinel_tpu.datasource.zookeeper_source import ZookeeperDataSource

        fake = FakeZk()
        store = ZookeeperRuleStore(server_addr=f"127.0.0.1:{fake.port}")
        dash = DashboardServer(port=0, fetch_interval_sec=999, rule_store=store).start()
        machine_src = ZookeeperDataSource(
            json_converter(st.FlowRule),
            path=store.path_for("zapp", "flow"),
            server_addr=f"127.0.0.1:{fake.port}",
            reconnect_interval_sec=0.05,
        ).start()
        try:
            st.flow_rule_manager.register_property(machine_src.get_property())
            data = json.dumps([{"resource": "zres", "count": 3}])
            code, body = _req(dash.port, "rules", app="zapp", type="flow", data=data)
            assert code == 200 and json.loads(body)["code"] == 0
            code, body = _req(dash.port, "rules", app="zapp", type="flow")
            assert json.loads(body)[0]["count"] == 3
            assert _wait(
                lambda: any(
                    r.count == 3 for r in (st.flow_rule_manager.get_rules() or [])
                )
            ), "published rules never reached the machine"
            manual_clock.set_ms(500)
            admitted = sum(st.try_entry("zres") is not None for _ in range(6))
            assert admitted == 3
        finally:
            machine_src.close()
            dash.stop()
            fake.close()


class _FakePortal(ThreadingHTTPServer):
    """Apollo Portal OpenAPI: item upsert + namespace release applied
    onto the FakeApollo config service."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, apollo):
        super().__init__(("127.0.0.1", 0), _PortalHandler)
        self.port = self.server_address[1]
        self.apollo = apollo
        self.pending = {}  # namespace -> {key: value} awaiting release
        self.auth_seen = []


class _PortalHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _done(self, code=200):
        body = b"{}"
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n).decode() or "{}")

    def do_PUT(self):
        srv: _FakePortal = self.server
        srv.auth_seen.append(self.headers.get("Authorization"))
        parts = urlsplit(self.path).path.strip("/").split("/")
        # openapi/v1/envs/E/apps/A/clusters/C/namespaces/NS/items/KEY
        if "items" in parts:
            ns = parts[parts.index("namespaces") + 1]
            payload = self._body()
            srv.pending.setdefault(ns, {})[payload["key"]] = payload["value"]
            self._done()
        else:
            self._done(404)

    def do_POST(self):
        srv: _FakePortal = self.server
        parts = urlsplit(self.path).path.strip("/").split("/")
        if parts[-1] == "releases":
            ns = parts[parts.index("namespaces") + 1]
            for k, v in srv.pending.pop(ns, {}).items():
                srv.apollo.set_prop(ns, k, v)
            self._done()
        else:
            self._done(404)


class TestApolloRuleStore:
    def test_publish_via_portal_read_via_config_service(self, manual_clock, engine):
        from tests.test_apollo_source import FakeApollo

        apollo = FakeApollo()
        t = threading.Thread(target=apollo.serve_forever, daemon=True)
        t.start()
        portal = _FakePortal(apollo)
        t2 = threading.Thread(target=portal.serve_forever, daemon=True)
        t2.start()
        store = ApolloRuleStore(
            config_endpoint=f"http://127.0.0.1:{apollo.port}",
            portal_endpoint=f"http://127.0.0.1:{portal.port}",
            token="tok-1",
        )
        try:
            # Publish: item upsert + release through the portal.
            store.publish("aapp", "degrade", [{"resource": "ares", "count": 0.5}])
            assert portal.auth_seen and portal.auth_seen[0] == "tok-1"
            # Read back through the config service (the machine path).
            rules = store.get_rules("aapp", "degrade")
            assert rules == [{"resource": "ares", "count": 0.5}]
            # Unreleased items are invisible (release gating works).
            assert store.get_rules("aapp", "flow") is None
        finally:
            portal.shutdown()
            apollo.shutdown()
