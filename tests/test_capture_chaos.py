"""Capture-journal chaos: the flight recorder across ``kill -9``.

The whole point of a black box is surviving the crash. This drives the
real supervised-process loop (sentinel_tpu/ipc/supervise.py) with
capture armed: a supervised engine child records its admission stream,
gets ``kill -9``'d mid-load, and the hot-restarted child must preserve
the dead boot's live segments as ``frozen-death-*`` BEFORE writing its
own — then every surviving file must parse (torn tails tear cleanly)
and the dead boot's capture must replay green through tools/replay.py.
"""

from __future__ import annotations

import os
import sys

import pytest

from sentinel_tpu.runtime import capture as cap_mod
from sentinel_tpu.utils.config import config

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


@pytest.mark.mp
class TestCaptureKill9:
    def test_kill9_preserves_parseable_replayable_capture(self, tmp_path):
        import ipc_procs
        import replay as replay_tool
        from sentinel_tpu.ipc.supervise import measure_restart_outage

        cap_dir = str(tmp_path / "blackbox")
        config.set(config.IPC_HEARTBEAT_MS, "50")
        config.set(config.IPC_ENGINE_DEAD_MS, "2000")
        config.set(config.SUPERVISE_BACKOFF_MS, "200")
        config.set(config.CAPTURE_ENABLED, "true")
        config.set(config.CAPTURE_DIR, cap_dir)
        out = measure_restart_outage(
            ipc_procs.restart_setup, "chaos-res", timeout_s=200
        )
        assert out["restarts"] >= 1, out

        # The killed boot's segments survived as frozen-death-*: the
        # restarted child renamed them before writing a byte.
        files = sorted(os.listdir(cap_dir))
        death = [f for f in files if f.startswith("frozen-death-")]
        assert death, files
        # Bounded + parseable: EVERY surviving file (dead boot and the
        # restarted boot's live segments alike) parses; a torn tail
        # ends the record list cleanly instead of raising.
        boots = set()
        for fn in files:
            header, recs = cap_mod.read_segment(os.path.join(cap_dir, fn))
            boots.add(header["boot_id"])
            for rec in recs:
                assert rec.rkind in cap_mod._RECORD_NAMES
        assert len(boots) == 2  # the killed boot and its replacement

        # The dead boot's capture holds the pre-kill traffic...
        death_paths = [os.path.join(cap_dir, f) for f in death]
        decoded = cap_mod.decode_capture(death_paths)
        chunks = [ck for k, ck in decoded["stream"] if k == "chunk"]
        assert chunks
        # (the ipc drainer coalesces per-resource frames into bulk
        # groups, so the probe traffic lands in ck.bulk).
        assert any(
            e["resource"] == "chaos-res"
            for ck in chunks
            for e in ck.entries + [r for g in ck.bulk for r in g]
        )

        # ...and replays green: zero verdict diffs over the comparable
        # rows (rows whose verdict fill died with the process are the
        # no_captured_verdict class, skipped — not diffs).
        report = replay_tool.verify(decoded, depth=0)
        assert report["diffs"] == 0, report
        assert report["compared"] > 0, report
